"""Benchmark harness — prints the headline JSON line (+ secondary lines).

North-star workload (BASELINE.md config 4, mirroring the reference's
cpp/bench/ann/conf/sift-128-euclidean.json): ANN build + search on a
SIFT-1M-scale synthetic set — 1M x 128 fp32, batch=5000, k=10,
run_count=3 — reporting QPS at recall >= 0.95
(cpp/bench/ann/scripts/eval.pl:26 "QPS at recall=0.95").  Headline line:
CAGRA (the reference's flagship graph index; packed-neighborhood walk),
then IVF-PQ (n_lists=4096, pq_dim=64) and k-means iter/s.  Each harness
sweeps its operating points and reports the fastest one clearing the
recall bar, exactly how the reference harness picks its summary row.

Second line: k-means fit iterations/s at 1M x 128, k=1024 (BASELINE.md
config 3; reference micro-bench cpp/bench/prims/cluster/kmeans.cu).

``vs_baseline`` is QPS / 2000 — the reference harness's own
"recall at QPS=2000" operating point (eval.pl:26) used as the provisional
scale until driver-recorded baselines exist (BASELINE.json ``published``
is ``{}``).
"""

import json
import os
import sys
import time

import numpy as np

# every emitted JSON line is retained and written to BENCH_rNN.json at
# the end of the run (any mode, pass or fail) — the machine-readable
# record CI uploads as an artifact, no shell redirection required
_EMITTED: list = []

#: env override for the artifact path (CI pins it; default auto-numbers)
BENCH_OUT_ENV = "RAFT_TPU_BENCH_OUT"


def _emit(obj) -> None:
    """Print one result line (the existing JSON-lines protocol) and
    retain it for :func:`_write_bench_artifact`."""
    _EMITTED.append(obj)
    print(json.dumps(obj), flush=True)


def _write_bench_artifact() -> str:
    """Write the retained result lines to ``$RAFT_TPU_BENCH_OUT`` or the
    next free ``BENCH_rNN.json`` beside this file.  Called from the
    entry-point ``finally`` so a failed run still leaves its partial
    record for the post-mortem."""
    path = os.environ.get(BENCH_OUT_ENV)
    if not path:
        here = os.path.dirname(os.path.abspath(__file__))
        n = 1
        while os.path.exists(os.path.join(here, f"BENCH_r{n:02d}.json")):
            n += 1
        path = os.path.join(here, f"BENCH_r{n:02d}.json")
    try:
        with open(path, "w") as f:
            json.dump({"results": _EMITTED}, f, indent=2)
    except OSError as e:
        print(f"BENCH FATAL: cannot write ${BENCH_OUT_ENV} artifact "
              f"{path!r}: {e} — the run's machine-readable record is "
              f"LOST", file=sys.stderr, flush=True)
        raise
    print(f"bench artifact: {path}", flush=True)
    return path


def _check_bench_out_writable() -> None:
    """Pre-flight for ``$RAFT_TPU_BENCH_OUT``: fail LOUDLY (exit 2)
    before the run when the artifact path can't be written, instead of
    burning the whole benchmark and silently dropping its record at the
    end (the failure mode the round-7 re-anchor flagged)."""
    path = os.environ.get(BENCH_OUT_ENV)
    if not path:
        return
    existed = os.path.exists(path)
    try:
        with open(path, "a"):
            pass
    except OSError as e:
        print(f"BENCH FATAL: ${BENCH_OUT_ENV}={path!r} is not writable: "
              f"{e}", file=sys.stderr, flush=True)
        raise SystemExit(2)
    if not existed:
        os.remove(path)     # probe only — leave no empty artifact

N_DB = 1_000_000
N_QUERIES = 5_000
DIM = 128
K = 10
N_LISTS = 4096
PQ_DIM = 64
# Operating points — the reference harness sweeps n_probes and supports
# refine_ratio for raft_ivf_pq (cpp/bench/ann/conf/sift-128-euclidean.json).
# Round 6 adds the compact-code-scan A/B axes (each dict feeds
# SearchParams directly): scan_mode picks the list-scan formulation,
# per_probe_topk narrows the extraction-bound kernels' per-pair keep-set
# (PERFORMANCE.md round 5: ~3.3 us/kept-candidate/group, flat in list
# size — with refine_ratio>=2 the refine pass re-ranks exactly, so small
# kt trades little recall for a near-linear scan speedup), and
# packed_extract halves the extraction's cross-lane reduces.
OPERATING_POINTS = (
    # recon-cache baseline (round-5 continuity)
    dict(n_probes=32, refine_ratio=1),
    dict(n_probes=64, refine_ratio=1),
    dict(n_probes=64, refine_ratio=2),
    dict(n_probes=72, refine_ratio=2),
    dict(n_probes=96, refine_ratio=2),
    # per-probe-topk on the recon kernel
    dict(n_probes=72, refine_ratio=2, per_probe_topk=4),
    dict(n_probes=96, refine_ratio=2, per_probe_topk=4),
    dict(n_probes=72, refine_ratio=2, per_probe_topk=8),
    # compact-code kernel (~pq_dim bytes/row HBM traffic)
    dict(n_probes=72, refine_ratio=2, scan_mode="codes"),
    dict(n_probes=72, refine_ratio=2, scan_mode="codes", per_probe_topk=4),
    dict(n_probes=96, refine_ratio=2, scan_mode="codes", per_probe_topk=4),
    dict(n_probes=72, refine_ratio=2, scan_mode="codes", per_probe_topk=4,
         packed_extract=True),
    # int8 recon cache (1 byte/dim/row)
    dict(n_probes=72, refine_ratio=2, scan_mode="recon8"),
    dict(n_probes=72, refine_ratio=2, scan_mode="recon8", per_probe_topk=4),
    # round-7 fused in-kernel top-k: scan + extraction in ONE stage,
    # candidate distance matrices never reach HBM (since round 14 these
    # resolve merge_window="auto" — the windowed merge engine)
    dict(n_probes=72, refine_ratio=2, scan_mode="fused"),
    dict(n_probes=72, refine_ratio=2, scan_mode="fused", per_probe_topk=4),
    dict(n_probes=96, refine_ratio=2, scan_mode="fused", per_probe_topk=4),
    # round-14 A/B anchor: the same point pinned to the per-step merge
    # (W=1, the round-7 behavior) — auto minus this is the windowed gain
    dict(n_probes=72, refine_ratio=2, scan_mode="fused", per_probe_topk=4,
         merge_window=1),
)

# Round-14 windowed fused-scan grid: (k, merge_window) at batch 1024 and
# matched kt=16 — large k exceeds the fused VMEM budget at the flagship
# batch, so the large-k serving bucket's batch is the operating shape.
# merge_window 0 = "auto" (largest W the budget admits); k=128 carries an
# explicit W=2 beside auto to expose the window axis itself, and k=128/256
# have NO W=1 point because the per-step merge gates at k <= 64 — exactly
# the gate the windowed engine lifts.
FUSED_WINDOWED_GRID = (
    (10, 1), (10, 0), (64, 1), (64, 0), (128, 2), (128, 0), (256, 0),
)
FUSED_WINDOWED_BATCH = 1024
FUSED_WINDOWED_KT = 16
MIN_RECALL = 0.95
# SIFT-like synthetic data: descriptors have low intrinsic dimensionality
# (~16) embedded in 128-d; uniform random 128-d is adversarial to PQ (all
# pairwise distances concentrate) and does not represent the workload
LATENT_DIM = 16
NOISE = 0.05
RUNS = 3                       # run_count=3, sift-128-euclidean.json
QPS_REFERENCE_POINT = 2000.0   # eval.pl:26 "recall at QPS=2000" condition

KMEANS_N = 1_000_000
KMEANS_K = 1024
KMEANS_ITERS = 20


def _recall(found: np.ndarray, gt: np.ndarray) -> float:
    hits = sum(len(set(f) & set(t)) for f, t in zip(found, gt))
    return hits / gt.size


def _recall_at_qps(points, qps_bar: float = QPS_REFERENCE_POINT):
    """eval.pl's third summary condition (eval.pl:26 ``recall at
    QPS=2000``): the best recall among operating points at or above the
    QPS bar (None when no point clears it)."""
    ok = [p["recall"] for p in points if p["qps"] >= qps_bar]
    return max(ok) if ok else None


def _check_sane(name: str, ids, n_rows: int, dists=None) -> None:
    """Integrity tripwire on benchmark outputs: ids in [-1, n_rows) and
    distances finite on filled slots — a broken kernel must fail the run,
    not post a great QPS number on nonsense answers."""
    ids = np.asarray(ids)
    assert ((ids >= -1) & (ids < n_rows)).all(), \
        f"{name}: ids outside [-1, {n_rows})"
    if dists is not None:
        d = np.asarray(dists)
        assert np.isfinite(d[ids >= 0]).all(), \
            f"{name}: non-finite distance on a filled slot"


def _integrity_counters() -> dict:
    """The integrity.* counter snapshot (boundary checks, canary/verify
    outcomes) for the emitted JSON."""
    from raft_tpu import observability as obs

    snap = obs.registry().snapshot()["counters"]
    return {k: v for k, v in sorted(snap.items())
            if k.startswith("integrity.")}


def _ground_truth(res, db, queries):
    from raft_tpu.neighbors import brute_force

    _, gt_i = brute_force.knn(res, db, queries, K)
    return np.asarray(gt_i)


def _print_stage_breakdown(harness: str, index) -> None:
    """Emit the per-stage build breakdown attached by
    ``observability.build_scope`` (one JSON line beside the headline).
    Collection is enabled only around the build — the timed QPS loops
    run with it off so the stage fences cannot skew search timings."""
    from raft_tpu import observability as obs

    rep = obs.build_report(index)
    if rep is None:
        return
    _emit({"stage_breakdown": {
        "harness": harness,
        "total_s": round(rep["total_s"], 3),
        "stages": {name: round(t["total_s"], 3)
                   for name, t in sorted(rep["stages"].items())},
        "counters": rep["counters"],
    }})


def _search_stage_probe(res, index, queries) -> dict:
    """One search per scan mode under stage collection — the round-7
    evidence line: in fused mode the ``code_scan`` (+ in-XLA extraction)
    stage pair collapses into the single ``fused_scan`` stage, and the
    ``fused_fallback`` counter says whether the fused kernel actually
    ran (0 new ticks) or the shape fell back (CPU, unsupported kt/k)."""
    from raft_tpu import observability as obs
    from raft_tpu.neighbors import ivf_pq

    def _counts(snap, kind, key=None):
        return {n: (t["count"] if key is None else t.get(key, 0))
                for n, t in snap.get(kind, {}).items()}

    out = {}
    for mode in ("codes", "fused"):
        sp = ivf_pq.SearchParams(n_probes=72, scan_mode=mode,
                                 per_probe_topk=4)
        with obs.collecting() as reg:
            before = reg.snapshot()
            _, i = ivf_pq.search(res, sp, index, queries, K)
            np.asarray(i)
            after = reg.snapshot()
        b_t = _counts(before, "timers")
        stages = sorted(
            n for n, c in _counts(after, "timers").items()
            if n.startswith("ivf_pq.search.") and c > b_t.get(n, 0))
        fb = (after.get("counters", {})
              .get("ivf_pq.search.fused_fallback", 0)
              - before.get("counters", {})
              .get("ivf_pq.search.fused_fallback", 0))
        out[mode] = {"stages": stages, "fused_fallback_ticks": fb}
    return out


def _fused_windowed_grid(res, index, queries) -> list:
    """Round-14 grid: the windowed fused-scan merge engine across
    (k, merge_window) at batch :data:`FUSED_WINDOWED_BATCH` and matched
    kt.  Results are bit-identical across W (the merge is
    order-insensitive over the finite-sentinel staging ring) — only QPS
    moves, so the grid reports QPS plus the fused_fallback tick delta
    that proves the fused kernel actually served the point (large k is
    exactly where the old per-step merge used to fall back)."""
    from raft_tpu import observability as obs
    from raft_tpu.neighbors import ivf_pq

    q = queries[:FUSED_WINDOWED_BATCH]
    points = []
    for k, mw in FUSED_WINDOWED_GRID:
        sp = ivf_pq.SearchParams(n_probes=72, scan_mode="fused",
                                 per_probe_topk=FUSED_WINDOWED_KT,
                                 merge_window=mw or "auto")
        with obs.collecting() as reg:
            before = reg.snapshot()["counters"].get(
                "ivf_pq.search.fused_fallback", 0)
            d, i = ivf_pq.search(res, sp, index, q, k)       # warm
            np.asarray(i)
            after = reg.snapshot()["counters"].get(
                "ivf_pq.search.fused_fallback", 0)
        _check_sane("ivf_pq_fused_windowed", i, N_DB, d)
        t0 = time.perf_counter()
        for _ in range(RUNS):
            _, i = ivf_pq.search(res, sp, index, q, k)
        np.asarray(i)
        qps = q.shape[0] / ((time.perf_counter() - t0) / RUNS)
        point = {"k": k, "merge_window": mw or "auto",
                 "batch": int(q.shape[0]), "kt": FUSED_WINDOWED_KT,
                 "qps": round(qps, 1),
                 "fused_fallback_ticks": after - before}
        _emit({"fused_windowed_point": point})
        points.append(point)
    return points


def bench_ivf_pq(res, db, queries, gt_i=None) -> dict:
    from raft_tpu.neighbors import ivf_pq

    # ground truth (the bench's naive_knn analogue)
    if gt_i is None:
        gt_i = _ground_truth(res, db, queries)

    from raft_tpu import observability as obs

    params = ivf_pq.IndexParams(n_lists=N_LISTS, pq_dim=PQ_DIM,
                                kmeans_n_iters=20)
    t0 = time.perf_counter()
    with obs.collecting():
        index = ivf_pq.build(res, params, db)
        index.list_codes.block_until_ready()
    build_s = time.perf_counter() - t0
    _print_stage_breakdown("ivf_pq", index)
    stage_probe = _search_stage_probe(res, index, queries)
    _emit({"search_stage_probe": stage_probe})
    windowed_points = _fused_windowed_grid(res, index, queries)

    from raft_tpu.neighbors.refine import refine as refine_fn

    def run_point(pt):
        """One operating point; refine_ratio>1 adds the reference harness's
        raft_ivf_pq refine pass (exact re-rank of K*ratio candidates)."""
        n_probes = pt["n_probes"]
        refine_ratio = pt.get("refine_ratio", 1)
        sp = ivf_pq.SearchParams(
            n_probes=n_probes,
            scan_mode=pt.get("scan_mode", "auto"),
            per_probe_topk=pt.get("per_probe_topk", 0),
            packed_extract=pt.get("packed_extract", False),
            merge_window=pt.get("merge_window", "auto"))
        kk = K * refine_ratio

        def query():
            d, i = ivf_pq.search(res, sp, index, queries, kk)
            if refine_ratio > 1:
                d, i = refine_fn(res, db, queries, i, K)
            return d, i

        d, i = query()                                     # warmup/compile
        _check_sane("ivf_pq", i, N_DB, d)
        recall = _recall(np.asarray(i), gt_i)
        t0 = time.perf_counter()
        for _ in range(RUNS):
            _, i = query()
        # host readback, not block_until_ready: the latter has been observed
        # to return early over the remote-tunnel backend, overstating QPS
        np.asarray(i)
        qps = N_QUERIES / ((time.perf_counter() - t0) / RUNS)
        out = dict(pt)
        out.update(recall=round(recall, 4), qps=round(qps, 1))
        return out

    best = None
    points = []
    for pt in OPERATING_POINTS:
        point = run_point(pt)
        _emit({"op_point": point})
        if point["recall"] >= MIN_RECALL and (
                best is None or point["qps"] > best["qps"]):
            best = point
        points.append(point)
    chosen = best or points[-1]
    met = chosen["recall"] >= MIN_RECALL
    from raft_tpu.neighbors import grouped
    return {
        "metric": (f"ivf_pq_qps@recall{MIN_RECALL:.2f}" if met
                   else f"ivf_pq_qps@recall={chosen['recall']:.3f}"
                        "(below_target)"),
        "value": chosen["qps"],
        "unit": "queries/s",
        "vs_baseline": round(chosen["qps"] / QPS_REFERENCE_POINT, 3),
        "detail": {"n_db": N_DB, "dim": DIM, "n_lists": N_LISTS,
                   "pq_dim": PQ_DIM, "batch": N_QUERIES, "k": K,
                   "build_s": round(build_s, 1),
                   "recall_at_qps2000": _recall_at_qps(points),
                   # static HBM traffic model per scan mode (the round-6
                   # decomposition profile measures the same quantities)
                   "scan_bytes_per_row": grouped.scan_traffic(
                       index.rot_dim, index.pq_dim, index.pq_bits),
                   "search_stage_probe": stage_probe,
                   "fused_windowed_grid": windowed_points,
                   "operating_point": chosen},
    }


# CAGRA operating points: (itopk, search_width) — the reference conf's
# itopk/search_width sweep (cpp/bench/ann/conf sift cagra entries)
CAGRA_POINTS = ((16, 1), (24, 1), (32, 1), (32, 2), (64, 2))


def bench_cagra(res, db, queries, gt_i=None) -> dict:
    """Graph index at the headline workload (the reference's flagship
    ANN index).  QPS at recall >= 0.95, packed-neighborhood walk."""
    from raft_tpu.neighbors import cagra

    if gt_i is None:
        gt_i = _ground_truth(res, db, queries)
    t0 = time.perf_counter()
    index = cagra.build(res, cagra.IndexParams(graph_degree=64), db)
    np.asarray(index.graph[0, 0])
    build_s = time.perf_counter() - t0
    # second build on the warm process: the steady-state number a
    # serving deployment rebuilding its index actually sees (the cold
    # number above includes one-time XLA compiles).  Stage collection
    # runs on this build only — the per-stage fences land on boundaries
    # the warm build already host-syncs, so the headline stays honest.
    from raft_tpu import observability as obs

    t0 = time.perf_counter()
    with obs.collecting():
        index = cagra.build(res, cagra.IndexParams(graph_degree=64), db)
        np.asarray(index.graph[0, 0])
    build_warm_s = time.perf_counter() - t0
    _print_stage_breakdown("cagra", index)

    best = None
    points = []
    for itopk, width in CAGRA_POINTS:
        sp = cagra.SearchParams(itopk_size=itopk, search_width=width)
        d, i = cagra.search(res, sp, index, queries, K)   # warmup
        _check_sane("cagra", i, N_DB, d)
        recall = _recall(np.asarray(i), gt_i)
        t0 = time.perf_counter()
        for _ in range(RUNS):
            i = cagra.search(res, sp, index, queries, K)[1]
        np.asarray(i)
        qps = N_QUERIES / ((time.perf_counter() - t0) / RUNS)
        point = {"itopk": itopk, "search_width": width,
                 "recall": round(recall, 4), "qps": round(qps, 1)}
        _emit({"cagra_op_point": point})
        if point["recall"] >= MIN_RECALL and (
                best is None or point["qps"] > best["qps"]):
            best = point
        points.append(point)
    chosen = best or points[-1]
    met = chosen["recall"] >= MIN_RECALL
    return {
        "metric": (f"cagra_qps@recall{MIN_RECALL:.2f}" if met
                   else f"cagra_qps@recall={chosen['recall']:.3f}"
                        "(below_target)"),
        "value": chosen["qps"],
        "unit": "queries/s",
        "vs_baseline": round(chosen["qps"] / QPS_REFERENCE_POINT, 3),
        "detail": {"n_db": N_DB, "dim": DIM, "graph_degree": 64,
                   "batch": N_QUERIES, "k": K,
                   "build_s": round(build_s, 1),
                   "build_warm_s": round(build_warm_s, 1),
                   "recall_at_qps2000": _recall_at_qps(points),
                   "operating_point": chosen},
    }


KMEANS_WINDOWS = 5


def bench_kmeans(res, X) -> dict:
    from raft_tpu.cluster import kmeans
    from raft_tpu.cluster.kmeans_types import InitMethod, KMeansParams

    # Random init + tol=0: the timed region is KMEANS_ITERS Lloyd
    # iterations (iter/s is the metric; ++ init would dominate the timing)
    params = KMeansParams(n_clusters=KMEANS_K, max_iter=KMEANS_ITERS,
                          tol=0.0, n_init=1, init=InitMethod.Random)
    c, _, _ = kmeans.fit(res, params, X)       # warmup/compile
    assert np.isfinite(np.asarray(c)).all(), "kmeans: non-finite centroids"
    np.asarray(c)   # forced readback: block_until_ready can return early
                    # over the remote tunnel, bleeding the warmup's
                    # remote compile + execution into the timed region
    # median of KMEANS_WINDOWS timed windows: a single window has been
    # observed to catch background-compile / tunnel jitter; the median is
    # the robust per-window estimate the driver tracks across rounds
    windows = []
    for _ in range(KMEANS_WINDOWS):
        t0 = time.perf_counter()
        c, inertia, n_iter = kmeans.fit(res, params, X)
        np.asarray(c)       # host readback (see bench_ivf_pq note)
        windows.append(time.perf_counter() - t0)
    elapsed = float(np.median(windows))
    iters_per_s = KMEANS_ITERS / elapsed
    return {
        "metric": "kmeans_iters_per_s_1Mx128_k1024",
        "value": round(iters_per_s, 3),
        "unit": "iter/s",
        "vs_baseline": round(iters_per_s, 3),
        "detail": {"n": KMEANS_N, "dim": DIM, "k": KMEANS_K,
                   "n_iter": KMEANS_ITERS,
                   "fit_s": round(elapsed, 2),
                   "fit_windows_s": [round(w, 2) for w in windows]},
    }


# IVF-Flat operating points (BASELINE.md config 4 runs IVF-Flat before
# IVF-PQ at the same nlist)
IVF_FLAT_POINTS = (16, 32, 64, 128)


def bench_ivf_flat(res, db, queries, gt_i=None) -> dict:
    from raft_tpu import observability as obs
    from raft_tpu.neighbors import ivf_flat

    if gt_i is None:
        gt_i = _ground_truth(res, db, queries)
    t0 = time.perf_counter()
    with obs.collecting():
        index = ivf_flat.build(res, ivf_flat.IndexParams(n_lists=N_LISTS),
                               db)
        index.list_data.block_until_ready()
    build_s = time.perf_counter() - t0
    _print_stage_breakdown("ivf_flat", index)

    best = None
    points = []
    for n_probes in IVF_FLAT_POINTS:
        sp = ivf_flat.SearchParams(n_probes=n_probes)
        d, i = ivf_flat.search(res, sp, index, queries, K)   # warmup
        _check_sane("ivf_flat", i, N_DB, d)
        recall = _recall(np.asarray(i), gt_i)
        t0 = time.perf_counter()
        for _ in range(RUNS):
            i = ivf_flat.search(res, sp, index, queries, K)[1]
        np.asarray(i)       # host readback (see bench_ivf_pq note)
        qps = N_QUERIES / ((time.perf_counter() - t0) / RUNS)
        point = {"n_probes": n_probes, "recall": round(recall, 4),
                 "qps": round(qps, 1)}
        _emit({"ivf_flat_op_point": point})
        if point["recall"] >= MIN_RECALL and (
                best is None or point["qps"] > best["qps"]):
            best = point
        points.append(point)
    chosen = best or points[-1]
    met = chosen["recall"] >= MIN_RECALL
    return {
        "metric": (f"ivf_flat_qps@recall{MIN_RECALL:.2f}" if met
                   else f"ivf_flat_qps@recall={chosen['recall']:.3f}"
                        "(below_target)"),
        "value": chosen["qps"],
        "unit": "queries/s",
        "vs_baseline": round(chosen["qps"] / QPS_REFERENCE_POINT, 3),
        "detail": {"n_db": N_DB, "dim": DIM, "n_lists": N_LISTS,
                   "batch": N_QUERIES, "k": K,
                   "build_s": round(build_s, 1),
                   "recall_at_qps2000": _recall_at_qps(points),
                   "operating_point": chosen},
    }


BF_N = 100_000
BF_K = 64


def bench_brute_force(res, db, queries) -> dict:
    """BASELINE.md config 2: brute-force kNN + fusedL2NN, 100k x 128,
    k=64 — exact, so the metric is pure throughput."""
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn
    from raft_tpu.neighbors import brute_force

    sub = db[:BF_N]
    d, i = brute_force.knn(res, sub, queries, BF_K)          # warmup
    _check_sane("bfknn", i, BF_N, d)
    t0 = time.perf_counter()
    for _ in range(RUNS):
        i = brute_force.knn(res, sub, queries, BF_K)[1]
    np.asarray(i)           # host readback (see bench_ivf_pq note)
    qps = N_QUERIES / ((time.perf_counter() - t0) / RUNS)

    v, fi = fused_l2_nn(queries, sub)                        # warmup
    _check_sane("fused_l2_nn", fi, BF_N, v)
    t0 = time.perf_counter()
    for _ in range(RUNS):
        v, fi = fused_l2_nn(queries, sub)
    np.asarray(fi)
    fused_qps = N_QUERIES / ((time.perf_counter() - t0) / RUNS)
    return {
        "metric": f"bfknn_qps_100kx{DIM}_k{BF_K}",
        "value": round(qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(qps / QPS_REFERENCE_POINT, 3),
        "detail": {"n_db": BF_N, "dim": DIM, "batch": N_QUERIES,
                   "k": BF_K,
                   "fused_l2_nn_qps": round(fused_qps, 1)},
    }


SERVING_N = 100_000            # 100k-index serving smoke (CI job)
SERVING_MAX_BATCH = 256
SERVING_K = 10


def bench_serving(res, db, queries, *, build_param=None, search_param=None,
                  k=SERVING_K, max_batch=SERVING_MAX_BATCH,
                  max_wait_us=1000.0, clients=8, request_rows=32,
                  duration_s=2.0, offered_fraction=0.7,
                  large_k=None) -> list:
    """Online serving over a warmed IVF-PQ index vs the raw batch path.

    Closed loop (``clients`` synchronous threads, ``request_rows`` rows
    per request) measures ``serving_qps_sustained``; the acceptance bar
    is >= 80% of raw-batch QPS at the same (index, params, max_batch)
    operating point.  The closed loop runs TWICE — tracing off, then
    tracing on (metrics collection is on in both arms, so the A/B
    isolates the tracing hooks) — and the ratio is emitted as
    ``serving_tracing_overhead`` (CI fails the smoke when tracing costs
    more than the conf's ``max_tracing_overhead``).  Open loop at
    ``offered_fraction`` of the measured capacity runs with tracing on
    and reports ``serving_p99_ms`` (client-observed submit->result,
    cross-checked against the ``serving.latency.total`` histogram) plus
    the mean per-span breakdown of the traces landed in the flight
    recorder.  The ``xla.compiles`` counter is sampled around the whole
    measured window — steady state must be recompile-free *with tracing
    enabled* (the closed bucket-shape contract; CI fails the smoke job
    otherwise).  When the conf declares a ``large_k`` bucket, that k is
    added to the executor's closed k set and replayed inside the
    measured window: the AOT cache key carries ``merge_window`` for
    fused large-k plans, and the zero-recompile assertion must hold
    across that dimension too.
    """
    import threading

    import jax
    import jax.numpy as jnp

    from raft_tpu import observability as obs
    from raft_tpu import serving
    from raft_tpu.observability import flight as _flight
    from raft_tpu.observability import trace as _trace
    from raft_tpu.neighbors import ivf_pq

    bp = build_param or {"nlist": 1024, "pq_dim": 32}
    spc = search_param or {"nprobe": 32}
    index = ivf_pq.build(
        res, ivf_pq.IndexParams(n_lists=bp["nlist"], pq_dim=bp["pq_dim"],
                                kmeans_n_iters=bp.get("kmeans_n_iters", 10)),
        db)
    sp = ivf_pq.SearchParams(n_probes=spc["nprobe"],
                             scan_mode=spc.get("scan_mode", "auto"),
                             per_probe_topk=spc.get("per_probe_topk", 0))
    q = np.asarray(queries)                 # clients submit host data
    reps = int(np.ceil(max_batch / q.shape[0])) if q.shape[0] < max_batch \
        else 1
    if reps > 1:
        q = np.concatenate([q] * reps)

    # raw batch reference: full max_batch batches, per-batch readback
    # (matches the serving dispatch, which reads each batch back)
    qb = jnp.asarray(q[:max_batch])
    d, i = ivf_pq.search(res, sp, index, qb, k)            # warmup
    jax.block_until_ready((d, i))
    iters = max(8, int(2.0 / max(_timed_batch(res, sp, index, qb, k), 1e-4)))
    iters = min(iters, 200)
    t0 = time.perf_counter()
    for _ in range(iters):
        d, i = ivf_pq.search(res, sp, index, qb, k)
        np.asarray(i)
    raw_qps = iters * max_batch / (time.perf_counter() - t0)

    ks = (k,) if not large_k else (k, int(large_k))
    ex = serving.Executor(res, "ivf_pq", index, ks=ks,
                          max_batch=max_batch, search_params=sp)
    out = []
    with obs.collecting():
        cfg = serving.ServerConfig(max_batch=max_batch,
                                   max_wait_us=max_wait_us,
                                   max_queue_rows=max_batch * 16)
        with serving.Server(ex, cfg) as srv:
            # ramp: settle residual one-time compiles (host transfers,
            # mask ops) before the measured window
            for m in (1, request_rows, max_batch):
                srv.search(q[:m], k)
            if large_k:
                srv.search(q[:request_rows], int(large_k))
            c0 = obs.registry().counter("xla.compiles").value

            # ---- closed loop: tracing off, then tracing on ----------
            def closed_loop():
                done = [0] * clients
                stop_at = time.perf_counter() + duration_s

                def client(j):
                    base = (j * 131) % max(1, q.shape[0] - request_rows)
                    sub = q[base:base + request_rows]
                    while time.perf_counter() < stop_at:
                        srv.search(sub, k)
                        done[j] += sub.shape[0]

                ts = [threading.Thread(target=client, args=(j,))
                      for j in range(clients)]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                return sum(done) / (time.perf_counter() - t0)

            serving_qps = closed_loop()
            with _trace.tracing_scope():
                traced_qps = closed_loop()
            # large-k bucket replay inside the measured window: its AOT
            # plan (keyed on merge_window for fused scans) was warmed at
            # start(), so these must hit the cache without a compile
            if large_k:
                for _ in range(4):
                    srv.search(q[:request_rows], int(large_k))
            # sampled AFTER the traced arm: tracing must add zero
            # compiles on warmed traffic, not just zero in its own arm
            recompiles = (obs.registry().counter("xla.compiles").value
                          - c0)

            # ---- open loop (tracing on: feeds the span breakdown) ---
            rate = max(serving_qps * offered_fraction, request_rows)
            interval = request_rows / rate
            lats, futs = [], []
            _flight.clear()
            with _trace.tracing_scope():
                t_end = time.perf_counter() + duration_s
                next_t = time.perf_counter()
                while time.perf_counter() < t_end:
                    lag = next_t - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                    t_sub = time.perf_counter()
                    f = srv.submit(q[:request_rows], k)
                    f.add_done_callback(
                        lambda fut, t=t_sub:
                        lats.append(time.perf_counter() - t))
                    futs.append(f)
                    next_t += interval
                for f in futs:
                    f.result(timeout=30.0)
            snap = obs.snapshot()
        hist = snap.get("histograms", {}).get("serving.latency.total", {})
        fill = snap.get("histograms", {}).get("serving.batch_fill", {})

    # mean per-span breakdown of the open-loop traces (flight ring keeps
    # the last DEFAULT_CAPACITY of them — enough for a mean)
    traced = _flight.traces()
    per_span: dict = {}
    for tr in traced:
        for sub_span in tr.spans:
            per_span.setdefault(sub_span.name, []).append(
                sub_span.duration)
    span_breakdown = {name: round(float(np.mean(v)) * 1e3, 4)
                      for name, v in sorted(per_span.items())}
    p50, p95, p99 = (float(v) * 1e3
                     for v in np.percentile(lats, [50, 95, 99]))
    out.append({
        "metric": "serving_qps_sustained",
        "value": round(serving_qps, 1),
        "unit": "rows/s",
        "vs_baseline": round(serving_qps / max(raw_qps, 1e-9), 3),
        "detail": {"raw_batch_qps": round(raw_qps, 1),
                   "fraction_of_raw": round(serving_qps
                                            / max(raw_qps, 1e-9), 3),
                   "recompiles_steady": int(recompiles),
                   "clients": clients, "request_rows": request_rows,
                   "max_batch": max_batch, "max_wait_us": max_wait_us,
                   "large_k": int(large_k) if large_k else None,
                   "batch_fill_p50": fill.get("p50")},
    })
    frac = traced_qps / max(serving_qps, 1e-9)
    out.append({
        "metric": "serving_tracing_overhead",
        "value": round(max(1.0 - frac, 0.0), 4),
        "unit": "fraction",
        "vs_baseline": round(frac, 3),
        "detail": {"qps_tracing_off": round(serving_qps, 1),
                   "qps_tracing_on": round(traced_qps, 1),
                   "fraction_of_untraced": round(frac, 3),
                   "recompiles_with_tracing": int(recompiles)},
    })
    out.append({
        "metric": "serving_p99_ms",
        "value": round(p99, 3),
        "unit": "ms",
        "vs_baseline": 1.0,
        "detail": {"p50_ms": round(p50, 3), "p95_ms": round(p95, 3),
                   "offered_rows_per_s": round(rate, 1),
                   "requests": len(lats),
                   "traced_requests": len(traced),
                   "span_breakdown_ms": span_breakdown,
                   "hist_p99_ms": (round(hist["p99"] * 1e3, 3)
                                   if hist.get("p99") is not None
                                   else None)},
    })
    return out


def _timed_batch(res, sp, index, qb, k) -> float:
    from raft_tpu.neighbors import ivf_pq
    t0 = time.perf_counter()
    np.asarray(ivf_pq.search(res, sp, index, qb, k)[1])
    return time.perf_counter() - t0


def run_serving(conf_path: str) -> int:
    """``--serving`` mode: the CI serving smoke.  Builds the conf's
    dataset + index, runs :func:`bench_serving`, prints its metric
    lines, and FAILS (exit 1) on steady-state recompiles or sustained
    throughput under ``min_qps_fraction_of_raw``."""
    from raft_tpu import DeviceResources

    with open(conf_path) as f:
        conf = json.load(f)
    res = DeviceResources(seed=0)
    db, queries = _make_dataset(conf["dataset"])
    s = conf["serving"]
    lines = bench_serving(
        res, db, queries,
        build_param=s.get("build_param"),
        search_param=s.get("search_param"),
        k=s.get("k", SERVING_K),
        max_batch=s.get("max_batch", SERVING_MAX_BATCH),
        max_wait_us=s.get("max_wait_us", 1000.0),
        clients=s.get("clients", 8),
        request_rows=s.get("request_rows", 32),
        duration_s=s.get("duration_s", 2.0),
        offered_fraction=s.get("offered_fraction", 0.7),
        large_k=s.get("large_k"))
    for line in lines:
        _emit(line)
    qps_line = lines[0]["detail"]
    failures = []
    if qps_line["recompiles_steady"] != 0:
        failures.append(f"{qps_line['recompiles_steady']} XLA recompiles "
                        "in steady state (want 0 after warmup)")
    bar = s.get("min_qps_fraction_of_raw", 0.8)
    if qps_line["fraction_of_raw"] < bar:
        failures.append(
            f"sustained serving QPS is {qps_line['fraction_of_raw']:.2f}x "
            f"raw batch QPS (bar: {bar:.2f}x)")
    overhead = next(ln for ln in lines
                    if ln["metric"] == "serving_tracing_overhead")
    max_overhead = s.get("max_tracing_overhead", 0.05)
    traced_frac = overhead["detail"]["fraction_of_untraced"]
    if traced_frac < 1.0 - max_overhead:
        failures.append(
            f"tracing-enabled QPS is {traced_frac:.2f}x the untraced "
            f"loop (bar: {1.0 - max_overhead:.2f}x)")
    for msg in failures:
        print(f"SERVING SMOKE FAIL: {msg}", flush=True)
    if failures:
        from raft_tpu.observability import flight as _flight
        dumped = _flight.maybe_auto_dump("serving_smoke_failure")
        if dumped:
            print(f"flight dump: {dumped}", flush=True)
    return 1 if failures else 0


OVERLOAD_MULTIPLIERS = (0.5, 1.0, 1.5, 2.0)
#: cumulative shed counters sampled around each overload step
_SHED_COUNTERS = ("serving.shed.deadline", "serving.shed.queue_full",
                  "serving.shed.quota", "serving.shed.brownout")


def bench_overload(res, db, queries, *, build_param=None, search_param=None,
                   k=SERVING_K, max_batch=SERVING_MAX_BATCH,
                   max_wait_us=1000.0, clients=8, request_rows=64,
                   step_duration_s=2.0, deadline_s=0.25,
                   load_multipliers=OVERLOAD_MULTIPLIERS,
                   ladder_divisors=(2, 4), best_effort_fraction=0.25,
                   brownout_conf=None) -> list:
    """Open-loop offered-load sweep with and without brownout control.

    Measures the closed-loop 1x peak (``clients`` synchronous threads at
    full quality — the capacity reference every offered rate is a
    multiple of), then replays an open-loop sweep at
    ``load_multipliers`` x peak TWICE: controller OFF (static admission
    only) and controller ON (the declared ladder: full quality, one rung
    per ``ladder_divisors`` entry at ``n_probes // d``, then a
    best-effort-shedding top rung).  Every request carries a
    ``deadline_s`` deadline and **goodput counts only rows answered
    within it** — late answers and sheds are wasted capacity either way,
    which is exactly the collapse static admission exhibits at 2x.

    Per step the bench emits an ``overload_point`` line with goodput,
    admitted p99, per-counter shed fractions, and the brownout-level
    residency delta; the summary lines are ``overload_goodput_2x`` with
    the controller (``vs_baseline`` = fraction of the closed-loop peak —
    the CI gate) and ``overload_goodput_2x_off`` without it.  The
    ``xla.compiles`` counter is sampled around each arm's whole measured
    window: brownout transitions must be recompile-free (every rung is
    pre-warmed through the AOT cache at ``Server.start()``).
    """
    import threading

    from raft_tpu import observability as obs
    from raft_tpu import serving
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.resilience.retry import Deadline

    bp = build_param or {"nlist": 1024, "pq_dim": 32}
    spc = search_param or {"nprobe": 32}
    index = ivf_pq.build(
        res, ivf_pq.IndexParams(n_lists=bp["nlist"], pq_dim=bp["pq_dim"],
                                kmeans_n_iters=bp.get("kmeans_n_iters", 10)),
        db)

    def _params(n_probes):
        return ivf_pq.SearchParams(
            n_probes=n_probes, scan_mode=spc.get("scan_mode", "auto"),
            per_probe_topk=spc.get("per_probe_topk", 0))

    sp = _params(spc["nprobe"])
    ladder = [serving.Rung("full")]
    ladder += [serving.Rung(f"probes/{d}", params=_params(
        max(1, spc["nprobe"] // d))) for d in ladder_divisors]
    ladder.append(serving.Rung("shed-best-effort", shed_best_effort=True))
    bc = brownout_conf or {}
    bcfg = serving.BrownoutConfig(
        step_down_p99_s=bc.get("step_down_p99_s", deadline_s * 0.5),
        step_up_p99_s=bc.get("step_up_p99_s", deadline_s * 0.1),
        queue_high_fraction=bc.get("queue_high_fraction", 0.5),
        queue_low_fraction=bc.get("queue_low_fraction", 0.125),
        shed_step_down=bc.get("shed_step_down", 1),
        dwell_s=bc.get("dwell_s", 0.5),
        interval_s=bc.get("interval_s", 0.1))
    q = np.asarray(queries)
    if q.shape[0] < max_batch:
        q = np.concatenate([q] * int(np.ceil(max_batch / q.shape[0])))
    # every Nth request is the best-effort tenant — the load the shed
    # rung is allowed to drop to protect the paying tenant's deadline
    be_every = (int(round(1.0 / best_effort_fraction))
                if best_effort_fraction > 0 else 0)

    def closed_loop(srv):
        done = [0] * clients
        stop_at = time.perf_counter() + step_duration_s

        def client(j):
            base = (j * 131) % max(1, q.shape[0] - request_rows)
            sub = q[base:base + request_rows]
            while time.perf_counter() < stop_at:
                srv.search(sub, k)
                done[j] += sub.shape[0]

        ts = [threading.Thread(target=client, args=(j,))
              for j in range(clients)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return sum(done) / (time.perf_counter() - t0)

    def open_loop_step(srv, rate):
        """One offered-load step: paced submits at ``rate`` rows/s,
        goodput = rows answered within the request deadline."""
        rec, futs = [], []
        shed_submit = n_requests = 0
        interval = request_rows / rate
        t_start = time.perf_counter()
        t_end = t_start + step_duration_s
        next_t = t_start
        while time.perf_counter() < t_end:
            lag = next_t - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
            tenant = ("batch" if be_every and n_requests % be_every == 0
                      else "default")
            t_sub = time.perf_counter()
            try:
                f = srv.submit(q[:request_rows], k, tenant=tenant,
                               deadline=Deadline(deadline_s))
            except serving.Overloaded:
                shed_submit += 1
            else:
                f.add_done_callback(
                    lambda fut, t=t_sub: rec.append(
                        (time.perf_counter() - t, fut.exception() is None)))
                futs.append(f)
            n_requests += 1
            next_t += interval
        for f in futs:
            try:
                f.result(timeout=30.0)
            except Exception:  # noqa: BLE001 - sheds surface as exceptions
                pass
        elapsed = time.perf_counter() - t_start
        good = [lat for lat, ok in rec if ok and lat <= deadline_s]
        return {
            "offered_rows_per_s": round(n_requests * request_rows
                                        / elapsed, 1),
            "goodput_rows_per_s": round(len(good) * request_rows
                                        / elapsed, 1),
            "requests": n_requests,
            "shed_at_submit": shed_submit,
            "admitted_p99_ms": (round(float(
                np.percentile(good, 99)) * 1e3, 3) if good else None),
        }

    def run_arm(with_controller, peak):
        # each arm starts from a clean registry: the off arm's windowed
        # shed counts and latency samples stay visible for a full window
        # (60s) and would otherwise feed the on arm's controller a
        # pressure signal from load it never saw
        obs.reset()
        ex = serving.Executor(res, "ivf_pq", index, ks=(k,),
                              max_batch=max_batch, search_params=sp)
        cfg = serving.ServerConfig(max_batch=max_batch,
                                   max_wait_us=max_wait_us,
                                   max_queue_rows=max_batch * 8)
        srv = serving.Server(ex, cfg)
        ctl = (serving.BrownoutController(srv, ladder, bcfg,
                                          best_effort_tenants={"batch"})
               if with_controller else None)
        srv.start()
        compiles = obs.registry().counter("xla.compiles")
        try:
            for m in (1, request_rows, max_batch):
                srv.search(q[:m], k)
            c0 = compiles.value
            if peak is None:
                peak = closed_loop(srv)
            if ctl is not None:
                ctl.start()
            points = []
            for mult in load_multipliers:
                shed0 = {n: obs.registry().counter(n).value
                         for n in _SHED_COUNTERS}
                res0 = ctl.stats()["residency_s"] if ctl else None
                step = open_loop_step(srv, max(mult * peak, request_rows))
                offered = step["requests"] * request_rows
                step["shed_fractions"] = {
                    n.removeprefix("serving.shed."):
                        round((obs.registry().counter(n).value - shed0[n])
                              * request_rows / max(offered, 1), 4)
                    for n in _SHED_COUNTERS}
                if ctl is not None:
                    res1 = ctl.stats()["residency_s"]
                    step["brownout_residency_s"] = {
                        name: round(res1[name] - res0[name], 2)
                        for name in res1}
                    step["level_end"] = ctl.state.level
                point = dict(step, multiplier=mult,
                             controller=with_controller)
                _emit({"overload_point": point})
                points.append(point)
            return peak, points, int(compiles.value - c0)
        finally:
            if ctl is not None:
                ctl.stop()
            srv.stop()

    out = []
    with obs.collecting():
        peak, points_off, recompiles_off = run_arm(False, None)
        _, points_on, recompiles_on = run_arm(True, peak)

    def at_2x(points):
        return max(points, key=lambda p: p["multiplier"])

    top_on, top_off = at_2x(points_on), at_2x(points_off)
    out.append({
        "metric": "overload_goodput_2x",
        "value": top_on["goodput_rows_per_s"],
        "unit": "rows/s",
        "vs_baseline": round(top_on["goodput_rows_per_s"]
                             / max(peak, 1e-9), 3),
        "detail": {"closed_loop_peak_rows_per_s": round(peak, 1),
                   "multiplier": top_on["multiplier"],
                   "controller": True,
                   "recompiles_steady": recompiles_on,
                   "deadline_s": deadline_s,
                   "ladder": [r.name for r in ladder],
                   "admitted_p99_ms": top_on["admitted_p99_ms"],
                   "shed_fractions": top_on["shed_fractions"],
                   "brownout_residency_s":
                       top_on.get("brownout_residency_s"),
                   "points": points_on},
    })
    out.append({
        "metric": "overload_goodput_2x_off",
        "value": top_off["goodput_rows_per_s"],
        "unit": "rows/s",
        "vs_baseline": round(top_off["goodput_rows_per_s"]
                             / max(peak, 1e-9), 3),
        "detail": {"closed_loop_peak_rows_per_s": round(peak, 1),
                   "multiplier": top_off["multiplier"],
                   "controller": False,
                   "recompiles_steady": recompiles_off,
                   "deadline_s": deadline_s,
                   "admitted_p99_ms": top_off["admitted_p99_ms"],
                   "shed_fractions": top_off["shed_fractions"],
                   "points": points_off},
    })
    return out


def run_overload(conf_path: str) -> int:
    """``--overload`` mode: the CI chaos smoke.  Builds the conf's
    dataset, activates the conf's seed-pinned latency plan (the
    ``serving.dispatch`` site — injected slowness is what turns 2x
    offered load into a real brownout), runs :func:`bench_overload`,
    and FAILS (exit 1) on goodput collapse at 2x with the controller,
    steady-state recompiles, or a missing brownout event trail."""
    from raft_tpu import DeviceResources
    from raft_tpu.observability import flight as _flight
    from raft_tpu.resilience import faults

    with open(conf_path) as f:
        conf = json.load(f)
    res = DeviceResources(seed=0)
    db, queries = _make_dataset(conf["dataset"])
    s = conf["serving"]
    o = conf.get("overload", {})
    plan = faults.FaultPlan()          # seed pinned via RAFT_TPU_FAULT_SEED
    for fp in o.get("faults", ()):
        plan.delay_at(fp["site"], delay=fp["delay"],
                      jitter=fp.get("jitter", 0.0))
    _flight.clear()
    with plan.active():
        lines = bench_overload(
            res, db, queries,
            build_param=s.get("build_param"),
            search_param=s.get("search_param"),
            k=s.get("k", SERVING_K),
            max_batch=s.get("max_batch", SERVING_MAX_BATCH),
            max_wait_us=s.get("max_wait_us", 1000.0),
            clients=s.get("clients", 8),
            request_rows=o.get("request_rows", 64),
            step_duration_s=o.get("step_duration_s", 2.0),
            deadline_s=o.get("deadline_s", 0.25),
            load_multipliers=tuple(o.get("load_multipliers",
                                         OVERLOAD_MULTIPLIERS)),
            ladder_divisors=tuple(o.get("ladder_divisors", (2, 4))),
            best_effort_fraction=o.get("best_effort_fraction", 0.25),
            brownout_conf=o.get("brownout"))
    for line in lines:
        _emit(line)
    on = next(ln for ln in lines if ln["metric"] == "overload_goodput_2x")
    failures = []
    bar = o.get("min_goodput_fraction_at_2x", 0.7)
    if on["vs_baseline"] < bar:
        failures.append(
            f"goodput collapse: {on['vs_baseline']:.2f}x the closed-loop "
            f"peak at 2x offered load WITH the controller (bar: {bar:.2f}x)")
    if on["detail"]["recompiles_steady"] != 0:
        failures.append(
            f"{on['detail']['recompiles_steady']} XLA recompiles during "
            "the controller sweep (brownout transitions must be "
            "recompile-free)")
    if not _flight.events("serving.brownout.step_down"):
        failures.append("no serving.brownout.step_down events landed in "
                        "the flight recorder — the controller never "
                        "engaged under 2x offered load")
    for msg in failures:
        print(f"OVERLOAD SMOKE FAIL: {msg}", flush=True)
    if failures:
        dumped = _flight.maybe_auto_dump("overload_smoke_failure")
        if dumped:
            print(f"flight dump: {dumped}", flush=True)
    return 1 if failures else 0


INGEST_WRITE_ROWS = 32         # rows per Server.write() batch


def bench_ingest(res, db, queries, *, build_param=None, search_param=None,
                 k=SERVING_K, max_batch=SERVING_MAX_BATCH,
                 max_wait_us=1000.0, clients=8, request_rows=32,
                 duration_s=2.0, write_rows=INGEST_WRITE_ROWS,
                 write_multiplier=2.0, write_rate_rows_per_s=None,
                 memtable_capacity=1 << 16, calib_s=0.5,
                 wal_dir=None) -> list:
    """Durable streaming ingest (PR 13) under concurrent serving load.

    One IVF-PQ server with the WAL-backed delta tier attached, three
    phases:

    1. closed-loop READ baseline — delta merge warmed, no writer;
    2. calibrate the closed-loop write peak (one synchronous writer:
       WAL append + fsync group commit + memtable apply per batch),
       then an OPEN-LOOP writer at ``write_multiplier`` x the target
       rate — ``write_rate_rows_per_s`` when the conf pins one (the
       smoke operating point: a host-peak-relative rate saturates a
       CPU core with fsync spin and measures GIL contention, not the
       serving path), else the calibrated peak —
       concurrent with the same closed-loop readers — writes the
       admission path can't absorb shed with typed ``Overloaded``
       (backpressure by design, counted, never crashing the writer);
    3. kill-and-recover — drop the ingest server without folding,
       replay the WAL into a fresh one, and verify EVERY acked id is
       present: the zero-acked-write-loss durability contract.

    Emits ``ingest_writes_per_s`` (acked write throughput + visibility
    p50/p99 from the ``serving.ingest.visibility`` histogram),
    ``ingest_qps_concurrent`` (``vs_baseline`` = fraction of the
    no-writer closed loop — the CI gate, bar 0.8x) and
    ``ingest_recovery`` (acked vs recovered rows, replay wall clock).
    The memtable is pre-sized to ``memtable_capacity`` so it never
    regrows mid-run: ``recompiles_steady`` samples ``xla.compiles``
    across phase 2 and must be zero (the write->search->write loop is
    value-only traffic through shape-static merge kernels)."""
    import shutil
    import tempfile
    import threading

    from raft_tpu import observability as obs
    from raft_tpu import serving
    from raft_tpu.neighbors import ivf_pq

    bp = build_param or {"nlist": 1024, "pq_dim": 32}
    spc = search_param or {"nprobe": 32}
    index = ivf_pq.build(
        res, ivf_pq.IndexParams(n_lists=bp["nlist"], pq_dim=bp["pq_dim"],
                                kmeans_n_iters=bp.get("kmeans_n_iters", 10)),
        db)
    sp = ivf_pq.SearchParams(n_probes=spc["nprobe"],
                             scan_mode=spc.get("scan_mode", "auto"),
                             per_probe_topk=spc.get("per_probe_topk", 0))
    q = np.asarray(queries)
    if q.shape[0] < max_batch:
        q = np.concatenate([q] * int(np.ceil(max_batch / q.shape[0])))
    db_h = np.asarray(db)
    n, dim = db_h.shape
    wrows = np.ascontiguousarray(db_h[:write_rows])
    wal_root = wal_dir or tempfile.mkdtemp(prefix="raft-tpu-bench-ingest-")

    def mk_ingest():
        # max_memtable_rows == capacity: admission sheds before a regrow
        # could change the merge kernel's shapes mid-measurement; tombs
        # sized to match (every first-seen upserted id costs one
        # tombstone masking its potential main-index copy)
        return serving.IngestServer(
            res,
            serving.IngestConfig(wal_dir=os.path.join(wal_root, "wal"),
                                 memtable_capacity=memtable_capacity,
                                 tomb_capacity=memtable_capacity,
                                 max_memtable_rows=memtable_capacity),
            dim=dim)

    out = []
    state = {"acked": [], "shed": 0, "errors": 0}
    next_id = [n]

    def write_batch(srv):
        nid = next_id[0]
        ids = np.arange(nid, nid + write_rows, dtype=np.int64)
        next_id[0] = nid + write_rows
        try:
            srv.write(ids, wrows)
        except serving.Overloaded:
            state["shed"] += 1
            return False
        except Exception:  # noqa: BLE001 - bench keeps writing
            state["errors"] += 1
            return False
        state["acked"].append(nid)
        return True

    with obs.collecting():
        ex = serving.Executor(res, "ivf_pq", index, ks=(k,),
                              max_batch=max_batch, search_params=sp)
        cfg = serving.ServerConfig(max_batch=max_batch,
                                   max_wait_us=max_wait_us,
                                   max_queue_rows=max_batch * 16)
        srv = serving.Server(ex, cfg)
        ig = mk_ingest()
        ig.recover(base_index=index)
        srv.attach_ingest(ig)
        srv.start()
        compiles = obs.registry().counter("xla.compiles")
        try:
            # warm EVERY bucket through the delta merge (one write so
            # the memtable view is live) — the dynamic batcher
            # coalesces concurrent clients into intermediate buckets —
            # then fence the compile count
            write_batch(srv)
            for m in serving.bucket_sizes(max_batch):
                srv.search(q[:m], k)
            c0 = compiles.value

            def closed_loop(dur, lats=None):
                done = [0] * clients
                stop_at = time.perf_counter() + dur

                def client(j):
                    base = (j * 131) % max(1, q.shape[0] - request_rows)
                    sub = q[base:base + request_rows]
                    while time.perf_counter() < stop_at:
                        t0 = time.perf_counter()
                        srv.search(sub, k)
                        if lats is not None:
                            lats.append(time.perf_counter() - t0)
                        done[j] += sub.shape[0]

                ts = [threading.Thread(target=client, args=(j,))
                      for j in range(clients)]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                return sum(done) / (time.perf_counter() - t0)

            # ---- phase 1: no-writer read baseline --------------------
            baseline_qps = closed_loop(duration_s)

            # ---- calibrate the closed-loop write peak ----------------
            stop_at = time.perf_counter() + calib_s
            t0 = time.perf_counter()
            calib_batches = 0
            while time.perf_counter() < stop_at:
                write_batch(srv)
                calib_batches += 1
            write_peak = (calib_batches * write_rows
                          / (time.perf_counter() - t0))

            # ---- phase 2: open-loop writer at 2x, concurrent reads ---
            acked0, shed0 = len(state["acked"]), state["shed"]
            stop_writer = threading.Event()

            def writer():
                base = write_rate_rows_per_s or write_peak
                rate = max(write_multiplier * base, write_rows)
                interval = write_rows / rate
                next_t = time.perf_counter()
                while not stop_writer.is_set():
                    lag = next_t - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                    write_batch(srv)
                    next_t += interval

            lats = []
            wt = threading.Thread(target=writer, daemon=True)
            t_phase = time.perf_counter()
            wt.start()
            concurrent_qps = closed_loop(duration_s, lats)
            stop_writer.set()
            wt.join(timeout=30.0)
            elapsed = time.perf_counter() - t_phase
            recompiles_steady = int(compiles.value - c0)
            acked_rows = (len(state["acked"]) - acked0) * write_rows
            offered_rows = ((len(state["acked"]) - acked0
                             + state["shed"] - shed0) * write_rows)
            h = obs.registry().histogram("serving.ingest.visibility")
            vis_p50_ms = round(h.quantile(0.5) * 1e3, 3)
            vis_p99_ms = round(h.quantile(0.99) * 1e3, 3)
            ig_stats = ig.stats()
        finally:
            srv.stop()

        # ---- phase 3: kill-and-recover (no fold ran: every acked ----
        # row must come back out of the WAL replay)
        acked_ids = set(state["acked"])
        ig.close()              # the "kill": nothing folded, no flush
        ig2 = mk_ingest()
        t0 = time.perf_counter()
        ig2.recover(base_index=index)
        recovery_s = time.perf_counter() - t0
        live_ids, _, _ = ig2.memtable.fold_payload()
        recovered = {int(i) for i in live_ids}
        lost = sorted(a for a in acked_ids if a not in recovered)
        ig2.close()
    if wal_dir is None:
        shutil.rmtree(wal_root, ignore_errors=True)

    frac = concurrent_qps / max(baseline_qps, 1e-9)
    p50, p95, p99 = ((float(v) * 1e3
                      for v in np.percentile(lats, [50, 95, 99]))
                     if lats else (0.0, 0.0, 0.0))
    out.append({
        "metric": "ingest_writes_per_s",
        "value": round(acked_rows / elapsed, 1),
        "unit": "rows/s",
        "vs_baseline": 1.0,
        "detail": {"write_rows": write_rows,
                   "write_peak_rows_per_s": round(write_peak, 1),
                   "write_multiplier": write_multiplier,
                   "offered_rows_per_s": round(offered_rows / elapsed, 1),
                   "shed_batches": state["shed"],
                   "writer_errors": state["errors"],
                   "visibility_p50_ms": vis_p50_ms,
                   "visibility_p99_ms": vis_p99_ms,
                   "wal_bytes_final": ig_stats["wal_bytes"],
                   "memtable_rows_final": ig_stats["memtable_rows"]},
    })
    out.append({
        "metric": "ingest_qps_concurrent",
        "value": round(concurrent_qps, 1),
        "unit": "rows/s",
        "vs_baseline": round(frac, 3),
        "detail": {"baseline_qps_no_writer": round(baseline_qps, 1),
                   "fraction_of_baseline": round(frac, 3),
                   "recompiles_steady": recompiles_steady,
                   "read_p50_ms": round(p50, 3),
                   "read_p95_ms": round(p95, 3),
                   "read_p99_ms": round(p99, 3),
                   "clients": clients, "request_rows": request_rows,
                   "max_batch": max_batch},
    })
    out.append({
        "metric": "ingest_recovery",
        "value": round(recovery_s, 3),
        "unit": "s",
        "vs_baseline": 1.0,
        "detail": {"acked_batches": len(acked_ids),
                   "acked_rows": len(acked_ids) * write_rows,
                   "recovered_rows": len(recovered),
                   "lost_batches": len(lost),
                   "zero_acked_loss": not lost},
    })
    return out


def bench_dist_ingest(res, db, queries, *, build_param=None,
                      search_param=None, k=SERVING_K, clients=4,
                      request_rows=16, duration_s=1.5, write_rows=16,
                      write_rate_rows_per_s=32.0, kill_shard=2,
                      kill_after=5, seed=20260805, wal_dir=None) -> list:
    """Round-19 routed arm of the durability smoke: replicated durable
    ingest (per-shard WALs, r=2) under concurrent routed reads with a
    seed-pinned shard kill MID-STREAM at the ``ingest.dist.append``
    boundary.

    One :class:`~raft_tpu.serving.dist_ingest.RoutedIngest` over an
    8-shard ``by_list`` placement at replication_factor=2, three
    phases:

    1. closed-loop routed READ baseline (all-memtable merge warmed, no
       writer);
    2. a writer thread streaming quorum-acked batches concurrent with
       the same closed-loop readers; ``kill_after`` leader appends in,
       ``FaultPlan.kill_shard_at`` drops ``kill_shard`` — the ack
       plan re-routes onto survivors with zero recompiles and every
       batch keeps acking;
    3. the production recovery arc: the tracker declares the shard
       FAILED, its WAL + memtable are wiped (process loss), the WAL
       delta phase rebuilds them from the live replicas' logs
       (``health.catch_up(..., ingest=...)``), readmission is
       canary-gated, and EVERY acked id must be present in the live
       delta tier both while the shard is down and after readmission.

    Emits ``dist_ingest_writes_per_s``, ``dist_ingest_qps_concurrent``
    (``vs_baseline`` = fraction of the no-writer routed closed loop,
    CI bar 0.8x) and ``dist_ingest_recovery`` (catch-up records,
    ``zero_acked_loss``, the flight-trail event counts)."""
    import shutil
    import tempfile
    import threading

    import jax

    from raft_tpu import observability as obs
    from raft_tpu.comms.session import CommsSession
    from raft_tpu.distributed import ann as dist_ann
    from raft_tpu.distributed import health
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.observability import flight as _flight
    from raft_tpu.resilience import FaultPlan
    from raft_tpu.serving.dist_ingest import DistIngestConfig, RoutedIngest

    bp = build_param or {"nlist": 256, "pq_dim": 32}
    spc = search_param or {"nprobe": 16}
    db_h = np.asarray(db)
    n, dim = db_h.shape
    q = np.asarray(queries)
    wrows = np.ascontiguousarray(db_h[:write_rows])
    wal_root = wal_dir or tempfile.mkdtemp(prefix="raft-tpu-bench-dist-")
    out = []
    session = CommsSession().init()
    try:
        handle = session.worker_handle(seed=0)
        n_shards = len(jax.devices())
        base = ivf_pq.build(
            handle,
            ivf_pq.IndexParams(n_lists=bp["nlist"], pq_dim=bp["pq_dim"],
                               kmeans_n_iters=bp.get("kmeans_n_iters", 4),
                               cache_reconstructions=True),
            db_h)
        routed = dist_ann.shard_by_list(handle, base,
                                        replication_factor=2)
        sp = ivf_pq.SearchParams(n_probes=spc["nprobe"])
        tracker = health.HealthTracker(n_shards, health.HealthConfig(
            suspect_after=1, fail_after=1, ok_to_clear=1, dwell_s=0.0))
        ing = RoutedIngest(
            handle, routed, base,
            config=DistIngestConfig(wal_dir=os.path.join(wal_root, "wal"),
                                    memtable_capacity=1 << 14,
                                    tomb_capacity=1 << 14),
            tracker=tracker)
        ing.recover()
        with obs.collecting():
            compiles = obs.registry().counter("xla.compiles")
            state = {"acked": [], "unavailable": 0, "errors": 0}
            next_id = [n]
            # ONE routed program in flight at a time: the routed read
            # and the write router are SPMD collectives over the full
            # mesh, and the single-controller CPU runtime deadlocks if
            # two threads interleave participants of different
            # rendezvous.  Dispatch is async, so the lock alone is not
            # enough — every search must also block_until_ready INSIDE
            # the lock, or in-flight collective programs pile up and
            # interleave anyway.  Both phases (baseline and concurrent)
            # queue through the same lock, so the QPS ratio stays
            # apples to apples — the writer steals device time, which
            # is exactly what the gate measures.
            dispatch = threading.Lock()

            def locked_search(sub):
                with dispatch:
                    jax.block_until_ready(ing.search(sp, sub, k))

            def write_batch():
                nid = next_id[0]
                ids = np.arange(nid, nid + write_rows, dtype=np.int64)
                next_id[0] = nid + write_rows
                try:
                    with dispatch:
                        ing.write(ids, wrows)
                except Exception as exc:  # noqa: BLE001 - bench keeps going
                    if type(exc).__name__ == "Unavailable":
                        state["unavailable"] += 1
                    else:
                        state["errors"] += 1
                    return False
                state["acked"].append(nid)
                return True

            def closed_loop(dur):
                done = [0] * clients
                stop_at = time.perf_counter() + dur

                def client(j):
                    base_q = (j * 131) % max(1, q.shape[0] - request_rows)
                    sub = q[base_q:base_q + request_rows]
                    while time.perf_counter() < stop_at:
                        locked_search(sub)
                        done[j] += sub.shape[0]

                ts = [threading.Thread(target=client, args=(j,))
                      for j in range(clients)]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                return sum(done) / (time.perf_counter() - t0)

            # warm the write router + BOTH read paths: healthy, and the
            # masked failover view (same shapes, but the mask fill ops
            # are their own tiny executables — first masked read after
            # the kill must not compile inside the fence)
            ing.prewarm([write_rows])
            write_batch()
            locked_search(q[:request_rows])
            warm_plan = FaultPlan(seed=seed).kill_shard_at(
                "ingest.dist.route", kill_shard, after=0)
            with warm_plan.active():
                write_batch()          # fires the warm kill at route
                locked_search(q[:request_rows])       # masked view
            locked_search(q[:request_rows])           # healthy again
            baseline_qps = closed_loop(duration_s)

            # ---- phase 2: writer + readers, shard killed mid-stream --
            c0 = compiles.value
            acked0 = len(state["acked"])
            stop_writer = threading.Event()

            def writer():
                # open-loop at the conf's offered write rate (same
                # contract as the single-node arm): the routed arm
                # measures failover correctness under a steady write
                # load, not the quorum-append ceiling
                period = write_rows / max(write_rate_rows_per_s, 1e-9)
                deadline = time.perf_counter()
                while not stop_writer.is_set():
                    write_batch()
                    deadline += period
                    lag = deadline - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                    else:
                        deadline = time.perf_counter()

            plan = FaultPlan(seed=seed).kill_shard_at(
                "ingest.dist.append", kill_shard, after=kill_after)
            with plan.active():
                wt = threading.Thread(target=writer, daemon=True)
                t_phase = time.perf_counter()
                wt.start()
                concurrent_qps = closed_loop(duration_s)
                stop_writer.set()
                wt.join(timeout=30.0)
                elapsed = time.perf_counter() - t_phase
                # the decision loop declares the killed shard FAILED
                # while the plan still masks it
                tracker.note_timeout(kill_shard)
                tracker.note_timeout(kill_shard)
            recompiles_steady = int(compiles.value - c0)
            kill_fired = sum(spec.fired for spec in plan.specs) == 1
            acked_batches = len(state["acked"]) - acked0

            def live_delta_ids(skip=()):
                ids = set()
                for s in range(n_shards):
                    if s in skip:
                        continue
                    li, _, _, _ = ing.memtables[s].fold_items()
                    ids.update(int(i) for i in li)
                return ids

            def lost_acked(present):
                # a batch counts as lost if ANY of its acked rows is
                # absent from the live delta tier
                return [nid for nid in state["acked"]
                        if any(i not in present
                               for i in range(nid, nid + write_rows))]

            # ---- phase 3: process loss -> delta catch-up -> readmit --
            if ing._wals[kill_shard] is not None:
                ing._wals[kill_shard].close()
                ing._wals[kill_shard] = None
            os.unlink(ing.wal_path(kill_shard))
            ing.memtables[kill_shard].reset()
            lost_down = lost_acked(live_delta_ids(skip=(kill_shard,)))
            t0 = time.perf_counter()
            caught = health.catch_up(handle, ing.index, kill_shard,
                                     tracker=tracker, ingest=ing)
            readmitted = health.readmit(handle, ing, caught, kill_shard,
                                        tracker=tracker)
            recovery_s = time.perf_counter() - t0
            lost_after = lost_acked(live_delta_ids())
            locked_search(q[:request_rows])        # post-readmit serve
            dist_events = sum(
                len(_flight.events(f"serving.ingest.dist.{name}"))
                for name in ("catch_up", "write_error", "unavailable",
                             "replay", "fold"))
            health_events = sum(
                len(_flight.events(f"distributed.health.{name}"))
                for name in ("failed", "suspect", "catch_up",
                             "readmitted"))
        ing.close()
    finally:
        session.destroy()
    if wal_dir is None:
        shutil.rmtree(wal_root, ignore_errors=True)
    frac = concurrent_qps / max(baseline_qps, 1e-9)
    out.append({
        "metric": "dist_ingest_writes_per_s",
        "value": round(acked_batches * write_rows / elapsed, 1),
        "unit": "rows/s",
        "vs_baseline": 1.0,
        "detail": {"write_rows": write_rows, "n_shards": n_shards,
                   "offered_rows_per_s": write_rate_rows_per_s,
                   "replication_factor": 2, "seed": seed,
                   "kill_site": "ingest.dist.append",
                   "kill_shard": kill_shard, "kill_fired": kill_fired,
                   "acked_batches": acked_batches,
                   "unavailable_refusals": state["unavailable"],
                   "writer_errors": state["errors"]},
    })
    out.append({
        "metric": "dist_ingest_qps_concurrent",
        "value": round(concurrent_qps, 1),
        "unit": "rows/s",
        "vs_baseline": round(frac, 3),
        "detail": {"baseline_qps_no_writer": round(baseline_qps, 1),
                   "fraction_of_baseline": round(frac, 3),
                   "recompiles_steady": recompiles_steady,
                   "clients": clients, "request_rows": request_rows},
    })
    out.append({
        "metric": "dist_ingest_recovery",
        "value": round(recovery_s, 3),
        "unit": "s",
        "vs_baseline": 1.0,
        "detail": {"acked_rows": len(state["acked"]) * write_rows,
                   "zero_acked_loss_while_down": not lost_down,
                   "zero_acked_loss_after_readmit": not lost_after,
                   "lost_batches_while_down": len(lost_down),
                   "lost_batches_after_readmit": len(lost_after),
                   "readmitted": bool(readmitted),
                   "dist_flight_events": dist_events,
                   "health_flight_events": health_events},
    })
    return out


def run_ingest(conf_path: str) -> int:
    """``--ingest`` mode: the CI durability smoke.  Builds the conf's
    dataset, runs :func:`bench_ingest` (open-loop writer at 2x the
    calibrated write peak concurrent with closed-loop reads, then
    kill-and-recover), and FAILS (exit 1) on concurrent-read QPS below
    the bar, ANY acked-write loss after recovery, steady-state
    recompiles, or a missing WAL-replay event trail.

    A ``routed`` section in the conf's ``ingest`` block adds the
    round-19 replicated arm (:func:`bench_dist_ingest`): per-shard
    WALs at r=2 with a seed-pinned mid-stream shard kill, gated on
    zero acked loss (both while the shard is down and after the
    catch-up readmission), the same 0.8x read-QPS bar, zero
    steady-state recompiles, and a non-empty ``ingest.dist`` + health
    flight trail.  Skipped (not failed) under 8 devices."""
    from raft_tpu import DeviceResources
    from raft_tpu.observability import flight as _flight

    with open(conf_path) as f:
        conf = json.load(f)
    res = DeviceResources(seed=0)
    db, queries = _make_dataset(conf["dataset"])
    s = conf["serving"]
    g = conf.get("ingest", {})
    _flight.clear()
    lines = bench_ingest(
        res, db, queries,
        build_param=s.get("build_param"),
        search_param=s.get("search_param"),
        k=s.get("k", SERVING_K),
        max_batch=s.get("max_batch", SERVING_MAX_BATCH),
        max_wait_us=s.get("max_wait_us", 1000.0),
        clients=s.get("clients", 8),
        request_rows=g.get("request_rows", 32),
        duration_s=g.get("duration_s", 2.0),
        write_rows=g.get("write_rows", INGEST_WRITE_ROWS),
        write_multiplier=g.get("write_multiplier", 2.0),
        write_rate_rows_per_s=g.get("write_rate_rows_per_s"),
        memtable_capacity=g.get("memtable_capacity", 1 << 16),
        calib_s=g.get("calib_s", 0.5))
    for line in lines:
        _emit(line)
    by = {ln["metric"]: ln for ln in lines}
    failures = []
    bar = g.get("min_qps_fraction_of_baseline", 0.8)
    qps = by["ingest_qps_concurrent"]
    if qps["vs_baseline"] < bar:
        failures.append(
            f"concurrent-read QPS {qps['vs_baseline']:.2f}x the "
            f"no-writer baseline under open-loop writer load "
            f"(bar: {bar:.2f}x)")
    if qps["detail"]["recompiles_steady"] != 0:
        failures.append(
            f"{qps['detail']['recompiles_steady']} XLA recompiles "
            "during the write->search steady state (the pre-sized "
            "memtable merge must be shape-static)")
    rec = by["ingest_recovery"]
    if not rec["detail"]["zero_acked_loss"]:
        failures.append(
            f"ACKED WRITE LOSS: {rec['detail']['lost_batches']} acked "
            f"batches missing after WAL replay "
            f"({rec['detail']['acked_rows']} rows acked, "
            f"{rec['detail']['recovered_rows']} recovered)")
    if by["ingest_writes_per_s"]["detail"]["writer_errors"]:
        failures.append(
            f"{by['ingest_writes_per_s']['detail']['writer_errors']} "
            "non-Overloaded writer errors (backpressure must be the "
            "only shed path)")
    if not _flight.events("serving.ingest.replay"):
        failures.append("no serving.ingest.replay events landed in the "
                        "flight recorder — recovery never replayed the "
                        "WAL")
    r = g.get("routed")
    if r:
        import jax as _jax
        if len(_jax.devices()) < 8:
            print("INGEST ROUTED SKIP: <8 devices, replicated routed "
                  "arm needs the 8-shard mesh", flush=True)
        else:
            _flight.clear()
            rlines = bench_dist_ingest(
                res, db, queries,
                build_param=r.get("build_param", s.get("build_param")),
                search_param=r.get("search_param",
                                   s.get("search_param")),
                k=s.get("k", SERVING_K),
                clients=r.get("clients", 4),
                request_rows=r.get("request_rows", 16),
                duration_s=r.get("duration_s", 1.5),
                write_rows=r.get("write_rows", 16),
                write_rate_rows_per_s=r.get("write_rate_rows_per_s",
                                            32.0),
                kill_shard=r.get("kill_shard", 2),
                kill_after=r.get("kill_after", 5),
                seed=r.get("seed", 20260805))
            for line in rlines:
                _emit(line)
            rby = {ln["metric"]: ln for ln in rlines}
            rbar = r.get("min_qps_fraction_of_baseline", bar)
            rqps = rby["dist_ingest_qps_concurrent"]
            if rqps["vs_baseline"] < rbar:
                failures.append(
                    f"routed concurrent-read QPS "
                    f"{rqps['vs_baseline']:.2f}x the no-writer routed "
                    f"baseline with a shard killed mid-stream "
                    f"(bar: {rbar:.2f}x)")
            if rqps["detail"]["recompiles_steady"] != 0:
                failures.append(
                    f"{rqps['detail']['recompiles_steady']} XLA "
                    "recompiles across the routed write->failover->"
                    "search steady state (masked replica views must "
                    "keep the merge pytree constant)")
            rw = rby["dist_ingest_writes_per_s"]["detail"]
            if not rw["kill_fired"]:
                failures.append(
                    "seed-pinned shard kill never fired — the routed "
                    "arm measured a healthy cluster")
            if rw["writer_errors"]:
                failures.append(
                    f"{rw['writer_errors']} routed writer errors "
                    "(quorum re-planning must absorb a single-shard "
                    "kill at r=2; Unavailable is the only refusal)")
            rrec = rby["dist_ingest_recovery"]["detail"]
            if not rrec["zero_acked_loss_while_down"]:
                failures.append(
                    f"ACKED WRITE LOSS while shard down: "
                    f"{rrec['lost_batches_while_down']} acked batches "
                    f"unreadable from surviving replicas")
            if not rrec["zero_acked_loss_after_readmit"]:
                failures.append(
                    f"ACKED WRITE LOSS after catch-up: "
                    f"{rrec['lost_batches_after_readmit']} acked "
                    f"batches missing post-readmission")
            if not rrec["readmitted"]:
                failures.append("caught-up shard failed canary "
                                "readmission")
            if not rrec["dist_flight_events"]:
                failures.append("no serving.ingest.dist.* events in "
                                "the flight recorder — the routed "
                                "write path left no trail")
            if not rrec["health_flight_events"]:
                failures.append("no distributed.health.* events in the "
                                "flight recorder — the failover arc "
                                "left no trail")
    for msg in failures:
        print(f"INGEST SMOKE FAIL: {msg}", flush=True)
    if failures:
        dumped = _flight.maybe_auto_dump("ingest_smoke_failure")
        if dumped:
            print(f"flight dump: {dumped}", flush=True)
    return 1 if failures else 0


def bench_quality(res, db, queries, *, build_param=None, search_param=None,
                  k=SERVING_K, max_batch=SERVING_MAX_BATCH,
                  max_wait_us=1000.0, clients=8, request_rows=32,
                  duration_s=2.0, sample_rows_per_s=512.0,
                  burst_rows=1024.0, shadow_max_batch=64,
                  recall_floor=None, op_log_path=None) -> list:
    """Shadow-replay quality monitoring over the closed serving loop.

    Runs the bench_serving closed loop TWICE — shadow monitor attached
    but disabled, then enabled (same server, same warmed executables, so
    the A/B isolates the sampling + replay cost) — and emits the QPS
    ratio as ``quality_shadow_overhead`` (CI fails the smoke above the
    conf's ``max_shadow_overhead``).  The enabled arm must produce at
    least one live recall estimate with a Wilson interval
    (``quality_live_recall``), add zero steady-state recompiles (the
    shadow executor pre-warms its own bucket set at the ground-truth
    operating point during ``Server.start()``), and append operating
    points that :func:`raft_tpu.observability.quality.
    read_operating_points` parses back into the calibrator-table shape
    (``quality_op_log``).
    """
    import tempfile
    import threading

    from raft_tpu import observability as obs
    from raft_tpu import serving
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.observability import quality as _quality

    bp = build_param or {"nlist": 256, "pq_dim": 32}
    spc = search_param or {"nprobe": 8}
    index = ivf_pq.build(
        res, ivf_pq.IndexParams(n_lists=bp["nlist"], pq_dim=bp["pq_dim"],
                                kmeans_n_iters=bp.get("kmeans_n_iters", 4)),
        db)
    sp = ivf_pq.SearchParams(n_probes=spc["nprobe"],
                             scan_mode=spc.get("scan_mode", "auto"),
                             per_probe_topk=spc.get("per_probe_topk", 0))
    q = np.asarray(queries)
    reps = int(np.ceil(max_batch / q.shape[0])) if q.shape[0] < max_batch \
        else 1
    if reps > 1:
        q = np.concatenate([q] * reps)
    if op_log_path is None:
        op_log_path = os.path.join(tempfile.mkdtemp(prefix="raft-tpu-oplog-"),
                                   "oplog.jsonl")

    out = []
    with obs.collecting():
        ex = serving.Executor(res, "ivf_pq", index, ks=(k,),
                              max_batch=max_batch, search_params=sp)
        monitor = serving.ShadowMonitor(serving.ShadowConfig(
            sample_rows_per_s=sample_rows_per_s, burst_rows=burst_rows,
            max_batch=shadow_max_batch,
            # flush manually at arm boundaries, not mid-measurement
            window_s=3600.0,
            recall_floor=recall_floor, op_log_path=op_log_path))
        cfg = serving.ServerConfig(max_batch=max_batch,
                                   max_wait_us=max_wait_us,
                                   max_queue_rows=max_batch * 16)
        srv = serving.Server(ex, cfg)
        srv.attach_shadow(monitor)
        srv.start()
        compiles = obs.registry().counter("xla.compiles")
        try:
            # ramp: settle one-time compiles on the live path AND one
            # shadow replay per bucket the sampler will see, then drain
            # the backlog before fencing the compile count
            for m in (1, request_rows, max_batch):
                srv.search(q[:m], k)
            stop_at = time.perf_counter() + 15.0
            while (monitor.stats()["backlog"]
                   and time.perf_counter() < stop_at):
                time.sleep(0.02)
            time.sleep(0.1)           # let an in-flight replay land
            c0 = compiles.value

            def closed_loop():
                done = [0] * clients
                stop_loop = time.perf_counter() + duration_s

                def client(j):
                    base = (j * 131) % max(1, q.shape[0] - request_rows)
                    sub = q[base:base + request_rows]
                    while time.perf_counter() < stop_loop:
                        srv.search(sub, k)
                        done[j] += sub.shape[0]

                ts = [threading.Thread(target=client, args=(j,))
                      for j in range(clients)]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                return sum(done) / (time.perf_counter() - t0)

            # ---- arm A: shadow disabled (one flag check per batch) ---
            monitor.disable()
            qps_off = closed_loop()
            # ---- arm B: shadow sampling + replaying ------------------
            monitor.enable()
            qps_on = closed_loop()
            stop_at = time.perf_counter() + 15.0
            while (monitor.stats()["backlog"]
                   and time.perf_counter() < stop_at):
                time.sleep(0.02)
            time.sleep(0.1)
            recompiles = int(compiles.value - c0)
            overall = monitor.estimator.estimate()
            records = monitor.flush()
            snap = obs.snapshot()
        finally:
            srv.stop()
        counters = snap.get("counters", {})
        warmed = snap.get("gauges", {}).get(
            "serving.shadow.warmed_executables")

    points = _quality.read_operating_points(op_log_path)
    table = _quality.calibrator_table(points)

    frac = qps_on / max(qps_off, 1e-9)
    out.append({
        "metric": "quality_shadow_overhead",
        "value": round(max(1.0 - frac, 0.0), 4),
        "unit": "fraction",
        "vs_baseline": round(frac, 3),
        "detail": {
            "qps_shadow_off": round(qps_off, 1),
            "qps_shadow_on": round(qps_on, 1),
            "fraction_of_unshadowed": round(frac, 3),
            "recompiles_steady": recompiles,
            "warmed_executables": warmed,
            "sampled_rows": counters.get("serving.shadow.sampled", 0),
            "replayed_rows": counters.get("serving.shadow.replayed", 0),
            "skipped_budget_rows":
                counters.get("serving.shadow.skipped.budget", 0),
            "dropped_backlog":
                counters.get("serving.shadow.dropped.backlog", 0),
            "dropped_generation":
                counters.get("serving.shadow.dropped.generation", 0),
        },
    })
    est = overall.as_dict() if overall is not None else None
    out.append({
        "metric": "quality_live_recall",
        "value": round(est["recall"], 4) if est else -1.0,
        "unit": f"recall@{k}",
        "vs_baseline": round(est["lo"], 4) if est else -1.0,
        "detail": {
            "estimate": est,
            "windows": len(records),
            "degraded_windows": sum(1 for r in records if r["degraded"]),
            "floor": records[0]["floor"] if records else None,
        },
    })
    out.append({
        "metric": "quality_op_log",
        "value": float(len(points)),
        "unit": "points",
        "vs_baseline": 1.0,
        "detail": {
            "path": op_log_path,
            "calibrator_rows": len(table),
            "knob_keys": sorted(points[0].knobs) if points else [],
            "measured_keys": sorted(points[0].measured) if points else [],
        },
    })
    return out


def run_quality(conf_path: str) -> int:
    """``--quality`` mode: the CI quality smoke.  Builds the conf's
    dataset + index, runs :func:`bench_quality`, and FAILS (exit 1) on
    shadow overhead above ``max_shadow_overhead``, any steady-state
    recompile, a missing recall estimate / malformed Wilson interval,
    or an operating-point log that doesn't parse back."""
    from raft_tpu import DeviceResources
    from raft_tpu.observability import flight as _flight

    with open(conf_path) as f:
        conf = json.load(f)
    res = DeviceResources(seed=0)
    db, queries = _make_dataset(conf["dataset"])
    g = conf["quality"]
    lines = bench_quality(
        res, db, queries,
        build_param=g.get("build_param"),
        search_param=g.get("search_param"),
        k=g.get("k", SERVING_K),
        max_batch=g.get("max_batch", SERVING_MAX_BATCH),
        max_wait_us=g.get("max_wait_us", 1000.0),
        clients=g.get("clients", 8),
        request_rows=g.get("request_rows", 32),
        duration_s=g.get("duration_s", 2.0),
        sample_rows_per_s=g.get("sample_rows_per_s", 512.0),
        burst_rows=g.get("burst_rows", 1024.0),
        shadow_max_batch=g.get("shadow_max_batch", 64),
        recall_floor=g.get("recall_floor"),
        op_log_path=g.get("op_log_path"))
    for line in lines:
        _emit(line)
    by = {ln["metric"]: ln for ln in lines}
    failures = []
    ov = by["quality_shadow_overhead"]
    max_overhead = g.get("max_shadow_overhead", 0.05)
    if ov["detail"]["fraction_of_unshadowed"] < 1.0 - max_overhead:
        failures.append(
            f"shadow-enabled QPS is "
            f"{ov['detail']['fraction_of_unshadowed']:.2f}x the disabled "
            f"loop (bar: {1.0 - max_overhead:.2f}x)")
    if ov["detail"]["recompiles_steady"] != 0:
        failures.append(
            f"{ov['detail']['recompiles_steady']} XLA recompiles in "
            "steady state (the shadow executor must pre-warm its bucket "
            "set at the ground-truth operating point)")
    if not ov["detail"]["replayed_rows"]:
        failures.append("shadow replayed zero rows — the sampler never "
                        "fed the replay thread")
    est = by["quality_live_recall"]["detail"]["estimate"]
    if est is None or est["rows"] < 1:
        failures.append("no live recall estimate produced")
    elif not (0.0 <= est["lo"] <= est["recall"] <= est["hi"] <= 1.0):
        failures.append(
            f"malformed Wilson interval: lo={est['lo']} "
            f"recall={est['recall']} hi={est['hi']}")
    op = by["quality_op_log"]
    if op["value"] < 1 or op["detail"]["calibrator_rows"] < 1:
        failures.append(
            "operating-point log did not round-trip: "
            f"{int(op['value'])} points parsed, "
            f"{op['detail']['calibrator_rows']} calibrator rows")
    for msg in failures:
        print(f"QUALITY SMOKE FAIL: {msg}", flush=True)
    if failures:
        dumped = _flight.maybe_auto_dump("quality_smoke_failure")
        if dumped:
            print(f"flight dump: {dumped}", flush=True)
    return 1 if failures else 0


# filtered-search selectivity grid (round 20): fraction of rows each
# query's admission bitset passes
FILTERED_SELECTIVITIES = (0.01, 0.1, 0.5, 1.0)


def bench_filtered(res, db, queries, *, build_param=None, search_param=None,
                   k=SERVING_K, n_queries=256,
                   selectivities=FILTERED_SELECTIVITIES, runs=5,
                   recompile_probes=6) -> list:
    """Filtered-search selectivity sweep at the flagship operating point.

    For each selectivity ``s`` a per-query random bitset admits ``s*n``
    rows and the probe budget scales to ``nprobe/s`` (capped at full
    probe) so both arms examine the SAME admitted-candidate budget —
    under that normalization a correct admission seam can only make the
    problem easier (fewer competitors per admitted candidate), so the
    gate ``filtered_recall >= unfiltered_recall`` is an invariant, not
    a tuning target.  Recall is measured against the exact top-
    ``min(k, admitted)`` of each query's admitted set (a filter with
    fewer than k admissible rows is not penalized for the shortfall).
    Emits one ``filtered_qps@s*`` line per selectivity plus the
    ``filtered_recall_gate`` summary with the steady-state recompile
    count across varying filters at a fixed bucket.
    """
    import jax.numpy as jnp

    from raft_tpu import observability as obs
    from raft_tpu import serving
    from raft_tpu.filters import SampleFilter, query_filter_words
    from raft_tpu.neighbors import ivf_pq

    bp = build_param or {"nlist": 256, "pq_dim": 32}
    spc = search_param or {"nprobe": 16}
    n_lists, nprobe = bp["nlist"], spc["nprobe"]
    index = ivf_pq.build(
        res, ivf_pq.IndexParams(n_lists=n_lists, pq_dim=bp["pq_dim"],
                                kmeans_n_iters=bp.get("kmeans_n_iters", 4)),
        db)

    def sp_at(p):
        return ivf_pq.SearchParams(
            n_probes=p, scan_mode=spc.get("scan_mode", "auto"),
            per_probe_topk=spc.get("per_probe_topk", 0))

    q = np.asarray(queries)[:n_queries]
    dbn = np.asarray(db)
    nq, n = q.shape[0], dbn.shape[0]
    # exact squared distances once (host): ground truth over ANY
    # admitted subset is a masked argsort of this
    qd = q.astype(np.float64)
    dbd = dbn.astype(np.float64)
    dist = ((qd * qd).sum(1)[:, None] + (dbd * dbd).sum(1)[None, :]
            - 2.0 * qd @ dbd.T)

    def timed(sp, filt):
        qj = jnp.asarray(q)
        d, i = ivf_pq.search(res, sp, index, qj, k, filter=filt)  # warm
        t0 = time.perf_counter()
        for _ in range(runs):
            _, i = ivf_pq.search(res, sp, index, qj, k, filter=filt)
        np.asarray(i)                      # host readback fence
        return nq / ((time.perf_counter() - t0) / runs), np.asarray(i)

    def recall_against(found, mask):
        hits = total = 0
        for qi in range(nq):
            adm = np.nonzero(mask[qi])[0]
            k_eff = min(k, adm.size)
            if not k_eff:
                continue
            gt = adm[np.argsort(dist[qi, adm], kind="stable")[:k_eff]]
            hits += np.isin(found[qi], gt).sum()
            total += k_eff
        return hits / total if total else 1.0

    rng = np.random.default_rng(20)
    out = []
    qps_unf, i_unf = timed(sp_at(nprobe), None)
    recall_unf = recall_against(i_unf, np.ones((nq, n), bool))

    grid = []
    for s in selectivities:
        mask = (rng.random((nq, n)) < s if s < 1.0
                else np.ones((nq, n), bool))
        filt = SampleFilter.from_mask(mask)
        p = min(n_lists, int(np.ceil(nprobe / s)))
        qps_f, i_f = timed(sp_at(p), filt)
        stray = sum(int(not mask[qi, ii]) for qi in range(nq)
                    for ii in i_f[qi] if ii >= 0)
        point = {
            "selectivity": s,
            "n_probes": p,
            "filtered_qps": round(qps_f, 1),
            "filtered_recall": round(recall_against(i_f, mask), 4),
            "unfiltered_recall": round(recall_unf, 4),
            "admitted_budget_rows": int(filt.admitted_counts().mean()),
            "inadmissible_returned": stray,
        }
        grid.append(point)
        out.append({
            "metric": f"filtered_qps@s{s:g}",
            "value": point["filtered_qps"],
            "unit": "queries/s",
            "vs_baseline": round(qps_f / max(qps_unf, 1e-9), 3),
            "detail": point,
        })

    # filters are data, not shape: varying bitsets at a fixed bucket
    # must not trigger a single steady-state recompile
    with obs.collecting():
        ex = serving.Executor(res, "ivf_pq", index, ks=(k,),
                              max_batch=64, search_params=sp_at(nprobe),
                              warm="jit", filter_rows=n)
        qb = jnp.asarray(q[:64])
        warm = query_filter_words(
            SampleFilter.from_mask(rng.random((64, n)) < 0.5), 64, "bench")
        ex.search_bucket(qb, 64, k, filter_words=warm)[0].block_until_ready()
        c0 = obs.registry().counter("xla.compiles").value
        for _ in range(recompile_probes):
            fw = query_filter_words(
                SampleFilter.from_mask(rng.random((64, n)) < 0.2),
                64, "bench")
            ex.search_bucket(qb, 64, k,
                             filter_words=fw)[0].block_until_ready()
        recompiles = int(obs.registry().counter("xla.compiles").value - c0)

    out.append({
        "metric": "filtered_recall_gate",
        "value": round(min(pt["filtered_recall"] - pt["unfiltered_recall"]
                           for pt in grid), 4),
        "unit": "recall_delta",
        "vs_baseline": round(recall_unf, 4),
        "detail": {
            "unfiltered_qps": round(qps_unf, 1),
            "unfiltered_recall": round(recall_unf, 4),
            "recompiles_steady": recompiles,
            "grid": grid,
            "k": k, "n_db": n, "batch": nq,
            "n_lists": n_lists, "nprobe": nprobe,
        },
    })
    return out


def run_filtered(conf_path: str) -> int:
    """``--filtered`` mode: the CI filtered-search smoke.  FAILS (exit 1)
    when any selectivity's filtered recall@k falls below the unfiltered
    recall@k at the matched admitted-candidate budget, when any
    inadmissible id is returned, or on any steady-state recompile
    across varying filters at a fixed bucket."""
    from raft_tpu import DeviceResources
    from raft_tpu.observability import flight as _flight

    with open(conf_path) as f:
        conf = json.load(f)
    res = DeviceResources(seed=0)
    db, queries = _make_dataset(conf["dataset"])
    g = conf["filtered"]
    lines = bench_filtered(
        res, db, queries,
        build_param=g.get("build_param"),
        search_param=g.get("search_param"),
        k=g.get("k", SERVING_K),
        n_queries=g.get("n_queries", 256),
        selectivities=tuple(g.get("selectivities",
                                  FILTERED_SELECTIVITIES)),
        runs=g.get("runs", 5),
        recompile_probes=g.get("recompile_probes", 6))
    for line in lines:
        _emit(line)
    gate = next(ln for ln in lines
                if ln["metric"] == "filtered_recall_gate")
    eps = g.get("recall_epsilon", 0.0)
    failures = []
    for pt in gate["detail"]["grid"]:
        if pt["filtered_recall"] + eps < pt["unfiltered_recall"]:
            failures.append(
                f"selectivity {pt['selectivity']}: filtered recall "
                f"{pt['filtered_recall']:.4f} below unfiltered "
                f"{pt['unfiltered_recall']:.4f} at matched admitted "
                f"budget (n_probes={pt['n_probes']})")
        if pt["inadmissible_returned"]:
            failures.append(
                f"selectivity {pt['selectivity']}: "
                f"{pt['inadmissible_returned']} inadmissible ids "
                "returned — the admission seam leaked")
    if gate["detail"]["recompiles_steady"] != 0:
        failures.append(
            f"{gate['detail']['recompiles_steady']} XLA recompiles "
            "across varying filters at a fixed bucket (filters must be "
            "data, not shape)")
    for msg in failures:
        print(f"FILTERED SMOKE FAIL: {msg}", flush=True)
    if failures:
        dumped = _flight.maybe_auto_dump("filtered_smoke_failure")
        if dumped:
            print(f"flight dump: {dumped}", flush=True)
    return 1 if failures else 0


MUTATION_CHURN = 0.01          # writer deletes AND extends 1% per cycle


def bench_mutation(res, db, queries, *, build_param=None, search_param=None,
                   k=SERVING_K, max_batch=SERVING_MAX_BATCH,
                   max_wait_us=1000.0, clients=8, request_rows=32,
                   duration_s=2.0, churn_fraction=MUTATION_CHURN,
                   churn_interval_s=0.25) -> list:
    """Serving under mutation churn at the flagship operating point.

    A background writer repeatedly deletes ``churn_fraction`` of the
    index and extends the same fraction of fresh rows, publishing each
    new generation through ``Server.swap_index`` (full re-warm, atomic
    publish).  Closed-loop clients run the whole time; the bench emits

    - ``mutation_qps_sustained`` — sustained rows/s with the writer
      active, ``vs_baseline`` = fraction of the same closed loop with no
      writer (acceptance bar: >= 0.8x);
    - ``mutation_p99_ms`` — client-observed p99 under churn.

    Recompiles are attributed per swap: the writer samples the
    ``xla.compiles`` counter around each ``swap_index`` call, so
    ``recompiles_steady`` counts only compiles OUTSIDE swap re-warms —
    the zero-steady-state contract between generation swaps.
    """
    import threading

    import jax
    import jax.numpy as jnp

    from raft_tpu import observability as obs
    from raft_tpu import serving
    from raft_tpu.neighbors import ivf_pq

    bp = build_param or {"nlist": 1024, "pq_dim": 32}
    spc = search_param or {"nprobe": 32}
    index = ivf_pq.build(
        res, ivf_pq.IndexParams(n_lists=bp["nlist"], pq_dim=bp["pq_dim"],
                                kmeans_n_iters=bp.get("kmeans_n_iters", 10)),
        db)
    sp = ivf_pq.SearchParams(n_probes=spc["nprobe"],
                             scan_mode=spc.get("scan_mode", "auto"),
                             per_probe_topk=spc.get("per_probe_topk", 0))
    q = np.asarray(queries)
    if q.shape[0] < max_batch:
        q = np.concatenate([q] * int(np.ceil(max_batch / q.shape[0])))
    db_h = np.asarray(db)
    n = db_h.shape[0]
    step = max(1, int(n * churn_fraction))

    ex = serving.Executor(res, "ivf_pq", index, ks=(k,),
                          max_batch=max_batch, search_params=sp)
    out = []
    with obs.collecting():
        cfg = serving.ServerConfig(max_batch=max_batch,
                                   max_wait_us=max_wait_us,
                                   max_queue_rows=max_batch * 16)
        with serving.Server(ex, cfg) as srv:
            for m in (1, request_rows, max_batch):
                srv.search(q[:m], k)

            def closed_loop(dur, lats=None):
                done = [0] * clients
                stop_at = time.perf_counter() + dur

                def client(j):
                    base = (j * 131) % max(1, q.shape[0] - request_rows)
                    sub = q[base:base + request_rows]
                    while time.perf_counter() < stop_at:
                        t0 = time.perf_counter()
                        srv.search(sub, k)
                        if lats is not None:
                            lats.append(time.perf_counter() - t0)
                        done[j] += sub.shape[0]

                ts = [threading.Thread(target=client, args=(j,))
                      for j in range(clients)]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                return sum(done) / (time.perf_counter() - t0)

            # ---- no-writer baseline, same loop -----------------------
            baseline_qps = closed_loop(duration_s)

            # ---- writer: delete 1% + extend 1% + swap per cycle ------
            state = {"index": index, "next_del": 0, "next_id": n,
                     "swaps": 0, "swap_compiles": 0, "errors": 0}
            stop_writer = threading.Event()
            compiles = obs.registry().counter("xla.compiles")

            def writer():
                while not stop_writer.wait(churn_interval_s):
                    try:
                        # the whole cycle's compiles (delete/extend traces
                        # + swap re-warm) belong to the writer; what's
                        # left over is the READER steady state, which the
                        # generation-keyed warm tables must keep at zero
                        c0 = compiles.value
                        idx = state["index"]
                        lo = state["next_del"]
                        doomed = np.arange(lo, lo + step, dtype=np.int64)
                        idx = ivf_pq.delete(res, idx, doomed)
                        rows = db_h[lo % n:(lo % n) + step]
                        if rows.shape[0] < step:        # wrap the slice
                            rows = db_h[:step]
                        ids = np.arange(state["next_id"],
                                        state["next_id"] + rows.shape[0],
                                        dtype=np.int64)
                        idx = ivf_pq.extend(res, idx, jnp.asarray(rows),
                                            ids)
                        srv.swap_index(idx)
                        state["swap_compiles"] += compiles.value - c0
                        state["index"] = idx
                        state["next_del"] = lo + step
                        state["next_id"] += rows.shape[0]
                        state["swaps"] += 1
                    except Exception:  # noqa: BLE001 - bench keeps serving
                        state["errors"] += 1

            lats = []
            c_start = compiles.value
            wt = threading.Thread(target=writer, daemon=True)
            wt.start()
            mutation_qps = closed_loop(duration_s, lats)
            stop_writer.set()
            wt.join(timeout=60.0)
            recompiles_steady = (compiles.value - c_start
                                 - state["swap_compiles"])

    from raft_tpu.neighbors import mutate as _mutate
    frac = mutation_qps / max(baseline_qps, 1e-9)
    p50, p95, p99 = (float(v) * 1e3
                     for v in np.percentile(lats, [50, 95, 99]))
    out.append({
        "metric": "mutation_qps_sustained",
        "value": round(mutation_qps, 1),
        "unit": "rows/s",
        "vs_baseline": round(frac, 3),
        "detail": {"baseline_qps_no_writer": round(baseline_qps, 1),
                   "fraction_of_baseline": round(frac, 3),
                   "recompiles_steady": int(recompiles_steady),
                   "writer_compiles": int(state["swap_compiles"]),
                   "generation_swaps": state["swaps"],
                   "writer_errors": state["errors"],
                   "churn_fraction": churn_fraction,
                   "churn_rows_per_cycle": step,
                   "dead_fraction_final": round(
                       _mutate.dead_fraction(state["index"]), 4),
                   "clients": clients, "request_rows": request_rows,
                   "max_batch": max_batch},
    })
    out.append({
        "metric": "mutation_p99_ms",
        "value": round(p99, 3),
        "unit": "ms",
        "vs_baseline": 1.0,
        "detail": {"p50_ms": round(p50, 3), "p95_ms": round(p95, 3),
                   "requests": len(lats),
                   "generation_swaps": state["swaps"]},
    })
    return out


PAIRWISE_N, PAIRWISE_DIM = 5000, 50


def bench_pairwise(res) -> dict:
    """BASELINE.md config 1: pairwise_distance L2SqrtExpanded over
    make_blobs 5000 x 50 (the README example) — a correctness check with
    a throughput number attached."""
    from raft_tpu.distance.pairwise import pairwise_distance
    from raft_tpu.distance.types import DistanceType

    rng = np.random.default_rng(3)
    centers = rng.normal(size=(16, PAIRWISE_DIM)) * 5
    lab = rng.integers(0, 16, PAIRWISE_N)
    X = (centers[lab]
         + rng.normal(size=(PAIRWISE_N, PAIRWISE_DIM))).astype(np.float32)
    d = pairwise_distance(X, X, DistanceType.L2SqrtExpanded)  # warmup
    # numpy oracle on a row sample (the full 5000^2 host check is slow)
    dh = np.asarray(d)[:64]
    oracle = np.sqrt(np.maximum(
        ((X[:64, None, :] - X[None, :, :]) ** 2).sum(-1), 0.0))
    max_err = float(np.max(np.abs(dh - oracle)))
    t0 = time.perf_counter()
    for _ in range(RUNS):
        d = pairwise_distance(X, X, DistanceType.L2SqrtExpanded)
    np.asarray(d[0, :1])    # host readback (see bench_ivf_pq note)
    ms = (time.perf_counter() - t0) / RUNS * 1000
    return {
        "metric": f"pairwise_l2sqrt_{PAIRWISE_N}x{PAIRWISE_DIM}_ms",
        "value": round(ms, 3),
        "unit": "ms",
        "vs_baseline": 1.0,
        "detail": {"n": PAIRWISE_N, "dim": PAIRWISE_DIM,
                   "max_abs_err_vs_numpy": round(max_err, 5),
                   "check": "pass" if max_err < 1e-2 else "fail"},
    }


MNMG_DIM = 256
MNMG_ROWS_PER_DEV = 1_250_000   # 10M across a v5e-8 (BASELINE.md config 5)
MNMG_K = 1024
MNMG_ITERS = 5


def bench_mnmg(res) -> dict:
    """BASELINE.md config 5: MNMG k-means + kNN over the available
    devices (10M x 256 across a v5e-8; the row count scales with the
    device count so single-chip runs stay in HBM)."""
    import jax

    from raft_tpu.cluster.kmeans_types import InitMethod, KMeansParams
    from raft_tpu.comms.session import CommsSession
    from raft_tpu.distributed import kmeans as dist_kmeans
    from raft_tpu.distributed import knn as dist_knn

    n_dev = len(jax.devices())
    n = MNMG_ROWS_PER_DEV * n_dev
    db, queries = _make_dataset({"n_db": n, "dim": MNMG_DIM,
                                 "latent_dim": 32, "n_queries": 1000})
    session = CommsSession().init()
    try:
        handle = session.worker_handle()
        params = KMeansParams(n_clusters=MNMG_K, max_iter=MNMG_ITERS,
                              tol=0.0, n_init=1, init=InitMethod.Random)
        c, _, _ = dist_kmeans.fit(handle, params, db)        # warmup
        np.asarray(c)
        t0 = time.perf_counter()
        c, inertia, n_iter = dist_kmeans.fit(handle, params, db)
        np.asarray(c)
        kmeans_s = time.perf_counter() - t0
        i = dist_knn.knn(handle, db, queries, K)[1]          # warmup
        t0 = time.perf_counter()
        for _ in range(RUNS):
            i = dist_knn.knn(handle, db, queries, K)[1]
        np.asarray(i)
        knn_qps = queries.shape[0] / ((time.perf_counter() - t0) / RUNS)
    finally:
        session.destroy()
    iters_per_s = MNMG_ITERS / kmeans_s
    return {
        "metric": f"mnmg_kmeans_iters_per_s_{n // 1_000_000}Mx{MNMG_DIM}"
                  f"_k{MNMG_K}_{n_dev}dev",
        "value": round(iters_per_s, 3),
        "unit": "iter/s",
        "vs_baseline": round(iters_per_s, 3),
        "detail": {"n": n, "dim": MNMG_DIM, "k": MNMG_K,
                   "n_devices": n_dev, "n_iter": MNMG_ITERS,
                   "fit_s": round(kmeans_s, 2),
                   "knn_qps": round(knn_qps, 1),
                   "knn_k": K, "knn_batch": queries.shape[0]},
    }


DIST_ROWS_PER_DEV = 131_072     # ~1M across a v5e-8
DIST_DIM = 96
DIST_N_LISTS = 512
DIST_N_PROBES = 32


def bench_distributed(res) -> list:
    """Round-8 grid: routed (``placement="by_list"``) vs data-parallel
    sharded IVF-PQ search over the available devices, emitting
    ``dist_qps_routed`` / ``dist_qps_dataparallel`` plus the per-query
    candidate-exchange bytes and the per-shard scanned-row ratio — the
    numbers PERFORMANCE.md's per-chip work / gather-bytes model
    predicts (routed scan work ~1/n_shards, gather fixed at (k, nq)
    pairs per shard for BOTH modes; the routed win is the scan).

    Round 10 adds the routed FUSED operating point (sync-free grouped
    scan under shard_map at static capacity) and
    ``dist_scan_bytes_per_row`` — the per-row HBM traffic of each scan
    form from :func:`raft_tpu.neighbors.grouped.scan_traffic`, the model
    behind the 264 -> 72 B/row routed headline."""
    import jax

    from raft_tpu.comms.session import CommsSession
    from raft_tpu.distributed import ann as dist_ann
    from raft_tpu.neighbors import grouped, ivf_pq

    n_dev = len(jax.devices())
    n = DIST_ROWS_PER_DEV * n_dev
    db, queries = _make_dataset({"n_db": n, "dim": DIST_DIM,
                                 "latent_dim": 32, "n_queries": 1000})
    nq, k = queries.shape[0], K
    params = ivf_pq.IndexParams(n_lists=DIST_N_LISTS, pq_dim=DIST_DIM // 2,
                                kmeans_n_iters=5,
                                cache_reconstructions=True)
    sp = ivf_pq.SearchParams(n_probes=DIST_N_PROBES)
    sp_fused = ivf_pq.SearchParams(n_probes=DIST_N_PROBES,
                                   scan_mode="fused")
    out = []
    session = CommsSession().init()
    try:
        handle = session.worker_handle()

        def qps(index, p=sp):
            i = dist_ann.search(handle, p, index, queries, k)[1]  # warm
            np.asarray(i)
            t0 = time.perf_counter()
            for _ in range(RUNS):
                i = dist_ann.search(handle, p, index, queries, k)[1]
            np.asarray(i)
            return nq / ((time.perf_counter() - t0) / RUNS)

        dp = dist_ann.build(handle, params, db)
        dp_qps = qps(dp)
        _, _, dp_stats = dist_ann.search(handle, sp, dp, queries, k,
                                         return_stats=True)
        routed = dist_ann.build(handle, params, db, placement="by_list")
        routed_qps = qps(routed)
        _, _, r_stats = dist_ann.search(handle, sp, routed, queries, k,
                                        return_stats=True)
        routed_fused_qps = qps(routed, sp_fused)
        _, _, rf_stats = dist_ann.search(handle, sp_fused, routed,
                                         queries, k, return_stats=True)
        rot_dim = int(routed.rotation.shape[-1])
        traffic = grouped.scan_traffic(
            rot_dim, pq_dim=params.pq_dim,
            pq_bits=int(getattr(routed, "pq_bits", 0)))
        # round 17: replicated failover — what ONE dead shard costs in
        # recall (vs the healthy routed answer at the same operating
        # point) and QPS at r=1 (lists lost, degraded merge) vs r=2
        # (replicas cover the loss; exact by the k-bounded argument)
        from raft_tpu.resilience import FaultPlan
        r2 = dist_ann.build(handle, params, db, placement="by_list",
                            replication_factor=2)
        failover = {}
        for tag, idx in (("r1", routed), ("r2", r2)):
            # each index's own healthy answer is the recall baseline —
            # the failover contract is per index (r2 trains its own
            # quantizer here, so cross-index ids don't compare)
            base_i = np.asarray(dist_ann.search(handle, sp, idx,
                                                queries, k)[1])
            i_f = np.asarray(dist_ann.search(handle, sp, idx, queries, k,
                                             failed_shards=[0])[1])
            t0 = time.perf_counter()
            for _ in range(RUNS):
                i_r = dist_ann.search(handle, sp, idx, queries, k,
                                      failed_shards=[0])[1]
            np.asarray(i_r)
            failover[tag] = {
                "recall": _recall(i_f, base_i),
                "qps": nq / ((time.perf_counter() - t0) / RUNS),
            }
        # hedged straggler reads: one shard scripted 10x slower than the
        # healthy per-search latency; the hedge re-issues its probes to
        # the replica and caps the wait at the per-shard deadline
        lat = []
        for _ in range(5):
            t0 = time.perf_counter()
            np.asarray(dist_ann.search(handle, sp, r2, queries, k)[1])
            lat.append(time.perf_counter() - t0)
        t_med = float(np.median(lat))
        hedge_deadline = max(t_med, 1e-3)
        hlat = []
        plan = FaultPlan(seed=17).straggle_shard(1, delay=10.0 * t_med)
        with plan.active():
            for _ in range(20):
                t0 = time.perf_counter()
                np.asarray(dist_ann.search(
                    handle, sp, r2, queries, k,
                    shard_deadline_s=hedge_deadline)[1])
                hlat.append(time.perf_counter() - t0)
        p99_hedged_ms = float(np.percentile(hlat, 99)) * 1e3
    finally:
        session.destroy()
    # the candidate exchange: each shard contributes (nq, k) f32+i32
    # pairs regardless of placement — fixed, not index-size-dependent
    gather_bytes = n_dev * nq * k * 8
    scan_ratio = (float(r_stats["scanned_rows"].max())
                  / max(float(dp_stats["scanned_rows"].max()), 1.0))
    shape = f"{n // 1_000_000}Mx{DIST_DIM}_{n_dev}dev"
    out.append({
        "metric": f"dist_qps_routed_{shape}",
        "value": round(routed_qps, 1), "unit": "qps",
        "vs_baseline": round(routed_qps / max(dp_qps, 1e-9), 3),
        "detail": {"n_probes": DIST_N_PROBES, "k": k, "batch": nq,
                   "gather_bytes": gather_bytes,
                   "scanned_rows_max": int(r_stats["scanned_rows"].max()),
                   "scan_ratio_vs_dataparallel": round(scan_ratio, 4)},
    })
    out.append({
        "metric": f"dist_qps_dataparallel_{shape}",
        "value": round(dp_qps, 1), "unit": "qps",
        "vs_baseline": 1.0,
        "detail": {"n_probes": DIST_N_PROBES, "k": k, "batch": nq,
                   "gather_bytes": gather_bytes,
                   "scanned_rows_max": int(dp_stats["scanned_rows"].max())},
    })
    # round 10: the sync-free fused grouped scan under the routed path —
    # vs_baseline is the CI tripwire ratio (fused must not regress below
    # the routed recon point it replaces as the default fast path)
    out.append({
        "metric": f"dist_qps_routed_fused_{shape}",
        "value": round(routed_fused_qps, 1), "unit": "qps",
        "vs_baseline": round(routed_fused_qps / max(routed_qps, 1e-9), 3),
        "detail": {"n_probes": DIST_N_PROBES, "k": k, "batch": nq,
                   "scan_mode": rf_stats.get("scan_mode"),
                   "gather_bytes": gather_bytes,
                   "scanned_rows_max": int(rf_stats["scanned_rows"].max())},
    })
    out.append({
        "metric": f"dist_scan_bytes_per_row_{shape}",
        "value": traffic["fused"], "unit": "B/row",
        "vs_baseline": round(traffic["fused"] / traffic["recon"], 3),
        "detail": dict(traffic, rot_dim=rot_dim, pq_dim=params.pq_dim,
                       pq_bits=int(getattr(routed, "pq_bits", 0))),
    })
    # round 17: the replication decision record — recall retained with
    # one shard dead (vs the healthy routed answer; r=2 MUST read 1.0,
    # the bit-identical failover contract) and the QPS each mode holds
    for tag in ("r1", "r2"):
        out.append({
            "metric": f"dist_recall_failed_shard_{tag}",
            "value": round(failover[tag]["recall"], 4),
            "unit": "recall@10",
            "vs_baseline": round(
                failover[tag]["qps"] / max(routed_qps, 1e-9), 3),
            "detail": {"failed_shards": [0], "n_probes": DIST_N_PROBES,
                       "k": k, "batch": nq, "shape": shape,
                       "replication_factor": int(tag[1]),
                       "qps_one_shard_failed":
                           round(failover[tag]["qps"], 1)},
        })
    out.append({
        "metric": "dist_p99_hedged_ms",
        "value": round(p99_hedged_ms, 2), "unit": "ms",
        # the tripwire ratio: hedged p99 vs what the scripted straggler
        # would cost unhedged (healthy median + 10x delay)
        "vs_baseline": round(
            p99_hedged_ms / max((t_med + 10.0 * t_med) * 1e3, 1e-9), 3),
        "detail": {"straggler_delay_ms": round(10.0 * t_med * 1e3, 2),
                   "shard_deadline_ms": round(hedge_deadline * 1e3, 2),
                   "healthy_p50_ms": round(t_med * 1e3, 2),
                   "shape": shape, "replication_factor": 2,
                   "samples": len(hlat)},
    })
    return out


# ---------------------------------------------------------------------------
# skewed-load replica routing (PR 18): the load-aware policy vs
# primary-only under a Zipf probe distribution
# ---------------------------------------------------------------------------

#: default workload seed when RAFT_TPU_FAULT_SEED is unset (the CI
#: chaos job pins the env var; local runs replay the same schedule)
SKEW_DEFAULT_SEED = 20260805


def _skew_workload(*, n_lists, dim, rows_mu, size_sigma, zipf_a,
                   n_queries, seed):
    """Clustered dataset with log-normal list sizes and Zipf(``zipf_a``)
    query heat over a permuted cluster order — heat independent of
    size, so the hot lists are NOT simply the big ones and size-only
    LPT cannot see them."""
    rng = np.random.default_rng(seed)
    centers = (rng.normal(size=(n_lists, dim)) * 6.0).astype(np.float32)
    sizes = np.maximum(rng.lognormal(np.log(rows_mu), size_sigma,
                                     n_lists).astype(np.int64), 16)
    db = np.concatenate([
        centers[g] + rng.normal(size=(sizes[g], dim)).astype(np.float32)
        for g in range(n_lists)])
    zipf = 1.0 / np.arange(1, n_lists + 1, dtype=np.float64) ** zipf_a
    zipf /= zipf.sum()
    heat = np.empty(n_lists)
    heat[rng.permutation(n_lists)] = zipf
    qc = rng.choice(n_lists, size=n_queries, p=heat)
    queries = (centers[qc]
               + 0.3 * rng.normal(size=(n_queries, dim))).astype(
                   np.float32)
    return db, queries


def bench_skew(*, n_lists=64, dim=32, rows_mu=160.0, size_sigma=1.0,
               zipf_a=1.0, n_queries=4096, batch_rows=512, n_probes=2,
               calib_batches=8, k=10, rebalance_overfull=1.15,
               seed=SKEW_DEFAULT_SEED) -> list:
    """PR 18: load-aware replica routing under skewed probe load.

    Workload: Zipf(``zipf_a``) query heat over ``n_lists`` clusters
    with log-normal sizes — a few lists absorb most probes, so the
    shard owning them is the SPMD bottleneck (the merge completes when
    the slowest shard answers).  Two arms over the same ``r=2`` routed
    index:

    - **primary-only**: every list served by its rank-0 owner (the
      pre-PR-18 healthy path);
    - **routed**: calibration traffic accumulates the policy's probe
      histograms (lazy, sync-free), one maintenance pass folds them and
      runs the probe-frequency-aware ``rebalance_routed``, then
      measured traffic routes per batch through
      :meth:`RoutingPolicy.plan` (greedy least-loaded over both ranks)
      with the tables updating every batch.

    QPS is **modeled from measured per-shard scanned rows**: on the
    virtual CPU mesh every device executes the same program serially,
    so wall-clock cannot show the SPMD win; ``t_batch ∝ max_s
    scanned_rows[s]`` (the slowest-shard model PERFORMANCE.md's
    per-chip work analysis rides on), normalized by the primary arm's
    measured scan rate.  Gates asserted by :func:`run_skew`: the
    modeled QPS ratio, full-probe bit-identity while the policy is
    active, and ZERO xla.compiles on warmed traffic while the tables
    update every batch (replica choice is data, not shape)."""
    import jax

    from raft_tpu import observability as obs
    from raft_tpu.comms.session import CommsSession
    from raft_tpu.distributed import ann as dist_ann
    from raft_tpu.distributed.health import HealthTracker
    from raft_tpu.distributed.routing import RoutingPolicy
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.serving import rebalancer

    db, queries = _skew_workload(
        n_lists=n_lists, dim=dim, rows_mu=rows_mu,
        size_sigma=size_sigma, zipf_a=zipf_a, n_queries=n_queries,
        seed=seed)
    import jax.numpy as jnp
    batches = [jnp.asarray(queries[i:i + batch_rows])
               for i in range(0, n_queries - batch_rows + 1, batch_rows)]
    out = []
    session = CommsSession().init()
    try:
        handle = session.worker_handle()
        n_dev = len(jax.devices())
        params = ivf_pq.IndexParams(n_lists=n_lists, pq_dim=dim // 4,
                                    kmeans_n_iters=4,
                                    cache_reconstructions=True)
        r2 = dist_ann.build(handle, params, db, placement="by_list",
                            replication_factor=2)
        sp = ivf_pq.SearchParams(n_probes=n_probes)

        def shard_rows(index, batch, routing=None):
            _, _, st = dist_ann.search(handle, sp, index, batch, k,
                                       return_stats=True,
                                       routing=routing)
            return np.asarray(st["scanned_rows"], np.int64)

        # -- arm 1: primary-only (rank-0 owners, the spare-replica
        #    status quo) -------------------------------------------------
        shard_rows(r2, batches[0])                      # warm
        t0 = time.perf_counter()
        prim = [shard_rows(r2, b) for b in batches]
        t_prim = time.perf_counter() - t0
        prim_max = float(np.mean([p.max() for p in prim]))

        # -- arm 2: calibrate -> heat-aware rebalance -> policy-routed --
        tracker = HealthTracker(n_dev)
        pol = RoutingPolicy(n_dev, tracker=tracker)
        # per-probe scan cost is the padded slab capacity — uniform
        # across lists — which is exactly the policy's default when no
        # rows are fed, so no note_list_rows seeding here (the serving
        # executor and rebalance_routed feed the same uniform cost).
        for b in batches[:calib_batches]:
            dist_ann.search(handle, sp, r2, b, k, routing=pol)
        cand = rebalancer.rebalance_routed(
            handle, r2, routing=pol,
            config=rebalancer.RebalanceConfig(
                overfull_factor=rebalance_overfull))
        heat_rebalanced = cand is not r2
        shard_rows(cand, batches[0], routing=pol)       # warm
        with obs.collecting():
            c0 = obs.registry().counter("xla.compiles").value
            t0 = time.perf_counter()
            routed = [shard_rows(cand, b, routing=pol) for b in batches]
            t_routed = time.perf_counter() - t0
            recompiles = (obs.registry().counter("xla.compiles").value
                          - c0)
        routed_max = float(np.mean([r.max() for r in routed]))

        # -- full-probe bit-identity while the policy routes ------------
        sp_full = ivf_pq.SearchParams(n_probes=n_lists)
        d0, i0 = dist_ann.search(handle, sp_full, cand, batches[0], k)
        d1, i1 = dist_ann.search(handle, sp_full, cand, batches[0], k,
                                 routing=pol)
        bit_identical = bool(
            np.array_equal(np.asarray(i0), np.asarray(i1))
            and np.array_equal(np.asarray(d0), np.asarray(d1)))
    finally:
        session.destroy()

    # modeled QPS: per-shard scan rate from the primary arm's wall
    # clock (rate = bottleneck rows per measured batch interval), then
    # qps_arm = batch_rows * rate / bottleneck_rows(arm)
    rate = prim_max * len(batches) / max(t_prim, 1e-9)
    qps_prim = batch_rows * rate / max(prim_max, 1.0)
    qps_routed = batch_rows * rate / max(routed_max, 1.0)
    ratio = prim_max / max(routed_max, 1.0)
    choice = pol.choice_summary()
    out.append({
        "metric": "skew_routed_qps_ratio_r2",
        "value": round(ratio, 3), "unit": "x primary-only",
        "vs_baseline": round(ratio, 3),
        "detail": {
            "seed": seed, "zipf_a": zipf_a, "n_lists": n_lists,
            "n_probes": n_probes, "batch_rows": batch_rows,
            "batches": len(batches), "n_devices": n_dev,
            "scanned_rows_max_primary": int(round(prim_max)),
            "scanned_rows_max_routed": int(round(routed_max)),
            "recompiles_steady": int(recompiles),
            "bit_identical_full_probe": bit_identical,
            "heat_rebalanced": heat_rebalanced,
            "per_rank_lists": choice.get("per_rank_lists"),
            "per_shard_lists": choice.get("per_shard_lists"),
        },
    })
    out.append({"skew_point": {"arm": "primary", "qps_model":
                               round(qps_prim, 1),
                               "wall_s": round(t_prim, 3),
                               "scanned_rows_max": int(round(prim_max))}})
    out.append({"skew_point": {"arm": "routed", "qps_model":
                               round(qps_routed, 1),
                               "wall_s": round(t_routed, 3),
                               "scanned_rows_max":
                                   int(round(routed_max))}})
    return out


def run_skew(conf_path: str) -> int:
    """``--skew`` mode: the CI skewed-load chaos leg.  Builds the
    conf's Zipf workload (seed pinned via ``RAFT_TPU_FAULT_SEED``),
    runs :func:`bench_skew`, and FAILS (exit 1) when routed goodput at
    ``r=2`` under the skew falls below ``min_qps_ratio`` x the
    primary-only arm, on any steady-state recompile while the routing
    tables update, on a full-probe bit-identity break, or on a missing
    ``distributed.replica_choice`` flight trail."""
    import jax

    from raft_tpu.observability import flight as _flight

    with open(conf_path) as f:
        conf = json.load(f)
    s = conf.get("skew", {})
    if len(jax.devices()) < s.get("min_devices", 8):
        _emit({"metric": "skew_routed_qps_ratio_r2", "skipped": True,
               "reason": f"{len(jax.devices())} devices < "
                         f"{s.get('min_devices', 8)}"})
        return 0
    seed = int(os.environ.get("RAFT_TPU_FAULT_SEED",
                              s.get("seed", SKEW_DEFAULT_SEED)))
    _flight.clear()
    lines = bench_skew(
        n_lists=s.get("n_lists", 64), dim=s.get("dim", 32),
        rows_mu=s.get("rows_mu", 160.0),
        size_sigma=s.get("size_sigma", 1.0),
        zipf_a=s.get("zipf_a", 1.0),
        n_queries=s.get("n_queries", 4096),
        batch_rows=s.get("batch_rows", 512),
        n_probes=s.get("n_probes", 2),
        calib_batches=s.get("calib_batches", 8),
        k=s.get("k", 10),
        rebalance_overfull=s.get("rebalance_overfull", 1.15),
        seed=seed)
    for line in lines:
        _emit(line)
    head = next(ln for ln in lines
                if ln.get("metric") == "skew_routed_qps_ratio_r2")
    failures = []
    bar = s.get("min_qps_ratio", 1.5)
    if head["value"] < bar:
        failures.append(
            f"routed goodput {head['value']:.2f}x primary-only under "
            f"Zipf({s.get('zipf_a', 1.0)}) skew at r=2 (bar: {bar:.2f}x)")
    if head["detail"]["recompiles_steady"] != 0:
        failures.append(
            f"{head['detail']['recompiles_steady']} XLA recompiles on "
            "warmed traffic while the routing tables updated (replica "
            "choice must stay data, not shape)")
    if not head["detail"]["bit_identical_full_probe"]:
        failures.append("full-probe results with the policy active "
                        "diverged from the primary answer — the "
                        "per-list exactness argument broke")
    if not _flight.events("distributed.replica_choice"):
        failures.append("no distributed.replica_choice events landed in "
                        "the flight recorder — the policy never routed")
    for msg in failures:
        print(f"SKEW SMOKE FAIL: {msg}", flush=True)
    if failures:
        dumped = _flight.maybe_auto_dump("skew_smoke_failure")
        if dumped:
            print(f"flight dump: {dumped}", flush=True)
    return 1 if failures else 0


# ---------------------------------------------------------------------------
# conf-driven multi-algo harness (reference: cpp/bench/ann/conf/*.json
# workloads + eval.pl summary conditions "QPS at recall=0.9/0.95",
# "recall at QPS=2000"; latency mode -l)
# ---------------------------------------------------------------------------

def _make_dataset(ds):
    rng = np.random.default_rng(0)
    # deep-scale confs bound the database (the reference's subset_size
    # option for the billion-scale sets, cuda_ann_benchmarks.md)
    n = ds.get("subset_size") or ds["n_db"]
    dim = ds["dim"]
    latent = ds.get("latent_dim", 16)
    Z = rng.normal(size=(n + ds["n_queries"], latent)).astype(np.float32)
    A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
    X = (Z @ A).astype(np.float32)
    X += ds.get("noise", 0.05) * rng.normal(size=X.shape).astype(np.float32)
    import jax.numpy as jnp
    X = jnp.asarray(X)
    return X[:n], X[n:]


def run_conf(conf_path: str) -> None:
    from raft_tpu import DeviceResources
    from raft_tpu.distance.types import resolve_metric
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
    from raft_tpu.neighbors.refine import refine as refine_fn

    with open(conf_path) as f:
        conf = json.load(f)
    res = DeviceResources(seed=0)
    ds = conf["dataset"]
    metric = resolve_metric(ds.get("distance", "euclidean"))
    db, queries = _make_dataset(ds)
    basic = conf["search_basic_param"]
    k, runs = basic["k"], basic.get("run_count", 3)
    batch = min(basic.get("batch_size", queries.shape[0]),
                queries.shape[0])
    q_batches = [queries[s:s + batch]
                 for s in range(0, queries.shape[0], batch)]

    _, gt_i = brute_force.knn(res, db, queries, k, metric=metric)
    gt_i = np.asarray(gt_i)
    results = []

    for entry in conf["index"]:
        algo, bp = entry["algo"], entry["build_param"]
        t0 = time.perf_counter()
        if bp.get("multigpu"):
            # the reference conf's multigpu option
            # (cuda_ann_benchmarks.md:163) — sharded build + search over
            # every visible device via distributed.{knn,ann}, for all
            # four algos
            from raft_tpu.comms.session import CommsSession
            from raft_tpu.distributed import ann as dist_ann

            session = CommsSession().init()
            handle = session.worker_handle()
            n_dev = len(session.mesh.devices.ravel())
            if db.shape[0] % n_dev:
                # truncating would silently cap recall: ground truth is
                # computed over the full db
                raise ValueError(
                    f"multigpu conf: n_db ({db.shape[0]}) must divide "
                    f"evenly over {n_dev} devices")
            mg_db = db
            if algo == "bfknn":
                index = None
            elif algo == "ivf_flat":
                index = dist_ann.build_flat(
                    handle, ivf_flat.IndexParams(n_lists=bp["nlist"],
                                                 metric=metric), mg_db)
            elif algo == "ivf_pq":
                index = dist_ann.build(
                    handle, ivf_pq.IndexParams(n_lists=bp["nlist"],
                                               pq_dim=bp.get("pq_dim", 0),
                                               metric=metric), mg_db)
            elif algo == "cagra":
                index = dist_ann.build_cagra(
                    handle, cagra.IndexParams(
                        graph_degree=bp.get("graph_degree", 64),
                        intermediate_graph_degree=bp.get(
                            "intermediate_graph_degree", 128),
                        build_n_lists=bp.get("nlist", 0),
                        build_n_probes=bp.get("build_n_probes", 32),
                        build_candidates=bp.get("build_candidates", 8192),
                        metric=metric), mg_db)
            else:
                raise ValueError(f"unknown multigpu algo {algo}")
            mg_handle = handle
        elif algo == "bfknn":
            index = None
        elif algo == "ivf_flat":
            index = ivf_flat.build(
                res, ivf_flat.IndexParams(n_lists=bp["nlist"],
                                          metric=metric), db)
        elif algo == "ivf_pq":
            index = ivf_pq.build(
                res, ivf_pq.IndexParams(
                    n_lists=bp["nlist"], pq_dim=bp.get("pq_dim", 0),
                    kmeans_trainset_fraction=bp.get("trainset_fraction",
                                                    0.5),
                    metric=metric), db)
        elif algo == "cagra":
            index = cagra.build(
                res, cagra.IndexParams(
                    graph_degree=bp.get("graph_degree", 64),
                    intermediate_graph_degree=bp.get(
                        "intermediate_graph_degree", 128),
                    build_n_lists=bp.get("nlist", 0),
                    build_n_probes=bp.get("build_n_probes", 32),
                    build_candidates=bp.get("build_candidates", 8192),
                    metric=metric), db)
        else:
            raise ValueError(f"unknown algo {algo}")
        build_s = time.perf_counter() - t0

        for sp in entry["search_params"]:
            def query(q):
                if bp.get("multigpu"):
                    from raft_tpu.distributed import ann as dist_ann
                    from raft_tpu.distributed import knn as dist_knn
                    if algo == "bfknn":
                        return dist_knn.knn(mg_handle, mg_db, q, k,
                                            metric=metric)[1]
                    if algo == "ivf_flat":
                        p = ivf_flat.SearchParams(n_probes=sp["nprobe"])
                        return dist_ann.search_flat(mg_handle, p, index,
                                                    q, k)[1]
                    if algo == "cagra":
                        p = cagra.SearchParams(
                            itopk_size=sp["itopk"],
                            search_width=sp.get("search_width", 1))
                        return dist_ann.search_cagra(mg_handle, p, index,
                                                     q, k)[1]
                    p = ivf_pq.SearchParams(
                        n_probes=sp["nprobe"],
                        scan_mode=sp.get("scan_mode", "auto"),
                        per_probe_topk=sp.get("per_probe_topk", 0),
                        packed_extract=sp.get("packed_extract", False))
                    return dist_ann.search(mg_handle, p, index, q, k)[1]
                if algo == "bfknn":
                    return brute_force.knn(res, db, q, k, metric=metric)[1]
                if algo == "ivf_flat":
                    return ivf_flat.search(
                        res, ivf_flat.SearchParams(n_probes=sp["nprobe"]),
                        index, q, k)[1]
                if algo == "ivf_pq":
                    ratio = sp.get("refine_ratio", 1)
                    p = ivf_pq.SearchParams(
                        n_probes=sp["nprobe"],
                        scan_mode=sp.get("scan_mode", "auto"),
                        per_probe_topk=sp.get("per_probe_topk", 0),
                        packed_extract=sp.get("packed_extract", False))
                    i = ivf_pq.search(res, p, index, q, k * ratio)[1]
                    if ratio > 1:
                        i = refine_fn(res, db, q, i, k, metric=metric)[1]
                    return i
                return cagra.search(
                    res, cagra.SearchParams(
                        itopk_size=sp["itopk"],
                        search_width=sp.get("search_width", 1)),
                    index, q, k)[1]

            found = [query(q) for q in q_batches]   # warmup/compile
            np.asarray(found[-1])   # forced readback (see bench_kmeans)
            _check_sane(entry["name"], np.concatenate(
                [np.asarray(f) for f in found]), db.shape[0])
            recall = _recall(np.concatenate([np.asarray(f)
                                             for f in found]), gt_i)
            t0 = time.perf_counter()
            for _ in range(runs):
                for q in q_batches:
                    i = query(q)
            np.asarray(i)       # host readback (see bench_ivf_pq note)
            per_run = (time.perf_counter() - t0) / runs
            # latency mode (eval.pl -l): per-batch wall clock with a
            # host sync per batch, reported as percentiles
            lats = []
            for _ in range(max(runs, 3)):
                for q in q_batches:
                    t1 = time.perf_counter()
                    np.asarray(query(q))
                    lats.append((time.perf_counter() - t1) * 1000)
            lats = np.asarray(lats)
            results.append({
                "name": entry["name"], "search_param": sp,
                "recall": round(recall, 4),
                "qps": round(queries.shape[0] / per_run, 1),
                "latency_ms": round(per_run / len(q_batches) * 1000, 2),
                "latency_p50_ms": round(float(np.percentile(lats, 50)), 2),
                "latency_p95_ms": round(float(np.percentile(lats, 95)), 2),
                "latency_p99_ms": round(float(np.percentile(lats, 99)), 2),
                "build_s": round(build_s, 1)})
            _emit(results[-1])

    # eval.pl-style summary conditions
    for bar in (0.9, 0.95):
        best = {}
        for r in results:
            if r["recall"] >= bar and (r["name"] not in best or
                                       r["qps"] > best[r["name"]]["qps"]):
                best[r["name"]] = r
        for name, r in best.items():
            _emit({"summary": f"QPS at recall={bar}",
                   "name": name, "qps": r["qps"],
                   "recall": r["recall"]})
    eligible = [r for r in results if r["qps"] >= QPS_REFERENCE_POINT]
    for name in {r["name"] for r in eligible}:
        top = max((r for r in eligible if r["name"] == name),
                  key=lambda r: r["recall"])
        _emit({"summary": "recall at QPS=2000", "name": name,
               "recall": top["recall"], "qps": top["qps"]})
    _emit({"integrity_counters": _integrity_counters()})


def _setup_jax_cache() -> None:
    # persistent compile cache: the remote TPU AOT compile dominates one-shot
    # build wall-clock (measured ~170s compile vs ~7s execute for a 100k
    # extend); caching amortizes it across bench invocations
    import os

    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                     "/tmp/raft_tpu_jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def main() -> None:
    _setup_jax_cache()

    from raft_tpu import DeviceResources

    res = DeviceResources(seed=0)
    db, queries = _make_dataset({"n_db": N_DB, "dim": DIM,
                                 "latent_dim": LATENT_DIM, "noise": NOISE,
                                 "n_queries": N_QUERIES})
    db.block_until_ready()

    # all five BASELINE.md configs emit metric lines in one run:
    # (1) pairwise check, (2) brute-force + fusedL2NN, (3) k-means,
    # (4) IVF-Flat then IVF-PQ (+ CAGRA, the headline), (5) MNMG
    gt_i = _ground_truth(res, db, queries)
    _emit(bench_pairwise(res))
    _emit(bench_brute_force(res, db, queries))
    _emit(bench_cagra(res, db, queries, gt_i))
    _emit(bench_ivf_flat(res, db, queries, gt_i))
    _emit(bench_ivf_pq(res, db, queries, gt_i))
    _emit(bench_kmeans(res, db[:KMEANS_N]))
    _emit(bench_mnmg(res))
    for line in bench_distributed(res):
        _emit(line)
    # online serving over a 100k slice of the same dataset (the CI
    # smoke runs the conf/serving-smoke.json variant of this)
    for line in bench_serving(res, db[:SERVING_N], queries[:2048]):
        _emit(line)
    # the same serving stack under 1% delete + 1% extend mutation churn
    for line in bench_mutation(res, db[:SERVING_N], queries[:2048]):
        _emit(line)
    # WAL-backed streaming ingest: open-loop writer at 2x the write
    # peak concurrent with reads, then kill-and-recover (zero acked
    # loss); the CI smoke runs the conf/ingest-smoke.json variant
    for line in bench_ingest(res, db[:SERVING_N], queries[:2048]):
        _emit(line)
    _emit({"integrity_counters": _integrity_counters()})


if __name__ == "__main__":
    _check_bench_out_writable()
    try:
        if len(sys.argv) >= 3 and sys.argv[1] == "--conf":
            _setup_jax_cache()
            run_conf(sys.argv[2])
        elif len(sys.argv) >= 2 and sys.argv[1] == "--serving":
            _setup_jax_cache()
            conf = sys.argv[2] if len(sys.argv) >= 3 else \
                os.path.join(os.path.dirname(__file__), "conf",
                             "serving-smoke.json")
            sys.exit(run_serving(conf))
        elif len(sys.argv) >= 2 and sys.argv[1] == "--overload":
            _setup_jax_cache()
            conf = sys.argv[2] if len(sys.argv) >= 3 else \
                os.path.join(os.path.dirname(__file__), "conf",
                             "overload-smoke.json")
            sys.exit(run_overload(conf))
        elif len(sys.argv) >= 2 and sys.argv[1] == "--quality":
            _setup_jax_cache()
            conf = sys.argv[2] if len(sys.argv) >= 3 else \
                os.path.join(os.path.dirname(__file__), "conf",
                             "quality-smoke.json")
            sys.exit(run_quality(conf))
        elif len(sys.argv) >= 2 and sys.argv[1] == "--skew":
            _setup_jax_cache()
            conf = sys.argv[2] if len(sys.argv) >= 3 else \
                os.path.join(os.path.dirname(__file__), "conf",
                             "skew-smoke.json")
            sys.exit(run_skew(conf))
        elif len(sys.argv) >= 2 and sys.argv[1] == "--filtered":
            _setup_jax_cache()
            conf = sys.argv[2] if len(sys.argv) >= 3 else \
                os.path.join(os.path.dirname(__file__), "conf",
                             "filtered-smoke.json")
            sys.exit(run_filtered(conf))
        elif len(sys.argv) >= 2 and sys.argv[1] == "--ingest":
            _setup_jax_cache()
            conf = sys.argv[2] if len(sys.argv) >= 3 else \
                os.path.join(os.path.dirname(__file__), "conf",
                             "ingest-smoke.json")
            sys.exit(run_ingest(conf))
        else:
            main()
    finally:
        # pass or fail, every run leaves its machine-readable record
        _write_bench_artifact()
