"""Benchmark harness — prints ONE JSON line with the headline metric.

Modeled on the reference's ANN bench summary metrics (cpp/bench/ann/scripts/
eval.pl:26: QPS at recall=0.9/0.95) and the driver's north-star
(BASELINE.md): IVF QPS@recall95 on a SIFT-like workload (128-dim, batch 5000,
k=10 — cpp/bench/ann/conf/sift-128-euclidean.json search_basic_param).

Until IVF-PQ lands this measures IVF-Flat, the closest built stage of the
flagship pipeline.  ``vs_baseline`` is QPS / 2000 — the reference harness's
own "recall at QPS=2000" operating point (eval.pl:26) used as the provisional
scale until driver-recorded baselines exist (BASELINE.json ``published`` is
``{}``).
"""

import json
import time

import jax
import numpy as np

N_DB = int(100_000)
N_QUERIES = 5_000
DIM = 128
K = 10
N_LISTS = 1024
N_PROBES = 32
MIN_RECALL = 0.95
QPS_REFERENCE_POINT = 2000.0  # eval.pl:26 "recall at QPS=2000" condition


def main() -> None:
    from raft_tpu import DeviceResources
    from raft_tpu.neighbors import brute_force, ivf_flat
    from raft_tpu.random import make_blobs

    res = DeviceResources(seed=0)
    X, _ = make_blobs(N_DB + N_QUERIES, DIM, n_clusters=1000,
                      cluster_std=4.0, seed=0)
    db, queries = X[:N_DB], X[N_DB:]
    db.block_until_ready()

    # ground truth for recall (the bench's naive_knn analogue)
    gt_d, gt_i = brute_force.knn(res, db, queries, K)
    gt_i = np.asarray(gt_i)

    params = ivf_flat.IndexParams(n_lists=N_LISTS, kmeans_n_iters=20)
    index = ivf_flat.build(res, params, db)

    sp = ivf_flat.SearchParams(n_probes=N_PROBES)
    # warmup (compile)
    d, i = ivf_flat.search(res, sp, index, queries, K)
    i.block_until_ready()

    runs = 3  # run_count=3, sift-128-euclidean.json
    t0 = time.perf_counter()
    for _ in range(runs):
        d, i = ivf_flat.search(res, sp, index, queries, K)
    i.block_until_ready()
    elapsed = (time.perf_counter() - t0) / runs

    found = np.asarray(i)
    hits = sum(len(set(f) & set(t)) for f, t in zip(found, gt_i))
    recall = hits / gt_i.size
    qps = N_QUERIES / elapsed

    print(json.dumps({
        "metric": f"ivf_flat_qps@recall{MIN_RECALL:.2f}"
                  if recall >= MIN_RECALL else
                  f"ivf_flat_qps@recall={recall:.3f}(below_target)",
        "value": round(qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(qps / QPS_REFERENCE_POINT, 3),
    }))


if __name__ == "__main__":
    main()
