"""Sparse solvers: Lanczos eigenpairs + Boruvka MST.

Reference: cpp/include/raft/sparse/solver/lanczos.cuh
(``computeSmallestEigenvectors`` / ``computeLargestEigenvectors``) and
sparse/solver/mst.cuh + mst_solver.cuh (Boruvka MST, used by
single-linkage) — SURVEY.md §2.5.

TPU design: both are fixed-iteration jittable loops —

- **Lanczos**: classic tridiagonalization with full reorthogonalization
  (the reference restarts; full reorth at these m is cheaper than restart
  logic and is XLA-friendly: one (m, n) panel matmul per step).  The small
  (m, m) tridiagonal eigenproblem solves with ``jnp.linalg.eigh``.
- **Boruvka**: edge-list halving — each round every component picks its
  minimum outgoing edge (``segment_min`` over encoded weight+id keys),
  merges via iterated pointer jumping (log-depth label propagation).
  Rounds are bounded by ceil(log2(n)) statically.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.sparse.formats import CooMatrix, CsrMatrix
from raft_tpu.sparse.linalg import spmv


# ---------------------------------------------------------------------------
# Lanczos
# ---------------------------------------------------------------------------

def lanczos_tridiag(
    matvec: Callable[[jax.Array], jax.Array],
    n: int,
    m: int,
    v0: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """m-step Lanczos: returns (V (m, n), alpha (m,), beta (m-1,))."""

    def step(carry, i):
        V, alpha, beta, v_prev, v = carry
        w = matvec(v)
        a = jnp.dot(w, v)
        w = w - a * v - jnp.where(i > 0, beta[jnp.maximum(i - 1, 0)],
                                  0.0) * v_prev
        # full reorthogonalization against the panel built so far
        mask = (jnp.arange(m) <= i)[:, None]
        proj = (V * mask) @ w
        w = w - (V * mask).T @ proj
        b = jnp.linalg.norm(w)
        v_next = jnp.where(b > 1e-10, w / jnp.maximum(b, 1e-30),
                           jnp.zeros_like(w))
        V = V.at[i].set(v)
        alpha = alpha.at[i].set(a)
        beta = jnp.where(i < m - 1, beta.at[jnp.minimum(i, m - 2)].set(b),
                         beta)
        return (V, alpha, beta, v, v_next), None

    V0 = jnp.zeros((m, n), jnp.float32)
    alpha0 = jnp.zeros((m,), jnp.float32)
    beta0 = jnp.zeros((max(m - 1, 1),), jnp.float32)
    v = v0 / jnp.maximum(jnp.linalg.norm(v0), 1e-30)
    (V, alpha, beta, _, _), _ = jax.lax.scan(
        step, (V0, alpha0, beta0, jnp.zeros_like(v), v), jnp.arange(m))
    return V, alpha, beta


def _eig_from_tridiag(V, alpha, beta, n_components, largest):
    m = alpha.shape[0]
    T = (jnp.diag(alpha) + jnp.diag(beta[:m - 1], 1)
         + jnp.diag(beta[:m - 1], -1))
    evals, evecs = jnp.linalg.eigh(T)        # ascending
    if largest:
        evals = evals[::-1]
        evecs = evecs[:, ::-1]
    ritz = V.T @ evecs[:, :n_components]     # (n, k)
    norms = jnp.linalg.norm(ritz, axis=0)
    ritz = ritz / jnp.maximum(norms, 1e-30)
    return evals[:n_components], ritz


def eigsh_smallest(
    res,
    A: CsrMatrix,
    n_components: int,
    *,
    ncv: int = 0,
    matvec: Optional[Callable[[jax.Array], jax.Array]] = None,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Smallest eigenpairs of a symmetric operator
    (reference: lanczos.cuh ``computeSmallestEigenvectors``).
    Returns (eigenvalues (k,), eigenvectors (n, k))."""
    n = A.shape[0] if A is not None else None
    mv = matvec or (lambda x: spmv(A, x))
    expects(n is not None, "eigsh_smallest: need a CSR matrix or n via A")
    m = ncv or min(max(2 * n_components + 1, 20), n)
    v0 = jax.random.normal(jax.random.key(seed), (n,), jnp.float32)
    V, alpha, beta = lanczos_tridiag(mv, n, m, v0)
    return _eig_from_tridiag(V, alpha, beta, n_components, largest=False)


def eigsh_largest(res, A: CsrMatrix, n_components: int, *, ncv: int = 0,
                  matvec=None, seed: int = 0):
    """Reference: lanczos.cuh ``computeLargestEigenvectors``."""
    n = A.shape[0]
    mv = matvec or (lambda x: spmv(A, x))
    m = ncv or min(max(2 * n_components + 1, 20), n)
    v0 = jax.random.normal(jax.random.key(seed), (n,), jnp.float32)
    V, alpha, beta = lanczos_tridiag(mv, n, m, v0)
    return _eig_from_tridiag(V, alpha, beta, n_components, largest=True)


# ---------------------------------------------------------------------------
# Boruvka MST
# ---------------------------------------------------------------------------

def _pointer_jump(parent: jax.Array, rounds: int) -> jax.Array:
    """Iterated parent[parent[...]] — log-depth component flattening."""
    def body(_, p):
        return p[p]
    return jax.lax.fori_loop(0, rounds, body, parent)


@functools.partial(jax.jit, static_argnames=("n_vertices",))
def _boruvka(rows, cols, weights, n_vertices):
    """Boruvka rounds on a symmetric edge list.  Returns
    (mst_src, mst_dst, mst_weight, in_mst mask) with n_vertices-1 real
    entries for a connected graph (others padded -1)."""
    n_edges = rows.shape[0]
    big = jnp.float32(jnp.inf)
    n_rounds = max(int(np.ceil(np.log2(max(n_vertices, 2)))) + 1, 1)
    jump_rounds = n_rounds + 2

    def round_body(state):
        color, in_mst, n_merged, rnd = state
        # min outgoing edge per component: key = (weight, edge_id) encoded
        src_c = color[rows]
        dst_c = color[cols]
        cross = src_c != dst_c
        w = jnp.where(cross, weights, big)
        # segment argmin via min over encoded (weight, id) — ids break ties
        # deterministically (the reference's alteration step)
        order = jnp.argsort(w, stable=True)
        # cheaper: for each component take min weight then first edge achieving it
        wmin = jax.ops.segment_min(w, src_c, num_segments=n_vertices)
        is_min = cross & (w <= wmin[src_c] + 0.0)
        # first edge index per component among is_min
        eid = jnp.where(is_min, jnp.arange(n_edges), n_edges)
        emin = jax.ops.segment_min(eid, src_c, num_segments=n_vertices)
        has_edge = emin < n_edges
        sel = jnp.minimum(emin, n_edges - 1)
        # proposed merges: component c -> color of the other endpoint
        partner = jnp.where(has_edge, color[cols[sel]],
                            jnp.arange(n_vertices))
        # symmetry breaking: merge into the smaller color when both chose
        # each other (standard Boruvka star contraction)
        partner_of_partner = partner[partner]
        root = jnp.where(
            (partner_of_partner == jnp.arange(n_vertices))
            & (jnp.arange(n_vertices) < partner),
            jnp.arange(n_vertices), partner)
        new_color_map = _pointer_jump(root, jump_rounds)
        # mark selected edges as MST members (only components that merged
        # into another root add their edge; dedupe mutual pairs)
        adds = has_edge & (new_color_map != jnp.arange(n_vertices)) | (
            has_edge & (partner_of_partner == jnp.arange(n_vertices))
            & (jnp.arange(n_vertices) > partner))
        in_mst = in_mst.at[sel].set(in_mst[sel] | adds)
        new_color = new_color_map[color]
        merged = jnp.sum(adds.astype(jnp.int32))
        return new_color, in_mst, n_merged + merged, rnd + 1

    def cond(state):
        color, _, _, rnd = state
        # stop when one component (or max rounds)
        n_comp = jnp.sum((color == jnp.arange(n_vertices)).astype(jnp.int32))
        return jnp.logical_and(rnd < n_rounds + 4, n_comp > 1)

    color0 = jnp.arange(n_vertices)
    in_mst0 = jnp.zeros(n_edges, jnp.bool_)
    color, in_mst, _, _ = jax.lax.while_loop(
        cond, round_body, (color0, in_mst0, jnp.int32(0), jnp.int32(0)))
    return color, in_mst


def mst(
    res,
    coo: CooMatrix,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Minimum spanning forest of a symmetric weighted graph.

    Reference: sparse/solver/mst.cuh ``mst`` (Boruvka; returns src/dst/weight
    edge list).  Returns ``(src, dst, weight, color)`` where the first
    entries flagged by weight < inf are forest edges and ``color`` is the
    final component labeling (useful for ``connect_components``).
    """
    n = coo.shape[0]
    pad = coo.rows >= n
    rows = jnp.where(pad, 0, coo.rows)
    cols = jnp.where(pad, 0, coo.cols)
    w = jnp.where(pad | (coo.rows == coo.cols), jnp.inf,
                  coo.vals.astype(jnp.float32))
    color, in_mst = _boruvka(rows, cols, w, n)
    src = jnp.where(in_mst, rows, -1)
    dst = jnp.where(in_mst, cols, -1)
    weight = jnp.where(in_mst, w, jnp.inf)
    return src, dst, weight, color
