"""Sparse solvers: Lanczos eigenpairs + Boruvka MST.

Reference: cpp/include/raft/sparse/solver/lanczos.cuh
(``computeSmallestEigenvectors`` / ``computeLargestEigenvectors``) and
sparse/solver/mst.cuh + mst_solver.cuh (Boruvka MST, used by
single-linkage) — SURVEY.md §2.5.

TPU design: both are fixed-iteration jittable loops —

- **Lanczos**: thick-restart Lanczos (the analogue of the reference's
  ``restartIter``/``maxIter`` restarted solver) with two-pass full
  reorthogonalization — XLA-friendly: one (m, n) panel matmul per step,
  one (m, m) ``jnp.linalg.eigh`` per restart.  ``lanczos_tridiag`` (the
  single-cycle tridiagonalization) stays exported as a building block.
- **Boruvka**: edge-list halving — each round every component picks its
  minimum outgoing edge (``segment_min`` over encoded weight+id keys),
  merges via iterated pointer jumping (log-depth label propagation).
  Rounds are bounded by ceil(log2(n)) statically.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.sparse.formats import CooMatrix, CsrMatrix
from raft_tpu.sparse.linalg import spmv


# ---------------------------------------------------------------------------
# Lanczos
# ---------------------------------------------------------------------------

def _breakdown_direction(Vm: jax.Array, n: int, i) -> jax.Array:
    """Fresh deterministic unit direction orthogonal to the masked panel.

    Used on β-breakdown (invariant subspace found): continuing with a zero
    vector would append spurious zero eigenvalues to the projected matrix —
    poison for "smallest" queries.  The ~0 beta splits it into honest
    diagonal blocks instead.
    """
    r = jnp.cos(jnp.arange(n, dtype=jnp.float32) * (1.37 + i))
    r = r - Vm.T @ (Vm @ r)
    r = r - Vm.T @ (Vm @ r)
    return r / jnp.maximum(jnp.linalg.norm(r), 1e-30)


def lanczos_tridiag(
    matvec: Callable[[jax.Array], jax.Array],
    n: int,
    m: int,
    v0: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """m-step Lanczos: returns (V (m, n), alpha (m,), beta (m-1,))."""

    def step(carry, i):
        V, alpha, beta, v_prev, v = carry
        V = V.at[i].set(v)     # panel now includes v_0..v_i (incl. current)
        w = matvec(v)
        a = jnp.dot(w, v)
        w = w - a * v - jnp.where(i > 0, beta[jnp.maximum(i - 1, 0)],
                                  0.0) * v_prev
        # full reorthogonalization against the panel incl. the current
        # vector; two passes ("twice is enough") — one fp32 pass leaves
        # enough drift to skew the smallest Ritz values at near-full ncv
        mask = (jnp.arange(m) <= i)[:, None]
        Vm = V * mask
        for _ in range(2):
            w = w - Vm.T @ (Vm @ w)
        b = jnp.linalg.norm(w)
        v_next = jnp.where(b > 1e-7, w / jnp.maximum(b, 1e-30),
                           _breakdown_direction(Vm, n, i))
        alpha = alpha.at[i].set(a)
        beta = jnp.where(i < m - 1, beta.at[jnp.minimum(i, m - 2)].set(b),
                         beta)
        return (V, alpha, beta, v, v_next), None

    V0 = jnp.zeros((m, n), jnp.float32)
    alpha0 = jnp.zeros((m,), jnp.float32)
    beta0 = jnp.zeros((max(m - 1, 1),), jnp.float32)
    v = v0 / jnp.maximum(jnp.linalg.norm(v0), 1e-30)
    (V, alpha, beta, _, _), _ = jax.lax.scan(
        step, (V0, alpha0, beta0, jnp.zeros_like(v), v), jnp.arange(m))
    return V, alpha, beta


def _thick_restart_lanczos(mv, n, k, m, v0, largest, max_restarts, tol):
    """Thick-restart Lanczos (Wu & Simon) — the analogue of the reference's
    restarted solver (lanczos.cuh ``restartIter``/``maxIter`` parameters).

    Keeps the projected operator as a full symmetric (m, m) matrix H (the
    locked block after a restart is an arrowhead, not tridiagonal) and the
    basis panel V (m, n).  Each cycle fills columns ``start..m-1`` of H via
    two-pass Gram–Schmidt projections (the projections ARE the H entries,
    so no three-term recurrence is relied on).  At restart, the ``l`` best
    Ritz pairs are locked: V[:l] <- Ritz vectors, H[:l,:l] <- diag(theta),
    and the cycle continues from index l with the residual vector — the
    coupling column H[:l, l] falls out of the projections automatically.
    """
    l = min(k + max(4, k), m - 2)          # locked block size

    def cycle(V, H, v, start):
        def step(carry, j):
            V, H, v = carry

            def do(args):
                V, H, v = args
                V = V.at[j].set(v)
                w = mv(v)
                mask = (jnp.arange(m) <= j)[:, None]
                Vm = V * mask
                p1 = Vm @ w
                w = w - Vm.T @ p1
                p2 = Vm @ w
                w = w - Vm.T @ p2
                H = H.at[:, j].set(p1 + p2)
                b = jnp.linalg.norm(w)
                v_next = jnp.where(b > 1e-7, w / jnp.maximum(b, 1e-30),
                                   _breakdown_direction(Vm, n, j))
                return (V, H, v_next), b

            def skip(args):
                return args, jnp.float32(0)

            (V, H, v), b = jax.lax.cond(j >= start, do, skip, (V, H, v))
            return (V, H, v), b

        (V, H, v), bs = jax.lax.scan(step, (V, H, v), jnp.arange(m))
        return V, H, v, bs[m - 1]

    def ritz(H):
        Hs = jnp.triu(H) + jnp.triu(H, 1).T
        evals, S = jnp.linalg.eigh(Hs)      # ascending
        if largest:
            evals, S = evals[::-1], S[:, ::-1]
        return evals, S

    # one (m, m) eigh per iteration: body computes the Ritz decomposition
    # once, uses it for both the convergence estimate (sets the done flag
    # read by cond) and the restart itself
    def body(state):
        V, H, v, b_last, it, _ = state
        evals, S = ritz(H)
        scale = jnp.maximum(jnp.abs(evals[:k]), 1e-6)
        resid = jnp.max(jnp.abs(b_last * S[m - 1, :k]) / scale)

        def do(args):
            V, H, v = args
            Y = S[:, :l].T @ V              # (l, n) locked Ritz vectors
            Vn = jnp.zeros_like(V).at[:l].set(Y)
            Hn = jnp.zeros_like(H).at[jnp.arange(l), jnp.arange(l)].set(
                evals[:l])
            return cycle(Vn, Hn, v, l)

        V, H, v, b_last = jax.lax.cond(
            resid > tol, do, lambda args: (args[0], args[1], args[2], b_last),
            (V, H, v))
        return V, H, v, b_last, it + 1, resid <= tol

    def cond(state):
        it, done = state[4], state[5]
        return jnp.logical_and(it < max_restarts, jnp.logical_not(done))

    v = v0 / jnp.maximum(jnp.linalg.norm(v0), 1e-30)
    V = jnp.zeros((m, n), jnp.float32)
    H = jnp.zeros((m, m), jnp.float32)
    V, H, v, b_last = cycle(V, H, v, 0)
    V, H, _, _, _, _ = jax.lax.while_loop(
        cond, body, (V, H, v, b_last, jnp.int32(0), jnp.bool_(False)))

    evals, S = ritz(H)
    vecs = V.T @ S[:, :k]                   # (n, k)
    vecs = vecs / jnp.maximum(jnp.linalg.norm(vecs, axis=0), 1e-30)
    return evals[:k], vecs


def eigsh_smallest(
    res,
    A: CsrMatrix,
    n_components: int,
    *,
    ncv: int = 0,
    matvec: Optional[Callable[[jax.Array], jax.Array]] = None,
    max_restarts: int = 30,
    tol: float = 1e-5,
    seed: int = 0,
    n: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Smallest eigenpairs of a symmetric operator
    (reference: lanczos.cuh ``computeSmallestEigenvectors``).
    Matrix-free use: pass ``matvec`` + ``n`` with ``A=None``.
    Returns (eigenvalues (k,), eigenvectors (n, k))."""
    n = A.shape[0] if A is not None else n
    mv = matvec or (lambda x: spmv(A, x))
    expects(n is not None, "eigsh_smallest: need a CSR matrix or explicit n")
    m = ncv or min(max(2 * n_components + 1, 20), n)
    v0 = jax.random.normal(jax.random.key(seed), (n,), jnp.float32)
    return _thick_restart_lanczos(mv, n, n_components, m, v0, False,
                                  max_restarts, tol)


def eigsh_largest(res, A: CsrMatrix, n_components: int, *, ncv: int = 0,
                  matvec=None, max_restarts: int = 30, tol: float = 1e-5,
                  seed: int = 0, n: Optional[int] = None):
    """Reference: lanczos.cuh ``computeLargestEigenvectors``."""
    n = A.shape[0] if A is not None else n
    expects(n is not None, "eigsh_largest: need a CSR matrix or explicit n")
    mv = matvec or (lambda x: spmv(A, x))
    m = ncv or min(max(2 * n_components + 1, 20), n)
    v0 = jax.random.normal(jax.random.key(seed), (n,), jnp.float32)
    return _thick_restart_lanczos(mv, n, n_components, m, v0, True,
                                  max_restarts, tol)


# ---------------------------------------------------------------------------
# Boruvka MST
# ---------------------------------------------------------------------------

def _pointer_jump(parent: jax.Array, rounds: int) -> jax.Array:
    """Iterated parent[parent[...]] — log-depth component flattening."""
    def body(_, p):
        return p[p]
    return jax.lax.fori_loop(0, rounds, body, parent)


@functools.partial(jax.jit, static_argnames=("n_vertices",))
def _boruvka(rows, cols, weights, n_vertices):
    """Boruvka rounds on a symmetric edge list.  Returns
    (mst_src, mst_dst, mst_weight, in_mst mask) with n_vertices-1 real
    entries for a connected graph (others padded -1)."""
    n_edges = rows.shape[0]
    big = jnp.float32(jnp.inf)
    n_rounds = max(int(np.ceil(np.log2(max(n_vertices, 2)))) + 1, 1)
    jump_rounds = n_rounds + 2

    def round_body(state):
        color, in_mst, n_merged, rnd = state
        # min outgoing edge per component: key = (weight, edge_id) encoded
        src_c = color[rows]
        dst_c = color[cols]
        cross = src_c != dst_c
        w = jnp.where(cross, weights, big)
        # min outgoing edge per component under the total order
        # (weight, min(u,v), max(u,v)): tie-breaking on the CANONICAL
        # undirected key (both directions of an edge compare equal) is what
        # guarantees equal-weight selections can only form 2-cycles, which
        # the star contraction below resolves (the reference's "alteration"
        # step serves the same purpose, mst_solver.cuh)
        cu = jnp.minimum(rows, cols)
        cv = jnp.maximum(rows, cols)
        wmin = jax.ops.segment_min(w, src_c, num_segments=n_vertices)
        is_w = cross & (w <= wmin[src_c])
        cu_k = jnp.where(is_w, cu, n_vertices)
        cumin = jax.ops.segment_min(cu_k, src_c, num_segments=n_vertices)
        is_cu = is_w & (cu == cumin[src_c])
        cv_k = jnp.where(is_cu, cv, n_vertices)
        cvmin = jax.ops.segment_min(cv_k, src_c, num_segments=n_vertices)
        is_min = is_cu & (cv == cvmin[src_c])
        # first edge index per component among the (now unique-undirected)
        # minimal edges
        eid = jnp.where(is_min, jnp.arange(n_edges), n_edges)
        emin = jax.ops.segment_min(eid, src_c, num_segments=n_vertices)
        has_edge = emin < n_edges
        sel = jnp.minimum(emin, n_edges - 1)
        # proposed merges: component c -> color of the other endpoint
        partner = jnp.where(has_edge, color[cols[sel]],
                            jnp.arange(n_vertices))
        # symmetry breaking: merge into the smaller color when both chose
        # each other (standard Boruvka star contraction)
        partner_of_partner = partner[partner]
        root = jnp.where(
            (partner_of_partner == jnp.arange(n_vertices))
            & (jnp.arange(n_vertices) < partner),
            jnp.arange(n_vertices), partner)
        new_color_map = _pointer_jump(root, jump_rounds)
        # mark selected edges as MST members (only components that merged
        # into another root add their edge; dedupe mutual pairs)
        adds = has_edge & (new_color_map != jnp.arange(n_vertices)) | (
            has_edge & (partner_of_partner == jnp.arange(n_vertices))
            & (jnp.arange(n_vertices) > partner))
        in_mst = in_mst.at[sel].set(in_mst[sel] | adds)
        new_color = new_color_map[color]
        merged = jnp.sum(adds.astype(jnp.int32))
        return new_color, in_mst, n_merged + merged, rnd + 1

    def cond(state):
        color, _, _, rnd = state
        # stop when one component (or max rounds)
        n_comp = jnp.sum((color == jnp.arange(n_vertices)).astype(jnp.int32))
        return jnp.logical_and(rnd < n_rounds + 4, n_comp > 1)

    color0 = jnp.arange(n_vertices)
    in_mst0 = jnp.zeros(n_edges, jnp.bool_)
    color, in_mst, _, _ = jax.lax.while_loop(
        cond, round_body, (color0, in_mst0, jnp.int32(0), jnp.int32(0)))
    return color, in_mst


def mst(
    res,
    coo: CooMatrix,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Minimum spanning forest of a symmetric weighted graph.

    Reference: sparse/solver/mst.cuh ``mst`` (Boruvka; returns src/dst/weight
    edge list).  Returns ``(src, dst, weight, color)`` where the first
    entries flagged by weight < inf are forest edges and ``color`` is the
    final component labeling (useful for ``connect_components``).
    """
    n = coo.shape[0]
    pad = coo.rows >= n
    rows = jnp.where(pad, 0, coo.rows)
    cols = jnp.where(pad, 0, coo.cols)
    w = jnp.where(pad | (coo.rows == coo.cols), jnp.inf,
                  coo.vals.astype(jnp.float32))
    color, in_mst = _boruvka(rows, cols, w, n)
    src = jnp.where(in_mst, rows, -1)
    dst = jnp.where(in_mst, cols, -1)
    weight = jnp.where(in_mst, w, jnp.inf)
    return src, dst, weight, color
