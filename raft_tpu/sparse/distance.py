"""Sparse pairwise distances (CSR × CSR).

Reference: cpp/include/raft/sparse/distance/distance.cuh:68
``pairwiseDistance`` with per-metric detail kernels (SURVEY.md §2.5).

TPU design: the MXU wants dense tiles — sparse×sparse products on TPU are
fastest as *densified blocks* feeding the same expanded-form math as the
dense metrics, which also reuses the dense epilogues exactly.  This is
the honest TPU answer to cuSPARSE's SpGEMM: for the dims RAFT targets
(feature dims ≤ ~100k with row nnz ≪ dim), block densification + MXU
beats scalar gather-multiply loops.

Round-4 restructure (VERDICT r3): the tiling is now *traced* —
``lax.map``/``fori_loop`` over row/column tiles instead of a Python loop
that unrolled O((m/T)·(n/T)) matmuls into the program — and the
inner-product family accumulates over **column blocks** of the feature
axis, so a (tile, dim) densified transient never materializes: peak
extra HBM is O(tile · _DIM_BLOCK), independent of m, n AND dim.  Row
norms/sums come straight from the CSR data (a segment-sum), never from
densified rows.  Metrics outside the inner-product family still densify
full-width tiles (their elementwise terms need aligned features), with
the traced tiling bounding compile size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.distance.types import DistanceType
from raft_tpu.sparse.formats import CsrMatrix
from raft_tpu.core.outputs import raw
from raft_tpu.utils.precision import get_matmul_precision

_TILE_ROWS = 2048
_DIM_BLOCK = 4096

# metrics whose pairwise term is a function of (x.y, row stats) only —
# these take the column-blocked MXU path
_EXPANDED = (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
             DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded,
             DistanceType.InnerProduct, DistanceType.CosineExpanded,
             DistanceType.CorrelationExpanded)


def _round_up(v, m):
    return -(-v // m) * m


def _densify_block(rows, cols, data, r0, tile, c0, db):
    """Densify the (tile, db) block [r0:r0+tile) × [c0:c0+db) of a COO
    triplet view; out-of-block entries scatter to a dropped guard row."""
    in_blk = ((rows >= r0) & (rows < r0 + tile)
              & (cols >= c0) & (cols < c0 + db))
    lr = jnp.where(in_blk, rows - r0, tile)
    lc = jnp.where(in_blk, cols - c0, 0)
    out = jnp.zeros((tile + 1, db), data.dtype)
    out = out.at[lr, lc].add(jnp.where(in_blk, data, 0))
    return out[:tile]


@functools.partial(jax.jit, static_argnames=("m", "n", "dim", "metric",
                                             "tile", "db"))
def _expanded_impl(xr, xc, xd, yr, yc, yd, x_stats, y_stats,
                   m, n, dim, metric, tile=_TILE_ROWS, db=_DIM_BLOCK):
    """Column-blocked CSR×CSR inner products + expanded-form epilogue.

    x_stats/y_stats: (rows, 2) — [sq_norm, sum] per row (from CSR data).
    """
    db = min(db, _round_up(dim, 128))
    mt = _round_up(m, tile) // tile
    nt = _round_up(n, tile) // tile
    dbt = _round_up(dim, db) // db
    acc = jnp.promote_types(xd.dtype, jnp.float32)

    def one_pair(args):
        i, j = args
        r0 = i * tile
        c0 = j * tile

        def dim_step(k, ip):
            d0 = k * db
            xb = _densify_block(xr, xc, xd, r0, tile, d0, db).astype(acc)
            yb = _densify_block(yr, yc, yd, c0, tile, d0, db).astype(acc)
            return ip + jax.lax.dot_general(
                xb, yb, (((1,), (1,)), ((), ())),
                precision=get_matmul_precision(),
                preferred_element_type=acc)

        return jax.lax.fori_loop(0, dbt, dim_step,
                                 jnp.zeros((tile, tile), acc))

    ij = jnp.stack(jnp.meshgrid(jnp.arange(mt), jnp.arange(nt),
                                indexing="ij"), axis=-1).reshape(-1, 2)
    ips = jax.lax.map(one_pair, (ij[:, 0], ij[:, 1]))   # (mt*nt, tile, tile)
    ip = ips.reshape(mt, nt, tile, tile).transpose(0, 2, 1, 3)
    ip = ip.reshape(mt * tile, nt * tile)[:m, :n]

    x_sq, x_sum = x_stats[:, 0][:, None], x_stats[:, 1][:, None]
    y_sq, y_sum = y_stats[:, 0][None, :], y_stats[:, 1][None, :]
    if metric == DistanceType.InnerProduct:
        return ip
    if metric == DistanceType.CosineExpanded:
        denom = jnp.maximum(jnp.sqrt(x_sq) * jnp.sqrt(y_sq), 1e-30)
        return 1.0 - ip / denom
    if metric == DistanceType.CorrelationExpanded:
        # centered cosine from raw sums: zeros count toward the mean
        # (dense semantics — the reference densifies means the same way)
        mx, my = x_sum / dim, y_sum / dim
        cov = ip - dim * mx * my
        vx = jnp.maximum(x_sq - dim * mx * mx, 0.0)
        vy = jnp.maximum(y_sq - dim * my * my, 0.0)
        denom = jnp.maximum(jnp.sqrt(vx) * jnp.sqrt(vy), 1e-30)
        return 1.0 - cov / denom
    d = jnp.maximum(x_sq + y_sq - 2.0 * ip, 0.0)
    if metric in (DistanceType.L2SqrtExpanded,
                  DistanceType.L2SqrtUnexpanded):
        d = jnp.sqrt(d)
    return d


@functools.partial(jax.jit, static_argnames=("n_cols", "m", "n", "metric",
                                             "metric_arg", "tile"))
def _general_impl(xr, xc, xd, yr, yc, yd, n_cols, m, n, metric, metric_arg,
                  tile=_TILE_ROWS):
    """Traced row/col-tiled path for elementwise metrics: densify
    full-width (tile, dim) blocks and reuse the dense metric impls."""
    mt = _round_up(m, tile) // tile
    nt = _round_up(n, tile) // tile

    def one_pair(args):
        i, j = args
        xb = _densify_block(xr, xc, xd, i * tile, tile, 0, n_cols)
        yb = _densify_block(yr, yc, yd, j * tile, tile, 0, n_cols)
        return raw(pairwise_distance)(xb, yb, metric,
                                      metric_arg=metric_arg)

    ij = jnp.stack(jnp.meshgrid(jnp.arange(mt), jnp.arange(nt),
                                indexing="ij"), axis=-1).reshape(-1, 2)
    tiles = jax.lax.map(one_pair, (ij[:, 0], ij[:, 1]))
    out = tiles.reshape(mt, nt, tile, tile).transpose(0, 2, 1, 3)
    return out.reshape(mt * tile, nt * tile)[:m, :n]


def _row_stats(csr: CsrMatrix) -> jax.Array:
    """(rows, 2) [squared norm, sum] per row, straight from CSR data."""
    acc = jnp.promote_types(csr.data.dtype, jnp.float32)
    d = csr.data.astype(acc)
    rows = csr.row_ids()
    sq = jax.ops.segment_sum(d * d, rows, num_segments=csr.shape[0])
    sm = jax.ops.segment_sum(d, rows, num_segments=csr.shape[0])
    return jnp.stack([sq, sm], axis=1)


def pairwise_distance_sparse(
    x: CsrMatrix,
    y: CsrMatrix,
    metric: int = DistanceType.L2Expanded,
    *,
    metric_arg: float = 2.0,
) -> jax.Array:
    """All-pairs distances between CSR row sets (reference:
    sparse/distance/distance.cuh:68).  Returns dense (m, n).

    Inner-product-family metrics never materialize a full-width dense
    block (column-blocked accumulation, see module docstring); the
    remaining metrics densify (tile, dim) blocks under a traced tile
    loop.
    """
    expects(x.shape[1] == y.shape[1],
            "sparse pairwise: feature dims differ")
    m, n = x.shape[0], y.shape[0]
    dim = x.shape[1]
    tile = min(_TILE_ROWS, _round_up(max(m, n), 8))
    if metric in _EXPANDED:
        return _expanded_impl(
            x.row_ids(), x.indices, x.data, y.row_ids(), y.indices, y.data,
            _row_stats(x), _row_stats(y), m, n, dim, metric, tile=tile)
    return _general_impl(x.row_ids(), x.indices, x.data, y.row_ids(),
                         y.indices, y.data, dim, m, n, metric,
                         float(metric_arg), tile=tile)
