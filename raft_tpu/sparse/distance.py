"""Sparse pairwise distances (CSR × CSR).

Reference: cpp/include/raft/sparse/distance/distance.cuh:68
``pairwiseDistance`` with per-metric detail kernels (SURVEY.md §2.5).

TPU design: the MXU wants dense tiles — sparse×sparse products on TPU are
fastest as *densified row blocks* feeding the same expanded-form math as the
dense metrics (one gather + matmul per tile), which also reuses the dense
epilogues exactly.  This is the honest TPU answer to cuSPARSE's SpGEMM: for
the dims RAFT targets (feature dims ≤ ~100k with row nnz ≪ dim), block
densification + MXU beats scalar gather-multiply loops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.distance.types import DistanceType
from raft_tpu.sparse.formats import CsrMatrix
from raft_tpu.core.outputs import raw

_TILE_ROWS = 2048


def pairwise_distance_sparse(
    x: CsrMatrix,
    y: CsrMatrix,
    metric: int = DistanceType.L2Expanded,
    *,
    metric_arg: float = 2.0,
) -> jax.Array:
    """All-pairs distances between CSR row sets (reference:
    sparse/distance/distance.cuh:68).  Returns dense (m, n).

    Both sides are densified in row *blocks* (never the whole operand):
    peak extra HBM is O(2 · tile · dim), independent of m and n, matching
    the reference's tiled CSR×CSR traversal in spirit while keeping the
    inner product on the MXU.
    """
    expects(x.shape[1] == y.shape[1],
            "sparse pairwise: feature dims differ")
    m, n = x.shape[0], y.shape[0]
    row_blocks = []
    for xs in range(0, m, _TILE_ROWS):
        xe = min(xs + _TILE_ROWS, m)
        xd = _dense_rows(x, xs, xe)
        cols = []
        for ys in range(0, n, _TILE_ROWS):
            ye = min(ys + _TILE_ROWS, n)
            yd = _dense_rows(y, ys, ye)
            cols.append(raw(pairwise_distance)(xd, yd, metric,
                                          metric_arg=metric_arg))
        row_blocks.append(jnp.concatenate(cols, axis=1)
                          if len(cols) > 1 else cols[0])
    return (jnp.concatenate(row_blocks, axis=0)
            if len(row_blocks) > 1 else row_blocks[0])


def _dense_rows(csr: CsrMatrix, start: int, stop: int) -> jax.Array:
    """Densify a row block of a CSR matrix."""
    n_rows, n_cols = csr.shape
    rows = csr.row_ids()
    in_block = (rows >= start) & (rows < stop)
    local = jnp.where(in_block, rows - start, stop - start)
    out = jnp.zeros((stop - start + 1, n_cols), csr.data.dtype)
    out = out.at[local, csr.indices].add(
        jnp.where(in_block, csr.data, 0))
    return out[:stop - start]
