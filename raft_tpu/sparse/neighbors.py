"""Sparse neighbors: brute-force kNN over CSR, kNN-graph construction, and
cross-component 1-NN (``connect_components``).

Reference: cpp/include/raft/sparse/neighbors/{brute_force,knn,knn_graph,
connect_components}.cuh (SURVEY.md §2.5).  ``connect_components`` is the
single-linkage fix-up: after an MST pass leaves a forest, find for every
component its nearest point in any other component and add those edges
(detail in sparse/neighbors/cross_component_nn.cuh).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.distance.types import DistanceType
from raft_tpu.matrix.select_k import select_k
from raft_tpu.sparse.distance import pairwise_distance_sparse
from raft_tpu.sparse.formats import CooMatrix, CsrMatrix, coo_sort
from raft_tpu.core.outputs import raw


def brute_force_knn_sparse(
    x: CsrMatrix,
    y: CsrMatrix,
    k: int,
    *,
    metric: int = DistanceType.L2Expanded,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN of CSR queries x against CSR database y
    (reference: sparse/neighbors/brute_force.cuh)."""
    d = pairwise_distance_sparse(x, y, metric)
    select_min = metric != DistanceType.InnerProduct
    return raw(select_k)(d, k, select_min=select_min)


def knn_graph(
    res,
    X,
    k: int,
    *,
    metric: int = DistanceType.L2SqrtExpanded,
) -> CooMatrix:
    """Symmetrized kNN graph of dense points as COO
    (reference: sparse/neighbors/knn_graph.cuh — feeds single-linkage).
    Each of the n*k edges appears with its mirror (max-symmetrized)."""
    from raft_tpu.neighbors.brute_force import knn as dense_knn
    from raft_tpu.sparse.linalg import symmetrize

    X = ensure_array(X, "X")
    n = X.shape[0]
    d, i = dense_knn(res, X, X, k + 1, metric=metric)
    # drop self column (first hit is the point itself)
    d, i = d[:, 1:], i[:, 1:]
    rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
    coo = CooMatrix(rows, i.ravel().astype(jnp.int32),
                    d.ravel(), (n, n))
    return symmetrize(coo_sort(coo), op="max")


def connect_components(
    res,
    X,
    labels: jax.Array,
    *,
    metric: int = DistanceType.L2Expanded,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Cross-component nearest neighbors (reference:
    sparse/neighbors/connect_components.cuh `cross_component_nn`):
    for each component, the closest point pair reaching any OTHER component.
    Returns (src, dst, dist) — one candidate edge per component (padded -1
    for absent).  Adding these to an MST forest makes it spanning.
    """
    from raft_tpu.distance.pairwise import pairwise_distance

    X = ensure_array(X, "X")
    labels = ensure_array(labels, "labels").astype(jnp.int32)
    n = X.shape[0]
    # full pairwise with same-component masking; for the sizes single-linkage
    # handles (fix-up stage) the dense (n, n) block is acceptable, as the
    # reference's fix-up also does an all-pairs NN over components
    d = raw(pairwise_distance)(X, X, metric)
    same = labels[:, None] == labels[None, :]
    d = jnp.where(same, jnp.inf, d)
    best_j = jnp.argmin(d, axis=1).astype(jnp.int32)      # (n,)
    best_d = jnp.min(d, axis=1)
    # per-component best row
    order_key = best_d
    comp_min = jax.ops.segment_min(order_key, labels, num_segments=n)
    is_best = order_key <= comp_min[labels]
    rid = jnp.where(is_best, jnp.arange(n), n)
    comp_rep = jax.ops.segment_min(rid, labels, num_segments=n)
    valid = comp_rep < n
    src = jnp.where(valid, jnp.minimum(comp_rep, n - 1), -1)
    dst = jnp.where(valid, best_j[jnp.minimum(comp_rep, n - 1)], -1)
    dist = jnp.where(valid, best_d[jnp.minimum(comp_rep, n - 1)], jnp.inf)
    return src, dst, dist
