"""Sparse linear algebra.

Reference: cpp/include/raft/sparse/linalg/ — spmm/spgemm via cuSPARSE
wrappers (sparse/detail/cusparse_wrappers.h), add, norm, degree, transpose,
symmetrize, Laplacian/spectral embedding helpers (SURVEY.md §2.5).

TPU design: CSR×dense products are ``segment_sum`` over gathered dense rows
(HBM-bandwidth bound, like any SpMV); everything structural (transpose,
symmetrize, add) is sort + segment reduction.  No cuSPARSE analogue exists —
these ARE the kernels.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.sparse.formats import (
    CooMatrix,
    CsrMatrix,
    coo_sort,
    coo_to_csr,
    csr_to_coo,
)


def spmv(csr: CsrMatrix, x: jax.Array) -> jax.Array:
    """y = A @ x for CSR A (reference: cusparsespmv wrapper path).

    One gather of x[indices] + one segment_sum over row ids — the TPU SpMV.
    """
    rows = csr.row_ids()
    n_rows = csr.shape[0]
    contrib = csr.data * x[csr.indices]
    return jax.ops.segment_sum(
        jnp.where(rows < n_rows, contrib, 0),
        jnp.minimum(rows, n_rows - 1), num_segments=n_rows)


def spmm(csr: CsrMatrix, B: jax.Array) -> jax.Array:
    """C = A @ B for CSR A, dense B (reference: cusparsespmm wrapper)."""
    rows = csr.row_ids()
    n_rows = csr.shape[0]
    contrib = csr.data[:, None] * B[csr.indices]     # (nnz, k)
    return jax.ops.segment_sum(
        jnp.where((rows < n_rows)[:, None], contrib, 0),
        jnp.minimum(rows, n_rows - 1), num_segments=n_rows)


def transpose(coo: CooMatrix) -> CooMatrix:
    """Reference: sparse/linalg/transpose.hpp."""
    n_rows, n_cols = coo.shape
    pad = coo.rows >= n_rows
    return coo_sort(CooMatrix(
        jnp.where(pad, n_cols, coo.cols).astype(jnp.int32),
        jnp.where(pad, 0, coo.rows).astype(jnp.int32),
        coo.vals, (n_cols, n_rows)))


def add(a: CooMatrix, b: CooMatrix) -> CooMatrix:
    """C = A + B with duplicate coalescing
    (reference: sparse/linalg/add.hpp ``csr_add_calc/csr_add_finalize``).
    Output nnz is (a.nnz + b.nnz) static slots; duplicates are summed into
    one slot and the shadow entries padded out."""
    expects(a.shape == b.shape, "sparse.add: shape mismatch")
    n_rows, n_cols = a.shape
    rows = jnp.concatenate([a.rows, b.rows])
    cols = jnp.concatenate([a.cols, b.cols])
    vals = jnp.concatenate([a.vals, b.vals])
    merged = coo_sort(CooMatrix(rows, cols, vals, a.shape))
    # coalesce duplicates: after the sort, equal (row, col) are adjacent
    first = jnp.concatenate([
        jnp.ones(1, jnp.bool_),
        (merged.rows[1:] != merged.rows[:-1])
        | (merged.cols[1:] != merged.cols[:-1])])
    seg = jnp.cumsum(first) - 1                       # group index per slot
    summed = jax.ops.segment_sum(merged.vals, seg,
                                 num_segments=merged.nnz)
    # one representative slot per group; shadows -> padding
    out_rows = jnp.where(first, merged.rows, n_rows)
    out_vals = jnp.where(first, summed[seg], 0)
    return coo_sort(CooMatrix(out_rows, jnp.where(first, merged.cols, 0),
                              out_vals, a.shape))


def symmetrize(coo: CooMatrix, op: str = "add") -> CooMatrix:
    """A ∪ Aᵀ (reference: sparse/linalg/symmetrize.hpp — used to build
    undirected kNN graphs).  op='add' sums mirrored entries; op='max' keeps
    the max (the reference's coo_symmetrize lambda hook)."""
    at = transpose(coo)
    if op == "add":
        return add(coo, at)
    expects(op == "max", "symmetrize: op must be 'add' or 'max'")
    n_rows, n_cols = coo.shape
    rows = jnp.concatenate([coo.rows, at.rows])
    cols = jnp.concatenate([coo.cols, at.cols])
    vals = jnp.concatenate([coo.vals, at.vals])
    merged = coo_sort(CooMatrix(rows, cols, vals, coo.shape))
    first = jnp.concatenate([
        jnp.ones(1, jnp.bool_),
        (merged.rows[1:] != merged.rows[:-1])
        | (merged.cols[1:] != merged.cols[:-1])])
    seg = jnp.cumsum(first) - 1
    maxed = jax.ops.segment_max(merged.vals, seg, num_segments=merged.nnz)
    out_rows = jnp.where(first, merged.rows, n_rows)
    return coo_sort(CooMatrix(out_rows, jnp.where(first, merged.cols, 0),
                              jnp.where(first, maxed[seg], 0), coo.shape))


def degree(coo: CooMatrix) -> jax.Array:
    """Per-row entry count (reference: sparse/linalg/degree.hpp)."""
    n_rows = coo.shape[0]
    return jax.ops.segment_sum(
        jnp.where(coo.rows < n_rows, 1, 0),
        jnp.minimum(coo.rows, n_rows - 1).astype(jnp.int32),
        num_segments=n_rows)


def row_norm_csr(csr: CsrMatrix, norm_type: str = "l2") -> jax.Array:
    """Per-row norms (reference: sparse/linalg/norm.hpp)."""
    rows = csr.row_ids()
    n_rows = csr.shape[0]
    if norm_type == "l1":
        v = jnp.abs(csr.data)
    elif norm_type == "l2":
        v = csr.data * csr.data
    elif norm_type == "linf":
        return jax.ops.segment_max(
            jnp.where(rows < n_rows, jnp.abs(csr.data), 0),
            jnp.minimum(rows, n_rows - 1), num_segments=n_rows)
    else:
        raise ValueError(f"unknown norm {norm_type!r}")
    out = jax.ops.segment_sum(jnp.where(rows < n_rows, v, 0),
                              jnp.minimum(rows, n_rows - 1),
                              num_segments=n_rows)
    return jnp.sqrt(out) if norm_type == "l2" else out


def laplacian(adj: CooMatrix, normalized: bool = True
              ) -> Tuple[CsrMatrix, jax.Array]:
    """Graph Laplacian L = D - A (or normalized I - D^-1/2 A D^-1/2) as the
    (CSR, diagonal) pair used by the spectral solver (reference:
    spectral/matrix_wrappers.hpp ``laplacian_matrix_t`` — spmv computes
    D·x - A·x there; we return the same operator pieces)."""
    d = jax.ops.segment_sum(
        jnp.where(adj.rows < adj.shape[0], adj.vals, 0),
        jnp.minimum(adj.rows, adj.shape[0] - 1).astype(jnp.int32),
        num_segments=adj.shape[0])
    if normalized:
        inv_sqrt = jnp.where(d > 0, 1.0 / jnp.sqrt(jnp.maximum(d, 1e-30)),
                             0.0)
        vals = -adj.vals * inv_sqrt[jnp.minimum(adj.rows, adj.shape[0] - 1)] \
            * inv_sqrt[adj.cols]
        diag = jnp.where(d > 0, 1.0, 0.0)
    else:
        vals = -adj.vals
        diag = d
    neg_a = CooMatrix(adj.rows, adj.cols, vals, adj.shape)
    return coo_to_csr(neg_a), diag


def laplacian_spmv(lap_csr: CsrMatrix, diag: jax.Array, x: jax.Array
                   ) -> jax.Array:
    """L @ x given the (off-diagonal CSR, diagonal) pair."""
    return diag * x + spmv(lap_csr, x)
