"""Sparse stack: formats, ops, linalg, distances, neighbors, solvers.

Reference: cpp/include/raft/sparse/ (72 files, SURVEY.md §2.5).  XLA has no
sparse runtime, so the containers are static-nnz padded index/value arrays
(see formats.py) and the kernels are sort/segment/gather compositions with
MXU-friendly densified tiles where FLOPs dominate.
"""

from raft_tpu.sparse.formats import (  # noqa: F401
    CooMatrix,
    CsrMatrix,
    coo_sort,
    coo_to_csr,
    csr_to_coo,
    coo_to_dense,
    csr_to_dense,
    dense_to_coo,
    dense_to_csr,
)
from raft_tpu.sparse.linalg import (  # noqa: F401
    spmv,
    spmm,
    transpose,
    add,
    symmetrize,
    degree,
    row_norm_csr,
    laplacian,
    laplacian_spmv,
)
from raft_tpu.sparse.op import (  # noqa: F401
    coo_remove_scalar,
    coo_remove_zeros,
    csr_row_slice,
    csr_row_op,
    compute_duplicates_mask,
    max_duplicates,
)
from raft_tpu.sparse.distance import pairwise_distance_sparse  # noqa: F401
from raft_tpu.sparse.neighbors import (  # noqa: F401
    brute_force_knn_sparse,
    knn_graph,
    connect_components,
)
from raft_tpu.sparse.solver import (  # noqa: F401
    eigsh_smallest,
    eigsh_largest,
    lanczos_tridiag,
    mst,
)
