"""Sparse formats: COO and CSR containers + conversions.

Reference: cpp/include/raft/sparse/coo.hpp, csr.hpp and the owning/view
types in core/ (coo_matrix.hpp, csr_matrix.hpp, device_coo_matrix.hpp,
device_csr_matrix.hpp, sparse_types.hpp); conversions under sparse/convert/
(SURVEY.md §2.5).

TPU design: XLA has no sparse runtime (the central impedance mismatch,
SURVEY.md §7) — both containers are pytrees of dense index/value arrays with
a **static nnz**; "unused" slots are padded with row=n_rows (COO) so they
sort to the end and segment reductions drop them.  This mirrors
jax.experimental.sparse's BCOO padding convention while keeping the
reference's API names.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class CooMatrix:
    """COO (reference: sparse/coo.hpp ``COO``).  Padding rows carry
    ``row == n_rows`` and val 0 so they never contribute."""

    rows: jax.Array      # (nnz,) int32
    cols: jax.Array      # (nnz,) int32
    vals: jax.Array      # (nnz,)
    shape: Tuple[int, int]

    @property
    def nnz(self) -> int:
        return self.rows.shape[0]

    def tree_flatten(self):
        return (self.rows, self.cols, self.vals), (self.shape,)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, shape=aux[0])


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class CsrMatrix:
    """CSR (reference: sparse/csr.hpp; core/csr_matrix.hpp).  ``indptr`` has
    n_rows+1 entries; padding sits past ``indptr[-1]`` with col 0, val 0."""

    indptr: jax.Array    # (n_rows+1,) int32
    indices: jax.Array   # (nnz,) int32
    data: jax.Array      # (nnz,)
    shape: Tuple[int, int]

    @property
    def nnz(self) -> int:
        return self.indices.shape[0]

    def row_ids(self) -> jax.Array:
        """Expand indptr to one row id per nnz slot (padding -> n_rows)."""
        n_rows = self.shape[0]
        counts = jnp.diff(self.indptr)
        ids = jnp.repeat(jnp.arange(n_rows, dtype=jnp.int32), counts,
                         total_repeat_length=self.nnz)
        # jnp.repeat pads the tail with the LAST row id; mark real padding
        slot = jnp.arange(self.nnz)
        return jnp.where(slot < self.indptr[-1], ids, n_rows)

    def tree_flatten(self):
        return (self.indptr, self.indices, self.data), (self.shape,)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, shape=aux[0])


# ---------------------------------------------------------------------------
# conversions (reference: sparse/convert/{coo.hpp,csr.hpp,dense.hpp})
# ---------------------------------------------------------------------------

def coo_sort(coo: CooMatrix) -> CooMatrix:
    """Sort entries by (row, col) (reference: sparse/op/sort.hpp
    ``coo_sort``).  Padding (row == n_rows) sorts to the end.
    lexsort keeps keys in int32 (no row*n_cols encoding overflow)."""
    order = jnp.lexsort((coo.cols, coo.rows))
    return CooMatrix(coo.rows[order], coo.cols[order], coo.vals[order],
                     coo.shape)


def coo_to_csr(coo: CooMatrix) -> CsrMatrix:
    """Reference: sparse/convert/csr.hpp ``sorted_coo_to_csr``."""
    coo = coo_sort(coo)
    n_rows = coo.shape[0]
    counts = jax.ops.segment_sum(
        jnp.where(coo.rows < n_rows, 1, 0).astype(jnp.int32),
        jnp.minimum(coo.rows, n_rows - 1).astype(jnp.int32),
        num_segments=n_rows)
    indptr = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts).astype(jnp.int32)])
    return CsrMatrix(indptr, coo.cols, coo.vals, coo.shape)


def csr_to_coo(csr: CsrMatrix) -> CooMatrix:
    """Reference: sparse/convert/coo.hpp ``csr_to_coo``."""
    return CooMatrix(csr.row_ids(), csr.indices, csr.data, csr.shape)


def coo_to_dense(coo: CooMatrix) -> jax.Array:
    """Reference: sparse/convert/dense.hpp."""
    n_rows, n_cols = coo.shape
    out = jnp.zeros((n_rows + 1, n_cols), coo.vals.dtype)
    out = out.at[jnp.minimum(coo.rows, n_rows),
                 coo.cols].add(coo.vals)
    return out[:n_rows]


def csr_to_dense(csr: CsrMatrix) -> jax.Array:
    return coo_to_dense(csr_to_coo(csr))


def dense_to_coo(dense, nnz: Optional[int] = None) -> CooMatrix:
    """Reference: sparse/convert/coo.hpp.  ``nnz`` caps the static slot
    count (defaults to all entries — callers with known sparsity pass less);
    entries are selected largest-|value|-first when capped."""
    dense = ensure_array(dense, "dense")
    n_rows, n_cols = dense.shape
    total = n_rows * n_cols
    nnz = nnz or total
    flat = dense.ravel()
    nonzero = flat != 0
    if nnz >= total:
        rows = (jnp.arange(total) // n_cols).astype(jnp.int32)
        cols = (jnp.arange(total) % n_cols).astype(jnp.int32)
        rows = jnp.where(nonzero, rows, n_rows)
        return coo_sort(CooMatrix(rows, jnp.where(nonzero, cols, 0),
                                  jnp.where(nonzero, flat, 0),
                                  (n_rows, n_cols)))
    score = jnp.where(nonzero, jnp.abs(flat), -jnp.inf)
    _, sel = jax.lax.top_k(score, nnz)
    keep = nonzero[sel]
    rows = jnp.where(keep, (sel // n_cols).astype(jnp.int32), n_rows)
    cols = jnp.where(keep, (sel % n_cols).astype(jnp.int32), 0)
    vals = jnp.where(keep, flat[sel], 0)
    return coo_sort(CooMatrix(rows, cols, vals, (n_rows, n_cols)))


def dense_to_csr(dense, nnz: Optional[int] = None) -> CsrMatrix:
    return coo_to_csr(dense_to_coo(dense, nnz))
