"""Sparse element/row operations over COO/CSR.

Reference surface: ``cpp/include/raft/sparse/op/`` — ``filter.cuh``
(:46 ``coo_remove_scalar``, :85 ``coo_remove_zeros``), ``slice.cuh``
(:40 ``csr_row_slice_indptr``, :65 ``csr_row_slice_populate``),
``row_op.cuh`` (:39 ``csr_row_op``), ``reduce.cuh``
(:49 ``compute_duplicates_mask``, :72 ``max_duplicates``);
``sort.cuh`` lives in :mod:`raft_tpu.sparse.formats` (``coo_sort``).

TPU design: nnz is static under XLA, so "removal" keeps the storage size
and moves dropped entries to the padding convention (``row == n_rows``,
val 0) — they sort to the end and every downstream segment reduction
ignores them.  This is the same static-capacity trade the IVF list layout
makes; callers that need a tight buffer re-materialize on host.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.sparse.formats import CooMatrix, CsrMatrix, coo_sort


def _drop(coo: CooMatrix, keep: jax.Array) -> CooMatrix:
    """Move entries with ``keep == False`` to padding and re-sort so live
    entries are contiguous at the front."""
    n_rows = coo.shape[0]
    rows = jnp.where(keep, coo.rows, n_rows)
    cols = jnp.where(keep, coo.cols, 0)
    vals = jnp.where(keep, coo.vals, 0)
    return coo_sort(CooMatrix(rows, cols, vals, coo.shape))


def coo_remove_scalar(coo: CooMatrix, scalar) -> CooMatrix:
    """Remove entries equal to ``scalar``
    (reference: sparse/op/filter.cuh:46,72)."""
    live = coo.rows < coo.shape[0]
    return _drop(coo, live & (coo.vals != scalar))


def coo_remove_zeros(coo: CooMatrix) -> CooMatrix:
    """Reference: sparse/op/filter.cuh:85."""
    return coo_remove_scalar(coo, 0)


def csr_row_slice(csr: CsrMatrix, start_row: int, stop_row: int
                  ) -> CsrMatrix:
    """Rows ``[start_row, stop_row)`` as a new CSR
    (reference: sparse/op/slice.cuh:40 ``csr_row_slice_indptr`` + :65
    ``csr_row_slice_populate``, fused).  Keeps the parent's nnz capacity;
    out-of-slice entries become padding.
    """
    n_rows, n_cols = csr.shape
    expects(0 <= start_row <= stop_row <= n_rows,
            "csr_row_slice: bad row range")
    out_rows = stop_row - start_row
    rows = csr.row_ids()
    keep = (rows >= start_row) & (rows < stop_row)
    new_rows = jnp.where(keep, rows - start_row, out_rows)
    sliced = coo_sort(CooMatrix(new_rows,
                                jnp.where(keep, csr.indices, 0),
                                jnp.where(keep, csr.data, 0),
                                (out_rows, n_cols)))
    counts = jax.ops.segment_sum(
        jnp.where(sliced.rows < out_rows, 1, 0).astype(jnp.int32),
        jnp.minimum(sliced.rows, max(out_rows - 1, 0)).astype(jnp.int32),
        num_segments=max(out_rows, 1))[:out_rows]
    indptr = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts).astype(jnp.int32)])
    return CsrMatrix(indptr, sliced.cols, sliced.vals, (out_rows, n_cols))


def csr_row_op(csr: CsrMatrix, op: Callable) -> CsrMatrix:
    """Apply a row-indexed op over the values
    (reference: sparse/op/row_op.cuh:39 ``csr_row_op`` — the CUDA version
    hands each row's [start, stop) range to a device lambda; the TPU form
    hands the whole value vector plus its row ids to a vectorized callable).

    ``op(row_ids, nnz_index, data) -> new_data``; padding slots keep 0.
    """
    rows = csr.row_ids()
    new_data = op(rows, jnp.arange(csr.nnz), csr.data)
    new_data = jnp.where(rows < csr.shape[0], new_data, 0)
    return CsrMatrix(csr.indptr, csr.indices, new_data, csr.shape)


def compute_duplicates_mask(coo: CooMatrix) -> jax.Array:
    """1 for the first occurrence of each (row, col) in sorted order, 0 for
    its duplicates (reference: sparse/op/reduce.cuh:49).  Input must be
    sorted (``coo_sort``); padding slots get 0."""
    n_rows = coo.shape[0]
    first = jnp.ones(coo.nnz, jnp.int32)
    same = (coo.rows[1:] == coo.rows[:-1]) & (coo.cols[1:] == coo.cols[:-1])
    first = first.at[1:].set(jnp.where(same, 0, 1))
    return jnp.where(coo.rows < n_rows, first, 0)


def max_duplicates(coo: CooMatrix) -> CooMatrix:
    """Combine duplicate (row, col) entries keeping the max value
    (reference: sparse/op/reduce.cuh:72 ``max_duplicates``).  Output keeps
    the input's nnz capacity with combined entries compacted to the front.
    """
    coo = coo_sort(coo)
    n_rows = coo.shape[0]
    mask = compute_duplicates_mask(coo)
    # group id per entry = running count of firsts - 1
    gid = jnp.cumsum(mask) - 1
    live = coo.rows < n_rows
    gid = jnp.where(live, gid, coo.nnz - 1)
    # reduce in the values' own dtype (a float32 detour would corrupt
    # int64 / float64 values beyond 2^24)
    if jnp.issubdtype(coo.vals.dtype, jnp.floating):
        lowest = jnp.array(-jnp.inf, coo.vals.dtype)
    else:
        lowest = jnp.array(jnp.iinfo(coo.vals.dtype).min, coo.vals.dtype)
    maxv = jnp.full((coo.nnz,), lowest, coo.vals.dtype) \
        .at[gid].max(jnp.where(live, coo.vals, lowest))
    n_groups = jnp.sum(mask)
    slot = jnp.arange(coo.nnz)
    is_first = mask == 1
    # scatter the first-occurrence (row, col) into group slots
    g_rows = jnp.full((coo.nnz,), n_rows, coo.rows.dtype) \
        .at[jnp.where(is_first, gid, coo.nnz - 1)].set(
            jnp.where(is_first, coo.rows, n_rows), mode="drop")
    g_cols = jnp.zeros((coo.nnz,), coo.cols.dtype) \
        .at[jnp.where(is_first, gid, coo.nnz - 1)].set(
            jnp.where(is_first, coo.cols, 0), mode="drop")
    g_vals = jnp.where(slot < n_groups, maxv, 0).astype(coo.vals.dtype)
    g_rows = jnp.where(slot < n_groups, g_rows, n_rows)
    return CooMatrix(g_rows, g_cols, g_vals, coo.shape)
