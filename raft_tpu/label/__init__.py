"""Label utilities.

Reference: cpp/include/raft/label/ (~551 LoC, SURVEY.md §2.8) —
``classlabels.cuh`` (getUniquelabels / make_monotonic) and
``merge_labels.cuh`` (union of labelings via label propagation, used by
connected components).
"""

from raft_tpu.label.classlabels import (  # noqa: F401
    get_unique_labels,
    make_monotonic,
)
from raft_tpu.label.merge_labels import merge_labels  # noqa: F401
