"""Merge two labelings (union over a mask).

Reference: raft/label/merge_labels.cuh ``merge_labels`` — given labels_a and
labels_b plus a core-point mask, iteratively propagates the minimum label
across rows where both labelings connect them (the connected-components
union step in cuML's DBSCAN).  The reference loops a min-propagation kernel
to fixpoint; here it's a jitted ``lax.while_loop``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.core.mdarray import ensure_array


def merge_labels(labels_a, labels_b, mask) -> jax.Array:
    """Union-merge: rows sharing a label in EITHER labeling (restricted to
    ``mask``) end up with the same (minimum) label of their merged group.
    Shapes: all (n,); returns int32 (n,).
    """
    a = ensure_array(labels_a, "labels_a").astype(jnp.int32)
    b = ensure_array(labels_b, "labels_b").astype(jnp.int32)
    m = ensure_array(mask, "mask").astype(jnp.bool_)
    n = a.shape[0]

    def min_over_groups(vals, groups):
        """For each row: min of vals over rows sharing its group id."""
        gmin = jax.ops.segment_min(jnp.where(m, vals, jnp.int32(n)),
                                   groups, num_segments=n)
        return jnp.where(m, jnp.minimum(vals, gmin[groups]), vals)

    def cond(state):
        cur, prev = state
        return jnp.any(cur != prev)

    def body(state):
        cur, _ = state
        nxt = min_over_groups(cur, a)
        nxt = min_over_groups(nxt, b)
        return nxt, cur

    init = jnp.where(m, a, a)  # start from labels_a
    out, _ = jax.lax.while_loop(cond, body, (init, init - 1))
    return out
