"""Class-label utilities.

Reference: raft/label/classlabels.cuh — ``getUniquelabels`` (sorted distinct
labels) and ``make_monotonic`` (remap arbitrary labels to 0..k-1 in sorted
order).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.mdarray import ensure_array


def get_unique_labels(labels, *, max_labels: int = 0
                      ) -> Tuple[jax.Array, jax.Array]:
    """Sorted distinct labels (reference: getUniquelabels).

    XLA needs a static output size: returns ``(unique (m,), count)`` where
    ``m = max_labels or n``; slots past ``count`` repeat the largest label.
    """
    labels = ensure_array(labels, "labels")
    n = labels.shape[0]
    m = max_labels or n
    s = jnp.sort(labels)
    first = jnp.concatenate([jnp.ones(1, jnp.bool_), s[1:] != s[:-1]])
    count = jnp.sum(first.astype(jnp.int32))
    # compact the firsts to the front (stable, preserving sorted order)
    order = jnp.argsort(~first, stable=True)
    compact = s[order]
    if m > n:
        compact = jnp.pad(compact, (0, m - n), mode="edge")
    compact = compact[:m]
    # slots >= count hold leftover duplicates (ascending, NOT the largest
    # label) — overwrite them with the max label so the array stays sorted
    # and searchsorted in make_monotonic maps every label to its first slot
    uniq = jnp.where(jnp.arange(m) < count, compact, s[-1])
    return uniq, count


def make_monotonic(labels, *, max_labels: int = 0,
                   zero_based: bool = True) -> jax.Array:
    """Remap labels to dense 0..k-1 (1..k when not zero_based, matching the
    reference's default) in sorted-label order (reference: make_monotonic)."""
    labels = ensure_array(labels, "labels")
    uniq, _ = get_unique_labels(labels, max_labels=max_labels)
    # padding repeats the largest label; searchsorted-left still lands every
    # label on its first (correct) slot
    idx = jnp.searchsorted(uniq, labels, side="left").astype(jnp.int32)
    return idx if zero_based else idx + 1
