"""Element-wise / fusion primitives.

Reference: raft/linalg/{unary_op,binary_op,ternary_op,map,map_reduce,
matrix_vector_op,eltwise,add,subtract,multiply,divide,power,sqrt}.cuh.  XLA
fuses chains of these automatically on TPU, so each is a direct jnp expression;
the named wrappers keep call-site parity with the reference.
"""

from __future__ import annotations

import builtins
from typing import Callable

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


def unary_op(x: jax.Array, op: Callable[[jax.Array], jax.Array]) -> jax.Array:
    """Reference: linalg/unary_op.cuh."""
    return op(x)


def binary_op(x: jax.Array, y: jax.Array,
              op: Callable[[jax.Array, jax.Array], jax.Array]) -> jax.Array:
    """Reference: linalg/binary_op.cuh."""
    return op(x, y)


def ternary_op(x: jax.Array, y: jax.Array, z: jax.Array,
               op: Callable[[jax.Array, jax.Array, jax.Array], jax.Array]
               ) -> jax.Array:
    """Reference: linalg/ternary_op.cuh."""
    return op(x, y, z)


def map(op: Callable, *arrays: jax.Array) -> jax.Array:
    """N-ary elementwise map (reference: linalg/map.cuh ``map``)."""
    return op(*arrays)


def map_offset(op: Callable, shape, dtype=jnp.int32) -> jax.Array:
    """Map over flat element offsets (reference: linalg/map.cuh ``map_offset``)."""
    import numpy as _np
    n = int(_np.prod(shape))
    idx = jnp.arange(n, dtype=dtype)
    return op(idx).reshape(shape)


def map_reduce(op: Callable, reduce_op: Callable, neutral,
               *arrays: jax.Array) -> jax.Array:
    """Fused map-then-reduce (reference: linalg/map_reduce.cuh,
    map_then_reduce.cuh) — XLA fuses the map into the reduction."""
    mapped = op(*arrays)
    flat = mapped.reshape(-1)
    return jax.lax.reduce(flat, jnp.asarray(neutral, flat.dtype), reduce_op, (0,))


def add(x, y):
    """Reference: linalg/add.cuh."""
    return jnp.add(x, y)


def subtract(x, y):
    """Reference: linalg/subtract.cuh."""
    return jnp.subtract(x, y)


def multiply(x, y):
    """Reference: linalg/multiply.cuh."""
    return jnp.multiply(x, y)


def divide(x, y):
    """Reference: linalg/divide.cuh."""
    return jnp.divide(x, y)


def eltwise_power(x, y):
    """Reference: linalg/power.cuh."""
    return jnp.power(x, y)


def eltwise_sqrt(x):
    """Reference: linalg/sqrt.cuh."""
    return jnp.sqrt(x)


def scalar_add(x, scalar):
    return x + scalar


def scalar_multiply(x, scalar):
    return x * scalar


def matrix_vector_op(matrix: jax.Array, vec: jax.Array,
                     op: Callable[[jax.Array, jax.Array], jax.Array],
                     *, along_rows: bool = True) -> jax.Array:
    """Broadcast a vector against every row (or column) of a matrix.

    Reference: linalg/matrix_vector_op.cuh.  ``along_rows=True`` means the
    vector spans the row (length = n_cols), applied to each row — the
    reference's ``bcastAlongRows``.
    """
    expects(matrix.ndim == 2 and vec.ndim == 1, "matrix_vector_op: (2d, 1d) required")
    if along_rows:
        expects(vec.shape[0] == matrix.shape[1], "vec length must equal n_cols")
        return op(matrix, vec[None, :])
    expects(vec.shape[0] == matrix.shape[0], "vec length must equal n_rows")
    return op(matrix, vec[:, None])
