"""Dense decompositions / solvers.

Reference: raft/linalg/{eig,svd,rsvd,qr,lstsq,cholesky_r1_update}.cuh, which
wrap cuSOLVER (detail/eig.cuh:40-57 cusolverDnsyevd, detail/svd.cuh, ...).  On
TPU the equivalents are ``jnp.linalg`` / ``jax.scipy.linalg``, which lower to
XLA's decomposition ops; randomized SVD is built from gemm+QR, which is the
TPU-friendly formulation (all MXU work).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


def eig_dc(res, A: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Eigendecomposition of a symmetric matrix; ascending eigenvalues.

    Reference: linalg/eig.cuh ``eig_dc`` (divide & conquer cusolverDnsyevd,
    detail/eig.cuh:40-57).  Returns (eigenvalues, eigenvectors[:, i]).
    """
    expects(A.ndim == 2 and A.shape[0] == A.shape[1], "eig_dc: square matrix required")
    w, v = jnp.linalg.eigh(A)
    return w, v


def eig_jacobi(res, A: jax.Array, tol: float = 1e-7,
               sweeps: int = 15) -> Tuple[jax.Array, jax.Array]:
    """Jacobi eigensolver surface (reference: linalg/eig.cuh ``eig_jacobi``).

    XLA's eigh is already Jacobi-free and accurate; tol/sweeps accepted for API
    parity and ignored.
    """
    return eig_dc(res, A)


def svd(res, A: jax.Array, *, full_matrices: bool = False
        ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """SVD: returns (U, S, V) with A = U @ diag(S) @ V.T.

    Reference: linalg/svd.cuh ``svd_qr`` — note the reference returns V (not
    V^T); we match that convention.
    """
    u, s, vh = jnp.linalg.svd(A, full_matrices=full_matrices)
    return u, s, vh.T


svd_qr = svd


def rsvd(res, A: jax.Array, k: int, *, p: int = 10, n_iter: int = 4,
         key: Optional[jax.Array] = None
         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Randomized SVD of rank k (reference: linalg/rsvd.cuh).

    Halko-Martinsson-Tropp sketch: range-find with (p) oversampling and
    ``n_iter`` power iterations (QR-stabilised), then exact SVD on the small
    projected matrix.  All heavy work is gemm+QR: ideal for the MXU.
    """
    m, n = A.shape
    l = min(k + p, min(m, n))
    if key is None:
        key = res.next_key() if res is not None else jax.random.key(0)
    from raft_tpu.utils.precision import get_matmul_precision
    prec = get_matmul_precision()
    mm = lambda a, b: jnp.matmul(a, b, precision=prec)
    omega = jax.random.normal(key, (n, l), dtype=A.dtype)
    Y = mm(A, omega)
    Q, _ = jnp.linalg.qr(Y)
    for _ in range(n_iter):
        Q, _ = jnp.linalg.qr(mm(A.T, Q))
        Q, _ = jnp.linalg.qr(mm(A, Q))
    B = mm(Q.T, A)
    ub, s, vh = jnp.linalg.svd(B, full_matrices=False)
    u = mm(Q, ub)
    return u[:, :k], s[:k], vh[:k].T


def qr_get_q(res, A: jax.Array) -> jax.Array:
    """Reference: linalg/qr.cuh ``qr_get_q``."""
    q, _ = jnp.linalg.qr(A)
    return q


def qr_get_qr(res, A: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Reference: linalg/qr.cuh ``qr_get_qr``."""
    return jnp.linalg.qr(A)


def lstsq(res, A: jax.Array, b: jax.Array) -> jax.Array:
    """Least-squares solve via SVD (reference: linalg/lstsq.cuh lstsqSvdQR)."""
    x, _, _, _ = jnp.linalg.lstsq(A, b)
    return x


def cholesky(res, A: jax.Array, lower: bool = True) -> jax.Array:
    """Cholesky factor (reference: detail/cholesky path of potrf wrappers)."""
    L = jnp.linalg.cholesky(A)
    return L if lower else L.T


def cholesky_rank_one_update(res, L: jax.Array, v: jax.Array,
                             lower: bool = True) -> jax.Array:
    """Rank-1 update of a Cholesky factor: chol(A + v v^T) given L = chol(A).

    Reference: linalg/cholesky_r1_update.cuh.  Implemented as a fixed-length
    scan of Givens-style rotations — jit-friendly (no data-dependent shapes).
    """
    expects(L.ndim == 2 and L.shape[0] == L.shape[1], "square factor required")
    Lw = L if lower else L.T
    n = Lw.shape[0]

    def body(carry, k):
        Lc, w = carry
        lkk = Lc[k, k]
        wk = w[k]
        r = jnp.sqrt(lkk * lkk + wk * wk)
        c = r / lkk
        s = wk / lkk
        col = Lc[:, k]
        mask = (jnp.arange(n) > k).astype(L.dtype)
        new_col = jnp.where(jnp.arange(n) >= k, (col + s * w) / c, col)
        new_w = c * w - s * new_col
        w = jnp.where(mask.astype(bool), new_w, w)
        Lc = Lc.at[:, k].set(new_col)
        return (Lc, w), None

    (Lw, _), _ = jax.lax.scan(body, (Lw, v.astype(L.dtype)), jnp.arange(n))
    return Lw if lower else Lw.T
