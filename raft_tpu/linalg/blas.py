"""BLAS-level primitives.

Reference: raft/linalg/gemm.cuh (detail/gemm.hpp:71-238 → cublasgemm),
gemv.cuh, axpy.cuh, dot.cuh.  On TPU these are ``lax.dot_general`` — XLA tiles
them onto the MXU; ``preferred_element_type`` keeps fp32 accumulation for
bf16/int8 inputs (the tensor-core-accumulator analogue).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


def gemm(x: jax.Array, y: jax.Array, *,
         alpha: float = 1.0, beta: float = 0.0,
         z: Optional[jax.Array] = None,
         trans_x: bool = False, trans_y: bool = False,
         precision=None) -> jax.Array:
    """out = alpha * op(x) @ op(y) + beta * z (reference: linalg/gemm.cuh)."""
    a = x.T if trans_x else x
    b = y.T if trans_y else y
    expects(a.ndim == 2 and b.ndim == 2, "gemm: rank-2 inputs required")
    expects(a.shape[1] == b.shape[0],
            f"gemm: inner dims mismatch {a.shape} @ {b.shape}")
    in_t = jnp.promote_types(x.dtype, y.dtype)
    # integer gemm returns the wide accumulator (cublas int8->int32 contract);
    # float gemm accumulates in >=fp32 and returns the promoted float type
    acc_t = jnp.promote_types(in_t, jnp.int32) if jnp.issubdtype(in_t, jnp.integer) \
        else jnp.promote_types(in_t, jnp.float32)
    if precision is None:
        from raft_tpu.utils.precision import get_matmul_precision
        precision = get_matmul_precision()
    out = jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())),
        precision=precision,
        preferred_element_type=acc_t,
    )
    if not jnp.issubdtype(in_t, jnp.integer):
        out = out.astype(in_t if jnp.issubdtype(in_t, jnp.floating) else acc_t)
    if alpha != 1.0:
        out = alpha * out
    if beta != 0.0:
        expects(z is not None, "gemm: beta != 0 requires z")
        out = out + beta * z
    return out


def gemv(A: jax.Array, x: jax.Array, *,
         alpha: float = 1.0, beta: float = 0.0,
         y: Optional[jax.Array] = None,
         trans: bool = False) -> jax.Array:
    """out = alpha * op(A) @ x + beta * y (reference: linalg/gemv.cuh)."""
    a = A.T if trans else A
    expects(a.ndim == 2 and x.ndim == 1, "gemv: A rank-2, x rank-1")
    expects(a.shape[1] == x.shape[0], "gemv: dims mismatch")
    from raft_tpu.utils.precision import get_matmul_precision
    out = jnp.matmul(a, x, precision=get_matmul_precision())
    if alpha != 1.0:
        out = alpha * out
    if beta != 0.0:
        expects(y is not None, "gemv: beta != 0 requires y")
        out = out + beta * y
    return out


def axpy(alpha: float, x: jax.Array, y: jax.Array) -> jax.Array:
    """alpha * x + y (reference: linalg/axpy.cuh)."""
    return alpha * x + y


def dot(x: jax.Array, y: jax.Array) -> jax.Array:
    """Inner product of flat vectors (reference: linalg/dot.cuh)."""
    expects(x.shape == y.shape, "dot: shape mismatch")
    return jnp.vdot(x, y)


def transpose(x: jax.Array) -> jax.Array:
    """Reference: linalg/transpose.cuh."""
    return x.T
