"""Dense linear algebra primitives.

Reference: cpp/include/raft/linalg/ (71 files, SURVEY.md §2.3) — cuBLAS/cuSOLVER
wrappers plus element-wise / reduction fusion primitives.  On TPU every one of
these lowers to XLA ops that the compiler fuses and maps onto the MXU/VPU, so
the value kept here is the API names and semantics (axis conventions, norm
types, key-grouped reductions) so reference call sites translate 1:1.
"""

from raft_tpu.linalg.blas import gemm, gemv, axpy, dot, transpose  # noqa: F401
from raft_tpu.linalg.solvers import (  # noqa: F401
    eig_dc,
    eig_jacobi,
    svd,
    svd_qr,
    rsvd,
    qr_get_q,
    qr_get_qr,
    lstsq,
    cholesky,
    cholesky_rank_one_update,
)
from raft_tpu.linalg.eltwise import (  # noqa: F401
    unary_op,
    binary_op,
    ternary_op,
    map,
    map_offset,
    map_reduce,
    add,
    subtract,
    multiply,
    divide,
    eltwise_power,
    eltwise_sqrt,
    scalar_add,
    scalar_multiply,
    matrix_vector_op,
)
from raft_tpu.linalg.reduce import (  # noqa: F401
    NormType,
    Apply,
    reduce,
    coalesced_reduction,
    strided_reduction,
    norm,
    row_norm,
    col_norm,
    normalize,
    reduce_rows_by_key,
    reduce_cols_by_key,
    mean_squared_error,
)

# Deprecated forward kept for reference parity: raft/linalg/lanczos.cuh:22-35
# forwards to sparse/solver/lanczos.cuh; the canonical home is
# raft_tpu.sparse.solver.
from raft_tpu.sparse.solver import (  # noqa: F401,E402
    eigsh_largest,
    eigsh_smallest,
    lanczos_tridiag,
)
