"""Reductions.

Reference: raft/linalg/{reduce,coalesced_reduction,strided_reduction,norm,
normalize,reduce_rows_by_key,reduce_cols_by_key,mean_squared_error}.cuh.

The reference distinguishes coalesced vs strided reductions for memory-access
reasons; on TPU XLA picks the schedule, so both reduce to axis reductions with
the reference's (main_op, reduce_op, final_op) functor composition.  Key-grouped
reductions use ``jax.ops.segment_sum`` (sorted/unsorted both fine; num_segments
is static as XLA requires).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


class NormType:
    """Reference: linalg/norm.cuh ``NormType``."""

    L1Norm = "l1"
    L2Norm = "l2"
    LinfNorm = "linf"


class Apply:
    """Reduction direction (reference: linalg/norm.cuh ``Apply``)."""

    ALONG_ROWS = "along_rows"      # one result per row
    ALONG_COLUMNS = "along_columns"  # one result per column


def _identity(x):
    return x


def reduce(data: jax.Array, *, along_rows: bool = True,
           main_op: Callable = _identity,
           reduce_op: str = "add",
           final_op: Callable = _identity,
           init=0) -> jax.Array:
    """General row/col reduction with pre/post ops (reference: linalg/reduce.cuh).

    ``reduce_op`` is one of add/min/max — the reference passes functors; on TPU
    named reductions let XLA use its native combiners.
    """
    expects(data.ndim == 2, "reduce: rank-2 input")
    axis = 1 if along_rows else 0
    mapped = main_op(data)
    init_v = jnp.asarray(init, mapped.dtype)
    if reduce_op == "add":
        out = jnp.sum(mapped, axis=axis) + init_v
    elif reduce_op == "min":
        # init always participates (reference: raft::linalg::reduce init semantics)
        out = jnp.minimum(jnp.min(mapped, axis=axis), init_v)
    elif reduce_op == "max":
        out = jnp.maximum(jnp.max(mapped, axis=axis), init_v)
    else:
        raise ValueError(f"unknown reduce_op {reduce_op!r}")
    return final_op(out)


def coalesced_reduction(data: jax.Array, **kw) -> jax.Array:
    """Reduce along the contiguous (last) dim (reference: coalesced_reduction.cuh)."""
    return reduce(data, along_rows=True, **kw)


def strided_reduction(data: jax.Array, **kw) -> jax.Array:
    """Reduce along the strided (first) dim (reference: strided_reduction.cuh)."""
    return reduce(data, along_rows=False, **kw)


def norm(data: jax.Array, norm_type: str = NormType.L2Norm, *,
         along_rows: bool = True, sqrt: bool = False) -> jax.Array:
    """Row/col norms (reference: linalg/norm.cuh ``rowNorm``/``colNorm``).

    NB: the reference's L2 norm is the *squared* L2 sum unless ``sqrt`` — we
    keep that contract (it feeds the expanded-distance identities).  The sqrt
    final-op applies to every norm type, as in detail/norm.cuh:38-77.
    """
    axis = 1 if along_rows else 0
    if norm_type == NormType.L1Norm:
        out = jnp.sum(jnp.abs(data), axis=axis)
    elif norm_type == NormType.L2Norm:
        out = jnp.sum(data * data, axis=axis)
    elif norm_type == NormType.LinfNorm:
        out = jnp.max(jnp.abs(data), axis=axis)
    else:
        raise ValueError(f"unknown norm type {norm_type!r}")
    if sqrt:
        out = jnp.sqrt(out)
    return out


def row_norm(data: jax.Array, norm_type: str = NormType.L2Norm,
             sqrt: bool = False) -> jax.Array:
    return norm(data, norm_type, along_rows=True, sqrt=sqrt)


def col_norm(data: jax.Array, norm_type: str = NormType.L2Norm,
             sqrt: bool = False) -> jax.Array:
    return norm(data, norm_type, along_rows=False, sqrt=sqrt)


def normalize(data: jax.Array, norm_type: str = NormType.L2Norm,
              eps: float = 1e-12) -> jax.Array:
    """Row-normalize (reference: linalg/normalize.cuh ``row_normalize``)."""
    if norm_type == NormType.L2Norm:
        n = jnp.sqrt(jnp.sum(data * data, axis=1, keepdims=True))
    elif norm_type == NormType.L1Norm:
        n = jnp.sum(jnp.abs(data), axis=1, keepdims=True)
    else:
        n = jnp.max(jnp.abs(data), axis=1, keepdims=True)
    return data / jnp.maximum(n, eps)


def reduce_rows_by_key(data: jax.Array, keys: jax.Array, n_keys: int,
                       weights: Optional[jax.Array] = None) -> jax.Array:
    """Sum rows sharing a key: out[k, :] = sum_{i: keys[i]==k} w[i] * data[i, :].

    Reference: linalg/reduce_rows_by_key.cuh — the k-means centroid-update
    primitive.  ``jax.ops.segment_sum`` lowers to an XLA scatter-add; n_keys is
    static (XLA shape requirement, matching the reference's n_uniquekeys arg).
    """
    expects(data.ndim == 2 and keys.ndim == 1, "reduce_rows_by_key: (2d, 1d)")
    expects(keys.shape[0] == data.shape[0], "one key per row required")
    if weights is not None:
        data = data * weights[:, None].astype(data.dtype)
    return jax.ops.segment_sum(data, keys, num_segments=n_keys)


def reduce_cols_by_key(data: jax.Array, keys: jax.Array,
                       n_keys: int) -> jax.Array:
    """Sum columns sharing a key (reference: linalg/reduce_cols_by_key.cuh)."""
    expects(data.ndim == 2 and keys.ndim == 1, "reduce_cols_by_key: (2d, 1d)")
    expects(keys.shape[0] == data.shape[1], "one key per column required")
    return jax.ops.segment_sum(data.T, keys, num_segments=n_keys).T


def mean_squared_error(a: jax.Array, b: jax.Array,
                       weight: float = 1.0) -> jax.Array:
    """Reference: linalg/mean_squared_error.cuh."""
    d = a - b
    return weight * jnp.mean(d * d)
