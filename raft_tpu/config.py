"""Global output-type configuration.

Parity with ``pylibraft.config`` (`/root/reference/python/pylibraft/pylibraft/
config.py:15-46` — ``SUPPORTED_OUTPUT_TYPES``, ``output_as_``,
``set_output_as``).  The reference returns ``device_ndarray`` ("raft") by
default and can convert to cupy/torch; raft_tpu returns ``jax.Array`` by
default and can convert to numpy / torch (via dlpack or host copy) or any
user callable.
"""

from __future__ import annotations

from typing import Callable, Union

SUPPORTED_OUTPUT_TYPES = ["jax", "numpy", "torch"]

output_as_: Union[str, Callable] = "jax"


def set_output_as(output: Union[str, Callable]) -> None:
    """Set the output format for raft_tpu functions.

    By default raft_tpu returns ``jax.Array`` from public functions.
    ``set_output_as`` switches the returned arrays to numpy arrays, torch
    tensors, or the result of an arbitrary callable applied to the
    ``jax.Array`` (mirroring ``pylibraft.config.set_output_as``,
    reference config.py:20-46).

    Parameters
    ----------
    output : {"jax", "numpy", "torch"} or callable
    """
    if output not in SUPPORTED_OUTPUT_TYPES and not callable(output):
        raise ValueError(f"Unsupported output option {output!r}")
    global output_as_
    output_as_ = output


def get_output_as() -> Union[str, Callable]:
    return output_as_


# ---------------------------------------------------------------------------
# input-validation policy (raft_tpu.integrity boundary layer)
# ---------------------------------------------------------------------------

# "raise": non-finite input rows raise integrity.ValidationError at the
#          public entry point (one fused isfinite pass + host sync).
# "mask":  non-finite query rows are replaced in-graph and flagged in the
#          outputs (ids -1 / worst distance) instead of poisoning the
#          batch; no host sync.
# "off":   no validation work at all — the jitted path is byte-identical
#          to an unvalidated call (the serving hot-path setting once
#          inputs are trusted).
SUPPORTED_VALIDATION_POLICIES = ("raise", "mask", "off")

validation_policy_: str = "raise"


def set_validation_policy(policy: str) -> None:
    """Set the boundary-validation policy for public entry points."""
    if policy not in SUPPORTED_VALIDATION_POLICIES:
        raise ValueError(
            f"Unsupported validation policy {policy!r}; expected one of "
            f"{SUPPORTED_VALIDATION_POLICIES}")
    global validation_policy_
    validation_policy_ = policy


def get_validation_policy() -> str:
    return validation_policy_


class validation_policy:
    """Context manager scoping the validation policy::

        with config.validation_policy("off"):
            ivf_pq.search(...)   # trusted hot path, zero validation work
    """

    def __init__(self, policy: str):
        if policy not in SUPPORTED_VALIDATION_POLICIES:
            raise ValueError(
                f"Unsupported validation policy {policy!r}; expected one of "
                f"{SUPPORTED_VALIDATION_POLICIES}")
        self._policy = policy
        self._saved: str = validation_policy_

    def __enter__(self) -> "validation_policy":
        global validation_policy_
        self._saved = validation_policy_
        validation_policy_ = self._policy
        return self

    def __exit__(self, *exc) -> None:
        global validation_policy_
        validation_policy_ = self._saved
