"""Global output-type configuration.

Parity with ``pylibraft.config`` (`/root/reference/python/pylibraft/pylibraft/
config.py:15-46` — ``SUPPORTED_OUTPUT_TYPES``, ``output_as_``,
``set_output_as``).  The reference returns ``device_ndarray`` ("raft") by
default and can convert to cupy/torch; raft_tpu returns ``jax.Array`` by
default and can convert to numpy / torch (via dlpack or host copy) or any
user callable.
"""

from __future__ import annotations

from typing import Callable, Union

SUPPORTED_OUTPUT_TYPES = ["jax", "numpy", "torch"]

output_as_: Union[str, Callable] = "jax"


def set_output_as(output: Union[str, Callable]) -> None:
    """Set the output format for raft_tpu functions.

    By default raft_tpu returns ``jax.Array`` from public functions.
    ``set_output_as`` switches the returned arrays to numpy arrays, torch
    tensors, or the result of an arbitrary callable applied to the
    ``jax.Array`` (mirroring ``pylibraft.config.set_output_as``,
    reference config.py:20-46).

    Parameters
    ----------
    output : {"jax", "numpy", "torch"} or callable
    """
    if output not in SUPPORTED_OUTPUT_TYPES and not callable(output):
        raise ValueError(f"Unsupported output option {output!r}")
    global output_as_
    output_as_ = output


def get_output_as() -> Union[str, Callable]:
    return output_as_
