"""Legacy spatial API — thin forwards to :mod:`raft_tpu.neighbors`.

Parity with the reference's ``raft::spatial::knn`` namespace
(`/root/reference/cpp/include/raft/spatial/knn/` — knn.cuh:20-24 includes
``neighbors/detail`` and forwards; ann.cuh, ball_cover.cuh,
epsilon_neighborhood.cuh, ivf_flat.cuh, ivf_pq.cuh are all forwarding
headers for the pre-``raft::neighbors`` spelling).  Kept so code written
against the old namespace ports mechanically.
"""

from raft_tpu.spatial import knn  # noqa: F401

__all__ = ["knn"]
