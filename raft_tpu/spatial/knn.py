"""``raft_tpu.spatial.knn`` — the legacy namespace's entry points.

Reference: cpp/include/raft/spatial/knn/knn.cuh (``brute_force_knn``,
``knn_merge_parts``, ``select_k``), ball_cover.cuh, epsilon_neighborhood.cuh,
ivf_flat.cuh / ivf_pq.cuh — all deprecated forwards to ``raft::neighbors`` /
``raft::matrix``; this module is the same shim for raft_tpu.
"""

from raft_tpu.matrix.select_k import select_k  # noqa: F401
from raft_tpu.neighbors import ball_cover, ivf_flat, ivf_pq  # noqa: F401
from raft_tpu.neighbors.brute_force import (  # noqa: F401
    knn as brute_force_knn,
    knn_merge_parts,
)
from raft_tpu.neighbors.epsilon_neighborhood import (  # noqa: F401
    eps_neighbors_l2sq,
)

__all__ = [
    "select_k",
    "ball_cover",
    "ivf_flat",
    "ivf_pq",
    "brute_force_knn",
    "knn_merge_parts",
    "eps_neighbors_l2sq",
]
