"""Durable streaming ingest: WAL-backed writes into the delta tier.

The foreground write path the read side has lacked: ``Server.write()``
appends upsert/delete records to a write-ahead log and acknowledges
only after the record is fsync-durable, applies them to the
always-mutable :class:`~raft_tpu.neighbors.delta.Memtable` (searched
alongside the main index — see ``Executor.attach_delta``), and
periodically **folds** the memtable into the main index as a
checkpointed, gated compaction that truncates the WAL only after the
swapped-in generation lands.  An acknowledged write survives a process
kill at any instruction boundary — the crash-safety contract the
rebalancer (PR 7) established for background maintenance, extended to
every foreground write.

WAL format (documented contract, docs/api.md "Streaming ingest &
durability")::

    <wal_dir>/wal.log        # append-only stream of framed records
    <wal_dir>/fold/          # CheckpointManager dir for the fold stage

Each record rides the RTIE envelope conventions from
:mod:`raft_tpu.core.serialize` — magic ``RTIE`` | u16 version |
u64 payload length | u32 CRC32(payload) — wrapping a payload of::

    u64 lsn | u8 op (1=upsert, 2=delete) | u32 n_rows | u32 dim |
    n_rows * i64 ids | n_rows * dim * f32 vectors   (upserts only)

Appends are single ``write()`` syscalls on an unbuffered fd (atomic
append), fsync is **group-committed**: concurrent writers share one
fsync covering every record appended so far, so the fsync cost
amortizes across the write burst while every ack stays strictly
durable.  Rows become *searchable* when applied to the memtable —
before the fsync — so visibility latency is decoupled from durability
latency; the ack still waits for the fsync.

Replay (:meth:`IngestServer.recover`) scans the log front to back:

- a record whose declared extent runs past EOF, a short/zero-filled
  header, or a CRC mismatch **on the final record** is a torn tail —
  physically truncated (fsync'd) and replay continues from the intact
  prefix;
- a CRC mismatch (or frame garbage) with intact records beyond it is
  real corruption — :class:`~raft_tpu.core.serialize.CorruptIndexError`
  naming the byte offset, never a silent skip;
- replayed records re-enter :meth:`Memtable.apply`, the same code the
  live path runs, with lsn-idempotence — recovered state is
  bit-identical to any other replay of the same bytes.

Fold lifecycle (crash-safe, in order): snapshot payload at fold LSN F →
``delete`` + ``extend`` on the main index under ONE generation bump
(the upsert pattern) → integrity verify + recall canary gate → durable
``commit`` checkpoint (candidate + F) → publish via
``Server.swap_index`` → WAL truncation → memtable reset → checkpoint
clear.  A kill before the commit marker rolls back (base index + full
WAL replay); after it, :meth:`recover` rolls forward (the committed
candidate is the main index, the WAL truncation completes).  Writes
are blocked for the duration of a fold — bounded by the memtable size,
which backpressure bounds in turn.

Admission control (BEFORE any WAL byte): bounded WAL lag
(``max_wal_bytes``) and memtable rows (``max_memtable_rows``) shed
with typed :class:`~raft_tpu.serving.admission.Overloaded`; per-tenant
write token buckets (rows/s) shed with :class:`QuotaExceeded`; a
brownout rung with ``shed_best_effort_writes=True`` sheds best-effort
tenants' writes with :class:`BrownedOut` while active.

Fault sites (:mod:`raft_tpu.resilience.faults`, incl. ``delay_at``):
``ingest.append`` / ``ingest.fsync`` / ``ingest.apply`` /
``ingest.fold`` / ``ingest.truncate`` — the kill-matrix tests inject a
failure at every one and assert recovery.  Counters:
``serving.ingest.{appended,acked,replayed,folds,truncations}`` plus the
``serving.ingest.shed.*`` family; ``serving.ingest.visibility`` is the
append→searchable latency histogram; fold / replay / backpressure
transitions land flight-recorder events.
"""

from __future__ import annotations

import dataclasses
import io
import os
import struct
import threading
import time
import zlib
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu import observability as obs
from raft_tpu.core import serialize as ser
from raft_tpu.core.error import expects
from raft_tpu.core.serialize import CorruptIndexError
from raft_tpu.distance.types import DistanceType
from raft_tpu.integrity import canary as _canary
from raft_tpu.integrity.verify import verify as _verify_index
from raft_tpu.neighbors import delta as _delta
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.neighbors import mutate as _mutate
from raft_tpu.observability import flight as _flight
from raft_tpu.observability import trace as _trace
from raft_tpu.resilience import faults
from raft_tpu.resilience.checkpoint import CheckpointManager, atomic_write
from raft_tpu.serving.admission import (
    BrownedOut,
    Overloaded,
    QuotaExceeded,
    TokenBucket,
)

_WAL_FILE = "wal.log"
_FOLD_DIR = "fold"
_FOLD_STAGE = "commit"
# payload head: u64 lsn | u8 op | u32 n_rows | u32 dim
_REC_HEAD = struct.Struct("<QBII")
_OPS = {"upsert": _delta.OP_UPSERT, "delete": _delta.OP_DELETE}


def _count(name: str) -> None:
    if obs.enabled():
        obs.registry().counter(name).inc()


def _gauge(name: str, value: float) -> None:
    if obs.enabled():
        obs.registry().gauge(name).set(value)


# ---------------------------------------------------------------------------
# record framing
# ---------------------------------------------------------------------------

def encode_record(lsn: int, op: int, ids: np.ndarray,
                  vectors: Optional[np.ndarray]) -> bytes:
    """One framed WAL record: RTIE envelope around the payload above."""
    ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
    dim = 0
    body = [_REC_HEAD.pack(lsn, op, ids.size, 0), ids.tobytes()]
    if op == _delta.OP_UPSERT:
        vecs = np.ascontiguousarray(vectors, np.float32)
        dim = int(vecs.shape[1])
        body[0] = _REC_HEAD.pack(lsn, op, ids.size, dim)
        body.append(vecs.tobytes())
    payload = b"".join(body)
    out = io.BytesIO()
    ser.write_envelope(out, payload)
    return out.getvalue()


def _decode_payload(payload: bytes, offset: int) -> _delta.Record:
    """Payload bytes -> Record; malformed structure under a VALID CRC is
    real corruption and raises naming the record's byte offset."""
    if len(payload) < _REC_HEAD.size:
        raise CorruptIndexError(
            f"corrupt WAL record at byte offset {offset}: payload shorter "
            f"than the record head ({len(payload)} bytes)")
    lsn, op, n, dim = _REC_HEAD.unpack_from(payload, 0)
    if op not in (_delta.OP_UPSERT, _delta.OP_DELETE):
        raise CorruptIndexError(
            f"corrupt WAL record at byte offset {offset}: unknown op {op}")
    want = _REC_HEAD.size + 8 * n + (4 * n * dim if op == _delta.OP_UPSERT
                                     else 0)
    if len(payload) != want or (op == _delta.OP_DELETE and dim != 0):
        raise CorruptIndexError(
            f"corrupt WAL record at byte offset {offset}: payload length "
            f"{len(payload)} does not match op={op} n={n} dim={dim}")
    ids = np.frombuffer(payload, np.int64, n, _REC_HEAD.size)
    vectors = None
    if op == _delta.OP_UPSERT:
        vectors = np.frombuffer(payload, np.float32, n * dim,
                                _REC_HEAD.size + 8 * n).reshape(n, dim)
    return _delta.Record(lsn=int(lsn), op=int(op), ids=ids, vectors=vectors)


def scan_wal(data: bytes) -> Tuple[list, int]:
    """Scan a WAL byte stream; returns ``(records, good_end)`` where
    ``good_end`` is the offset of the first torn byte (== len(data) for
    a clean log).  Mid-log corruption — a bad frame or CRC mismatch
    with intact bytes beyond the record's declared extent — raises
    :class:`CorruptIndexError` with the record's byte offset; only
    damage that reaches EOF is a (repairable) torn tail."""
    records = []
    off, n = 0, len(data)
    head = ser._ENVELOPE_HEADER
    while off < n:
        if n - off < head.size:
            return records, off                      # torn header at EOF
        magic, version, length, crc = head.unpack_from(data, off)
        if magic != ser._ENVELOPE_MAGIC or version != ser._ENVELOPE_VERSION:
            if data.find(ser._ENVELOPE_MAGIC, off) == -1:
                return records, off                  # garbage tail only
            raise CorruptIndexError(
                f"corrupt WAL: bad record frame at byte offset {off} "
                f"(magic {magic!r}, version {version})")
        end = off + head.size + length
        if end > n:
            return records, off                      # record runs past EOF
        payload = data[off + head.size:end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            if end == n:
                return records, off                  # torn final record
            raise CorruptIndexError(
                f"corrupt WAL: CRC mismatch in record at byte offset {off}")
        records.append(_decode_payload(payload, off))
        off = end
    return records, off


class WriteAheadLog:
    """Append-only framed record log with group-commit durability.

    Appends are single unbuffered ``write()`` calls (atomic append, no
    Python-level buffer to race a concurrent fsync); :meth:`sync` is
    one fsync covering everything appended so far.  Callers serialize
    appends (the ingest lock) — this class only owns the fd."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = open(path, "ab", buffering=0)
        self._size = self._f.seek(0, os.SEEK_END)

    @property
    def size_bytes(self) -> int:
        return self._size

    def append(self, record: bytes) -> None:
        faults.maybe_fail("ingest.append")
        self._f.write(record)
        self._size += len(record)

    def sync(self) -> None:
        faults.maybe_fail("ingest.fsync")
        os.fsync(self._f.fileno())

    def truncate_all(self) -> None:
        """Atomically reset the log to empty (post-fold: every record is
        folded into the committed candidate)."""
        faults.maybe_fail("ingest.truncate")
        self._f.close()
        atomic_write(self.path, b"")
        self._f = open(self.path, "ab", buffering=0)
        self._size = 0
        _count("serving.ingest.truncations")

    def repair_tail(self, good_end: int) -> int:
        """Truncate a torn tail at ``good_end``; returns dropped bytes.
        The truncation is fsync'd through the same ``ingest.fsync``
        fault site as the append path — an injected fsync failure
        during replay propagates cleanly and the next recover retries."""
        dropped = self._size - good_end
        if dropped <= 0:
            return 0
        self._f.truncate(good_end)
        self.sync()
        self._size = good_end
        return dropped

    def read_bytes(self) -> bytes:
        with open(self.path, "rb") as f:
            return f.read()

    def close(self) -> None:
        self._f.close()


# ---------------------------------------------------------------------------
# the ingest server
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class IngestConfig:
    """Write-path knobs (docs/api.md "Streaming ingest & durability").

    ``memtable_capacity`` is the initial shape-static scan capacity
    (regrow doubles it under a generation bump); ``max_memtable_rows``
    and ``max_wal_bytes`` are the backpressure bounds — beyond either,
    writes shed with :class:`Overloaded` until a fold drains the tier.
    ``write_quotas`` maps tenant -> (rate_rows_per_s, burst_rows).
    ``fold_rows`` / ``fold_tombstones`` are the ``maybe_fold``
    size thresholds; ``fold_replay_debt_rows`` / ``fold_visibility_lag_s``
    are the round-19 WAL-lag / visibility-target triggers (0 disables
    any trigger; the rebalancer hook calls ``maybe_fold`` each pass).
    """

    wal_dir: str = "ingest-wal"
    memtable_capacity: int = 1024
    tomb_capacity: int = 1024
    max_memtable_rows: int = 8192
    max_wal_bytes: int = 64 << 20
    fold_rows: int = 0
    fold_tombstones: int = 0
    #: fold when the WAL replay debt (rows logged since the last fold —
    #: the rows a recovery would have to replay) reaches this bound
    #: (0 disables)
    fold_replay_debt_rows: int = 0
    #: fold when the OLDEST unfolded record has been pinned in the
    #: delta tier longer than this many seconds (0 disables) — the
    #: visibility-target trigger: it bounds both recovery replay time
    #: and how long the memtable merge carries a row
    fold_visibility_lag_s: float = 0.0
    write_quotas: Optional[Dict[str, Tuple[float, float]]] = None
    verify_level: str = "statistical"


class IngestServer:
    """The durable write path over one :class:`Memtable` + main index.

    Standalone (tests, offline loaders) or bound to a serving
    :class:`~raft_tpu.serving.server.Server` via ``server.attach_ingest``
    — binding attaches the memtable's device view to the executor's
    delta-merge seam and routes fold publications through
    ``Server.swap_index``.  Call :meth:`recover` before serving: it
    rolls an interrupted fold forward or back and replays the WAL."""

    def __init__(self, res, config: Optional[IngestConfig] = None, *,
                 dim: int, metric=DistanceType.L2Expanded,
                 clock=time.monotonic) -> None:
        self.res = res
        self.config = config or IngestConfig()
        self.memtable = _delta.Memtable(
            dim, capacity=self.config.memtable_capacity,
            tomb_capacity=self.config.tomb_capacity, metric=metric)
        os.makedirs(self.config.wal_dir, exist_ok=True)
        self._ck = CheckpointManager(
            os.path.join(self.config.wal_dir, _FOLD_DIR))
        self._wal: Optional[WriteAheadLog] = None
        self._clock = clock
        self._buckets = {t: TokenBucket(r, b, clock)
                         for t, (r, b) in
                         (self.config.write_quotas or {}).items()}
        self._server = None
        self._brownout = None
        self._index = None            # served index when no server is bound
        self._lsn = 0
        self._lock = threading.Lock()        # append order + memtable apply
        self._fold_lock = threading.Lock()
        self._sync_cond = threading.Condition()
        self._synced_lsn = 0
        self._sync_busy = False
        # group-commit failure fence: bumped when a group fsync fails,
        # with the exception retained so every rider of the failed
        # group re-raises it instead of riding a later, luckier fsync
        self._sync_epoch = 0
        self._sync_exc: Optional[BaseException] = None
        self._backpressured = False
        self._recovered = False
        # fold-trigger state (round 19): rows a recovery would replay
        # and the append time of the oldest unfolded record
        self._replay_debt_rows = 0
        self._oldest_pending_ts: Optional[float] = None

    # ---- wiring ----------------------------------------------------------

    @property
    def wal_path(self) -> str:
        return os.path.join(self.config.wal_dir, _WAL_FILE)

    def bind(self, server) -> None:
        """Attach to a serving Server (call via ``server.attach_ingest``
        BEFORE ``server.start()`` — the delta merge joins every warmed
        shape)."""
        self._server = server
        self._brownout = server.brownout
        server.executor.attach_delta(self.memtable.device_view)

    def _current_index(self):
        if self._server is not None:
            return self._server.executor.index
        return self._index

    def _publish(self, cand) -> None:
        if self._server is not None:
            self._server.swap_index(cand)
        self._index = cand

    # ---- recovery --------------------------------------------------------

    def recover(self, base_index=None):
        """Roll an interrupted fold forward/back, repair a torn WAL
        tail, replay the intact records into the memtable, and return
        the index to serve (the committed fold candidate when one
        landed, else ``base_index``).  Idempotent; must run before the
        first :meth:`write`."""
        main = base_index if base_index is not None else self._index
        rolled_forward = False
        if self._ck.has(_FOLD_STAGE):
            try:
                cand, fold_lsn = self._load_fold()
                # committed fold: the candidate IS the main index; finish
                # the interrupted truncation (every logged record <= F is
                # folded in) and retire the checkpoint
                self._open_wal()
                self._wal.truncate_all()
                self.memtable.reset()
                self._ck.clear()
                main = cand
                rolled_forward = True
                _flight.record_event("serving.ingest.replay",
                                     rolled_forward=True, fold_lsn=fold_lsn,
                                     generation=_mutate.generation(cand))
            except CorruptIndexError:
                # torn/corrupt candidate: abandon the fold, full replay
                self._ck.clear()
        elif self._ck.completed:
            # fold died before its commit marker: roll back (the WAL
            # still holds every record; the base index is untouched)
            self._ck.clear()
        self._open_wal()
        if not rolled_forward:
            data = self._wal.read_bytes()
            records, good_end = scan_wal(data)
            dropped = self._wal.repair_tail(good_end)
            replayed = 0
            for rec in records:
                if self.memtable.apply(rec):
                    replayed += 1
                    _count("serving.ingest.replayed")
            self._lsn = max((r.lsn for r in records), default=0)
            self._synced_lsn = self._lsn
            self._replay_debt_rows = int(
                sum(r.ids.size for r in records))
            self._oldest_pending_ts = (self._clock() if records else None)
            if records or dropped:
                _flight.record_event("serving.ingest.replay",
                                     rolled_forward=False, records=replayed,
                                     truncated_bytes=dropped,
                                     last_lsn=self._lsn)
        self._index = main
        self._recovered = True
        return main

    def _open_wal(self) -> None:
        if self._wal is None:
            self._wal = WriteAheadLog(self.wal_path)

    # ---- the write path --------------------------------------------------

    def write(self, ids, vectors=None, *, op: str = "upsert",
              tenant: str = "default") -> int:
        """Append one upsert/delete record, fsync (group-committed),
        apply to the memtable, and return the record's LSN — the ack.
        A raised exception means NOT acknowledged: the record may or may
        not be durable and the caller must retry (upserts/deletes are
        idempotent by id).  Sheds with :class:`Overloaded` subclasses
        before touching the WAL."""
        expects(self._recovered,
                "ingest: recover() must run before the first write")
        t0 = self._clock()
        # per-write trace (PR 11 parity with the read path): adopt an
        # ambient recorder when the caller already minted one, else mint
        # a root here so ingest requests produce full Chrome-trace
        # chains.  One flag check when tracing is off.
        rt = _trace.current()
        minted = rt is None and _trace.tracing()
        if minted:
            rt = _trace.start_request("serving.ingest.request")
        opcode = _OPS.get(op)
        expects(opcode is not None,
                f"ingest: op must be 'upsert' or 'delete', got {op!r}")
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        expects(ids.size > 0, "ingest: write needs at least one id")
        expects(int(ids.min()) >= 0, "ingest: source ids must be >= 0")
        if opcode == _delta.OP_UPSERT:
            vecs = np.ascontiguousarray(vectors, np.float32)
            if vecs.ndim == 1:
                vecs = vecs[None, :]
            expects(vecs.shape == (ids.size, self.memtable.dim),
                    f"ingest: vectors must be ({ids.size}, "
                    f"{self.memtable.dim}), got {vecs.shape}")
        else:
            expects(vectors is None, "ingest: delete takes no vectors")
            vecs = None
        if rt is not None:
            rt.annotate("tenant", tenant)
            rt.annotate("op", op)
            rt.annotate("rows", int(ids.size))
        try:
            self._admit(int(ids.size), tenant, opcode)
        except Overloaded:
            if minted:
                # shed at the door: the trace still lands in the flight
                # recorder, same contract as a shed read submit
                rt.annotate("shed", True)
                _flight.record_trace(rt.close())
            raise
        with self._lock:
            lsn = self._lsn + 1
            t_append = _trace.now() if rt is not None else 0.0
            self._wal.append(encode_record(lsn, opcode, ids, vecs))
            self._lsn = lsn
            self._replay_debt_rows += int(ids.size)
            if self._oldest_pending_ts is None:
                self._oldest_pending_ts = t0
            _count("serving.ingest.appended")
            # apply inside the append lock: memtable order == WAL order,
            # so replay reproduces the live state record for record.
            # Rows are searchable HERE — before the fsync — so
            # visibility is decoupled from durability; the ack below
            # still waits for the fsync.
            faults.maybe_fail("ingest.apply")
            if rt is not None:
                t_apply = _trace.now()
                rt.span("serving.ingest.append", t_append, t_apply,
                        lsn=lsn, rows=int(ids.size))
            self.memtable.apply(_delta.Record(lsn=lsn, op=opcode, ids=ids,
                                              vectors=vecs))
            if rt is not None:
                rt.span("serving.ingest.apply", t_apply, _trace.now())
            if obs.enabled():
                obs.registry().histogram(
                    "serving.ingest.visibility").observe(self._clock() - t0)
        t_sync = _trace.now() if rt is not None else 0.0
        self._sync_upto(lsn)
        _count("serving.ingest.acked")
        _gauge("serving.ingest.wal_bytes", self._wal.size_bytes)
        _gauge("serving.ingest.memtable_rows", self.memtable.live_rows)
        if rt is not None:
            rt.span("serving.ingest.fsync", t_sync, _trace.now(), lsn=lsn)
            rt.annotate("lsn", lsn)
            if minted:
                _flight.record_trace(rt.close())
        return lsn

    def _sync_upto(self, lsn: int) -> None:
        """Group commit: wait until the WAL is durable through ``lsn``.
        The first waiter performs ONE fsync covering every record
        appended so far; concurrent writers ride it.

        A failed fsync fails the ack of the WHOLE group: the performer
        re-raises, and every rider whose record was in flight during
        the failed epoch re-raises the same error instead of silently
        riding a later, luckier fsync — their rows were applied
        (visible) but never proven durable, so acking them would break
        the durability contract.  The WAL tail is left exactly as
        appended: any torn suffix is the repairable-tail case
        :func:`scan_wal` already handles, so the next :meth:`recover`
        repairs and replays cleanly.  Writers arriving AFTER the
        failure start a fresh epoch and may ack on a new fsync."""
        with self._sync_cond:
            if self._synced_lsn >= lsn:
                return
            epoch = self._sync_epoch
        while True:
            with self._sync_cond:
                if self._synced_lsn >= lsn:
                    return
                if self._sync_epoch != epoch:
                    # a group fsync covering our in-flight record
                    # failed: this ack fails with the group
                    raise self._sync_exc
                if self._sync_busy:
                    self._sync_cond.wait(timeout=1.0)
                    continue
                self._sync_busy = True
            try:
                with self._lock:
                    target = self._lsn
                self._wal.sync()
            except BaseException as exc:
                with self._sync_cond:
                    self._sync_busy = False
                    self._sync_epoch += 1
                    self._sync_exc = exc
                    self._sync_cond.notify_all()
                raise
            with self._sync_cond:
                self._synced_lsn = max(self._synced_lsn, target)
                self._sync_busy = False
                self._sync_cond.notify_all()

    # ---- admission -------------------------------------------------------

    def _admit(self, n_rows: int, tenant: str, opcode: int) -> None:
        bo = self._brownout
        if (bo is not None
                and getattr(bo, "shed_best_effort_writes", False)
                and tenant in bo.best_effort_tenants):
            _count("serving.ingest.shed.brownout")
            _flight.record_event("serving.ingest.shed.brownout",
                                 tenant=tenant, rows=n_rows,
                                 level=bo.level)
            raise BrownedOut(
                f"ingest: best-effort tenant {tenant!r} writes shed at "
                f"brownout level {bo.level} — retry with backoff")
        bucket = self._buckets.get(tenant)
        if bucket is not None and not bucket.try_acquire(n_rows):
            _count("serving.ingest.shed.quota")
            _flight.record_event("serving.ingest.shed.quota",
                                 tenant=tenant, rows=n_rows,
                                 rate=bucket.rate, burst=bucket.burst)
            raise QuotaExceeded(
                f"ingest: tenant {tenant!r} exceeded its write quota "
                f"({bucket.rate:g} rows/s, burst {bucket.burst:g})")
        rows = self.memtable.live_rows
        wal_bytes = self._wal.size_bytes if self._wal is not None else 0
        # the rows bound gates UPSERTS only: deletes drain live rows, so
        # shedding them under row pressure would wedge the very writes
        # that relieve it (they still respect the WAL-lag bound)
        over_rows = (opcode == _delta.OP_UPSERT
                     and rows + n_rows > self.config.max_memtable_rows)
        over_wal = wal_bytes >= self.config.max_wal_bytes
        if over_rows or over_wal:
            _count("serving.ingest.shed.backpressure")
            if not self._backpressured:
                self._backpressured = True
                _flight.record_event("serving.ingest.backpressure",
                                     state="enter", memtable_rows=rows,
                                     wal_bytes=wal_bytes, rows=n_rows)
            raise Overloaded(
                f"ingest: write backpressure (memtable {rows} rows"
                f"{' > bound' if over_rows else ''}, WAL {wal_bytes} bytes"
                f"{' > bound' if over_wal else ''}) — retry after the "
                f"next fold")
        if self._backpressured:
            self._backpressured = False
            _flight.record_event("serving.ingest.backpressure",
                                 state="exit", memtable_rows=rows,
                                 wal_bytes=wal_bytes)

    # ---- fold ------------------------------------------------------------

    def maybe_fold(self):
        """Fold when a configured trigger fires (the rebalancer's
        per-pass hook); returns the new index or None.

        Two trigger families: the PR 13 size thresholds (``fold_rows``
        / ``fold_tombstones``) and the round-19 WAL-lag / visibility
        targets — ``fold_replay_debt_rows`` fires when the rows a
        recovery would have to replay exceed the bound, and
        ``fold_visibility_lag_s`` fires when the oldest unfolded record
        has been pinned in the delta tier past the target.  Each
        lag-family firing ticks its own counter
        (``serving.ingest.fold_trigger.rows`` /
        ``serving.ingest.fold_trigger.lag``) so the fold cadence is
        attributable to a cause, not just observed."""
        rows, tombs = self.memtable.live_rows, self.memtable.n_tombstones
        cfg = self.config
        trigger = None
        if ((cfg.fold_rows and rows >= cfg.fold_rows)
                or (cfg.fold_tombstones
                    and tombs >= cfg.fold_tombstones)):
            trigger = "threshold"
        elif (cfg.fold_replay_debt_rows
                and self._replay_debt_rows >= cfg.fold_replay_debt_rows):
            trigger = "rows"
            _count("serving.ingest.fold_trigger.rows")
        elif (cfg.fold_visibility_lag_s
                and self._oldest_pending_ts is not None
                and (self._clock() - self._oldest_pending_ts
                     >= cfg.fold_visibility_lag_s)):
            trigger = "lag"
            _count("serving.ingest.fold_trigger.lag")
        if trigger is None:
            return None
        return self.fold()

    def fold(self):
        """Fold the memtable into the main index: one checkpointed,
        gated compaction (see the module docstring for the crash-window
        analysis).  Writes block for the duration; searches keep serving
        the pre-fold view until the swap publishes.  Returns the new
        index, or None when the delta tier is empty."""
        with self._fold_lock, self._lock:
            mem = self.memtable
            if mem.live_rows == 0 and mem.n_tombstones == 0:
                return None
            base = self._current_index()
            expects(base is not None,
                    "ingest: fold needs a bound server or a recovered "
                    "base index")
            faults.maybe_fail("ingest.fold")
            # fold trace: adopt the ambient recorder when one is active
            # (a traced caller), else mint a root — the stage() below
            # mirrors its timer onto whichever is current, so the
            # Chrome-trace chain shows the fold span either way
            frt = None
            if _trace.current() is None and _trace.tracing():
                frt = _trace.start_request("serving.ingest.request")
                frt.annotate("op", "fold")
            with _trace.activating(frt), obs.stage("serving.ingest.fold"):
                fold_lsn = self._lsn
                live_ids, live_rows, tomb_ids = mem.fold_payload()
                mod = (ivf_flat if isinstance(base, ivf_flat.Index)
                       else ivf_pq)
                parent_gen = _mutate.generation(base)
                # upsert semantics: clear EVERY touched id (deletes and
                # overwrites), then extend the live rows back — exactly
                # the module-level upsert pattern, ONE public bump
                clear = np.union1d(tomb_ids, live_ids).astype(np.int32)
                cand = base
                if clear.size:
                    cand = mod.delete(self.res, cand, jnp.asarray(clear))
                if live_ids.size:
                    cand = mod.extend(self.res, cand,
                                      jnp.asarray(live_rows),
                                      jnp.asarray(live_ids))
                cand.generation = parent_gen + 1
                # the gate: no fold candidate is published unverified
                _verify_index(cand, self.config.verify_level, res=self.res,
                              n_rows=_id_span(cand))
                if getattr(cand, "canaries", None) is not None:
                    _canary.health_check(self.res, cand, raise_on_fail=True)
                # durable commit marker BEFORE the swap: a kill after
                # this point rolls FORWARD (recover publishes the
                # candidate and finishes the truncation)
                self._save_fold(cand, mod, fold_lsn)
                self._publish(cand)
                # truncate only after the gated swap landed
                self._wal.truncate_all()
                mem.reset()
                with self._sync_cond:
                    self._synced_lsn = self._lsn
                self._replay_debt_rows = 0
                self._oldest_pending_ts = None
                self._ck.clear()
                _count("serving.ingest.folds")
                _flight.record_event("serving.ingest.fold",
                                     rows=int(live_ids.size),
                                     tombstones=int(tomb_ids.size),
                                     fold_lsn=fold_lsn,
                                     generation=_mutate.generation(cand))
            if frt is not None:
                frt.annotate("rows", int(live_ids.size))
                frt.annotate("tombstones", int(tomb_ids.size))
                frt.annotate("generation", _mutate.generation(cand))
                _flight.record_trace(frt.close())
            return cand

    def _save_fold(self, cand, mod, fold_lsn: int) -> None:
        buf = io.BytesIO()
        mod.serialize(self.res, buf, cand)
        self._ck.save(_FOLD_STAGE, {
            "index": np.frombuffer(buf.getvalue(), np.uint8),
            "kind": np.frombuffer(
                ("ivf_flat" if mod is ivf_flat else "ivf_pq").encode(),
                np.uint8),
            "generation": np.asarray([_mutate.generation(cand)], np.int64),
            "fold_lsn": np.asarray([fold_lsn], np.int64)})

    def _load_fold(self):
        arrays = self._ck.load(_FOLD_STAGE)
        kind = bytes(arrays["kind"]).decode()
        mod = ivf_flat if kind == "ivf_flat" else ivf_pq
        idx = mod.deserialize(self.res, io.BytesIO(bytes(arrays["index"])))
        idx.generation = int(arrays["generation"][0])
        return idx, int(arrays["fold_lsn"][0])

    # ---- lifecycle -------------------------------------------------------

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None

    def __enter__(self) -> "IngestServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> Dict[str, object]:
        return {
            "last_lsn": self._lsn,
            "synced_lsn": self._synced_lsn,
            "wal_bytes": self._wal.size_bytes if self._wal else 0,
            "memtable_rows": self.memtable.live_rows,
            "tombstones": self.memtable.n_tombstones,
            "memtable_capacity": self.memtable.capacity,
            "backpressured": self._backpressured,
            "replay_debt_rows": self._replay_debt_rows,
        }


def _id_span(index) -> int:
    """Max decoded source id + 1 — the verify bound for a folded
    snapshot, whose live id space is sparse (same convention as the
    rebalancer's gate)."""
    li = np.asarray(index.list_indices)
    dec = np.where(li <= -2, -li.astype(np.int64) - 2, li.astype(np.int64))
    vals = dec[(li >= 0) | (li <= -2)]
    return int(vals.max()) + 1 if vals.size else 0
