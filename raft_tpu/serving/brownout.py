"""Brownout control — adaptive overload degradation for the serving path.

Static admission (PR 5) sheds requests outright when offered load beats
capacity, so goodput collapses instead of degrading.  This module adds
the missing control loop: a :class:`BrownoutController` watches the
PR 11 windowed telemetry — ``serving.latency.total`` window p99, the
``serving.queue_depth`` gauge (read directly off the queue), and the
windowed ``serving.shed.*`` counters — and steps the serving bucket
down/up a **pre-declared degradation ladder** of operating points::

    ladder = [
        brownout.Rung("full"),                          # rung 0: full quality
        brownout.Rung("probes/2", params=half_probes),  # reduced n_probes
        brownout.Rung("probes/4", params=quarter),      # cheaper still
        brownout.Rung("shed-best-effort",               # same executables,
                      shed_best_effort=True),           # + tenant shedding
    ]
    ctl = brownout.BrownoutController(server, ladder,
                                      brownout.BrownoutConfig(...),
                                      best_effort_tenants={"batch"})
    server.start()        # warms EVERY rung through the AOT cache
    ctl.start()           # control loop: evaluate() every interval_s

Declare-then-warm is the whole design: the ladder is fixed before
``Server.start()``, every rung's executables are pre-warmed through
:class:`~raft_tpu.core.aot.ExecutableCache` (the rung is part of the
cache key, like ``scan_mode``), and a brownout transition is ONE
integer store read by the batcher on its next cut — zero recompiles,
zero host syncs, the same closed-shape discipline PRs 5/10 established
(and graftlint now guards).  A :class:`Rung` with ``params=None``
inherits the previous rung's executables (no extra warmup); a rung with
``shed_best_effort=True`` additionally sheds requests from the
best-effort tenant set at admission (``serving.shed.brownout``).

Flapping is pinned two ways: **hysteresis** (the step-up threshold
``step_up_p99_s`` must sit strictly below the step-down threshold
``step_down_p99_s``, and likewise the queue fractions) and **dwell
time** (``dwell_s`` must elapse at a level before the next transition
in either direction).  Transitions land ``serving.brownout.step_down``
/ ``serving.brownout.step_up`` events in the always-on flight recorder
and move the ``serving.brownout.level`` gauge; per-level residency is
tracked for the overload bench (:func:`bench.bench_overload`).

The controller is deliberately NOT in the request path: it reads
aggregated telemetry on its own thread (or under a test's synchronous
:meth:`~BrownoutController.evaluate` calls with an injected clock) and
publishes one small state object the hot path reads lock-free.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence

from raft_tpu import observability as obs
from raft_tpu.core.error import expects
from raft_tpu.observability import flight as _flight

#: the serving.shed.* counters that signal OVERLOAD (quota sheds are
#: policy, not pressure, and must not brown the bucket out)
_PRESSURE_SHEDS = ("serving.shed.deadline", "serving.shed.queue_full")


@dataclasses.dataclass(frozen=True)
class Rung:
    """One declared operating point on the degradation ladder.

    ``params`` is a SearchParams variant (e.g. ``n_probes`` halved,
    ``kt`` reduced, refinement off) compiled as its own executable rung;
    ``None`` inherits the previous rung's executables — the idiom for a
    shed-only top rung.  ``shed_best_effort`` turns on admission-time
    shedding of the best-effort tenant set while this rung is active;
    ``shed_best_effort_writes`` does the same for the WRITE path — the
    ingest tier (:mod:`raft_tpu.serving.ingest`) sheds best-effort
    tenants' writes with ``BrownedOut`` while the rung holds, keeping
    fold pressure off an already-degraded read path.
    """

    name: str
    params: Optional[object] = None
    shed_best_effort: bool = False
    shed_best_effort_writes: bool = False


class BrownoutState:
    """The one object the hot path reads: current ladder level, the
    executor rung serving it, and the best-effort shed switch.  Plain
    attribute stores/loads (GIL-atomic) — admission and the batcher read
    it lock-free on every request/cut."""

    __slots__ = ("level", "rung", "shed_best_effort",
                 "shed_best_effort_writes", "best_effort_tenants")

    def __init__(self, best_effort_tenants: Iterable[str] = ()) -> None:
        self.level = 0
        self.rung = 0
        self.shed_best_effort = False
        self.shed_best_effort_writes = False
        self.best_effort_tenants: FrozenSet[str] = frozenset(
            best_effort_tenants)


@dataclasses.dataclass
class BrownoutConfig:
    """Control-loop knobs.  Hysteresis is enforced at validation: the
    step-up (recovery) thresholds must sit strictly below the step-down
    (pressure) thresholds, and ``dwell_s`` must elapse at a level before
    the next transition — together they pin ladder oscillation.
    """

    #: window p99 of ``serving.latency.total`` (seconds) at/above which
    #: the controller steps DOWN (degrades)
    step_down_p99_s: float = 0.5
    #: window p99 (seconds) at/below which it may step UP (recover);
    #: must be < step_down_p99_s (the hysteresis gap)
    step_up_p99_s: float = 0.1
    #: queued-rows fraction of ``max_queue_rows`` at/above which the
    #: controller steps down even before latency moves
    queue_high_fraction: float = 0.5
    #: queued-rows fraction at/below which recovery is allowed;
    #: must be < queue_high_fraction
    queue_low_fraction: float = 0.125
    #: windowed pressure-shed count (deadline + queue_full) that forces
    #: a step down regardless of latency
    shed_step_down: int = 1
    #: minimum seconds at a level before ANY further transition
    dwell_s: float = 2.0
    #: control-loop period for the background thread
    interval_s: float = 1.0

    def validate(self) -> None:
        expects(self.step_up_p99_s < self.step_down_p99_s,
                "brownout: step_up_p99_s must be below step_down_p99_s "
                "(the hysteresis gap)")
        expects(0.0 < self.queue_low_fraction < self.queue_high_fraction
                <= 1.0,
                "brownout: need 0 < queue_low_fraction < "
                "queue_high_fraction <= 1")
        expects(self.dwell_s >= 0.0, "brownout: dwell_s must be >= 0")
        expects(self.interval_s > 0.0, "brownout: interval_s must be > 0")
        expects(self.shed_step_down >= 1,
                "brownout: shed_step_down must be >= 1")


class BrownoutController:
    """Steps one :class:`~raft_tpu.serving.server.Server` down/up its
    declared ladder.  Construct BEFORE ``server.start()`` — installing
    the ladder grows the executor's closed rung set, which must be
    warmed with everything else."""

    def __init__(self, server, ladder: Sequence[Rung],
                 config: Optional[BrownoutConfig] = None, *,
                 best_effort_tenants: Iterable[str] = (),
                 clock=time.monotonic) -> None:
        expects(len(ladder) >= 2,
                "brownout: a ladder needs at least a full-quality rung "
                "and one degraded rung")
        expects(ladder[0].params is None and not ladder[0].shed_best_effort
                and not ladder[0].shed_best_effort_writes,
                "brownout: rung 0 must be the undegraded operating point "
                "(params=None, no shedding)")
        self.server = server
        self.ladder = tuple(ladder)
        self.config = config or BrownoutConfig()
        self.config.validate()
        self._clock = clock
        # resolve ladder levels onto executor rungs: params=None inherits
        # the previous level's executables, so a shed-only rung costs no
        # extra warmup and no extra cache entries
        exec_params: List[object] = []
        self._exec_rung: List[int] = [0]
        for r in self.ladder[1:]:
            if r.params is not None:
                exec_params.append(r.params)
                self._exec_rung.append(len(exec_params))
            else:
                self._exec_rung.append(self._exec_rung[-1])
        server.executor.set_ladder(exec_params)
        self.state = server.brownout
        self.state.best_effort_tenants = frozenset(best_effort_tenants)
        now = clock()
        self._t_level = now            # when the current level was entered
        self._residency = [0.0] * len(self.ladder)
        self._transitions = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- telemetry reads -------------------------------------------------

    def _latency_p99(self) -> Optional[float]:
        """Window p99 of end-to-end serving latency, or None when
        collection is off or the window is empty (no latency signal —
        the queue and shed signals still steer)."""
        if not obs.enabled():
            return None
        w = obs.registry().histogram("serving.latency.total").windowed_dict()
        if not w["count"]:
            return None
        return float(w["p99"])

    def _pressure_sheds(self) -> int:
        """Windowed deadline + queue_full shed count (quota sheds are
        excluded — tenant policy is not overload)."""
        if not obs.enabled():
            return 0
        reg = obs.registry()
        return sum(reg.counter(name).windowed() for name in _PRESSURE_SHEDS)

    # ---- the control decision --------------------------------------------

    def evaluate(self) -> Optional[str]:
        """One control decision from current telemetry; called by the
        background loop every ``interval_s`` (or synchronously by tests
        with an injected clock).  Returns ``"step_down"``, ``"step_up"``
        or None."""
        now = self._clock()
        with self._lock:
            level = self.state.level
            if now - self._t_level < self.config.dwell_s:
                return None        # dwell pins flapping in BOTH directions
            p99 = self._latency_p99()
            queue_rows = self.server.queue.rows
            max_rows = self.server.config.max_queue_rows
            sheds = self._pressure_sheds()
            pressed = (
                (p99 is not None and p99 >= self.config.step_down_p99_s)
                or queue_rows >= self.config.queue_high_fraction * max_rows
                or sheds >= self.config.shed_step_down)
            if pressed and level < len(self.ladder) - 1:
                self._apply(level + 1, "step_down", now,
                            p99=p99, queue_rows=queue_rows, sheds=sheds)
                return "step_down"
            calm = (
                (p99 is None or p99 <= self.config.step_up_p99_s)
                and queue_rows <= self.config.queue_low_fraction * max_rows
                and sheds == 0)
            if calm and level > 0:
                self._apply(level - 1, "step_up", now,
                            p99=p99, queue_rows=queue_rows, sheds=sheds)
                return "step_up"
            return None

    def _apply(self, new_level: int, direction: str, now: float, *,
               p99: Optional[float], queue_rows: int, sheds: int) -> None:
        """Publish one transition (caller holds the lock).  Ordering
        matters: the rung store happens before the level store so a
        racing batch cut never pairs a new level with a stale rung."""
        old = self.state.level
        self._residency[old] += now - self._t_level
        self._t_level = now
        self._transitions += 1
        rung = self.ladder[new_level]
        self.state.rung = self._exec_rung[new_level]
        self.state.shed_best_effort = rung.shed_best_effort
        self.state.shed_best_effort_writes = rung.shed_best_effort_writes
        self.state.level = new_level
        if obs.enabled():
            obs.registry().gauge("serving.brownout.level").set(new_level)
        # always-on anomaly event: a quality change is exactly what a
        # post-mortem needs to see next to the latency it reacted to
        _flight.record_event(f"serving.brownout.{direction}",
                             from_level=old, to_level=new_level,
                             rung=rung.name, p99_s=p99,
                             queue_rows=queue_rows, window_sheds=sheds)
        shadow = getattr(self.server, "shadow", None)
        if shadow is not None:
            # close the quality window at the rung boundary so one
            # operating-point record never pools samples served at two
            # different operating points (flag only — the flush itself
            # runs on the shadow thread, never under this lock)
            shadow.mark_transition()

    # ---- background loop -------------------------------------------------

    def start(self) -> "BrownoutController":
        """Run :meth:`evaluate` every ``interval_s`` on a daemon thread
        (the rebalancer's lifecycle pattern)."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="raft-tpu-brownout",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=30.0)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            self.evaluate()

    def __enter__(self) -> "BrownoutController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- introspection ---------------------------------------------------

    def stats(self) -> Dict[str, object]:
        """Level, transition count, and per-level residency seconds
        (the current level's open interval included)."""
        now = self._clock()
        with self._lock:
            res = list(self._residency)
            res[self.state.level] += now - self._t_level
            return {
                "level": self.state.level,
                "rung": self.ladder[self.state.level].name,
                "transitions": self._transitions,
                "residency_s": {self.ladder[i].name: res[i]
                                for i in range(len(self.ladder))},
            }
