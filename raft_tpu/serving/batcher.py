"""Dynamic batcher — coalesce submissions into padded bucket batches.

One dispatcher thread drains the admission queue and cuts batches under
the policy the reference-class serving stacks use (and the ISSUE names):
dispatch when the pending rows reach ``max_batch`` OR the oldest queued
request has waited ``max_wait_us`` — whichever comes first.  A cut batch
is concatenated, zero-padded up to its bucket (powers of two — see
:mod:`raft_tpu.serving.buckets`), searched through the warmed executor,
and sliced back per request.

Timing uses ``time.monotonic`` (the deadline clock) — wall-profiling
belongs to :func:`raft_tpu.observability.stage`, but the batcher needs
timestamps even when collection is off, because ``max_wait`` and
deadlines are control flow, not telemetry.  Histograms
(``serving.latency.queue``, ``.exec``, ``.total`` seconds and
``serving.batch_fill``) are recorded only while collection is enabled.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from raft_tpu import observability as obs
from raft_tpu.observability import flight as _flight
from raft_tpu.observability import trace as _trace
from raft_tpu.resilience import faults as _faults
from raft_tpu.resilience.retry import DeadlineExceededError
from raft_tpu.serving.admission import AdmissionQueue
from raft_tpu.serving.buckets import bucket_for


class DynamicBatcher:
    """Owns the dispatcher thread between an admission queue and an
    executor (``raft_tpu.serving.executor.Executor``).

    ``brownout`` is the server's shared
    :class:`~raft_tpu.serving.brownout.BrownoutState`: each cut batch
    executes at the state's current executor rung (one lock-free int
    read — every rung is pre-warmed, so a level change never compiles).
    ``on_error`` is called with the exception after a batch dispatch
    fails (after the per-request futures are failed) — the server's
    generation watchdog listens here for :class:`IntegrityError`.
    """

    def __init__(self, queue: AdmissionQueue, executor, *,
                 max_batch: int, max_wait_us: float,
                 on_batch: Optional[Callable] = None,
                 brownout=None,
                 on_error: Optional[Callable] = None) -> None:
        self.queue = queue
        self.executor = executor
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_us) * 1e-6
        self._on_batch = on_batch
        self._on_error = on_error
        self.brownout = brownout
        # live quality monitor (serving.shadow) — set by Server.start();
        # None costs one check per dispatched batch
        self.shadow = None
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop = False
        self._thread = threading.Thread(target=self._run,
                                        name="raft-tpu-serving-batcher",
                                        daemon=True)
        self._thread.start()

    def stop(self, drain: bool = True) -> None:
        """Stop the dispatcher.  With ``drain`` (default) queued requests
        are dispatched first; otherwise they fail with Overloaded."""
        if self._thread is None:
            return
        with self.queue.cond:
            self._drain = drain
            self._stop = True
            self.queue.cond.notify_all()
        self._thread.join(timeout=30.0)
        self._thread = None

    # ---- dispatcher loop ------------------------------------------------

    def _run(self) -> None:
        self._drain = True
        while True:
            batch = None
            with self.queue.cond:
                while True:
                    if self._stop and (not self._drain or not len(self.queue)):
                        break
                    oldest = self.queue.peek_oldest()
                    if oldest is None:
                        self.queue.cond.wait(timeout=0.1)
                        continue
                    waited = time.monotonic() - oldest.t_enqueue
                    if (self.queue.rows >= self.max_batch
                            or waited >= self.max_wait_s
                            or self._stop):
                        batch = self.queue.cut_batch(self.max_batch)
                        break
                    # no timeout underrun: wake exactly when the oldest
                    # request hits max_wait (or earlier on new arrivals)
                    self.queue.cond.wait(timeout=self.max_wait_s - waited)
            if batch:
                self._dispatch(batch)
            elif self._stop:
                self._fail_remaining()
                return

    def _fail_remaining(self) -> None:
        from raft_tpu.serving.admission import Overloaded
        with self.queue.cond:
            rest = self.queue.cut_batch(10 ** 9)
            while rest:
                for r in rest:
                    r.future.set_exception(
                        Overloaded("serving: server stopped"))
                rest = self.queue.cut_batch(10 ** 9)

    # ---- one batch ------------------------------------------------------

    def _dispatch(self, batch) -> None:
        t_dispatch = time.monotonic()
        bo = self.brownout
        level = bo.level if bo is not None else 0
        rung = bo.rung if bo is not None else 0
        live = []
        for r in batch:
            if r.deadline is not None and r.deadline.expired:
                # the dispatch-phase half of the deadline-shed ledger:
                # SAME counter as the submit-phase check in admission
                # (phase distinguishes them on the flight event), so
                # `serving.shed.deadline` is the one total a dashboard
                # needs, and each request ticks it exactly once —
                # admission raises before enqueue, this path only sees
                # requests admission let through
                _count("serving.shed.deadline")
                _flight.record_event("serving.shed.deadline",
                                     trace_id=r.trace_id, tenant=r.tenant,
                                     rows=r.n, phase="dispatch",
                                     queued_s=t_dispatch - r.t_enqueue,
                                     level=level)
                if r.trace is not None:
                    r.trace.span("serving.queue", r.t_enqueue, t_dispatch)
                    r.trace.annotate("shed", True)
                    if level:
                        r.trace.annotate("brownout_level", level)
                    _flight.record_trace(r.trace.close(t_dispatch))
                r.future.set_exception(DeadlineExceededError(
                    f"serving: deadline expired after "
                    f"{t_dispatch - r.t_enqueue:.3f}s in queue"))
            else:
                live.append(r)
        if not live:
            return
        k = live[0].k
        n = sum(r.n for r in live)
        bucket = bucket_for(n, self.max_batch)
        # batch-level recorder: the batch's cut/exec spans and whatever the
        # executor path annotates (scan mode, shard status, scanned rows —
        # see distributed.ann.search) are recorded once here and adopted
        # into every live request's trace afterwards.  Spans are immutable,
        # so sharing them across traces is safe.
        traced = [r for r in live if r.trace is not None]
        batch_rec = (_trace.SpanRecorder("serving.batch",
                                         trace_id=traced[0].trace.trace_id,
                                         t0=t_dispatch)
                     if traced else None)
        # batch assembly and result slicing are HOST-side numpy: request
        # sizes vary continuously, and any jnp op keyed on them
        # (concatenate / pad / slice) would compile per novel shape —
        # breaking the zero-recompile contract the buckets exist for.
        # The device only ever sees the warmed (bucket, dim) shapes.
        buf = np.zeros((bucket, self.executor.dim),
                       dtype=self.executor.query_dtype)
        off = 0
        for r in live:
            buf[off:off + r.n] = np.asarray(r.queries)
            off += r.n
        # per-query admission bitsets ride the SAME assembly path: a
        # fixed (bucket, n_words) int32 buffer — data, not shape — with
        # all-ones rows (admit everything) for unfiltered requests and
        # padding.  Skipped entirely (None -> the executor's cached
        # all-ones buffer) when no live request carries a filter.
        fbuf = None
        nw = getattr(self.executor, "n_filter_words", 0)
        if nw and any(r.filter_words is not None for r in live):
            fbuf = np.full((bucket, nw), -1, dtype=np.int32)
            off = 0
            for r in live:
                if r.filter_words is not None:
                    fbuf[off:off + r.n] = r.filter_words
                off += r.n
        t_exec0 = time.monotonic()
        # the generation snapshot this batch serves from — pinned here so
        # the shadow monitor can refuse to compare across a swap
        idx_gen = self.executor.index
        try:
            # named fault site: latency plans here (faults.delay_at) are
            # how the chaos bench/CI slow the serving path down on
            # demand; inactive it is one None check on the hot path
            _faults.maybe_fail("serving.dispatch")
            with _trace.activating(batch_rec):
                # kwarg only when a live request carries a filter, so
                # executors (and test doubles) with the pre-filter
                # search_bucket signature keep working unfiltered
                fkw = ({"filter_words": jnp.asarray(fbuf)}
                       if fbuf is not None else {})
                d, i = self.executor.search_bucket(
                    jnp.asarray(buf), n, k, rung=rung, **fkw)
                # graftlint: disable=host-sync -- THE one readback: results must leave the device to resolve request futures
                d, i = np.asarray(d), np.asarray(i)
        except BaseException as e:  # noqa: BLE001 - forwarded per request
            _flight.record_event("serving.batch_error",
                                 trace_id=(traced[0].trace.trace_id
                                           if traced else None),
                                 error=repr(e), rows=n, bucket=bucket, k=k)
            for r in traced:
                r.trace.annotate("error", repr(e))
                _flight.record_trace(r.trace.close())
            # post-mortem artifact: if RAFT_TPU_FLIGHT_DUMP is set, the
            # ring (this error included) is written before futures fail
            _flight.maybe_auto_dump("serving.batch_error")
            for r in live:
                r.future.set_exception(e)
            if self._on_error is not None:
                self._on_error(e)
            return
        t_done = time.monotonic()
        if batch_rec is not None:
            batch_rec.span("serving.batch_cut", t_dispatch, t_exec0,
                           rows=n, bucket=bucket, requests=len(live))
            batch_rec.span("serving.exec", t_exec0, t_done)
            if level:
                batch_rec.annotate("brownout_level", level)
                batch_rec.annotate("rung", rung)
        self._record(live, n, bucket, t_dispatch, t_done)
        off = 0
        worst = np.inf if self.executor.select_min else -np.inf
        results = []
        for r in live:
            rd = d[off:off + r.n]
            ri = i[off:off + r.n]
            if r.ok_rows is not None:
                # per-request boundary mask (policy "mask"): same output
                # contract as integrity.boundary.mask_search_outputs,
                # applied host-side on the already-fetched slice
                bad = ~np.asarray(r.ok_rows)[:, None]
                rd = np.where(bad, np.asarray(worst, rd.dtype), rd)
                ri = np.where(bad, np.asarray(-1, ri.dtype), ri)
            off += r.n
            results.append((r, rd, ri))
        t_sliced = time.monotonic()
        for r, rd, ri in results:
            if r.trace is not None:
                rt = r.trace
                rt.span("serving.queue", r.t_enqueue, t_dispatch)
                rt.adopt(batch_rec)
                rt.span("serving.result_slice", t_done, t_sliced)
                _flight.record_trace(rt.close(t_sliced))
            r.future.set_result((rd, ri))
        sh = self.shadow
        if sh is not None:
            # host-side arrays only — the sampler must add no device
            # work to this thread (see ShadowMonitor.offer)
            sh.offer(results, k, idx_gen, rung)
        if self._on_batch is not None:
            self._on_batch(n, bucket)

    def _record(self, live, n, bucket, t_dispatch, t_done) -> None:
        if not obs.enabled():
            return
        reg = obs.registry()
        reg.counter("serving.batches").inc()
        reg.counter("serving.batched_rows").inc(n)
        reg.counter("serving.padded_rows").inc(bucket - n)
        reg.histogram("serving.batch_fill",
                      bounds=[i / 16 for i in range(1, 17)]).observe(
                          n / bucket)
        h_queue = reg.histogram("serving.latency.queue")
        h_total = reg.histogram("serving.latency.total")
        for r in live:
            h_queue.observe(t_dispatch - r.t_enqueue)
            h_total.observe(t_done - r.t_enqueue)
        reg.histogram("serving.latency.exec").observe(t_done - t_dispatch)


def _count(name: str) -> None:
    if obs.enabled():
        obs.registry().counter(name).inc()
