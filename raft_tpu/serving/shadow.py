"""Shadow-replay quality monitoring for the serving path.

The serving stack's quality loop: a budgeted fraction of admitted query
rows is sampled AFTER their batch completes (the arrays are already
host-side — sampling adds zero device readbacks to the dispatch
thread), re-executed off the hot path at a **ground-truth operating
point** (full coarse probe, no per-probe candidate truncation) against
the SAME index generation that served them, and compared top-k against
top-k.  The per-window estimates, drift checks and operating-point log
live in :mod:`raft_tpu.observability.quality`; this module owns the
sampling, the budget, the replay thread and its pre-warmed executor.

Contracts (the same ones the rest of serving holds):

- **zero steady-state recompiles** — the shadow executor warms its own
  closed (bucket, k) set at the ground-truth params during
  ``Server.start()``, and follows generation swaps by rebuilding its
  table inside ``Server.swap_index`` (already the slow path).  Samples
  from a generation the shadow executor has moved past are dropped
  (``serving.shadow.dropped.generation``) — an estimate never mixes
  generations.
- **zero added host syncs on the request path** — ``offer()`` touches
  only numpy arrays the batcher already read back; the replay's own
  device round-trip happens on the shadow thread.
- **zero cost when disabled** — ``offer()`` is one flag check; with no
  monitor attached the batcher pays one ``None`` check.

Degradation verdicts reuse the integrity layer's canary floor: when the
Wilson lower confidence bound of a (tenant, k) window falls below the
floor, a ``serving.quality.degraded`` flight event fires, and (opt-in)
the generation watchdog takes a strike — live recall loss becomes a
rollback signal with the same machinery as a canary failure.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu import observability as obs
from raft_tpu.core.error import expects
from raft_tpu.observability import flight as _flight
from raft_tpu.observability import quality as _quality
from raft_tpu.serving.admission import TokenBucket
from raft_tpu.serving.buckets import bucket_for


def _count(name: str, n: int = 1) -> None:
    if obs.enabled():
        obs.registry().counter(name).inc(n)


@dataclasses.dataclass
class ShadowConfig:
    """Shadow sampling knobs (docs/api.md "Quality observability").

    ``sample_rows_per_s`` / ``burst_rows`` meter the GLOBAL replay
    budget in query rows per second (the same token-bucket units as
    admission quotas); ``tenant_budgets`` overrides per tenant.  The
    budget bounds replay device work, so the ≤5% overhead gate in CI is
    a configuration property, not luck.  ``max_batch`` caps the shadow
    executor's bucket set — larger sampled batches are truncated.
    ``recall_floor`` defaults to the served index's canary floor (the
    build-time quality contract); ``arm_watchdog`` additionally files an
    integrity strike per degraded window, making sustained live recall
    loss a rollback trigger.  ``ground_truth_params`` overrides the
    derived full-probe operating point (required for index kinds
    without a derivable exact point, e.g. CAGRA).
    """

    sample_rows_per_s: float = 64.0
    burst_rows: float = 128.0
    tenant_budgets: Optional[Dict[str, Tuple[float, float]]] = None
    max_backlog: int = 16
    max_batch: int = 64
    window_s: float = 30.0
    # rows a window needs before a degraded verdict may fire — a 2-row
    # window's lower bound is meaninglessly wide
    min_rows: int = 8
    z: float = _quality.DEFAULT_Z
    recall_floor: Optional[float] = None
    arm_watchdog: bool = False
    op_log_path: Optional[str] = None
    op_log_max_bytes: int = 1 << 20
    op_log_keep: int = 8
    ground_truth_params: Optional[object] = None
    drift: Optional[_quality.DriftThresholds] = None
    track_swaps: bool = True


@dataclasses.dataclass
class ShadowSample:
    """One sampled slice of a served request, host-side."""

    queries: np.ndarray       # (n, dim) as served
    served_ids: np.ndarray    # (n, k) ids the request was answered with
    k: int
    tenant: str
    rung: int
    index: Any                # the generation snapshot that served it
    t: float
    # the request's packed admission bitset (host numpy, (n, n_words))
    # — the ground-truth replay runs under the SAME filter the served
    # answer did, so a selective filter never reads as recall loss
    filter_words: Optional[np.ndarray] = None


def ground_truth_search_params(kind: str, index, params=None):
    """The derived ground-truth operating point for a local executor:
    every coarse list probed, exact coarse ranking, no per-probe
    candidate truncation — the strongest answer the SAME index can give
    (RAFT's recall-vs-reference methodology, with the index itself as
    the reference since raw vectors are gone at serve time)."""
    if kind == "brute_force":
        return None               # already exact
    if kind == "ivf_pq":
        from raft_tpu.neighbors import ivf_pq as _pq
        base = params if params is not None else _pq.SearchParams()
        mode = ("recon" if getattr(index, "list_recon", None) is not None
                else "lut")
        return dataclasses.replace(
            base, n_probes=int(index.n_lists), scan_mode=mode,
            per_probe_topk=0, exact_coarse=True, use_reconstruction=None)
    if kind == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat as _flat
        base = params if params is not None else _flat.SearchParams()
        return dataclasses.replace(base, n_probes=int(index.n_lists))
    raise ValueError(
        f"serving.shadow: no derivable ground-truth operating point for "
        f"executor kind {kind!r} — pass ShadowConfig.ground_truth_params")


class ShadowMonitor:
    """The live quality monitor: sampler + replay thread + estimator.

    Wiring (mirrors ``attach_ingest``)::

        monitor = serving.ShadowMonitor(serving.ShadowConfig(...))
        server.attach_ingest(ingest)      # first, if any — the shadow
        server.attach_shadow(monitor)     # executor shares the delta view
        server.start()                    # warms shadow executables too

    ``attach_shadow`` must run BEFORE ``start()`` (the shadow bucket set
    is part of the warmed-shape contract) and AFTER ``attach_ingest``
    when an ingest tier exists — the ground-truth replay must see the
    same memtable merge the served answer saw, or fresh delta-tier hits
    would read as recall loss."""

    def __init__(self, config: Optional[ShadowConfig] = None, *,
                 clock=time.monotonic) -> None:
        self.config = config or ShadowConfig()
        self._clock = clock
        self._enabled = True
        self._server = None
        self._executor = None
        self._delta_attached = False
        self._budget = TokenBucket(self.config.sample_rows_per_s,
                                   self.config.burst_rows, clock)
        self._tenant_budgets = {
            t: TokenBucket(r, b, clock)
            for t, (r, b) in (self.config.tenant_budgets or {}).items()}
        self.estimator = _quality.RecallEstimator(
            window_s=self.config.window_s, z=self.config.z)
        self.detector = _quality.DriftDetector(self.config.drift)
        self.op_log = (_quality.OperatingPointLog(
            self.config.op_log_path,
            max_bytes=self.config.op_log_max_bytes,
            keep=self.config.op_log_keep)
            if self.config.op_log_path else None)
        self._cond = threading.Condition()
        self._samples: deque = deque()
        self._stop = False
        self._flush_now = False
        self._thread: Optional[threading.Thread] = None
        self._last_flush = clock()
        # queries retained for the window's drift measurement (bounded)
        self._drift_queries: List[np.ndarray] = []
        self._drift_rows = 0
        self.last_records: List[Dict[str, Any]] = []

    # ---- wiring ----------------------------------------------------------

    def bind(self, server) -> None:
        """Attach to a Server (call via ``server.attach_shadow``)."""
        expects(self._server is None,
                "serving.shadow: monitor is already bound to a server")
        self._server = server
        self._executor = self._make_executor(server)

    def _make_executor(self, server):
        from raft_tpu.serving.executor import DistributedExecutor, Executor

        ex = server.executor
        mb = min(int(self.config.max_batch), ex.max_batch)
        if isinstance(ex, DistributedExecutor):
            from raft_tpu.distributed import ann as _ann
            params = (self.config.ground_truth_params
                      or _ann.ground_truth_params(ex.index, ex.params))
            # same handle, same index object: shadow replays route
            # through the same placement map as live traffic
            return DistributedExecutor(ex.handle, ex.index, ks=ex.ks,
                                       max_batch=mb, search_params=params,
                                       failed_shards=ex.failed_shards,
                                       filter_rows=ex.filter_rows)
        params = (self.config.ground_truth_params
                  or ground_truth_search_params(ex.kind, ex.index,
                                                ex.params))
        return Executor(ex.res, ex.kind, ex.index, ks=ex.ks, max_batch=mb,
                        search_params=params, warm=ex.warm,
                        filter_rows=ex.filter_rows)

    @property
    def executor(self):
        return self._executor

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        """Freeze sampling: ``offer()`` becomes one flag check (the
        disabled-cost contract — no lock, no budget read, no copy)."""
        self._enabled = False

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> "ShadowMonitor":
        """Warm the shadow executables and start the replay thread —
        called by ``Server.start()`` after the live executor warms."""
        expects(self._executor is not None,
                "serving.shadow: start before attach_shadow")
        server = self._server
        if (server is not None and server.ingest is not None
                and not self._delta_attached):
            self._executor.attach_delta(server.ingest.memtable.device_view)
            self._delta_attached = True
        n = self._executor.warmup()
        if obs.enabled():
            obs.registry().gauge("serving.shadow.warmed_executables").set(n)
        if self._thread is None:
            self._stop = False
            self._last_flush = self._clock()
            self._thread = threading.Thread(target=self._loop,
                                            name="raft-tpu-serving-shadow",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Drain the backlog, flush the final window, stop the thread."""
        if self._thread is None:
            return
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=30.0)
        self._thread = None
        if self.op_log is not None:
            self.op_log.flush()

    def on_swap(self, new_index) -> None:
        """Follow a generation swap: rebuild the shadow fn table against
        the new generation (inside ``Server.swap_index`` — already the
        slow path), at the ground-truth point re-derived for it."""
        if not self.config.track_swaps or self._executor is None:
            return
        if self.config.ground_truth_params is None:
            from raft_tpu.serving.executor import DistributedExecutor
            if isinstance(self._executor, DistributedExecutor):
                from raft_tpu.distributed import ann as _ann
                self._executor.params = _ann.ground_truth_params(
                    new_index, self._server.executor.params)
            else:
                self._executor.params = ground_truth_search_params(
                    self._executor.kind, new_index,
                    self._server.executor.params)
            self._executor._rung_params = (self._executor.params,)
        self._executor.swap_index(new_index)

    def mark_transition(self) -> None:
        """Flush the window at the next loop tick — called by the
        brownout controller on rung transitions so one operating-point
        record never straddles two rungs."""
        with self._cond:
            self._flush_now = True
            self._cond.notify_all()

    # ---- the sampling hook (dispatch thread — keep it readback-free) -----

    def offer(self, results, k, index, rung: int = 0) -> None:
        """Sample completed requests from one dispatched batch.

        ``results`` is the batcher's ``[(request, distances, ids), ...]``
        with HOST-side arrays — this method must never touch the device
        or read anything back (it runs on the dispatch thread; the
        host-sync lint polices it like the rest of the hot path).
        Disabled: one flag check."""
        if not self._enabled:
            return
        sampled = 0
        backlogged = 0
        for r, _rd, ri in results:
            budget = self._tenant_budgets.get(r.tenant, self._budget)
            if not budget.try_acquire(r.n):
                _count("serving.shadow.skipped.budget", r.n)
                continue
            q = r.queries
            ids = ri
            fw = getattr(r, "filter_words", None)
            if r.ok_rows is not None:
                ok = r.ok_rows
                q = q[ok]
                ids = ids[ok]
                if fw is not None:
                    fw = fw[np.asarray(ok)]
            if q.shape[0] == 0:
                continue
            sample = ShadowSample(queries=q.copy(), served_ids=ids.copy(),
                                  k=k, tenant=r.tenant, rung=rung,
                                  index=index, t=self._clock(),
                                  filter_words=(np.array(fw, np.int32)
                                                if fw is not None else None))
            sampled += sample.queries.shape[0]
            with self._cond:
                self._samples.append(sample)
                while len(self._samples) > self.config.max_backlog:
                    self._samples.popleft()
                    backlogged += 1
                self._cond.notify()
        if sampled:
            _count("serving.shadow.sampled", sampled)
        if backlogged:
            _count("serving.shadow.dropped.backlog", backlogged)

    # ---- the replay thread -----------------------------------------------

    def _loop(self) -> None:
        while True:
            sample = None
            flush_now = False
            with self._cond:
                if not self._samples and not self._stop:
                    self._cond.wait(timeout=0.1)
                if self._samples:
                    sample = self._samples.popleft()
                flush_now, self._flush_now = self._flush_now, False
                stopping = self._stop and sample is None
            if sample is not None:
                self._replay(sample)
            if stopping:
                break
            if (flush_now
                    or self._clock() - self._last_flush
                    >= self.config.window_s):
                self.flush()
        self.flush()

    def _replay(self, sample: ShadowSample) -> None:
        ex = self._executor
        if sample.index is not ex.index:
            # the served generation was swapped out before replay — an
            # estimate must never mix generations, so the sample dies
            _count("serving.shadow.dropped.generation")
            return
        q = sample.queries
        served = sample.served_ids
        fw = sample.filter_words
        if q.shape[0] > ex.max_batch:
            _count("serving.shadow.truncated",
                   q.shape[0] - ex.max_batch)
            q = q[:ex.max_batch]
            served = served[:ex.max_batch]
            if fw is not None:
                fw = fw[:ex.max_batch]
        n = int(q.shape[0])
        bucket = bucket_for(n, ex.max_batch)
        buf = np.zeros((bucket, ex.dim), dtype=ex.query_dtype)
        buf[:n] = q
        # filtered-recall accounting: the ground truth is computed under
        # the SAME admission bitset the served answer used — padded rows
        # get all-ones (they are sliced away below)
        fwords = None
        if fw is not None:
            nw = int(ex.n_filter_words)
            fbuf = np.full((bucket, nw), -1, dtype=np.int32)
            fbuf[:n] = fw
            fwords = jnp.asarray(fbuf)
            _count("serving.shadow.replayed.filtered", n)
        with obs.stage("serving.shadow.replay"):
            _d, i = ex.search_bucket(jnp.asarray(buf), n, sample.k, rung=0,
                                     filter_words=fwords)
            gt = np.asarray(i)[:n]
        hits = total = 0
        h_sample = (obs.registry().histogram("serving.quality.sample_recall")
                    if obs.enabled() else None)
        for row in range(n):
            g = gt[row]
            g = g[g >= 0]
            if g.size == 0:
                continue
            s = served[row]
            s = s[s >= 0]
            h = int(np.intersect1d(s, g).size)
            hits += h
            total += int(g.size)
            if h_sample is not None:
                h_sample.observe(h / g.size)
        if total:
            self.estimator.record(sample.tenant, sample.k, hits, total,
                                  rows=n)
        _count("serving.shadow.replayed", n)
        if self._drift_rows < self.config.max_batch:
            self._drift_queries.append(q)
            self._drift_rows += n

    # ---- the window flush ------------------------------------------------

    def _floor(self) -> Optional[float]:
        if self.config.recall_floor is not None:
            return float(self.config.recall_floor)
        if self._server is None:
            return None
        from raft_tpu.integrity import canary as _canary
        return _canary.floor_of(self._server.executor.index)

    def flush(self) -> List[Dict[str, Any]]:
        """Close the current window: export gauges, emit degraded /
        drift verdicts, append operating-point records.  Runs on the
        replay thread (or synchronously from tests / bench)."""
        self._last_flush = self._clock()
        server = self._server
        ests = self.estimator.estimates()
        overall = self.estimator.estimate()
        latency = None
        if obs.enabled():
            reg = obs.registry()
            reg.counter("serving.quality.windows").inc()
            latency = reg.histogram("serving.latency.total").windowed_dict()
            if overall is not None:
                reg.gauge("serving.quality.recall").set(overall.recall)
                reg.gauge("serving.quality.recall_lo").set(overall.lo)
                reg.gauge("serving.quality.recall_hi").set(overall.hi)
                reg.gauge("serving.quality.samples").set(overall.rows)
            for (tenant, _k), est in ests.items():
                reg.gauge(f"serving.quality.recall.{tenant}").set(est.recall)
        floor = self._floor()
        p99 = (float(latency["p99"])
               if latency and latency.get("count") else None)
        records: List[Dict[str, Any]] = []
        for (tenant, k), est in ests.items():
            rec = {"tenant": tenant, "k": k, **est.as_dict(),
                   "p99_s": p99, "floor": floor}
            rec["degraded"] = bool(
                floor is not None and est.rows >= self.config.min_rows
                and est.lo < floor)
            records.append(rec)
            if rec["degraded"]:
                # always-on anomaly event: the live-quality analogue of
                # integrity.canary_failure, with the CI bound that fired
                _flight.record_event("serving.quality.degraded",
                                     tenant=tenant, k=k,
                                     recall=est.recall, lo=est.lo,
                                     hi=est.hi, rows=est.rows, floor=floor)
                _count("serving.quality.degraded")
                if self.config.arm_watchdog and server is not None:
                    server.note_integrity_strike(
                        f"shadow recall lower bound {est.lo:.3f} < floor "
                        f"{floor:.3f} (tenant {tenant!r}, k={k})")
        self.last_records = records
        if server is None:
            self._drift_queries, self._drift_rows = [], 0
            return records
        # drift + op-point log share one probe-stats measurement over
        # the window's sampled queries (off the hot path — syncs fine)
        index = server.executor.index
        knobs = server.executor.operating_knobs(server.brownout.rung)
        queries = (np.concatenate(self._drift_queries)
                   if self._drift_queries else None)
        self._drift_queries, self._drift_rows = [], 0
        probe_stats = None
        n_probes = knobs.get("n_probes")
        if queries is not None and n_probes:
            probe_stats = _quality.measure_probe_stats(
                index, queries[:self.config.max_batch], n_probes)
        memtable = (server.ingest.memtable
                    if server.ingest is not None else None)
        self.detector.check(index=index, memtable=memtable,
                            probe_stats=probe_stats)
        if self.op_log is not None and ests:
            from raft_tpu.neighbors import mutate as _mutate
            gen = _mutate.generation(index)
            for (tenant, k), est in ests.items():
                kn = dict(knobs)
                kn["k"] = int(k)
                measured = est.as_dict()
                if latency and latency.get("count"):
                    for qtile in ("p50", "p95", "p99"):
                        measured[qtile] = float(latency[qtile])
                if probe_stats and "probed_rows_per_query" in probe_stats:
                    measured["scan_rows"] = (
                        probe_stats["probed_rows_per_query"])
                self.op_log.append(_quality.OpPoint(
                    t=time.time(), generation=gen, knobs=kn,
                    measured=measured, tenant=tenant))
        return records

    # ---- introspection ---------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        return {
            "enabled": self._enabled,
            "backlog": len(self._samples),
            "estimates": {f"{t}/k={k}": e.as_dict()
                          for (t, k), e in self.estimator.estimates().items()},
            "records": list(self.last_records),
        }
