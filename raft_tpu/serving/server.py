"""The online serving front end — submit/search over a warmed executor.

Wiring: ``submit()`` boundary-validates the request (per-request batch,
the PR 4 contract), stamps it with the enqueue time, and offers it to
the admission queue (shedding / quotas / deadline checks live there).
The dynamic batcher's dispatcher thread coalesces queued requests into
padded bucket batches and completes each request's Future.

Lifecycle::

    server = serving.Server(executor, serving.ServerConfig(...))
    server.start()                      # warms every bucket (AOT)
    fut = server.submit(q, k=10)        # -> Future[(distances, indices)]
    d, i = server.search(q, k=10)       # submit + wait
    server.stop()

Zero-recompile contract: ``start()`` warms every (bucket, k) executable;
afterwards the ``xla.compiles`` counter stays flat under any traffic mix
that respects the closed shape set (asserted by the serving bench / CI
smoke).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from raft_tpu import observability as obs
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.integrity import IntegrityError
from raft_tpu.integrity import boundary as _boundary
from raft_tpu.observability import flight as _flight
from raft_tpu.observability import trace as _trace
from raft_tpu.resilience.retry import Deadline
from raft_tpu.serving.admission import AdmissionQueue, Overloaded, Request
from raft_tpu.serving.batcher import DynamicBatcher
from raft_tpu.serving.brownout import BrownoutState


def _host_filter_words(filter, n: int, nw: int) -> np.ndarray:
    """Normalize a per-request filter to HOST-side ``(n, nw)`` packed
    int32 words.  Accepts a :class:`~raft_tpu.filters.SampleFilter` (one
    row broadcasts to the request) or a bool mask (``(n_rows,)`` or
    ``(n, n_rows)``).  Narrower filters zero-pad — ids beyond the
    filter's coverage stay rejected, matching the device-side coverage
    check in :func:`raft_tpu.filters.bitset.query_bits`."""
    from raft_tpu.filters import SampleFilter
    if isinstance(filter, SampleFilter):
        w = np.asarray(filter.words).astype(np.int32, copy=False)
    else:
        m = np.asarray(filter, dtype=bool)
        if m.ndim == 1:
            m = m[None, :]
        expects(m.ndim == 2,
                "serving: filter mask must be 1-D or (n, n_rows)")
        pad = (-m.shape[1]) % 32
        if pad:
            m = np.pad(m, ((0, 0), (0, pad)))
        w = np.packbits(m, axis=1, bitorder="little").view(np.int32)
    expects(w.shape[0] in (1, n),
            f"serving: filter has {w.shape[0]} rows for a {n}-row request")
    expects(w.shape[1] <= nw,
            f"serving: filter coverage ({w.shape[1]} words) exceeds the "
            f"executor's filter_rows bound ({nw} words)")
    if w.shape[1] < nw:
        w = np.pad(w, ((0, 0), (0, nw - w.shape[1])))
    if w.shape[0] == 1 and n > 1:
        w = np.broadcast_to(w, (n, nw))
    return w


@dataclasses.dataclass
class ServerConfig:
    """Serving knobs (see docs/api.md "Serving" for sizing guidance).

    ``max_wait_us`` is the latency the batcher may spend waiting to fill
    a bucket — it bounds added p99; size it well below the latency SLO.
    ``max_queue_rows`` bounds queue memory and worst-case queueing delay;
    beyond it, submissions shed with :class:`Overloaded`.
    ``tenant_quotas`` maps tenant -> (rate_rows_per_s, burst_rows).
    """

    max_batch: int = 1024
    max_wait_us: float = 2000.0
    max_queue_rows: int = 8192
    tenant_quotas: Optional[Dict[str, Tuple[float, float]]] = None
    # tenant NAMESPACES (round 20): a raft_tpu.filters.TenantFilter
    # mapping tenant -> disjoint id range.  When set, every submit's
    # tenant= resolves to its namespace bitset (ANDed with any request
    # filter) so a tenant can only ever surface its own ids; requires an
    # executor constructed with filter_rows > 0.
    tenants: Optional[object] = None
    # default per-request deadline (seconds); None = no deadline
    default_deadline_s: Optional[float] = None
    # generation watchdog (auto-rollback): N integrity strikes within
    # rollback_window_s seconds swap back to the retained last-known-good
    # index generation.  0 disables the watchdog.
    rollback_strikes: int = 0
    rollback_window_s: float = 30.0


class Server:
    """Online request path over one warmed :class:`Executor`."""

    def __init__(self, executor, config: Optional[ServerConfig] = None
                 ) -> None:
        self.executor = executor
        self.config = config or ServerConfig()
        expects(self.config.max_batch <= executor.max_batch,
                "serving: config.max_batch exceeds the executor's bucket set")
        # one BrownoutState shared with admission and the batcher: the
        # controller (serving.brownout) writes it, the hot path reads it
        # lock-free.  Level 0 with no controller attached — a plain
        # server behaves exactly as before.
        self.brownout = BrownoutState()
        if self.config.tenants is not None:
            expects(getattr(executor, "n_filter_words", 0) > 0,
                    "serving: tenant namespaces need a filter-configured "
                    "executor — construct with filter_rows=<id bound>")
        self.queue = AdmissionQueue(self.config.max_queue_rows,
                                    self.config.tenant_quotas,
                                    brownout=self.brownout)
        self.batcher = DynamicBatcher(self.queue, executor,
                                      max_batch=self.config.max_batch,
                                      max_wait_us=self.config.max_wait_us,
                                      brownout=self.brownout,
                                      on_error=self._on_batch_error)
        self._started = False
        self.ingest = None          # durable write path (attach_ingest)
        self.shadow = None          # quality monitor (attach_shadow)
        # generation watchdog state: the last-known-good index retained
        # by swap_index, and the strike timestamps within the window
        self._last_good = None
        self._strikes: List[float] = []
        self._watchdog_lock = threading.Lock()

    # ---- lifecycle ------------------------------------------------------

    def start(self) -> "Server":
        """Warm every bucket executable, then start dispatching."""
        with obs.stage("serving.warmup") as st:
            n = self.executor.warmup()
            st.fence()
        if obs.enabled():
            obs.registry().gauge("serving.warmed_executables").set(n)
        if self.shadow is not None:
            # the shadow executor warms its own (bucket, k) set at the
            # ground-truth params — part of the same pre-start compile
            # budget, so steady state stays recompile-free with the
            # monitor on
            self.shadow.start()
            self.batcher.shadow = self.shadow
        self.batcher.start()
        self._started = True
        return self

    def stop(self, drain: bool = True) -> None:
        self.batcher.stop(drain=drain)
        if self.shadow is not None:
            self.shadow.stop()
        self._started = False

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def attach_ingest(self, ingest) -> "Server":
        """Attach a durable write path (:class:`serving.IngestServer`)
        BEFORE :meth:`start`: binding joins the memtable's device view to
        the executor's delta-merge seam (part of every warmed shape) and
        routes fold publications through :meth:`swap_index`.  Run
        ``ingest.recover(...)`` first — writes refuse until recovery has
        replayed the WAL."""
        expects(not self._started,
                "serving: attach_ingest after start would break the "
                "zero-recompile contract — attach before Server.start()")
        self.ingest = ingest
        ingest.bind(self)
        return self

    def attach_shadow(self, monitor) -> "Server":
        """Attach a live quality monitor
        (:class:`serving.ShadowMonitor`) BEFORE :meth:`start` — its
        ground-truth executables join the warmed closed-shape set — and
        AFTER :meth:`attach_ingest` when an ingest tier exists, so the
        shadow replay merges the same memtable view the served answers
        saw.  The batcher then offers every completed batch's host-side
        results to the monitor's sampler (one flag check per batch when
        sampling is off)."""
        expects(not self._started,
                "serving: attach_shadow after start would break the "
                "zero-recompile contract — attach before Server.start()")
        monitor.bind(self)
        self.shadow = monitor
        return self

    def write(self, ids, vectors=None, *, op: str = "upsert",
              tenant: str = "default") -> int:
        """Durably ingest one upsert/delete batch; returns the record's
        LSN once it is fsync-durable AND searchable (see
        :meth:`serving.IngestServer.write` for the ack contract and the
        :class:`Overloaded` shed taxonomy)."""
        expects(self.ingest is not None,
                "serving: no ingest tier attached — Server.write needs "
                "attach_ingest before start()")
        return self.ingest.write(ids, vectors, op=op, tenant=tenant)

    def swap_index(self, new_index) -> int:
        """Swap the executor onto a new index generation while serving.

        Delegates to :meth:`Executor.swap_index`: a complete replacement
        executable table is built and warmed against ``new_index`` before
        one atomic publish, so requests in flight finish on the
        generation they started on, later requests see only the new one,
        and steady-state traffic after the swap triggers zero recompiles.
        The swapped-out index is RETAINED as the last-known-good
        generation for the watchdog (see :meth:`note_integrity_strike`),
        and the strike window resets — strikes against the old
        generation must not indict the new one.  Returns the number of
        bucket executables built."""
        old = self.executor.index
        with obs.stage("serving.generation_swap") as st:
            n = self.executor.swap_index(new_index)
            st.fence()
        if self.shadow is not None:
            # rebuild the shadow table against the new generation (still
            # on the swap path); backlog samples from the old generation
            # drop rather than replay cross-generation
            self.shadow.on_swap(new_index)
        with self._watchdog_lock:
            self._last_good = old
            self._strikes.clear()
        return n

    # ---- generation watchdog (auto-rollback) ----------------------------

    def _on_batch_error(self, exc: BaseException) -> None:
        # integrity failures are the watchdog's signal: a bad generation
        # corrupts results; transient executor errors (OOM, interrupt)
        # are the retry layer's problem, not a generation's guilt
        if isinstance(exc, IntegrityError):
            self.note_integrity_strike(f"batch_error: {exc}")

    def check_canary(self, res) -> bool:
        """Run the canary health check against the CURRENT generation;
        a floor violation is one watchdog strike.  Returns True when the
        index passes (or carries no canaries).  Call this from the ops
        loop (or a rebalancer hook) after swaps — sustained post-swap
        canary failure is exactly the regime auto-rollback exists for."""
        from raft_tpu.integrity import canary as _canary
        report = _canary.health_check(res, self.executor.index,
                                      raise_on_fail=False)
        if report is not None and not report.ok:
            self.note_integrity_strike(
                f"canary: recall {report.recall:.3f} < floor "
                f"{report.floor:.3f}")
            return False
        return True

    def note_integrity_strike(self, reason: str) -> bool:
        """Record one integrity strike against the current generation;
        on the Nth strike (``rollback_strikes``) within
        ``rollback_window_s``, swap back to the retained last-known-good
        generation.  Returns True when this strike triggered the
        rollback."""
        limit = self.config.rollback_strikes
        if limit <= 0:
            return False
        now = time.monotonic()
        if obs.enabled():
            obs.registry().counter("serving.integrity_strikes").inc()
        with self._watchdog_lock:
            horizon = now - self.config.rollback_window_s
            self._strikes = [t for t in self._strikes if t > horizon]
            self._strikes.append(now)
            n_strikes = len(self._strikes)
            if n_strikes < limit or self._last_good is None:
                return False
            # rollback: take the retained generation and clear it so a
            # still-failing environment cannot ping-pong the swap —
            # the NEXT rollback needs a NEW good generation first
            target, self._last_good = self._last_good, None
            self._strikes.clear()
        bad_gen = getattr(self.executor.index, "generation", None)
        with obs.stage("serving.generation_swap") as st:
            self.executor.swap_index(target)
            st.fence()
        if self.shadow is not None:
            self.shadow.on_swap(target)
        if obs.enabled():
            obs.registry().counter("serving.auto_rollbacks").inc()
        # always-on flight event: THE post-mortem marker — which
        # generation was indicted, by how many strikes, and why
        _flight.record_event("serving.auto_rollback",
                             bad_generation=bad_gen,
                             restored_generation=getattr(
                                 target, "generation", None),
                             strikes=n_strikes, reason=reason)
        return True

    # ---- request path ---------------------------------------------------

    def submit(self, queries, k: Optional[int] = None, *,
               tenant: str = "default",
               deadline: Optional[Deadline] = None,
               filter=None) -> Future:
        """Enqueue one request; returns a Future resolving to
        ``(distances, indices)`` of shape (n, k).

        Raises :class:`Overloaded` / :class:`QuotaExceeded` when shed at
        admission; the Future fails with
        :class:`~raft_tpu.resilience.retry.DeadlineExceededError` when
        the deadline expires while queued.  Under validation policy
        ``mask``, non-finite query rows resolve to id -1 / worst
        distance (the integrity mask path).

        ``filter`` (round 20): a per-request admission predicate — a
        :class:`~raft_tpu.filters.SampleFilter` or a bool mask over
        global row ids (one row broadcasts to the request; (n, n_rows)
        applies per query).  Needs an executor constructed with
        ``filter_rows > 0``.  With :attr:`ServerConfig.tenants`
        configured, the request's ``tenant=`` resolves to its namespace
        bitset and is ANDed in — a tenant can only surface its own ids
        regardless of the request filter.  Filters are data, not shape:
        they ride the queue host-side and never change the warmed
        bucket executables (zero steady-state recompiles).
        """
        expects(self._started, "serving: server not started")
        # per-request trace: minted HERE, at the front door, so spans from
        # admission / queue / batch / exec all hang off one trace id.  One
        # flag check when tracing is off.
        rt = _trace.start_request() if _trace.tracing() else None
        t_sub = rt.t0 if rt is not None else 0.0
        k = int(k) if k is not None else self.executor.ks[0]
        expects(k in self.executor.ks,
                f"serving: k={k} is not in the warmed set {self.executor.ks}")
        # requests stay HOST-side through admission: their shapes are
        # unbounded, so validation runs the numpy twin of the boundary
        # guard (host=True) and the batcher assembles the device batch
        # at the fixed bucket shape — no per-request device work
        if not isinstance(queries, np.ndarray):
            queries = ensure_array(queries, "queries")
        if queries.ndim == 1:
            queries = queries[None, :]
        queries = np.asarray(queries)
        queries, ok_rows = _boundary.check_matrix(
            queries, "queries", site="serving.submit",
            dim=self.executor.dim, allow_empty=False, host=True)
        expects(queries.ndim == 2 and queries.shape[1] == self.executor.dim,
                "serving.submit: query dim mismatch")
        n = int(queries.shape[0])
        if n > self.config.max_batch:
            raise Overloaded(
                f"serving: request of {n} rows exceeds max_batch="
                f"{self.config.max_batch}; split the request")
        if deadline is None and self.config.default_deadline_s is not None:
            deadline = Deadline(self.config.default_deadline_s)
        # per-request admission bitset: normalized host-side (numpy) so
        # the queue carries no device arrays; the tenant namespace ANDs
        # in last, making isolation non-bypassable by the request filter
        nw = getattr(self.executor, "n_filter_words", 0)
        fw = None
        if filter is not None:
            expects(nw > 0,
                    "serving: executor not configured for filters — "
                    "construct with filter_rows=<id bound>")
            fw = _host_filter_words(filter, n, nw)
        if self.config.tenants is not None:
            tw = self.config.tenants.words_for(tenant)
            expects(tw.size == nw,
                    "serving: tenant namespace width "
                    f"({tw.size} words) != executor filter width ({nw}) "
                    "— configure TenantFilter with n_rows=filter_rows")
            fw = (np.broadcast_to(tw, (n, nw)) if fw is None
                  else fw & tw[None, :])
        req = Request(queries=queries, k=k, tenant=tenant,
                      deadline=deadline, future=Future(), n=n,
                      t_enqueue=time.monotonic(), ok_rows=ok_rows,
                      trace=rt, filter_words=fw)
        if rt is not None:
            rt.annotate("tenant", tenant)
            rt.annotate("rows", n)
            rt.annotate("k", k)
            if fw is not None:
                rt.annotate("filtered", True)
            # a degraded bucket stamps every trace — including one shed
            # below — with the level that served (or refused) it
            lvl = self.brownout.level
            if lvl:
                rt.annotate("brownout_level", lvl)
        try:
            self.queue.offer(req)
        except Overloaded:
            if rt is not None:
                # shed at the door: the trace still lands in the flight
                # recorder (the shed event itself is recorded by offer())
                rt.span("serving.admission", t_sub, _trace.now())
                rt.annotate("shed", True)
                _flight.record_trace(rt.close())
            raise
        if rt is not None:
            rt.span("serving.admission", t_sub, _trace.now())
        return req.future

    def search(self, queries, k: Optional[int] = None, *,
               tenant: str = "default",
               deadline: Optional[Deadline] = None,
               timeout: Optional[float] = None,
               filter=None):
        """Synchronous convenience: ``submit(...).result(timeout)``."""
        return self.submit(queries, k, tenant=tenant, deadline=deadline,
                           filter=filter).result(timeout=timeout)

    # ---- routing maintenance --------------------------------------------

    def refresh_routing(self) -> int:
        """Fold the executor's pending probe histograms into the
        routing policy's heat window (the maintenance-path host read —
        the dispatch path only retains lazy device arrays).  Call from
        the ops / rebalancer cadence; returns the number of batches
        folded (0 with no policy attached)."""
        routing = getattr(self.executor, "routing", None)
        if routing is None:
            return 0
        return routing.refresh()

    # ---- introspection --------------------------------------------------

    def stats(self) -> Dict[str, object]:
        """Point-in-time serving stats (cheap; registry-backed numbers
        appear only while collection is enabled)."""
        snap = obs.snapshot() if obs.enabled() else {}
        routing = getattr(self.executor, "routing", None)
        return {
            "queue_rows": self.queue.rows,
            "queue_requests": len(self.queue),
            "buckets": list(self.executor.buckets),
            "ks": list(self.executor.ks),
            "brownout_level": self.brownout.level,
            "routing": routing.stats() if routing is not None else None,
            "counters": {name: v
                         for name, v in snap.get("counters", {}).items()
                         if name.startswith(("serving.", "xla."))},
            "histograms": {name: {q: h[q] for q in ("count", "p50", "p95",
                                                    "p99")}
                           for name, h in snap.get("histograms", {}).items()
                           if name.startswith("serving.")},
        }
