"""Bucket policy — the closed shape set the executors are warmed for.

The jitted/AOT search executables are shape-specialized (core/aot.py):
every distinct (batch, k) pair is a separate compilation.  Serving
therefore admits only a *closed* set of batch shapes — powers of two up
to ``max_batch`` — and pads every cut batch up to its bucket.  The
warmup pass at server start compiles each bucket once, so steady state
sees zero recompiles no matter how request sizes fluctuate.

Padding rows are zeros; their outputs are flagged through the SAME mask
path the integrity boundary uses for non-finite rows
(:func:`raft_tpu.integrity.boundary.mask_search_outputs`): id -1 and the
worst distance for the metric.  A padded row can never be confused with
a real answer.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects


def bucket_sizes(max_batch: int, min_bucket: int = 1) -> Tuple[int, ...]:
    """The closed bucket set: powers of two in [min_bucket, max_batch].

    ``max_batch`` itself is always included even when it is not a power
    of two (it is the shape the closed-loop peak runs at).
    """
    expects(max_batch >= 1, "serving: max_batch must be >= 1")
    expects(min_bucket >= 1, "serving: min_bucket must be >= 1")
    out = []
    b = 1
    while b <= max_batch:
        if b >= min_bucket:
            out.append(b)
        b *= 2
    if not out or out[-1] != max_batch:
        out.append(max_batch)
    return tuple(out)


def bucket_for(n: int, max_batch: int, min_bucket: int = 1) -> int:
    """Smallest bucket holding ``n`` rows (n must be <= max_batch)."""
    expects(1 <= n <= max_batch,
            f"serving: batch of {n} rows exceeds max_batch={max_batch}")
    for b in bucket_sizes(max_batch, min_bucket):
        if b >= n:
            return b
    return max_batch


def pad_rows(x, bucket: int):
    """Zero-pad (n, dim) -> (bucket, dim); returns the input unchanged
    when it already fills the bucket."""
    n = x.shape[0]
    if n == bucket:
        return x
    return jnp.pad(x, ((0, bucket - n), (0, 0)))


def valid_rows_mask(n_valid: int, bucket: int) -> jnp.ndarray:
    """Bool (bucket,) vector, True for real rows — the ``ok_rows``
    contract of :func:`raft_tpu.integrity.boundary.mask_search_outputs`."""
    return jnp.asarray(np.arange(bucket) < n_valid)
