"""Bucket-shaped search executors over the existing indexes.

One executor wraps one searchable index (IVF-PQ / IVF-Flat / CAGRA /
brute force, or a :mod:`raft_tpu.distributed.ann` sharded index) and
serves a CLOSED set of (batch, k) shapes — the buckets.  ``warmup()``
compiles every bucket once at server start; after that, a dispatch at
any bucket shape is a cache hit (zero recompiles, the steady-state
contract the serving bench asserts via the ``xla.compiles`` counter).

Two warm paths:

``"aot"`` (default for IVF-PQ / IVF-Flat / brute force)
    Executables come from :func:`raft_tpu.core.aot.executables` — the
    index is exported per bucket (StableHLO) and reloaded, the same
    artifact shape a compile-free deployment process would load.  Falls
    back to ``"jit"`` per bucket when an exporter refuses (e.g. CAGRA's
    calibration-dependent fallback walk).
``"jit"``
    The live module search functions, warmed by calling each bucket
    shape once.  The choice for distributed indexes (the cross-shard
    merge is a shard_map closure over a mesh, not exportable) —
    degraded-mode shard masking and post-load ``health_check`` compose
    unchanged because the executor calls the same public entry points.
    Since round 10 group construction under the routed path is
    shape-static (a static group capacity rides in the compiled shape
    instead of a host-synced count), so a warmed distributed bucket
    dispatches with ZERO host syncs — same steady-state contract as the
    local AOT path.  Per-shard routed programs (including the fused
    grouped scan at static capacity) ARE exportable individually via
    :class:`~raft_tpu.core.aot.ExecutableCache` kind ``"ivf_pq_routed"``
    — see :meth:`DistributedExecutor.prewarm_shard_artifacts`.

Padded rows are flagged through the integrity mask path
(:func:`~raft_tpu.integrity.boundary.mask_search_outputs`): id -1 /
worst distance, exactly like a masked non-finite row.
"""

from __future__ import annotations

import warnings
from typing import Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import observability as obs
from raft_tpu.core.aot import executables as _aot_executables
from raft_tpu.core.error import expects
from raft_tpu.observability import flight as _flight
from raft_tpu.distance.types import DistanceType
from raft_tpu.filters import SampleFilter
from raft_tpu.filters import bitset as _fbits
from raft_tpu.integrity import boundary as _boundary
from raft_tpu.neighbors import delta as _delta
from raft_tpu.serving.buckets import bucket_sizes, pad_rows, valid_rows_mask

_KINDS = ("ivf_pq", "ivf_flat", "cagra", "brute_force")


class Executor:
    """Warmed bucket-shaped search over one local index.

    ``search_params`` is the algorithm's SearchParams (n_probes etc.) —
    fixed for the executor's lifetime, part of every bucket's compiled
    shape.  ``ks`` is the closed set of supported k values.

    ``ladder`` (brownout, PR 12) is an optional sequence of ADDITIONAL
    SearchParams variants — the degraded operating points the brownout
    controller steps through under overload.  Rung 0 is always
    ``search_params`` (full quality); rung ``i`` serves ``ladder[i-1]``.
    The rung set is closed and part of every warmed shape: ``warmup()``
    compiles every (bucket, k, rung) once, and the rung joins the AOT
    cache key (see :meth:`ExecutableCache.get`), so a brownout
    transition is a dict lookup — zero recompiles, zero host syncs.
    """

    def __init__(self, res, kind: str, index, *, ks: Sequence[int] = (10,),
                 max_batch: int = 1024, search_params=None,
                 ladder: Sequence = (), warm: str = "aot",
                 filter_rows: int = 0) -> None:
        expects(kind in _KINDS,
                f"serving: unknown executor kind {kind!r} (one of {_KINDS})")
        expects(warm in ("aot", "jit"),
                f"serving: warm mode must be 'aot' or 'jit', got {warm!r}")
        self.res = res
        self.kind = kind
        self.index = index
        self.ks = tuple(int(k) for k in ks)
        self.max_batch = int(max_batch)
        self.params = search_params
        self._rung_params: Tuple = (search_params, *ladder)
        self.warm = warm
        # filtered serving (PR 20): filter_rows > 0 declares the id
        # space admission bitsets cover; every warmed executable then
        # takes a (bucket, n_filter_words) int32 words input — data, not
        # shape, so one compiled program serves every predicate
        # (all-ones words = unfiltered).  0 keeps the one-input shapes.
        self.filter_rows = int(filter_rows)
        self.n_filter_words = (_fbits.n_words_for(self.filter_rows)
                               if self.filter_rows else 0)
        self._ones_words: Dict[int, jax.Array] = {}
        self.buckets = bucket_sizes(self.max_batch)
        self._fns: Dict[Tuple[int, int, int], Callable] = {}
        self._delta = None
        self._warmed = False

    @property
    def n_rungs(self) -> int:
        """Number of degradation-ladder operating points (>= 1)."""
        return len(self._rung_params)

    def set_ladder(self, ladder: Sequence) -> None:
        """Install the degraded-rung SearchParams variants (rungs 1..N).
        Must happen before :meth:`warmup` — the rung set is part of the
        closed warmed-shape contract, so growing it later would put a
        compile on the serving path."""
        expects(not self._warmed,
                "serving: set_ladder after warmup would break the "
                "zero-recompile contract — declare the ladder before "
                "Server.start()")
        self._rung_params = (self.params, *ladder)

    def attach_delta(self, view: Callable) -> None:
        """Attach the streaming-ingest delta tier: ``view`` is a
        zero-arg callable (``Memtable.device_view``) returning the
        shape-static ``(data, ids, tombs)`` snapshot.  Every
        :meth:`search_bucket` then merges the memtable as one more
        "shard" through the k-bounded ``finalize_topk`` epilogue, with
        tombstones masking main-index hits through the id<0 seam.  Must
        happen before :meth:`warmup` — the merge joins every warmed
        bucket shape, keeping steady state compile-free."""
        expects(not self._warmed,
                "serving: attach_delta after warmup would break the "
                "zero-recompile contract — attach the ingest tier before "
                "Server.start()")
        self._delta = view

    # ---- geometry -------------------------------------------------------

    @property
    def dim(self) -> int:
        return self._index_dim(self.index)

    def _index_dim(self, index) -> int:
        if self.kind == "brute_force":
            return int(index.shape[1])
        return int(index.dim)

    @property
    def select_min(self) -> bool:
        metric = getattr(self.index, "metric", DistanceType.L2Expanded)
        return metric != DistanceType.InnerProduct

    @property
    def query_dtype(self):
        if self.kind == "brute_force":
            return self.index.dtype
        if self.kind == "cagra":
            return self.index.dataset.dtype
        return self.index.centers.dtype

    # ---- warmup ---------------------------------------------------------

    def warmup(self) -> int:
        """Compile every (bucket, k, rung) once; returns the number of
        warmed executables.  Idempotent."""
        if self._warmed:
            return len(self._fns)
        for b in self.buckets:
            for k in self.ks:
                for r in range(self.n_rungs):
                    zeros = jnp.zeros((b, self.dim), self.query_dtype)
                    # b-1 valid rows also warms the padded-row mask ops at
                    # this bucket shape (mask shape is n_valid-independent)
                    d, i = self.search_bucket(zeros, max(1, b - 1), k,
                                              rung=r)
                    jax.block_until_ready((d, i))
                    if obs.enabled():
                        obs.registry().counter(
                            "serving.warmed_executables").inc()
        self._warmed = True
        return len(self._fns)

    def _obtain(self, bucket: int, k: int, rung: int = 0) -> Callable:
        key = (bucket, k, rung)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        fn = self._build_fn(self.index, bucket, k, rung)
        self._fns[key] = fn
        return fn

    def _build_fn(self, index, bucket: int, k: int, rung: int = 0
                  ) -> Callable:
        """One bucket executable against an EXPLICIT index — the builder
        :meth:`swap_index` uses to assemble a replacement table without
        touching the published one."""
        params = self._rung_params[rung]
        fn = None
        if self.warm == "aot":
            try:
                fn = self._aot_fn(index, bucket, k, params, rung)
            except Exception as e:  # noqa: BLE001 - exporter refusal
                warnings.warn(
                    f"serving: AOT export failed for {self.kind} bucket "
                    f"({bucket}, {k}, rung {rung}) — falling back to live "
                    f"search: {e}",
                    stacklevel=2)
        if fn is None:
            fn = self._live_fn(index, k, params)
        return fn

    def _aot_fn(self, index, bucket: int, k: int, params, rung: int
                ) -> Callable:
        cache = _aot_executables()
        # the filter-buffer width joins the export kwargs (and so the
        # cache key) — its shape depends only on the declared id bound,
        # never on filter contents, so the key stays bucket-shaped
        fkw = ({"n_filter_words": self.n_filter_words}
               if self.n_filter_words else {})
        if self.kind == "ivf_pq":
            from raft_tpu.ops import vmem_budget as vb
            n_probes = min(params.n_probes, index.n_lists)
            mode = getattr(params, "scan_mode", "auto")
            if mode not in ("recon", "codes", "lut", "fused"):
                mode = ("recon" if index.list_recon is not None
                        else "lut")
            # the merge window is an export specialization like the
            # bucket shape: it rides the ExecutableCache key (via the
            # sorted export kwargs) so warmup compiles one executable
            # per (bucket, k, merge_window) point and a steady-state
            # window change can never alias onto a warm entry
            mw = vb.merge_window_request(
                getattr(params, "merge_window", "auto"))
            return cache.get("ivf_pq", self.res, index, batch=bucket,
                             k=k, n_probes=n_probes, scan_mode=mode,
                             rung=rung, merge_window=mw, **fkw)
        if self.kind == "ivf_flat":
            n_probes = min(params.n_probes, index.n_lists)
            return cache.get("ivf_flat", self.res, index, batch=bucket,
                             k=k, n_probes=n_probes, rung=rung, **fkw)
        if self.kind == "brute_force":
            return cache.get("brute_force", self.res, index,
                             batch=bucket, k=k, rung=rung, **fkw)
        # cagra: export when the packed walk calibrates, else live
        itopk = max(getattr(params, "itopk_size", 64), k)
        width = getattr(params, "search_width", 1)
        return cache.get("cagra", self.res, index, batch=bucket, k=k,
                         rung=rung, itopk=itopk, search_width=width,
                         **fkw)

    def _live_fn(self, index, k: int, params) -> Callable:
        # live module entry points under validation policy "off": the
        # server already boundary-checked each request at submit, and
        # padded zero rows must not be re-flagged.  The closure captures
        # the index ARGUMENT (not self.index) so a built fn table stays
        # pinned to the generation it was built against.
        from raft_tpu import config

        if self.kind == "ivf_pq":
            from raft_tpu.neighbors import ivf_pq as mod
        elif self.kind == "ivf_flat":
            from raft_tpu.neighbors import ivf_flat as mod
        elif self.kind == "cagra":
            from raft_tpu.neighbors import cagra as mod
        else:
            from raft_tpu.neighbors import brute_force

            if self.n_filter_words:
                n_rows = self.filter_rows

                def bf_f(queries, fw):
                    with config.validation_policy("off"):
                        return brute_force.knn(
                            self.res, index, queries, k,
                            filter=SampleFilter.from_words(fw, n_rows))
                return bf_f

            def bf(queries):
                with config.validation_policy("off"):
                    return brute_force.knn(self.res, index, queries, k)
            return bf

        if self.n_filter_words:
            n_rows = self.filter_rows

            def live_f(queries, fw):
                with config.validation_policy("off"):
                    return mod.search(
                        self.res, params, index, queries, k,
                        filter=SampleFilter.from_words(fw, n_rows))
            return live_f

        def live(queries):
            with config.validation_policy("off"):
                return mod.search(self.res, params, index,
                                  queries, k)
        return live

    # ---- generation swap ------------------------------------------------

    def swap_index(self, new_index) -> int:
        """Swap in a new index generation without a serving gap.

        Builds a COMPLETE replacement executable table against
        ``new_index`` and (when the executor was warmed) warms every
        (bucket, k) with a zero batch before anything is published; the
        swap itself is one tuple assignment of ``(index, _fns)``, atomic
        under the GIL.  In-flight :meth:`search_bucket` calls captured
        the old table on entry and finish on the generation they started
        on; calls arriving after the swap see only the new one — no
        reader ever observes a mixed table, and steady-state traffic
        after the swap recompiles nothing.  Returns the number of bucket
        executables built."""
        expects(new_index is not None, "serving: swap_index needs an index")
        dim = self._index_dim(new_index)
        expects(dim == self.dim,
                f"serving: swap_index dim mismatch ({dim} != {self.dim})")
        fns: Dict[Tuple[int, int, int], Callable] = {}
        for b in self.buckets:
            for k in self.ks:
                for r in range(self.n_rungs):
                    fn = self._build_fn(new_index, b, k, r)
                    if self._warmed:
                        zeros = jnp.zeros((b, dim), self.query_dtype)
                        if self.n_filter_words:
                            jax.block_until_ready(
                                fn(zeros, self._all_ones_words(b)))
                        else:
                            jax.block_until_ready(fn(zeros))
                    fns[(b, k, r)] = fn
        self.index, self._fns = new_index, fns
        if obs.enabled():
            obs.registry().counter("serving.generation_swaps").inc()
        # always-on flight event: a generation swap is exactly the kind of
        # state change a post-mortem needs to see next to shed/error events
        _flight.record_event("serving.generation_swap",
                             generation=getattr(new_index, "generation",
                                                None),
                             executables=len(fns))
        return len(fns)

    # ---- the hot path ---------------------------------------------------

    def _all_ones_words(self, bucket: int) -> jax.Array:
        """The cached admit-everything words buffer for ``bucket`` —
        what an unfiltered dispatch feeds a filter-configured executor
        so every dispatch shares ONE compiled shape."""
        w = self._ones_words.get(bucket)
        if w is None:
            w = jnp.full((bucket, self.n_filter_words), -1, jnp.int32)
            self._ones_words[bucket] = w
        return w

    def search_bucket(self, queries, n_valid: int, k: int, rung: int = 0,
                      filter_words=None) -> Tuple[jax.Array, jax.Array]:
        """Search a padded bucket batch; rows past ``n_valid`` come back
        masked (id -1 / worst distance) through the integrity mask path.
        ``rung`` selects the degradation-ladder operating point (0 =
        full quality); every rung is warmed, so the selection is a dict
        lookup, never a compile.

        ``filter_words`` is the batch's packed admission bitset
        ``(bucket, n_filter_words)`` int32 — only meaningful on an
        executor constructed with ``filter_rows > 0`` (None there means
        admit everything via the cached all-ones buffer; filters are
        data, so either way it is the same warmed executable)."""
        bucket = queries.shape[0]
        expects(0 <= rung < self.n_rungs,
                f"serving: rung {rung} outside the declared ladder "
                f"(n_rungs={self.n_rungs})")
        expects(filter_words is None or self.n_filter_words > 0,
                "serving: executor not configured for filters — "
                "construct with filter_rows=<id bound>")
        # one capture of the published table: a concurrent swap_index
        # replaces self._fns wholesale, so everything below dispatches
        # against a single consistent generation
        fns = self._fns
        fn = fns.get((bucket, k, rung))
        expects(fn is not None or not self._warmed,
                f"serving: shape ({bucket}, {k}, rung {rung}) is not a "
                f"warmed bucket")
        if fn is None:
            fn = self._obtain(bucket, k, rung)
        fw = None
        if self.n_filter_words:
            fw = (filter_words if filter_words is not None
                  else self._all_ones_words(bucket))
            expects(fw.shape == (bucket, self.n_filter_words),
                    f"serving: filter words shape {fw.shape} != "
                    f"({bucket}, {self.n_filter_words})")
            d, i = fn(queries, fw)
        else:
            d, i = fn(queries)
        delta = self._delta
        if delta is not None:
            data, ids, tombs = delta()
            d, i = _delta.merge_with_main(
                d, i, queries, data, ids, tombs, k=k,
                metric=getattr(self.index, "metric",
                               DistanceType.L2Expanded),
                filter_words=fw)
        if n_valid < bucket:
            d, i = _boundary.mask_search_outputs(
                d, i, valid_rows_mask(n_valid, bucket),
                select_min=self.select_min)
        return d, i

    def pad(self, queries, bucket: int):
        return pad_rows(queries, bucket)

    # ---- introspection --------------------------------------------------

    def operating_knobs(self, rung: int = 0) -> Dict[str, object]:
        """The closed-shape coordinates this executor serves ``rung``
        at — the knob half of an operating-point record (see
        :class:`raft_tpu.observability.quality.OpPoint`).  Keys absent
        from the rung's SearchParams come back None (e.g. brute force
        has no probes)."""
        expects(0 <= rung < self.n_rungs,
                f"serving: rung {rung} outside the declared ladder "
                f"(n_rungs={self.n_rungs})")
        params = self._rung_params[rung]
        # a None rung inherits the previous rung's params (the shed-only
        # ladder idiom) — walk back to the operative point
        r = rung
        while params is None and r > 0:
            r -= 1
            params = self._rung_params[r]
        mw = getattr(params, "merge_window", None)
        return {
            "kind": self.kind,
            "bucket": self.max_batch,
            "rung": int(rung),
            "n_probes": getattr(params, "n_probes", None),
            "scan_mode": getattr(params, "scan_mode", None),
            "kt": getattr(params, "per_probe_topk", None),
            "merge_window": mw if isinstance(mw, (int, str,
                                                  type(None))) else str(mw),
            "filtered": bool(self.n_filter_words),
        }


class DistributedExecutor(Executor):
    """Executor over a :mod:`raft_tpu.distributed.ann` sharded index —
    both placements: the data-parallel :class:`DistributedIndex` and the
    routed-probe :class:`RoutedIndex` (``placement="by_list"``), whose
    search routes each query's probes to owning shards via the
    replicated placement map.

    Always ``warm="jit"`` (the cross-shard merge is a shard_map closure,
    not exportable).  The resilience surface passes through untouched:
    ``failed_shards`` / fault-plan masking and per-shard status behave
    exactly as in direct :func:`raft_tpu.distributed.ann.search` calls,
    and post-load :func:`raft_tpu.distributed.ann.health_check` works on
    the wrapped index because the executor never copies or re-wraps it.
    Under ``by_list`` a ``swap_index`` to a rebalanced snapshot is the
    global generation barrier: the warmed fn table is rebuilt completely
    against the new placement before the single atomic swap, so no
    request ever mixes placements.

    Zero-sync steady state (round 10): ``scan_mode="fused"`` lowers
    under shard_map at a static group capacity, so a warmed bucket
    dispatch reads nothing back to the host.  The one exception is an
    index calibrated with a tightened capacity
    (:func:`raft_tpu.neighbors.ivf_pq.calibrate_group_capacity`): its
    dispatch carries an in-graph overflow flag whose single host read
    gates the exact re-dispatch — uncalibrated indexes run at the exact
    worst bound and never read it.

    ``routing`` (a :class:`raft_tpu.distributed.routing.RoutingPolicy`)
    adds load-aware replica selection with **per-bucket replica
    groups**: when the executor builds its warmed fn table it consults
    ``routing.spread_bucket(bucket)`` per ``(bucket, k)`` — hot
    small-batch buckets close over the policy (every dispatch plans
    least-loaded replica tables; data-parallel across the ranks) while
    memory-bound large-batch buckets close over ``None`` (pinned at
    the rank-0 primary).  The choice is baked into the fn-table
    closure, NOT the executable cache key: routing tables are runtime
    data, so both groups share the same warmed shapes and the AOT /
    executable cache key is unchanged.
    """

    def __init__(self, handle, index, *, ks: Sequence[int] = (10,),
                 max_batch: int = 1024, search_params=None,
                 failed_shards: Sequence[int] = (),
                 routing=None, filter_rows: int = 0) -> None:
        self.handle = handle
        self.failed_shards = tuple(failed_shards)
        self.routing = routing
        super().__init__(handle, "ivf_pq", index, ks=ks,
                         max_batch=max_batch, search_params=search_params,
                         warm="jit", filter_rows=filter_rows)
        self._feed_routing_rows(index)

    def _index_dim(self, index) -> int:
        # rotation is (n_dev, dim, rot_dim) stacked (by_row) or
        # (dim, rot_dim) replicated (by_list) — [-2] is dim in both
        return int(index.rotation.shape[-2])

    @property
    def query_dtype(self):
        centers = getattr(self.index, "coarse_centers", None)
        if centers is None:
            centers = self.index.centers
        return centers.dtype

    def _aot_fn(self, index, bucket: int, k: int, params, rung: int
                ) -> Callable:
        raise NotImplementedError("distributed indexes are jit-warmed")

    # ---- per-bucket replica groups --------------------------------------

    def _bucket_routing(self, bucket: int):
        """The bucket→replica-group map: the routing policy for hot
        buckets (spread across replica ranks), None for memory-bound
        ones (pinned at the primary)."""
        r = self.routing
        if r is None:
            return None
        return r if r.spread_bucket(bucket) else None

    def _build_fn(self, index, bucket: int, k: int, rung: int = 0
                  ) -> Callable:
        # the replica-group choice is made HERE, per (bucket, k, rung),
        # and baked into the fn-table closure — the warmed shapes and
        # the executable cache key never see it (routing tables are
        # runtime data, not shape)
        params = self._rung_params[rung]
        return self._routed_fn(index, k, params,
                               self._bucket_routing(bucket))

    def _feed_routing_rows(self, index) -> None:
        # per-list probe cost for the policy's expected-work weights —
        # read once per build/swap (never on the dispatch path).  The
        # routed scans run over PADDED list slabs (every probe touches
        # the full (cap,) slot row regardless of live rows), so the
        # honest per-probe cost is the slab capacity — uniform across
        # lists, which makes the plan weight pure measured heat
        r = self.routing
        placement = getattr(index, "placement", None)
        li = getattr(index, "list_indices", None)
        if r is None or placement is None or li is None:
            return
        n_lists = int(np.asarray(placement.owner).shape[0])
        r.note_list_rows(np.full(n_lists, float(li.shape[-1])))

    def swap_index(self, new_index) -> int:
        n = super().swap_index(new_index)
        self._feed_routing_rows(new_index)
        return n

    def prewarm_shard_artifacts(self, scan_mode: str = "fused") -> int:
        """Load one PER-SHARD routed executable per (bucket, k, shard)
        into the process :class:`~raft_tpu.core.aot.ExecutableCache`
        (kind ``"ivf_pq_routed"``) so a single-shard deployment process
        answers its first request compile-free.

        Only meaningful for ``by_list`` (:class:`RoutedIndex`) indexes —
        data-parallel placements return 0.  For ``scan_mode="fused"``
        each artifact bakes the grouped scan at the STATIC group
        capacity for its bucket shape; that capacity rides in the cache
        key via the export kwargs, so re-warming after a bucket change
        never aliases a stale group count.  A replicated placement
        (``replication_factor > 1``) warms one executable per REPLICA
        RANK as well — a failover that promotes a shard's rank-j tables
        must hit a warmed program, not a first-request compile (the
        rank joins the cache key via the export kwargs).  Returns the
        number of cached shard executables."""
        index = self.index
        if getattr(index, "local_centers", None) is None:
            return 0
        from raft_tpu.neighbors import grouped

        cache = _aot_executables()
        n_probes = min(self.params.n_probes, index.n_lists)
        slots = int(index.local_centers.shape[1])
        rf = (index.placement.replication_factor
              if getattr(index, "placement", None) is not None else 1)
        n = 0
        for b in self.buckets:
            cap = grouped.group_capacity(b, n_probes, slots)[0]
            for k in self.ks:
                for s in range(index.n_shards):
                    for rank in range(rf):
                        kwargs = {"shard": s}
                        if scan_mode == "fused":
                            kwargs["group_capacity"] = cap
                        if rank > 0:
                            kwargs["replica_rank"] = rank
                        cache.get("ivf_pq_routed", self.handle, index,
                                  batch=b, k=k, n_probes=n_probes,
                                  scan_mode=scan_mode, **kwargs)
                        n += 1
        return n

    def _live_fn(self, index, k: int, params) -> Callable:
        return self._routed_fn(index, k, params, self.routing)

    def _routed_fn(self, index, k: int, params, routing) -> Callable:
        from raft_tpu import config
        from raft_tpu.distributed import ann

        if self.n_filter_words:
            n_rows = self.filter_rows

            def live_f(queries, fw):
                with config.validation_policy("off"):
                    return ann.search(
                        self.handle, params, index, queries, k,
                        failed_shards=self.failed_shards,
                        routing=routing,
                        filter=SampleFilter.from_words(fw, n_rows))
            return live_f

        def live(queries):
            with config.validation_policy("off"):
                return ann.search(self.handle, params, index,
                                  queries, k,
                                  failed_shards=self.failed_shards,
                                  routing=routing)
        return live
