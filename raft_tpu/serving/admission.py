"""Admission control — bounded queueing, load shedding, tenant quotas.

The serving layer's survival rules, applied BEFORE a request touches an
accelerator: a bounded queue with typed :class:`Overloaded` rejection
(the load-shedding contract: clients see an immediate, retryable error
instead of unbounded latency), per-tenant token-bucket quotas
(:class:`QuotaExceeded`), and deadline awareness — a request whose
:class:`~raft_tpu.resilience.retry.Deadline` is already spent is refused
at the door, and one that expires while queued is completed with
:class:`~raft_tpu.resilience.retry.DeadlineExceededError` at dispatch
instead of wasting a bucket slot.

Counters (collection-gated): ``serving.admitted``,
``serving.shed.queue_full``, ``serving.shed.quota``,
``serving.shed.deadline``, and — while a brownout ladder's top rung is
active — ``serving.shed.brownout`` for best-effort-tenant requests
refused at the door (see :mod:`raft_tpu.serving.brownout`).  Every shed
additionally lands an anomaly event of the same name in the always-on
flight recorder (flight.py), carrying the request's trace id when
tracing is enabled.  Exactly ONE shed counter ticks per shed request:
each check below raises immediately, and a request refused here never
reaches the dispatcher's dispatch-time deadline accounting.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Dict, Optional, Tuple

from raft_tpu import observability as obs
from raft_tpu.core.error import RaftError
from raft_tpu.observability import flight as _flight
from raft_tpu.resilience.retry import Deadline


class Overloaded(RaftError):
    """The server shed this request (queue full).  Retryable by the
    client after backoff — the serving analogue of
    :class:`~raft_tpu.resilience.faults.TransientFault`."""


class QuotaExceeded(Overloaded):
    """The tenant's token bucket is empty.  A subclass of
    :class:`Overloaded` so quota-blind clients need one handler."""


class BrownedOut(Overloaded):
    """Shed because the brownout ladder's active rung drops best-effort
    tenants.  A subclass of :class:`Overloaded`: same client contract
    (retry with backoff), distinct type for tests and dashboards."""


class TokenBucket:
    """Per-tenant rate limiter: ``rate`` tokens/s, ``burst`` capacity.

    One token per query row (not per request), so a 100-row submission
    spends 100 tokens — quota units are rows/s of accelerator work.
    """

    __slots__ = ("rate", "burst", "_tokens", "_t_last", "_clock", "_lock")

    def __init__(self, rate: float, burst: float,
                 clock=time.monotonic) -> None:
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._clock = clock
        self._t_last = clock()
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t_last) * self.rate)
            self._t_last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


@dataclasses.dataclass
class Request:
    """One queued submission (host-side bookkeeping around a Future)."""

    queries: object               # (n, dim) array, already boundary-checked
    k: int
    tenant: str
    deadline: Optional[Deadline]
    future: Future
    n: int                        # row count (static, pre-pad)
    t_enqueue: float              # time.monotonic at admission
    # per-row validity from the boundary validator under policy "mask"
    # (None under "raise"/"off"); applied to this request's output slice
    ok_rows: Optional[object] = None
    # per-request SpanRecorder minted by Server.submit when tracing is
    # enabled (None otherwise); the batcher closes + flight-records it
    trace: Optional[object] = None
    # packed (n, n_filter_words) int32 admission bitset over global row
    # ids — HOST-side numpy (filters are data, not shape: the batcher
    # copies rows into the bucket's fixed-width filter buffer exactly
    # like query rows).  None = admit everything for this request.
    filter_words: Optional[object] = None

    @property
    def trace_id(self) -> Optional[int]:
        return self.trace.trace_id if self.trace is not None else None


class AdmissionQueue:
    """Bounded FIFO of :class:`Request` with quota + shed policy.

    ``max_queue_rows`` bounds the total queued *rows* (the unit the
    executor pays for).  ``quotas`` maps tenant name -> (rate, burst) in
    rows/s; absent tenants are unmetered.
    """

    def __init__(self, max_queue_rows: int,
                 quotas: Optional[Dict[str, Tuple[float, float]]] = None,
                 clock=time.monotonic, brownout=None) -> None:
        self._max_rows = int(max_queue_rows)
        self._clock = clock
        # shared BrownoutState (serving.brownout) — read lock-free per
        # offer; None for a standalone queue (tests, direct use)
        self.brownout = brownout
        self._buckets = {t: TokenBucket(r, b, clock)
                         for t, (r, b) in (quotas or {}).items()}
        self._lock = threading.Lock()
        self.cond = threading.Condition(self._lock)
        self._items: list = []
        self._rows = 0

    # ---- admission ------------------------------------------------------

    def offer(self, req: Request) -> None:
        """Admit or shed (raises :class:`Overloaded` / subclasses).
        Checks are ordered deadline → brownout → quota → queue bound and
        each raises immediately, so a shed request ticks exactly one
        ``serving.shed.*`` counter."""
        bo = self.brownout
        level = bo.level if bo is not None else 0
        if req.deadline is not None and req.deadline.expired:
            _count("serving.shed.deadline")
            _flight.record_event("serving.shed.deadline",
                                 trace_id=req.trace_id,
                                 tenant=req.tenant, rows=req.n,
                                 phase="submit", level=level)
            raise Overloaded(
                "serving: request deadline already expired at submit")
        if (bo is not None and bo.shed_best_effort
                and req.tenant in bo.best_effort_tenants):
            _count("serving.shed.brownout")
            _flight.record_event("serving.shed.brownout",
                                 trace_id=req.trace_id,
                                 tenant=req.tenant, rows=req.n,
                                 level=level)
            raise BrownedOut(
                f"serving: best-effort tenant {req.tenant!r} shed at "
                f"brownout level {level} — retry with backoff")
        bucket = self._buckets.get(req.tenant)
        if bucket is not None and not bucket.try_acquire(req.n):
            _count("serving.shed.quota")
            _flight.record_event("serving.shed.quota",
                                 trace_id=req.trace_id,
                                 tenant=req.tenant, rows=req.n,
                                 rate=bucket.rate, burst=bucket.burst)
            raise QuotaExceeded(
                f"serving: tenant {req.tenant!r} exceeded its quota "
                f"({bucket.rate:g} rows/s, burst {bucket.burst:g})")
        with self.cond:
            if self._rows + req.n > self._max_rows:
                _count("serving.shed.queue_full")
                _flight.record_event("serving.shed.queue_full",
                                     trace_id=req.trace_id,
                                     tenant=req.tenant, rows=req.n,
                                     queued_rows=self._rows,
                                     bound=self._max_rows, level=level)
                raise Overloaded(
                    f"serving: queue full ({self._rows} rows queued, "
                    f"bound {self._max_rows}) — retry with backoff")
            self._items.append(req)
            self._rows += req.n
            _count("serving.admitted")
            if obs.enabled():
                obs.registry().gauge("serving.queue_depth").set(self._rows)
            self.cond.notify_all()

    # ---- dispatcher side (call with ``cond`` held) ----------------------

    def peek_oldest(self) -> Optional[Request]:
        return self._items[0] if self._items else None

    def cut_batch(self, max_rows: int) -> list:
        """Pop the FIFO head run: requests sharing the head's ``k`` whose
        rows fit in ``max_rows``.  Expired requests are popped and
        returned too — the dispatcher completes them with
        DeadlineExceededError without spending bucket rows on them."""
        out, rows, batch_k = [], 0, None
        while self._items:
            head = self._items[0]
            expired = head.deadline is not None and head.deadline.expired
            if not expired:
                if batch_k is not None and head.k != batch_k:
                    break           # k is fixed per bucket; next cut gets it
                if rows + head.n > max_rows:
                    break
                batch_k = head.k
                rows += head.n
            self._items.pop(0)
            self._rows -= head.n
            out.append(head)
        if obs.enabled():
            obs.registry().gauge("serving.queue_depth").set(self._rows)
        return out

    @property
    def rows(self) -> int:
        return self._rows

    def __len__(self) -> int:
        return len(self._items)


def _count(name: str) -> None:
    if obs.enabled():
        obs.registry().counter(name).inc()
