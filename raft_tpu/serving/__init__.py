"""raft_tpu.serving — online query serving over the batch indexes.

Every entry point below this package is batch-shaped: ``search()`` wants
a pre-formed query matrix.  This package is the request scheduler that
turns those batch kernels into an online service (the layer every
RAFT-class deployment interposes between users and the GPU/TPU):

- :mod:`~raft_tpu.serving.buckets` — the closed shape set: power-of-two
  query-count buckets, fixed k/n_probes per bucket, padded rows flagged
  through the integrity mask path (id -1 / worst distance);
- :mod:`~raft_tpu.serving.admission` — bounded queue with typed
  :class:`Overloaded` load-shedding, per-tenant :class:`TokenBucket`
  quotas, deadline-aware queueing on
  :class:`~raft_tpu.resilience.retry.Deadline`;
- :mod:`~raft_tpu.serving.batcher` — the dynamic batcher: dispatch on
  ``max_batch`` OR ``max_wait_us``, whichever first;
- :mod:`~raft_tpu.serving.executor` — bucket-warmed executors over
  IVF-PQ / IVF-Flat / CAGRA / brute force (AOT-exported via
  ``core/aot``) and :mod:`raft_tpu.distributed.ann` (jit-warmed;
  degraded-mode shard masking and ``health_check`` compose unchanged);
- :mod:`~raft_tpu.serving.server` — the ``Server`` front end:
  ``submit() -> Future``, boundary validation per request, serving
  counters + latency histograms at enqueue→dispatch→complete, plus the
  generation watchdog: N integrity strikes within a window auto-roll
  the executor back to the retained last-known-good index;
- :mod:`~raft_tpu.serving.brownout` — adaptive overload degradation:
  a :class:`BrownoutController` watches windowed latency/queue/shed
  telemetry and steps the bucket down/up a pre-declared, pre-warmed
  degradation ladder (reduced ``n_probes`` → … → best-effort-tenant
  shed) with hysteresis and dwell — goodput degrades instead of
  collapsing, with zero steady-state recompiles;
- :mod:`~raft_tpu.serving.rebalancer` — crash-safe background index
  maintenance for the mutable IVF indexes: overfull-list re-clustering
  + tombstone compaction, checkpointed stages
  (``resilience.CheckpointManager``), every swap-in gated behind
  ``integrity.verify`` + the recall canary, atomic generation swaps
  through ``Server.swap_index``;
- :mod:`~raft_tpu.serving.ingest` — the durable write path:
  ``Server.write()`` appends to a CRC-framed write-ahead log (fsync
  group commit) before acknowledging, applies to the always-mutable
  :class:`~raft_tpu.neighbors.delta.Memtable` searched alongside the
  main index (the delta-as-extra-shard ``finalize_topk`` merge), and
  periodically folds the memtable into the main index as a
  checkpointed, gated compaction; ``recover()`` replays the WAL to
  bit-identical state after a kill at any boundary;
- :mod:`~raft_tpu.serving.dist_ingest` — the replicated durable write
  path over the routed distributed index: owner-routed writes through
  the replicated coarse quantizer, per-shard CRC-framed WALs with a
  write-quorum ack, typed :class:`Unavailable` refusal when a list
  loses every replica, WAL delta catch-up for recovering shards, and
  an all-memtable fold under one placement-generation bump.

Quick tour::

    from raft_tpu import serving
    ex = serving.Executor(res, "ivf_pq", index, ks=(10,),
                          max_batch=1024, search_params=sp)
    with serving.Server(ex, serving.ServerConfig(max_wait_us=500)) as srv:
        d, i = srv.search(queries[:3], k=10)
"""

from raft_tpu.serving.admission import (  # noqa: F401
    AdmissionQueue,
    BrownedOut,
    Overloaded,
    QuotaExceeded,
    Request,
    TokenBucket,
)
from raft_tpu.serving.batcher import DynamicBatcher  # noqa: F401
from raft_tpu.serving.brownout import (  # noqa: F401
    BrownoutConfig,
    BrownoutController,
    BrownoutState,
    Rung,
)
from raft_tpu.serving.buckets import (  # noqa: F401
    bucket_for,
    bucket_sizes,
    pad_rows,
    valid_rows_mask,
)
from raft_tpu.serving.executor import (  # noqa: F401
    DistributedExecutor,
    Executor,
)
from raft_tpu.serving.dist_ingest import (  # noqa: F401
    DistIngestConfig,
    RoutedIngest,
    Unavailable,
)
from raft_tpu.serving.ingest import (  # noqa: F401
    IngestConfig,
    IngestServer,
    WriteAheadLog,
)
from raft_tpu.serving.rebalancer import (  # noqa: F401
    RebalanceConfig,
    Rebalancer,
    rebalance_routed,
)
from raft_tpu.serving.server import Server, ServerConfig  # noqa: F401
from raft_tpu.serving.shadow import (  # noqa: F401
    ShadowConfig,
    ShadowMonitor,
    ground_truth_search_params,
)

__all__ = [
    "AdmissionQueue",
    "BrownedOut",
    "BrownoutConfig",
    "BrownoutController",
    "BrownoutState",
    "DistIngestConfig",
    "DistributedExecutor",
    "DynamicBatcher",
    "Executor",
    "IngestConfig",
    "IngestServer",
    "Overloaded",
    "Rung",
    "QuotaExceeded",
    "RebalanceConfig",
    "Rebalancer",
    "rebalance_routed",
    "Request",
    "RoutedIngest",
    "Server",
    "ServerConfig",
    "Unavailable",
    "ShadowConfig",
    "ShadowMonitor",
    "TokenBucket",
    "WriteAheadLog",
    "bucket_for",
    "bucket_sizes",
    "ground_truth_search_params",
    "pad_rows",
    "valid_rows_mask",
]
