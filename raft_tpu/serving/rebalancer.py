"""Crash-safe background rebalancer over the mutable IVF indexes.

Long-lived mutable indexes degrade two ways: tombstones from ``delete``
accumulate scan overhead (every probe still streams and masks the dead
slots), and ``extend`` drifts rows into whichever coarse lists happened
to be nearest, leaving some lists far fuller than others (worst-case
probe cost is set by the fullest list).  The rebalancer repairs both in
the background, one staged, checkpointed, verification-gated pass at a
time:

``plan``
    Measure the damage: per-list live sizes, the tombstoned fraction of
    occupied slots.  Nothing to repair -> the pass is a no-op.
``recluster``
    For every overfull list (live size > ``overfull_factor`` x mean),
    pull its live rows (IVF-Flat: the stored vectors; IVF-PQ: the
    decoded ``center + residual`` reconstructions rotated back to input
    space), ``delete`` them and ``extend`` them back under their
    original ids — extend's nearest-center assignment IS the
    re-clustering step, spreading drifted rows over the current
    centroids.
``compact``
    Past ``dead_fraction`` (or after any recluster, whose deletes
    create tombstones by construction), rewrite every list live-rows
    first and drop the dead slots, shrinking capacity back down.

Each stage checkpoints the full serialized index through
:class:`~raft_tpu.resilience.checkpoint.CheckpointManager` (atomic
tmp+fsync+rename, CRC-protected).  A crash mid-pass leaves the serving
tier on the last good generation — mutations build NEW snapshots, they
never touch the served one — and :meth:`Rebalancer.resume` either
finishes the pass from the furthest checkpoint or rolls back to the
checkpointed base; both paths re-run the gate.

The gate: no candidate index is ever swapped in before it passes
``integrity.verify`` (at ``verify_level``, with the id-space bound
computed from the candidate itself — delete + compact makes the live id
space sparse, so ``sum(list_sizes)`` is not the bound) AND the recall
canary ``health_check`` when the index carries canaries.  Swap-in goes
through ``Server.swap_index``: a fully warmed replacement executable
table published atomically, in-flight readers pinned on the generation
they started on.

Fault sites (``resilience.faults``): ``rebalance.plan`` /
``rebalance.recluster`` / ``rebalance.compact`` / ``rebalance.verify`` /
``rebalance.swap``, plus the manager's own ``checkpoint.save`` /
``checkpoint.load`` — the CI crash-recovery job kills a pass at every
one of these boundaries and asserts resume-or-rollback lands on a
verify-clean, canary-passing index.
"""

from __future__ import annotations

import dataclasses
import io
import threading
from typing import Dict, Optional, Union

import jax.numpy as jnp
import numpy as np

from raft_tpu import observability as obs
from raft_tpu.core.error import expects
from raft_tpu.integrity import canary as _canary
from raft_tpu.observability import flight as _flight
from raft_tpu.integrity.verify import verify as _verify_index
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.neighbors import mutate as _mutate
from raft_tpu.resilience import faults
from raft_tpu.resilience.checkpoint import CheckpointManager, as_manager


@dataclasses.dataclass
class RebalanceConfig:
    """Rebalancer knobs (see docs/api.md "Mutation & generations").

    ``dead_fraction`` is the tombstone budget: compaction triggers when
    dead/(live+dead) occupied slots exceeds it (PERFORMANCE.md carries a
    measured sweep of scan overhead vs. this number).
    ``overfull_factor`` flags lists for re-clustering when their live
    size exceeds that multiple of the mean live list size.
    ``max_lists_per_pass`` bounds one pass's recluster work so the
    background thread stays incremental (0 = no bound).
    """

    dead_fraction: float = 0.2
    overfull_factor: float = 2.0
    max_lists_per_pass: int = 8
    interval_s: float = 30.0
    verify_level: str = "statistical"


class Rebalancer:
    """Staged, checkpointed, gated maintenance over one mutable index.

    ``checkpoint`` (a path or :class:`CheckpointManager`) enables crash
    safety; without it the pass still runs gated, just without resume.
    ``server`` (a :class:`~raft_tpu.serving.server.Server` or bare
    executor with ``swap_index``) receives every accepted generation.
    """

    def __init__(self, res, index, *,
                 config: Optional[RebalanceConfig] = None,
                 checkpoint: Optional[Union[str, CheckpointManager]] = None,
                 server=None, ingest=None) -> None:
        expects(isinstance(index, (ivf_flat.Index, ivf_pq.Index)),
                "rebalancer: only IVF-Flat / IVF-PQ indexes rebalance "
                "(CAGRA's delete shim requires a rebuild to reclaim rows)")
        self.res = res
        self.config = config or RebalanceConfig()
        self.checkpoint = as_manager(checkpoint)
        self.server = server
        # streaming-ingest compaction hook: each background pass first
        # offers the ingest tier a fold (its own checkpointed, gated
        # stage — see serving/ingest.py); a published fold moves this
        # rebalancer's base forward so a later pass never swaps a
        # pre-fold generation back in
        self.ingest = ingest
        self.last_good = index
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stats: Dict[str, int] = {
            "passes": 0, "swaps": 0, "rollbacks": 0, "noops": 0,
            "errors": 0, "reclustered_rows": 0, "compactions": 0}

    # ---- introspection --------------------------------------------------

    def stats(self) -> Dict[str, object]:
        out: Dict[str, object] = dict(self._stats)
        out["generation"] = _mutate.generation(self.last_good)
        out["dead_fraction"] = _mutate.dead_fraction(self.last_good)
        return out

    # ---- one staged pass ------------------------------------------------

    def run_once(self):
        """One full pass: plan -> recluster -> compact -> gate -> swap.
        Returns the index now serving (a new generation when repair work
        was accepted, ``last_good`` unchanged otherwise).  An injected
        fault or crash mid-pass propagates — the served index is
        untouched; call :meth:`resume` afterwards."""
        with self._lock:
            return self._run_once_locked()

    def _run_once_locked(self):
        index = self.last_good
        self._stats["passes"] += 1
        faults.maybe_fail("rebalance.plan")
        overfull, dead = self._plan(index)
        if not overfull.size and dead < self.config.dead_fraction:
            self._stats["noops"] += 1
            return self.last_good
        self._save_stage("base", index)

        faults.maybe_fail("rebalance.recluster")
        moved = 0
        work = index
        if overfull.size:
            work, moved = self._recluster(index, overfull)
            self._stats["reclustered_rows"] += moved
        self._save_stage("recluster", work)

        faults.maybe_fail("rebalance.compact")
        if moved or _mutate.dead_fraction(work) >= self.config.dead_fraction:
            mod = self._mod(work)
            work = mod.compact(self.res, work)
            self._stats["compactions"] += 1
        self._save_stage("compact", work)

        return self._gate_and_swap(work)

    # ---- streaming-ingest compaction hook -------------------------------

    def maybe_fold_ingest(self):
        """Offer the attached ingest tier a threshold-triggered memtable
        fold (the LSM compaction stage); a published fold becomes this
        rebalancer's new base.  No-op without an ingest tier.  Returns
        the folded index or None."""
        if self.ingest is None:
            return None
        folded = self.ingest.maybe_fold()
        if folded is not None:
            with self._lock:
                self.last_good = folded
        return folded

    # ---- crash recovery -------------------------------------------------

    def resume(self):
        """Recover after a crash / injected fault: finish the pass from
        the furthest completed checkpoint stage (re-running the gate), or
        roll back to the checkpointed base generation when the candidate
        cannot be recovered or fails the gate.  Either way the result is
        a gated index and a cleared checkpoint directory — an unverified
        candidate is never served."""
        with self._lock:
            return self._resume_locked()

    def _resume_locked(self):
        ck = self.checkpoint
        if ck is None or not ck.completed:
            return self.last_good
        done = ck.completed
        for stage in ("compact", "recluster"):
            if stage in done:
                try:
                    cand = self._load_stage(stage)
                    return self._gate_and_swap(cand)
                except Exception:  # noqa: BLE001 - any failure -> roll back
                    self._stats["errors"] += 1
                    break
        # rollback: the base checkpoint is the last generation that
        # passed a gate; re-serve it and drop the partial pass
        if "base" in done:
            try:
                base = self._load_stage("base")
                if _mutate.generation(base) != _mutate.generation(
                        self.last_good):
                    self.last_good = base
                    self._swap(base)
            except Exception:  # noqa: BLE001 - keep serving in-memory good
                self._stats["errors"] += 1
        ck.clear()
        self._stats["rollbacks"] += 1
        # a rollback means a candidate generation was abandoned — exactly
        # the state transition a post-mortem wants on the anomaly timeline
        _flight.record_event("rebalance.rollback",
                             generation=_mutate.generation(self.last_good),
                             errors=self._stats["errors"])
        return self.last_good

    # ---- stages ---------------------------------------------------------

    def _plan(self, index):
        live = np.asarray(_mutate.live_sizes(index.list_indices))
        dead = _mutate.dead_fraction(index)
        filled = live[live > 0]
        if not filled.size:
            return np.empty(0, np.int64), dead
        mean = float(filled.mean())
        overfull = np.nonzero(live > self.config.overfull_factor
                              * max(mean, 1.0))[0]
        cap = self.config.max_lists_per_pass
        if cap and overfull.size > cap:
            # fullest first: bounded passes repair the worst skew first
            overfull = overfull[np.argsort(-live[overfull])][:cap]
        return overfull, dead

    def _recluster(self, index, overfull):
        """delete + re-extend the overfull lists' live rows: extend's
        nearest-center assignment redistributes them over the CURRENT
        centroids (adaptive centers have drifted since these rows were
        placed), and the original ids ride along unchanged."""
        rows, ids = self._gather_rows(index, overfull)
        if not ids.size:
            return index, 0
        mod = self._mod(index)
        work = mod.delete(self.res, index, jnp.asarray(ids))
        work = mod.extend(self.res, work, jnp.asarray(rows),
                          jnp.asarray(ids))
        return work, int(ids.size)

    def _gather_rows(self, index, overfull):
        li = np.asarray(index.list_indices)
        if isinstance(index, ivf_flat.Index):
            data = np.asarray(index.list_data)
            recon = rot = centers = None
        else:
            recon = (index.list_recon if index.list_recon is not None
                     else ivf_pq._decode_lists(
                         index.centers, index.codebooks, index.list_codes,
                         index.codebook_kind, index.pq_dim, index.pq_bits))
            recon = np.asarray(recon, np.float32)
            rot = np.asarray(index.rotation, np.float32)
            centers = np.asarray(index.centers, np.float32)
        out_rows, out_ids = [], []
        for l in overfull:
            sel = li[l] >= 0
            if not sel.any():
                continue
            out_ids.append(li[l][sel].astype(np.int32))
            if isinstance(index, ivf_flat.Index):
                out_rows.append(np.asarray(data[l][sel], np.float32))
            else:
                # decoded residual + center live in rotated space; the
                # rotation is orthonormal (dim, rot_dim), so @ rotation.T
                # maps the reconstruction back to input space (exact
                # inverse when rot_dim == dim, the default)
                out_rows.append((recon[l][sel] + centers[l][None]) @ rot.T)
        if not out_ids:
            return (np.empty((0, int(index.dim)), np.float32),
                    np.empty(0, np.int32))
        return np.concatenate(out_rows), np.concatenate(out_ids)

    # ---- the gate -------------------------------------------------------

    def _gate_and_swap(self, cand):
        faults.maybe_fail("rebalance.verify")
        _verify_index(cand, self.config.verify_level, res=self.res,
                      n_rows=self._id_span(cand))
        if getattr(cand, "canaries", None) is not None:
            _canary.health_check(self.res, cand, raise_on_fail=True)
        faults.maybe_fail("rebalance.swap")
        self.last_good = cand
        self._swap(cand)
        if self.checkpoint is not None:
            self.checkpoint.clear()
        self._stats["swaps"] += 1
        if obs.enabled():
            obs.registry().counter("rebalance.swaps").inc()
        return cand

    def _swap(self, cand) -> None:
        if self.server is None:
            return
        ex = getattr(self.server, "executor", self.server)
        if getattr(ex, "index", None) is not cand:
            self.server.swap_index(cand)

    @staticmethod
    def _id_span(index) -> int:
        """The candidate's true id-space bound: max decoded source id
        (live or tombstoned) + 1.  After delete + compact the live id
        space is sparse, so ``sum(list_sizes)`` under-counts — verify's
        default convention does not apply to rebalanced snapshots."""
        li = np.asarray(index.list_indices)
        dec = np.where(li <= -2, -li.astype(np.int64) - 2,
                       li.astype(np.int64))
        vals = dec[(li >= 0) | (li <= -2)]
        return int(vals.max()) + 1 if vals.size else 0

    # ---- checkpoint plumbing --------------------------------------------

    def _mod(self, index):
        return ivf_flat if isinstance(index, ivf_flat.Index) else ivf_pq

    def _save_stage(self, stage: str, index) -> None:
        if self.checkpoint is None:
            return
        mod = self._mod(index)
        buf = io.BytesIO()
        mod.serialize(self.res, buf, index)
        self.checkpoint.save(stage, {
            "index": np.frombuffer(buf.getvalue(), np.uint8),
            "kind": np.frombuffer(
                ("ivf_flat" if mod is ivf_flat else "ivf_pq").encode(),
                np.uint8),
            "generation": np.asarray([_mutate.generation(index)],
                                     np.int64)})

    def _load_stage(self, stage: str):
        arrays = self.checkpoint.load(stage)
        kind = bytes(arrays["kind"]).decode()
        mod = ivf_flat if kind == "ivf_flat" else ivf_pq
        idx = mod.deserialize(self.res, io.BytesIO(bytes(arrays["index"])))
        idx.generation = int(arrays["generation"][0])
        return idx

    # ---- background thread ----------------------------------------------

    def start(self) -> "Rebalancer":
        """Run :meth:`run_once` every ``config.interval_s`` on a daemon
        thread until :meth:`stop`.  A failing pass (including injected
        faults) is recorded and the loop continues serving ``last_good``
        — the background thread never propagates into request threads."""
        expects(self._thread is None, "rebalancer: already started")
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.config.interval_s):
                try:
                    self.maybe_fold_ingest()
                    self.run_once()
                except Exception:  # noqa: BLE001 - keep last_good serving
                    self._stats["errors"] += 1

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="raft-tpu-rebalancer")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None

    def __enter__(self) -> "Rebalancer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# routed (placement="by_list") distributed indexes: per-shard passes +
# a global generation barrier
# ---------------------------------------------------------------------------

def rebalance_routed(handle, index, *,
                     config: Optional[RebalanceConfig] = None,
                     server=None, routing=None):
    """One maintenance pass over a routed distributed index
    (:class:`raft_tpu.distributed.ann.RoutedIndex`): per-shard
    compaction passes followed by a placement recompute, published
    under ONE global generation bump.

    **Per-shard passes**: each shard's owned lists are examined
    independently — only shards whose owned tombstone fraction reaches
    ``config.dead_fraction`` get their lists rewritten (stable
    live-rows-first, dead slots dropped from the occupied prefix); a
    healthy shard's leaves pass through untouched, so the pass cost
    scales with the damaged shards, not the mesh.

    **The global barrier**: list moves are only safe if every chip
    flips placements together — a reader seeing shard ``a`` at
    placement ``g`` and shard ``b`` at ``g+1`` would double-count or
    drop the moved lists.  So the pass assembles the COMPLETE new
    pytree (every shard's leaves under the recomputed LPT placement)
    before anything is published, bumps the index generation ONCE, and
    publishes through ``server.swap_index`` — which warms a full
    replacement executable table against the new placement generation
    and installs it with a single atomic assignment.  In-flight
    searches finish on the snapshot they started on.

    Gate: the recall-canary ``health_check`` (when the index carries
    canaries) must pass before the swap — same contract as the
    single-index :class:`Rebalancer`.  Recluster (moving rows between
    lists) needs the PQ encoder and stays with the single-index pass;
    this pass repairs tombstone debt and placement skew.

    **Probe-frequency-aware placement** (``routing``, a
    :class:`raft_tpu.distributed.routing.RoutingPolicy`): the policy's
    pending probe histograms are refreshed (the one maintenance-path
    host read of the probe counters — steady-state dispatch stays
    sync-free) and the LPT recompute balances by *expected probe load*
    — the measured per-list probe rate (each probe scans the full
    padded slot row, so the per-probe cost is the slab capacity,
    uniform across lists) — instead of live rows alone, so a
    synthetically hot list's replicas land on shards that are cold by
    measured heat.  Heat skew above ``overfull_factor`` makes the pass
    eligible even when row counts look balanced.

    Returns the index now serving: a new generation when repair work
    was accepted, ``index`` unchanged on a no-op.  Fault sites:
    ``rebalance.plan`` / ``rebalance.compact`` / ``rebalance.verify`` /
    ``rebalance.swap``.
    """
    from raft_tpu.distributed import ann as _dann

    expects(isinstance(index, _dann.RoutedIndex),
            "rebalance_routed: a RoutedIndex (placement='by_list') is "
            "required — data-parallel shards rebalance per shard with "
            "the single-index Rebalancer")
    config = config or RebalanceConfig()
    faults.maybe_fail("rebalance.plan")

    heat = None
    if routing is not None:
        routing.refresh()
        heat = routing.expected_probe_load()
        if heat is not None and heat.shape[0] != int(
                index.placement.owner.shape[0]):
            heat = None  # stale window from another index shape

    li = index.list_indices                       # (n_dev, L+1, cap)
    live_per_shard = jnp.sum(li >= 0, axis=(1, 2))
    dead_per_shard = jnp.sum(li <= -2, axis=(1, 2))
    occupied = jnp.maximum(live_per_shard + dead_per_shard, 1)
    frac = np.asarray(dead_per_shard / occupied)
    eligible = [s for s in range(index.n_shards)
                if frac[s] >= config.dead_fraction]
    load = np.asarray(live_per_shard, np.int64)
    skew = load.max() / max(load.mean(), 1.0)
    hot_skew = 0.0
    if heat is not None:
        # measured per-shard heat under the CURRENT primaries.  The
        # routed scans run over PADDED list slabs — every probe costs
        # the full (cap,) slot row whatever the live count — so the
        # per-shard scan load is the probe rate alone (host-side
        # tables only, no device reads)
        own = np.asarray(index.placement.owner)
        hot_load = np.bincount(own, weights=heat,
                               minlength=index.n_shards)
        hot_skew = hot_load.max() / max(hot_load.mean(), 1e-12)
    if (not eligible and skew <= config.overfull_factor
            and hot_skew <= config.overfull_factor):
        if obs.enabled():
            obs.registry().counter("rebalance.routed.noops").inc()
        return index

    centers, recon, rsq, gli, sizes, code_leaves = _dann._gather_global(
        index)

    faults.maybe_fail("rebalance.compact")
    if eligible:
        order, live = _mutate.compaction_order(gli)
        sel = jnp.asarray(
            np.isin(np.asarray(index.owner), eligible))   # (n_lists,)
        cap = gli.shape[1]
        ident = jnp.broadcast_to(jnp.arange(cap, dtype=order.dtype),
                                 gli.shape)
        order = jnp.where(sel[:, None], order, ident)
        drop = sel[:, None] & (jnp.arange(cap)[None, :] >= live[:, None])
        gli = jnp.where(drop, -1, jnp.take_along_axis(gli, order, axis=1))
        recon = jnp.where(
            drop[:, :, None], 0,
            jnp.take_along_axis(recon, order[:, :, None], axis=1))
        rsq = jnp.where(drop, 0, jnp.take_along_axis(rsq, order, axis=1))
        sizes = jnp.where(sel, live, sizes)
        if code_leaves is not None:
            # the lane-major code cache is row-indexed on its LAST axis
            # (n_lists, Wi, cap): same permutation, broadcast over lanes
            books, lanes, crsq = code_leaves
            lanes = jnp.where(
                drop[:, None, :], 0,
                jnp.take_along_axis(lanes, order[:, None, :], axis=2))
            crsq = jnp.where(drop, 0,
                             jnp.take_along_axis(crsq, order, axis=1))
            code_leaves = (books, lanes, crsq)

    live_rows = np.asarray(jnp.sum(gli >= 0, axis=1), np.int64)
    weights = live_rows
    if heat is not None:
        # expected probe load: measured probe rate × the padded slab
        # cost — what the makespan actually depends on (every probe
        # scans the full (cap,) slot row, so a hot tiny list costs as
        # much per probe as a hot huge one; a never-probed list costs
        # nothing).  Scaling by n_lists × mean rows keeps the int64
        # weights at row magnitudes, and the +1 floor keeps
        # never-probed lists ordered by a stable tiebreak
        scale = heat.shape[0] * max(float(live_rows.mean()), 1.0)
        weights = np.maximum((heat * scale).astype(np.int64), 1)
    placement = _dann.compute_placement(
        weights, index.n_shards,
        generation=index.placement.generation + 1,
        replication_factor=index.placement.replication_factor)
    cand = _dann._place_lists(handle, (centers, recon, rsq, gli, sizes),
                              index.rotation, placement, index.metric,
                              index.size, code_leaves=code_leaves,
                              pq_bits=index.pq_bits,
                              group_est=index.group_est)
    cand.canaries = index.canaries
    _mutate.next_generation(index, cand)          # the ONE global bump

    faults.maybe_fail("rebalance.verify")
    if cand.canaries is not None:
        _dann.health_check(handle, cand, raise_on_fail=True)
    faults.maybe_fail("rebalance.swap")
    if server is not None:
        ex = getattr(server, "executor", server)
        if getattr(ex, "index", None) is not cand:
            server.swap_index(cand)
    if routing is not None:
        # re-seed the policy's per-probe cost from the new placement's
        # slab capacity — uniform over the padded lists, so the plan
        # weight stays pure measured heat (the serving executor's
        # swap_index does the same when a server is attached; direct
        # callers need it here)
        n_lists = int(np.asarray(cand.placement.owner).shape[0])
        routing.note_list_rows(
            np.full(n_lists, float(cand.list_indices.shape[-1])))
    if obs.enabled():
        obs.registry().counter("rebalance.routed.passes").inc()
        obs.registry().counter("rebalance.swaps").inc()
    return cand
