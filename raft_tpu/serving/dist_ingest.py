"""Replicated durable ingest over the routed distributed index.

The PR 13 ingest tier is single-writer: ONE WAL, ONE memtable, folded
into ONE index.  This module (round 19) extends it onto the replicated
routed placement: ``write()`` routes each upsert to its home IVF list
through the SAME replicated coarse quantizer the probe path uses
(:func:`raft_tpu.distributed.ann.route_vectors` — a written row is
found by exactly the probes that would scan it after a fold), every
owning replica appends to its OWN per-shard CRC-framed WAL + memtable,
and the ack gates on a **write quorum** ``w`` (default ``w = r``, the
replication factor; ``w < r`` is permitted — the id<0 mask seam plus
the k-bounded merge guarantee reads still see every acked row from any
single live replica).

Layout (per shard ``s``)::

    <wal_dir>/shard-<s>/wal.log    # that shard's framed record stream
    <wal_dir>/fold/                # ONE CheckpointManager for the fold

**The two-LSN broadcast-tombstone scheme.**  Routing is by VECTOR, so
re-upserting an id whose embedding moved may route it to a DIFFERENT
list — and a different owner set — leaving stale live copies of the id
on the old owners, invisible to the new ones.  Every upsert therefore
consumes two global LSNs: ``base+1`` is an ``OP_DELETE`` record
carrying the WHOLE batch's ids, broadcast to EVERY live shard (it
tombstones any stale copy anywhere and masks the main index through
the union-tombstone merge), and ``base+2`` is the ``OP_UPSERT`` record
each owner receives with its owned row subset.  Both records share one
per-shard fsync, the returned ack LSN is the upsert's, and the
memtable's lsn-idempotence still holds (one record per LSN per shard).
Deletes are the degenerate case: one LSN, broadcast everywhere.

**Write ownership follows the health lifecycle.**  A FAILED (or
CATCHING_UP) shard has no write eligibility: the ack planner
(:meth:`raft_tpu.distributed.routing.RoutingPolicy.ack_plan`) re-plans
acks onto the surviving replicas with zero recompiles (routing tables
are data, not shape).  A write whose touched lists have lost ALL their
replicas refuses with a typed :class:`Unavailable` — before a single
WAL byte — instead of silently dropping.  A per-shard fsync failure
strikes the shard (``HealthTracker.note_write_error``) and fails the
ack only when it leaves some touched list under quorum.

**Catch-up delta phase.**  A recovering shard's WAL + memtable are
rebuilt from the live replicas' logs (:meth:`RoutedIngest.catch_up_shard`,
invoked by :func:`raft_tpu.distributed.health.catch_up` while the
shard is CATCHING_UP): records are merged ACROSS source WALs by global
LSN (row subsets union per LSN), upsert rows are re-routed and
filtered to the lists the shard owns at any rank, deletes are kept
whole (they were broadcast), and the rebuilt log is fsync'd before the
canary-gated readmission.

**Fold.**  :meth:`RoutedIngest.fold` drains ALL shard memtables under
ONE placement-generation bump: the per-shard fold payloads are unioned
with keep-max-LSN duplicate-id resolution, applied to the single-node
base index as the delete+extend upsert pattern (one index generation
bump), verified + canary-gated, committed to the checkpoint (the
crash-window discipline of the PR 13 fold: before the marker rolls
back, after it rolls forward), re-sharded under the NEXT placement
generation, published, and only then are the per-shard WALs truncated
and memtables reset.

Fault sites: ``ingest.dist.{route,append,ack,replicate,fold,catch_up}``
(plus the per-shard WALs' inherited ``ingest.{append,fsync,truncate}``)
— the kill matrix injects ``FaultPlan.kill_shard_at`` at every one and
asserts zero acked-row loss and bit-identical post-recovery search at
r=2.  Counters: ``serving.ingest.dist.{appended,acked,replayed,folds}``;
events: ``serving.ingest.dist.{unavailable,write_error,replay,
catch_up,fold}``.
"""

from __future__ import annotations

import dataclasses
import io
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu import observability as obs
from raft_tpu.core.error import RaftError, expects
from raft_tpu.core.serialize import CorruptIndexError
from raft_tpu.distance.types import DistanceType
from raft_tpu.integrity import canary as _canary
from raft_tpu.integrity.verify import verify as _verify_index
from raft_tpu.neighbors import delta as _delta
from raft_tpu.neighbors import ivf_pq
from raft_tpu.neighbors import mutate as _mutate
from raft_tpu.observability import flight as _flight
from raft_tpu.resilience import faults
from raft_tpu.resilience.checkpoint import CheckpointManager
from raft_tpu.serving.ingest import (
    _FOLD_STAGE,
    _OPS,
    _WAL_FILE,
    WriteAheadLog,
    _id_span,
    encode_record,
    scan_wal,
)

_FOLD_DIR = "fold"


def _count(name: str) -> None:
    if obs.enabled():
        obs.registry().counter(name).inc()


def _gauge(name: str, value: float) -> None:
    if obs.enabled():
        obs.registry().gauge(name).set(value)


class Unavailable(RaftError):
    """A write's routed lists have lost ALL their replicas (or no shard
    is live at all): the write is REFUSED — typed, before any WAL byte
    — never silently dropped.  Retry after a catch-up readmits a
    replica."""


@dataclasses.dataclass
class DistIngestConfig:
    """Distributed write-path knobs (docs/api.md "Distributed ingest &
    write quorum").

    ``write_quorum`` is ``w`` — how many owning replicas must be
    fsync-durable before a write acks (0 means ``w = r``, full
    replication).  ``w < r`` trades re-replication debt for ack
    latency; reads stay correct from any single acked replica (id<0
    mask seam + k-bounded merge).  The remaining knobs mirror
    :class:`~raft_tpu.serving.ingest.IngestConfig` per shard."""

    wal_dir: str = "dist-ingest-wal"
    write_quorum: int = 0
    memtable_capacity: int = 1024
    tomb_capacity: int = 1024
    max_memtable_rows: int = 8192
    fold_rows: int = 0
    fold_tombstones: int = 0
    verify_level: str = "statistical"


class RoutedIngest:
    """The durable replicated write path over one
    :class:`~raft_tpu.distributed.ann.RoutedIndex` plus its single-node
    base index (the fold substrate — the routed pytree is re-sharded
    from it under each placement-generation bump).

    ``tracker`` (a :class:`~raft_tpu.distributed.health.HealthTracker`)
    makes write eligibility follow the shard lifecycle; ``policy`` (a
    :class:`~raft_tpu.distributed.routing.RoutingPolicy`) load-orders
    the ack plan.  Both optional: without them, down shards come from
    the active fault plan alone and acks follow replica-rank order.
    Call :meth:`recover` before the first :meth:`write`."""

    def __init__(self, handle, routed, base, *,
                 config: Optional[DistIngestConfig] = None,
                 tracker=None, policy=None,
                 clock=time.monotonic) -> None:
        from raft_tpu.distributed import ann as _dann
        expects(isinstance(routed, _dann.RoutedIndex)
                and routed.placement is not None,
                "dist_ingest: RoutedIngest needs a RoutedIndex with a "
                "placement map (placement='by_list')")
        self.handle = handle
        self.config = config or DistIngestConfig()
        self.tracker = tracker
        self.policy = policy
        self._clock = clock
        self._index = routed
        self._base = base
        self.n_shards = int(routed.n_shards)
        self.dim = int(routed.dim)
        self.metric = DistanceType(routed.metric)
        self.memtables = [
            _delta.Memtable(self.dim,
                            capacity=self.config.memtable_capacity,
                            tomb_capacity=self.config.tomb_capacity,
                            metric=self.metric)
            for _ in range(self.n_shards)]
        for s in range(self.n_shards):
            os.makedirs(self._shard_dir(s), exist_ok=True)
        self._ck = CheckpointManager(
            os.path.join(self.config.wal_dir, _FOLD_DIR))
        self._wals: List[Optional[WriteAheadLog]] = [None] * self.n_shards
        self._server = None
        self._lsn = 0
        self._lock = threading.Lock()        # append order + routing
        self._fold_lock = threading.Lock()
        self._recovered = False

    # ---- wiring ----------------------------------------------------------

    def _shard_dir(self, s: int) -> str:
        return os.path.join(self.config.wal_dir, f"shard-{s}")

    def wal_path(self, s: int) -> str:
        return os.path.join(self._shard_dir(s), _WAL_FILE)

    def bind(self, server) -> None:
        """Attach a publish target for fold generations (``Server``-like:
        anything with ``swap_index``).  Unlike the single-writer tier
        there is no delta-seam attach — the distributed read path merges
        every shard memtable through :meth:`search`."""
        self._server = server

    def swap_index(self, routed) -> None:
        """Install a new routed generation (the readmission publish
        path: :func:`raft_tpu.distributed.health.readmit` hands the
        caught-up index here or to a bound server)."""
        self._index = routed
        if self._server is not None:
            self._server.swap_index(routed)

    @property
    def index(self):
        return self._index

    def _down(self) -> Tuple[int, ...]:
        down = set(faults.failed_shards(self.n_shards))
        if self.tracker is not None:
            down |= set(self.tracker.failed_shards())
        return tuple(sorted(down))

    def _open_wal(self, s: int) -> WriteAheadLog:
        if self._wals[s] is None:
            self._wals[s] = WriteAheadLog(self.wal_path(s))
        return self._wals[s]

    # ---- recovery --------------------------------------------------------

    def recover(self, base=None, routed=None):
        """Roll an interrupted fold forward/back, then per shard: repair
        a torn WAL tail and replay the intact records into that shard's
        memtable.  Returns the routed index to serve.  Idempotent; must
        run before the first :meth:`write`.

        Roll-FORWARD (commit marker present): the checkpointed fold
        candidate (base index + placement) is re-sharded and served, and
        the interrupted per-shard truncations complete.  Roll-BACK
        (fold died before its marker): the base index is untouched and
        the full per-shard replay reproduces every logged record."""
        from raft_tpu.distributed import ann as _dann
        if base is not None:
            self._base = base
        if routed is not None:
            self._index = routed
        rolled_forward = False
        if self._ck.has(_FOLD_STAGE):
            try:
                cand, placement, fold_lsn = self._load_fold()
                self._base = cand
                self._index = _dann.shard_by_list(self.handle, cand,
                                                  placement=placement)
                for s in range(self.n_shards):
                    self._open_wal(s).truncate_all()
                    self.memtables[s].reset()
                self._ck.clear()
                rolled_forward = True
                _flight.record_event("serving.ingest.dist.replay",
                                     rolled_forward=True,
                                     fold_lsn=fold_lsn,
                                     generation=_mutate.generation(cand))
            except CorruptIndexError:
                self._ck.clear()
        elif self._ck.completed:
            self._ck.clear()
        if not rolled_forward:
            last_lsn = 0
            total = 0
            dropped = 0
            for s in range(self.n_shards):
                wal = self._open_wal(s)
                records, good_end = scan_wal(wal.read_bytes())
                dropped += wal.repair_tail(good_end)
                for rec in records:
                    if self.memtables[s].apply(rec):
                        total += 1
                        _count("serving.ingest.dist.replayed")
                last_lsn = max(last_lsn,
                               max((r.lsn for r in records), default=0))
            self._lsn = max(self._lsn, last_lsn)
            if total or dropped:
                _flight.record_event("serving.ingest.dist.replay",
                                     rolled_forward=False, records=total,
                                     truncated_bytes=dropped,
                                     last_lsn=self._lsn)
        self._recovered = True
        return self._index

    # ---- the write path --------------------------------------------------

    def write(self, ids, vectors=None, *, op: str = "upsert",
              tenant: str = "default") -> int:
        """Route one upsert/delete batch to its list owners, append to
        every live owning replica's WAL (upserts ride the two-LSN
        broadcast-tombstone scheme — see the module docstring), fsync
        per shard, and ack once the write quorum ``w`` holds for every
        touched list.  Returns the batch's ack LSN.

        A raised exception means NOT acknowledged — the records may be
        durable on some replicas and the caller must retry (idempotent
        by id and LSN).  :class:`Unavailable` means some touched list
        has NO live replica: nothing was appended anywhere."""
        expects(self._recovered,
                "dist_ingest: recover() must run before the first write")
        opcode = _OPS.get(op)
        expects(opcode is not None,
                f"dist_ingest: op must be 'upsert' or 'delete', got {op!r}")
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        expects(ids.size > 0, "dist_ingest: write needs at least one id")
        expects(int(ids.min()) >= 0,
                "dist_ingest: source ids must be >= 0")
        if opcode == _delta.OP_UPSERT:
            vecs = np.ascontiguousarray(vectors, np.float32)
            if vecs.ndim == 1:
                vecs = vecs[None, :]
            expects(vecs.shape == (ids.size, self.dim),
                    f"dist_ingest: vectors must be ({ids.size}, "
                    f"{self.dim}), got {vecs.shape}")
        else:
            expects(vectors is None, "dist_ingest: delete takes no vectors")
            vecs = None
        with self._lock:
            return self._write_locked(opcode, ids, vecs)

    def _write_locked(self, opcode: int, ids: np.ndarray,
                      vecs: Optional[np.ndarray]) -> int:
        from raft_tpu.distributed import ann as _dann
        # lifecycle-boundary kill site: a shard killed HERE is seen by
        # the NEXT write's down-set; this write keeps pre-kill routing
        # (the documented kill_shard_at membership semantics)
        faults.maybe_fail("ingest.dist.route")
        down = self._down()
        downset = set(down)
        live = [s for s in range(self.n_shards) if s not in downset]
        placement = self._index.placement
        if opcode == _delta.OP_UPSERT:
            lists = _dann.route_vectors(self._index, vecs)
            touched = sorted({int(g) for g in lists})
            plan = self._ack_plan(placement, down, touched)
            lost = [g for g in touched if not plan[g]]
        else:
            lists = None
            touched = []
            plan = {}
            lost = [] if live else [-1]
        if lost:
            _count("serving.ingest.dist.unavailable")
            _flight.record_event("serving.ingest.dist.unavailable",
                                 lists=[int(g) for g in lost],
                                 rows=int(ids.size), down=list(down))
            raise Unavailable(
                f"dist_ingest: lists {lost} have no live replica "
                f"(down shards {list(down)}) — the write is refused, "
                f"not dropped; retry after a replica is readmitted")
        r = placement.replication_factor
        w = min(self.config.write_quorum or r, r)
        # leaders: the first live owner of each touched list (deletes:
        # the lowest live shard) — their appends classify as the
        # ``ingest.dist.append`` site, every other live shard's as
        # ``ingest.dist.replicate``
        leaders = ({plan[g][0] for g in touched} if touched
                   else {live[0]})
        base = self._lsn
        tomb_rec = encode_record(base + 1, _delta.OP_DELETE, ids, None)
        tomb = _delta.Record(lsn=base + 1, op=_delta.OP_DELETE, ids=ids)
        up_recs: Dict[int, Tuple[bytes, _delta.Record]] = {}
        if opcode == _delta.OP_UPSERT:
            owners_of: Dict[int, List[int]] = {}
            for g in touched:
                for s in plan[g]:
                    owners_of.setdefault(s, []).append(g)
            for s, gs in owners_of.items():
                mask = np.isin(lists, gs)
                sub_ids = ids[mask]
                sub_vecs = vecs[mask]
                up_recs[s] = (
                    encode_record(base + 2, _delta.OP_UPSERT, sub_ids,
                                  sub_vecs),
                    _delta.Record(lsn=base + 2, op=_delta.OP_UPSERT,
                                  ids=sub_ids, vectors=sub_vecs))
            ack_lsn = base + 2
        else:
            ack_lsn = base + 1
        self._lsn = ack_lsn
        synced: set = set()
        first_err: Optional[BaseException] = None
        for s in live:
            try:
                # literal site per branch: the leader's append is the
                # ``ingest.dist.append`` boundary, every other replica's
                # the ``ingest.dist.replicate`` one
                if s in leaders:
                    faults.maybe_fail("ingest.dist.append")
                else:
                    faults.maybe_fail("ingest.dist.replicate")
                wal = self._open_wal(s)
                wal.append(tomb_rec)
                if s in up_recs:
                    wal.append(up_recs[s][0])
                # ONE fsync covers both records — the tombstone and its
                # upsert half are atomically durable together
                wal.sync()
                synced.add(s)
                _count("serving.ingest.dist.appended")
            except Exception as exc:      # noqa: BLE001 — per-shard fault
                if first_err is None:
                    first_err = exc
                _count("serving.ingest.dist.write_error")
                _flight.record_event("serving.ingest.dist.write_error",
                                     shard=int(s), lsn=ack_lsn,
                                     error=type(exc).__name__)
                if self.tracker is not None:
                    self.tracker.note_write_error(s)
                continue
            # searchable on the durable replicas (memtable order == WAL
            # order per shard; visibility decoupled from the quorum ack,
            # same as the single-writer tier)
            self.memtables[s].apply(tomb)
            if s in up_recs:
                self.memtables[s].apply(up_recs[s][1])
        faults.maybe_fail("ingest.dist.ack")
        if opcode == _delta.OP_UPSERT:
            short = [g for g in touched
                     if len([s for s in plan[g] if s in synced])
                     < min(w, len(plan[g]))]
        else:
            short = [] if len(synced) >= min(w, len(live)) else [-1]
        if short:
            if first_err is not None:
                raise first_err
            raise Unavailable(
                f"dist_ingest: write quorum w={w} not met for lists "
                f"{short} — the batch is NOT acknowledged; retry")
        _count("serving.ingest.dist.acked")
        _gauge("serving.ingest.dist.last_lsn", ack_lsn)
        return ack_lsn

    def _ack_plan(self, placement, down: Sequence[int],
                  lists: Sequence[int]) -> Dict[int, List[int]]:
        if self.policy is not None:
            return self.policy.ack_plan(placement, down, lists=lists)
        owners, _ = placement.rank_tables()
        downset = {int(s) for s in down}
        return {int(g): [int(owners[j, g]) for j in range(owners.shape[0])
                         if int(owners[j, g]) not in downset]
                for g in lists}

    # ---- the read path ---------------------------------------------------

    def search(self, params, queries, k: int, **kwargs):
        """Routed search merged with EVERY shard memtable's delta scan
        (:func:`raft_tpu.neighbors.delta.merge_with_main_multi`).  Down
        shards join as MASKED views (ids/tombs all -1) with identical
        shapes, so shard membership stays data, not shape — zero
        recompiles across failover; the k-bounded merge pulls every
        acked row from whichever live replica holds it."""
        from raft_tpu.distributed import ann as _dann
        from raft_tpu.integrity import boundary as _boundary
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        queries, _ok = _boundary.check_matrix(
            queries, "queries", site="serving.ingest.dist.search",
            dim=self.dim, allow_empty=False, host=True)
        q = jnp.asarray(queries)
        d, i = _dann.search(self.handle, params, self._index, q, int(k),
                            health=self.tracker, routing=self.policy,
                            **kwargs)
        downset = set(self._down())
        deltas = []
        tombs = []
        for s in range(self.n_shards):
            data, mids, tb = self.memtables[s].device_view()
            if s in downset:
                mids = jnp.full_like(mids, -1)
                tb = jnp.full_like(tb, -1)
            deltas.append((data, mids))
            tombs.append(tb)
        return _delta.merge_with_main_multi(d, i, q, deltas, tombs,
                                            k=int(k), metric=self.metric)

    # ---- catch-up delta phase --------------------------------------------

    def catch_up_shard(self, shard: int) -> int:
        """Rebuild ``shard``'s WAL + memtable from the live replicas'
        logs — the delta phase of
        :func:`raft_tpu.distributed.health.catch_up`, run while the
        shard is CATCHING_UP (out of the routing).  Records are merged
        across the source WALs by global LSN (row subsets union per
        LSN), upsert rows are re-routed and kept only when their home
        list is owned by ``shard`` at ANY replica rank, deletes are
        kept whole (they were broadcast).  Returns the number of
        records the rebuilt shard holds."""
        from raft_tpu.distributed import ann as _dann
        s = int(shard)
        expects(0 <= s < self.n_shards,
                f"dist_ingest: shard {s} out of range")
        with self._lock:
            faults.maybe_fail("ingest.dist.catch_up")
            downset = set(self._down()) | {s}
            sources = [j for j in range(self.n_shards) if j not in downset]
            expects(bool(sources),
                    "dist_ingest: catch-up needs at least one live "
                    "replica to replay from")
            # merge by LSN across sources: replicated copies of a record
            # share an LSN; partial-quorum histories leave different
            # subsets per source, so rows UNION per LSN
            ops: Dict[int, int] = {}
            rows: Dict[int, Dict[int, Optional[np.ndarray]]] = {}
            for j in sources:
                data = self._open_wal(j).read_bytes()
                records, _good_end = scan_wal(data)
                for rec in records:
                    ops[rec.lsn] = rec.op
                    bucket = rows.setdefault(rec.lsn, {})
                    for t, i in enumerate(rec.ids):
                        bucket[int(i)] = (rec.vectors[t]
                                          if rec.vectors is not None
                                          else None)
            owned = {int(g) for g in
                     self._index.placement.shard_lists(s)}
            wal = self._open_wal(s)
            wal.truncate_all()
            self.memtables[s].reset()
            kept = 0
            for lsn in sorted(ops):
                op = ops[lsn]
                rids = np.array(sorted(rows[lsn]), np.int64)
                if op == _delta.OP_UPSERT:
                    vecs = np.stack([rows[lsn][int(i)] for i in rids]
                                    ) if rids.size else np.zeros(
                                        (0, self.dim), np.float32)
                    home = (_dann.route_vectors(self._index, vecs)
                            if rids.size else np.zeros(0, np.int64))
                    keep = np.array([g in owned for g in home], bool)
                    rids, vecs = rids[keep], vecs[keep]
                    if not rids.size:
                        continue
                else:
                    vecs = None
                rec = _delta.Record(lsn=lsn, op=op, ids=rids, vectors=vecs)
                wal.append(encode_record(lsn, op, rids, vecs))
                self.memtables[s].apply(rec)
                kept += 1
            wal.sync()
            _flight.record_event("serving.ingest.dist.catch_up",
                                 shard=s, records=kept,
                                 sources=len(sources),
                                 rows=self.memtables[s].live_rows)
            return kept

    # ---- fold ------------------------------------------------------------

    def maybe_fold(self):
        """Fold when the summed memtable rows / tombstones cross the
        configured thresholds (the maintenance-pass hook); returns the
        new routed index or None."""
        rows = sum(m.live_rows for m in self.memtables)
        tombs = sum(m.n_tombstones for m in self.memtables)
        cfg = self.config
        if ((cfg.fold_rows and rows >= cfg.fold_rows)
                or (cfg.fold_tombstones and tombs >= cfg.fold_tombstones)):
            return self.fold()
        return None

    def fold(self):
        """Drain ALL shard memtables into the base index under ONE
        placement-generation bump: union the per-shard fold payloads
        (keep-max-LSN per duplicate id — replicated copies share an
        LSN; a partial-quorum history keeps the newest write), run the
        delete+extend upsert pattern on the single-node base (one index
        generation bump), verify + canary-gate, commit the checkpoint,
        re-shard under the bumped placement, publish, then truncate
        every shard WAL and reset every memtable.  Returns the new
        routed index, or None when every delta tier is empty."""
        from raft_tpu.distributed import ann as _dann
        with self._fold_lock, self._lock:
            if all(m.live_rows == 0 and m.n_tombstones == 0
                   for m in self.memtables):
                return None
            faults.maybe_fail("ingest.dist.fold")
            with obs.stage("serving.ingest.dist.fold"):
                fold_lsn = self._lsn
                best: Dict[int, Tuple[int, np.ndarray]] = {}
                tomb_ids: set = set()
                for mem in self.memtables:
                    li, rows, lsns, tids = mem.fold_items()
                    tomb_ids.update(int(t) for t in tids)
                    for j in range(li.size):
                        i = int(li[j])
                        cur = best.get(i)
                        if cur is None or int(lsns[j]) > cur[0]:
                            best[i] = (int(lsns[j]), rows[j])
                live_ids = np.array(sorted(best), np.int64)
                live_rows = (np.stack([best[int(i)][1] for i in live_ids])
                             if live_ids.size
                             else np.zeros((0, self.dim), np.float32))
                base = self._base
                parent_gen = _mutate.generation(base)
                clear = np.union1d(
                    np.array(sorted(tomb_ids), np.int64),
                    live_ids).astype(np.int32)
                cand = base
                if clear.size:
                    cand = ivf_pq.delete(self.handle, cand,
                                         jnp.asarray(clear))
                if live_ids.size:
                    cand = ivf_pq.extend(self.handle, cand,
                                         jnp.asarray(live_rows),
                                         jnp.asarray(live_ids))
                cand.generation = parent_gen + 1
                _verify_index(cand, self.config.verify_level,
                              res=self.handle, n_rows=_id_span(cand))
                if getattr(cand, "canaries", None) is not None:
                    _canary.health_check(self.handle, cand,
                                         raise_on_fail=True)
                # ONE placement-generation bump for the whole drain: the
                # re-shard below carries every shard's drained rows
                old_placement = self._index.placement
                new_placement = _dann.compute_placement(
                    np.asarray(_mutate.live_sizes(cand.list_indices)),
                    self.n_shards,
                    generation=old_placement.generation + 1,
                    replication_factor=old_placement.replication_factor)
                # durable commit marker BEFORE the publish: a kill after
                # this point rolls FORWARD in recover()
                self._save_fold(cand, new_placement, fold_lsn)
                routed = _dann.shard_by_list(self.handle, cand,
                                             placement=new_placement)
                self._base = cand
                self.swap_index(routed)
                for s in range(self.n_shards):
                    self._open_wal(s).truncate_all()
                    self.memtables[s].reset()
                self._ck.clear()
                _count("serving.ingest.dist.folds")
                _flight.record_event(
                    "serving.ingest.dist.fold",
                    rows=int(live_ids.size),
                    tombstones=len(tomb_ids), fold_lsn=fold_lsn,
                    generation=_mutate.generation(cand),
                    placement_generation=new_placement.generation)
            return routed

    def _save_fold(self, cand, placement, fold_lsn: int) -> None:
        buf = io.BytesIO()
        ivf_pq.serialize(self.handle, buf, cand)
        pbuf = io.BytesIO()
        from raft_tpu.distributed import ann as _dann
        _dann.placement_to_stream(self.handle, pbuf, placement)
        self._ck.save(_FOLD_STAGE, {
            "index": np.frombuffer(buf.getvalue(), np.uint8),
            "placement": np.frombuffer(pbuf.getvalue(), np.uint8),
            "generation": np.asarray([_mutate.generation(cand)], np.int64),
            "fold_lsn": np.asarray([fold_lsn], np.int64)})

    def _load_fold(self):
        from raft_tpu.distributed import ann as _dann
        arrays = self._ck.load(_FOLD_STAGE)
        cand = ivf_pq.deserialize(
            self.handle, io.BytesIO(bytes(arrays["index"])))
        cand.generation = int(arrays["generation"][0])
        placement = _dann.placement_from_stream(
            self.handle, io.BytesIO(bytes(arrays["placement"])))
        return cand, placement, int(arrays["fold_lsn"][0])

    # ---- lifecycle -------------------------------------------------------

    def prewarm(self, batches: Sequence[int]) -> int:
        """Pre-trace the write router at the serving batch shapes (see
        :func:`raft_tpu.core.aot.warm_write_router`) so the first write
        after a deploy or failover is compile-free."""
        from raft_tpu.core import aot as _aot
        return _aot.warm_write_router(self._index, batches)

    def close(self) -> None:
        for s in range(self.n_shards):
            if self._wals[s] is not None:
                self._wals[s].close()
                self._wals[s] = None

    def __enter__(self) -> "RoutedIngest":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> Dict[str, object]:
        return {
            "last_lsn": self._lsn,
            "memtable_rows": [m.live_rows for m in self.memtables],
            "tombstones": [m.n_tombstones for m in self.memtables],
            "wal_bytes": [w.size_bytes if w is not None else 0
                          for w in self._wals],
            "down": list(self._down()),
            "placement_generation": self._index.placement.generation,
        }
