"""Matrix primitives.

Reference: cpp/include/raft/matrix/ (SURVEY.md §2.4) — headlined by
``select_k`` (matrix/select_k.cuh:78), the batched top-k primitive that gates
every ANN search path, plus gather/argmin/slice/sort/linewise utilities.
"""

from raft_tpu.matrix.select_k import select_k, merge_topk  # noqa: F401
from raft_tpu.matrix.ops import (  # noqa: F401
    gather,
    gather_if,
    scatter,
    argmax,
    argmin,
    slice as slice_matrix,
    copy,
    init,
    linewise_op,
    col_wise_sort,
    reverse,
    sign_flip,
    diagonal,
    set_diagonal,
    triangular_upper,
    zero_small_values,
    row_duplicate_mask,
)
