"""Batched top-k selection.

Reference: raft/matrix/select_k.cuh:78 — THE central ANN primitive.  The
reference dispatches between a radix select (detail/select_radix.cuh, 8/11-bit
digit passes) and a warp-bitonic sort select (detail/select_warpsort.cuh,
k<=256) on a heuristic (detail/select_k.cuh:67-89: radix is faster for
batch>=64 && len>=102400 && k>=128).

TPU-first design: XLA's ``lax.top_k`` / ``lax.approx_max_k`` already lower to
tuned TPU sort networks — there are no warp shuffles to hand-roll.  We keep the
reference semantics (select smallest or largest, optional input index payload,
stable ordering of results) and add a *two-pass tiled* path for very wide
inputs, mirroring the radix path's role: tile the length dimension, take a
local top-k per tile (parallel, VMEM-sized), then a final top-k over the
concatenated candidates.  That caps the sort length at
``n_tiles * k`` regardless of len.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.outputs import auto_convert_output

# Length beyond which the two-pass tiled path wins (the analogue of the
# reference's radix_faster heuristic, detail/select_k.cuh:67-89).
_TILE_LEN = 16384


def _top_k_smallest(x: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    vals, idx = jax.lax.top_k(-x, k)
    return -vals, idx


@auto_convert_output
def select_k(
    in_val: jax.Array,
    k: int,
    *,
    in_idx: Optional[jax.Array] = None,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Select the k smallest (or largest) values per row, with their indices.

    Parameters mirror matrix/select_k.cuh:78: ``in_val`` is (batch, len);
    optional ``in_idx`` is a per-element payload of indices (defaults to
    0..len-1 per row); returns ``(out_val, out_idx)`` each (batch, k), sorted
    ascending when ``select_min`` else descending.
    """
    expects(in_val.ndim == 2, "select_k: (batch, len) input required")
    batch, length = in_val.shape
    expects(0 < k <= length, f"select_k: need 0 < k <= len, got k={k}, len={length}")
    if in_idx is not None:
        expects(in_idx.shape == in_val.shape, "select_k: in_idx shape mismatch")

    if length > _TILE_LEN and length >= 4 * k:
        vals, idx = _tiled_select(in_val, k, select_min)
    else:
        vals, idx = (_top_k_smallest(in_val, k) if select_min
                     else jax.lax.top_k(in_val, k))

    if in_idx is not None:
        idx = jnp.take_along_axis(in_idx, idx, axis=1)
    return vals, idx


@auto_convert_output
def merge_topk(
    best_val: jax.Array,
    best_idx: jax.Array,
    new_val: jax.Array,
    new_idx: jax.Array,
    *,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Merge a running (batch, k) top-k with (batch, kt) new candidates.

    The shared streaming-selection step used by brute-force kNN tiling and the
    IVF probe scans (the role detail/knn_merge_parts.cuh's kernel plays in the
    reference): concatenate, re-select k, gather payloads.
    """
    k = best_val.shape[1]
    cat_v = jnp.concatenate([best_val, new_val], axis=1)
    cat_i = jnp.concatenate([best_idx, new_idx], axis=1)
    if select_min:
        vals, pos = jax.lax.top_k(-cat_v, k)
        vals = -vals
    else:
        vals, pos = jax.lax.top_k(cat_v, k)
    return vals, jnp.take_along_axis(cat_i, pos, axis=1)


def _tiled_select(in_val: jax.Array, k: int, select_min: bool
                  ) -> Tuple[jax.Array, jax.Array]:
    """Two-pass selection: per-tile top-k, then top-k of candidates.

    Plays the role of the radix path (detail/select_radix.cuh): avoids sorting
    the full length at once.  Padding uses +/-inf sentinels so partial tiles
    never win.
    """
    batch, length = in_val.shape
    n_tiles = -(-length // _TILE_LEN)
    padded = n_tiles * _TILE_LEN
    sentinel = jnp.inf if select_min else -jnp.inf
    x = jnp.pad(in_val, ((0, 0), (0, padded - length)),
                constant_values=sentinel)
    x = x.reshape(batch, n_tiles, _TILE_LEN)

    kk = min(k, _TILE_LEN)
    if select_min:
        tile_vals, tile_idx = jax.lax.top_k(-x, kk)
        tile_vals = -tile_vals
    else:
        tile_vals, tile_idx = jax.lax.top_k(x, kk)
    # global index of each candidate
    base = (jnp.arange(n_tiles) * _TILE_LEN)[None, :, None]
    cand_idx = (tile_idx + base).reshape(batch, n_tiles * kk)
    cand_vals = tile_vals.reshape(batch, n_tiles * kk)

    if select_min:
        out_vals, pos = jax.lax.top_k(-cand_vals, k)
        out_vals = -out_vals
    else:
        out_vals, pos = jax.lax.top_k(cand_vals, k)
    out_idx = jnp.take_along_axis(cand_idx, pos, axis=1)
    return out_vals, out_idx
