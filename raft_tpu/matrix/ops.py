"""Matrix utilities.

Reference: raft/matrix/{gather,argmax,argmin,slice,copy,init,linewise_op,
col_wise_sort,reverse,sign_flip,diagonal,triangular,threshold}.cuh — each a
bespoke CUDA kernel there; each a fused XLA op here.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


def gather(matrix: jax.Array, map_idx: jax.Array) -> jax.Array:
    """Collect rows by index: out[i] = matrix[map_idx[i]] (reference: gather.cuh)."""
    expects(matrix.ndim == 2 and map_idx.ndim == 1, "gather: (2d, 1d)")
    return jnp.take(matrix, map_idx, axis=0)


def gather_if(matrix: jax.Array, map_idx: jax.Array, stencil: jax.Array,
              pred: Callable[[jax.Array], jax.Array],
              out: jax.Array) -> jax.Array:
    """Conditional row gather (reference: gather.cuh ``gather_if``): rows where
    pred(stencil[i]) keep out[i] replaced by matrix[map_idx[i]]."""
    taken = jnp.take(matrix, map_idx, axis=0)
    mask = pred(stencil)[:, None]
    return jnp.where(mask, taken, out)


def scatter(matrix: jax.Array, map_idx: jax.Array,
            updates: jax.Array) -> jax.Array:
    """out[map_idx[i]] = updates[i] (reference: matrix/scatter.cuh)."""
    return matrix.at[map_idx].set(updates)


def argmax(matrix: jax.Array) -> jax.Array:
    """Per-row argmax (reference: matrix/argmax.cuh)."""
    return jnp.argmax(matrix, axis=1)


def argmin(matrix: jax.Array) -> jax.Array:
    """Per-row argmin (reference: matrix/argmin.cuh)."""
    return jnp.argmin(matrix, axis=1)


def slice(matrix: jax.Array, x1: int, y1: int, x2: int, y2: int) -> jax.Array:
    """Copy the [x1:x2, y1:y2] submatrix (reference: matrix/slice.cuh)."""
    return matrix[x1:x2, y1:y2]


def copy(matrix: jax.Array) -> jax.Array:
    return jnp.array(matrix)


def init(shape: Tuple[int, ...], value, dtype=jnp.float32) -> jax.Array:
    """Reference: matrix/init.cuh."""
    return jnp.full(shape, value, dtype=dtype)


def linewise_op(matrix: jax.Array, op: Callable, *vecs: jax.Array,
                along_lines: bool = True) -> jax.Array:
    """Apply op(row_element, vec_element...) line-wise
    (reference: matrix/linewise_op.cuh)."""
    if along_lines:
        bvecs = [v[None, :] for v in vecs]
    else:
        bvecs = [v[:, None] for v in vecs]
    return op(matrix, *bvecs)


def col_wise_sort(matrix: jax.Array, *, ascending: bool = True) -> jax.Array:
    """Sort each column independently (reference: matrix/col_wise_sort.cuh)."""
    out = jnp.sort(matrix, axis=0)
    return out if ascending else out[::-1, :]


def reverse(matrix: jax.Array, *, along_rows: bool = True) -> jax.Array:
    """Reference: matrix/reverse.cuh."""
    return matrix[:, ::-1] if along_rows else matrix[::-1, :]


def sign_flip(matrix: jax.Array) -> jax.Array:
    """Flip column signs so the max-|.| entry of each column is positive —
    deterministic eigenvector orientation (reference: matrix/math.cuh signFlip)."""
    pivot = jnp.take_along_axis(
        matrix, jnp.argmax(jnp.abs(matrix), axis=0)[None, :], axis=0)
    return matrix * jnp.where(pivot < 0, -1.0, 1.0).astype(matrix.dtype)


def diagonal(matrix: jax.Array) -> jax.Array:
    """Reference: matrix/diagonal.cuh ``get_diagonal``."""
    return jnp.diagonal(matrix)


def set_diagonal(matrix: jax.Array, vec: jax.Array) -> jax.Array:
    n = min(matrix.shape)
    idx = jnp.arange(n)
    return matrix.at[idx, idx].set(vec[:n])


def triangular_upper(matrix: jax.Array) -> jax.Array:
    """Upper-triangular copy (reference: matrix/triangular.cuh)."""
    return jnp.triu(matrix)


def zero_small_values(matrix: jax.Array, thresh: float) -> jax.Array:
    """Zero entries below threshold (reference: matrix/threshold.cuh)."""
    return jnp.where(jnp.abs(matrix) < thresh, 0.0, matrix).astype(matrix.dtype)


def row_duplicate_mask(matrix: jax.Array) -> jax.Array:
    """Per-row mask of duplicate values, keeping each value's FIRST
    occurrence (stable double-argsort maps the sorted adjacent-equal
    flags back to original positions, so earlier columns win ties).
    Shared by the scan paths that merge candidate-id operands (CAGRA
    rerank, IVF-Flat super-tile probe dedupe) — the tie/stability
    semantics are subtle enough that one copy must own them."""
    s = jnp.sort(matrix, axis=1)
    dup_sorted = jnp.concatenate(
        [jnp.zeros((matrix.shape[0], 1), jnp.bool_),
         s[:, 1:] == s[:, :-1]], axis=1)
    rank = jnp.argsort(jnp.argsort(matrix, axis=1, stable=True), axis=1)
    return jnp.take_along_axis(dup_sorted, rank, axis=1)
