"""The delta tier: an always-mutable memtable absorbing streaming writes.

Every batch index in :mod:`raft_tpu.neighbors` mutates by *snapshot* —
``extend`` / ``delete`` / ``upsert`` return a NEW index one generation
up, and the serving tier re-warms an executable table per generation.
That is the right contract for batch updates and exactly the wrong one
for sustained write traffic: a generation bump per write puts an AOT
re-warm on the write path.  This module is the LSM answer (ROADMAP
item 2): a small, flat, **always-mutable** :class:`Memtable` that
absorbs upserts/deletes at host-memory speed and is searched alongside
the main index by treating the delta as one more "shard" in the PR 8
``finalize_topk`` k-bounded merge.

Design invariants:

- **Single deterministic mutation path.**  :meth:`Memtable.apply` is
  the only way state changes — live writes and WAL replay
  (:mod:`raft_tpu.serving.ingest`) drive the same code, so recovery is
  bit-identical by construction, not by testing luck.  Records carry a
  log sequence number; ``apply`` is idempotent under replay
  (``lsn <= applied_lsn`` is a no-op), which makes duplicated replay
  after a torn-tail repair safe.
- **Shape-static device snapshot.**  The scan arrays are fixed at
  ``capacity`` rows (empty slots carry id -1 and ride the existing
  id<0 mask seam), so steady-state searches with the delta attached
  never recompile.  Filling past capacity doubles it under a
  generation bump — ONE expected recompile per regrow, never one per
  write (the PR 10 shape-static discipline, asserted via
  ``xla.compiles`` in the serving tests).
- **Tombstones mask the main index through the id<0 seam.**  A delete
  (and the delete-half of an upsert whose id may live in the main
  index) records the id in the tombstone set; :func:`merge_with_main`
  rewrites matching main-index hits to worst-distance / id -1 — the
  same sentinel convention every scan kernel already honors — before
  the shared :func:`~raft_tpu.neighbors.grouped.finalize_topk`
  epilogue selects the public top-k.
- **Upserts never double-tombstone.**  An id already live in the
  memtable overwrites its slot in place; the main-index tombstone was
  recorded once at first sight and is NOT re-emitted — rapid same-id
  churn costs one slot and one tombstone total (the upsert double-work
  fix; see ``tests/test_ingest.py`` churn regression).

Folding the memtable into the main index (the compaction half of the
LSM) lives in :mod:`raft_tpu.serving.ingest`; this module only exposes
the deterministic state and :meth:`Memtable.fold_payload`.
"""

from __future__ import annotations

import functools
import hashlib
import threading
from typing import Optional, Tuple

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.distance.types import DistanceType
from raft_tpu.matrix.select_k import select_k
from raft_tpu.neighbors import grouped

#: WAL / memtable record opcodes (stable wire values — see ingest.py)
OP_UPSERT = 1
OP_DELETE = 2

_SQRT_METRICS = (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded)
_SUPPORTED_METRICS = (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                      DistanceType.L2Unexpanded,
                      DistanceType.L2SqrtUnexpanded,
                      DistanceType.InnerProduct)


@dataclasses.dataclass(frozen=True)
class Record:
    """One logged write: ``lsn`` orders and deduplicates replay, ``op``
    is :data:`OP_UPSERT` / :data:`OP_DELETE`, ``ids`` the source ids and
    ``vectors`` the (n, dim) float32 rows (upserts only)."""

    lsn: int
    op: int
    ids: np.ndarray
    vectors: Optional[np.ndarray] = None


def _scan_body(data, ids, queries, k: int, metric, filter_words=None):
    """Brute-force delta scan producing PUBLIC-form distances (sqrt
    applied for the sqrt-L2 metrics) so they merge against the main
    index's output without rescaling.  Empty slots (id -1) ride the
    worst-distance sentinel, the same convention every kernel's
    tombstone mask uses.  ``filter_words`` (nq, n_words) int32 packed
    admission bits fold inadmissible memtable rows through the same
    seam — the delta tier honors per-query filters like every other
    scan path."""
    nq = queries.shape[0]
    cap = data.shape[0]
    f32q = queries.astype(jnp.float32)
    if metric == DistanceType.InnerProduct:
        d = f32q @ data.T
        worst = -jnp.inf
        select_min = False
    else:
        qq = jnp.sum(f32q * f32q, axis=1, keepdims=True)
        xx = jnp.sum(data * data, axis=1)[None, :]
        d = jnp.maximum(qq + xx - 2.0 * (f32q @ data.T), 0.0)
        worst = jnp.inf
        select_min = True
    d = jnp.where(ids[None, :] < 0, worst, d)
    bids = jnp.broadcast_to(ids[None, :], (nq, cap))
    if filter_words is not None:
        from raft_tpu.filters import bitset as _fbits
        adm = _fbits.query_bits(filter_words, jnp.arange(nq), bids)
        d = jnp.where(adm > 0, d, worst)
        bids = jnp.where(adm > 0, bids, -1)
    kf = min(k, cap)
    best_d, best_i = select_k(d, kf, in_idx=bids, select_min=select_min)
    if kf < k:
        best_d = jnp.pad(best_d, ((0, 0), (0, k - kf)),
                         constant_values=worst)
        best_i = jnp.pad(best_i, ((0, 0), (0, k - kf)),
                         constant_values=-1)
    best_i = jnp.maximum(best_i, -1)
    if metric in _SQRT_METRICS:
        best_d = jnp.where(jnp.isfinite(best_d),
                           jnp.sqrt(jnp.maximum(best_d, 0.0)), best_d)
    return best_d, best_i


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _delta_scan(data, ids, queries, k: int, metric):
    return _scan_body(data, ids, queries, k, metric)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _merge_with_main(main_d, main_i, queries, data, ids, tombs,
                     k: int, metric, filter_words=None):
    """Delta-as-extra-shard merge: scan the memtable, mask tombstoned
    main-index hits to the worst/-1 sentinel (the id<0 seam), then run
    the shared :func:`grouped.finalize_topk` epilogue over the
    concatenated (nq, 2k) candidates — exactly the PR 8 k-bounded
    routed-shard merge shape with the delta as one more shard.
    ``filter_words`` applies the caller's admission bitset to the delta
    scan (the main results are assumed already filtered)."""
    nq = main_d.shape[0]
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    dd, di = _scan_body(data, ids, queries, k, metric,
                        filter_words=filter_words)
    hit = (main_i >= 0) & jnp.isin(main_i, tombs)
    md = jnp.where(hit, worst, main_d)
    mi = jnp.where(hit, -1, main_i)
    alld = jnp.concatenate([md, dd], axis=1)
    alli = jnp.concatenate([mi, di], axis=1)
    # main_d is already public-form (sqrt applied by the main search)
    # and _scan_body matches it, so the epilogue must not re-sqrt
    return grouped.finalize_topk(alld, alli, nq, k, select_min,
                                 sqrt=False, select_k_fn=select_k)


def merge_with_main(main_d, main_i, queries, data, ids, tombs, *,
                    k: int, metric, filter_words=None
                    ) -> Tuple[jax.Array, jax.Array]:
    """Public wrapper over the jitted merge (static ``k`` / ``metric``)."""
    return _merge_with_main(main_d, main_i, queries, data, ids, tombs,
                            k=int(k), metric=DistanceType(metric),
                            filter_words=filter_words)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _merge_with_main_multi(main_d, main_i, queries, datas, idss, tombs,
                           k: int, metric, filter_words=None):
    """Multi-shard delta merge (round 19, distributed ingest): every
    per-shard memtable joins the :func:`grouped.finalize_topk` merge as
    one more shard.  Two things the single-delta merge never needed:

    - the tombstone mask over the MAIN index is the UNION of every
      shard's tombstone set (deletes broadcast to all live shards, so
      any live memtable may carry the only copy of a tombstone);
    - replicated placement stores each row on ``r`` shards, so the same
      id can surface from up to ``r`` deltas — duplicates are masked to
      the worst/-1 sentinel before the final select, keeping exactly one
      candidate per id (best distance, earliest position on ties; live
      copies are bit-identical replicas, so any survivor is correct).

    The shard tuples are pytree inputs: a down shard is passed as a
    masked view (ids/tombs all -1) with the SAME shapes, so shard
    membership is data, not shape — zero recompiles across failover."""
    nq = main_d.shape[0]
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    all_tombs = jnp.concatenate([t.reshape(-1) for t in tombs])
    hit = (main_i >= 0) & jnp.isin(main_i, all_tombs)
    ds = [jnp.where(hit, worst, main_d)]
    is_ = [jnp.where(hit, -1, main_i)]
    for data, ids in zip(datas, idss):
        dd, di = _scan_body(data, ids, queries, k, metric,
                            filter_words=filter_words)
        ds.append(dd)
        is_.append(di)
    alld = jnp.concatenate(ds, axis=1)
    alli = jnp.concatenate(is_, axis=1)
    # replica dedup over the (nq, C) candidate strip: candidate j is
    # dropped when some j' holds the same id with a better distance (or
    # an equal distance at an earlier position).  C = k*(n_shards+2) is
    # small, so the O(C^2) mask is a few comparisons per query.
    pos = jnp.arange(alld.shape[1])
    same = (alli[:, :, None] == alli[:, None, :]) & (alli[:, :, None] >= 0)
    if select_min:
        beats = alld[:, None, :] < alld[:, :, None]
    else:
        beats = alld[:, None, :] > alld[:, :, None]
    beats = beats | ((alld[:, None, :] == alld[:, :, None])
                     & (pos[None, None, :] < pos[None, :, None]))
    dup = jnp.any(same & beats, axis=-1)
    alld = jnp.where(dup, worst, alld)
    alli = jnp.where(dup, -1, alli)
    return grouped.finalize_topk(alld, alli, nq, k, select_min,
                                 sqrt=False, select_k_fn=select_k)


def merge_with_main_multi(main_d, main_i, queries, deltas, tombs, *,
                          k: int, metric, filter_words=None
                          ) -> Tuple[jax.Array, jax.Array]:
    """Merge the main-index top-k with EVERY shard memtable's delta scan
    (``deltas`` is a sequence of ``(data, ids)`` device views, ``tombs``
    the matching tombstone arrays), deduplicating replicated rows and
    masking the union of tombstones — see :func:`_merge_with_main_multi`.
    """
    datas = tuple(d for d, _ in deltas)
    idss = tuple(i for _, i in deltas)
    return _merge_with_main_multi(main_d, main_i, queries, datas, idss,
                                  tuple(tombs), k=int(k),
                                  metric=DistanceType(metric),
                                  filter_words=filter_words)


class Memtable:
    """Host-canonical mutable row store with a shape-static device view.

    The canonical state is host numpy (``apply`` is a few row writes —
    no device round trip on the write path); the device snapshot used by
    searches is refreshed lazily and swapped as one tuple, so a
    concurrent search sees either the old or the new snapshot wholesale.
    ``capacity`` (rows) and ``tomb_capacity`` (tombstone slots) are the
    compiled shapes; filling either doubles it under a generation bump.
    """

    def __init__(self, dim: int, *, capacity: int = 1024,
                 tomb_capacity: int = 1024,
                 metric=DistanceType.L2Expanded) -> None:
        expects(dim > 0, "delta: dim must be positive")
        expects(capacity > 0 and tomb_capacity > 0,
                "delta: capacity and tomb_capacity must be positive")
        self.dim = int(dim)
        self.metric = DistanceType(metric)
        expects(self.metric in _SUPPORTED_METRICS,
                f"delta: unsupported memtable metric {self.metric!r}")
        self.capacity = int(capacity)
        self.tomb_capacity = int(tomb_capacity)
        self.generation = 0
        self.applied_lsn = 0
        self._data = np.zeros((self.capacity, self.dim), np.float32)
        self._ids = np.full(self.capacity, -1, np.int32)
        self._slot_lsn = np.zeros(self.capacity, np.int64)
        self._slot_of: dict = {}      # id -> slot
        self._tombs: dict = {}        # id -> lsn (masks the main index)
        self._n_used = 0              # append high-water mark
        self._lock = threading.Lock()
        self._dirty = True
        self._dev: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None

    # ---- introspection --------------------------------------------------

    @property
    def live_rows(self) -> int:
        return len(self._slot_of)

    @property
    def n_tombstones(self) -> int:
        return len(self._tombs)

    @property
    def select_min(self) -> bool:
        return self.metric != DistanceType.InnerProduct

    def digest(self) -> str:
        """SHA-256 over the canonical state — the bit-identical-recovery
        assertion surface (kill-matrix tests compare digests across
        independent replays)."""
        with self._lock:
            h = hashlib.sha256()
            h.update(np.int64([self.capacity, self.tomb_capacity,
                               self._n_used, self.applied_lsn]).tobytes())
            h.update(self._data.tobytes())
            h.update(self._ids.tobytes())
            h.update(self._slot_lsn.tobytes())
            for i, lsn in sorted(self._tombs.items()):
                h.update(np.int64([i, lsn]).tobytes())
            return h.hexdigest()

    # ---- the one mutation path ------------------------------------------

    def apply(self, rec: Record) -> bool:
        """Apply one record; returns False for an already-applied lsn
        (duplicate-replay idempotence).  Both the live write path and
        WAL replay come through here — determinism by construction."""
        with self._lock:
            if rec.lsn <= self.applied_lsn:
                return False
            ids = np.asarray(rec.ids, np.int64).reshape(-1)
            if rec.op == OP_UPSERT:
                vecs = np.asarray(rec.vectors, np.float32)
                expects(vecs.ndim == 2 and vecs.shape == (ids.size, self.dim),
                        f"delta: upsert vectors must be ({ids.size}, "
                        f"{self.dim}), got {vecs.shape}")
                for j, raw in enumerate(ids):
                    i = int(raw)
                    expects(i >= 0, "delta: source ids must be >= 0")
                    slot = self._slot_of.get(i)
                    if slot is None:
                        if self._n_used >= self.capacity:
                            self._grow_rows()
                        slot = self._n_used
                        self._n_used += 1
                        self._slot_of[i] = slot
                        self._ids[slot] = i
                        # first sight since the last fold: ONE tombstone
                        # masks any published main-index copy.  An id
                        # already live here was tombstoned then — an
                        # overwrite must not emit a second (the upsert
                        # double-work fix).
                        if i not in self._tombs:
                            if len(self._tombs) >= self.tomb_capacity:
                                self._grow_tombs()
                            self._tombs[i] = int(rec.lsn)
                    self._data[slot] = vecs[j]
                    self._slot_lsn[slot] = rec.lsn
            elif rec.op == OP_DELETE:
                for raw in ids:
                    i = int(raw)
                    slot = self._slot_of.pop(i, None)
                    if slot is not None:
                        self._ids[slot] = -1
                        self._data[slot] = 0.0
                        self._slot_lsn[slot] = rec.lsn
                    if i not in self._tombs:
                        if len(self._tombs) >= self.tomb_capacity:
                            self._grow_tombs()
                    self._tombs[i] = int(rec.lsn)
            else:
                raise ValueError(f"delta: unknown record op {rec.op!r}")
            self.applied_lsn = int(rec.lsn)
            self._dirty = True
            return True

    def _grow_rows(self) -> None:
        """Double the row capacity.  A new compiled shape — ONE expected
        recompile at the next search, bumped as a generation so caches
        and tests can attribute it."""
        new_cap = self.capacity * 2
        data = np.zeros((new_cap, self.dim), np.float32)
        ids = np.full(new_cap, -1, np.int32)
        lsn = np.zeros(new_cap, np.int64)
        data[:self.capacity] = self._data
        ids[:self.capacity] = self._ids
        lsn[:self.capacity] = self._slot_lsn
        self._data, self._ids, self._slot_lsn = data, ids, lsn
        self.capacity = new_cap
        self.generation += 1
        self._dirty = True

    def _grow_tombs(self) -> None:
        self.tomb_capacity *= 2
        self.generation += 1
        self._dirty = True

    def reset(self) -> None:
        """Drop all rows and tombstones (post-fold): shapes are kept, so
        the next search is still a cache hit; ``applied_lsn`` resets with
        the truncated WAL."""
        with self._lock:
            self._data[:] = 0.0
            self._ids[:] = -1
            self._slot_lsn[:] = 0
            self._slot_of.clear()
            self._tombs.clear()
            self._n_used = 0
            self.applied_lsn = 0
            self.generation += 1
            self._dirty = True

    # ---- search side ----------------------------------------------------

    def device_view(self) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """The shape-static ``(data, ids, tombs)`` snapshot searches
        consume.  Refreshed only when dirty; published as one tuple so a
        concurrent reader never sees data from one write and ids from
        another."""
        with self._lock:
            if self._dirty or self._dev is None:
                tombs = np.full(self.tomb_capacity, -1, np.int32)
                if self._tombs:
                    t = np.fromiter(self._tombs.keys(), np.int32,
                                    len(self._tombs))
                    tombs[:t.size] = t
                self._dev = (jnp.asarray(self._data),
                             jnp.asarray(self._ids),
                             jnp.asarray(tombs))
                self._dirty = False
            return self._dev

    def search(self, queries, k: int) -> Tuple[jax.Array, jax.Array]:
        """Standalone delta search (tests / debugging — serving merges
        via :func:`merge_with_main` instead)."""
        data, ids, _ = self.device_view()
        return _delta_scan(data, ids, jnp.asarray(queries), k=int(k),
                           metric=self.metric)

    # ---- fold side -------------------------------------------------------

    def fold_payload(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Deterministic fold inputs: ``(live_ids, live_rows, tomb_ids)``
        in slot order / sorted id order — what the ingest fold feeds the
        main index's ``delete`` + ``extend`` under one generation bump."""
        with self._lock:
            live = np.nonzero(self._ids[:self._n_used] >= 0)[0]
            live_ids = self._ids[live].astype(np.int32)
            live_rows = self._data[live].astype(np.float32)
            tomb_ids = np.array(sorted(self._tombs), np.int32)
            return live_ids, live_rows, tomb_ids

    def fold_items(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                  np.ndarray]:
        """:meth:`fold_payload` plus the per-row LSNs:
        ``(live_ids, live_rows, live_lsns, tomb_ids)``.  The distributed
        fold unions payloads across shard memtables and needs the LSN to
        break duplicate-id collisions deterministically (replicated
        copies share an LSN; keep-max-LSN keeps the newest write when a
        partial-quorum history left copies at different LSNs)."""
        with self._lock:
            live = np.nonzero(self._ids[:self._n_used] >= 0)[0]
            return (self._ids[live].astype(np.int64),
                    self._data[live].astype(np.float32),
                    self._slot_lsn[live].astype(np.int64),
                    np.array(sorted(self._tombs), np.int64))
