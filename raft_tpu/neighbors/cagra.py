"""CAGRA: graph-based ANN — build a kNN graph, prune it to a fixed-degree
search graph, answer queries by greedy graph walk.

Reference: raft/neighbors/cagra.cuh:77 ``build_knn_graph``, :109 ``prune``
(renamed ``optimize`` upstream), :205 ``search``; types cagra_types.hpp:41,55,
114.  Build: detail/cagra/cagra_build.cuh:43 (ivf_pq::build :91 + batched
search with gpu_top_k = 2×degree :104-160, then ``refine_host`` exact re-rank
:171).  Prune: detail/cagra/graph_core.cuh:415 (rank-based edge pruning +
reverse-edge addition).  Search: detail/cagra/factory.cuh dispatching
single-cta / multi-cta / multi-kernel greedy-walk kernels with a bitonic
top-M buffer and a hashmap visited set.

TPU design (SURVEY.md §7 flags this as the XLA-hostile one):

- **build** composes the existing IVF-PQ + refine exactly like the reference;
- **prune** keeps the reference's *rank-based detour* criterion, computed in
  node blocks (``lax.map``): per block, neighbor-of-neighbor lists are
  sorted once and membership resolves by ``searchsorted`` —
  O(B·deg²·log deg) and O(B·deg²) memory, never the naive
  (n, deg, deg, deg) tensor.  The reverse-edge pass
  (graph_core.cuh's rev_graph) is a device-side sort-based bucketing:
  edges sorted by (dst, rank) and scattered into per-node reverse slots;
  leftover slots take the next-best pruned-out forward edges;
- **search** replaces the data-dependent walk + hashmap with a
  fixed-iteration ``lax.while_loop`` over a static (q, itopk) candidate
  buffer: each step expands the best unvisited candidates' adjacency rows
  (one gather + one MXU distance block), suppresses duplicates by masked
  membership test against the buffer (the visited-hashmap analogue), and
  re-selects top-itopk.  Termination: all buffered candidates visited, or
  max_iterations.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import BinaryIO, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import serialize as ser
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu.distance.types import DistanceType
from raft_tpu.matrix.select_k import merge_topk, select_k
from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
from raft_tpu.neighbors.refine import refine
from raft_tpu.utils.precision import get_matmul_precision
from raft_tpu.core.outputs import auto_convert_output, raw


@dataclasses.dataclass
class IndexParams:
    """Reference: cagra_types.hpp:41 ``index_params``."""

    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    metric: int = DistanceType.L2Expanded
    build_pq_bits: int = 8
    build_pq_dim: int = 0
    build_n_lists: int = 0        # 0 -> auto sqrt(n)-scaled
    build_n_probes: int = 32
    build_refine_rate: float = 2.0


@dataclasses.dataclass
class SearchParams:
    """Reference: cagra_types.hpp:55 ``search_params`` (itopk_size,
    search_width, max_iterations)."""

    max_iterations: int = 0       # 0 -> auto
    itopk_size: int = 64
    search_width: int = 1
    num_random_samplings: int = 1
    rand_xor_mask: int = 0x128394


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """Reference: cagra_types.hpp:114 ``index`` — dataset + fixed-degree
    graph (row i holds the neighbor ids of node i)."""

    dataset: jax.Array            # (n, dim)
    graph: jax.Array              # (n, graph_degree) int32
    metric: int = DistanceType.L2Expanded

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]

    def tree_flatten(self):
        return (self.dataset, self.graph), (self.metric,)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0])


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------

def build_knn_graph(
    res,
    dataset,
    intermediate_degree: int,
    *,
    params: Optional[IndexParams] = None,
    batch: int = 8192,
) -> jax.Array:
    """All-nodes kNN graph via IVF-PQ + exact refine
    (reference: cagra.cuh:77 → cagra_build.cuh:43-171).
    Returns (n, intermediate_degree) int32 (self-edges removed).
    """
    with named_range("cagra::build_knn_graph"):
        dataset = ensure_array(dataset, "dataset")
        n, dim = dataset.shape
        p = params or IndexParams()
        n_lists = p.build_n_lists or max(min(n // 64, 4 * int(np.sqrt(n))), 8)
        pq_params = ivf_pq_mod.IndexParams(
            n_lists=n_lists, metric=p.metric, pq_bits=p.build_pq_bits,
            pq_dim=p.build_pq_dim, kmeans_n_iters=10)
        pq_index = ivf_pq_mod.build(res, pq_params, dataset)
        sp = ivf_pq_mod.SearchParams(n_probes=min(p.build_n_probes, n_lists))

        # gpu_top_k = refine_rate × degree oversampling, +1 for self hit
        top_k = min(int(p.build_refine_rate * intermediate_degree) + 1, n)
        rows = []
        for start in range(0, n, batch):
            q = dataset[start:start + batch]
            _, cand = raw(ivf_pq_mod.search)(res, sp, pq_index, q, top_k)
            _, idx = raw(refine)(res, dataset, q, cand,
                            min(intermediate_degree + 1, top_k),
                            metric=DistanceType.L2Expanded
                            if p.metric != DistanceType.InnerProduct
                            else p.metric)
            rows.append(idx)
        knn = jnp.concatenate(rows, axis=0)           # (n, deg+1)

        # drop self-edges: shift left where the first column is the node
        ids = jnp.arange(n, dtype=knn.dtype)[:, None]
        is_self = knn == ids
        # stable partition: non-self first
        order = jnp.argsort(is_self, axis=1, stable=True)
        knn = jnp.take_along_axis(knn, order, axis=1)
        return knn[:, :intermediate_degree].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("block",))
def _detour_order(knn_graph, block=256):
    """Rank-based detour ordering (graph_core.cuh:415 ``prune``).

    Edge i→knn[i,r] is *detourable* when ∃ r' < r with knn[i,r'] = k and
    knn[i,r] ∈ knn[k, :] — a 2-hop path whose first hop is a strictly
    stronger edge.  Edges are ordered by (detour_count, original rank);
    callers slice the first ``graph_degree`` columns.

    Blocked: ``lax.map`` over node blocks; per block the neighbor-of-
    neighbor lists (B, deg, deg) are sorted once and each membership
    resolves via ``searchsorted`` — O(B·deg²) memory, no
    (n, deg, deg, deg) intermediate (that is ~2×10¹⁵ elements at the
    reference's 1M×128 defaults).
    """
    n, deg = knn_graph.shape
    rank = jnp.arange(deg)
    n_pad = ((n + block - 1) // block) * block
    knn_p = jnp.pad(knn_graph, ((0, n_pad - n), (0, 0)))
    blocks = knn_p.reshape(n_pad // block, block, deg)

    def one_block(kb):                               # (B, deg)
        non = knn_graph[jnp.clip(kb, 0, n - 1)]      # (B, deg, deg)
        snon = jnp.sort(non, axis=-1)

        def row_member(sn, keys):
            # sn (deg, deg) row-sorted; keys (deg,) -> member (deg_rp, deg_r)
            idx = jax.vmap(lambda s: jnp.searchsorted(s, keys))(sn)
            vals = jnp.take_along_axis(sn, jnp.clip(idx, 0, deg - 1), axis=1)
            return vals == keys[None, :]

        member = jax.vmap(row_member)(snon, kb)      # (B, rp, r)
        stronger = rank[:, None] < rank[None, :]     # first hop rp < r
        detours = jnp.sum(member & stronger[None], axis=1)   # (B, deg)
        score = detours * deg + rank[None, :]
        order = jnp.argsort(score, axis=1)
        return jnp.take_along_axis(kb, order, axis=1)

    out = jax.lax.map(one_block, blocks)
    return out.reshape(n_pad, deg)[:n]


@functools.partial(jax.jit, static_argnames=("n", "rev_cap"))
def _reverse_edges(fwd, n, rev_cap):
    """Device-side reverse-edge lists (graph_core.cuh rev_graph).

    For each directed edge (i→j), j collects i into up to ``rev_cap``
    reverse slots, strongest (lowest-rank) edges first: sort all edges by
    (dst, rank) via two stable argsorts, compute each edge's position
    within its dst group, and scatter the first ``rev_cap`` per group.
    """
    half = fwd.shape[1]
    # rank-major edge order is a transpose, not a sort; the single stable
    # argsort by dst then yields (dst asc, rank asc) order
    dst = fwd.T.ravel()
    src = jnp.tile(jnp.arange(n, dtype=jnp.int32), half)
    o = jnp.argsort(dst, stable=True)
    dsts = dst[o]
    srcs = src[o]
    e = dsts.shape[0]
    # position within each dst group: running max of group-start indices
    first = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), dsts[1:] != dsts[:-1]])
    starts = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, jnp.arange(e), 0))
    pos = jnp.arange(e) - starts
    keep = (pos < rev_cap) & (dsts >= 0) & (dsts < n)
    row = jnp.where(keep, dsts, n)                   # n = dummy row
    col = jnp.clip(pos, 0, rev_cap - 1)
    rev = jnp.full((n + 1, rev_cap), -1, jnp.int32)
    rev = rev.at[row, col].set(jnp.where(keep, srcs, -1))
    return rev[:n]


def prune(res, knn_graph, graph_degree: int) -> jax.Array:
    """Prune an intermediate kNN graph to ``graph_degree`` with detour
    counting + reverse-edge fill (reference: cagra.cuh:109 ``prune``,
    graph_core.cuh:415)."""
    with named_range("cagra::prune"):
        knn_graph = ensure_array(knn_graph, "knn_graph")
        n, deg = knn_graph.shape
        expects(graph_degree <= deg,
                "cagra.prune: graph_degree > intermediate degree")
        ordered = _detour_order(knn_graph)
        half = (max(graph_degree // 2, 1) if graph_degree < deg
                else graph_degree)
        fwd = ordered[:, :half]
        if half == graph_degree:
            return fwd
        rev_cap = graph_degree - half
        rev = _reverse_edges(fwd, n, rev_cap)
        # leftover slots: next-best pruned-out forward edges (not a repeat
        # of one edge — that wastes degree budget)
        fillers = ordered[:, half:half + rev_cap]
        cand = jnp.concatenate([rev, fillers], axis=1)
        sel = jnp.argsort(cand < 0, axis=1, stable=True)[:, :rev_cap]
        rest = jnp.take_along_axis(cand, sel, axis=1)
        return jnp.concatenate([fwd, rest], axis=1)


def build(res, params: IndexParams, dataset) -> Index:
    """Full CAGRA build (reference: cagra.cuh ``build`` = build_knn_graph +
    prune)."""
    dataset = ensure_array(dataset, "dataset")
    knn = build_knn_graph(res, dataset, params.intermediate_graph_degree,
                          params=params)
    graph = prune(res, knn, params.graph_degree)
    return Index(dataset=dataset, graph=graph, metric=params.metric)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=(
    "k", "itopk", "search_width", "max_iterations", "metric"))
def _search_impl(dataset, graph, queries, seed_ids, k, itopk, search_width,
                 max_iterations, metric):
    nq = queries.shape[0]
    n, dim = dataset.shape
    degree = graph.shape[1]
    qf = queries.astype(jnp.float32)
    ip_metric = metric == DistanceType.InnerProduct
    worst = -jnp.inf if ip_metric else jnp.inf

    def dists_to(ids):
        """(q, m) ids -> (q, m) distances to the query."""
        vecs = dataset[ids].astype(jnp.float32)       # (q, m, d)
        ip = jnp.einsum("qd,qmd->qm", qf, vecs,
                        precision=get_matmul_precision())
        if ip_metric:
            return ip
        sq = jnp.sum(vecs * vecs, axis=-1)
        qsq = jnp.sum(qf * qf, axis=-1, keepdims=True)
        return jnp.maximum(qsq + sq - 2.0 * ip, 0.0)

    # ---- init buffer: best itopk of the random probe set -----------------
    # (the reference's random-sampling buffer fill: probing more random
    # candidates than itopk prevents the greedy walk from starting in the
    # wrong region and never escaping — cluster-structured data needs it)
    seed_d = dists_to(seed_ids)
    # dedupe random draws: a node sampled twice would occupy two buffer slots
    sorted_seeds = jnp.sort(seed_ids, axis=1)
    dup_sorted = jnp.concatenate(
        [jnp.zeros((nq, 1), jnp.bool_),
         sorted_seeds[:, 1:] == sorted_seeds[:, :-1]], axis=1)
    rank = jnp.argsort(jnp.argsort(seed_ids, axis=1), axis=1)
    seed_dup = jnp.take_along_axis(dup_sorted, rank, axis=1)
    seed_d = jnp.where(seed_dup, worst, seed_d)
    if ip_metric:
        buf_d, pos = jax.lax.top_k(seed_d, itopk)
    else:
        buf_d, pos = jax.lax.top_k(-seed_d, itopk)
        buf_d = -buf_d
    buf_i = jnp.take_along_axis(seed_ids, pos, axis=1)
    buf_i = jnp.where(jnp.isinf(buf_d), -1, buf_i)
    visited = jnp.zeros((nq, itopk), jnp.bool_)

    def cond(state):
        _, _, visited, it = state
        return jnp.logical_and(it < max_iterations,
                               jnp.logical_not(jnp.all(visited)))

    def body(state):
        buf_d, buf_i, visited, it = state
        # pick the search_width best unvisited candidates
        masked = jnp.where(visited | (buf_i < 0), worst, buf_d)
        if ip_metric:
            _, sel = jax.lax.top_k(masked, search_width)
        else:
            _, sel = jax.lax.top_k(-masked, search_width)
        sel_ids = jnp.take_along_axis(buf_i, sel, axis=1)  # (q, w)
        visited = visited.at[jnp.arange(nq)[:, None], sel].set(True)

        # expand adjacency of selected nodes
        nbrs = graph[jnp.where(sel_ids >= 0, sel_ids, 0)]  # (q, w, degree)
        nbrs = nbrs.reshape(nq, search_width * degree)
        nbrs = jnp.where(jnp.repeat(sel_ids >= 0, degree, axis=1), nbrs, -1)
        nd = dists_to(jnp.where(nbrs >= 0, nbrs, 0))
        nd = jnp.where(nbrs < 0, worst, nd)

        cat_d = jnp.concatenate([buf_d, nd], axis=1)
        cat_i = jnp.concatenate([buf_i, nbrs], axis=1)
        cat_v = jnp.concatenate(
            [visited, jnp.zeros_like(nd, jnp.bool_)], axis=1)

        # duplicate suppression (the hashmap visited-set analogue): the same
        # node may appear in the buffer AND in several expansions; keep one
        # copy per id — sort by distance (stable), then by id (stable): the
        # first slot of each id-group is its best copy, and for equal
        # distances the buffer copy (with its visited flag) wins.
        sort_d = -cat_d if ip_metric else cat_d
        ord_d = jnp.argsort(sort_d, axis=1, stable=True)
        i1 = jnp.take_along_axis(cat_i, ord_d, axis=1)
        d1 = jnp.take_along_axis(cat_d, ord_d, axis=1)
        v1 = jnp.take_along_axis(cat_v, ord_d, axis=1)
        ord_i = jnp.argsort(i1, axis=1, stable=True)
        i2 = jnp.take_along_axis(i1, ord_i, axis=1)
        d2 = jnp.take_along_axis(d1, ord_i, axis=1)
        v2 = jnp.take_along_axis(v1, ord_i, axis=1)
        dup = jnp.concatenate(
            [jnp.zeros((nq, 1), jnp.bool_), i2[:, 1:] == i2[:, :-1]], axis=1)
        d2 = jnp.where(dup, worst, d2)
        i2 = jnp.where(dup, -1, i2)

        if ip_metric:
            new_d, pos = jax.lax.top_k(d2, itopk)
        else:
            new_d, pos = jax.lax.top_k(-d2, itopk)
            new_d = -new_d
        new_i = jnp.take_along_axis(i2, pos, axis=1)
        new_v = jnp.take_along_axis(v2, pos, axis=1)
        return new_d, new_i, new_v, it + 1

    buf_d, buf_i, visited, _ = jax.lax.while_loop(
        cond, body, (buf_d, buf_i, visited, jnp.int32(0)))

    out_d, pos = (jax.lax.top_k(buf_d, k) if ip_metric
                  else (lambda v, p: (-v, p))(*jax.lax.top_k(-buf_d, k)))
    out_i = jnp.take_along_axis(buf_i, pos, axis=1)
    if metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
    return out_d, out_i


@auto_convert_output
def search(res, params: SearchParams, index: Index, queries, k: int
           ) -> Tuple[jax.Array, jax.Array]:
    """Greedy graph-walk search (reference: cagra.cuh:205)."""
    with named_range("cagra::search"):
        queries = ensure_array(queries, "queries")
        expects(queries.ndim == 2 and queries.shape[1] == index.dim,
                "cagra.search: query dim mismatch")
        itopk = max(params.itopk_size, k)
        # probe 4×itopk random nodes (min 128) and keep the best itopk —
        # the reference's random-sampling buffer init scaled the same way
        n_seeds = max(itopk,
                      min(index.size,
                          max(params.num_random_samplings * 4 * itopk, 128)))
        key = res.next_key()
        seed_ids = jax.random.randint(
            key, (queries.shape[0], n_seeds), 0, index.size,
            dtype=jnp.int32)
        max_iter = params.max_iterations or (
            10 + itopk // max(params.search_width, 1))
        return _search_impl(index.dataset, index.graph, queries, seed_ids,
                            k, itopk, params.search_width, max_iter,
                            index.metric)


# ---------------------------------------------------------------------------
# serialization (reference: cagra_serialize.cuh)
# ---------------------------------------------------------------------------

_SERIALIZATION_VERSION = 1


def serialize(res, stream: BinaryIO, index: Index) -> None:
    ser.serialize_scalar(res, stream, np.int32(_SERIALIZATION_VERSION))
    ser.serialize_scalar(res, stream, np.int32(index.metric))
    ser.serialize_mdspan(res, stream, index.dataset)
    ser.serialize_mdspan(res, stream, index.graph)


def deserialize(res, stream: BinaryIO) -> Index:
    version = int(ser.deserialize_scalar(res, stream))
    if version != _SERIALIZATION_VERSION:
        raise ValueError(
            f"cagra serialization version mismatch: got {version}, "
            f"expected {_SERIALIZATION_VERSION}")
    metric = int(ser.deserialize_scalar(res, stream))
    dataset = jnp.asarray(ser.deserialize_mdspan(res, stream))
    graph = jnp.asarray(ser.deserialize_mdspan(res, stream))
    return Index(dataset=dataset, graph=graph, metric=metric)
