"""CAGRA: graph-based ANN — build a kNN graph, prune it to a fixed-degree
search graph, answer queries by greedy graph walk.

Reference: raft/neighbors/cagra.cuh:77 ``build_knn_graph``, :109 ``prune``
(renamed ``optimize`` upstream), :205 ``search``; types cagra_types.hpp:41,55,
114.  Build: detail/cagra/cagra_build.cuh:43 (ivf_pq::build :91 + batched
search with gpu_top_k = 2×degree :104-160, then ``refine_host`` exact re-rank
:171).  Prune: detail/cagra/graph_core.cuh:415 (rank-based edge pruning +
reverse-edge addition).  Search: detail/cagra/factory.cuh dispatching
single-cta / multi-cta / multi-kernel greedy-walk kernels with a bitonic
top-M buffer and a hashmap visited set.

TPU design (SURVEY.md §7 flags this as the XLA-hostile one):

- **build** replaces the reference's streamed IVF-PQ search + refine
  batches with a list-major pass: rows are packed into padded coarse
  lists, each list block scores its top-t neighbor lists' contiguous
  tile with one batched MXU GEMM in calibrated-PCA space, and the
  oversampled survivors are exact-refined inside the same dispatch
  (see :func:`_build_knn_graph_clustered`);
- **prune** keeps the reference's *rank-based detour* criterion, computed in
  node blocks over host-chunked dispatches: per block, membership is a
  sorted-merge (multi-operand sort + cummax run scan — ``searchsorted``
  measured 50x slower, and one whole-graph dispatch trips execution
  watchdogs), never the naive (n, deg, deg, deg) tensor.  The
  reverse-edge pass (graph_core.cuh's rev_graph) is scatter-free:
  edges sorted by (dst, rank), slots read back by gather at
  group_start + slot; leftover slots take the next-best pruned-out
  forward edges;
- **search** replaces the data-dependent walk + hashmap with a
  fixed-iteration ``lax.while_loop`` over a static (q, itopk) candidate
  buffer: each step expands the best unvisited candidates' adjacency rows,
  suppresses duplicates by masked membership test against the buffer (the
  visited-hashmap analogue), and re-selects top-itopk.  Termination: all
  buffered candidates visited, or max_iterations.

Round-4 search redesign (measured, profiles/gather_bench.py): scattered
row gathers on TPU are **per-row latency-bound** (~18 ns/row whether the
row is 128 B or 1 KB; bf16 rows are *slower* than f32), so the round-3
loop — one dataset-row gather per candidate, 64+ rows per expanded node
— was gather-bound at ~5 ms/iteration.  The walk now fetches ONE fat row
per expanded node from a packed **neighborhood table**: all ``degree``
neighbors' PCA-projected vectors (bf16) + full-precision norms and ids
(everything bitcast into int16 lanes — see _WalkCache for why the
container must be an integer dtype) in a single flat row.
Distances along the walk are approximate (exact norms, PCA cross term);
the final buffer is re-ranked with exact distances in one dense pass.
Entry points come from a dense (q, S) matmul against a fixed random
entry set — no scattered seed gather at all.  The reference's hashmap +
bitonic-buffer kernels (detail/cagra/search_single_cta.cuh) solve a
SIMT problem; on TPU the costs invert: membership masks and top-k are
cheap vector ops, scattered fetches are the scarce resource.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import BinaryIO, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.core import serialize as ser
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu import observability as obs
from raft_tpu.integrity import boundary as _boundary
from raft_tpu.integrity import canary as _canary
from raft_tpu.distance.types import DistanceType
from raft_tpu.filters import bitset as _fbits
from raft_tpu.matrix import ops as matrix_ops
from raft_tpu.matrix.select_k import select_k
from raft_tpu.utils.precision import get_matmul_precision
from raft_tpu.core.outputs import auto_convert_output
from raft_tpu.neighbors import mutate as _mutate


@dataclasses.dataclass
class IndexParams:
    """Reference: cagra_types.hpp:41 ``index_params``.

    The ``build_*`` knobs steer the cluster-blocked kNN-graph pass (the
    analogue of the reference's IVF-PQ build params inside
    cagra_build.cuh:43): ``build_n_lists`` coarse clusters (0 -> auto),
    up to ``build_n_probes`` candidate lists per node block, targeting
    ``build_candidates`` candidate rows per node, with
    ``build_refine_rate`` × degree survivors exact-refined."""

    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    metric: int = DistanceType.L2Expanded
    build_n_lists: int = 0        # 0 -> auto sqrt(n)-scaled
    build_n_probes: int = 32
    build_refine_rate: float = 2.0
    build_candidates: int = 8192
    build_proj_dim: int = 0       # 0 -> auto-calibrated scan PCA dim
    build_scan_recall: float = 0.95   # approx_max_k target in the scan
    build_reverse_rounds: int = 1     # reverse-edge merge rounds
    build_walk_rounds: int = 2        # graph-walk refinement rounds
    build_walk_iters: int = 8         # expansion steps per walk round
    # recall canaries (raft_tpu.integrity): > 0 samples that many sentinel
    # queries at build, stores their exact neighbors in the index, and
    # health-checks recall against the floor after load()/resume
    canary_queries: int = 0
    canary_k: int = 10
    canary_floor: float = 0.5


@dataclasses.dataclass
class SearchParams:
    """Reference: cagra_types.hpp:55 ``search_params`` (itopk_size,
    search_width, max_iterations).

    TPU additions (see module docstring, round-4 search redesign):

    - ``walk_pdim``: PCA dimension of the packed neighborhood table the
      greedy walk reads (0 disables it — the walk then gathers full
      dataset rows per candidate, exact but gather-bound);
    - ``entry_points``: size of the fixed random entry set scored
      densely to seed the buffer (the ``num_random_samplings``
      analogue);
    - ``rerank_topk``: how many of the final buffer entries get exact
      re-ranked distances (0 -> auto: ``max(32, 2k)``).
    """

    max_iterations: int = 0       # 0 -> auto
    itopk_size: int = 64
    search_width: int = 1
    num_random_samplings: int = 1
    rand_xor_mask: int = 0x128394
    # None -> auto: the smallest PCA dim whose projected distances keep
    # >= _WALK_FIDELITY top-k overlap with exact distances on a
    # density-matched calibration pool (lossless-in-practice on manifold
    # data; falls all the way back to the exact direct walk on data no
    # projection can order).  0 -> exact walk; >0 -> forced dim.
    walk_pdim: Optional[int] = None
    entry_points: int = 4096
    rerank_topk: int = 0
    # Fused-hop merge engine ("auto" | int, parsed by
    # ops.vmem_budget.merge_window_request like ivf_pq's knob): the hop
    # kernel cannot defer merges ACROSS hops (parent selection consumes
    # the merged buffer every hop), so >1 selects the staged WITHIN-hop
    # merge — candidates are extracted into a sorted staging block and
    # merged by one bitonic pass, lifting the itopk gate from 32 to 64.
    # "auto" keeps the legacy in-pass merge where it is allowed and
    # stages only for itopk > 32; 1 forces legacy.
    merge_window: object = "auto"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """Reference: cagra_types.hpp:114 ``index`` — dataset + fixed-degree
    graph (row i holds the neighbor ids of node i)."""

    dataset: jax.Array            # (n, dim)
    graph: jax.Array              # (n, graph_degree) int32
    metric: int = DistanceType.L2Expanded
    # Recall-canary sentinel set (integrity.CanarySet) — host-side
    # metadata, deliberately NOT a pytree leaf (aux must stay hashable),
    # so jax transforms drop it; build/serialize carry it explicitly.
    canaries: Optional[object] = None
    # Mutation-generation counter (see neighbors.mutate): host-side like
    # canaries, bumped by delete(); readers snapshot by object identity.
    generation: int = 0

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]

    def tree_flatten(self):
        return (self.dataset, self.graph), (self.metric,)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0])


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------

# datasets at or below this row count take the exact all-pairs path (one
# fused dispatch; clustering overhead is not worth it at this scale)
_BRUTE_BUILD_MAX = 32768
# the projected candidate scan must place >= this fraction of the exact
# top-(deg+1) inside its top-C oversampled candidates (recall@C, scored
# on a density-matched sample — the same lesson as _WALK_FIDELITY)
_BUILD_FIDELITY = 0.95
# _calib_build_recall measures the overlap with approx_max_k on BOTH
# sides (compile-time diet), so the statistic it reports is biased low
# by up to 2*(1 - recall_target) — misses on the exact side subtract a
# hit, misses on the approx side can hide one.  Run the calibration
# selects at a tight target and raise the gate by that worst-case bias,
# so the EFFECTIVE acceptance threshold stays at _BUILD_FIDELITY:
# gate = 0.95 + 2*(1 - 0.99) = 0.97, with 1 - 0.97 = 0.03 of headroom
# left before the gate saturates at 1.0 and would reject everything.
_CALIB_RT = 0.99
_BUILD_FIDELITY_GATE = min(_BUILD_FIDELITY + 2 * (1 - _CALIB_RT), 1.0)


@functools.partial(jax.jit, static_argnames=("kg", "metric", "chunk"))
def _knn_graph_exact(dataset, kg, metric, chunk=4096):
    """Exact all-pairs kNN graph for small n: ``lax.map`` over query
    chunks, one f32 GEMM + select per chunk."""
    n, dim = dataset.shape
    xf = dataset.astype(jnp.float32)
    x_sq = jnp.sum(xf * xf, axis=1)
    ip_metric = metric == DistanceType.InnerProduct
    n_pad = -(-n // chunk) * chunk
    qp = jnp.pad(xf, ((0, n_pad - n), (0, 0)))

    def one(q):
        ip = jax.lax.dot_general(q, xf, (((1,), (1,)), ((), ())),
                                 precision=get_matmul_precision(),
                                 preferred_element_type=jnp.float32)
        d = -ip if ip_metric else x_sq[None, :] - 2.0 * ip
        _, idx = select_k(d, kg, select_min=True)
        return idx

    idx = jax.lax.map(one, qp.reshape(n_pad // chunk, chunk, dim))
    return idx.reshape(n_pad, kg)[:n].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("pdim", "kg", "C", "ip_metric"))
def _calib_build_recall(queries, pool, self_col, vecs, pdim, kg, C,
                        ip_metric=False):
    """Fraction of the exact top-``kg`` found inside the ``pdim``-projected
    top-``C`` — the coverage the scan + reverse-merge pipeline needs
    (unlike :func:`_calib_overlap`, which scores symmetric top-k
    agreement).  ``self_col`` masks each query's own pool column (the
    guaranteed self-hit would inflate recall by ~1/kg)."""
    dim = pool.shape[1]
    ip = jax.lax.dot_general(queries, pool, (((1,), (1,)), ((), ())),
                             precision=get_matmul_precision(),
                             preferred_element_type=jnp.float32)
    proj = vecs[:, dim - pdim:]
    qp = (queries @ proj).astype(jnp.bfloat16)
    pp = (pool @ proj).astype(jnp.bfloat16)
    ipa = jax.lax.dot_general(qp, pp, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    if ip_metric:
        d_exact, d_apx = -ip, -ipa
    else:
        p_sq = jnp.sum(pool * pool, axis=1)
        d_exact = p_sq[None, :] - 2.0 * ip
        d_apx = p_sq[None, :] - 2.0 * ipa
    cols = jnp.arange(pool.shape[0], dtype=jnp.int32)
    self_mask = cols[None, :] == self_col[:, None]
    d_exact = jnp.where(self_mask, jnp.inf, d_exact)
    d_apx = jnp.where(self_mask, jnp.inf, d_apx)
    # approx_max_k on both sides: the gate reads an overlap STATISTIC,
    # not a ranking — the exact selects were ~10 s of per-process XLA
    # compile (the build pays calibration exactly once).  The resulting
    # measurement bias is compensated in _BUILD_FIDELITY_GATE; keep
    # _CALIB_RT and that margin in sync.
    _, ie = jax.lax.approx_max_k(-d_exact, kg, recall_target=_CALIB_RT)
    _, ia = jax.lax.approx_max_k(-d_apx, C, recall_target=_CALIB_RT)
    hits = jnp.any(ie[:, :, None] == ia[:, None, :], axis=-1)
    return jnp.mean(hits.astype(jnp.float32))


def _build_pdim(dataset, metric, kg, C) -> Tuple[int, jax.Array]:
    """Smallest multiple-of-8 PCA dim whose projected top-C candidates
    cover >= _BUILD_FIDELITY of the exact top-kg on a density-matched
    sample.  ``C`` is ~2·kg: the scan emits projected top-kg per node,
    but the reverse-merge immediately doubles each node's exactly
    re-ranked candidate set, so top-kg-within-top-2kg is the coverage
    the pipeline actually needs.  Returns (pdim, eigvecs); pdim == dim
    means rotation-only."""
    n, dim = dataset.shape
    # a smaller pool than the search-time calibration: the scan only
    # seeds the walk-refinement rounds, so its fidelity gate need not
    # resolve index-scale NN gaps (and the wide select over the pool is
    # per-pdim-try cost)
    queries, pool, self_col = _calib_sample(dataset,
                                            _WALK_CALIB_POOL // 2)
    mp = pool.shape[0]
    ip_metric = metric == DistanceType.InnerProduct
    _, vecs = jnp.linalg.eigh(_second_moment(dataset))
    p = 16
    while p < dim:
        ov = float(_calib_build_recall(queries, pool, self_col, vecs, p,
                                       kg, min(C, mp), ip_metric))
        # gate at the bias-compensated threshold (see _BUILD_FIDELITY_GATE)
        if ov >= _BUILD_FIDELITY_GATE:
            return p, vecs
        p *= 2
    return dim, vecs


@functools.partial(jax.jit, static_argnames=("n_lists", "cap"))
def _build_layout(xf, xp32, labels, n_lists, cap):
    """Pack rows into the padded per-list layout the blocked scan reads:
    per list, PCA-projected rows (bf16, ``xp32`` precomputed by the
    caller), exact squared norms (f32, +inf padding) and original ids
    (-1 padding).

    The TPU analogue of the reference's dataset blocking inside
    cagra_build.cuh:104-160 — but list-major, so every query block
    shares one contiguous candidate tile (pure batched MXU GEMMs, no
    per-query gathers in the scan)."""
    n, dim = xf.shape
    order = jnp.argsort(labels)
    sl = labels[order]
    sizes = jax.ops.segment_sum(jnp.ones(n, jnp.int32), labels,
                                num_segments=n_lists)
    starts = jnp.cumsum(sizes) - sizes
    slot = sl * cap + (jnp.arange(n, dtype=jnp.int32) - starts[sl])
    xp = xp32.astype(jnp.bfloat16)
    x_sq = jnp.sum(xf * xf, axis=1)
    pdim = xp32.shape[1]
    P_proj = jnp.zeros((n_lists * cap, pdim), jnp.bfloat16
                       ).at[slot].set(xp[order])
    P_sq = jnp.full((n_lists * cap,), jnp.inf, jnp.float32
                    ).at[slot].set(x_sq[order])
    P_id = jnp.full((n_lists * cap,), -1, jnp.int32
                    ).at[slot].set(order.astype(jnp.int32))
    return (P_proj.reshape(n_lists, cap, pdim),
            P_sq.reshape(n_lists, cap),
            P_id.reshape(n_lists, cap))


@functools.partial(jax.jit, static_argnames=("t", "ip_metric"))
def _center_neighbors(centers, t, ip_metric):
    """Top-``t`` nearest lists per list by center distance (self first)."""
    cf = centers.astype(jnp.float32)
    ip = jax.lax.dot_general(cf, cf, (((1,), (1,)), ((), ())),
                             precision=get_matmul_precision(),
                             preferred_element_type=jnp.float32)
    d = -ip if ip_metric else jnp.sum(cf * cf, axis=1)[None, :] - 2.0 * ip
    m = centers.shape[0]
    d = jnp.where(jnp.eye(m, dtype=jnp.bool_), -jnp.inf, d)
    _, nb = jax.lax.top_k(-d, t)
    return nb.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("cap", "kg", "ip_metric",
                                             "LB", "rt"))
def _scan_chunk(P_proj, P_sq, P_id, center_nbrs, list_ids,
                cap, kg, ip_metric, LB, rt=0.95):
    """Projected candidate scan for a chunk of lists.

    Per LB-list block: ONE batched bf16 MXU GEMM scores every query in
    the block against the block's shared (t·cap)-row candidate tile in
    projected space (exact norms + projected cross term — the same
    approximation the packed walk uses); ``approx_max_k`` keeps the
    top-``kg`` ids.  No exact refine here: the reverse-merge that
    follows re-ranks everything exactly anyway, so an in-tile refine
    paid its gather bill twice (round-5 diet).  This replaces the
    reference's per-query IVF-PQ search + refine_host batches
    (cagra_build.cuh:104-171) with a list-major pass whose candidate
    reads are contiguous."""
    t = center_nbrs.shape[1]

    def block(lb_ids):                                  # (LB,)
        nb = center_nbrs[lb_ids]                        # (LB, t)
        qp = P_proj[lb_ids]                             # (LB, cap, pdim)
        cp = P_proj[nb].reshape(LB, t * cap, pdim := P_proj.shape[2])
        csq = P_sq[nb].reshape(LB, t * cap)
        cid = P_id[nb].reshape(LB, t * cap)
        ip = jnp.einsum("bqp,bcp->bqc", qp, cp,
                        preferred_element_type=jnp.float32)
        d = -ip if ip_metric else csq[:, None, :] - 2.0 * ip
        d = jnp.where(cid[:, None, :] >= 0, d, jnp.inf)

        negd = -d.reshape(LB * cap, t * cap)
        _, pos = jax.lax.approx_max_k(negd, kg, recall_target=rt)
        cidf = jnp.broadcast_to(cid[:, None, :], (LB, cap, t * cap)
                                ).reshape(LB * cap, t * cap)
        out = jnp.take_along_axis(cidf, pos, axis=1)    # (LB*cap, kg)
        return out.reshape(LB, cap, kg)

    return jax.lax.map(block, list_ids.reshape(-1, LB)
                       ).reshape(-1, cap, kg)


# lists per _scan_chunk dispatch — bounds single-execution time (the
# remote-tunnel watchdog, see _DETOUR_ROWS_PER_DISPATCH) while keeping
# ONE compiled shape (list ids are padded to a full multiple)
_SCAN_LISTS_PER_DISPATCH = 512

# above this edge count the reverse-edge sort runs on the host: the
# device path's argsort transients (~3 edge-list copies) plus the padded
# (n, kg) carriers exceed HBM in the deep-scale regime
_REV_HOST_EDGES = 200_000_000

# row count at which the build switches to the deep-scale memory
# regime (in-place fused walk rounds, host reverse/prune tails)
_DEEP_SCALE_ROWS = 4_000_000


def _hbm_bytes() -> int:
    """Default-device HBM, from the runtime when it reports it (a v5e
    constant otherwise — the one chip this repo is tuned on)."""
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return limit
    except Exception:
        pass
    return 16 << 30


def _deep_walk_round(dataset, knn, kg, metric, pdim, iters, vecs=None):
    """One fused in-place walk-refinement round for the deep-scale
    regime: the packed table is sized to the HBM headroom left by the
    dataset and the (lane-padded) knn carrier, and the walk + exact
    rerank run per chunk inside one donated dispatch
    (:func:`_walk_refine_fused`)."""
    n, dim = dataset.shape
    budget = min(_WALK_TABLE_MAX_BYTES,
                 _hbm_bytes() - n * dim * 4
                 - n * (-(-kg // 128) * 128) * 4 - (3 << 30))
    itopk = min(max(-(-(kg + 16) // 32) * 32, 64), 256)
    plan = _table_plan(n, kg, pdim, budget, deep=True)
    if plan is None:
        return knn                 # no table fits: round skipped
    table, proj, scales, q = _build_refine_table(dataset, knn, plan,
                                                 vecs)
    return _walk_refine_fused(dataset, knn, table, proj, scales, kg,
                              itopk, iters, metric, plan[0], quant=q)


def _reverse_edges_host(fwd: np.ndarray, n: int, rev_cap: int
                        ) -> np.ndarray:
    """Host twin of :func:`_reverse_edges` (same (dst asc, rank asc)
    semantics) for the deep-scale regime."""
    kg = fwd.shape[1]
    dst = fwd.T.ravel()
    src = np.tile(np.arange(n, dtype=np.int32), kg)
    order = np.argsort(dst, kind="stable")
    dsts = dst[order]
    srcs = src[order]
    starts = np.searchsorted(dsts, np.arange(n))
    counts = np.searchsorted(dsts, np.arange(n), side="right") - starts
    idx = starts[:, None] + np.arange(rev_cap)[None, :]
    rev = srcs[np.clip(idx, 0, dsts.shape[0] - 1)]
    valid = np.arange(rev_cap)[None, :] < counts[:, None]
    return np.where(valid, rev, -1).astype(np.int32)


# reverse-edge SOURCE width for the refinement reranks: "u ranks v in
# its top-48" is the strong reverse relation, and the edge sort scales
# with n*width (129 -> 48 columns cut the 1M device sort ~2.7x; the
# exact rerank filters weak candidates either way)
_REV_SRC_CAP = 48


def _reverse_edges_auto(knn, n, rev_cap):
    """Reverse edges from the top-``_REV_SRC_CAP`` forward columns —
    device path, or the host counting-sort fallback when the edge-list
    sort transients would not fit next to the deep-scale carriers.
    The width cap is applied per path: slicing on device BEFORE the
    host transfer materializes a second lane-padded (n, 128) copy
    (n*512 B — 5 GB at 10M), which is exactly the transient the host
    path exists to avoid."""
    kg = min(knn.shape[1], _REV_SRC_CAP)
    if n * kg <= _REV_HOST_EDGES:
        return _reverse_edges(knn[:, :kg], n, rev_cap)
    return jnp.asarray(_reverse_edges_host(np.asarray(knn)[:, :kg], n,
                                           rev_cap))


# toggled by tests / RAFT_TPU_DEBUG_CHECKS=1: host-side validation of
# internal fast-path preconditions that jitted code cannot afford
_DEBUG_CHECKS = os.environ.get("RAFT_TPU_DEBUG_CHECKS", "0").lower() \
    not in ("0", "", "false")


def _merge_refine_chunked(xf, first, second, kg, ip_metric, chunk=4096,
                          first_d=None, with_d=False):
    """Exact re-rank of [first | second] candidate ids per node.

    Fast-path precondition — when ``first_d`` is given, every row of
    ``(first, first_d)`` must already be sorted non-decreasing by key
    and duplicate-free (invalid tail slots padded id=-1 / key=+inf).
    The bitonic ``_merge_candidates`` merge treats ``first`` as a
    sorted, deduped buffer and only dedupes ``second`` AGAINST it; an
    unsorted or duplicated ``first`` silently corrupts the merged
    ranking.  The refinement rounds satisfy this by construction (each
    round's output IS the previous merge's sorted top-``kg``).  With
    the module debug flag on (``RAFT_TPU_DEBUG_CHECKS=1``) the
    precondition is checked host-side and violations raise.
    """
    if _DEBUG_CHECKS and first_d is not None:
        fd = np.asarray(first_d, dtype=np.float64)
        expects(bool(np.all(np.diff(fd, axis=1) >= 0)),
                "cagra._merge_refine_chunked: first_d rows must be "
                "sorted non-decreasing (fast-path precondition)")
        fi = np.asarray(first)
        srt = np.sort(fi, axis=1)
        dup = (srt[:, 1:] == srt[:, :-1]) & (srt[:, 1:] >= 0)
        expects(not bool(np.any(dup)),
                "cagra._merge_refine_chunked: first rows must be "
                "duplicate-free (fast-path precondition)")
    return _merge_refine_chunked_impl(xf, first, second, kg, ip_metric,
                                      chunk, first_d, with_d)


@functools.partial(jax.jit, static_argnames=("kg", "ip_metric", "chunk",
                                             "with_d"))
def _merge_refine_chunked_impl(xf, first, second, kg, ip_metric,
                               chunk=4096, first_d=None, with_d=False):
    """Jitted body of :func:`_merge_refine_chunked` (``lax.map`` over
    node chunks): gather bf16 rows, one f32-accumulate einsum,
    duplicate/invalid slots masked to +inf, keep top-``kg``.

    ``first_d`` (optional) carries already-exact keys for ``first`` so
    only ``second`` is gathered/scored — the refinement rounds carry
    their graph's distances this way, halving the gather bill.
    ``with_d=True`` also returns the top-``kg`` keys."""
    n, dim = xf.shape
    xb = xf.astype(jnp.bfloat16)
    x_sq = jnp.sum(xf * xf, axis=1)
    m1 = first.shape[1]
    cand = jnp.concatenate([first, second], axis=1)     # (n, m)
    m = cand.shape[1]
    n_pad = -(-n // chunk) * chunk
    cand = jnp.pad(cand, ((0, n_pad - n), (0, 0)), constant_values=-1)
    qx = jnp.pad(xb, ((0, n_pad - n), (0, 0)))
    if first_d is not None:
        fd = jnp.pad(first_d, ((0, n_pad - n), (0, 0)),
                     constant_values=jnp.inf)
    else:
        fd = jnp.zeros((n_pad, 1), jnp.float32)   # unused placeholder

    def one(args):
        c, q, f = args                  # (chunk, m), (chunk, dim), (chunk, m1?)
        if first_d is None:
            return _rerank_rows(xb, x_sq, q, c[:, :m1], c[:, m1:], kg,
                                ip_metric)
        # first carries exact sorted keys (the previous round's merge
        # output): score only `second`, then reuse the search path's
        # sorted-buffer bitonic merge — membership-mask dedupe + one
        # narrow candidate sort instead of three full-width (m1+m2)
        # stable sorts + a wide top_k (the build rounds were
        # merge-sort-bound, ~14 s/round at 1M before this)
        sec = c[:, m1:]
        valid = sec >= 0
        safe = jnp.where(valid, sec, 0)
        rows = xb[safe]                              # (chunk, m2, dim)
        ip = jnp.einsum("qd,qmd->qm", q, rows,
                        preferred_element_type=jnp.float32)
        d2 = -ip if ip_metric else x_sq[safe] - 2.0 * ip
        d2 = jnp.where(valid, d2, jnp.inf)
        bd, bi, _ = _merge_candidates(
            f, c[:, :m1], jnp.zeros((c.shape[0], m1), jnp.bool_),
            d2, sec, kg)
        return bi, bd

    out, outd = jax.lax.map(one, (cand.reshape(-1, chunk, m),
                                  qx.reshape(-1, chunk, dim),
                                  fd.reshape(-1, chunk, fd.shape[1])))
    out = out.reshape(n_pad, kg)[:n]
    if with_d:
        return out, outd.reshape(n_pad, kg)[:n]
    return out


def _build_knn_graph_clustered(res, dataset, kg: int, p: IndexParams
                               ) -> jax.Array:
    """Cluster-blocked kNN-graph pass (device-side; no per-batch host
    loop).  Returns (n, kg) int32 ranked ids (self included)."""
    n, dim = dataset.shape
    xf = dataset.astype(jnp.float32)
    ip_metric = p.metric == DistanceType.InnerProduct
    n_lists = p.build_n_lists or max(min(n // 64, 4 * int(np.sqrt(n))), 8)
    n_lists = min(n_lists, n)

    # projection FIRST: clustering, assignment and the candidate scan
    # all run in the calibrated-PCA space — the full-dim f32 assignment
    # pass alone was ~24 PFLOP at 10M x 12649 lists (~20 min on chip);
    # projected it is dim/pdim (8x at 128->16) cheaper, and the scan
    # scores in this space anyway so the pipeline stays self-consistent
    C = max(int(p.build_refine_rate * kg), kg)
    with obs.stage("cagra.build.calibration") as st:
        if p.build_proj_dim:
            pdim = min(p.build_proj_dim, dim)
            _, vecs = jnp.linalg.eigh(_second_moment(dataset))
        else:
            pdim, vecs = _build_pdim(dataset, p.metric, kg, C)
        proj = (vecs[:, dim - pdim:] if pdim < dim
                else jnp.eye(dim, dtype=jnp.float32))
        xp32 = xf @ proj                               # (n, pdim) f32
        st.fence(xp32)

    # coarse centers on a strided subsample (strided, not leading — see
    # _second_moment), then one assignment pass over all rows
    with obs.stage("cagra.build.kmeans"):
        n_train = min(n, max(n_lists * 8, max(65536, n // 10)))
        bal = kmeans_balanced.KMeansBalancedParams(
            n_iters=10, metric=p.metric if ip_metric
            else DistanceType.L2Expanded)
        trainset = xp32[::max(n // n_train, 1)][:n_train]
        centers = kmeans_balanced.fit(res, bal, trainset, n_lists)
        labels = kmeans_balanced.predict(res, bal, xp32, centers)
        sizes = jax.ops.segment_sum(jnp.ones(n, jnp.int32), labels,
                                    num_segments=n_lists)
        cap = max(-(-int(jnp.max(sizes)) // 8) * 8, 8)  # one host sync

    # candidate width: enough lists to reach ~build_candidates candidate
    # rows per node, never fewer than build_n_probes lists — per-LIST
    # probing needs a wider net than the reference's per-query probes
    # (boundary nodes; measured ceiling 0.86 at 32 small lists vs 0.96
    # at 64 on a 40k sample)
    mean = max(n / n_lists, 1.0)
    t = min(n_lists,
            max(p.build_n_probes, -(-p.build_candidates // int(mean))))
    expects(kg <= t * cap, "cagra.build: candidate pool smaller than "
            "intermediate degree — raise build_n_probes/build_candidates")

    with obs.stage("cagra.build.layout") as st:
        P_proj, P_sq, P_id = _build_layout(xf, xp32, labels, n_lists, cap)
        del xp32
        nbrs = _center_neighbors(centers, t, ip_metric)
        st.fence(P_id, nbrs)

    # block size: bound the (LB, cap, t*cap) f32 distance transient
    LB = max(1, min(8, (256 << 20) // max(cap * t * cap * 4, 1)))
    CH = _SCAN_LISTS_PER_DISPATCH
    n_pad = -(-n_lists // (LB * CH)) * (LB * CH) if n_lists > LB * CH \
        else -(-n_lists // LB) * LB
    ids = np.minimum(np.arange(n_pad, dtype=np.int32), n_lists - 1)
    # scatter each chunk's rows straight into the (n, kg) output by the
    # chunk lists' original ids — the flat (n_lists_pad*cap, kg) slot
    # array this replaces cost 8.8 GB at 10M (TPU lane padding doubles
    # any (rows, kg<=128) int32 array)
    knn = jnp.full((n, kg), -1, jnp.int32)
    with obs.stage("cagra.build.scan") as st:
        for s in range(0, n_pad, LB * CH):
            cid = jnp.asarray(ids[s:s + LB * CH])
            out_c = _scan_chunk(P_proj, P_sq, P_id, nbrs, cid, cap, kg,
                                ip_metric, LB, rt=p.build_scan_recall)
            rows = P_id[cid].reshape(-1)           # original ids (-1 pad)
            rows = jnp.where(rows >= 0, rows, n)   # pad -> dropped
            knn = knn.at[rows].set(out_c.reshape(-1, kg), mode="drop")
        st.fence(knn)
    # reverse edges: a boundary node whose true neighbor fell outside
    # its own list's candidate tile is usually inside that neighbor's
    # tile (the kNN relation is nearly symmetric).  They join the FIRST
    # refinement rerank below instead of paying their own full-width
    # exact pass (round-5 diet: the standalone reverse-merge was 17 s
    # of the 1M build; source width capped inside _reverse_edges_auto).
    with obs.stage("cagra.build.reverse_edges") as st:
        rev = _reverse_edges_auto(knn, n, min(kg, 64))
        st.fence(rev)
    deep = n >= _DEEP_SCALE_ROWS
    if deep:
        # deep-scale memory regime (TPU lane padding makes EVERY
        # (n, w<=128) int32 array n*512 bytes): fold the reverse edges
        # immediately and drop them, then run fused in-place rounds
        with obs.stage("cagra.build.reverse_merge") as st:
            knn = _merge_refine_inplace(dataset, knn, rev, kg, ip_metric)
            st.fence(knn)
        rev = None
        if pdim < dim:
            for _ in range(p.build_walk_rounds):
                with obs.stage("cagra.build.walk_refine") as st:
                    knn = _deep_walk_round(dataset, knn, kg, p.metric,
                                           pdim, p.build_walk_iters,
                                           vecs=vecs)
                    st.fence(knn)
        return knn
    knn_d = None
    if pdim < dim and p.build_walk_rounds > 0:
        # graph-walk refinement rounds: escape the candidate-pool
        # ceiling entirely (see _graph_refine_round).  Skipped when no
        # projection passed calibration (pdim == dim would pack
        # full-dim rows: a 17 GB table at 1M, and projected ordering is
        # unreliable there anyway).
        for r in range(p.build_walk_rounds):
            with obs.stage("cagra.build.walk_refine") as st:
                knn, knn_d = _graph_refine_round(
                    res, dataset, knn, kg, p.metric, pdim,
                    p.build_walk_iters, knn_d=knn_d,
                    extra=rev if r == 0 else None, vecs=vecs)
                st.fence(knn)
    else:
        for r in range(max(p.build_reverse_rounds, 1)):
            with obs.stage("cagra.build.reverse_merge") as st:
                if r > 0:
                    rev = _reverse_edges_auto(knn, n, min(kg, 64))
                knn, knn_d = _merge_refine_chunked(xf, knn, rev, kg,
                                                   ip_metric, with_d=True)
                st.fence(knn)
    return knn


@functools.partial(jax.jit, static_argnames=("itopk", "iters",
                                             "search_width", "metric",
                                             "deg", "chunk", "quant"))
def _self_walk_chunked(dataset, table, proj, itopk, iters, search_width,
                       metric, deg, chunk=8192, quant=False, scales=None):
    """Warm-seeded greedy walk with queries = the dataset itself
    (``lax.map`` over node chunks): each node's buffer is seeded by
    expanding its OWN packed-neighborhood row (so the walk starts at its
    current approximate neighbors, not at random entries), then runs
    ``iters`` expansion steps over the packed table.  Returns each
    node's (itopk) candidate ids, best-first by the projected key.

    This is the engine of :func:`_graph_refine_round` — unlike the
    candidate-tile scan, its reach is not bounded by any cluster
    geometry: each step can cross the whole graph."""
    n = dataset.shape[0]
    ip_metric = metric == DistanceType.InnerProduct
    n_pad = -(-n // chunk) * chunk
    ids_all = jnp.arange(n_pad, dtype=jnp.int32).reshape(-1, chunk)

    def one(ids):
        ids_c = jnp.minimum(ids, n - 1)
        qf = dataset[ids_c].astype(jnp.float32)
        return _walk_chunk_body(qf, ids_c, table, proj, scales, itopk,
                                iters, search_width, ip_metric, deg,
                                quant)

    out = jax.lax.map(one, ids_all)
    return out.reshape(n_pad, itopk)[:n]


def _walk_chunk_body(qf, ids_c, table, proj, scales, itopk, iters,
                     search_width, ip_metric, deg, quant):
    """Warm-seeded walk for one chunk of self-queries (the shared engine
    of :func:`_self_walk_chunked` and :func:`_walk_refine_fused`):
    buffer seeded by expanding each node's OWN packed row, then
    ``iters`` expansion steps.  Returns (chunk, itopk) candidate ids."""
    chunk = qf.shape[0]
    pdim = proj.shape[1]
    unit = _quant_unit(pdim) if quant else pdim + 4
    q_sq = jnp.sum(qf * qf, axis=1)
    qpf = qf @ proj
    if quant:
        qpf = qpf * (scales[0] / 127.0)
    qp = qpf.astype(jnp.bfloat16)

    def expand(sel_ids, parent_ok):
        rows = table[jnp.where(parent_ok, sel_ids, 0)]
        w = sel_ids.shape[1]
        rows = rows[..., :deg * unit].reshape(chunk, w, deg, unit)
        nb_p, nb_sq, nb_id = _decode_neighborhood(rows, pdim, deg,
                                                  quant, scales)
        nb_id = jnp.where(parent_ok[:, :, None], nb_id, -1)
        ipx = jnp.einsum("qp,qwdp->qwd", qp, nb_p,
                         preferred_element_type=jnp.float32)
        d = -ipx if ip_metric else q_sq[:, None, None] + nb_sq \
            - 2.0 * ipx
        return d.reshape(chunk, w * deg), nb_id.reshape(chunk, w * deg)

    # seed: expand self (one fat fetch per node)
    d0, i0 = expand(ids_c[:, None], jnp.ones((chunk, 1), jnp.bool_))
    if d0.shape[1] < itopk:
        d0 = jnp.pad(d0, ((0, 0), (0, itopk - d0.shape[1])),
                     constant_values=jnp.inf)
        i0 = jnp.pad(i0, ((0, 0), (0, itopk - i0.shape[1])),
                     constant_values=-1)
    buf_d, pos = jax.lax.top_k(-d0, itopk)
    buf_d = -buf_d
    buf_i = jnp.take_along_axis(i0, pos, axis=1)
    buf_i = jnp.where(jnp.isinf(buf_d), -1, buf_i)
    # the node itself is its own nearest neighbor — pre-mark it
    # visited so the first expansion step does not re-expand it
    visited = buf_i == ids_c[:, None]

    def body(it, state):
        buf_d, buf_i, visited = state
        sel_ids, parent_ok, visited = _select_parents(
            buf_d, buf_i, visited, search_width)
        d_c, nb_id = expand(sel_ids, parent_ok)
        buf_d, buf_i, visited = _merge_candidates(
            buf_d, buf_i, visited, d_c, nb_id, itopk)
        return buf_d, buf_i, visited

    _, buf_i, _ = jax.lax.fori_loop(0, iters, body,
                                    (buf_d, buf_i, visited))
    return buf_i


@functools.partial(jax.jit, static_argnames=("kg", "itopk", "iters",
                                             "metric", "deg", "chunk",
                                             "quant"),
                   donate_argnums=(1,))
def _walk_refine_fused(dataset, knn, table, proj, scales, kg, itopk,
                       iters, metric, deg, chunk=8192, quant=False):
    """Deep-scale walk-refinement round: walk + exact rerank fused per
    node chunk inside ONE donated ``fori_loop``, updating ``knn`` in
    place — neither the (n, itopk) candidate array nor a second (n, kg)
    output ever exists (each is ~5 GB at 10M after TPU lane padding).
    Rows are processed once, so in-place chunk updates cannot corrupt a
    later chunk's inputs (the walk reads the packed TABLE, a snapshot,
    not ``knn``)."""
    n, dim = dataset.shape
    ip_metric = metric == DistanceType.InnerProduct
    x_sq_all = jnp.sum(dataset.astype(jnp.float32) ** 2, axis=1)
    n_chunks = -(-n // chunk)

    def body(ci, carry):
        start = jnp.minimum(ci * chunk, n - chunk)
        ids_c = start + jnp.arange(chunk, dtype=jnp.int32)
        qf = jax.lax.dynamic_slice(dataset, (start, 0),
                                   (chunk, dim)).astype(jnp.float32)
        cand = _walk_chunk_body(qf, ids_c, table, proj, scales, itopk,
                                iters, 1, ip_metric, deg, quant)
        old = jax.lax.dynamic_slice(carry, (start, 0), (chunk, kg))
        new_rows, _ = _rerank_rows(dataset, x_sq_all, qf, old, cand, kg,
                                   ip_metric)
        return jax.lax.dynamic_update_slice(carry, new_rows, (start, 0))

    return jax.lax.fori_loop(0, n_chunks, body, knn)


def _rerank_rows(dataset, x_sq_all, qf, old, cand, kg, ip_metric):
    """Exact rerank of [old | cand] ids for one chunk of self-queries —
    the ONE copy of the duplicate-mask + rerank body (duplicates keep
    their FIRST occurrence via :func:`matrix_ops.row_duplicate_mask`,
    so ``old`` entries win ties).  Callers that already hold exact
    sorted keys for ``old`` should use the bitonic-merge path in
    :func:`_merge_refine_chunked` instead.  Gathered rows cast to bf16
    AFTER the gather — a full bf16 dataset copy is a ~2 GB transient at
    deep scale.  Returns (ids (chunk, kg), keys (chunk, kg))."""
    c = jnp.concatenate([old, cand], axis=1)
    valid = c >= 0
    safe = jnp.where(valid, c, 0)
    dup = matrix_ops.row_duplicate_mask(c)
    rows = dataset[safe].astype(jnp.bfloat16)
    ip = jnp.einsum("qd,qmd->qm", qf.astype(jnp.bfloat16), rows,
                    preferred_element_type=jnp.float32)
    d = -ip if ip_metric else x_sq_all[safe] - 2.0 * ip
    d = jnp.where(valid & ~dup, d, jnp.inf)
    nd, pos = jax.lax.top_k(-d, kg)
    return jnp.take_along_axis(c, pos, axis=1), -nd


@functools.partial(jax.jit, static_argnames=("kg", "ip_metric", "chunk"),
                   donate_argnums=(1,))
def _merge_refine_inplace(dataset, knn, second, kg, ip_metric,
                          chunk=8192):
    """Deep-scale twin of :func:`_merge_refine_chunked`: the rerank of
    [knn | second] runs per chunk inside one donated ``fori_loop`` —
    the full-width concat alone would be a ~10 GB lane-padded temp at
    10M."""
    n, dim = dataset.shape
    m2 = second.shape[1]
    x_sq_all = jnp.sum(dataset.astype(jnp.float32) ** 2, axis=1)
    n_chunks = -(-n // chunk)

    def body(ci, carry):
        start = jnp.minimum(ci * chunk, n - chunk)
        qf = jax.lax.dynamic_slice(dataset, (start, 0),
                                   (chunk, dim)).astype(jnp.float32)
        old = jax.lax.dynamic_slice(carry, (start, 0), (chunk, kg))
        sec = jax.lax.dynamic_slice(second, (start, 0), (chunk, m2))
        new_rows, _ = _rerank_rows(dataset, x_sq_all, qf, old, sec, kg,
                                   ip_metric)
        return jax.lax.dynamic_update_slice(carry, new_rows, (start, 0))

    return jax.lax.fori_loop(0, n_chunks, body, knn)


def _graph_refine_round(res, dataset, knn, kg, metric, pdim, iters,
                        itopk=0, knn_d=None, extra=None, vecs=None):
    """One graph-walk refinement round: pack the current graph's best
    edges into a walk table, self-walk every node, and exact-rerank
    [current neighbors | walk buffer (| extra)].  Monotone: the rerank
    set contains the current neighbors, so per-node recall cannot drop.
    Returns (knn, exact keys) for the next round's carry.  ``extra``
    (n, m) ids join the rerank set — the build folds the reverse edges
    in here instead of paying a separate full-width rerank pass.

    This is how the build escapes the candidate-pool ceiling of any
    clustered scan (measured at 1M: per-list pools cap at ~0.47
    recall@128 even at 2x the candidate budget; the walk's reach is the
    whole graph)."""
    # ~kg + 25% slack, rounded to a 32 lane multiple (kg 129 -> 160)
    itopk = itopk or min(max(-(-(kg + 16) // 32) * 32, 64), 256)
    ip_metric = metric == DistanceType.InnerProduct
    n = dataset.shape[0]
    plan = _table_plan(n, kg, pdim, _WALK_TABLE_MAX_BYTES)
    if plan is None:           # nothing fits: no walk, but never drop
        # the reverse edges — merge them (exactly) and return
        second = extra if extra is not None else knn[:, :1]
        return _merge_refine_chunked(
            dataset.astype(jnp.float32), knn, second, kg, ip_metric,
            first_d=knn_d, with_d=True)
    table, proj, scales, q = _build_refine_table(dataset, knn, plan,
                                                 vecs)
    cand = _self_walk_chunked(dataset, table, proj, itopk, iters, 1,
                              metric, plan[0], quant=q, scales=scales)
    if extra is not None:
        cand = jnp.concatenate([cand, extra], axis=1)
    return _merge_refine_chunked(dataset.astype(jnp.float32), knn, cand,
                                 kg, ip_metric, first_d=knn_d,
                                 with_d=True)


def build_knn_graph(
    res,
    dataset,
    intermediate_degree: int,
    *,
    params: Optional[IndexParams] = None,
    batch: int = 8192,
) -> jax.Array:
    """All-nodes kNN graph (reference: cagra.cuh:77 →
    cagra_build.cuh:43-171 — there: IVF-PQ build + batched search with
    gpu_top_k = 2×degree + refine_host).  Returns
    (n, intermediate_degree) int32 (self-edges removed).

    TPU design: the reference streams per-query IVF-PQ searches; here
    the whole pass is list-major — rows are packed into padded coarse
    lists, each list block scans its top-t neighbor lists' contiguous
    tile with one batched MXU GEMM in calibrated-PCA space, and the
    oversampled survivors are exact-refined in the same fused dispatch
    (round 5; the round-4 host loop over 123 search+refine batches was
    ~200 s of the 250 s 1M build).  ``batch`` is the query chunk of the
    small-n exact path.
    """
    with named_range("cagra::build_knn_graph"):
        dataset = ensure_array(dataset, "dataset")
        n, dim = dataset.shape
        p = params or IndexParams()
        kg = min(intermediate_degree + 1, n)
        if n <= _BRUTE_BUILD_MAX:
            with obs.stage("cagra.build.knn_exact") as st:
                knn = _knn_graph_exact(dataset, kg, p.metric,
                                       chunk=min(batch, 4096))
                st.fence(knn)
        else:
            knn = _build_knn_graph_clustered(res, dataset, kg, p)

        # drop self-edges: shift left where the first column is the node
        ids = jnp.arange(n, dtype=knn.dtype)[:, None]
        is_self = knn == ids
        # stable partition: non-self first
        order = jnp.argsort(is_self, axis=1, stable=True)
        knn = jnp.take_along_axis(knn, order, axis=1)
        return knn[:, :intermediate_degree].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("block",))
def _detour_chunk(knn_graph, blocks, block=256):
    """Detour-order a chunk of node blocks (see :func:`_detour_order`).

    Membership (is neighbor r in neighbor rp's adjacency?) is a
    **sorted-merge**: concat [adjacency row | keys] per (node, rp),
    one ``lax.sort`` by (value, source-tag), run-aware member flags via
    two ``cummax`` scans (robust to duplicate ids on either side), and
    a second small sort carrying the flags back into key order.  The
    earlier ``searchsorted`` formulation lowered to serial per-key
    gathers — measured **50x slower** on TPU than this all-sort form
    (profiles round 4: 50.0 s vs 0.97 s per 32k rows).  When ids fit,
    (value, tag, rank) are packed into ONE int32 key so both sorts are
    single-operand — the multi-operand form cost ~1.6x more (round 5).
    """
    n, deg = knn_graph.shape
    rank = jnp.arange(deg)
    packed = n * 2 * deg < 2**31
    iota = jnp.arange(2 * deg, dtype=jnp.int32)

    def one_block(kb):                               # (B, deg)
        B = kb.shape[0]
        non = knn_graph[jnp.clip(kb, 0, n - 1)]      # (B, rp=deg, deg)
        keys = jnp.broadcast_to(kb[:, None, :], (B, deg, deg))
        if packed:
            # key = val*(2deg) + (tag ? deg + r : 0): sorts by
            # (val, tag, r) with ONE operand, decoded after
            adj_k = non * (2 * deg)
            key_k = keys * (2 * deg) + deg + rank[None, None, :]
            sk = jax.lax.sort(
                jnp.concatenate([adj_k, key_k], axis=-1), dimension=-1)
            sv = sk // (2 * deg)
            rem = sk - sv * (2 * deg)
            st1 = rem >= deg                         # from the key side
            sr = rem - deg
        else:
            vals = jnp.concatenate([non, keys], axis=-1)       # (B,deg,2deg)
            tags = jnp.concatenate(
                [jnp.zeros((B, deg, deg), jnp.int32),
                 jnp.ones((B, deg, deg), jnp.int32)], -1)
            ridx = jnp.concatenate(
                [jnp.zeros((B, deg, deg), jnp.int32),
                 jnp.broadcast_to(rank[None, None, :], (B, deg, deg))], -1)
            sv, st, sr = jax.lax.sort((vals, tags, ridx), dimension=-1,
                                      num_keys=2)
            st1 = st == 1
        # run-aware membership: a key is a member iff its equal-value
        # run contains an adjacency (tag==0) element
        is_start = jnp.concatenate(
            [jnp.ones_like(sv[..., :1], jnp.bool_),
             sv[..., 1:] != sv[..., :-1]], -1)
        run_start = jax.lax.cummax(jnp.where(is_start, iota, 0), axis=2)
        last_sn = jax.lax.cummax(jnp.where(~st1, iota, -1), axis=2)
        is_member_key = st1 & (last_sn >= run_start)
        # flags back into key order r via one packed single-operand
        # sort: key2 = sr2*2 + member (non-keys to the end via sentinel)
        sr2 = jnp.where(st1, sr, deg)
        sk2 = jax.lax.sort(sr2 * 2 + is_member_key.astype(jnp.int32),
                           dimension=-1)
        member = (sk2[..., :deg] & 1).astype(jnp.bool_)        # (B, rp, r)

        stronger = rank[:, None] < rank[None, :]     # first hop rp < r
        detours = jnp.sum(member & stronger[None], axis=1)   # (B, deg)
        score = detours * deg + rank[None, :]
        order = jnp.argsort(score, axis=1)
        return jnp.take_along_axis(kb, order, axis=1)

    return jax.lax.map(one_block, blocks)


# node rows per _detour_chunk dispatch: ONE lax.map over all of 1M nodes
# is a single multi-minute XLA execution, which the remote-tunnel
# watchdog kills ("TPU worker process crashed") — bound each dispatch
_DETOUR_ROWS_PER_DISPATCH = 32768


def _detour_order(knn_graph, block=256):
    """Rank-based detour ordering (graph_core.cuh:415 ``prune``).

    Edge i→knn[i,r] is *detourable* when ∃ r' < r with knn[i,r'] = k and
    knn[i,r] ∈ knn[k, :] — a 2-hop path whose first hop is a strictly
    stronger edge.  Edges are ordered by (detour_count, original rank);
    callers slice the first ``graph_degree`` columns.

    Blocked: ``lax.map`` over node blocks; per block membership resolves
    via the multi-operand sorted-merge in :func:`_detour_chunk` —
    O(B·deg²) memory, no (n, deg, deg, deg) intermediate (that is
    ~2×10¹⁵ elements at the reference's 1M×128 defaults).  The blocks
    are dispatched in
    fixed-size host chunks (two compiled shapes max) so no single
    device execution runs long enough to trip execution watchdogs.
    """
    n, deg = knn_graph.shape
    n_pad = ((n + block - 1) // block) * block
    knn_p = jnp.pad(knn_graph, ((0, n_pad - n), (0, 0)))
    blocks = knn_p.reshape(n_pad // block, block, deg)

    cpb = max(_DETOUR_ROWS_PER_DISPATCH // block, 1)
    nb = blocks.shape[0]
    nb_pad = ((nb + cpb - 1) // cpb) * cpb
    blocks = jnp.pad(blocks, ((0, nb_pad - nb), (0, 0), (0, 0)))
    out = []
    for ci, s in enumerate(range(0, nb_pad, cpb)):
        out.append(_detour_chunk(knn_graph, blocks[s:s + cpb],
                                 block=block))
        if n >= _DEEP_SCALE_ROWS and ci % 8 == 7:
            # pace the dispatch queue at deep scale: hundreds of
            # enqueued sort-heavy dispatches have crashed the remote
            # TPU worker; a tiny readback every few chunks bounds the
            # in-flight queue without serializing every dispatch
            np.asarray(out[-1][0, 0])
    out = jnp.concatenate(out, axis=0) if len(out) > 1 else out[0]
    return out.reshape(nb_pad * block, deg)[:n]


@functools.partial(jax.jit, static_argnames=("n", "rev_cap"))
def _reverse_edges(fwd, n, rev_cap):
    """Device-side reverse-edge lists (graph_core.cuh rev_graph).

    For each directed edge (i→j), j collects i into up to ``rev_cap``
    reverse slots, strongest (lowest-rank) edges first: ONE stable
    argsort of the rank-major edge list by dst yields (dst asc, rank
    asc) order; each node's slots then read **by gather** at
    ``group_start + slot`` (group starts via vectorized binary search).
    Scatter-free on purpose: a 32M-singleton scatter measured seconds-
    to-minutes on TPU (round-4 profiling) and made the fused prune
    dispatch long enough to trip the remote execution watchdog, while
    sort + searchsorted + gather are each sub-4s at 1M x 32.
    """
    half = fwd.shape[1]
    # rank-major edge order is a transpose, not a sort; the single stable
    # key-val sort by dst then yields (dst asc, rank asc) order.
    # sort_key_val carries src through the sort directly — the earlier
    # argsort + two 129M-element payload gathers were ~5 s of the 1M
    # build on their own.
    dst = fwd.T.ravel()
    src = jnp.tile(jnp.arange(n, dtype=jnp.int32), half)
    dsts, srcs = jax.lax.sort_key_val(dst, src, is_stable=True)
    e = dsts.shape[0]
    nodes = jnp.arange(n, dtype=dsts.dtype)
    starts = jnp.searchsorted(dsts, nodes)                   # (n,)
    counts = jnp.searchsorted(dsts, nodes, side="right") - starts
    idx = starts[:, None] + jnp.arange(rev_cap)[None, :]     # (n, rev_cap)
    rev = srcs[jnp.clip(idx, 0, e - 1)]
    valid = jnp.arange(rev_cap)[None, :] < counts[:, None]
    return jnp.where(valid, rev, -1)


def prune(res, knn_graph, graph_degree: int) -> jax.Array:
    """Prune an intermediate kNN graph to ``graph_degree`` with detour
    counting + reverse-edge fill (reference: cagra.cuh:109 ``prune``,
    graph_core.cuh:415)."""
    with named_range("cagra::prune"), obs.stage("cagra.build.prune") as stg:
        knn_graph = ensure_array(knn_graph, "knn_graph")
        n, deg = knn_graph.shape
        expects(graph_degree <= deg,
                "cagra.prune: graph_degree > intermediate degree")
        ordered = _detour_order(knn_graph)
        half = (max(graph_degree // 2, 1) if graph_degree < deg
                else graph_degree)
        if n >= _DEEP_SCALE_ROWS:
            # deep-scale: the tail's (n, <=128) temporaries each cost
            # n*512 B after lane padding — run it on the host
            o = np.asarray(ordered)
            del ordered
            fwd = o[:, :half]
            if half == graph_degree:
                return jnp.asarray(fwd)
            rev_cap = graph_degree - half
            rev = _reverse_edges_host(fwd, n, rev_cap)
            fillers = o[:, half:half + rev_cap]
            cand = np.concatenate([rev, fillers], axis=1)
            sel = np.argsort(cand < 0, axis=1, kind="stable")[:, :rev_cap]
            rest = np.take_along_axis(cand, sel, axis=1)
            return jnp.asarray(np.concatenate([fwd, rest], axis=1))
        fwd = ordered[:, :half]
        if half == graph_degree:
            stg.fence(fwd)
            return fwd
        rev_cap = graph_degree - half
        rev = _reverse_edges(fwd, n, rev_cap)
        # leftover slots: next-best pruned-out forward edges (not a repeat
        # of one edge — that wastes degree budget)
        fillers = ordered[:, half:half + rev_cap]
        cand = jnp.concatenate([rev, fillers], axis=1)
        sel = jnp.argsort(cand < 0, axis=1, stable=True)[:, :rev_cap]
        rest = jnp.take_along_axis(cand, sel, axis=1)
        out = jnp.concatenate([fwd, rest], axis=1)
        stg.fence(out)
        return out


def build(res, params: IndexParams, dataset, *,
          checkpoint=None, resume: bool = False) -> Index:
    """Full CAGRA build (reference: cagra.cuh ``build`` = build_knn_graph +
    prune).

    ``checkpoint`` (a directory path or
    :class:`~raft_tpu.resilience.CheckpointManager`) persists the two
    build stages (intermediate kNN graph, pruned graph) atomically right
    before their ``interruptible`` sync points; ``resume=True`` loads
    completed stages instead of recomputing.  The build consumes no
    ``res`` key draws, so a resumed build is bit-identical for free.
    """
    from raft_tpu.core.interruptible import interruptible
    from raft_tpu.resilience import as_manager
    ckpt = as_manager(checkpoint)
    dataset = ensure_array(dataset, "dataset")
    dataset, _ = _boundary.check_matrix(dataset, "dataset",
                                        site="cagra.build",
                                        allow_empty=False)
    with obs.build_scope("cagra.build") as rep:
        if resume and ckpt is not None and ckpt.has("knn_graph"):
            knn = jnp.asarray(ckpt.load("knn_graph")["knn"])
        else:
            knn = build_knn_graph(res, dataset,
                                  params.intermediate_graph_degree,
                                  params=params)
            if ckpt is not None:
                ckpt.save("knn_graph", {"knn": np.asarray(knn)})
        # cancellation point: stage state is durable before a pending
        # cancel() can raise
        interruptible.synchronize(knn)
        if resume and ckpt is not None and ckpt.has("graph"):
            graph = jnp.asarray(ckpt.load("graph")["graph"])
        else:
            graph = prune(res, knn, params.graph_degree)
            if ckpt is not None:
                ckpt.save("graph", {"graph": np.asarray(graph)})
        interruptible.synchronize(graph)
        index = Index(dataset=dataset, graph=graph, metric=params.metric)
        if params.canary_queries > 0:
            cs = _canary.make(res, dataset, metric=params.metric,
                              n_queries=params.canary_queries,
                              k=params.canary_k, floor=params.canary_floor)
            index.canaries = cs
            cs.build_recall = _canary.measure(res, index, cs)
            if resume:
                _canary.auto_check(res, index, site="resume")
    return rep.attach(index)


# ---------------------------------------------------------------------------
# search — packed-neighborhood walk (round-4 design, see module docstring)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _WalkCache:
    """Derived search-time state (lazily attached to the Index).

    ``table`` (n, W) **int16**, W = pad(degree*(pdim+4), 128) — per
    node, each neighbor's PCA-projected vector (pdim bf16 values),
    full-precision squared norm (f32) and id (int32), ALL bitcast into
    int16 lanes: the whole neighborhood in ONE scattered row fetch.

    The container dtype must be an INTEGER type: bf16 lanes measurably
    corrupt the packed ids/norms — XLA relayout copies at large n flush
    bf16-denormal bit patterns (an int32 id like 1000 bitcasts to a
    denormal low lane), which silently zeroed neighbor ids at 1M and
    collapsed walk recall to 0.02 while every small-scale test passed
    (round-4 debugging).  Integer copies are bit-exact.  The flat
    lane-aligned width also avoids the 2x tiling padding XLA gave the
    (n, degree, pdim+4) 3-D layout.

    ``proj`` (dim, pdim) f32; ``entry_*`` the fixed random entry set
    scored densely at search time.
    """

    table: jax.Array
    proj: jax.Array
    entry_proj: jax.Array      # (S, pdim) bf16
    entry_sq: jax.Array        # (S,) f32
    entry_ids: jax.Array       # (S,) int32
    quant: bool = False        # int8/uint16 row format (10M regime)
    scales: Optional[jax.Array] = None   # (3,) [a, sq_min, sq_scale]


@jax.jit
def _second_moment(dataset):
    xf = dataset.astype(jnp.float32)
    n = xf.shape[0]
    m = min(n, 32768)
    # strided, not leading, sample: on-disk datasets are often grouped
    # by cluster and the first rows would bias the subspace estimate
    sub = xf[::max(n // m, 1)][:m]
    m = sub.shape[0]
    return jax.lax.dot_general(sub, sub, (((0,), (0,)), ((), ())),
                               precision=get_matmul_precision(),
                               preferred_element_type=jnp.float32) / m


# the auto walk projection must preserve NN ordering at this top-k
# overlap, measured for sample queries against a LARGE candidate pool
# (spectral ENERGY is the wrong criterion — on clustered data the
# ordering among a node's neighbors lives in the residual dims; and a
# small within-sample test is wrong too: NN gaps shrink with n, so a
# projection that orders a sparse 1k sample perfectly can scramble the
# true neighbors at 1M density — measured recall collapse both ways, r4)
_WALK_FIDELITY = 0.9
_WALK_CALIB_QUERIES = 256
_WALK_CALIB_POOL = 131072
_WALK_CALIB_K = 10


@functools.partial(jax.jit, static_argnames=("pdim", "k", "ip_metric",
                                             "quant"))
def _calib_overlap(queries, pool, self_col, vecs, pdim, k,
                   ip_metric=False, quant=False):
    """Top-k overlap between exact and pdim-projected distances for
    calibration queries against a candidate pool — scored under the
    index's own metric (an IP walk ranks purely by the projected cross
    term; gating it on L2 overlap would let the exact-norm term mask
    cross-term error).  ``self_col`` (q,) is each query's own column in
    the pool (-1 when absent): the guaranteed self-match would inflate
    overlap by ~1/k, silently loosening the fidelity gate.  ``quant``
    additionally applies the int8 table quantization to the pool side
    (the format _build_walk_table_q stores), so the quantized walk is
    gated on its own fidelity, not the bf16 format's."""
    dim = pool.shape[1]
    ip = jax.lax.dot_general(queries, pool, (((1,), (1,)), ((), ())),
                             precision=get_matmul_precision(),
                             preferred_element_type=jnp.float32)
    proj = vecs[:, dim - pdim:]
    ppf = pool @ proj
    if quant:
        a = jnp.maximum(jnp.percentile(jnp.abs(ppf), 99.9), 1e-12)
        pp = jnp.clip(jnp.round(ppf / a * 127.0), -127,
                      127).astype(jnp.bfloat16)
        qp = ((queries @ proj) * (a / 127.0)).astype(jnp.bfloat16)
    else:
        pp = ppf.astype(jnp.bfloat16)
        qp = (queries @ proj).astype(jnp.bfloat16)
    ipa = jax.lax.dot_general(qp, pp, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    if ip_metric:
        d_exact, d_apx = -ip, -ipa
    else:
        p_sq = jnp.sum(pool * pool, axis=1)
        d_exact = p_sq[None, :] - 2.0 * ip
        d_apx = p_sq[None, :] - 2.0 * ipa
    cols = jnp.arange(pool.shape[0], dtype=jnp.int32)
    self_mask = cols[None, :] == self_col[:, None]
    d_exact = jnp.where(self_mask, jnp.inf, d_exact)
    d_apx = jnp.where(self_mask, jnp.inf, d_apx)
    _, ie = jax.lax.top_k(-d_exact, k)
    _, ia = jax.lax.top_k(-d_apx, k)
    hits = jnp.any(ie[:, :, None] == ia[:, None, :], axis=-1)
    return jnp.mean(hits.astype(jnp.float32))


def _auto_pdim(index: Index) -> int:
    """Smallest multiple-of-8 PCA dim whose projected distances keep
    >= _WALK_FIDELITY top-k overlap with exact distances on a sample
    (cached on the index; a few tiny host syncs, once per index)."""
    cached = getattr(index, "_walk_auto_pdim", None)
    if cached is None:
        dim = index.dim
        queries, pool, self_col = _calib_sample(index.dataset)
        ip_metric = index.metric == DistanceType.InnerProduct
        vecs = _calib_vecs(index)
        p = 8
        cached = 0
        while p < dim:
            ov = float(_calib_overlap(queries, pool, self_col, vecs, p,
                                      _WALK_CALIB_K, ip_metric))
            if ov >= _WALK_FIDELITY:
                cached = p
                break
            p *= 2
        if cached == 0:
            # full-dim projection = rotation only, but the packed table
            # is bf16 — if even that loses the ordering (tight clusters
            # with |x| >> NN gaps), 0 routes to the exact direct walk
            ov = float(_calib_overlap(queries, pool, self_col, vecs, dim,
                                      _WALK_CALIB_K, ip_metric))
            cached = dim if ov >= _WALK_FIDELITY else 0
        object.__setattr__(index, "_walk_auto_pdim", cached)
    return cached


def _calib_sample(dataset, pool_size=_WALK_CALIB_POOL):
    """Strided calibration (queries, pool, self_col) — strided, not
    leading (see _second_moment: leading rows bias cluster-grouped
    datasets); the pool must be large so its NN gaps approach
    index-scale density.  ``self_col`` marks each query's own pool
    column for masking."""
    n = dataset.shape[0]
    mq = min(n, _WALK_CALIB_QUERIES)
    mp = min(n, pool_size)
    sq_, sp_ = max(n // mq, 1), max(n // mp, 1)
    queries = dataset[::sq_][:mq].astype(jnp.float32)
    pool = dataset[::sp_][:mp].astype(jnp.float32)
    mq, mp = queries.shape[0], pool.shape[0]
    # each query is dataset row i*sq_; it sits in the pool at column
    # i*sq_/sp_ when divisible
    qrow = np.arange(mq, dtype=np.int64) * sq_
    col = qrow // sp_
    self_col = jnp.asarray(
        np.where((qrow % sp_ == 0) & (col < mp), col, -1),
        dtype=jnp.int32)
    return queries, pool, self_col


def _calib_vecs(index: Index) -> jax.Array:
    """Second-moment eigenvectors, computed once per index (both the
    pdim ladder and the quantized-format gate need them; recomputing
    the full-dataset moment per probe is seconds at 10M)."""
    vecs = getattr(index, "_walk_calib_vecs", None)
    if vecs is None:
        _, vecs = jnp.linalg.eigh(_second_moment(index.dataset))
        object.__setattr__(index, "_walk_calib_vecs", vecs)
    return vecs


def _quant_calib_ok(index: Index, pdim: int) -> bool:
    """Does the int8-quantized pdim projection still clear the walk
    fidelity bar?  (cached per (index, pdim))."""
    cache = getattr(index, "_walk_quant_ok", None)
    if cache is None:
        cache = {}
        object.__setattr__(index, "_walk_quant_ok", cache)
    if pdim not in cache:
        queries, pool, self_col = _calib_sample(index.dataset)
        ip_metric = index.metric == DistanceType.InnerProduct
        ov = float(_calib_overlap(queries, pool, self_col,
                                  _calib_vecs(index),
                                  min(pdim, index.dim), _WALK_CALIB_K,
                                  ip_metric, quant=True))
        cache[pdim] = ov >= _WALK_FIDELITY
    return cache[pdim]


def _walk_proj(dataset, pdim, vecs=None):
    """(dim, pdim) projection for the packed walk: uncentered PCA (top
    singular subspace of the second moment) — the walk approximates the
    CROSS TERM <q, x> by <q P, x P>, so the right subspace is the one
    capturing raw inner products, not the mean-centered covariance's.
    Pass precomputed ``vecs`` to skip the full-dataset moment pass
    (multi-second at 10M; the build/calibration already holds them)."""
    dim = dataset.shape[1]
    if pdim < dim:
        if vecs is None:
            _, vecs = jnp.linalg.eigh(_second_moment(dataset))  # ascending
        return vecs[:, dim - pdim:]
    return jnp.eye(dim, dtype=jnp.float32)


def _table_plan(n, kg, pdim, budget, deep=False):
    """First (deg_t, pdim, quant) packed-table rung whose 128-lane
    padded bytes fit ``budget`` (quant pdims forced even — the int8
    format packs lane pairs).  The deep regime skips the bf16 rung:
    its builder's unchunked gathers materialize the very lane-padded
    transients the regime exists to avoid.  None when nothing fits."""
    pde = max(pdim - pdim % 2, 8)
    rungs = [] if deep else [(min(kg, 64), pdim, False)]
    rungs += [(min(kg, 64), pde, True),
              (min(kg, 32), pde, True),
              (min(kg, 32), max(pde // 2 - (pde // 2) % 2, 8), True),
              (min(kg, 16), 8, True)]
    for deg_t, pd, q in rungs:
        if _table_bytes(n, deg_t, pd, q) <= budget:
            return deg_t, pd, q
    return None


def _build_refine_table(dataset, knn, plan, vecs):
    """Build the walk table for a refinement round per ``plan``;
    returns (table, proj, scales-or-None, quant)."""
    deg_t, pd, q = plan
    if q:
        table, proj, scales = _build_walk_table_q(dataset, knn, pd,
                                                  deg=deg_t, vecs=vecs)
        return table, proj, scales, True
    table, proj = _build_walk_table(dataset, knn[:, :deg_t], pd,
                                    vecs=vecs)
    return table, proj, None, False


def _quant_unit(pdim: int) -> int:
    """int16 lanes per neighbor in the quantized row format: pdim/2
    lanes of int8 pairs + 1 norm lane + 2 id lanes."""
    return pdim // 2 + 3


def _table_bytes(n: int, deg: int, pdim: int, quant: bool) -> int:
    """Packed-table bytes for n rows at this (deg, pdim, format) —
    the 128-lane padded row width times int16 (the ONE definition of
    the size gate; five call sites diverged before round 5)."""
    unit = _quant_unit(pdim) if quant else pdim + 4
    return n * (-(-(deg * unit) // 128) * 128) * 2


def _search_table_format(index: "Index", pdim: int):
    """Format selection for the SEARCH walk table (shared by
    ``search`` and the AOT exporter): bf16 when it fits the byte gate,
    else the int8/uint16 format at the calibrated pdim then half of it
    (each quant rung gated on its own measured fidelity).  Returns
    (pdim, quant) or None when nothing fits."""
    deg = index.graph_degree
    pdim = min(pdim, index.dim)
    if _table_bytes(index.size, deg, pdim, False) <= _WALK_TABLE_MAX_BYTES:
        return pdim, False
    for p_try in dict.fromkeys(
            (max(pdim - pdim % 2, 8),
             max(pdim // 2 - (pdim // 2) % 2, 8))):
        if p_try > index.dim:      # tiny-dim index: no even rung exists
            continue
        if (_table_bytes(index.size, deg, p_try, True)
                <= _WALK_TABLE_MAX_BYTES
                and _quant_calib_ok(index, p_try)):
            return p_try, True
    return None


@functools.partial(jax.jit, static_argnames=("pdim",))
def _build_walk_table(dataset, graph, pdim, vecs=None):
    """bf16 packed-neighborhood table (n, W) int16 — see _WalkCache."""
    n, dim = dataset.shape
    xf = dataset.astype(jnp.float32)
    proj = _walk_proj(dataset, pdim, vecs)
    xp = (xf @ proj).astype(jnp.bfloat16)          # (n, pdim)
    x_sq = jnp.sum(xf * xf, axis=1)                # (n,) f32

    nb = graph.astype(jnp.int32)                   # (n, deg), all >= 0
    deg = nb.shape[1]
    nb_p = jax.lax.bitcast_convert_type(xp[nb], jnp.int16)
    sq2 = jax.lax.bitcast_convert_type(x_sq[nb], jnp.int16)   # (n,deg,2)
    id2 = jax.lax.bitcast_convert_type(nb, jnp.int16)         # (n,deg,2)
    unit = pdim + 4
    table = jnp.concatenate([nb_p, sq2, id2], axis=2)
    table = table.reshape(n, deg * unit)
    w_pad = -(-(deg * unit) // 128) * 128
    table = jnp.pad(table, ((0, 0), (0, w_pad - deg * unit)))
    return table, proj


@functools.partial(jax.jit, static_argnames=("pdim", "deg", "chunk"))
def _build_walk_table_q(dataset, graph, pdim, deg=0, chunk=65536,
                        vecs=None):
    """Quantized packed-neighborhood table: int8 projected lanes (two
    per int16 lane, global symmetric scale at the 99.9th |value|
    percentile) + uint16-quantized squared norms + int32 ids — 2.5x
    smaller than the bf16 format at pdim 16, the difference between
    CAGRA fitting 10M rows on one chip or not.  ``deg`` (0 -> all)
    takes a per-chunk prefix of ``graph`` — passing a pre-sliced
    (n, deg) array would materialize a lane-padded 5 GB temp at 10M.
    Rows pack in chunks for the same reason.  Returns (table (n, Wq)
    int16, proj, scales (3,) f32 = [a, sq_min, sq_scale])."""
    n, dim = dataset.shape
    deg = deg or graph.shape[1]
    xf32 = dataset.astype(jnp.float32)
    proj = _walk_proj(dataset, pdim, vecs)
    xp = xf32 @ proj                               # (n, pdim) f32
    x_sq = jnp.sum(xf32 * xf32, axis=1)
    # clip-scale at the 99.9th percentile of |xp| (outlier-robust)
    a = jnp.percentile(jnp.abs(xp[:: max(n // 65536, 1)]), 99.9)
    a = jnp.maximum(a, 1e-12)
    s8 = jnp.clip(jnp.round(xp / a * 127.0), -127, 127).astype(jnp.int8)
    del xp
    sq_min = jnp.min(x_sq)
    sq_scale = jnp.maximum(jnp.max(x_sq) - sq_min, 1e-12) / 65535.0
    sq_q = jnp.round((x_sq - sq_min) / sq_scale).astype(jnp.uint16)

    unit = _quant_unit(pdim)
    w_pad = -(-(deg * unit) // 128) * 128
    chunk = min(chunk, n)
    n_chunks = -(-n // chunk)

    def body(ci, table):
        start = jnp.minimum(ci * chunk, n - chunk)
        nb = jax.lax.dynamic_slice(
            graph, (start, 0), (chunk, graph.shape[1])
        )[:, :deg].astype(jnp.int32)
        p16 = jax.lax.bitcast_convert_type(
            s8[nb].reshape(chunk, deg, pdim // 2, 2), jnp.int16)
        sq1 = jax.lax.bitcast_convert_type(sq_q[nb], jnp.int16)[..., None]
        id2 = jax.lax.bitcast_convert_type(nb, jnp.int16)
        rows = jnp.concatenate([p16, sq1, id2], axis=2
                               ).reshape(chunk, deg * unit)
        rows = jnp.pad(rows, ((0, 0), (0, w_pad - deg * unit)))
        return jax.lax.dynamic_update_slice(table, rows, (start, 0))

    table = jax.lax.fori_loop(
        0, n_chunks, body, jnp.zeros((n, w_pad), jnp.int16))
    scales = jnp.stack([a, sq_min, sq_scale * 1.0])
    return table, proj, scales.astype(jnp.float32)


def _decode_neighborhood(rows, pdim, deg, quant, scales):
    """Shared unpack of (q, w, deg, unit) int16 neighborhood rows into
    (nb_p bf16 (q,w,deg,pdim), nb_sq f32, nb_id int32).  For the
    quantized format the int8 lanes decode EXACTLY into bf16 (integers
    up to 256 are representable); the caller's query side carries the
    a/127 scale."""
    if not quant:
        nb_p = jax.lax.bitcast_convert_type(rows[..., :pdim],
                                            jnp.bfloat16)
        nb_sq = jax.lax.bitcast_convert_type(
            rows[..., pdim:pdim + 2], jnp.float32)
        nb_id = jax.lax.bitcast_convert_type(
            rows[..., pdim + 2:pdim + 4], jnp.int32)
        return nb_p, nb_sq, nb_id
    h = pdim // 2
    v = rows[..., :h].astype(jnp.int32)
    lo = ((v << 24) >> 24).astype(jnp.bfloat16)            # sign-extended
    hi = ((v << 16) >> 24).astype(jnp.bfloat16)
    nb_p = jnp.stack([lo, hi], axis=-1).reshape(*rows.shape[:-1], pdim)
    uq = rows[..., h].astype(jnp.int32) & 0xFFFF
    nb_sq = scales[1] + scales[2] * uq.astype(jnp.float32)
    nb_id = jax.lax.bitcast_convert_type(rows[..., h + 1:h + 3],
                                         jnp.int32)
    return nb_p, nb_sq, nb_id


@functools.partial(jax.jit, static_argnames=("n_entries",))
def _build_entry_set(dataset, proj, key, n_entries):
    n = dataset.shape[0]
    entry_ids = jax.random.choice(key, n, (n_entries,),
                                  replace=False).astype(jnp.int32)
    rows = dataset[entry_ids].astype(jnp.float32)
    return ((rows @ proj).astype(jnp.bfloat16),
            jnp.sum(rows * rows, axis=1), entry_ids)


def _walk_cache(res, index: Index, pdim: int, n_entries: int,
                quant: bool = False) -> _WalkCache:
    """Get-or-build the packed neighborhood table (mutates the index —
    the cache stays attached, same lazy pattern as ivf_flat's
    ``list_data_sq``).  At most ONE table is kept: a caller sweeping
    ``walk_pdim`` values would otherwise accumulate several multi-GB
    tables until the index is dropped.  The small entry sets are cached
    per (pdim, n_entries) — a second entry size must not rebuild the
    multi-GB table."""
    pdim = min(pdim, index.dim)
    n_entries = min(n_entries, index.size)
    tables = getattr(index, "_walk_tables", None)
    if tables is None:
        tables = {}
        object.__setattr__(index, "_walk_tables", tables)
        object.__setattr__(index, "_walk_entries", {})
    tkey = (pdim, quant)
    if tkey not in tables:
        tables.clear()                     # evict any previous table
        vecs = _calib_vecs(index) if pdim < index.dim else None
        if quant:
            tables[tkey] = _build_walk_table_q(index.dataset, index.graph,
                                               pdim, vecs=vecs)
        else:
            tables[tkey] = _build_walk_table(index.dataset, index.graph,
                                             pdim, vecs=vecs) + (None,)
    table, proj, scales = tables[tkey]
    entries = index._walk_entries
    ekey = (pdim, n_entries)
    if ekey not in entries:
        entries[ekey] = _build_entry_set(index.dataset, proj,
                                         res.next_key(), n_entries)
    eproj, esq, eids = entries[ekey]
    return _WalkCache(table, proj, eproj, esq, eids, quant=quant,
                      scales=scales)


def _merge_candidates(buf_d, buf_i, visited, cand_d, cand_i, itopk):
    """Dedupe candidates against the buffer and themselves (membership
    masks — the visited-hashmap analogue; O(wd·(itopk+wd)) cheap vector
    compares instead of the round-3 double stable argsort), then merge.

    The buffer is kept SORTED ascending-better across iterations, so the
    merge is one narrow candidate sort + a log2-depth bitonic merge —
    the full-width ``top_k`` it replaces was 83% of measured iteration
    time (round-4 ablation: 8.0 -> 1.4 ms/iter budget at itopk 64).
    ``buf_d``/``cand_d`` are KEYS (ascending-better: d for L2, -score
    for IP), so no metric branches are needed.
    """
    nq, wd = cand_i.shape
    dup_buf = jnp.any(cand_i[:, :, None] == buf_i[:, None, :], axis=-1)
    earlier = jnp.tril(jnp.ones((wd, wd), jnp.bool_), k=-1)
    dup_self = jnp.any((cand_i[:, :, None] == cand_i[:, None, :])
                       & earlier[None], axis=-1)
    keep = (cand_i >= 0) & ~dup_buf & ~dup_self
    cand_d = jnp.where(keep, cand_d, jnp.inf)
    cand_i = jnp.where(keep, cand_i, -1)

    sk, si = jax.lax.sort((cand_d, cand_i), dimension=1, num_keys=1)
    return _bitonic_merge(buf_d, buf_i, visited, sk, si, itopk)


def _bitonic_merge(a_k, a_i, a_v, b_k, b_i, itopk):
    """Merge sorted-ascending (a_k, a_i, a_v) with sorted-ascending
    (b_k, b_i, unvisited) and keep the best ``itopk``: concat
    [a | reverse(b)] is bitonic, so log2(size) compare-exchange passes
    sort it — no full-width sort."""
    nq, A = a_k.shape
    B = b_k.shape[1]
    size = 1 << (A + B - 1).bit_length()
    pad = size - A - B
    if pad:
        b_k = jnp.pad(b_k, ((0, 0), (0, pad)), constant_values=jnp.inf)
        b_i = jnp.pad(b_i, ((0, 0), (0, pad)), constant_values=-1)
    k = jnp.concatenate([a_k, b_k[:, ::-1]], axis=1)
    i = jnp.concatenate([a_i, b_i[:, ::-1]], axis=1)
    v = jnp.concatenate(
        [a_v, jnp.zeros((nq, b_k.shape[1]), jnp.bool_)], axis=1)

    stride = size // 2
    while stride >= 1:
        ks = k.reshape(nq, size // (2 * stride), 2, stride)
        is_ = i.reshape(nq, size // (2 * stride), 2, stride)
        vs = v.reshape(nq, size // (2 * stride), 2, stride)
        swap = ks[:, :, 0] > ks[:, :, 1]
        k = jnp.stack(
            [jnp.where(swap, ks[:, :, 1], ks[:, :, 0]),
             jnp.where(swap, ks[:, :, 0], ks[:, :, 1])],
            axis=2).reshape(nq, size)
        i = jnp.stack(
            [jnp.where(swap, is_[:, :, 1], is_[:, :, 0]),
             jnp.where(swap, is_[:, :, 0], is_[:, :, 1])],
            axis=2).reshape(nq, size)
        v = jnp.stack(
            [jnp.where(swap, vs[:, :, 1], vs[:, :, 0]),
             jnp.where(swap, vs[:, :, 0], vs[:, :, 1])],
            axis=2).reshape(nq, size)
        stride //= 2
    return k[:, :itopk], i[:, :itopk], v[:, :itopk]


def _select_parents(buf_d, buf_i, visited, search_width):
    """Best ``search_width`` unvisited buffer entries; marks them
    visited.  Returns (sel_ids, parent_ok, visited).  The buffer is
    sorted ascending-better, so the j-th best unvisited entry is the
    j-th unvisited POSITION — ``search_width`` cheap argmin passes, no
    top_k.  ``buf_d`` is a key (see _merge_candidates)."""
    nq, A = buf_d.shape
    iota = jnp.arange(A)
    ids, oks = [], []
    for _ in range(search_width):
        pos = jnp.min(jnp.where(visited | (buf_i < 0)
                                | jnp.isinf(buf_d), A, iota), axis=1)
        ok = pos < A
        # when no VALID unvisited entry remains, consume an arbitrary
        # unvisited slot instead — dead (-1/inf) slots must still fill
        # up so the while_loop's all(visited) termination fires on
        # small indices rather than running out max_iterations
        pos_any = jnp.min(jnp.where(visited, A, iota), axis=1)
        pc = jnp.minimum(jnp.where(ok, pos, pos_any), A - 1)
        ids.append(jnp.where(
            ok, jnp.take_along_axis(buf_i, pc[:, None], axis=1)[:, 0], -1))
        oks.append(ok)
        visited = visited.at[jnp.arange(nq), pc].set(True)
    return (jnp.stack(ids, axis=1), jnp.stack(oks, axis=1), visited)


@functools.partial(jax.jit, static_argnames=(
    "k", "itopk", "search_width", "max_iterations", "metric", "rerank",
    "deg", "quant", "fused_hop", "merge_window", "pallas_interpret"))
def _search_impl_walk(dataset, table, entry_proj, entry_sq, entry_ids,
                      proj, queries, k, itopk, search_width,
                      max_iterations, metric, rerank, deg, quant=False,
                      scales=None, fused_hop=False, merge_window=0,
                      pallas_interpret=False, filter_words=None):
    """Greedy walk over the packed neighborhood table.

    Walk distances are approximate (exact ||x||², PCA-projected bf16
    cross term); the final ``rerank`` buffer entries are re-scored
    exactly.  One scattered fat-row fetch per expanded node per
    iteration — the gather-latency analysis that motivates this is in
    the module docstring.  ``quant`` selects the int8/uint16 row format
    (see :func:`_build_walk_table_q`); ``scales`` carries its dequant
    constants.

    ``fused_hop`` routes each hop's score + dedupe + merge through the
    low-batch Pallas kernel (:mod:`raft_tpu.ops.cagra_hop_pallas`):
    candidate distances stay in VMEM and only the sorted itopk buffer
    is written back.  Callers gate it on ``supported_hop`` shapes and
    ids that are exact in f32 (index size < 2^24).
    """
    nq, dim = queries.shape
    n = dataset.shape[0]
    pdim = proj.shape[1]
    unit = _quant_unit(pdim) if quant else pdim + 4
    wd = search_width * deg
    ip_metric = metric == DistanceType.InnerProduct
    # the walk works in KEY space (ascending-better: d for L2, -score
    # for IP) so the sorted-buffer merge needs no metric branches
    worst = jnp.inf

    qf = queries.astype(jnp.float32)
    q_sq = jnp.sum(qf * qf, axis=1)
    qpf = qf @ proj                                  # (q, pdim) f32
    qp = qpf.astype(jnp.bfloat16)      # entry scoring (unscaled bf16)
    if quant:
        # fold the int8 scale into the query side for TABLE rows only:
        # <q, x> ~ (a/127) <q, s8>  (the entry set stays bf16/unscaled)
        qp_t = (qpf * (scales[0] / 127.0)).astype(jnp.bfloat16)
    else:
        qp_t = qp

    # ---- dense entry scoring (no scattered seed gather) ------------------
    ip_e = jax.lax.dot_general(qp, entry_proj, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
    if ip_metric:
        d_e = -ip_e
    else:
        d_e = q_sq[:, None] + entry_sq[None, :] - 2.0 * ip_e
    S = d_e.shape[1]
    ids_e = jnp.broadcast_to(entry_ids[None, :], (nq, S))
    if filter_words is not None:
        # inadmissible entry points must not seed the buffer: they could
        # otherwise survive to the re-rank and be returned
        adm_e = _fbits.query_bits(filter_words, jnp.arange(nq), ids_e)
        d_e = jnp.where(adm_e > 0, d_e, worst)
    if S < itopk:
        pad = itopk - S
        d_e = jnp.concatenate(
            [d_e, jnp.full((nq, pad), worst, jnp.float32)], axis=1)
        ids_e = jnp.concatenate(
            [ids_e, jnp.full((nq, pad), -1, jnp.int32)], axis=1)
    buf_d, pos = jax.lax.top_k(-d_e, itopk)
    buf_d = -buf_d                     # sorted ascending key
    buf_i = jnp.take_along_axis(ids_e, pos, axis=1)
    buf_i = jnp.where(jnp.isinf(buf_d), -1, buf_i)
    visited = jnp.zeros((nq, itopk), jnp.bool_)

    def cond(state):
        _, _, visited, it = state
        return jnp.logical_and(it < max_iterations,
                               jnp.logical_not(jnp.all(visited)))

    def body(state):
        buf_d, buf_i, visited, it = state
        sel_ids, parent_ok, visited = _select_parents(
            buf_d, buf_i, visited, search_width)

        # ONE fat row per parent: the whole neighborhood (projected
        # vectors + norms + ids) in a single scattered fetch
        rows = table[jnp.where(parent_ok, sel_ids, 0)]  # (q, w, W) int16
        rows = rows[..., :deg * unit].reshape(nq, search_width, deg, unit)
        nb_p, nb_sq, nb_id = _decode_neighborhood(rows, pdim, deg, quant,
                                                  scales)
        nb_id = jnp.where(parent_ok[:, :, None], nb_id, -1)
        adm_words = None
        if filter_words is not None:
            # per-hop admission over this hop's wd candidates: rejected
            # ids never enter the buffer, so they are neither returned
            # nor expanded — under selective filters raise itopk /
            # search_width to keep the walk connected
            adm = _fbits.query_bits(filter_words, jnp.arange(nq),
                                    nb_id.reshape(nq, wd))
            if fused_hop:
                adm_words = _fbits.pack_mask(adm > 0)
            else:
                nb_id = jnp.where(adm.reshape(nb_id.shape) > 0, nb_id, -1)

        if fused_hop:
            from raft_tpu.ops import cagra_hop_pallas as chp
            buf_d, buf_i, visited = chp.fused_hop(
                qp_t, q_sq, nb_p.reshape(nq, wd, pdim),
                nb_sq.reshape(nq, wd), nb_id.reshape(nq, wd),
                buf_d, buf_i, visited, itopk=itopk, ip_metric=ip_metric,
                interpret=pallas_interpret, merge_window=merge_window,
                adm_words=adm_words)
            return buf_d, buf_i, visited, it + 1

        ipx = jnp.einsum("qp,qwdp->qwd", qp_t, nb_p,
                         preferred_element_type=jnp.float32)
        if ip_metric:
            d_c = -ipx
        else:
            d_c = q_sq[:, None, None] + nb_sq - 2.0 * ipx

        buf_d, buf_i, visited = _merge_candidates(
            buf_d, buf_i, visited, d_c.reshape(nq, wd),
            nb_id.reshape(nq, wd), itopk)
        return buf_d, buf_i, visited, it + 1

    buf_d, buf_i, visited, _ = jax.lax.while_loop(
        cond, body, (buf_d, buf_i, visited, jnp.int32(0)))

    # ---- exact re-rank of the best `rerank` buffer entries ---------------
    # (the buffer is sorted ascending-better: the best R are a slice)
    r_ids = buf_i[:, :rerank]                            # (q, R)
    vecs = dataset[jnp.clip(r_ids, 0, n - 1)].astype(jnp.float32)
    if ip_metric:
        d_e = jnp.einsum("qd,qrd->qr", qf, vecs,
                         preferred_element_type=jnp.float32)
        d_e = jnp.where(r_ids >= 0, d_e, -jnp.inf)
        out_d, pos = jax.lax.top_k(d_e, k)
    else:
        diff = qf[:, None, :] - vecs
        d_e = jnp.sum(diff * diff, axis=-1)
        d_e = jnp.where(r_ids >= 0, d_e, jnp.inf)
        out_d, pos = jax.lax.top_k(-d_e, k)
        out_d = -out_d
    out_i = jnp.take_along_axis(r_ids, pos, axis=1)
    if metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
    return out_d, out_i


# ---------------------------------------------------------------------------
# search — direct exact walk (fallback: tracers, walk_pdim=0, huge tables)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=(
    "k", "itopk", "search_width", "max_iterations", "metric"))
def _search_impl(dataset, graph, queries, seed_ids, k, itopk, search_width,
                 max_iterations, metric, filter_words=None):
    nq = queries.shape[0]
    n, dim = dataset.shape
    degree = graph.shape[1]
    qf = queries.astype(jnp.float32)
    ip_metric = metric == DistanceType.InnerProduct
    # KEY space (ascending-better; see _merge_candidates)
    worst = jnp.inf

    def dists_to(ids):
        """(q, m) ids -> (q, m) distance KEYS to the query."""
        vecs = dataset[ids].astype(jnp.float32)       # (q, m, d)
        ip = jnp.einsum("qd,qmd->qm", qf, vecs,
                        precision=get_matmul_precision())
        if ip_metric:
            return -ip
        sq = jnp.sum(vecs * vecs, axis=-1)
        qsq = jnp.sum(qf * qf, axis=-1, keepdims=True)
        return jnp.maximum(qsq + sq - 2.0 * ip, 0.0)

    # ---- init buffer: best itopk of the random probe set -----------------
    # (the reference's random-sampling buffer fill: probing more random
    # candidates than itopk prevents the greedy walk from starting in the
    # wrong region and never escaping — cluster-structured data needs it)
    seed_d = dists_to(seed_ids)
    # dedupe random draws: a node sampled twice would occupy two buffer slots
    sorted_seeds = jnp.sort(seed_ids, axis=1)
    dup_sorted = jnp.concatenate(
        [jnp.zeros((nq, 1), jnp.bool_),
         sorted_seeds[:, 1:] == sorted_seeds[:, :-1]], axis=1)
    rank = jnp.argsort(jnp.argsort(seed_ids, axis=1), axis=1)
    seed_dup = jnp.take_along_axis(dup_sorted, rank, axis=1)
    seed_d = jnp.where(seed_dup, worst, seed_d)
    if filter_words is not None:
        adm_s = _fbits.query_bits(filter_words, jnp.arange(nq), seed_ids)
        seed_d = jnp.where(adm_s > 0, seed_d, worst)
    buf_d, pos = jax.lax.top_k(-seed_d, itopk)
    buf_d = -buf_d                     # sorted ascending key
    buf_i = jnp.take_along_axis(seed_ids, pos, axis=1)
    buf_i = jnp.where(jnp.isinf(buf_d), -1, buf_i)
    visited = jnp.zeros((nq, itopk), jnp.bool_)

    def cond(state):
        _, _, visited, it = state
        return jnp.logical_and(it < max_iterations,
                               jnp.logical_not(jnp.all(visited)))

    def body(state):
        buf_d, buf_i, visited, it = state
        sel_ids, parent_ok, visited = _select_parents(
            buf_d, buf_i, visited, search_width)

        # expand adjacency of selected nodes
        nbrs = graph[jnp.where(parent_ok, sel_ids, 0)]     # (q, w, degree)
        nbrs = nbrs.reshape(nq, search_width * degree)
        nbrs = jnp.where(jnp.repeat(parent_ok, degree, axis=1), nbrs, -1)
        if filter_words is not None:
            adm = _fbits.query_bits(filter_words, jnp.arange(nq), nbrs)
            nbrs = jnp.where(adm > 0, nbrs, -1)
        nd = dists_to(jnp.where(nbrs >= 0, nbrs, 0))
        nd = jnp.where(nbrs < 0, worst, nd)

        buf_d, buf_i, visited = _merge_candidates(
            buf_d, buf_i, visited, nd, nbrs, itopk)
        return buf_d, buf_i, visited, it + 1

    buf_d, buf_i, visited, _ = jax.lax.while_loop(
        cond, body, (buf_d, buf_i, visited, jnp.int32(0)))

    # sorted ascending key: the output is a slice (keys back to metric)
    out_d = -buf_d[:, :k] if ip_metric else buf_d[:, :k]
    out_i = buf_i[:, :k]
    if metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
    return out_d, out_i


# tables beyond this working-set size fall back to the direct exact walk
_WALK_TABLE_MAX_BYTES = 6 << 30


@auto_convert_output
def search(res, params: SearchParams, index: Index, queries, k: int,
           *, filter=None) -> Tuple[jax.Array, jax.Array]:
    """Greedy graph-walk search (reference: cagra.cuh:205).

    .. note:: the first search builds and attaches the packed
       neighborhood table (:class:`_WalkCache`) to the index in place —
       a non-pytree attribute, so jitted closures over the index do not
       retrace; pass ``walk_pdim=0`` to skip it.

    Queries pass through the boundary validator (see
    :mod:`raft_tpu.integrity.boundary`): under policy ``mask``,
    non-finite query rows return id -1 / worst distance instead of
    poisoning the batch.

    ``filter`` (a :class:`raft_tpu.filters.SampleFilter` or (q, n) bool
    mask) restricts admission: rejected candidates never enter the walk
    buffer, so they are neither returned nor expanded as parents.
    Unlike the exhaustive scans, the walk is approximate — filtered
    recall is NOT guaranteed to match a post-hoc-filtered exact scan;
    raise ``itopk_size``/``search_width`` under selective filters.
    """
    queries = ensure_array(queries, "queries")
    queries, ok_rows = _boundary.check_matrix(
        queries, "queries", site="cagra.search", dim=index.dim)
    # legacy shape guard: still fires when the validator policy is "off"
    expects(queries.ndim == 2 and queries.shape[1] == index.dim,
            "cagra.search: query dim mismatch")
    dist, ids = _search_checked(res, params, index, queries, k,
                                filter=filter)
    if ok_rows is not None:
        dist, ids = _boundary.mask_search_outputs(
            dist, ids, ok_rows,
            select_min=index.metric != DistanceType.InnerProduct)
    return dist, ids


def _search_checked(res, params: SearchParams, index: Index, queries,
                    k: int, filter=None) -> Tuple[jax.Array, jax.Array]:
    with named_range("cagra::search"):
        fw = _fbits.query_filter_words(filter, queries.shape[0],
                                       "cagra.search")
        if fw is not None and obs.enabled():
            obs.registry().counter("cagra.search.filtered").inc()
        itopk = max(params.itopk_size, k)
        max_iter = params.max_iterations or (
            10 + itopk // max(params.search_width, 1))

        traced = (isinstance(queries, jax.core.Tracer)
                  or isinstance(index.dataset, jax.core.Tracer))
        pdim = 0
        if params.walk_pdim != 0 and not traced:
            pdim = min(params.walk_pdim or _auto_pdim(index), index.dim)
        fmt = _search_table_format(index, pdim) if pdim > 0 else None
        if fmt is not None:
            pdim, quant = fmt
            cache = _walk_cache(res, index, pdim,
                                max(params.entry_points, itopk),
                                quant=quant)
            rerank = min(itopk,
                         params.rerank_topk or max(32, 2 * k))
            rerank = max(rerank, k)
            # low-batch latency path: fuse each hop's score/dedupe/merge
            # into one Pallas kernel (serving buckets of 1-64; ids must
            # be f32-exact for the in-kernel id lanes)
            from raft_tpu.ops import cagra_hop_pallas as chp
            from raft_tpu.ops import vmem_budget as vb
            wd = params.search_width * index.graph_degree
            mw_req = vb.merge_window_request(
                getattr(params, "merge_window", "auto"))
            # the window doubles as the variant selector: 1 = legacy
            # in-pass merge (itopk <= 32), 2 = staged bitonic merge
            # (itopk <= 64); 0 = shape unsupported -> XLA hop
            mw = chp.hop_merge_window(queries.shape[0], itopk, wd,
                                      min(pdim, index.dim),
                                      requested=mw_req)
            fused = (jax.default_backend() == "tpu"
                     and index.size < (1 << 24)
                     and mw > 0)
            stage = ("cagra.search.fused_walk" if fused
                     else "cagra.search.walk")
            with obs.stage(stage) as st:
                out = _search_impl_walk(
                    index.dataset, cache.table, cache.entry_proj,
                    cache.entry_sq, cache.entry_ids, cache.proj, queries,
                    k, itopk, params.search_width, max_iter, index.metric,
                    rerank, index.graph_degree, quant=cache.quant,
                    scales=cache.scales, fused_hop=fused,
                    merge_window=mw if fused else 0, filter_words=fw)
                st.fence(out)
            return _mask_deleted(index, *out)

        # direct exact walk: probe 4×itopk random nodes (min 128) and
        # keep the best itopk — the reference's random-sampling buffer
        # init scaled the same way
        n_seeds = max(itopk,
                      min(index.size,
                          max(params.num_random_samplings * 4 * itopk, 128)))
        key = res.next_key()
        seed_ids = jax.random.randint(
            key, (queries.shape[0], n_seeds), 0, index.size,
            dtype=jnp.int32)
        with obs.stage("cagra.search.walk") as st:
            out = _search_impl(index.dataset, index.graph, queries,
                               seed_ids, k, itopk, params.search_width,
                               max_iter, index.metric, filter_words=fw)
            st.fence(out)
        return _mask_deleted(index, *out)


def _mask_deleted(index: Index, dist, ids) -> Tuple[jax.Array, jax.Array]:
    """Post-filter for the graph delete shim: results whose id is in the
    index's ``deleted_ids`` mask take worst distance / id -1 and sink to
    the end of their row (stable re-sort by distance).  A no-op (zero
    dispatches) for indexes with no recorded deletions."""
    dropped = getattr(index, "deleted_ids", None)
    if not dropped:
        return dist, ids
    del_arr = jnp.asarray(sorted(dropped), jnp.int32)
    select_min = index.metric != DistanceType.InnerProduct
    worst = jnp.asarray(jnp.inf if select_min else -jnp.inf, dist.dtype)
    hit = jnp.isin(ids, del_arr) & (ids >= 0)
    dist = jnp.where(hit, worst, dist)
    ids = jnp.where(hit, -1, ids)
    order = jnp.argsort(dist if select_min else -dist, axis=1,
                        stable=True)
    return (jnp.take_along_axis(dist, order, axis=1),
            jnp.take_along_axis(ids, order, axis=1))


def delete(res, index: Index, ids) -> Index:
    """Delete-mask shim for the graph index (tentpole parity with the
    IVF ``delete``): rows stay in the dataset and graph — the greedy walk
    may still traverse them as waypoints — but they are excluded from
    every search result by :func:`_mask_deleted` and from canary
    ground truth by ``integrity.canary.measure``.

    Returns a new generation-bumped :class:`Index` snapshot sharing the
    dataset/graph arrays; the ``deleted_ids`` frozenset is host-side
    metadata (like canaries, dropped by jax transforms and not
    serialized).  Reclaiming the rows for real requires a rebuild."""
    with named_range("cagra::delete"):
        ids = ensure_array(ids, "ids")
        expects(ids.ndim == 1, "cagra.delete: 1-D ids required")
        prior = getattr(index, "deleted_ids", None) or frozenset()
        dropped = frozenset(prior) | {
            int(v) for v in np.asarray(ids).tolist()}
        out = Index(dataset=index.dataset, graph=index.graph,
                    metric=index.metric)
        out.canaries = index.canaries
        out.deleted_ids = dropped
        # the walk tables depend only on dataset/graph (both shared) —
        # carry them so a delete-mask costs no table rebuild
        for attr in ("_walk_auto_pdim", "_walk_calib_vecs",
                     "_walk_quant_ok", "_walk_tables", "_walk_entries"):
            if hasattr(index, attr):
                object.__setattr__(out, attr, getattr(index, attr))
        _mutate.next_generation(index, out)
        if index.canaries is not None:
            _canary.auto_check(res, out, site="delete")
        return out


# ---------------------------------------------------------------------------
# serialization (reference: cagra_serialize.cuh)
# ---------------------------------------------------------------------------

# v2: trailing recall-canary block (nested envelope, may be absent)
_SERIALIZATION_VERSION = 2
_MIN_READ_VERSION = 1


def serialize(res, stream: BinaryIO, index: Index) -> None:
    """CRC32-enveloped versioned dump (reference: cagra_serialize.cuh)."""
    with ser.enveloped_writer(stream) as body:
        ser.serialize_scalar(res, body, np.int32(_SERIALIZATION_VERSION))
        ser.serialize_scalar(res, body, np.int32(index.metric))
        ser.serialize_mdspan(res, body, index.dataset)
        ser.serialize_mdspan(res, body, index.graph)
        _canary.to_stream(res, body, index.canaries)


def deserialize(res, stream: BinaryIO) -> Index:
    """Truncated / bit-flipped streams raise
    :class:`~raft_tpu.core.serialize.CorruptIndexError`."""
    body = ser.open_envelope(stream)
    version = int(ser.deserialize_scalar(res, body))
    if not _MIN_READ_VERSION <= version <= _SERIALIZATION_VERSION:
        raise ValueError(
            f"cagra serialization version mismatch: got {version}, "
            f"expected {_MIN_READ_VERSION}..{_SERIALIZATION_VERSION}")
    metric = int(ser.deserialize_scalar(res, body))
    dataset = jnp.asarray(ser.deserialize_mdspan(res, body))
    graph = jnp.asarray(ser.deserialize_mdspan(res, body))
    index = Index(dataset=dataset, graph=graph, metric=metric)
    if version >= 2:
        index.canaries = _canary.from_stream(res, body)
    return index


def save(res, filename: str, index: Index, *, retry_policy=None,
         deadline=None) -> None:
    """Atomic file dump (tmp + fsync + rename) with transient-IO retry."""
    from raft_tpu.resilience import save_index
    save_index("cagra.save", lambda b: serialize(res, b, index),
               filename, retry_policy, deadline)


def load(res, filename: str, *, retry_policy=None, deadline=None) -> Index:
    """File-load overload; transient IO retries, corruption fails fast.

    Indexes carrying recall canaries are health-checked before being
    returned (see :func:`raft_tpu.integrity.health_check`)."""
    from raft_tpu.resilience import load_index
    index = load_index("cagra.load", lambda b: deserialize(res, b),
                       filename, retry_policy, deadline)
    _canary.auto_check(res, index, site="load")
    return index
