"""CAGRA: graph-based ANN — build a kNN graph, prune it to a fixed-degree
search graph, answer queries by greedy graph walk.

Reference: raft/neighbors/cagra.cuh:77 ``build_knn_graph``, :109 ``prune``
(renamed ``optimize`` upstream), :205 ``search``; types cagra_types.hpp:41,55,
114.  Build: detail/cagra/cagra_build.cuh:43 (ivf_pq::build :91 + batched
search with gpu_top_k = 2×degree :104-160, then ``refine_host`` exact re-rank
:171).  Prune: detail/cagra/graph_core.cuh:415 (rank-based edge pruning +
reverse-edge addition).  Search: detail/cagra/factory.cuh dispatching
single-cta / multi-cta / multi-kernel greedy-walk kernels with a bitonic
top-M buffer and a hashmap visited set.

TPU design (SURVEY.md §7 flags this as the XLA-hostile one):

- **build** composes the existing IVF-PQ + refine exactly like the reference;
- **prune** keeps the reference's *rank-based detour* criterion, computed in
  node blocks over host-chunked dispatches: per block, membership is a
  sorted-merge (multi-operand sort + cummax run scan — ``searchsorted``
  measured 50x slower, and one whole-graph dispatch trips execution
  watchdogs), never the naive (n, deg, deg, deg) tensor.  The
  reverse-edge pass (graph_core.cuh's rev_graph) is scatter-free:
  edges sorted by (dst, rank), slots read back by gather at
  group_start + slot; leftover slots take the next-best pruned-out
  forward edges;
- **search** replaces the data-dependent walk + hashmap with a
  fixed-iteration ``lax.while_loop`` over a static (q, itopk) candidate
  buffer: each step expands the best unvisited candidates' adjacency rows,
  suppresses duplicates by masked membership test against the buffer (the
  visited-hashmap analogue), and re-selects top-itopk.  Termination: all
  buffered candidates visited, or max_iterations.

Round-4 search redesign (measured, profiles/gather_bench.py): scattered
row gathers on TPU are **per-row latency-bound** (~18 ns/row whether the
row is 128 B or 1 KB; bf16 rows are *slower* than f32), so the round-3
loop — one dataset-row gather per candidate, 64+ rows per expanded node
— was gather-bound at ~5 ms/iteration.  The walk now fetches ONE fat row
per expanded node from a packed **neighborhood table**: all ``degree``
neighbors' PCA-projected vectors (bf16) + full-precision norms and ids
(everything bitcast into int16 lanes — see _WalkCache for why the
container must be an integer dtype) in a single flat row.
Distances along the walk are approximate (exact norms, PCA cross term);
the final buffer is re-ranked with exact distances in one dense pass.
Entry points come from a dense (q, S) matmul against a fixed random
entry set — no scattered seed gather at all.  The reference's hashmap +
bitonic-buffer kernels (detail/cagra/search_single_cta.cuh) solve a
SIMT problem; on TPU the costs invert: membership masks and top-k are
cheap vector ops, scattered fetches are the scarce resource.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import BinaryIO, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import serialize as ser
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu.distance.types import DistanceType
from raft_tpu.matrix.select_k import merge_topk, select_k
from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
from raft_tpu.neighbors.refine import refine
from raft_tpu.utils.precision import get_matmul_precision
from raft_tpu.core.outputs import auto_convert_output, raw


@dataclasses.dataclass
class IndexParams:
    """Reference: cagra_types.hpp:41 ``index_params``."""

    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    metric: int = DistanceType.L2Expanded
    build_pq_bits: int = 8
    build_pq_dim: int = 0
    build_n_lists: int = 0        # 0 -> auto sqrt(n)-scaled
    build_n_probes: int = 32
    build_refine_rate: float = 2.0


@dataclasses.dataclass
class SearchParams:
    """Reference: cagra_types.hpp:55 ``search_params`` (itopk_size,
    search_width, max_iterations).

    TPU additions (see module docstring, round-4 search redesign):

    - ``walk_pdim``: PCA dimension of the packed neighborhood table the
      greedy walk reads (0 disables it — the walk then gathers full
      dataset rows per candidate, exact but gather-bound);
    - ``entry_points``: size of the fixed random entry set scored
      densely to seed the buffer (the ``num_random_samplings``
      analogue);
    - ``rerank_topk``: how many of the final buffer entries get exact
      re-ranked distances (0 -> auto: ``max(32, 2k)``).
    """

    max_iterations: int = 0       # 0 -> auto
    itopk_size: int = 64
    search_width: int = 1
    num_random_samplings: int = 1
    rand_xor_mask: int = 0x128394
    # None -> auto: the smallest PCA dim whose projected distances keep
    # >= _WALK_FIDELITY top-k overlap with exact distances on a
    # density-matched calibration pool (lossless-in-practice on manifold
    # data; falls all the way back to the exact direct walk on data no
    # projection can order).  0 -> exact walk; >0 -> forced dim.
    walk_pdim: Optional[int] = None
    entry_points: int = 4096
    rerank_topk: int = 0


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """Reference: cagra_types.hpp:114 ``index`` — dataset + fixed-degree
    graph (row i holds the neighbor ids of node i)."""

    dataset: jax.Array            # (n, dim)
    graph: jax.Array              # (n, graph_degree) int32
    metric: int = DistanceType.L2Expanded

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]

    def tree_flatten(self):
        return (self.dataset, self.graph), (self.metric,)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0])


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------

def build_knn_graph(
    res,
    dataset,
    intermediate_degree: int,
    *,
    params: Optional[IndexParams] = None,
    batch: int = 8192,
) -> jax.Array:
    """All-nodes kNN graph via IVF-PQ + exact refine
    (reference: cagra.cuh:77 → cagra_build.cuh:43-171).
    Returns (n, intermediate_degree) int32 (self-edges removed).
    """
    with named_range("cagra::build_knn_graph"):
        dataset = ensure_array(dataset, "dataset")
        n, dim = dataset.shape
        p = params or IndexParams()
        n_lists = p.build_n_lists or max(min(n // 64, 4 * int(np.sqrt(n))), 8)
        pq_params = ivf_pq_mod.IndexParams(
            n_lists=n_lists, metric=p.metric, pq_bits=p.build_pq_bits,
            pq_dim=p.build_pq_dim, kmeans_n_iters=10)
        pq_index = ivf_pq_mod.build(res, pq_params, dataset)
        sp = ivf_pq_mod.SearchParams(n_probes=min(p.build_n_probes, n_lists))

        # gpu_top_k = refine_rate × degree oversampling, +1 for self hit
        top_k = min(int(p.build_refine_rate * intermediate_degree) + 1, n)
        rows = []
        for start in range(0, n, batch):
            q = dataset[start:start + batch]
            _, cand = raw(ivf_pq_mod.search)(res, sp, pq_index, q, top_k)
            _, idx = raw(refine)(res, dataset, q, cand,
                            min(intermediate_degree + 1, top_k),
                            metric=DistanceType.L2Expanded
                            if p.metric != DistanceType.InnerProduct
                            else p.metric)
            rows.append(idx)
        knn = jnp.concatenate(rows, axis=0)           # (n, deg+1)

        # drop self-edges: shift left where the first column is the node
        ids = jnp.arange(n, dtype=knn.dtype)[:, None]
        is_self = knn == ids
        # stable partition: non-self first
        order = jnp.argsort(is_self, axis=1, stable=True)
        knn = jnp.take_along_axis(knn, order, axis=1)
        return knn[:, :intermediate_degree].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("block",))
def _detour_chunk(knn_graph, blocks, block=256):
    """Detour-order a chunk of node blocks (see :func:`_detour_order`).

    Membership (is neighbor r in neighbor rp's adjacency?) is a
    **sorted-merge**: concat [adjacency row | keys] per (node, rp),
    one multi-operand ``lax.sort`` by (value, source-tag), run-aware
    member flags via two ``cummax`` scans (robust to duplicate ids on
    either side), and a second small sort carrying the flags back into
    key order.  The earlier ``searchsorted`` formulation lowered to
    serial per-key gathers — measured **50x slower** on TPU than this
    all-sort form (profiles round 4: 50.0 s vs 0.97 s per 32k rows).
    """
    n, deg = knn_graph.shape
    rank = jnp.arange(deg)

    def one_block(kb):                               # (B, deg)
        B = kb.shape[0]
        non = knn_graph[jnp.clip(kb, 0, n - 1)]      # (B, rp=deg, deg)
        keys = jnp.broadcast_to(kb[:, None, :], (B, deg, deg))
        vals = jnp.concatenate([non, keys], axis=-1)           # (B,deg,2deg)
        tags = jnp.concatenate(
            [jnp.zeros((B, deg, deg), jnp.int32),
             jnp.ones((B, deg, deg), jnp.int32)], -1)
        ridx = jnp.concatenate(
            [jnp.zeros((B, deg, deg), jnp.int32),
             jnp.broadcast_to(rank[None, None, :], (B, deg, deg))], -1)
        sv, st, sr = jax.lax.sort((vals, tags, ridx), dimension=-1,
                                  num_keys=2)
        # run-aware membership: a key is a member iff its equal-value
        # run contains an adjacency (tag==0) element
        iota = jnp.arange(2 * deg, dtype=jnp.int32)
        is_start = jnp.concatenate(
            [jnp.ones_like(sv[..., :1], jnp.bool_),
             sv[..., 1:] != sv[..., :-1]], -1)
        run_start = jax.lax.cummax(jnp.where(is_start, iota, 0), axis=2)
        last_sn = jax.lax.cummax(jnp.where(st == 0, iota, -1), axis=2)
        is_member_key = (st == 1) & (last_sn >= run_start)
        # flags back into key order r (non-keys past the end via sentinel)
        sr2 = jnp.where(st == 1, sr, deg)
        _, member_r = jax.lax.sort((sr2, is_member_key.astype(jnp.int32)),
                                   dimension=-1, num_keys=1)
        member = member_r[..., :deg].astype(jnp.bool_)         # (B, rp, r)

        stronger = rank[:, None] < rank[None, :]     # first hop rp < r
        detours = jnp.sum(member & stronger[None], axis=1)   # (B, deg)
        score = detours * deg + rank[None, :]
        order = jnp.argsort(score, axis=1)
        return jnp.take_along_axis(kb, order, axis=1)

    return jax.lax.map(one_block, blocks)


# node rows per _detour_chunk dispatch: ONE lax.map over all of 1M nodes
# is a single multi-minute XLA execution, which the remote-tunnel
# watchdog kills ("TPU worker process crashed") — bound each dispatch
_DETOUR_ROWS_PER_DISPATCH = 32768


def _detour_order(knn_graph, block=256):
    """Rank-based detour ordering (graph_core.cuh:415 ``prune``).

    Edge i→knn[i,r] is *detourable* when ∃ r' < r with knn[i,r'] = k and
    knn[i,r] ∈ knn[k, :] — a 2-hop path whose first hop is a strictly
    stronger edge.  Edges are ordered by (detour_count, original rank);
    callers slice the first ``graph_degree`` columns.

    Blocked: ``lax.map`` over node blocks; per block the neighbor-of-
    neighbor lists (B, deg, deg) are sorted once and each membership
    resolves via ``searchsorted`` — O(B·deg²) memory, no
    (n, deg, deg, deg) intermediate (that is ~2×10¹⁵ elements at the
    reference's 1M×128 defaults).  The blocks are dispatched in
    fixed-size host chunks (two compiled shapes max) so no single
    device execution runs long enough to trip execution watchdogs.
    """
    n, deg = knn_graph.shape
    n_pad = ((n + block - 1) // block) * block
    knn_p = jnp.pad(knn_graph, ((0, n_pad - n), (0, 0)))
    blocks = knn_p.reshape(n_pad // block, block, deg)

    cpb = max(_DETOUR_ROWS_PER_DISPATCH // block, 1)
    nb = blocks.shape[0]
    nb_pad = ((nb + cpb - 1) // cpb) * cpb
    blocks = jnp.pad(blocks, ((0, nb_pad - nb), (0, 0), (0, 0)))
    out = [_detour_chunk(knn_graph, blocks[s:s + cpb], block=block)
           for s in range(0, nb_pad, cpb)]
    out = jnp.concatenate(out, axis=0) if len(out) > 1 else out[0]
    return out.reshape(nb_pad * block, deg)[:n]


@functools.partial(jax.jit, static_argnames=("n", "rev_cap"))
def _reverse_edges(fwd, n, rev_cap):
    """Device-side reverse-edge lists (graph_core.cuh rev_graph).

    For each directed edge (i→j), j collects i into up to ``rev_cap``
    reverse slots, strongest (lowest-rank) edges first: ONE stable
    argsort of the rank-major edge list by dst yields (dst asc, rank
    asc) order; each node's slots then read **by gather** at
    ``group_start + slot`` (group starts via vectorized binary search).
    Scatter-free on purpose: a 32M-singleton scatter measured seconds-
    to-minutes on TPU (round-4 profiling) and made the fused prune
    dispatch long enough to trip the remote execution watchdog, while
    sort + searchsorted + gather are each sub-4s at 1M x 32.
    """
    half = fwd.shape[1]
    # rank-major edge order is a transpose, not a sort; the single stable
    # argsort by dst then yields (dst asc, rank asc) order
    dst = fwd.T.ravel()
    src = jnp.tile(jnp.arange(n, dtype=jnp.int32), half)
    o = jnp.argsort(dst, stable=True)
    dsts = dst[o]
    srcs = src[o]
    e = dsts.shape[0]
    nodes = jnp.arange(n, dtype=dsts.dtype)
    starts = jnp.searchsorted(dsts, nodes)                   # (n,)
    counts = jnp.searchsorted(dsts, nodes, side="right") - starts
    idx = starts[:, None] + jnp.arange(rev_cap)[None, :]     # (n, rev_cap)
    rev = srcs[jnp.clip(idx, 0, e - 1)]
    valid = jnp.arange(rev_cap)[None, :] < counts[:, None]
    return jnp.where(valid, rev, -1)


def prune(res, knn_graph, graph_degree: int) -> jax.Array:
    """Prune an intermediate kNN graph to ``graph_degree`` with detour
    counting + reverse-edge fill (reference: cagra.cuh:109 ``prune``,
    graph_core.cuh:415)."""
    with named_range("cagra::prune"):
        knn_graph = ensure_array(knn_graph, "knn_graph")
        n, deg = knn_graph.shape
        expects(graph_degree <= deg,
                "cagra.prune: graph_degree > intermediate degree")
        ordered = _detour_order(knn_graph)
        half = (max(graph_degree // 2, 1) if graph_degree < deg
                else graph_degree)
        fwd = ordered[:, :half]
        if half == graph_degree:
            return fwd
        rev_cap = graph_degree - half
        rev = _reverse_edges(fwd, n, rev_cap)
        # leftover slots: next-best pruned-out forward edges (not a repeat
        # of one edge — that wastes degree budget)
        fillers = ordered[:, half:half + rev_cap]
        cand = jnp.concatenate([rev, fillers], axis=1)
        sel = jnp.argsort(cand < 0, axis=1, stable=True)[:, :rev_cap]
        rest = jnp.take_along_axis(cand, sel, axis=1)
        return jnp.concatenate([fwd, rest], axis=1)


def build(res, params: IndexParams, dataset) -> Index:
    """Full CAGRA build (reference: cagra.cuh ``build`` = build_knn_graph +
    prune)."""
    dataset = ensure_array(dataset, "dataset")
    knn = build_knn_graph(res, dataset, params.intermediate_graph_degree,
                          params=params)
    graph = prune(res, knn, params.graph_degree)
    return Index(dataset=dataset, graph=graph, metric=params.metric)


# ---------------------------------------------------------------------------
# search — packed-neighborhood walk (round-4 design, see module docstring)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _WalkCache:
    """Derived search-time state (lazily attached to the Index).

    ``table`` (n, W) **int16**, W = pad(degree*(pdim+4), 128) — per
    node, each neighbor's PCA-projected vector (pdim bf16 values),
    full-precision squared norm (f32) and id (int32), ALL bitcast into
    int16 lanes: the whole neighborhood in ONE scattered row fetch.

    The container dtype must be an INTEGER type: bf16 lanes measurably
    corrupt the packed ids/norms — XLA relayout copies at large n flush
    bf16-denormal bit patterns (an int32 id like 1000 bitcasts to a
    denormal low lane), which silently zeroed neighbor ids at 1M and
    collapsed walk recall to 0.02 while every small-scale test passed
    (round-4 debugging).  Integer copies are bit-exact.  The flat
    lane-aligned width also avoids the 2x tiling padding XLA gave the
    (n, degree, pdim+4) 3-D layout.

    ``proj`` (dim, pdim) f32; ``entry_*`` the fixed random entry set
    scored densely at search time.
    """

    table: jax.Array
    proj: jax.Array
    entry_proj: jax.Array      # (S, pdim) bf16
    entry_sq: jax.Array        # (S,) f32
    entry_ids: jax.Array       # (S,) int32


@jax.jit
def _second_moment(dataset):
    xf = dataset.astype(jnp.float32)
    n = xf.shape[0]
    m = min(n, 32768)
    # strided, not leading, sample: on-disk datasets are often grouped
    # by cluster and the first rows would bias the subspace estimate
    sub = xf[::max(n // m, 1)][:m]
    m = sub.shape[0]
    return jax.lax.dot_general(sub, sub, (((0,), (0,)), ((), ())),
                               precision=get_matmul_precision(),
                               preferred_element_type=jnp.float32) / m


# the auto walk projection must preserve NN ordering at this top-k
# overlap, measured for sample queries against a LARGE candidate pool
# (spectral ENERGY is the wrong criterion — on clustered data the
# ordering among a node's neighbors lives in the residual dims; and a
# small within-sample test is wrong too: NN gaps shrink with n, so a
# projection that orders a sparse 1k sample perfectly can scramble the
# true neighbors at 1M density — measured recall collapse both ways, r4)
_WALK_FIDELITY = 0.9
_WALK_CALIB_QUERIES = 256
_WALK_CALIB_POOL = 131072
_WALK_CALIB_K = 10


@functools.partial(jax.jit, static_argnames=("pdim", "k", "ip_metric"))
def _calib_overlap(queries, pool, vecs, pdim, k, ip_metric=False):
    """Top-k overlap between exact and pdim-projected distances for
    calibration queries against a candidate pool — scored under the
    index's own metric (an IP walk ranks purely by the projected cross
    term; gating it on L2 overlap would let the exact-norm term mask
    cross-term error)."""
    dim = pool.shape[1]
    ip = jax.lax.dot_general(queries, pool, (((1,), (1,)), ((), ())),
                             precision=get_matmul_precision(),
                             preferred_element_type=jnp.float32)
    proj = vecs[:, dim - pdim:]
    qp = (queries @ proj).astype(jnp.bfloat16)
    pp = (pool @ proj).astype(jnp.bfloat16)
    ipa = jax.lax.dot_general(qp, pp, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    if ip_metric:
        d_exact, d_apx = -ip, -ipa
    else:
        p_sq = jnp.sum(pool * pool, axis=1)
        d_exact = p_sq[None, :] - 2.0 * ip
        d_apx = p_sq[None, :] - 2.0 * ipa
    _, ie = jax.lax.top_k(-d_exact, k + 1)   # +1: query may be in pool
    _, ia = jax.lax.top_k(-d_apx, k + 1)
    hits = jnp.any(ie[:, :, None] == ia[:, None, :], axis=-1)
    return jnp.mean(hits.astype(jnp.float32))


def _auto_pdim(index: Index) -> int:
    """Smallest multiple-of-8 PCA dim whose projected distances keep
    >= _WALK_FIDELITY top-k overlap with exact distances on a sample
    (cached on the index; a few tiny host syncs, once per index)."""
    cached = getattr(index, "_walk_auto_pdim", None)
    if cached is None:
        dim = index.dim
        n = index.size
        # strided samples (see _second_moment: leading rows bias
        # cluster-grouped datasets); the pool must be large so its NN
        # gaps approach index-scale density
        mq = min(n, _WALK_CALIB_QUERIES)
        mp = min(n, _WALK_CALIB_POOL)
        queries = index.dataset[::max(n // mq, 1)][:mq].astype(jnp.float32)
        pool = index.dataset[::max(n // mp, 1)][:mp].astype(jnp.float32)
        ip_metric = index.metric == DistanceType.InnerProduct
        _, vecs = jnp.linalg.eigh(_second_moment(index.dataset))
        p = 8
        cached = 0
        while p < dim:
            ov = float(_calib_overlap(queries, pool, vecs, p,
                                      _WALK_CALIB_K, ip_metric))
            if ov >= _WALK_FIDELITY:
                cached = p
                break
            p *= 2
        if cached == 0:
            # full-dim projection = rotation only, but the packed table
            # is bf16 — if even that loses the ordering (tight clusters
            # with |x| >> NN gaps), 0 routes to the exact direct walk
            ov = float(_calib_overlap(queries, pool, vecs, dim,
                                      _WALK_CALIB_K, ip_metric))
            cached = dim if ov >= _WALK_FIDELITY else 0
        object.__setattr__(index, "_walk_auto_pdim", cached)
    return cached


@functools.partial(jax.jit, static_argnames=("pdim",))
def _build_walk_table(dataset, graph, pdim):
    n, dim = dataset.shape
    xf = dataset.astype(jnp.float32)
    if pdim < dim:
        # uncentered PCA (top singular subspace of the second moment):
        # the walk approximates the CROSS TERM <q, x> by <q P, x P>, so
        # the right subspace is the one capturing raw inner products,
        # not the mean-centered covariance's
        _, vecs = jnp.linalg.eigh(_second_moment(dataset))  # ascending
        proj = vecs[:, dim - pdim:]                # (dim, pdim)
    else:
        proj = jnp.eye(dim, dtype=jnp.float32)
    xp = (xf @ proj).astype(jnp.bfloat16)          # (n, pdim)
    x_sq = jnp.sum(xf * xf, axis=1)                # (n,) f32

    nb = graph.astype(jnp.int32)                   # (n, deg), all >= 0
    deg = nb.shape[1]
    nb_p = jax.lax.bitcast_convert_type(xp[nb], jnp.int16)
    sq2 = jax.lax.bitcast_convert_type(x_sq[nb], jnp.int16)   # (n,deg,2)
    id2 = jax.lax.bitcast_convert_type(nb, jnp.int16)         # (n,deg,2)
    unit = pdim + 4
    table = jnp.concatenate([nb_p, sq2, id2], axis=2)
    table = table.reshape(n, deg * unit)
    w_pad = -(-(deg * unit) // 128) * 128
    table = jnp.pad(table, ((0, 0), (0, w_pad - deg * unit)))
    return table, proj


@functools.partial(jax.jit, static_argnames=("n_entries",))
def _build_entry_set(dataset, proj, key, n_entries):
    n = dataset.shape[0]
    entry_ids = jax.random.choice(key, n, (n_entries,),
                                  replace=False).astype(jnp.int32)
    rows = dataset[entry_ids].astype(jnp.float32)
    return ((rows @ proj).astype(jnp.bfloat16),
            jnp.sum(rows * rows, axis=1), entry_ids)


def _walk_cache(res, index: Index, pdim: int, n_entries: int) -> _WalkCache:
    """Get-or-build the packed neighborhood table (mutates the index —
    the cache stays attached, same lazy pattern as ivf_flat's
    ``list_data_sq``).  The big table is cached PER pdim; the small
    entry set per (pdim, n_entries) — a second entry size must not
    duplicate the multi-GB table."""
    pdim = min(pdim, index.dim)
    n_entries = min(n_entries, index.size)
    tables = getattr(index, "_walk_tables", None)
    if tables is None:
        tables = {}
        object.__setattr__(index, "_walk_tables", tables)
        object.__setattr__(index, "_walk_entries", {})
    if pdim not in tables:
        tables[pdim] = _build_walk_table(index.dataset, index.graph, pdim)
    table, proj = tables[pdim]
    entries = index._walk_entries
    ekey = (pdim, n_entries)
    if ekey not in entries:
        entries[ekey] = _build_entry_set(index.dataset, proj,
                                         res.next_key(), n_entries)
    eproj, esq, eids = entries[ekey]
    return _WalkCache(table, proj, eproj, esq, eids)


def _merge_candidates(buf_d, buf_i, visited, cand_d, cand_i, itopk):
    """Dedupe candidates against the buffer and themselves (membership
    masks — the visited-hashmap analogue; O(wd·(itopk+wd)) cheap vector
    compares instead of the round-3 double stable argsort), then merge.

    The buffer is kept SORTED ascending-better across iterations, so the
    merge is one narrow candidate sort + a log2-depth bitonic merge —
    the full-width ``top_k`` it replaces was 83% of measured iteration
    time (round-4 ablation: 8.0 -> 1.4 ms/iter budget at itopk 64).
    ``buf_d``/``cand_d`` are KEYS (ascending-better: d for L2, -score
    for IP), so no metric branches are needed.
    """
    nq, wd = cand_i.shape
    dup_buf = jnp.any(cand_i[:, :, None] == buf_i[:, None, :], axis=-1)
    earlier = jnp.tril(jnp.ones((wd, wd), jnp.bool_), k=-1)
    dup_self = jnp.any((cand_i[:, :, None] == cand_i[:, None, :])
                       & earlier[None], axis=-1)
    keep = (cand_i >= 0) & ~dup_buf & ~dup_self
    cand_d = jnp.where(keep, cand_d, jnp.inf)
    cand_i = jnp.where(keep, cand_i, -1)

    sk, si = jax.lax.sort((cand_d, cand_i), dimension=1, num_keys=1)
    return _bitonic_merge(buf_d, buf_i, visited, sk, si, itopk)


def _bitonic_merge(a_k, a_i, a_v, b_k, b_i, itopk):
    """Merge sorted-ascending (a_k, a_i, a_v) with sorted-ascending
    (b_k, b_i, unvisited) and keep the best ``itopk``: concat
    [a | reverse(b)] is bitonic, so log2(size) compare-exchange passes
    sort it — no full-width sort."""
    nq, A = a_k.shape
    B = b_k.shape[1]
    size = 1 << (A + B - 1).bit_length()
    pad = size - A - B
    if pad:
        b_k = jnp.pad(b_k, ((0, 0), (0, pad)), constant_values=jnp.inf)
        b_i = jnp.pad(b_i, ((0, 0), (0, pad)), constant_values=-1)
    k = jnp.concatenate([a_k, b_k[:, ::-1]], axis=1)
    i = jnp.concatenate([a_i, b_i[:, ::-1]], axis=1)
    v = jnp.concatenate(
        [a_v, jnp.zeros((nq, b_k.shape[1]), jnp.bool_)], axis=1)

    stride = size // 2
    while stride >= 1:
        ks = k.reshape(nq, size // (2 * stride), 2, stride)
        is_ = i.reshape(nq, size // (2 * stride), 2, stride)
        vs = v.reshape(nq, size // (2 * stride), 2, stride)
        swap = ks[:, :, 0] > ks[:, :, 1]
        k = jnp.stack(
            [jnp.where(swap, ks[:, :, 1], ks[:, :, 0]),
             jnp.where(swap, ks[:, :, 0], ks[:, :, 1])],
            axis=2).reshape(nq, size)
        i = jnp.stack(
            [jnp.where(swap, is_[:, :, 1], is_[:, :, 0]),
             jnp.where(swap, is_[:, :, 0], is_[:, :, 1])],
            axis=2).reshape(nq, size)
        v = jnp.stack(
            [jnp.where(swap, vs[:, :, 1], vs[:, :, 0]),
             jnp.where(swap, vs[:, :, 0], vs[:, :, 1])],
            axis=2).reshape(nq, size)
        stride //= 2
    return k[:, :itopk], i[:, :itopk], v[:, :itopk]


def _select_parents(buf_d, buf_i, visited, search_width):
    """Best ``search_width`` unvisited buffer entries; marks them
    visited.  Returns (sel_ids, parent_ok, visited).  The buffer is
    sorted ascending-better, so the j-th best unvisited entry is the
    j-th unvisited POSITION — ``search_width`` cheap argmin passes, no
    top_k.  ``buf_d`` is a key (see _merge_candidates)."""
    nq, A = buf_d.shape
    iota = jnp.arange(A)
    ids, oks = [], []
    for _ in range(search_width):
        pos = jnp.min(jnp.where(visited | (buf_i < 0)
                                | jnp.isinf(buf_d), A, iota), axis=1)
        ok = pos < A
        # when no VALID unvisited entry remains, consume an arbitrary
        # unvisited slot instead — dead (-1/inf) slots must still fill
        # up so the while_loop's all(visited) termination fires on
        # small indices rather than running out max_iterations
        pos_any = jnp.min(jnp.where(visited, A, iota), axis=1)
        pc = jnp.minimum(jnp.where(ok, pos, pos_any), A - 1)
        ids.append(jnp.where(
            ok, jnp.take_along_axis(buf_i, pc[:, None], axis=1)[:, 0], -1))
        oks.append(ok)
        visited = visited.at[jnp.arange(nq), pc].set(True)
    return (jnp.stack(ids, axis=1), jnp.stack(oks, axis=1), visited)


@functools.partial(jax.jit, static_argnames=(
    "k", "itopk", "search_width", "max_iterations", "metric", "rerank",
    "deg"))
def _search_impl_walk(dataset, table, entry_proj, entry_sq, entry_ids,
                      proj, queries, k, itopk, search_width,
                      max_iterations, metric, rerank, deg):
    """Greedy walk over the packed neighborhood table.

    Walk distances are approximate (exact ||x||², PCA-projected bf16
    cross term); the final ``rerank`` buffer entries are re-scored
    exactly.  One scattered fat-row fetch per expanded node per
    iteration — the gather-latency analysis that motivates this is in
    the module docstring.
    """
    nq, dim = queries.shape
    n = dataset.shape[0]
    pdim = proj.shape[1]
    unit = pdim + 4
    wd = search_width * deg
    ip_metric = metric == DistanceType.InnerProduct
    # the walk works in KEY space (ascending-better: d for L2, -score
    # for IP) so the sorted-buffer merge needs no metric branches
    worst = jnp.inf

    qf = queries.astype(jnp.float32)
    q_sq = jnp.sum(qf * qf, axis=1)
    qp = (qf @ proj).astype(jnp.bfloat16)            # (q, pdim)

    # ---- dense entry scoring (no scattered seed gather) ------------------
    ip_e = jax.lax.dot_general(qp, entry_proj, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
    if ip_metric:
        d_e = -ip_e
    else:
        d_e = q_sq[:, None] + entry_sq[None, :] - 2.0 * ip_e
    S = d_e.shape[1]
    ids_e = jnp.broadcast_to(entry_ids[None, :], (nq, S))
    if S < itopk:
        pad = itopk - S
        d_e = jnp.concatenate(
            [d_e, jnp.full((nq, pad), worst, jnp.float32)], axis=1)
        ids_e = jnp.concatenate(
            [ids_e, jnp.full((nq, pad), -1, jnp.int32)], axis=1)
    buf_d, pos = jax.lax.top_k(-d_e, itopk)
    buf_d = -buf_d                     # sorted ascending key
    buf_i = jnp.take_along_axis(ids_e, pos, axis=1)
    buf_i = jnp.where(jnp.isinf(buf_d), -1, buf_i)
    visited = jnp.zeros((nq, itopk), jnp.bool_)

    def cond(state):
        _, _, visited, it = state
        return jnp.logical_and(it < max_iterations,
                               jnp.logical_not(jnp.all(visited)))

    def body(state):
        buf_d, buf_i, visited, it = state
        sel_ids, parent_ok, visited = _select_parents(
            buf_d, buf_i, visited, search_width)

        # ONE fat row per parent: the whole neighborhood (projected
        # vectors + norms + ids) in a single scattered fetch
        rows = table[jnp.where(parent_ok, sel_ids, 0)]  # (q, w, W) int16
        rows = rows[..., :deg * unit].reshape(nq, search_width, deg, unit)
        nb_p = jax.lax.bitcast_convert_type(rows[..., :pdim],
                                            jnp.bfloat16)
        nb_sq = jax.lax.bitcast_convert_type(
            rows[..., pdim:pdim + 2], jnp.float32)      # (q, w, deg)
        nb_id = jax.lax.bitcast_convert_type(
            rows[..., pdim + 2:pdim + 4], jnp.int32)
        nb_id = jnp.where(parent_ok[:, :, None], nb_id, -1)

        ipx = jnp.einsum("qp,qwdp->qwd", qp, nb_p,
                         preferred_element_type=jnp.float32)
        if ip_metric:
            d_c = -ipx
        else:
            d_c = q_sq[:, None, None] + nb_sq - 2.0 * ipx

        buf_d, buf_i, visited = _merge_candidates(
            buf_d, buf_i, visited, d_c.reshape(nq, wd),
            nb_id.reshape(nq, wd), itopk)
        return buf_d, buf_i, visited, it + 1

    buf_d, buf_i, visited, _ = jax.lax.while_loop(
        cond, body, (buf_d, buf_i, visited, jnp.int32(0)))

    # ---- exact re-rank of the best `rerank` buffer entries ---------------
    # (the buffer is sorted ascending-better: the best R are a slice)
    r_ids = buf_i[:, :rerank]                            # (q, R)
    vecs = dataset[jnp.clip(r_ids, 0, n - 1)].astype(jnp.float32)
    if ip_metric:
        d_e = jnp.einsum("qd,qrd->qr", qf, vecs,
                         preferred_element_type=jnp.float32)
        d_e = jnp.where(r_ids >= 0, d_e, -jnp.inf)
        out_d, pos = jax.lax.top_k(d_e, k)
    else:
        diff = qf[:, None, :] - vecs
        d_e = jnp.sum(diff * diff, axis=-1)
        d_e = jnp.where(r_ids >= 0, d_e, jnp.inf)
        out_d, pos = jax.lax.top_k(-d_e, k)
        out_d = -out_d
    out_i = jnp.take_along_axis(r_ids, pos, axis=1)
    if metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
    return out_d, out_i


# ---------------------------------------------------------------------------
# search — direct exact walk (fallback: tracers, walk_pdim=0, huge tables)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=(
    "k", "itopk", "search_width", "max_iterations", "metric"))
def _search_impl(dataset, graph, queries, seed_ids, k, itopk, search_width,
                 max_iterations, metric):
    nq = queries.shape[0]
    n, dim = dataset.shape
    degree = graph.shape[1]
    qf = queries.astype(jnp.float32)
    ip_metric = metric == DistanceType.InnerProduct
    # KEY space (ascending-better; see _merge_candidates)
    worst = jnp.inf

    def dists_to(ids):
        """(q, m) ids -> (q, m) distance KEYS to the query."""
        vecs = dataset[ids].astype(jnp.float32)       # (q, m, d)
        ip = jnp.einsum("qd,qmd->qm", qf, vecs,
                        precision=get_matmul_precision())
        if ip_metric:
            return -ip
        sq = jnp.sum(vecs * vecs, axis=-1)
        qsq = jnp.sum(qf * qf, axis=-1, keepdims=True)
        return jnp.maximum(qsq + sq - 2.0 * ip, 0.0)

    # ---- init buffer: best itopk of the random probe set -----------------
    # (the reference's random-sampling buffer fill: probing more random
    # candidates than itopk prevents the greedy walk from starting in the
    # wrong region and never escaping — cluster-structured data needs it)
    seed_d = dists_to(seed_ids)
    # dedupe random draws: a node sampled twice would occupy two buffer slots
    sorted_seeds = jnp.sort(seed_ids, axis=1)
    dup_sorted = jnp.concatenate(
        [jnp.zeros((nq, 1), jnp.bool_),
         sorted_seeds[:, 1:] == sorted_seeds[:, :-1]], axis=1)
    rank = jnp.argsort(jnp.argsort(seed_ids, axis=1), axis=1)
    seed_dup = jnp.take_along_axis(dup_sorted, rank, axis=1)
    seed_d = jnp.where(seed_dup, worst, seed_d)
    buf_d, pos = jax.lax.top_k(-seed_d, itopk)
    buf_d = -buf_d                     # sorted ascending key
    buf_i = jnp.take_along_axis(seed_ids, pos, axis=1)
    buf_i = jnp.where(jnp.isinf(buf_d), -1, buf_i)
    visited = jnp.zeros((nq, itopk), jnp.bool_)

    def cond(state):
        _, _, visited, it = state
        return jnp.logical_and(it < max_iterations,
                               jnp.logical_not(jnp.all(visited)))

    def body(state):
        buf_d, buf_i, visited, it = state
        sel_ids, parent_ok, visited = _select_parents(
            buf_d, buf_i, visited, search_width)

        # expand adjacency of selected nodes
        nbrs = graph[jnp.where(parent_ok, sel_ids, 0)]     # (q, w, degree)
        nbrs = nbrs.reshape(nq, search_width * degree)
        nbrs = jnp.where(jnp.repeat(parent_ok, degree, axis=1), nbrs, -1)
        nd = dists_to(jnp.where(nbrs >= 0, nbrs, 0))
        nd = jnp.where(nbrs < 0, worst, nd)

        buf_d, buf_i, visited = _merge_candidates(
            buf_d, buf_i, visited, nd, nbrs, itopk)
        return buf_d, buf_i, visited, it + 1

    buf_d, buf_i, visited, _ = jax.lax.while_loop(
        cond, body, (buf_d, buf_i, visited, jnp.int32(0)))

    # sorted ascending key: the output is a slice (keys back to metric)
    out_d = -buf_d[:, :k] if ip_metric else buf_d[:, :k]
    out_i = buf_i[:, :k]
    if metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
    return out_d, out_i


# tables beyond this working-set size fall back to the direct exact walk
_WALK_TABLE_MAX_BYTES = 6 << 30


@auto_convert_output
def search(res, params: SearchParams, index: Index, queries, k: int
           ) -> Tuple[jax.Array, jax.Array]:
    """Greedy graph-walk search (reference: cagra.cuh:205).

    .. note:: the first search builds and attaches the packed
       neighborhood table (:class:`_WalkCache`) to the index in place —
       a non-pytree attribute, so jitted closures over the index do not
       retrace; pass ``walk_pdim=0`` to skip it.
    """
    with named_range("cagra::search"):
        queries = ensure_array(queries, "queries")
        expects(queries.ndim == 2 and queries.shape[1] == index.dim,
                "cagra.search: query dim mismatch")
        itopk = max(params.itopk_size, k)
        max_iter = params.max_iterations or (
            10 + itopk // max(params.search_width, 1))

        traced = (isinstance(queries, jax.core.Tracer)
                  or isinstance(index.dataset, jax.core.Tracer))
        pdim = 0
        if params.walk_pdim != 0 and not traced:
            pdim = min(params.walk_pdim or _auto_pdim(index), index.dim)
        table_bytes = index.size * index.graph_degree * (pdim + 4) * 2
        if pdim > 0 and table_bytes <= _WALK_TABLE_MAX_BYTES:
            cache = _walk_cache(res, index, pdim,
                                max(params.entry_points, itopk))
            rerank = min(itopk,
                         params.rerank_topk or max(32, 2 * k))
            rerank = max(rerank, k)
            return _search_impl_walk(
                index.dataset, cache.table, cache.entry_proj,
                cache.entry_sq, cache.entry_ids, cache.proj, queries, k,
                itopk, params.search_width, max_iter, index.metric,
                rerank, index.graph_degree)

        # direct exact walk: probe 4×itopk random nodes (min 128) and
        # keep the best itopk — the reference's random-sampling buffer
        # init scaled the same way
        n_seeds = max(itopk,
                      min(index.size,
                          max(params.num_random_samplings * 4 * itopk, 128)))
        key = res.next_key()
        seed_ids = jax.random.randint(
            key, (queries.shape[0], n_seeds), 0, index.size,
            dtype=jnp.int32)
        return _search_impl(index.dataset, index.graph, queries, seed_ids,
                            k, itopk, params.search_width, max_iter,
                            index.metric)


# ---------------------------------------------------------------------------
# serialization (reference: cagra_serialize.cuh)
# ---------------------------------------------------------------------------

_SERIALIZATION_VERSION = 1


def serialize(res, stream: BinaryIO, index: Index) -> None:
    ser.serialize_scalar(res, stream, np.int32(_SERIALIZATION_VERSION))
    ser.serialize_scalar(res, stream, np.int32(index.metric))
    ser.serialize_mdspan(res, stream, index.dataset)
    ser.serialize_mdspan(res, stream, index.graph)


def deserialize(res, stream: BinaryIO) -> Index:
    version = int(ser.deserialize_scalar(res, stream))
    if version != _SERIALIZATION_VERSION:
        raise ValueError(
            f"cagra serialization version mismatch: got {version}, "
            f"expected {_SERIALIZATION_VERSION}")
    metric = int(ser.deserialize_scalar(res, stream))
    dataset = jnp.asarray(ser.deserialize_mdspan(res, stream))
    graph = jnp.asarray(ser.deserialize_mdspan(res, stream))
    return Index(dataset=dataset, graph=graph, metric=metric)
