"""List-centric grouped scan machinery shared by the IVF searches.

The probe-order scan (one step per probe rank) re-reads every probed list's
data once per probing query — at SIFT-1M bench shapes that is ~55 GB of
HBM gather traffic per 5000-query batch, and the per-query einsum is a
batched mat-vec the MXU cannot tile.  The measured trace
(`profiles/ab_trace`, round 3) shows the scan's gather+einsum fusion
bandwidth-bound at ~320 GB/s.

The grouped scan inverts the loop the way the reference's
``compute_similarity_kernel`` assigns one CTA per (list, query-group)
(ivf_pq_search.cuh:611): (query, probe) pairs are bucketed BY LIST, so each
list's data is read once.  A first cut bucketed pairs into one
``qcap``-wide bucket per list; probe-popularity skew made ``qcap`` ~3.3x
the mean occupancy and the padding inflated both the GEMM and the select
by the same factor (measured slower than probe-order).  This module
implements the fix: **fixed-size pair groups** — each list's pair count is
padded to a multiple of ``G`` (128, a full MXU tile of queries), so hot
lists get several groups instead of widening every bucket.  Padding
overhead is bounded by ``n_lists·G/2`` slots total (~16% at bench shapes),
independent of skew.

The number of groups a batch *needs* is data-dependent, but dispatch no
longer syncs it (round 10): :func:`group_capacity` gives a static,
shape-only bound — ``ceil(P/G) + n_touched_lists`` — at which
:func:`build_groups` provably cannot drop a pair, so the grouped scans
are fully traceable (they lower under ``jit`` and ``shard_map``) and a
warmed executable serves every batch at that shape.  A calibrated
per-index estimate tightens the touched-lists term; only then is an
in-graph overflow count armed, read *after* the scan is enqueued, and
the rare overflowing batch re-dispatches at the exact-safe bound.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.matrix import ops as matrix_ops

GROUP = 128          # pair-group size: one full MXU tile of queries
_GROUP_ROUND = 256   # n_groups rounding quantum (compile-cache stability)


def num_groups(probes: jax.Array, n_lists: int) -> jax.Array:
    """Total fixed-size groups this batch needs: sum over lists of
    ceil(count/G).  Probes ``>= n_lists`` (sentinels) are excluded by the
    segment reduction, matching what :func:`build_groups` lays out.  The
    dispatch path no longer syncs this (see :func:`group_capacity`); it
    remains the calibrated regime's overflow count and the measurement
    :func:`raft_tpu.neighbors.ivf_pq.calibrate_group_capacity` reads."""
    counts = jax.ops.segment_sum(
        jnp.ones(probes.size, jnp.int32), probes.reshape(-1),
        num_segments=n_lists)
    return jnp.sum(-(-counts // GROUP))


num_groups = jax.jit(num_groups, static_argnames=("n_lists",))


@functools.partial(jax.jit, static_argnames=("n_lists",))
def touched_lists(probes: jax.Array, n_lists: int) -> jax.Array:
    """Distinct in-range lists the batch probes — the quantity
    :func:`group_capacity`'s calibrated estimate models."""
    counts = jax.ops.segment_sum(
        jnp.ones(probes.size, jnp.int32), probes.reshape(-1),
        num_segments=n_lists)
    return jnp.sum((counts > 0).astype(jnp.int32))


def round_groups(n: int) -> int:
    """Round a group count up to the compile-cache quantum."""
    return -(-max(n, 1) // _GROUP_ROUND) * _GROUP_ROUND


# estimate safety margin: a calibrated capacity covers probe
# distributions that touch up to 25% more lists than measured before the
# overflow re-dispatch path triggers
_EST_MARGIN = 1.25


def group_capacity(nq: int, n_probes: int, n_lists: int,
                   est: float = 0.0) -> Tuple[int, bool]:
    """Static group capacity for dispatching :func:`build_groups` at a
    traceable shape.  Returns ``(capacity, exact)``.

    Worst case: with ``P = nq * n_probes`` pairs, each touched list
    wastes at most one partial group, so
    ``sum_l ceil(c_l/G) <= ceil(P/G) + n_touched`` and
    ``n_touched <= min(n_lists, P)``.  Dispatching at that bound can
    NEVER drop a pair — ``exact=True`` means no overflow machinery (and
    no host sync of any kind) is needed.

    ``est`` (the calibrated fraction of ``min(n_lists, P)`` a real batch
    touches, measured by ``ivf_pq.calibrate_group_capacity`` and carried
    in the index envelope) tightens the touched-lists term under a 25%
    safety margin.  The tightened capacity is rounded
    (:func:`round_groups`) so nearby estimates share executables and
    clamped to the worst bound; when it lands below the bound,
    ``exact=False`` tells the caller to arm the in-graph overflow count
    and re-dispatch at the worst bound if exceeded.
    """
    P = nq * n_probes
    if P <= 0:
        return 1, True
    touched_worst = min(n_lists, P)
    worst = -(-P // GROUP) + touched_worst
    if est <= 0.0:
        return worst, True
    touched = min(int(est * _EST_MARGIN * touched_worst) + 1, touched_worst)
    capacity = min(round_groups(-(-P // GROUP) + touched), worst)
    return capacity, capacity >= worst


def ids_f32_exact(index_obj, list_indices: jax.Array) -> bool:
    """True when every candidate id in ``list_indices`` is exactly
    representable in float32 (|id| < 2^24) — the precondition for the
    Pallas kernel's one-hot f32 id contraction.

    ``extend(new_indices=...)`` accepts arbitrary user int32 ids, so a
    row-count proxy (n_lists * capacity) is not a safe bound.  The check
    reads the true max |id| once (one tiny host sync) and caches the
    verdict on the index object; extend() returns a fresh Index, so the
    cache never goes stale.
    """
    cached = getattr(index_obj, "_ids_f32_exact", None)
    if cached is None:
        max_abs = int(jnp.max(jnp.abs(list_indices)))
        cached = max_abs < (1 << 24)
        object.__setattr__(index_obj, "_ids_f32_exact", cached)
    return cached


def build_groups(probes: jax.Array, n_lists: int, n_groups: int
                 ) -> Tuple[jax.Array, jax.Array]:
    """Bucket (query, probe) pairs into fixed-size per-list groups.

    Returns ``(group_list, slot_pairs)``:

    - ``group_list`` (n_groups,) int32 — the list each group scans (tail
      groups beyond the real count alias the last list; their slots are
      empty);
    - ``slot_pairs`` (n_groups, GROUP) int32 — flattened pair index
      (q * n_probes + probe_rank) per slot, with ``P = probes.size`` as
      the empty-slot sentinel (scatters through it are dropped).

    Pair → (group, slot): sort pairs by list; pair with in-list rank r of
    list l lands in group ``group_start[l] + r // G``, slot ``r % G``.
    """
    P = probes.size
    pl = probes.reshape(-1)
    order = jnp.argsort(pl)
    pl_s = pl[order]
    counts = jax.ops.segment_sum(jnp.ones(P, jnp.int32), pl,
                                 num_segments=n_lists)
    groups_per_list = -(-counts // GROUP)
    gstart = jnp.cumsum(groups_per_list) - groups_per_list
    group_list = jnp.repeat(jnp.arange(n_lists, dtype=jnp.int32),
                            groups_per_list, total_repeat_length=n_groups)
    starts = jnp.cumsum(counts) - counts
    rank = jnp.arange(P) - starts[pl_s]
    g = gstart[jnp.minimum(pl_s, n_lists - 1)] + rank // GROUP
    s = rank % GROUP
    slot_pairs = jnp.full((n_groups, GROUP), P, jnp.int32)
    # probes >= n_lists are sentinels (the super-tile dedupe marks
    # duplicate pairs that way): their pairs write the empty-slot
    # sentinel wherever they land, so they can never surface results
    vals = jnp.where(pl_s < n_lists, order, P)
    slot_pairs = slot_pairs.at[g, s].set(vals, mode="drop")
    return group_list, slot_pairs


@functools.partial(jax.jit, static_argnames=("n_lists",))
def probe_overlap_order(probes: jax.Array, n_lists: int) -> jax.Array:
    """Probe-overlap query grouping: a permutation of the batch's queries
    that clusters queries probing the SAME lists.

    Queries sort by their (rank-0, rank-1) probe pair — nearest coarse
    centers, the strongest overlap signal the probe table carries.
    Combined with :func:`build_groups`'s (list, pair-index) sort this
    makes a hot list's pair groups hold runs of CONSECUTIVE queries:

    - adjacent groups of one list keep the same BlockSpec index, so the
      Pallas pipeline skips the re-DMA and each hot list's data streams
      from HBM once per BATCH, not once per probing query;
    - the fused kernels' accumulator one-hots touch a narrow band of
      query rows per group (the prerequisite for windowed merges).

    Returns ``qorder`` (nq,) int32; callers permute queries/probes by it
    before grouping and un-permute results with ``argsort(qorder)``.
    The permutation changes only iteration order — distances and ids
    are untouched.
    """
    nq, n_probes = probes.shape
    if n_probes == 0:
        # degenerate batch (no probes — e.g. every list emptied by
        # delete/compaction upstream): identity order, nothing to cluster
        return jnp.arange(nq, dtype=jnp.int32)
    r0 = jnp.minimum(probes[:, 0].astype(jnp.int32), n_lists)
    r1 = jnp.minimum(probes[:, min(1, n_probes - 1)].astype(jnp.int32),
                     n_lists)
    # sentinels (>= n_lists, from super-tile dedupe) clamp into range so
    # the ordering stays monotone
    if n_lists + 1 <= 46340:
        # (n_lists+1)^2 fits int32: one fused sort key
        key = r0 * (n_lists + 1) + r1
        return jnp.argsort(key).astype(jnp.int32)
    # above ~46k lists the packed key wraps int32 (and x64 is disabled
    # by default, so an int64 key would silently downcast): lexsort via
    # two STABLE passes — secondary key first, primary second
    o1 = jnp.argsort(r1, stable=True)
    o0 = jnp.argsort(r0[o1], stable=True)
    return o1[o0].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("factor", "n_super"))
def dedup_super_probes(probes: jax.Array, factor: int, n_super: int
                       ) -> jax.Array:
    """Map per-query probes onto super-tiles of ``factor`` adjacent
    lists and mask per-row duplicates with the ``n_super`` sentinel.

    Small lists fragment pairs into many groups whose per-group cost is
    flat (~22 us measured at any cap, round 5); scanning ``factor``
    lists per tile cuts the group count, and a query probing several
    lists of one tile pays for the tile ONCE — the duplicate pairs are
    sentineled out here and dropped by :func:`build_groups`."""
    sp = probes // factor
    dup = matrix_ops.row_duplicate_mask(sp)
    return jnp.where(dup, n_super, sp)


def finalize_topk(outd: jax.Array, outi: jax.Array, nq: int, k: int,
                  select_min: bool, sqrt: bool, select_k_fn
                  ) -> Tuple[jax.Array, jax.Array]:
    """Final hierarchical select over the per-pair top-kt survivors.

    ``outd``/``outi`` are (P, kt) — or already (nq, n_probes*kt) — laid
    out so reshaping to (nq, n_probes*kt) groups each query's candidates
    (pair id is q * n_probes + probe_rank).  Shared epilogue of every
    probe-order and grouped scan: one narrow select, sentinel padding to
    k, optional sqrt for the sqrt-L2 metrics.
    """
    worst = jnp.inf if select_min else -jnp.inf
    alld = outd.reshape(nq, -1)
    alli = outi.reshape(nq, -1)
    kf = min(k, alld.shape[1])
    best_d, best_i = select_k_fn(alld, kf, in_idx=alli,
                                 select_min=select_min)
    if kf < k:
        best_d = jnp.pad(best_d, ((0, 0), (0, k - kf)),
                         constant_values=worst)
        best_i = jnp.pad(best_i, ((0, 0), (0, k - kf)),
                         constant_values=-1)
    # tombstoned slots (neighbors/mutate: id <= -2) carry worst-sentinel
    # distances through every scan, but when k exceeds the valid
    # candidate count their ENCODED ids can survive the select — clamp
    # every negative id to the public -1 sentinel here, the one epilogue
    # all probe-order and grouped scans share.  Filter-rejected rows
    # (filters.SampleFilter) fold to the worst distance with their REAL
    # id still attached; map any worst-distance survivor to -1 so every
    # scan path shares the fused epilogue's (worst, -1) contract.
    best_i = jnp.where(best_d == worst, -1, jnp.maximum(best_i, -1))
    if sqrt:
        best_d = jnp.sqrt(jnp.maximum(best_d, 0.0))
    return best_d, best_i


def scatter_packed(vals, ids, slot_pairs, P, select_min):
    """Scatter per-pair kernel results into (P, kt) buffers in ONE pass.

    Two separate (values, ids) row scatters measured ~36 ms each at bench
    shapes; bitcast-packing halves the per-row scatter bookkeeping.
    Rows with +inf values (exhausted: fewer than kt finite candidates)
    get the -1 id sentinel, matching the XLA scan path.
    """
    kt = vals.shape[-1]
    worst = jnp.inf if select_min else -jnp.inf
    ids = jnp.where(jnp.isinf(vals), -1, ids)
    flat = slot_pairs.reshape(-1)
    packed = jnp.concatenate(
        [jax.lax.bitcast_convert_type(vals, jnp.int32).reshape(-1, kt),
         ids.reshape(-1, kt)], axis=1)                 # (rows, 2*kt)
    init = jnp.concatenate(
        [jnp.broadcast_to(
            jax.lax.bitcast_convert_type(jnp.float32(worst), jnp.int32),
            (P, kt)),
         jnp.full((P, kt), -1, jnp.int32)], axis=1)
    outp = init.at[flat].set(packed, mode="drop")
    outd = jax.lax.bitcast_convert_type(outp[:, :kt], jnp.float32)
    outi = outp[:, kt:]
    return outd, outi


def scan_traffic(rot: int, pq_dim: int = 0, pq_bits: int = 0) -> dict:
    """Per-candidate-row HBM bytes each grouped scan mode streams.

    Every mode reads the (int32) candidate-id row and an (f32) cached row
    norm per candidate; what differs is the data payload — bf16
    reconstructions (2 B/dim), int8 reconstructions (1 B/dim), or
    lane-major packed codes (int32 words covering pq_dim*pq_bits bits).
    Query/center/codebook traffic is per GROUP (128 pairs), not per row,
    and amortizes out at scan scale; this model is what the round-6
    decomposition profile and the docs' memory-traffic table report."""
    base = 4 + 4                      # id row (int32) + row norm (f32)
    out = {"recon": 2 * rot + base, "recon8": rot + base}
    if pq_dim and pq_bits:
        w_bytes = -(-pq_dim * pq_bits // 8)
        out["codes"] = 4 * -(-w_bytes // 4) + base
    # fused mode streams the same candidate rows as its backing source
    # (codes when eligible, else recon) — its win is on the OUTPUT side:
    # the per-pair (vals, ids) round-trip plus scatter and final select
    # disappear (see pair_output_traffic)
    out["fused"] = out.get("codes", out["recon"])
    return out


def pair_output_traffic(kt: int) -> int:
    """Per-(query, probe) HBM bytes of the NON-fused epilogue that the
    fused kernels eliminate: the kernel's (kt f32, kt int32) output
    write, the scatter's read + packed write, and the final select's
    read of the (P, 2*kt) buffers.  This is the round-7 column of the
    decomposition profile."""
    row = 2 * 4 * kt                  # one (vals, ids) pair row
    return row * 4                    # write + scatter r/w + select read


def block_size(n_groups: int, *per_group_bytes: int,
               budget: int = 96 << 20, quantum: int = 16) -> int:
    """Groups per scan step such that the listed per-group transients stay
    under ``budget`` bytes."""
    per = max(sum(per_group_bytes), 1)
    b = budget // per
    b = max(quantum, b - b % quantum)
    # floor at 1: n_groups == 0 (every probed list empty after
    # delete/compaction) must not produce a zero block size — the scan
    # driver guards the empty case itself
    return min(b, max(n_groups, 1))


def scan_and_scatter(group_list, slot_pairs, P, cap, k, select_min, block,
                     select_k_fn, distance_block, kt=0, merge_window=0):
    """Shared scan driver: for each block of groups, compute distances via
    ``distance_block(gl, slot) -> ((B, GROUP, cap) masked distances,
    (B, cap) candidate ids)`` and take each pair-row's local top-kt.

    Per-block results are emitted as scan *outputs* and scattered into the
    (P, kt) buffers ONCE after the loop — a (P, kt) scan carry would be
    copied every iteration by the in-loop scatter (measured ~150 MB/block
    at bench shapes).  Candidate ids are resolved by gathering the block's
    (B, cap) id rows at the selected positions, which broadcasting
    ``take_along_axis`` does without materializing a (B, GROUP, cap) id
    tensor.  Sentinel slots scatter out of bounds and are dropped; the
    clamped tail block emits duplicate pairs with identical values, so the
    final scatter stays idempotent.

    ``merge_window`` is the XLA twin of the fused kernels' staging ring
    (ops.vmem_budget): 0 stages every block's outputs before the single
    scatter (the round-7 shape, maximal staging footprint); W >= 1
    scatters once per W-block window inside an outer scan, bounding the
    staged (n_blocks * B * GROUP, kt) output pair to W blocks at the
    cost of one (P, kt) carry copy per window instead of none.  Exact
    either way — each pair-row is written with the same value no matter
    which window carries it (overlap only at the clamped tail block,
    which emits duplicates with identical values)."""
    n_groups = group_list.shape[0]
    worst = jnp.inf if select_min else -jnp.inf
    # kt (SearchParams.per_probe_topk) narrows the per-pair keep-set below
    # k; 0 keeps the exact-merge default
    kt = min(kt or k, cap) if cap else (kt or k)

    if n_groups == 0 or block <= 0 or cap == 0:
        # nothing to scan (all probed lists empty — possible after
        # delete/compaction empties the index): every pair is exhausted
        return (jnp.full((P, kt), worst, jnp.float32),
                jnp.full((P, kt), -1, jnp.int32))
    block = min(block, n_groups)
    n_blocks = -(-n_groups // block)
    block_starts = jnp.minimum(jnp.arange(n_blocks) * block,
                               n_groups - block)

    def step(_, start):
        gl = jax.lax.dynamic_slice(group_list, (start,), (block,))
        slot = jax.lax.dynamic_slice(slot_pairs, (start, 0), (block, GROUP))
        d, ids = distance_block(gl, slot)            # (B, G, cap), (B, cap)
        td, pos = select_k_fn(d.reshape(block * GROUP, cap), kt,
                              select_min=select_min)
        ti = jnp.take_along_axis(ids[:, None, :],
                                 pos.reshape(block, GROUP, kt), axis=2)
        return None, (td, ti.reshape(block * GROUP, kt), slot.reshape(-1))

    outd = jnp.full((P, kt), worst, jnp.float32)
    outi = jnp.full((P, kt), -1, jnp.int32)

    if 0 < merge_window < n_blocks:
        W = merge_window
        n_windows = -(-n_blocks // W)
        # pad by repeating the last start: duplicate blocks re-write
        # identical values, same idempotence as the clamped tail
        pad = n_windows * W - n_blocks
        starts = jnp.concatenate(
            [block_starts, jnp.broadcast_to(block_starts[-1:], (pad,))])

        def window(carry, wstarts):
            od, oi = carry
            _, (tds, tis, flats) = jax.lax.scan(step, None, wstarts)
            flat = flats.reshape(-1)
            od = od.at[flat].set(tds.reshape(-1, kt), mode="drop")
            oi = oi.at[flat].set(tis.reshape(-1, kt), mode="drop")
            return (od, oi), None

        (outd, outi), _ = jax.lax.scan(window, (outd, outi),
                                       starts.reshape(n_windows, W))
        return outd, outi

    _, (tds, tis, flats) = jax.lax.scan(step, None, block_starts)
    flat = flats.reshape(-1)
    outd = outd.at[flat].set(tds.reshape(-1, kt), mode="drop")
    outi = outi.at[flat].set(tis.reshape(-1, kt), mode="drop")
    return outd, outi
