"""Candidate refinement (exact re-ranking).

Reference: raft/neighbors/refine.cuh:105 ``refine`` — given approximate
candidate neighbors (e.g. from IVF-PQ or CAGRA's graph build), recompute exact
distances to the candidates and keep the best k (detail/refine.cuh; the host
path ``refine_host`` is what CAGRA's build uses).

TPU design: one gather of the candidate vectors (q, n_cand, d) + a batched
distance einsum + top-k — entirely fused by XLA; invalid candidate slots
(id < 0, the reference's out-of-list marker) are masked to +inf.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu.integrity import boundary as _boundary
from raft_tpu import observability as obs
from raft_tpu.distance.types import DistanceType
from raft_tpu.matrix.select_k import select_k
from raft_tpu.utils.precision import get_matmul_precision
from raft_tpu.core.outputs import auto_convert_output


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _refine_impl(dataset, queries, candidates, k, metric):
    nq, n_cand = candidates.shape
    valid = candidates >= 0
    safe = jnp.where(valid, candidates, 0)
    cand_vecs = dataset[safe]                       # (q, n_cand, d)
    qf = queries.astype(jnp.float32)
    cf = cand_vecs.astype(jnp.float32)

    if metric == DistanceType.InnerProduct:
        ip = jnp.einsum("qd,qcd->qc", qf, cf,
                        precision=get_matmul_precision())
        d = jnp.where(valid, ip, -jnp.inf)
        vals, pos = jax.lax.top_k(d, k)
    else:
        # squared L2 (sqrt applied for the sqrt metrics below)
        diff2 = jnp.sum(cf * cf, axis=-1) - 2.0 * jnp.einsum(
            "qd,qcd->qc", qf, cf, precision=get_matmul_precision())
        d = jnp.maximum(diff2 + jnp.sum(qf * qf, axis=-1, keepdims=True), 0.0)
        if metric in (DistanceType.L2SqrtExpanded,
                      DistanceType.L2SqrtUnexpanded):
            d = jnp.sqrt(d)
        d = jnp.where(valid, d, jnp.inf)
        vals, pos = select_k(d, k, select_min=True)
    idx = jnp.take_along_axis(candidates, pos, axis=1)
    return vals, idx


@auto_convert_output
def refine(
    res,
    dataset,
    queries,
    candidates,
    k: int,
    *,
    metric: int = DistanceType.L2Unexpanded,
) -> Tuple[jax.Array, jax.Array]:
    """Exact re-rank of candidate ids; returns (distances, indices) (q, k).

    Reference: neighbors/refine.cuh:105 (metric limited to L2/IP families
    there too).  ``candidates`` is (q, n_candidates) int ids into ``dataset``;
    negative ids are treated as empty slots.
    """
    with named_range("refine"):
        dataset = ensure_array(dataset, "dataset")
        queries = ensure_array(queries, "queries")
        candidates = ensure_array(candidates, "candidates")
        expects(candidates.ndim == 2
                and candidates.shape[0] == queries.shape[0],
                "refine: (q, n_candidates) ids required")
        expects(k <= candidates.shape[1],
                "refine: k exceeds candidate count")
        expects(metric in (DistanceType.L2Expanded,
                           DistanceType.L2SqrtExpanded,
                           DistanceType.L2Unexpanded,
                           DistanceType.L2SqrtUnexpanded,
                           DistanceType.InnerProduct),
                "refine: L2 / InnerProduct metrics only (as the reference)")
        queries, ok_rows = _boundary.check_matrix(
            queries, "queries", site="refine", dim=dataset.shape[1])
        with obs.stage("refine") as st:
            out = _refine_impl(dataset, queries, candidates, k, metric)
            st.fence(out)
        if ok_rows is not None:
            out = _boundary.mask_search_outputs(
                out[0], out[1], ok_rows,
                select_min=metric != DistanceType.InnerProduct)
        return out
