"""IVF-PQ: inverted-file index with product-quantized residuals — the
performance flagship (BASELINE.md north-star workload).

Reference: raft/neighbors/ivf_pq.cuh:224 ``build``, :266 ``extend``, :342
``search``; params/types ivf_pq_types.hpp:48 (index_params: pq_bits, pq_dim,
codebook_kind, force_random_rotation), :110 (search_params: n_probes,
lut_dtype, internal_distance_dtype), :264 (index).  Build internals:
detail/ivf_pq_build.cuh:337 ``train_per_subset``, :417 ``train_per_cluster``
(both via kmeans_balanced), :944 ``process_and_fill_codes_kernel``; search:
detail/ivf_pq_search.cuh:133 ``select_clusters``, :611
``compute_similarity_kernel`` (shared-memory LUT), :373
``postprocess_neighbors``; code packing detail/ivf_pq_codepacking.cuh.

TPU design:

- **codebook training** is a ``vmap`` of the balanced-k-means loop over the
  ``pq_dim`` subspaces — one compilation, all books trained in parallel on
  the MXU (the reference loops build_clusters per subspace);
- **encoding** is a single batched argmin over (n, pq_dim, book) distances —
  the ``process_and_fill_codes`` analogue is the same scatter used by
  IVF-Flat's list packer (static-shape padded lists, SURVEY.md §7);
- **search** scans probed lists like IVF-Flat, but each step builds the
  per-(query, probe) look-up table on the fly — an einsum against the
  codebooks (MXU) — then accumulates code distances with a
  ``take_along_axis`` gather over the book axis (VPU).  The LUT never leaves
  VMEM-scale shapes: (q_tile, pq_dim, 2^pq_bits).  ``lut_dtype=bf16``
  halves LUT bandwidth, mirroring the reference's fp8/half LutT option
  (ivf_pq_search.cuh:70).
- the optional **random rotation** (force_random_rotation /
  dim-padding rotation in the reference) is a fixed orthonormal matrix from
  QR of a seeded normal draw, applied before subspace splitting.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import BinaryIO, Optional, Tuple
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_types import KMeansBalancedParams
from raft_tpu.core import serialize as ser
from raft_tpu.core.error import expects
from raft_tpu.core.interruptible import interruptible
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu import observability as obs
from raft_tpu.integrity import boundary as _boundary
from raft_tpu.integrity import canary as _canary
from raft_tpu.distance.types import DistanceType
from raft_tpu.filters import bitset as _fbits
from raft_tpu.matrix.select_k import select_k
from raft_tpu.neighbors import mutate as _mutate
from raft_tpu.neighbors.ivf_flat import (_append_lists_multi, _pack_lists,
                                         _round_up, _LIST_ALIGN)
from raft_tpu.utils.precision import get_matmul_precision
from raft_tpu.core.outputs import auto_convert_output


class CodebookKind:
    """Reference: ivf_pq_types.hpp ``codebook_gen`` enum."""

    PER_SUBSPACE = 0
    PER_CLUSTER = 1


@dataclasses.dataclass
class IndexParams:
    """Reference: ivf_pq_types.hpp:48 ``index_params``."""

    n_lists: int = 1024
    metric: int = DistanceType.L2Expanded
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    pq_bits: int = 8          # 4..8 supported in the reference
    pq_dim: int = 0           # 0 -> auto: dim/4 rounded (reference heuristic)
    codebook_kind: int = CodebookKind.PER_SUBSPACE
    force_random_rotation: bool = False
    add_data_on_build: bool = True
    # Build the bf16 reconstruction search cache ((n, rot_dim) extra HBM —
    # 2x the codes' footprint per byte of pq_dim*8/rot_dim compression).
    # Set False for datasets whose reconstructions would not fit HBM; search
    # then uses the memory-lean LUT formulation.
    cache_reconstructions: bool = True
    # Recall canaries (integrity.canary): > 0 samples that many sentinel
    # queries at build, stores their exact neighbors inside the index and
    # re-checks recall after load()/extend()/resume (floor below).
    canary_queries: int = 0
    canary_k: int = 10
    canary_floor: float = 0.5


@dataclasses.dataclass
class SearchParams:
    """Reference: ivf_pq_types.hpp:110 ``search_params``."""

    n_probes: int = 20
    # coarse probe ranking controls, inherited from IVF-Flat (ONE copy of
    # the rank arithmetic, ivf_flat._select_clusters): the approx_max_k
    # recall target, and an exact lax.top_k override.  The exact select is
    # also auto-chosen when n_probes is close to n_lists.
    coarse_recall_target: float = 0.95
    exact_coarse: bool = False
    # lut_dtype applies to the LUT formulation only (fp32 | bf16, the fp8
    # analogue); the reconstruction path stores bf16 residuals and always
    # accumulates fp32 (internal_distance_dtype's contract).
    lut_dtype: object = jnp.float32
    internal_distance_dtype: object = jnp.float32
    # None -> auto: scan the bf16 reconstruction cache when the index
    # carries one (the TPU fast path; ~identical recall, see
    # test_recon_path_matches_lut_path); False forces the LUT formulation.
    # Indexes built with IndexParams.cache_reconstructions=False carry no
    # cache and use the LUT path automatically.
    # DEPRECATED in favour of scan_mode (kept for compat: when set it
    # overrides scan_mode with "recon"/"lut").
    use_reconstruction: Optional[bool] = None
    # Which list-scan formulation serves the query batch:
    #   "recon"  — bf16 reconstruction cache (2 B/dim/row HBM traffic);
    #   "codes"  — compact-code Pallas kernel: bit-packed codes stream
    #              from HBM (~pq_bits/8 B/subspace/row, ~4x less than
    #              recon at the bench shape) and are decoded in-register
    #              against the VMEM-resident codebook table (the TPU
    #              analogue of the reference's shared-memory LUT scan,
    #              ivf_pq_search.cuh:611); falls back to "lut" off-TPU or
    #              for unsupported shapes (see pq_code_scan_pallas);
    #   "recon8" — int8-quantized recon cache with per-list scale
    #              (1 B/dim/row, in-register dequantization);
    #   "lut"    — the XLA take_along_axis LUT formulation (traceable,
    #              memory-lean; the AOT export path);
    #   "fused"  — the in-kernel top-k variants of "codes"/"recon": a
    #              per-query accumulator lives in VMEM across the whole
    #              scan grid, so candidates never reach HBM and the
    #              scatter + final-select extraction stage disappears
    #              (backed by the compact-code kernel when eligible,
    #              else the recon cache; falls back to the non-fused
    #              path off-TPU or for unsupported shapes, counted by
    #              the ivf_pq.search.fused_fallback counter);
    #   "auto"   — "recon" when the index carries the cache, else "codes"
    #              when the kernel supports the index's static config,
    #              else "lut" — UPGRADED to the fused kernel whenever
    #              the batch's shape supports it on TPU.
    scan_mode: str = "auto"
    # Per-(query, probe) candidates kept by the grouped scans before the
    # final merge (the kernel's kt).  0 -> k.  The grouped kernels are
    # extraction-bound (~3.3 us per kept candidate per group, flat in list
    # size — PERFORMANCE.md round 5), so at refine-heavy operating points
    # a small value (e.g. 4 with refine_ratio>=2) trades a little
    # pre-refine recall for a near-linear scan speedup.  The probe-order
    # LUT formulation (and therefore any off-TPU fallback to it) has no
    # per-pair keep-set and ignores this knob — the fallback errs toward
    # MORE candidates, never fewer.
    per_probe_topk: int = 0
    # Opt-in packed-key top-kt extraction inside the codes/recon8 kernels:
    # one cross-lane reduce per kept candidate instead of three, at the
    # cost of truncating ~log2(capacity) distance mantissa bits (~2^-13
    # relative at bench shapes; ordering-only effect, far below PQ noise).
    packed_extract: bool = False
    # Fused-scan merge window W: the fused kernels stage each grid
    # step's kt candidates into a VMEM ring and pay the full top-k merge
    # only every W-th step (~W x fewer merge passes; bit-identical
    # results — the merge is order-insensitive over the finite-sentinel
    # ring).  "auto" (or 0) picks the largest W the kernel's VMEM budget
    # admits via ops.vmem_budget; an explicit int >= 1 is honored as an
    # upper bound (1 = the round-7 per-step merge).  Also selects the
    # staged CAGRA-hop merge — see cagra.SearchParams.merge_window.
    merge_window: object = "auto"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """Reference: ivf_pq_types.hpp:264 ``index``.

    ``codebooks``: PER_SUBSPACE (pq_dim, book, pq_len);
                   PER_CLUSTER (n_lists, book, pq_len).
    ``list_codes``: (n_lists, capacity, W) uint8 **bit-packed** PQ codes,
    W = ceil(pq_dim*pq_bits/8) (reference: ivf_pq_codepacking.cuh; at
    pq_bits=8 this is one byte per sub-dim);
    ``rotation``: (dim, rot_dim) orthonormal (identity when not rotated).
    """

    centers: jax.Array
    codebooks: jax.Array
    list_codes: jax.Array
    list_indices: jax.Array
    list_sizes: jax.Array
    rotation: jax.Array
    metric: int = DistanceType.L2Expanded
    codebook_kind: int = CodebookKind.PER_SUBSPACE
    pq_bits: int = 8
    # Derived search-time cache: bf16 PQ reconstructions in list layout
    # (n_lists, capacity, rot_dim).  The codes remain the source of truth
    # (serialization stores codes only; deserialize re-decodes).  On TPU the
    # per-element LUT gather of the reference's compute_similarity_kernel
    # (ivf_pq_search.cuh:611) is VPU-gather-bound (~1e8 elem/s measured); an
    # MXU einsum over cached bf16 reconstructions computes the *identical*
    # quantized distance ||q_rot - recon||^2 at ~100x the throughput.  bf16
    # rounding is finer than the reference's own fp8 LUT option.
    list_recon: Optional[jax.Array] = None
    # Derived with list_recon: per-row squared norms (n_lists, capacity)
    # fp32.  Loop-invariant across searches; caching it keeps a full pass
    # over the recon cache out of every search call (it measurably fused
    # into the probe loop when computed in-call).
    list_recon_sq: Optional[jax.Array] = None
    # Derived search-time cache for scan_mode="codes": the bit-packed
    # codes re-laid out lane-major as (n_lists, Wi, capacity) int32 words
    # (pq_code_scan_pallas.pack_code_lanes) so the Pallas kernel streams
    # ~pq_dim*pq_bits/8 bytes/row, plus the per-row squared norms of the
    # bf16 reconstructions (n_lists, capacity) f32 the distance
    # decomposition needs.  Like list_recon these are derived from the
    # codes (never serialized) and attach lazily on first codes-mode
    # search.
    list_code_lanes: Optional[jax.Array] = None
    list_code_rsq: Optional[jax.Array] = None
    # Derived search-time cache for scan_mode="recon8": the recon cache
    # quantized to int8 with ONE f32 scale per list (lanes zero-padded to
    # a 128 multiple for the kernel), plus squared norms of the
    # DEQUANTIZED rows so kernel distances are self-consistent.
    list_recon_i8: Optional[jax.Array] = None
    list_recon_scale: Optional[jax.Array] = None
    list_recon_i8_sq: Optional[jax.Array] = None
    # explicit because list_codes is bit-packed (its trailing axis is the
    # packed byte width, not pq_dim); 0 -> equal to the code width (the
    # pq_bits=8 layout where packing is the identity)
    pq_dim_: int = 0
    # Recall-canary sentinel set (integrity.CanarySet) — host-side
    # metadata, deliberately NOT a pytree leaf (and not aux data either:
    # aux must stay hashable for jit caching), so it does not survive
    # jax transforms; build/extend/serialize carry it explicitly.
    canaries: Optional[object] = None
    # Mutation generation counter (see neighbors/mutate): host-side like
    # canaries — a leaf would be wrong and aux would force a retrace per
    # mutation.  extend/delete/compact stamp parent+1 on the new index.
    generation: int = 0
    # Calibrated group-capacity estimate (round 10): the measured
    # fraction of min(n_lists, P) lists a representative batch's probes
    # touch (see :func:`calibrate_group_capacity`).  0.0 = uncalibrated,
    # which dispatches the grouped scans at the exact-safe worst-case
    # capacity — zero host syncs, no overflow machinery.  Host-side like
    # generation; serialized (v4) through the index envelope.
    group_est: float = 0.0

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[1]

    @property
    def pq_dim(self) -> int:
        # derive from rotation/codebook shapes, NOT list_codes.shape[2]:
        # codes are bit-packed, so their trailing axis is the packed byte
        # width W != pq_dim whenever pq_bits < 8 — an Index constructed
        # directly with default pq_dim_=0 must still decode correctly
        return self.pq_dim_ or self.rotation.shape[1] // self.codebooks.shape[2]

    @property
    def code_width(self) -> int:
        """Packed bytes per vector in ``list_codes``."""
        return self.list_codes.shape[2]

    @property
    def pq_len(self) -> int:
        return self.codebooks.shape[2]

    @property
    def pq_book_size(self) -> int:
        return 1 << self.pq_bits

    @property
    def capacity(self) -> int:
        return self.list_codes.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_sizes))

    def tree_flatten(self):
        leaves = (self.centers, self.codebooks, self.list_codes,
                  self.list_indices, self.list_sizes, self.rotation,
                  self.list_recon, self.list_recon_sq,
                  self.list_code_lanes, self.list_code_rsq,
                  self.list_recon_i8, self.list_recon_scale,
                  self.list_recon_i8_sq)
        return leaves, (self.metric, self.codebook_kind, self.pq_bits,
                        self.pq_dim_)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves[:6], list_recon=leaves[6],
                   list_recon_sq=leaves[7], list_code_lanes=leaves[8],
                   list_code_rsq=leaves[9], list_recon_i8=leaves[10],
                   list_recon_scale=leaves[11], list_recon_i8_sq=leaves[12],
                   metric=aux[0], codebook_kind=aux[1], pq_bits=aux[2],
                   pq_dim_=aux[3])


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------

def _make_rotation(dim: int, rot_dim: int, random: bool, seed: int
                   ) -> jax.Array:
    """Orthonormal (dim, rot_dim) transform.  The reference composes
    dim-padding + optional random rotation (ivf_pq_build.cuh rotation matrix);
    identity-pad when not random."""
    if not random and dim == rot_dim:
        return jnp.eye(dim, dtype=jnp.float32)
    key = jax.random.key(seed)
    g = jax.random.normal(key, (dim, rot_dim), jnp.float32) if dim >= rot_dim \
        else jax.random.normal(key, (rot_dim, dim), jnp.float32).T
    q, _ = jnp.linalg.qr(jnp.pad(g, ((0, max(0, rot_dim - dim)), (0, 0))))
    return q[:dim, :rot_dim]


def _subspace_split(x: jax.Array, pq_dim: int) -> jax.Array:
    """(n, rot_dim) -> (n, pq_dim, pq_len)."""
    n, rd = x.shape
    return x.reshape(n, pq_dim, rd // pq_dim)


# ---------------------------------------------------------------------------
# bit-packed code storage (reference: ivf_pq_codepacking.cuh — codes are
# packed to the bit; at pq_bits=4 the index stores HALF the bytes of a
# one-byte-per-subdim layout, which directly caps database size per chip)
# ---------------------------------------------------------------------------

def packed_code_width(pq_dim: int, pq_bits: int) -> int:
    """Bytes per vector of bit-packed codes."""
    return -(-pq_dim * pq_bits // 8)


def _pack_codes(codes: jax.Array, pq_bits: int) -> jax.Array:
    """(..., pq_dim) uint8 codes (< 2^pq_bits) -> (..., W) uint8 packed
    LSB-first, W = ceil(pq_dim*pq_bits/8).  Identity at pq_bits=8."""
    if pq_bits == 8:
        return codes
    *lead, pq_dim = codes.shape
    total = pq_dim * pq_bits
    W = packed_code_width(pq_dim, pq_bits)
    c = codes.astype(jnp.int32)
    bit = jnp.arange(pq_bits, dtype=jnp.int32)
    bits = (c[..., None] >> bit) & 1                   # (..., pq_dim, bits)
    bits = bits.reshape(*lead, total)
    bits = jnp.pad(bits, [(0, 0)] * len(lead) + [(0, W * 8 - total)])
    bits = bits.reshape(*lead, W, 8)
    weights = jnp.int32(1) << jnp.arange(8, dtype=jnp.int32)
    return jnp.sum(bits * weights, axis=-1).astype(jnp.uint8)


def _unpack_codes(packed: jax.Array, pq_dim: int, pq_bits: int) -> jax.Array:
    """Inverse of :func:`_pack_codes`: (..., W) uint8 -> (..., pq_dim)
    uint8.  Each pq_bits-wide field spans at most two bytes; bits past
    the last byte are masked off, so the clipped high-byte read is safe."""
    if pq_bits == 8:
        return packed
    p = packed.astype(jnp.int32)
    W = p.shape[-1]
    bitpos = jnp.arange(pq_dim) * pq_bits
    b0 = bitpos // 8
    shift = bitpos % 8
    lo = jnp.take(p, b0, axis=-1)                      # (..., pq_dim)
    hi = jnp.take(p, jnp.minimum(b0 + 1, W - 1), axis=-1)
    mask = (1 << pq_bits) - 1
    return (((lo | (hi << 8)) >> shift) & mask).astype(jnp.uint8)


# codebook k-means needs ~book_size * a-few-hundred rows; more adds wall
# clock without moving the centroids
_BOOK_TRAIN_ROWS = 65_536


@functools.partial(jax.jit, static_argnames=("book_size", "n_iters"))
def _train_books_per_subspace(resid_sub, keys, book_size, n_iters):
    """Balanced k-means per subspace, sequential over subspaces.

    resid_sub: (pq_dim, n, pq_len) -> codebooks (pq_dim, book, pq_len).
    Reference: train_per_subset (ivf_pq_build.cuh:337) loops
    build_clusters per subspace.  ``lax.map`` (NOT vmap): a vmapped
    balanced loop materializes the (pq_dim, n, book) distance tile at
    once — 16 GB at SIFT-1M scale — while the sequential map peaks at one
    subspace's tile.  Rows are subsampled to _BOOK_TRAIN_ROWS (strided —
    the trainset is already caller-shuffled).
    """
    n = resid_sub.shape[1]
    if n > _BOOK_TRAIN_ROWS:
        stride = n // _BOOK_TRAIN_ROWS
        resid_sub = resid_sub[:, ::stride][:, :_BOOK_TRAIN_ROWS]

    def one(args):
        sub, key = args
        m = sub.shape[0]
        stride = max(m // book_size, 1)
        c0 = sub[::stride][:book_size]
        c0 = jnp.pad(c0, ((0, book_size - c0.shape[0]), (0, 0)), mode="edge")
        centers, _ = kmeans_balanced._balanced_loop(
            sub, c0, key, book_size, n_iters, DistanceType.L2Expanded)
        return centers

    return jax.lax.map(one, (resid_sub, keys))


def _encode(codebooks, resid, codebook_kind, labels=None):
    """PQ-encode residuals (n, pq_dim, pq_len) -> (n, pq_dim) uint8.

    Reference: process_and_fill_codes_kernel (ivf_pq_build.cuh:944) — the
    per-subspace argmin over the codebook.  Chunked over rows with
    ``lax.map``: the full (n, pq_dim, book) distance tensor is 32 GB at
    SIFT-1M scale.
    """
    n = resid.shape[0]
    chunk = 65_536

    def enc(args):
        r, lab = args
        if codebook_kind == CodebookKind.PER_SUBSPACE:
            # d[c, j, k] = ||r[c,j,:] - cb[j,k,:]||^2; argmin over k
            ip = jnp.einsum("njl,jkl->njk", r, codebooks,
                            precision=get_matmul_precision())
            cb_sq = jnp.sum(codebooks * codebooks, axis=-1)  # (j, k)
            d = cb_sq[None, :, :] - 2.0 * ip
        else:
            cb = codebooks[lab]                          # (c, book, pq_len)
            ip = jnp.einsum("njl,nkl->njk", r, cb,
                            precision=get_matmul_precision())
            cb_sq = jnp.sum(cb * cb, axis=-1)            # (c, k)
            d = cb_sq[:, None, :] - 2.0 * ip
        return jnp.argmin(d, axis=-1).astype(jnp.uint8)

    if labels is None:
        labels = jnp.zeros(n, jnp.int32)
    if n <= chunk:
        return enc((resid, labels))
    n_pad = -(-n // chunk) * chunk
    rp = jnp.pad(resid, ((0, n_pad - n), (0, 0), (0, 0)))
    lp = jnp.pad(labels, (0, n_pad - n))
    rp = rp.reshape(n_pad // chunk, chunk, *resid.shape[1:])
    lp = lp.reshape(n_pad // chunk, chunk)
    out = jax.lax.map(enc, (rp, lp))
    return out.reshape(n_pad, -1)[:n]


def build(res, params: IndexParams, dataset, *,
          checkpoint=None, resume: bool = False) -> Index:
    """Build an IVF-PQ index (reference: ivf_pq.cuh:224).

    ``checkpoint`` (a directory path or
    :class:`~raft_tpu.resilience.CheckpointManager`) persists each build
    stage's carry atomically right before its ``interruptible``
    sync point; with ``resume=True`` completed stages are loaded instead
    of recomputed.  Skipped stages still burn the same ``res.next_key()``
    draws they would have consumed, so a resumed build is bit-identical
    to an uninterrupted one.
    """
    from raft_tpu.resilience import as_manager
    ckpt = as_manager(checkpoint)
    with named_range("ivf_pq::build"), \
            obs.build_scope("ivf_pq.build") as rep:
        dataset = ensure_array(dataset, "dataset")
        expects(dataset.ndim == 2, "ivf_pq.build: 2-D dataset required")
        dataset, _ = _boundary.check_matrix(dataset, "dataset",
                                            site="ivf_pq.build",
                                            allow_empty=False)
        n, dim = dataset.shape
        expects(params.n_lists <= n, "ivf_pq.build: n_lists > n_rows")
        expects(4 <= params.pq_bits <= 8,
                "ivf_pq.build: pq_bits in [4, 8] (as the reference)")

        pq_dim = params.pq_dim or max(dim // 4, 1)
        rot_dim = _round_up(dim, pq_dim)
        rotation = _make_rotation(dim, rot_dim,
                                  params.force_random_rotation or
                                  rot_dim != dim, seed=7)

        # ---- coarse quantizer (rotated space) --------------------------
        with obs.stage("ivf_pq.build.kmeans") as st:
            n_train = max(params.n_lists,
                          int(n * params.kmeans_trainset_fraction))
            if n_train < n:
                sel = jax.random.choice(res.next_key(), n, (n_train,),
                                        replace=False)
                trainset = dataset[sel]
            else:
                trainset = dataset
            train_rot = trainset.astype(jnp.float32) @ rotation
            bal = KMeansBalancedParams(n_iters=params.kmeans_n_iters)
            if resume and ckpt is not None and ckpt.has("kmeans"):
                # skip the fit but burn its key draw: the downstream key
                # stream must match an uninterrupted build bit-for-bit
                res.next_key()
                centers = jnp.asarray(ckpt.load("kmeans")["centers"])
            else:
                centers = kmeans_balanced.fit(res, bal, train_rot,
                                              params.n_lists)
                if ckpt is not None:
                    ckpt.save("kmeans", {"centers": np.asarray(centers)})
            # cancellation point: the stage above is durable before a
            # pending cancel() can raise
            interruptible.synchronize(centers)
            st.fence(centers)

        # ---- codebooks over residuals ----------------------------------
        with obs.stage("ivf_pq.build.codebooks") as st:
            book = 1 << params.pq_bits
            if resume and ckpt is not None and ckpt.has("codebooks"):
                # burn this stage's key draws (1 per-subspace, 2
                # per-cluster) for the same reason as above
                res.next_key()
                if params.codebook_kind != CodebookKind.PER_SUBSPACE:
                    res.next_key()
                codebooks = jnp.asarray(ckpt.load("codebooks")["codebooks"])
            else:
                labels_t = kmeans_balanced.predict(res, bal, train_rot,
                                                   centers)
                resid = _subspace_split(train_rot - centers[labels_t],
                                        pq_dim)
                if params.codebook_kind == CodebookKind.PER_SUBSPACE:
                    keys = jax.random.split(res.next_key(), pq_dim)
                    codebooks = _train_books_per_subspace(
                        jnp.transpose(resid, (1, 0, 2)), keys, book,
                        params.kmeans_n_iters)
                else:
                    # per-cluster: one book per coarse list over all its
                    # residual subvectors (train_per_cluster,
                    # ivf_pq_build.cuh:417)
                    flat = resid.reshape(-1, rot_dim // pq_dim)
                    flat_labels = jnp.repeat(labels_t, pq_dim)
                    codebooks = _train_books_per_cluster(
                        res, flat, flat_labels, params.n_lists, book,
                        params.kmeans_n_iters)
                if ckpt is not None:
                    ckpt.save("codebooks",
                              {"codebooks": np.asarray(codebooks)})
            interruptible.synchronize(codebooks)
            st.fence(codebooks)

        index = Index(
            centers=centers, codebooks=codebooks,
            list_codes=jnp.zeros(
                (params.n_lists, _LIST_ALIGN,
                 packed_code_width(pq_dim, params.pq_bits)), jnp.uint8),
            list_indices=jnp.full((params.n_lists, _LIST_ALIGN), -1,
                                  jnp.int32),
            list_sizes=jnp.zeros(params.n_lists, jnp.int32),
            rotation=rotation, metric=params.metric,
            codebook_kind=params.codebook_kind, pq_bits=params.pq_bits,
            pq_dim_=pq_dim)
        if params.add_data_on_build:
            index = extend(res, index, dataset,
                           jnp.arange(n, dtype=jnp.int32))
        if params.cache_reconstructions and index.list_recon is None:
            with obs.stage("ivf_pq.build.recon_cache") as st:
                index = _with_recon(res, index)
                st.fence(index.list_recon)
        if params.canary_queries > 0 and params.add_data_on_build:
            cs = _canary.make(res, dataset, metric=params.metric,
                              n_queries=params.canary_queries,
                              k=params.canary_k, floor=params.canary_floor)
            index.canaries = cs
            cs.build_recall = _canary.measure(res, index, cs)
            if resume:
                _canary.auto_check(res, index, site="resume")
        return rep.attach(index)


def _train_books_per_cluster(res, flat, flat_labels, n_lists, book, n_iters):
    """Per-cluster codebooks: k-means over each list's residual subvectors.

    XLA-friendly approximation of train_per_cluster (ivf_pq_build.cuh:417):
    rather than ragged per-cluster trainsets, run the vmapped balanced loop
    over per-cluster *resampled* fixed-size subsets.
    """
    n = flat.shape[0]
    per = max(book * 4, 256)
    # sample `per` member rows per cluster (with replacement via gumbel over
    # membership mask)
    key = res.next_key()
    g = jax.random.gumbel(key, (n_lists, n))
    member = (flat_labels[None, :] == jnp.arange(n_lists)[:, None])
    scores = jnp.where(member, g, -jnp.inf)
    vals, idx = jax.lax.top_k(scores, per)            # (n_lists, per)
    # clusters with < per members: top_k falls through to -inf scores whose
    # indices point at OTHER clusters' rows — replace them by cycling over
    # the cluster's valid members (top_k sorts valid picks first)
    n_valid = jnp.sum(vals > -jnp.inf, axis=1)        # (n_lists,)
    j_mod = jnp.arange(per)[None, :] % jnp.maximum(n_valid, 1)[:, None]
    idx = jnp.take_along_axis(idx, j_mod, axis=1)
    subsets = flat[idx]                               # (n_lists, per, len)
    keys = jax.random.split(res.next_key(), n_lists)

    def one(sub, k):
        stride = max(per // book, 1)
        c0 = sub[::stride][:book]
        c0 = jnp.pad(c0, ((0, book - c0.shape[0]), (0, 0)), mode="edge")
        centers, _ = kmeans_balanced._balanced_loop(
            sub, c0, k, book, n_iters, DistanceType.L2Expanded)
        return centers

    return jax.vmap(one)(subsets, keys)


def extend(res, index: Index, new_vectors, new_indices=None) -> Index:
    """Encode + add vectors (reference: ivf_pq.cuh:266 ``extend``)."""
    with named_range("ivf_pq::extend"):
        new_vectors = ensure_array(new_vectors, "new_vectors")
        new_vectors, _ = _boundary.check_matrix(
            new_vectors, "new_vectors", site="ivf_pq.extend", dim=index.dim)
        expects(new_vectors.ndim == 2 and new_vectors.shape[1] == index.dim,
                "ivf_pq.extend: dim mismatch")
        n_new = new_vectors.shape[0]
        if new_indices is None:
            new_indices = index.size + jnp.arange(n_new, dtype=jnp.int32)
        else:
            new_indices = ensure_array(new_indices, "new_indices")

        bal = KMeansBalancedParams()
        # chunk the rotate→assign→encode pipeline: at deep scale (10M+
        # rows) the full-width rotation + residual transients are
        # several copies of the dataset and OOM a single chip; per-chunk
        # the peak extra memory is O(chunk * rot_dim)
        chunk = 1 << 20
        # the decoded rows also feed the code-lane cache's row norms, so
        # a lean codes-mode index (lanes attached, no bf16 recon) still
        # gets coherent appended norms
        want_recon_rows = (index.list_recon is not None
                           or index.list_code_lanes is not None)
        with obs.stage("ivf_pq.extend.encode") as st:
            codes_parts, label_parts, recon_parts = [], [], []
            for s0 in range(0, n_new, chunk):
                v = new_vectors[s0:s0 + chunk]
                rot_c = v.astype(jnp.float32) @ index.rotation
                lab_c = kmeans_balanced.predict(res, bal, rot_c,
                                                index.centers)
                resid_c = _subspace_split(rot_c - index.centers[lab_c],
                                          index.pq_dim)
                cu = _encode(index.codebooks, resid_c, index.codebook_kind,
                             lab_c)
                if want_recon_rows:
                    recon_parts.append(_decode_rows(index.codebooks, cu,
                                                    lab_c,
                                                    index.codebook_kind))
                codes_parts.append(_pack_codes(cu, index.pq_bits))
                label_parts.append(lab_c)
            codes = (jnp.concatenate(codes_parts)
                     if len(codes_parts) > 1 else codes_parts[0])
            labels = (jnp.concatenate(label_parts)
                      if len(label_parts) > 1 else label_parts[0])
            recon_rows = None
            if want_recon_rows:
                recon_rows = (jnp.concatenate(recon_parts)
                              if len(recon_parts) > 1 else recon_parts[0])
            st.fence(codes, labels)

        new_counts = jax.ops.segment_sum(
            jnp.ones(n_new, jnp.int32), labels,
            num_segments=index.n_lists)
        needed = index.list_sizes + new_counts
        # fast path: headroom in every touched list — O(n_new) scatter-append
        # (one (n_lists,)-reduction host sync decides; see ivf_flat.extend)
        if int(jnp.max(needed)) <= index.capacity:
            with obs.stage("ivf_pq.extend.pack") as st:
                rsq_rows = (jnp.sum(recon_rows.astype(jnp.float32) ** 2,
                                    axis=-1)
                            if recon_rows is not None else None)
                # every derived cache appends at the same slots in the
                # same scatter pass — `at[name]` records each buffer's
                # position in the returned tuple
                bufs, rows, at = [index.list_codes], [codes], {}

                def _add(name, buf, row):
                    at[name] = len(bufs)
                    bufs.append(buf)
                    rows.append(row)

                if index.list_recon is not None:
                    _add("recon", index.list_recon, recon_rows)
                    if index.list_recon_sq is not None:
                        _add("recon_sq", index.list_recon_sq, rsq_rows)
                # the code-lane cache's row norms may alias list_recon_sq
                # (see _with_code_lanes) — append the shared buffer once
                rsq_shared = (index.list_code_lanes is not None
                              and index.list_code_rsq is not None
                              and index.list_code_rsq is index.list_recon_sq
                              and "recon_sq" in at)
                lane_bufs, lane_rows = (), ()
                if index.list_code_lanes is not None:
                    from raft_tpu.ops import pq_code_scan_pallas as pcs
                    lane_bufs = (index.list_code_lanes,)
                    lane_rows = (pcs.pack_row_lanes(codes),)
                    if index.list_code_rsq is not None and not rsq_shared:
                        _add("code_rsq", index.list_code_rsq, rsq_rows)
                new_bufs, new_lanes, list_idx, sizes = _append_lists_multi(
                    tuple(bufs), tuple(rows), index.list_indices,
                    index.list_sizes, labels, new_indices,
                    lane_bufs, lane_rows)
                st.fence(new_bufs)
            out = Index(
                centers=index.centers, codebooks=index.codebooks,
                list_codes=new_bufs[0], list_indices=list_idx,
                list_sizes=sizes, rotation=index.rotation,
                metric=index.metric, codebook_kind=index.codebook_kind,
                pq_bits=index.pq_bits, pq_dim_=index.pq_dim)
            if index.list_recon is not None:
                out.list_recon = new_bufs[at["recon"]]
                out.list_recon_sq = (new_bufs[at["recon_sq"]]
                                     if "recon_sq" in at
                                     else _recon_sq(out.list_recon))
            if index.list_code_lanes is not None:
                out.list_code_lanes = new_lanes[0]
                if rsq_shared:
                    out.list_code_rsq = out.list_recon_sq
                elif "code_rsq" in at:
                    out.list_code_rsq = new_bufs[at["code_rsq"]]
            # int8 recon caches are NOT carried: their per-list symmetric
            # scale was chosen from the pre-extend residual range, so
            # appended rows could overflow it — the next recon8 search
            # re-quantizes lazily (integrity.verify flags a stale copy)
            _mutate.next_generation(index, out)
            if index.canaries is not None:
                out.canaries = index.canaries
                _canary.auto_check(res, out, site="extend")
            return out

        # flatten existing + concat + repack (same dance as ivf_flat.extend)
        old_valid = (index.list_indices >= 0).ravel()
        old_labels = jnp.repeat(jnp.arange(index.n_lists, dtype=jnp.int32),
                                index.capacity)[old_valid]
        old_codes = index.list_codes.reshape(-1, index.code_width)[old_valid]
        old_ids = index.list_indices.ravel()[old_valid]

        all_codes = jnp.concatenate([old_codes, codes])
        all_ids = jnp.concatenate([old_ids, new_indices.astype(jnp.int32)])
        all_labels = jnp.concatenate([old_labels, labels])

        # +1 before rounding: never leave the fullest list brim-full after
        # a repack (see ivf_flat.extend) — a build lands here via the empty
        # index, so this also guarantees every fresh build has append room
        capacity = _round_up(max(int(jnp.max(needed)) + 1, _LIST_ALIGN),
                             _LIST_ALIGN)
        with obs.stage("ivf_pq.extend.pack") as st:
            list_codes, list_idx, sizes = _pack_lists(
                all_codes, all_labels, all_ids, index.n_lists, capacity)
            st.fence(list_codes)

        out = Index(
            centers=index.centers, codebooks=index.codebooks,
            list_codes=list_codes, list_indices=list_idx,
            list_sizes=sizes, rotation=index.rotation,
            metric=index.metric, codebook_kind=index.codebook_kind,
            pq_bits=index.pq_bits, pq_dim_=index.pq_dim)
        # the cache is attached only when the source index carries one (or
        # at build time per IndexParams.cache_reconstructions) — a lean
        # index never materializes (n, rot_dim) reconstructions
        if index.list_recon is not None:
            out = _with_recon(res, out)
        # repack moved every row, so scan caches rebuild from the fresh
        # codes rather than arriving cold at the next search
        if index.list_code_lanes is not None:
            out = _with_code_lanes(out)
        if index.list_recon_i8 is not None:
            out = _with_recon8(out)
        _mutate.next_generation(index, out)
        if index.canaries is not None:
            out.canaries = index.canaries
            _canary.auto_check(res, out, site="extend")
        return out


def delete(res, index: Index, ids) -> Index:
    """Tombstone-delete rows by source id (the online mutation layer —
    see :mod:`raft_tpu.neighbors.mutate` for the encoding).

    Rewrites the matching ``list_indices`` slots to tombstones; every
    scan formulation (recon/codes/recon8/lut and the fused Pallas
    kernels) already masks negative ids to the worst-distance sentinel,
    so deleted rows vanish from results immediately without touching
    the codes, any derived cache, or fused-path eligibility.  Storage
    is reclaimed by :func:`compact`.  Ids not present match nothing.

    Returns a NEW index — the next generation — sharing every array
    except ``list_indices`` with its parent; readers pinned on the
    parent are unaffected.
    """
    with named_range("ivf_pq::delete"):
        ids = ensure_array(ids, "ids")
        expects(ids.ndim == 1, "ivf_pq.delete: 1-D ids required")
        new_li, _ = _mutate.tombstone(index.list_indices, ids)
        out = Index(
            centers=index.centers, codebooks=index.codebooks,
            list_codes=index.list_codes, list_indices=new_li,
            list_sizes=index.list_sizes, rotation=index.rotation,
            metric=index.metric, codebook_kind=index.codebook_kind,
            pq_bits=index.pq_bits, pq_dim_=index.pq_dim,
            list_recon=index.list_recon,
            list_recon_sq=index.list_recon_sq,
            list_code_lanes=index.list_code_lanes,
            list_code_rsq=index.list_code_rsq,
            list_recon_i8=index.list_recon_i8,
            list_recon_scale=index.list_recon_scale,
            list_recon_i8_sq=index.list_recon_i8_sq)
        out.canaries = index.canaries
        _mutate.next_generation(index, out)
        if index.canaries is not None:
            _canary.auto_check(res, out, site="delete")
        return out


def upsert(res, index: Index, ids, vectors) -> Index:
    """Replace-or-insert rows under explicit source ids: tombstone any
    existing rows with these ids, then encode + append ``vectors`` under
    the same ids — one logical mutation, ONE generation bump (the churn
    loop ``upsert -> upsert`` advances the counter like a single
    ``extend``, so generation-keyed caches see one swap per batch, not
    two).  Ids not present simply insert; duplicate live ids are all
    tombstoned first, so each id resolves to exactly one live row."""
    with named_range("ivf_pq::upsert"):
        ids = ensure_array(ids, "ids")
        vectors = ensure_array(vectors, "vectors")
        expects(ids.ndim == 1 and ids.shape[0] == vectors.shape[0],
                "ivf_pq.upsert: ids must be 1-D, one per vector")
        parent_gen = _mutate.generation(index)
        out = extend(res, delete(res, index, ids), vectors,
                     new_indices=ids)
        out.generation = parent_gen + 1
        if obs.enabled():
            obs.registry().counter("ivf_pq.upserts").inc()
        return out


def compact(res, index: Index) -> Index:
    """Reclaim tombstoned slots: stable-partition each list's live rows
    to the front, drop every tombstone, shrink the shared capacity to
    fit the fullest surviving list, and rebuild whichever derived scan
    caches the parent carried from the fresh codes (compaction moves
    rows, so the caches cannot be permuted in place safely at 3
    different layouts).  Returns a new generation sharing
    ``centers``/``codebooks``/``rotation`` with its parent."""
    with named_range("ivf_pq::compact"):
        order, sizes = _mutate.compaction_order(index.list_indices)
        max_size = int(jnp.max(sizes)) if index.n_lists else 0
        capacity = _round_up(max(max_size + 1, _LIST_ALIGN), _LIST_ALIGN)
        capacity = min(capacity, max(index.capacity, _LIST_ALIGN))

        li = jnp.take_along_axis(index.list_indices, order,
                                 axis=1)[:, :capacity]
        codes = jnp.take_along_axis(index.list_codes, order[:, :, None],
                                    axis=1)[:, :capacity]
        live = (jnp.arange(capacity, dtype=jnp.int32)[None, :]
                < sizes[:, None])
        li = jnp.where(live, li, -1)
        codes = jnp.where(live[:, :, None], codes, 0)

        out = Index(
            centers=index.centers, codebooks=index.codebooks,
            list_codes=codes, list_indices=li, list_sizes=sizes,
            rotation=index.rotation, metric=index.metric,
            codebook_kind=index.codebook_kind, pq_bits=index.pq_bits,
            pq_dim_=index.pq_dim)
        if index.list_recon is not None:
            out = _with_recon(res, out)
        if index.list_code_lanes is not None:
            out = _with_code_lanes(out)
        if index.list_recon_i8 is not None:
            out = _with_recon8(out)
        out.canaries = index.canaries
        _mutate.next_generation(index, out)
        if index.canaries is not None:
            _canary.auto_check(res, out, site="compact")
        return out


# ---------------------------------------------------------------------------
# reconstruction cache (TPU-native replacement for the smem LUT scan)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("codebook_kind", "pq_dim",
                                             "pq_bits"))
def _decode_lists(centers, codebooks, list_codes, codebook_kind, pq_dim,
                  pq_bits):
    """Decode every list's PQ codes to bf16 RESIDUAL reconstructions
    (n_lists, capacity, rot_dim) = concat_j codebook_j[code_j].

    Residuals (not absolute vectors) keep magnitudes small so bf16 rounding
    stays small relative to the distances — the absolute form suffers
    catastrophic cancellation when ||x||^2 >> d.  One-time cost per
    build/extend; the per-element codebook gather runs once here instead of
    once per query-probe in the reference's compute_similarity LUT loop
    (ivf_pq_search.cuh:611).
    """
    del centers  # residual space: centers fold in at search time, in fp32
    L, cap, W = list_codes.shape
    pq_len = codebooks.shape[-1]
    mask = (1 << pq_bits) - 1

    def code_at(j):
        """Unpack subspace j's codes only — a full upfront unpack is an
        (L, cap, pq_dim) int32 transient, 4x the packed bytes (2.5 GB at
        deep scale); per-step it is one (L, cap) slice."""
        bitpos = j * pq_bits
        b0 = bitpos // 8
        shift = bitpos % 8
        lo = jnp.take(list_codes, b0, axis=-1).astype(jnp.int32)
        hi = jnp.take(list_codes, jnp.minimum(b0 + 1, W - 1),
                      axis=-1).astype(jnp.int32)
        return ((lo | (hi << 8)) >> shift) & mask

    # One subspace at a time via scan + dynamic_update_slice: a single
    # (L, cap, pq_dim, pq_len) gather output gets its pq_len axis padded to
    # 128 lanes by TPU tiling — a 32x HBM blowup (OOM at realistic sizes).
    # The per-step (L, cap, pq_len) transient keeps peak memory at ~2x the
    # final (L, cap, rot_dim) cache.
    def step(acc, j):
        cj = code_at(j)                                  # (L, cap) int32
        if codebook_kind == CodebookKind.PER_SUBSPACE:
            part = codebooks[j][cj]                      # (L, cap, len)
        else:
            part = codebooks[jnp.arange(L)[:, None], cj]
        return jax.lax.dynamic_update_slice(
            acc, part.astype(jnp.bfloat16), (0, 0, j * pq_len)), None

    acc0 = jnp.zeros((L, cap, pq_dim * pq_len), jnp.bfloat16)
    acc, _ = jax.lax.scan(step, acc0, jnp.arange(pq_dim))
    return acc


@functools.partial(jax.jit, static_argnames=("codebook_kind",))
def _decode_rows(codebooks, codes, labels, codebook_kind):
    """Decode (n, pq_dim) codes to bf16 residual reconstructions
    (n, rot_dim) — the row-wise twin of :func:`_decode_lists`, used by the
    extend fast path to update the cache without re-decoding the index."""
    n, pq_dim = codes.shape
    pq_len = codebooks.shape[-1]
    ci = codes.astype(jnp.int32)

    def step(acc, j):
        if codebook_kind == CodebookKind.PER_SUBSPACE:
            part = codebooks[j][ci[:, j]]                # (n, len)
        else:
            part = codebooks[labels, ci[:, j]]
        return jax.lax.dynamic_update_slice(
            acc, part.astype(jnp.bfloat16), (0, j * pq_len)), None

    acc0 = jnp.zeros((n, pq_dim * pq_len), jnp.bfloat16)
    acc, _ = jax.lax.scan(step, acc0, jnp.arange(pq_dim))
    return acc


@jax.jit
def _recon_sq(list_recon):
    return jnp.sum(list_recon.astype(jnp.float32) ** 2, axis=-1)


def _with_recon(res, index: Index) -> Index:
    """Attach the derived reconstruction cache (+ squared norms)."""
    index.list_recon = _decode_lists(index.centers, index.codebooks,
                                     index.list_codes, index.codebook_kind,
                                     index.pq_dim, index.pq_bits)
    index.list_recon_sq = _recon_sq(index.list_recon)
    return index


@functools.partial(jax.jit, static_argnames=("pq_dim", "pq_bits"))
def _rsq_from_codes(codebooks, list_codes, pq_dim, pq_bits):
    """Per-row ||recon||^2 (n_lists, cap) f32 straight from the packed
    codes — Σ_j ||cb_bf16[j, code_j]||^2.  Subspaces occupy disjoint
    coordinates of the concatenated reconstruction, so the per-subspace
    norms sum exactly; squaring the *bf16-rounded* codebook keeps the
    value identical to _recon_sq(list_recon) without materializing the
    (n_lists, cap, rot_dim) cache (per-subspace codebooks only)."""
    L, cap, W = list_codes.shape
    mask = (1 << pq_bits) - 1
    cb_sq = jnp.sum(
        codebooks.astype(jnp.bfloat16).astype(jnp.float32) ** 2,
        axis=-1)                                         # (pq_dim, book)

    def step(acc, j):
        bitpos = j * pq_bits
        b0 = bitpos // 8
        shift = bitpos % 8
        lo = jnp.take(list_codes, b0, axis=-1).astype(jnp.int32)
        hi = jnp.take(list_codes, jnp.minimum(b0 + 1, W - 1),
                      axis=-1).astype(jnp.int32)
        cj = ((lo | (hi << 8)) >> shift) & mask          # (L, cap)
        return acc + cb_sq[j][cj], None

    acc, _ = jax.lax.scan(step, jnp.zeros((L, cap), jnp.float32),
                          jnp.arange(pq_dim))
    return acc


def _with_code_lanes(index: Index) -> Index:
    """Attach the lane-major packed-code cache for the compact-code
    kernel (plus the row norms its distance decomposition needs)."""
    from raft_tpu.ops import pq_code_scan_pallas as pcs
    index.list_code_lanes = pcs.pack_code_lanes(index.list_codes)
    if index.list_recon_sq is not None:
        index.list_code_rsq = index.list_recon_sq
    else:
        index.list_code_rsq = _rsq_from_codes(
            index.codebooks, index.list_codes, index.pq_dim, index.pq_bits)
    return index


@functools.partial(jax.jit, static_argnames=("rot_pad",))
def _quantize_recon(list_recon, rot_pad):
    """bf16 recon cache -> (int8 codes, per-list f32 scale, dequantized
    row norms).  Residual magnitudes cluster within a list, so one
    symmetric scale per list (max|recon|/127) keeps quantization error
    ~1/256 of the list's residual range — well under PQ noise (measured:
    recall moves <0.3% at bench shapes, PERFORMANCE.md round 6)."""
    r = list_recon.astype(jnp.float32)                   # (L, cap, rot)
    L, cap, rot = r.shape
    maxabs = jnp.max(jnp.abs(r), axis=(1, 2))            # (L,)
    scale = jnp.where(maxabs > 0, maxabs / 127.0, 1.0)
    q = jnp.clip(jnp.round(r / scale[:, None, None]), -127, 127)
    rsq8 = scale[:, None] ** 2 * jnp.sum(q * q, axis=-1)  # (L, cap) f32
    qi = jnp.pad(q.astype(jnp.int8), ((0, 0), (0, 0), (0, rot_pad - rot)))
    return qi, scale, rsq8


def _with_recon8(index: Index) -> Index:
    """Attach the int8-quantized recon cache (derives the bf16 recon on
    the fly when the index carries none — only the int8 copy is kept)."""
    recon = index.list_recon
    if recon is None:
        recon = _decode_lists(index.centers, index.codebooks,
                              index.list_codes, index.codebook_kind,
                              index.pq_dim, index.pq_bits)
    rot_pad = _round_up(index.rot_dim, 128)
    qi, scale, rsq8 = _quantize_recon(recon, rot_pad)
    index.list_recon_i8 = qi
    index.list_recon_scale = scale
    index.list_recon_i8_sq = rsq8
    return index


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "metric"))
def _search_impl_recon(centers, list_recon, list_indices, rotation, queries,
                       k, n_probes, metric, probes=None, list_recon_sq=None,
                       filter_words=None):
    """MXU scan over cached bf16 reconstructions — same quantized distance
    as the LUT path (||q_rot - recon||^2), structured like the IVF-Flat
    interleaved scan instead of the GPU's shared-memory LUT kernel.
    ``probes``/``list_recon_sq`` are accepted precomputed (the public
    search paths already have them); both are derived here when absent."""
    nq = queries.shape[0]
    qrot = (queries.astype(jnp.float32) @ rotation)
    cf = centers.astype(jnp.float32)
    ip_metric = metric == DistanceType.InnerProduct

    q_dot_c = jax.lax.dot_general(qrot, cf, (((1,), (1,)), ((), ())),
                                  precision=get_matmul_precision(),
                                  preferred_element_type=jnp.float32)
    if probes is None:
        probes = _select_clusters(centers, rotation, queries, n_probes,
                                  metric)

    worst = -jnp.inf if ip_metric else jnp.inf
    cap = list_recon.shape[1]
    # loop-invariant: per-row squared norms of the residual reconstructions
    rec_sq = (list_recon_sq if list_recon_sq is not None
              else jnp.sum(list_recon.astype(jnp.float32) ** 2, axis=-1))

    def probe_distances(p):
        """(q, cap) quantized distances + ids for probe rank p."""
        lists = probes[:, p]                         # (q,)
        data = list_recon[lists]                     # (q, cap, rot) bf16
        ids = list_indices[lists]                    # (q, cap)
        if ip_metric:
            # q.x = q.center_l + q.dec_resid
            qb = qrot.astype(jnp.bfloat16)
            ip = jnp.einsum("qd,qcd->qc", qb, data,
                            preferred_element_type=jnp.float32)
            d = ip + jnp.take_along_axis(q_dot_c, lists[:, None], axis=1)
        else:
            # residual space: ||resid_q - dec_resid||^2 — small magnitudes,
            # so the bf16 MXU pass loses no meaningful precision
            sub = qrot - cf[lists]                   # (q, rot) fp32
            ip = jnp.einsum("qd,qcd->qc", sub.astype(jnp.bfloat16), data,
                            preferred_element_type=jnp.float32)
            d = jnp.maximum(jnp.sum(sub * sub, axis=1)[:, None]
                            + rec_sq[lists] - 2.0 * ip, 0.0)
        d = jnp.where(ids >= 0, d, worst)
        if filter_words is not None:
            # admission fold through the same seam as tombstones: a
            # rejected row is worst BEFORE the per-probe top-kt, so the
            # select never spends a slot on it
            adm = _fbits.query_bits(filter_words, jnp.arange(nq), ids)
            d = jnp.where(adm > 0, d, worst)
        return d, ids

    # Hierarchical select (exact): every probe keeps its local top-k inside
    # the scan — any global top-k candidate is necessarily in its own
    # probe's top-k — and ONE final select runs over the (n_probes * k)
    # survivors.  This beats both per-probe merge chains (n_probes running
    # merges) and a single select over all n_probes*cap candidates (a
    # 40k-wide sort dominated the trace at 128 probes): the in-loop top_k
    # is over cap-wide rows and the final sort is k/cap times narrower.
    kt = min(k, cap)

    def acc_step(carry, p):
        alld, alli = carry
        d, ids = probe_distances(p)
        td, ti = select_k(d, kt, in_idx=ids, select_min=not ip_metric)
        alld = jax.lax.dynamic_update_slice(alld, td, (0, p * kt))
        alli = jax.lax.dynamic_update_slice(alli, ti, (0, p * kt))
        return (alld, alli), None

    alld = jnp.full((nq, n_probes * kt), worst, jnp.float32)
    alli = jnp.full((nq, n_probes * kt), -1, jnp.int32)
    (alld, alli), _ = jax.lax.scan(acc_step, (alld, alli),
                                   jnp.arange(n_probes))
    from raft_tpu.neighbors import grouped
    return grouped.finalize_topk(
        alld, alli, nq, k, not ip_metric,
        metric in (DistanceType.L2SqrtExpanded,
                   DistanceType.L2SqrtUnexpanded), select_k)


@functools.partial(jax.jit, static_argnames=("n_probes", "metric",
                                             "recall_target", "exact"))
def _select_clusters(centers, rotation, queries, n_probes, metric,
                     recall_target=0.95, exact=False):
    """Coarse top-``n_probes`` ranking (ivf_pq_search.cuh:133
    ``select_clusters``): rotate queries, then the IVF-Flat ranking —
    ONE copy of the rank arithmetic serves both index types."""
    from raft_tpu.neighbors import ivf_flat as _flat

    qrot = queries.astype(jnp.float32) @ rotation
    return _flat._select_clusters(centers, qrot, n_probes, metric,
                                  recall_target=recall_target, exact=exact)


@functools.partial(jax.jit, static_argnames=("k", "metric", "n_groups",
                                             "block", "use_pallas",
                                             "pallas_interpret", "kt",
                                             "merge_window"))
def _search_impl_recon_grouped(centers, list_recon, list_recon_sq,
                               list_indices, rotation, queries, probes, k,
                               metric, n_groups, block, use_pallas=False,
                               pallas_interpret=False, kt=0,
                               merge_window=0, filter_words=None):
    """List-centric recon scan over fixed-size pair groups.

    See :mod:`raft_tpu.neighbors.grouped` for the design (and the measured
    failure of the earlier one-bucket-per-list variant).  Each group is
    GROUP (query, probe) pairs of ONE list: the (B, GROUP, rot) query tile
    against the (B, cap, rot) list tile is a full-width batched MXU GEMM,
    each list's data is read ~once, and padding is bounded regardless of
    probe-popularity skew.  Same quantized distance as the probe-order
    path (differences are bf16-accumulation-order level; measured top-k
    overlap >99%); only the iteration order changes.
    """
    from raft_tpu.neighbors import grouped

    nq, n_probes = probes.shape
    P = nq * n_probes
    n_lists, cap, rot = list_recon.shape
    ip_metric = metric == DistanceType.InnerProduct
    worst = -jnp.inf if ip_metric else jnp.inf

    qrot = queries.astype(jnp.float32) @ rotation
    cf = centers.astype(jnp.float32)

    group_list, slot_pairs = grouped.build_groups(probes, n_lists, n_groups)
    # per-(slot, candidate) admission words in list-slot order — shared
    # by the Pallas kernel (streamed through VMEM) and derived once here
    adm_words = None
    if filter_words is not None:
        adm_words = _fbits.group_admission_words(
            filter_words, group_list, slot_pairs, list_indices, n_probes, P)

    # kt < k (SearchParams.per_probe_topk) narrows the per-pair keep-set:
    # the extraction-bound kernel speeds up near-linearly, at the cost of
    # candidates a single probe contributed beyond rank kt
    kt = min(kt or k, cap)
    if use_pallas:
        from raft_tpu.ops import pq_group_scan_pallas as pqp

        if pqp.supported(not ip_metric, cap, rot, kt, nq):
            # fused query-gather + MXU-distance + in-VMEM top-kt + id
            # mapping: neither the distance matrix nor the gathered query
            # residuals ever reach HBM (see the kernel module docstring)
            vals, ti = pqp.grouped_l2_scan(
                group_list, slot_pairs, qrot, cf, list_recon,
                list_recon_sq, list_indices, kt, n_probes,
                interpret=pallas_interpret, adm_words=adm_words)
            outd, outi = grouped.scatter_packed(vals, ti, slot_pairs, P,
                                                not ip_metric)
            return grouped.finalize_topk(
                outd, outi, nq, k, not ip_metric,
                metric in (DistanceType.L2SqrtExpanded,
                           DistanceType.L2SqrtUnexpanded), select_k)

    def distance_block(gl, slot):
        qid = jnp.where(slot < P, slot // n_probes, 0)
        qv = qrot[qid]                                   # (B, G, rot)
        data = list_recon[gl]                            # (B, cap, rot) bf16
        ids = list_indices[gl]
        cfb = cf[gl]                                     # (B, rot)
        if ip_metric:
            ip = jnp.einsum("bqr,bcr->bqc", qv.astype(jnp.bfloat16), data,
                            preferred_element_type=jnp.float32)
            qc = jnp.einsum("bqr,br->bq", qv, cfb,
                            precision=get_matmul_precision())
            d = ip + qc[:, :, None]
        else:
            rsq = list_recon_sq[gl]                      # (B, cap)
            sub = qv - cfb[:, None, :]                   # (B, G, rot)
            ip = jnp.einsum("bqr,bcr->bqc", sub.astype(jnp.bfloat16), data,
                            preferred_element_type=jnp.float32)
            d = jnp.maximum(jnp.sum(sub * sub, axis=-1)[:, :, None]
                            + rsq[:, None, :] - 2.0 * ip, 0.0)
        d = jnp.where(ids[:, None, :] >= 0, d, worst)
        if filter_words is not None:
            qid = jnp.where(slot < P, slot // n_probes, 0)
            adm = _fbits.query_bits(
                filter_words, qid,
                jnp.broadcast_to(ids[:, None, :], d.shape))
            d = jnp.where(adm > 0, d, worst)
        return d, ids

    outd, outi = grouped.scan_and_scatter(
        group_list, slot_pairs, P, cap, k, not ip_metric, block,
        select_k, distance_block, kt=kt, merge_window=merge_window)
    return grouped.finalize_topk(
        outd, outi, nq, k, not ip_metric,
        metric in (DistanceType.L2SqrtExpanded,
                   DistanceType.L2SqrtUnexpanded), select_k)


@functools.partial(jax.jit, static_argnames=("k", "kt", "metric", "n_groups",
                                             "pq_bits", "packed",
                                             "pallas_interpret"))
def _search_impl_codes_grouped(centers, codebooks, list_code_lanes,
                               list_code_rsq, list_indices, rotation,
                               queries, probes, k, kt, metric, n_groups,
                               pq_bits, packed=False,
                               pallas_interpret=False, filter_words=None):
    """Grouped COMPACT-CODE scan: the Pallas kernel streams lane-major
    packed codes (~pq_bits/8 bytes per subspace per row — the recon path
    reads 2*pq_len) and decodes them in-register against the
    VMEM-resident codebook table via per-subspace one-hot MXU
    contractions (pq_code_scan_pallas).  Distances equal the recon path's
    bit-for-bit: the kernel's bf16 codebook cast reproduces the bf16
    cache values.  L2-family metrics + per-subspace codebooks only —
    search() gates on pq_code_scan_pallas.supported_codes and falls back
    to the LUT formulation otherwise."""
    from raft_tpu.neighbors import grouped
    from raft_tpu.ops import pq_code_scan_pallas as pcs

    nq, n_probes = probes.shape
    P = nq * n_probes
    n_lists = centers.shape[0]
    cap = list_code_lanes.shape[2]
    qrot = queries.astype(jnp.float32) @ rotation
    cf = centers.astype(jnp.float32)

    group_list, slot_pairs = grouped.build_groups(probes, n_lists, n_groups)
    adm_words = None
    if filter_words is not None:
        adm_words = _fbits.group_admission_words(
            filter_words, group_list, slot_pairs, list_indices, n_probes, P)
    kt = min(kt or k, cap)
    vals, ti = pcs.grouped_code_scan(
        group_list, slot_pairs, qrot, cf, list_code_lanes, codebooks,
        list_code_rsq, list_indices, kt, n_probes, pq_bits, packed=packed,
        interpret=pallas_interpret, adm_words=adm_words)
    outd, outi = grouped.scatter_packed(vals, ti, slot_pairs, P, True)
    return grouped.finalize_topk(
        outd, outi, nq, k, True,
        metric in (DistanceType.L2SqrtExpanded,
                   DistanceType.L2SqrtUnexpanded), select_k)


@functools.partial(jax.jit, static_argnames=("k", "kt", "metric", "n_groups",
                                             "block", "use_pallas", "packed",
                                             "pallas_interpret"))
def _search_impl_recon8_grouped(centers, list_recon_i8, list_recon_scale,
                                list_recon_i8_sq, list_indices, rotation,
                                queries, probes, k, kt, metric, n_groups,
                                block, use_pallas=False, packed=False,
                                pallas_interpret=False, filter_words=None):
    """Grouped scan over the int8-quantized recon cache (1 byte/dim/row):
    the Pallas kernel dequantizes in-register with the per-list scale —
    ``d = ||sub||^2 + rsq8 - 2*scale*(sub . q8)``.  The XLA fallback
    computes the identical quantized distance for CPU / unsupported
    shapes.  L2-family metrics only (search() gates)."""
    from raft_tpu.neighbors import grouped

    nq, n_probes = probes.shape
    P = nq * n_probes
    n_lists = centers.shape[0]
    _, cap, rot_pad = list_recon_i8.shape
    rot = rotation.shape[1]

    qrot = queries.astype(jnp.float32) @ rotation
    cf = centers.astype(jnp.float32)

    group_list, slot_pairs = grouped.build_groups(probes, n_lists, n_groups)
    adm_words = None
    if filter_words is not None:
        adm_words = _fbits.group_admission_words(
            filter_words, group_list, slot_pairs, list_indices, n_probes, P)
    kt = min(kt or k, cap)
    if use_pallas:
        from raft_tpu.ops import pq_code_scan_pallas as pcs

        vals, ti = pcs.grouped_recon8_scan(
            group_list, slot_pairs, qrot, cf, list_recon_i8,
            list_recon_scale, list_recon_i8_sq, list_indices, kt, n_probes,
            packed=packed, interpret=pallas_interpret, adm_words=adm_words)
        outd, outi = grouped.scatter_packed(vals, ti, slot_pairs, P, True)
        return grouped.finalize_topk(
            outd, outi, nq, k, True,
            metric in (DistanceType.L2SqrtExpanded,
                       DistanceType.L2SqrtUnexpanded), select_k)

    # lane padding: the int8 cache's zero rot->rot_pad pad contributes
    # nothing as long as the query side is zero-padded identically
    qrot_p = jnp.pad(qrot, ((0, 0), (0, rot_pad - rot)))
    cf_p = jnp.pad(cf, ((0, 0), (0, rot_pad - rot)))

    def distance_block(gl, slot):
        qid = jnp.where(slot < P, slot // n_probes, 0)
        qv = qrot_p[qid]                                 # (B, G, rot_pad)
        data = list_recon_i8[gl].astype(jnp.bfloat16)    # (B, cap, rot_pad)
        ids = list_indices[gl]
        sc = list_recon_scale[gl]                        # (B,)
        rsq = list_recon_i8_sq[gl]                       # (B, cap)
        sub = qv - cf_p[gl][:, None, :]
        ip = jnp.einsum("bqr,bcr->bqc", sub.astype(jnp.bfloat16), data,
                        preferred_element_type=jnp.float32)
        d = jnp.maximum(jnp.sum(sub * sub, axis=-1)[:, :, None]
                        + rsq[:, None, :]
                        - 2.0 * sc[:, None, None] * ip, 0.0)
        d = jnp.where(ids[:, None, :] >= 0, d, jnp.inf)
        if filter_words is not None:
            qid = jnp.where(slot < P, slot // n_probes, 0)
            adm = _fbits.query_bits(
                filter_words, qid,
                jnp.broadcast_to(ids[:, None, :], d.shape))
            d = jnp.where(adm > 0, d, jnp.inf)
        return d, ids

    outd, outi = grouped.scan_and_scatter(
        group_list, slot_pairs, P, cap, k, True, block,
        select_k, distance_block, kt=kt)
    return grouped.finalize_topk(
        outd, outi, nq, k, True,
        metric in (DistanceType.L2SqrtExpanded,
                   DistanceType.L2SqrtUnexpanded), select_k)


def _fused_epilogue(vals, ids, qorder, nq, k, metric):
    """Shared tail of the fused scans: column-major (k, nq_pad) kernel
    output -> (nq, k) rows, finite-worst sentinel -> the public +inf /
    id -1 contract, sqrt for the sqrt-L2 metrics, and the un-permute of
    the probe-overlap query order.  Note what is ABSENT: no scatter and
    no select — the kernel already holds each query's final top-k."""
    from raft_tpu.ops.pq_group_scan_pallas import _ACC_WORST

    d = vals[:, :nq].T
    i = ids[:, :nq].T
    bad = d >= _ACC_WORST / 2
    d = jnp.where(bad, jnp.inf, d)
    i = jnp.where(bad, -1, i)
    if metric in (DistanceType.L2SqrtExpanded,
                  DistanceType.L2SqrtUnexpanded):
        d = jnp.sqrt(jnp.maximum(d, 0.0))
    inv = jnp.argsort(qorder)
    return d[inv], i[inv]


@functools.partial(jax.jit, static_argnames=("k", "kt", "metric", "n_groups",
                                             "pq_bits", "merge_window",
                                             "pallas_interpret"))
def _search_impl_fused_codes_grouped(centers, codebooks, list_code_lanes,
                                     list_code_rsq, list_indices, rotation,
                                     queries, probes, k, kt, metric,
                                     n_groups, pq_bits, merge_window=1,
                                     pallas_interpret=False,
                                     filter_words=None):
    """Fused compact-code scan: the grouped code scan with the per-query
    top-k folded INTO the kernel (pq_code_scan_pallas
    ``grouped_code_scan_fused``) — per-pair candidates never reach HBM,
    and the scatter + final-select stages of
    :func:`_search_impl_codes_grouped` do not exist here.  Queries are
    pre-permuted by probe overlap (grouped.probe_overlap_order) so hot
    lists stream once per batch."""
    from raft_tpu.neighbors import grouped
    from raft_tpu.ops import pq_code_scan_pallas as pcs

    nq, n_probes = probes.shape
    n_lists = centers.shape[0]
    cap = list_code_lanes.shape[2]
    qrot = queries.astype(jnp.float32) @ rotation
    cf = centers.astype(jnp.float32)

    qorder = grouped.probe_overlap_order(probes, n_lists)
    group_list, slot_pairs = grouped.build_groups(probes[qorder], n_lists,
                                                  n_groups)
    adm_words = None
    if filter_words is not None:
        # slot pairs decode to PERMUTED query ids — permute the filter
        # rows identically or every query consults its neighbor's bits
        adm_words = _fbits.group_admission_words(
            filter_words[qorder], group_list, slot_pairs, list_indices,
            n_probes, nq * n_probes)
    kt = min(kt or k, cap)
    vals, ids = pcs.grouped_code_scan_fused(
        group_list, slot_pairs, qrot[qorder], cf, list_code_lanes,
        codebooks, list_code_rsq, list_indices, kt, k, n_probes, pq_bits,
        interpret=pallas_interpret, merge_window=merge_window,
        adm_words=adm_words)
    return _fused_epilogue(vals, ids, qorder, nq, k, metric)


@functools.partial(jax.jit, static_argnames=("k", "kt", "metric", "n_groups",
                                             "merge_window",
                                             "pallas_interpret"))
def _search_impl_fused_recon_grouped(centers, list_recon, list_recon_sq,
                                     list_indices, rotation, queries,
                                     probes, k, kt, metric, n_groups,
                                     merge_window=1,
                                     pallas_interpret=False,
                                     filter_words=None):
    """Fused recon scan: :func:`_search_impl_recon_grouped`'s Pallas
    path with the per-query top-k folded into the kernel
    (pq_group_scan_pallas ``grouped_l2_scan_fused``) — same quantized
    distances, no scatter, no final select."""
    from raft_tpu.neighbors import grouped
    from raft_tpu.ops import pq_group_scan_pallas as pqp

    nq, n_probes = probes.shape
    n_lists, cap, _ = list_recon.shape
    qrot = queries.astype(jnp.float32) @ rotation
    cf = centers.astype(jnp.float32)

    qorder = grouped.probe_overlap_order(probes, n_lists)
    group_list, slot_pairs = grouped.build_groups(probes[qorder], n_lists,
                                                  n_groups)
    adm_words = None
    if filter_words is not None:
        adm_words = _fbits.group_admission_words(
            filter_words[qorder], group_list, slot_pairs, list_indices,
            n_probes, nq * n_probes)
    kt = min(kt or k, cap)
    vals, ids = pqp.grouped_l2_scan_fused(
        group_list, slot_pairs, qrot[qorder], cf, list_recon,
        list_recon_sq, list_indices, kt, k, n_probes,
        interpret=pallas_interpret, merge_window=merge_window,
        adm_words=adm_words)
    return _fused_epilogue(vals, ids, qorder, nq, k, metric)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=(
    "k", "n_probes", "metric", "codebook_kind", "lut_dtype", "pq_bits",
    "coarse_recall_target", "exact_coarse"))
def _search_impl(centers, codebooks, list_codes, list_indices, rotation,
                 queries, k, n_probes, metric, codebook_kind, lut_dtype,
                 pq_bits=8, coarse_recall_target=0.95, exact_coarse=False,
                 filter_words=None):
    nq = queries.shape[0]
    qrot = queries.astype(jnp.float32) @ rotation       # (q, rot_dim)
    cf = centers.astype(jnp.float32)
    # pq_dim from rotation/codebook shapes: list_codes' trailing axis is
    # the packed byte width
    pq_dim = rotation.shape[1] // codebooks.shape[-1]
    ip_metric = metric == DistanceType.InnerProduct

    # ---- select_clusters (ivf_pq_search.cuh:133): coarse top-n_probes ----
    probes = _select_clusters(centers, rotation, queries, n_probes, metric,
                              recall_target=coarse_recall_target,
                              exact=exact_coarse)
    q_dot_c = jax.lax.dot_general(qrot, cf, (((1,), (1,)), ((), ())),
                                  precision=get_matmul_precision(),
                                  preferred_element_type=jnp.float32)

    worst = -jnp.inf if ip_metric else jnp.inf
    cap = list_codes.shape[1]
    kt = min(k, cap)
    cb_sq = jnp.sum(codebooks.astype(jnp.float32) ** 2, axis=-1)

    q_sub = _subspace_split(qrot, pq_dim)               # (q, j, l)

    def probe_step(carry, p):
        alld, alli = carry
        lists = probes[:, p]                            # (q,)
        if ip_metric:
            # score = q·x ≈ q·center + Σ_j <q_j, cb[code_j]>: the LUT is the
            # *query* subvectors against the books; q·center folds in below.
            sub = q_sub
        else:
            # d = ||resid_q - codevec||² = ||resid_q||² + Σ_j (||cb||² - 2<r_j,cb>)
            sub = _subspace_split(qrot - cf[lists], pq_dim)
        if codebook_kind == CodebookKind.PER_SUBSPACE:
            ip = jnp.einsum("qjl,jkl->qjk", sub,
                            codebooks.astype(jnp.float32),
                            precision=get_matmul_precision())
            bsq = cb_sq[None, :, :]
        else:
            books = codebooks[lists]                     # (q, book, l)
            ip = jnp.einsum("qjl,qkl->qjk", sub, books.astype(jnp.float32),
                            precision=get_matmul_precision())
            bsq = cb_sq[lists][:, None, :]
        lut = (ip if ip_metric else bsq - 2.0 * ip).astype(lut_dtype)

        codes = _unpack_codes(list_codes[lists], pq_dim,
                              pq_bits)                  # (q, cap, j) uint8
        ids = list_indices[lists]                       # (q, cap)
        # gather LUT entries by code: (q, cap, j) — the compute_similarity
        # kernel's smem-LUT lookup (ivf_pq_search.cuh:611)
        gathered = jnp.take_along_axis(
            lut[:, None, :, :],                         # (q, 1, j, book)
            codes[..., None].astype(jnp.int32),         # (q, cap, j, 1)
            axis=-1)[..., 0]
        d = jnp.sum(gathered.astype(jnp.float32), axis=-1)  # (q, cap)
        if ip_metric:
            d = d + jnp.take_along_axis(q_dot_c, lists[:, None], axis=1)
        else:
            # ||resid_q||² varies across probes — required for cross-probe
            # comparability in the merged top-k
            d = d + jnp.sum(sub * sub, axis=(1, 2))[:, None]
        d = jnp.where(ids >= 0, d, worst)
        if filter_words is not None:
            adm = _fbits.query_bits(filter_words, jnp.arange(nq), ids)
            d = jnp.where(adm > 0, d, worst)
        td, ti = select_k(d, kt, in_idx=ids, select_min=not ip_metric)
        alld = jax.lax.dynamic_update_slice(alld, td, (0, p * kt))
        alli = jax.lax.dynamic_update_slice(alli, ti, (0, p * kt))
        return (alld, alli), None

    # hierarchical select (exact; see _search_impl_recon)
    init = (jnp.full((nq, n_probes * kt), worst, jnp.float32),
            jnp.full((nq, n_probes * kt), -1, jnp.int32))
    (alld, alli), _ = jax.lax.scan(probe_step, init,
                                   jnp.arange(n_probes))
    from raft_tpu.neighbors import grouped
    return grouped.finalize_topk(
        alld, alli, nq, k, not ip_metric,
        metric in (DistanceType.L2SqrtExpanded,
                   DistanceType.L2SqrtUnexpanded), select_k)


_SCAN_MODES = ("auto", "codes", "recon", "recon8", "lut", "fused")

_L2_METRICS = (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
               DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded)


def _codes_mode_eligible(index: Index) -> bool:
    """Static preconditions of the compact-code kernel (the shape/VMEM
    gate runs later, per batch): L2-family metric, per-subspace
    codebooks, and pq_bits that divide an int32 word so no code field
    straddles words."""
    return (index.metric in _L2_METRICS
            and index.codebook_kind == CodebookKind.PER_SUBSPACE
            and index.pq_bits in (4, 8))


@auto_convert_output
def search(res, params: SearchParams, index: Index, queries, k: int, *,
           filter=None) -> Tuple[jax.Array, jax.Array]:
    """Search (reference: ivf_pq.cuh:342).  Returns (distances, indices).

    ``params.scan_mode`` picks the list-scan formulation (see
    :class:`SearchParams`); "codes" and "recon8" silently fall back to
    the LUT / XLA formulations off-TPU or for unsupported shapes, so the
    same call works on every backend.

    ``filter`` (a :class:`~raft_tpu.filters.SampleFilter` or an
    (nq, n_rows) bool mask — see docs/api.md, "Filtered search &
    tenancy") restricts each query's candidate set by source id: a
    rejected row folds to the worst-distance sentinel *before* every
    top-k, on every scan mode, so filtered results are bit-identical to
    a post-hoc filtered exact scan at full probe.  Rejected slots
    surface as (+inf/-inf, -1) like tombstones.  Filters are data, not
    shape — varying filters re-enter the same compiled executable.

    Queries pass through the boundary validator (see
    :mod:`raft_tpu.integrity.boundary`): under policy ``mask``,
    non-finite query rows return id -1 / worst distance instead of
    poisoning the batch.

    .. note:: the first search may mutate ``index`` in place, lazily
       attaching derived caches (``list_recon``/``list_recon_sq``, the
       codes-lane and int8 caches of their scan modes, the group count
       and id-exactness caches); the derived caches are pytree leaves, so
       the registered pytree structure can change after the first search
       (one retrace for jitted closures over the index).
    """
    queries = ensure_array(queries, "queries")
    queries, ok_rows = _boundary.check_matrix(
        queries, "queries", site="ivf_pq.search", dim=index.dim)
    # legacy shape guard: still fires when the validator policy is "off"
    expects(queries.ndim == 2 and queries.shape[1] == index.dim,
            "ivf_pq.search: query dim mismatch")
    dist, ids = _search_checked(res, params, index, queries, k,
                                filter=filter)
    if ok_rows is not None:
        dist, ids = _boundary.mask_search_outputs(
            dist, ids, ok_rows,
            select_min=index.metric != DistanceType.InnerProduct)
    return dist, ids


def _search_checked(res, params: SearchParams, index: Index, queries,
                    k: int, filter=None) -> Tuple[jax.Array, jax.Array]:
    with named_range("ivf_pq::search"):
        fw = _fbits.query_filter_words(filter, queries.shape[0],
                                       "ivf_pq.search")
        if fw is not None and obs.enabled():
            obs.registry().counter("ivf_pq.search.filtered").inc()
        n_probes = min(params.n_probes, index.n_lists)
        coarse_rt = getattr(params, "coarse_recall_target", 0.95)
        exact_coarse = getattr(params, "exact_coarse", False)
        mode = getattr(params, "scan_mode", "auto") or "auto"
        if getattr(params, "use_reconstruction", None) is not None:
            # compat override (pre-scan_mode API)
            mode = "recon" if params.use_reconstruction else "lut"
        expects(mode in _SCAN_MODES,
                f"ivf_pq.search: unknown scan_mode {mode!r} "
                f"(one of {_SCAN_MODES})")
        kt_req = int(getattr(params, "per_probe_topk", 0) or 0)
        packed = bool(getattr(params, "packed_extract", False))

        # "fused" and "auto" both resolve to a BACKING mode (codes /
        # recon / lut) that owns the derived caches and the fallback
        # path; want_fused marks that the grouped dispatch should
        # upgrade to the in-kernel top-k variant when the batch's shape
        # supports it.  Every upgrade miss is counted
        # (ivf_pq.search.fused_fallback) — the CI tripwire watches it.
        want_fused = mode in ("auto", "fused")
        if mode == "fused":
            mode = ("codes" if _codes_mode_eligible(index)
                    else "recon" if index.list_recon is not None
                    else "lut")
        if mode == "auto":
            if index.list_recon is not None:
                mode = "recon"
            elif _codes_mode_eligible(index):
                mode = "codes"
            else:
                mode = "lut"
        if mode in ("codes", "recon8") and index.metric not in _L2_METRICS:
            mode = "lut" if index.list_recon is None else "recon"

        def note_fused_fallback(reason="backend"):
            # reason codes (shared with distributed.ann): "dtype",
            # "k-too-large", "bucket-too-wide", "itopk-gate" from the
            # kernel reject helpers; "backend" for off-TPU / non-f32-id
            # misses; "mode" when the backing mode has no fused variant.
            if obs.enabled():
                reg = obs.registry()
                reg.counter("ivf_pq.search.fused_fallback").inc()
                reg.counter(
                    f"ivf_pq.search.fused_fallback.reason.{reason}").inc()
            from raft_tpu.observability import flight as _flight
            from raft_tpu.observability import trace as _rtrace
            rec = _rtrace.current()
            _flight.record_event("ivf_pq.fused_fallback", reason=reason,
                                 trace_id=rec.trace_id if rec else None)

        tracing = (isinstance(queries, jax.core.Tracer)
                   or isinstance(index.centers, jax.core.Tracer))
        if tracing:
            # queries or the Index pytree traced by an outer jit/vmap:
            # the grouped dispatch itself is shape-static since round 10,
            # but a calibrated index's overflow re-dispatch gate is a
            # host read that cannot run under a trace — use the fully
            # traceable probe-order formulations instead (the LUT scan
            # computes the same quantized distance as the codes kernel,
            # so AOT-exported "codes" searches stay exact)
            if mode in ("recon", "recon8") and index.list_recon is not None:
                return _search_impl_recon(
                    index.centers, index.list_recon, index.list_indices,
                    index.rotation, queries, k, n_probes, index.metric,
                    list_recon_sq=index.list_recon_sq, filter_words=fw)
            return _search_impl(index.centers, index.codebooks,
                                index.list_codes, index.list_indices,
                                index.rotation, queries, k, n_probes,
                                index.metric, index.codebook_kind,
                                jnp.dtype(params.lut_dtype).name,
                                pq_bits=index.pq_bits,
                                coarse_recall_target=coarse_rt,
                                exact_coarse=exact_coarse,
                                filter_words=fw)

        def lut_scan():
            with obs.stage("ivf_pq.search.lut") as st:
                out = _search_impl(index.centers, index.codebooks,
                                   index.list_codes, index.list_indices,
                                   index.rotation, queries, k, n_probes,
                                   index.metric, index.codebook_kind,
                                   jnp.dtype(params.lut_dtype).name,
                                   pq_bits=index.pq_bits,
                                   coarse_recall_target=coarse_rt,
                                   exact_coarse=exact_coarse,
                                   filter_words=fw)
                st.fence(out)
            return out

        if mode == "lut":
            if want_fused:
                note_fused_fallback("mode")
            return lut_scan()

        from raft_tpu.neighbors import grouped
        from raft_tpu.ops import pq_code_scan_pallas as pcs

        # ---- lazy derived caches (one-time per index) -------------------
        if mode == "recon":
            if index.list_recon is None:
                # One-time materialization of the (n_lists, cap, rot_dim)
                # bf16 cache on an index built without it; the cache stays
                # attached for subsequent searches.
                warnings.warn(
                    "ivf_pq.search: scan_mode='recon' on an index built "
                    "without a reconstruction cache — materializing the "
                    "(n_lists, cap, rot_dim) bf16 cache now (and keeping "
                    "it on the index). Build with "
                    "cache_reconstructions=True or pick another scan_mode "
                    "to avoid this.")
                index = _with_recon(res, index)
            if index.list_recon_sq is None:
                index.list_recon_sq = _recon_sq(index.list_recon)
        elif mode == "codes":
            if not _codes_mode_eligible(index):
                return lut_scan()
            if index.list_code_lanes is None or index.list_code_rsq is None:
                # the VMEM-LUT analogue of the reference's per-probe smem
                # LUT build: here the scan tables are built once per index
                with obs.stage("ivf_pq.search.lut_build") as st:
                    index = _with_code_lanes(index)
                    st.fence(index.list_code_lanes, index.list_code_rsq)
        elif mode == "recon8":
            if index.list_recon_i8 is None:
                with obs.stage("ivf_pq.search.lut_build") as st:
                    index = _with_recon8(index)
                    st.fence(index.list_recon_i8)

        cap = index.capacity
        nq = queries.shape[0]
        rot = index.rot_dim
        kt = min(kt_req or k, cap)
        from raft_tpu.ops import vmem_budget as vb
        mw_req = vb.merge_window_request(
            getattr(params, "merge_window", "auto"))
        G = grouped.GROUP
        on_tpu = jax.default_backend() == "tpu"
        # the fused kernels' one-hot id contraction is f32 — require
        # every actual candidate id (incl. user-supplied extend ids)
        # to be f32-exact, not just the row count
        ids_ok = grouped.ids_f32_exact(index, index.list_indices)

        if mode == "codes" and not (
                on_tpu and ids_ok
                and pcs.supported_codes(True, True, cap, rot, kt, nq,
                                        index.pq_dim, index.pq_bits,
                                        packed)):
            # no XLA twin of the codes kernel is worth running (it would
            # re-decode every row anyway) — the LUT formulation computes
            # the same quantized distance
            if want_fused:
                note_fused_fallback(
                    "backend" if not (on_tpu and ids_ok) else
                    pcs.fused_codes_reject_reason(
                        True, True, cap, rot, kt, k, nq, index.pq_dim,
                        index.pq_bits) or "bucket-too-wide")
            return lut_scan()

        with obs.stage("ivf_pq.search.coarse") as st:
            probes = _select_clusters(index.centers, index.rotation,
                                      queries, n_probes, index.metric,
                                      recall_target=coarse_rt,
                                      exact=exact_coarse)
            st.fence(probes)
        # static group capacity (round 10): uncalibrated indexes dispatch
        # at the exact-safe worst-case bound — the shape depends only on
        # (nq, n_probes, n_lists), so NO host sync of a group count
        # exists anywhere on this path and one warmed executable serves
        # every batch at the shape.  A calibrated index (group_est > 0)
        # dispatches at the tightened capacity and arms the in-graph
        # overflow count, enqueued BEFORE the scan so the read overlaps
        # the scan's execution; only the rare batch whose probe skew
        # exceeds the calibrated bound pays a second pass.
        n_groups, exact = grouped.group_capacity(
            nq, n_probes, index.n_lists, est=index.group_est)
        needed_dev = (None if exact
                      else grouped.num_groups(probes, index.n_lists))

        def run_grouped(stage_label, dispatch):
            with obs.stage(stage_label) as st:
                out = dispatch(n_groups)
                if needed_dev is not None and int(needed_dev) > n_groups:
                    # calibrated capacity exceeded: tick the overflow
                    # counter and re-dispatch at the worst-case bound,
                    # where no pair can drop — results stay exact
                    if obs.enabled():
                        obs.registry().counter(
                            "ivf_pq.search.group_overflow").inc()
                    worst, _ = grouped.group_capacity(
                        nq, n_probes, index.n_lists)
                    out = dispatch(worst)
                st.fence(out)
            return out

        if mode == "codes":
            if want_fused:
                if pcs.supported_fused_codes(True, True, cap, rot, kt, k,
                                             nq, index.pq_dim,
                                             index.pq_bits,
                                             merge_window=mw_req):
                    # one stage where code_scan + extraction used to be
                    # two: the kernel output IS the final top-k.  The
                    # merge window is resolved host-statically from the
                    # same shapes the gate saw (never from n_groups), so
                    # the overflow re-dispatch reuses the choice.
                    mw = pcs.fused_codes_merge_window(
                        cap, rot, kt, k, nq, index.pq_dim, index.pq_bits,
                        requested=mw_req)
                    return run_grouped(
                        "ivf_pq.search.fused_scan",
                        lambda ng: _search_impl_fused_codes_grouped(
                            index.centers, index.codebooks,
                            index.list_code_lanes, index.list_code_rsq,
                            index.list_indices, index.rotation, queries,
                            probes, k, kt, index.metric, ng,
                            index.pq_bits, merge_window=mw,
                            filter_words=fw))
                note_fused_fallback(pcs.fused_codes_reject_reason(
                    True, True, cap, rot, kt, k, nq, index.pq_dim,
                    index.pq_bits, merge_window=mw_req)
                    or "bucket-too-wide")
            return run_grouped(
                "ivf_pq.search.code_scan",
                lambda ng: _search_impl_codes_grouped(
                    index.centers, index.codebooks, index.list_code_lanes,
                    index.list_code_rsq, index.list_indices, index.rotation,
                    queries, probes, k, kt, index.metric, ng,
                    index.pq_bits, packed=packed, filter_words=fw))

        if mode == "recon8":
            rot_pad = index.list_recon_i8.shape[2]
            use_pallas = (on_tpu and ids_ok
                          and pcs.supported_recon8(True, cap, rot, kt, nq,
                                                   packed))

            def dispatch8(ng):
                block = grouped.block_size(
                    ng,
                    G * cap * 8,          # fp32 distances + broadcast ids
                    cap * rot_pad * 3,    # int8 slice + bf16 upcast
                    G * rot_pad * 4)      # query gather
                return _search_impl_recon8_grouped(
                    index.centers, index.list_recon_i8,
                    index.list_recon_scale, index.list_recon_i8_sq,
                    index.list_indices, index.rotation, queries, probes, k,
                    kt, index.metric, ng, block, use_pallas=use_pallas,
                    packed=packed, filter_words=fw)

            return run_grouped("ivf_pq.search.recon8_scan", dispatch8)

        use_pallas = on_tpu and ids_ok

        if want_fused:
            from raft_tpu.ops import pq_group_scan_pallas as pqp

            if use_pallas and pqp.supported_fused(
                    index.metric in _L2_METRICS, cap, rot, kt, k, nq,
                    merge_window=mw_req):
                mw = pqp.fused_merge_window(cap, rot, kt, k, nq,
                                            requested=mw_req)
                return run_grouped(
                    "ivf_pq.search.fused_scan",
                    lambda ng: _search_impl_fused_recon_grouped(
                        index.centers, index.list_recon,
                        index.list_recon_sq, index.list_indices,
                        index.rotation, queries, probes, k, kt,
                        index.metric, ng, merge_window=mw,
                        filter_words=fw))
            note_fused_fallback(
                "backend" if not use_pallas else
                pqp.fused_reject_reason(index.metric in _L2_METRICS, cap,
                                        rot, kt, k, nq,
                                        merge_window=mw_req)
                or "bucket-too-wide")

        def dispatch(ng):
            block = grouped.block_size(
                ng,
                G * cap * 8,      # fp32 distances + broadcast ids
                cap * rot * 2,    # bf16 recon slice
                G * rot * 4)      # query gather
            return _search_impl_recon_grouped(
                index.centers, index.list_recon, index.list_recon_sq,
                index.list_indices, index.rotation, queries, probes, k,
                index.metric, ng, block, use_pallas=use_pallas, kt=kt,
                filter_words=fw)

        return run_grouped("ivf_pq.search.scan", dispatch)


def calibrate_group_capacity(res, index: Index, queries,
                             n_probes: int) -> float:
    """Measure the grouped-scan capacity estimate on a representative
    query batch and store it on the index (round 10).

    The grouped dispatch needs a static group count; without calibration
    it uses the exact-safe worst case ``ceil(P/G) + min(n_lists, P)``
    (see :func:`raft_tpu.neighbors.grouped.group_capacity`).  Real probe
    distributions touch far fewer lists than the bound assumes, so this
    measures the touched-list fraction under the index's own coarse
    router and records it as ``index.group_est`` — searches then
    dispatch at the tightened capacity with the in-graph overflow
    fallback armed.  Repeated calls ratchet the estimate upward (max),
    so calibrating on several batches converges to the widest observed
    distribution.  The estimate rides the serialization envelope (v4);
    loading a pre-v4 stream leaves the index uncalibrated, which is
    always correct (worst-bound dispatch).

    Returns the stored estimate (a fraction of ``min(n_lists, P)``).
    """
    from raft_tpu.neighbors import grouped

    queries = ensure_array(queries, "queries")
    expects(queries.ndim == 2 and queries.shape[1] == index.dim,
            "ivf_pq.calibrate_group_capacity: queries must be "
            f"(n, {index.dim})")
    n_probes = min(int(n_probes), index.n_lists)
    expects(n_probes >= 1,
            "ivf_pq.calibrate_group_capacity: n_probes must be >= 1")
    probes = _select_clusters(index.centers, index.rotation,
                              jnp.asarray(queries), n_probes, index.metric)
    P = int(queries.shape[0]) * n_probes
    touched = int(grouped.touched_lists(probes, index.n_lists))
    est = touched / max(min(index.n_lists, P), 1)
    index.group_est = max(float(index.group_est), est)
    return index.group_est


# ---------------------------------------------------------------------------
# serialization (reference: ivf_pq_serialize.cuh:38 kSerializationVersion)
# ---------------------------------------------------------------------------

# v2: list_codes are bit-packed; pq_dim is stored explicitly
# v3: trailing recall-canary block (nested envelope, may be absent)
# v4: calibrated group-capacity estimate (group_est float64 scalar)
#     between the fixed header and the mdspans
_SERIALIZATION_VERSION = 4
_MIN_READ_VERSION = 2


def serialize(res, stream: BinaryIO, index: Index) -> None:
    """CRC32-enveloped versioned dump (reference: ivf_pq_serialize.cuh)."""
    with ser.enveloped_writer(stream) as body:
        ser.serialize_scalar(res, body, np.int32(_SERIALIZATION_VERSION))
        ser.serialize_scalar(res, body, np.int32(index.metric))
        ser.serialize_scalar(res, body, np.int32(index.codebook_kind))
        ser.serialize_scalar(res, body, np.int32(index.pq_bits))
        ser.serialize_scalar(res, body, np.int32(index.pq_dim))
        ser.serialize_scalar(res, body, np.float64(index.group_est))
        for arr in (index.centers, index.codebooks, index.list_codes,
                    index.list_indices, index.list_sizes, index.rotation):
            ser.serialize_mdspan(res, body, arr)
        _canary.to_stream(res, body, index.canaries)


def deserialize(res, stream: BinaryIO, *,
                cache_reconstructions: bool = True) -> Index:
    """Truncated / bit-flipped streams raise
    :class:`~raft_tpu.core.serialize.CorruptIndexError`."""
    body = ser.open_envelope(stream)
    version = int(ser.deserialize_scalar(res, body))
    if not _MIN_READ_VERSION <= version <= _SERIALIZATION_VERSION:
        raise ValueError(
            f"ivf_pq serialization version mismatch: got {version}, "
            f"expected {_MIN_READ_VERSION}..{_SERIALIZATION_VERSION}")
    metric = int(ser.deserialize_scalar(res, body))
    kind = int(ser.deserialize_scalar(res, body))
    pq_bits = int(ser.deserialize_scalar(res, body))
    pq_dim = int(ser.deserialize_scalar(res, body))
    # back-compat read window: pre-v4 streams carry no capacity estimate
    # — the index loads uncalibrated (worst-bound dispatch, always safe)
    group_est = (float(ser.deserialize_scalar(res, body))
                 if version >= 4 else 0.0)
    arrays = [jnp.asarray(ser.deserialize_mdspan(res, body))
              for _ in range(6)]
    index = Index(*arrays, metric=metric, codebook_kind=kind,
                  pq_bits=pq_bits, pq_dim_=pq_dim, group_est=group_est)
    if version >= 3:
        index.canaries = _canary.from_stream(res, body)
    # the reconstruction cache is derived state: re-decode from codes —
    # unless the caller opted out (indexes too large for the cache, the
    # same regime as IndexParams.cache_reconstructions=False)
    if cache_reconstructions:
        index = _with_recon(res, index)
    return index


def save(res, filename: str, index: Index, *, retry_policy=None,
         deadline=None) -> None:
    """Atomic file dump (tmp + fsync + rename) with transient-IO retry."""
    from raft_tpu.resilience import save_index
    save_index("ivf_pq.save", lambda b: serialize(res, b, index),
               filename, retry_policy, deadline)


def load(res, filename: str, *, cache_reconstructions: bool = True,
         retry_policy=None, deadline=None) -> Index:
    """File-load overload; transient IO retries, corruption fails fast.

    Indexes carrying recall canaries are health-checked before being
    returned: a loaded index whose recall dropped below the stored floor
    raises :class:`~raft_tpu.integrity.IntegrityError` here, not in
    production traffic."""
    from raft_tpu.resilience import load_index
    index = load_index(
        "ivf_pq.load",
        lambda b: deserialize(
            res, b, cache_reconstructions=cache_reconstructions),
        filename, retry_policy, deadline)
    _canary.auto_check(res, index, site="load")
    return index
