"""Shared tombstone / generation helpers for the mutable IVF indexes.

The online mutation layer encodes per-row state entirely inside
``list_indices`` — the one array every scan formulation already masks on:

- slot value ``>= 0``  — live row (the value is the source id);
- slot value ``-1``    — never-filled padding (the pre-existing contract);
- slot value ``<= -2`` — tombstoned row: original id ``v`` is stored as
  ``-(v + 2)`` (decode with :func:`decode_tombstones`).

Every scan path — the probe-order XLA scans, the grouped XLA distance
blocks, and the Pallas kernels including the fused in-kernel top-k
variants — masks candidates with ``id < 0`` to the worst-distance
sentinel, so tombstoned rows vanish from search results through the exact
same mechanism as capacity padding: zero kernel changes, zero per-search
cost, and no effect on fused-path shape eligibility.  The one id that a
mask cannot fix — a tombstone *encoding* surfacing when ``k`` exceeds the
valid candidate count — is clamped to the public ``-1`` sentinel in
``grouped.finalize_topk`` (the shared epilogue) and mapped by the fused
kernels' sentinel-distance epilogue.

Mutations never edit an index in place: ``delete`` / ``compact`` /
``extend`` return a NEW Index (the next *generation*) sharing every
unchanged array with its parent, so in-flight readers pinned on the
parent are never corrupted.  The ``generation`` counter is a plain
host-side attribute — deliberately neither a pytree leaf nor aux data
(aux participation would force a retrace per mutation) — that orders the
snapshots and keys the serving tier's warmed-executable cache
(``core/aot.ExecutableCache``).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def generation(index) -> int:
    """The index's generation counter (0 for a freshly built index or
    any index predating the mutation layer)."""
    return int(getattr(index, "generation", 0) or 0)


def next_generation(parent, child):
    """Stamp ``child`` as the generation after ``parent``; returns
    ``child``.  Called by every mutation (extend/delete/compact) on the
    new index it is about to return."""
    child.generation = generation(parent) + 1
    return child


def encode_tombstones(ids: jax.Array) -> jax.Array:
    """Id ``v`` -> tombstone slot value ``-(v + 2)``."""
    return -(ids + 2)


def tombstone(list_indices: jax.Array, ids) -> Tuple[jax.Array, jax.Array]:
    """Rewrite every live slot whose id is in ``ids`` to its tombstone
    encoding.  Returns ``(new_list_indices, hit_mask)``; ids not present
    in the index simply match nothing.  Pure elementwise — O(slots)
    regardless of how many ids are deleted, no repacking."""
    ids = jnp.asarray(ids, jnp.int32).reshape(-1)
    hit = jnp.isin(list_indices, ids) & (list_indices >= 0)
    return jnp.where(hit, encode_tombstones(list_indices), list_indices), hit


def decode_tombstones(list_indices) -> np.ndarray:
    """Host-side decode of every tombstoned id in ``list_indices``."""
    a = np.asarray(list_indices).reshape(-1)
    enc = a[a <= -2]
    return (-enc.astype(np.int64) - 2)


def deleted_ids(index) -> frozenset:
    """The set of deleted source ids, host-side.

    Graph indexes (CAGRA) carry an explicit ``deleted_ids`` attribute
    (the delete-mask shim); IVF indexes decode it from the tombstones in
    ``list_indices``.  An id that is tombstoned in one slot but live in
    another (the delete -> re-insert pattern the rebalancer's recluster
    step produces) is NOT deleted — the live copy answers searches.  Used
    by the canary recall measurement to exclude deleted rows from the
    ground-truth sets."""
    ext = getattr(index, "deleted_ids", None)
    if ext is not None:
        return frozenset(int(v) for v in ext)
    li = getattr(index, "list_indices", None)
    if li is None:
        return frozenset()
    a = np.asarray(li).reshape(-1)
    dead = frozenset(int(v) for v in decode_tombstones(a))
    if not dead:
        return dead
    live = frozenset(int(v) for v in a[a >= 0])
    return dead - live


def live_sizes(list_indices: jax.Array) -> jax.Array:
    """Per-list live-row counts (tombstones and padding excluded)."""
    return jnp.sum(list_indices >= 0, axis=1).astype(jnp.int32)


def live_count(index) -> int:
    """Total live rows (one tiny host sync)."""
    return int(jnp.sum(index.list_indices >= 0))


def dead_fraction(index) -> float:
    """Tombstoned fraction of occupied slots: ``dead / (live + dead)``
    (0.0 for an empty index).  Tombstones cost scan work — every probe
    still streams and masks them — so the rebalancer compacts past a
    configurable threshold of this number."""
    li = index.list_indices
    live = int(jnp.sum(li >= 0))
    dead = int(jnp.sum(li <= -2))
    total = live + dead
    return (dead / total) if total else 0.0


def compaction_order(list_indices: jax.Array
                     ) -> Tuple[jax.Array, jax.Array]:
    """Stable live-rows-first permutation of each list + live sizes.

    ``jnp.argsort`` is stable, so live rows keep their relative order —
    compaction permutes but never reorders survivors, which keeps
    results (and the canary ground truth) comparable across the swap."""
    order = jnp.argsort((list_indices < 0).astype(jnp.int32), axis=1)
    return order, live_sizes(list_indices)
