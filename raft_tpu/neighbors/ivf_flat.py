"""IVF-Flat: inverted-file index over a balanced-k-means coarse quantizer.

Reference: raft/neighbors/ivf_flat.cuh:65 ``build``, :201 ``extend``, :389
``search``; types ivf_flat_types.hpp:44 (index_params), :76 (search_params),
:126 (index).  Build internals: detail/ivf_flat_build.cuh (kmeans_balanced fit
:336-339, predict + calc_centers_and_sizes :180-204); search:
detail/ivf_flat_search.cuh:670 ``interleaved_scan_kernel`` + select_k.

TPU design — the central impedance mismatch is the reference's *ragged*
inverted lists vs XLA's static shapes (SURVEY.md §7 "hard parts"):

- lists are stored **padded to one shared capacity** (rounded to a multiple of
  32, like the reference rounds list allocations — ivf_flat_types.hpp /
  ivf_list.hpp); slot validity comes from ``list_indices >= 0``;
- balanced k-means keeps the padding overhead bounded (that is *why* the
  reference uses a balanced quantizer: list occupancy = search cost);
- search scans the ``n_probes`` probed lists with a ``lax.scan``, each step
  one gathered (q, capacity, d) block → a batched-matmul distance + masked
  top-k merge.  The gather+matmul per probe is the TPU analogue of the
  interleaved-scan kernel: MXU does the FLOPs, the mask replaces list length.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import BinaryIO, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_types import KMeansBalancedParams
from raft_tpu.core import serialize as ser
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu import observability as obs
from raft_tpu.integrity import boundary as _boundary
from raft_tpu.integrity import canary as _canary
from raft_tpu.neighbors import mutate as _mutate
from raft_tpu.distance.types import DistanceType
from raft_tpu.filters import bitset as _fbits
from raft_tpu.matrix.select_k import select_k
from raft_tpu.utils.precision import get_matmul_precision
from raft_tpu.core.outputs import auto_convert_output

_LIST_ALIGN = 32  # reference: list sizes rounded to warp multiples (ivf_list.hpp)


@dataclasses.dataclass
class IndexParams:
    """Reference: ivf_flat_types.hpp:44 ``index_params``."""

    n_lists: int = 1024
    metric: int = DistanceType.L2Expanded
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    adaptive_centers: bool = False
    add_data_on_build: bool = True
    # recall canaries (raft_tpu.integrity): > 0 samples that many sentinel
    # queries at build, stores their exact neighbors in the index, and
    # health-checks recall against the floor after load()/extend()
    canary_queries: int = 0
    canary_k: int = 10
    canary_floor: float = 0.5


@dataclasses.dataclass
class SearchParams:
    """Reference: ivf_flat_types.hpp:76 ``search_params``.

    ``coarse_recall_target`` / ``exact_coarse`` control the approx probe
    ranking (``approx_max_k``) of :func:`_select_clusters`: the recall
    target trades coarse ranking fidelity for speed, and ``exact_coarse``
    forces ``lax.top_k``.  Probe selection also falls back to the exact
    select on its own when ``n_probes`` is close to ``n_lists`` (the
    approximation saves nothing when nearly every list is probed anyway).
    Inherited by :class:`raft_tpu.neighbors.ivf_pq.SearchParams`.
    """

    n_probes: int = 20
    coarse_recall_target: float = 0.95
    exact_coarse: bool = False


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """Reference: ivf_flat_types.hpp:126 ``index`` (centers + per-list data
    + per-list source ids + sizes).  ``list_data`` is (n_lists, capacity, dim)
    with invalid slots zero; ``list_indices`` is (n_lists, capacity) int32
    with -1 marking empty slots."""

    centers: jax.Array          # (n_lists, dim) f32
    list_data: jax.Array        # (n_lists, capacity, dim)
    list_indices: jax.Array     # (n_lists, capacity) int32
    list_sizes: jax.Array       # (n_lists,) int32
    metric: int = DistanceType.L2Expanded
    adaptive_centers: bool = False
    # Derived search-time cache: per-row squared norms (n_lists, capacity)
    # fp32, loop-invariant across searches (recomputing it per call costs
    # a full pass over the raw vectors).  Lazily attached by search().
    list_data_sq: Optional[jax.Array] = None
    # Recall-canary sentinel set (integrity.CanarySet) — host-side
    # metadata, deliberately NOT a pytree leaf (aux must stay hashable),
    # so jax transforms drop it; build/extend/serialize carry it.
    canaries: Optional[object] = None
    # Mutation generation counter (see neighbors/mutate): host-side like
    # canaries — a leaf would be wrong and aux would force a retrace per
    # mutation.  extend/delete/compact stamp parent+1 on the new index.
    generation: int = 0

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def capacity(self) -> int:
        return self.list_data.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_sizes))

    def tree_flatten(self):
        leaves = (self.centers, self.list_data, self.list_indices,
                  self.list_sizes, self.list_data_sq)
        return leaves, (self.metric, self.adaptive_centers)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves[:4], metric=aux[0], adaptive_centers=aux[1],
                   list_data_sq=leaves[4])


def _round_up(x: int, align: int) -> int:
    return -(-x // align) * align


def _pack_lists(dataset: jax.Array, labels: jax.Array, source_ids: jax.Array,
                n_lists: int, capacity: int
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Scatter rows into padded per-list storage.

    The TPU analogue of the reference's list layout + fill kernels
    (detail/ivf_flat_build.cuh; codepacking in ivf_pq does the same dance):
    sort by label, compute each row's rank within its list, one scatter.
    """
    n = dataset.shape[0]
    order = jnp.argsort(labels)
    sorted_labels = labels[order]
    sizes = jax.ops.segment_sum(jnp.ones(n, jnp.int32), labels,
                                num_segments=n_lists)
    starts = jnp.cumsum(sizes) - sizes
    rank = jnp.arange(n) - starts[sorted_labels]
    list_data = jnp.zeros((n_lists, capacity, dataset.shape[1]),
                          dataset.dtype)
    list_idx = jnp.full((n_lists, capacity), -1, jnp.int32)
    list_data = list_data.at[sorted_labels, rank].set(dataset[order])
    list_idx = list_idx.at[sorted_labels, rank].set(
        source_ids[order].astype(jnp.int32))
    return list_data, list_idx, sizes


@jax.jit
def _append_lists_multi(bufs, rows, list_idx: jax.Array,
                        list_sizes: jax.Array, new_labels: jax.Array,
                        new_ids: jax.Array, lane_bufs=(), lane_rows=()):
    """Scatter-append rows into existing padded lists — the O(n_new)
    extend fast path (callers must have verified no list overflows the
    current capacity).  The reference's extend likewise appends in place
    when lists have headroom and only reallocates grown lists
    (ivf_list.hpp resize semantics).

    ``bufs``/``rows`` are matching tuples of per-list storages and their
    new rows (IVF-PQ appends codes + recon cache + recon norms in one
    pass); the slot layout is computed once and shared.  ``lane_bufs`` /
    ``lane_rows`` are lane-major (n_lists, X, capacity) storages (the
    packed-code-lane cache) whose new (n_new, X) rows scatter at
    ``[label, :, slot]``."""
    n_lists = list_sizes.shape[0]
    n_new = new_ids.shape[0]
    order = jnp.argsort(new_labels)
    sl = new_labels[order]
    new_counts = jax.ops.segment_sum(jnp.ones(n_new, jnp.int32), new_labels,
                                     num_segments=n_lists)
    starts = jnp.cumsum(new_counts) - new_counts
    slot = list_sizes[sl] + (jnp.arange(n_new) - starts[sl])
    bufs = tuple(b.at[sl, slot].set(r[order].astype(b.dtype))
                 for b, r in zip(bufs, rows))
    lane_bufs = tuple(
        b.at[sl[:, None], jnp.arange(b.shape[1])[None, :],
             slot[:, None]].set(r[order].astype(b.dtype))
        for b, r in zip(lane_bufs, lane_rows))
    list_idx = list_idx.at[sl, slot].set(new_ids[order].astype(jnp.int32))
    return bufs, lane_bufs, list_idx, list_sizes + new_counts


def _append_lists(list_data: jax.Array, list_idx: jax.Array,
                  list_sizes: jax.Array, new_rows: jax.Array,
                  new_labels: jax.Array, new_ids: jax.Array
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-payload convenience wrapper over _append_lists_multi."""
    (list_data,), _, list_idx, sizes = _append_lists_multi(
        (list_data,), (new_rows,), list_idx, list_sizes, new_labels,
        new_ids)
    return list_data, list_idx, sizes


def build(res, params: IndexParams, dataset) -> Index:
    """Build an IVF-Flat index (reference: ivf_flat.cuh:65).

    Trains the balanced coarse quantizer on a subsample
    (``kmeans_trainset_fraction``, as detail/ivf_flat_build.cuh:336), then
    assigns and packs all rows.
    """
    with named_range("ivf_flat::build"), \
            obs.build_scope("ivf_flat.build") as rep:
        dataset = ensure_array(dataset, "dataset")
        expects(dataset.ndim == 2, "ivf_flat.build: 2-D dataset required")
        dataset, _ = _boundary.check_matrix(dataset, "dataset",
                                            site="ivf_flat.build",
                                            allow_empty=False)
        n, dim = dataset.shape
        expects(params.n_lists <= n, "ivf_flat.build: n_lists > n_rows")

        with obs.stage("ivf_flat.build.kmeans") as st:
            n_train = max(params.n_lists,
                          int(n * params.kmeans_trainset_fraction))
            if n_train < n:
                key = res.next_key()
                sel = jax.random.choice(key, n, (n_train,), replace=False)
                trainset = dataset[sel]
            else:
                trainset = dataset
            bal = KMeansBalancedParams(n_iters=params.kmeans_n_iters,
                                       metric=params.metric
                                       if params.metric == DistanceType.InnerProduct
                                       else DistanceType.L2Expanded)
            centers = kmeans_balanced.fit(res, bal, trainset, params.n_lists)
            # order lists along the centers' first principal component:
            # spatially adjacent lists get adjacent ids, so a query's probes
            # cluster into few super-tiles (the small-cap scan regime —
            # see search()'s super-tile dedupe)
            cf = centers.astype(jnp.float32)
            # mean-center before the gram: off-origin data (e.g. all-positive
            # SIFT features) would otherwise put the mean direction in the
            # top eigenvector and make the projections ~constant
            cc = cf - jnp.mean(cf, axis=0, keepdims=True)
            _, cvecs = jnp.linalg.eigh(
                jax.lax.dot_general(cc, cc, (((0,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32))
            centers = centers[jnp.argsort(cc @ cvecs[:, -1])]
            st.fence(centers)

        index = Index(centers=centers,
                      list_data=jnp.zeros((params.n_lists, _LIST_ALIGN, dim),
                                          dataset.dtype),
                      list_indices=jnp.full((params.n_lists, _LIST_ALIGN), -1,
                                            jnp.int32),
                      list_sizes=jnp.zeros(params.n_lists, jnp.int32),
                      metric=params.metric,
                      adaptive_centers=params.adaptive_centers)
        if params.add_data_on_build:
            index = extend(res, index, dataset,
                           jnp.arange(n, dtype=jnp.int32))
            if params.canary_queries > 0:
                cs = _canary.make(res, dataset, metric=params.metric,
                                  n_queries=params.canary_queries,
                                  k=params.canary_k,
                                  floor=params.canary_floor)
                index.canaries = cs
                cs.build_recall = _canary.measure(res, index, cs)
        return rep.attach(index)


def extend(res, index: Index, new_vectors, new_indices=None) -> Index:
    """Add vectors to an index (reference: ivf_flat.cuh:201 ``extend``).

    Fast path (no list outgrows the current capacity): one O(n_new)
    scatter-append into the existing padded storage.  Slow path (some list
    overflows): flatten + repack at a larger capacity — the reference
    likewise reallocates lists that outgrow their capacity (ivf_list.hpp).
    The coarse centers optionally drift when ``adaptive_centers`` is set
    (ivf_flat_types.hpp adaptive_centers semantics).
    """
    with named_range("ivf_flat::extend"):
        new_vectors = ensure_array(new_vectors, "new_vectors")
        expects(new_vectors.ndim == 2 and new_vectors.shape[1] == index.dim,
                "ivf_flat.extend: dim mismatch")
        new_vectors, _ = _boundary.check_matrix(
            new_vectors, "new_vectors", site="ivf_flat.extend",
            dim=index.dim)
        n_new = new_vectors.shape[0]
        if new_indices is None:
            new_indices = index.size + jnp.arange(n_new, dtype=jnp.int32)
        else:
            new_indices = ensure_array(new_indices, "new_indices")

        with obs.stage("ivf_flat.extend.assign") as st:
            bal = KMeansBalancedParams(metric=index.metric
                                       if index.metric == DistanceType.InnerProduct
                                       else DistanceType.L2Expanded)
            new_labels = kmeans_balanced.predict(res, bal, new_vectors,
                                                 index.centers)
            new_counts = jax.ops.segment_sum(
                jnp.ones(n_new, jnp.int32), new_labels,
                num_segments=index.n_lists)
            needed = index.list_sizes + new_counts
            st.fence(new_labels)

        # one host sync over an (n_lists,) reduction decides the path — the
        # only data-dependent choice (capacity is a static shape)
        if int(jnp.max(needed)) <= index.capacity:
            with obs.stage("ivf_flat.extend.pack") as st:
                bufs, rows = [index.list_data], [new_vectors]
                if index.list_data_sq is not None:
                    bufs.append(index.list_data_sq)
                    rows.append(jnp.sum(
                        new_vectors.astype(jnp.float32) ** 2, axis=-1))
                new_bufs, _, list_idx, sizes = _append_lists_multi(
                    tuple(bufs), tuple(rows), index.list_indices,
                    index.list_sizes, new_labels, new_indices)
                st.fence(new_bufs)
            list_data = new_bufs[0]
            data_sq = new_bufs[1] if len(new_bufs) > 1 else None
            centers = index.centers
            if index.adaptive_centers:
                # incremental drift: centers approximate list means, so the
                # updated mean is the size-weighted blend with the new rows
                # (reference: ivf_flat_build extend center update)
                new_sums = jax.ops.segment_sum(
                    new_vectors.astype(jnp.float32), new_labels,
                    num_segments=index.n_lists)
                blend = (centers * index.list_sizes[:, None] + new_sums
                         ) / jnp.maximum(needed, 1)[:, None]
                centers = jnp.where((new_counts > 0)[:, None], blend, centers)
                if index.metric == DistanceType.InnerProduct:
                    # spherical quantizer: keep the unit-norm invariant the
                    # build-time balanced k-means enforces
                    centers = centers / jnp.maximum(
                        jnp.linalg.norm(centers, axis=1, keepdims=True),
                        1e-12)
            out = Index(centers=centers, list_data=list_data,
                        list_indices=list_idx, list_sizes=sizes,
                        metric=index.metric,
                        adaptive_centers=index.adaptive_centers,
                        list_data_sq=data_sq)
            _mutate.next_generation(index, out)
            if index.canaries is not None:
                out.canaries = index.canaries
                _canary.auto_check(res, out, site="extend")
            return out

        # slow path: existing rows, flattened back out of the padded storage
        old_valid = index.list_indices >= 0
        old_labels = jnp.repeat(jnp.arange(index.n_lists, dtype=jnp.int32),
                                index.capacity)[old_valid.ravel()]
        old_vecs = index.list_data.reshape(-1, index.dim)[old_valid.ravel()]
        old_ids = index.list_indices.ravel()[old_valid.ravel()]

        all_vecs = jnp.concatenate([old_vecs, new_vectors.astype(
            index.list_data.dtype)], axis=0)
        all_ids = jnp.concatenate([old_ids, new_indices.astype(jnp.int32)])
        all_labels = jnp.concatenate([old_labels, new_labels])

        # +1 before rounding: a repack must never leave the fullest list
        # brim-full (max exactly on an alignment boundary), or the very
        # next one-row extend is forced back onto this O(n) path
        capacity = _round_up(max(int(jnp.max(needed)) + 1, _LIST_ALIGN),
                             _LIST_ALIGN)
        with obs.stage("ivf_flat.extend.pack") as st:
            list_data, list_idx, sizes = _pack_lists(
                all_vecs, all_labels, all_ids, index.n_lists, capacity)
            st.fence(list_data)

        centers = index.centers
        if index.adaptive_centers:
            # drift centers toward the new per-list means (reference:
            # ivf_flat_build extend updates centers when adaptive)
            sums = jax.ops.segment_sum(all_vecs.astype(jnp.float32),
                                       all_labels,
                                       num_segments=index.n_lists)
            means = sums / jnp.maximum(sizes, 1)[:, None]
            centers = jnp.where((sizes > 0)[:, None], means, centers)
            if index.metric == DistanceType.InnerProduct:
                centers = centers / jnp.maximum(
                    jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-12)

        out = Index(centers=centers, list_data=list_data,
                    list_indices=list_idx, list_sizes=sizes,
                    metric=index.metric,
                    adaptive_centers=index.adaptive_centers)
        _mutate.next_generation(index, out)
        if index.canaries is not None:
            out.canaries = index.canaries
            _canary.auto_check(res, out, site="extend")
        return out


def delete(res, index: Index, ids) -> Index:
    """Tombstone-delete rows by source id (the online mutation layer —
    see :mod:`raft_tpu.neighbors.mutate` for the encoding).

    Every slot whose id is in ``ids`` is rewritten in ``list_indices``
    to a tombstone; all scan paths (XLA and Pallas, fused included)
    already mask negative ids to the worst-distance sentinel, so
    deleted rows disappear from search results immediately at zero
    per-search cost.  Storage is reclaimed by :func:`compact`, not
    here.  Ids not present in the index match nothing.

    Returns a NEW index — the next generation — sharing every array
    except ``list_indices`` with its parent; readers pinned on the
    parent are unaffected.
    """
    with named_range("ivf_flat::delete"):
        ids = ensure_array(ids, "ids")
        expects(ids.ndim == 1, "ivf_flat.delete: 1-D ids required")
        new_li, _ = _mutate.tombstone(index.list_indices, ids)
        out = Index(centers=index.centers, list_data=index.list_data,
                    list_indices=new_li, list_sizes=index.list_sizes,
                    metric=index.metric,
                    adaptive_centers=index.adaptive_centers,
                    list_data_sq=index.list_data_sq)
        out.canaries = index.canaries
        _mutate.next_generation(index, out)
        if index.canaries is not None:
            _canary.auto_check(res, out, site="delete")
        return out


def upsert(res, index: Index, ids, vectors) -> Index:
    """Replace-or-insert rows under explicit source ids: tombstone any
    existing rows with these ids, then append ``vectors`` under the same
    ids — one logical mutation, ONE generation bump (a churn loop of
    upserts advances the counter like a single ``extend`` per batch, so
    generation-keyed caches see one swap, not two).  Ids not present
    simply insert; duplicate live ids are all tombstoned first, so each
    id resolves to exactly one live row."""
    with named_range("ivf_flat::upsert"):
        ids = ensure_array(ids, "ids")
        vectors = ensure_array(vectors, "vectors")
        expects(ids.ndim == 1 and ids.shape[0] == vectors.shape[0],
                "ivf_flat.upsert: ids must be 1-D, one per vector")
        parent_gen = _mutate.generation(index)
        out = extend(res, delete(res, index, ids), vectors,
                     new_indices=ids)
        out.generation = parent_gen + 1
        if obs.enabled():
            obs.registry().counter("ivf_flat.upserts").inc()
        return out


def compact(res, index: Index) -> Index:
    """Reclaim tombstoned slots: stable-partition each list's live rows
    to the front, drop every tombstone, and shrink the shared capacity
    to fit the fullest surviving list (aligned, with the same one-row
    headroom the extend repack keeps).  O(n_lists * capacity) — the
    rebalancer calls this past its dead-fraction threshold rather than
    on every delete.  Returns a new generation sharing ``centers`` with
    its parent."""
    with named_range("ivf_flat::compact"):
        order, sizes = _mutate.compaction_order(index.list_indices)
        max_size = int(jnp.max(sizes)) if index.n_lists else 0
        capacity = _round_up(max(max_size + 1, _LIST_ALIGN), _LIST_ALIGN)
        capacity = min(capacity, max(index.capacity, _LIST_ALIGN))

        li = jnp.take_along_axis(index.list_indices, order, axis=1)
        data = jnp.take_along_axis(index.list_data, order[:, :, None],
                                   axis=1)
        li, data = li[:, :capacity], data[:, :capacity]
        live = (jnp.arange(capacity, dtype=jnp.int32)[None, :]
                < sizes[:, None])
        li = jnp.where(live, li, -1)
        data = jnp.where(live[:, :, None], data, 0)
        data_sq = None
        if index.list_data_sq is not None:
            data_sq = jnp.take_along_axis(index.list_data_sq, order,
                                          axis=1)[:, :capacity]
            data_sq = jnp.where(live, data_sq, 0)

        out = Index(centers=index.centers, list_data=data,
                    list_indices=li, list_sizes=sizes,
                    metric=index.metric,
                    adaptive_centers=index.adaptive_centers,
                    list_data_sq=data_sq)
        out.canaries = index.canaries
        _mutate.next_generation(index, out)
        if index.canaries is not None:
            _canary.auto_check(res, out, site="compact")
        return out


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "metric",
                                             "recall_target", "exact"))
def _search_impl(centers, list_data, list_indices, queries, k, n_probes,
                 metric, recall_target=0.95, exact=False,
                 filter_words=None):
    nq = queries.shape[0]
    qf = queries.astype(jnp.float32)
    cf = centers.astype(jnp.float32)
    ip_metric = metric == DistanceType.InnerProduct

    # ---- coarse: pick n_probes lists per query (select_clusters analogue) --
    probes = _select_clusters(centers, queries, n_probes, metric,
                              recall_target=recall_target, exact=exact)

    # ---- fine: scan probed lists, hierarchical select --------------------
    # per-probe local top-k inside the scan + ONE final select over the
    # n_probes*k survivors (exact — probe lists are disjoint; same
    # restructure as ivf_pq._search_impl_recon, where the trace showed the
    # per-probe merge chain / single wide sort dominating)
    worst = -jnp.inf if ip_metric else jnp.inf
    q_sq = jnp.sum(qf * qf, axis=1)
    cap = list_data.shape[1]
    kt = min(k, cap)

    def probe_step(carry, p):
        alld, alli = carry
        lists = probes[:, p]                        # (q,)
        data = list_data[lists].astype(jnp.float32)  # (q, cap, d)
        ids = list_indices[lists]                   # (q, cap)
        ip = jnp.einsum("qd,qcd->qc", qf, data,
                        precision=get_matmul_precision())
        if ip_metric:
            d = jnp.where(ids >= 0, ip, worst)
        else:
            d_sq = jnp.sum(data * data, axis=-1)
            d = jnp.maximum(q_sq[:, None] + d_sq - 2.0 * ip, 0.0)
            d = jnp.where(ids >= 0, d, worst)
        if filter_words is not None:
            # admission fold through the tombstone seam: rejected rows
            # are worst before the per-probe top-kt
            adm = _fbits.query_bits(filter_words, jnp.arange(nq), ids)
            d = jnp.where(adm > 0, d, worst)
        td, ti = select_k(d, kt, in_idx=ids, select_min=not ip_metric)
        alld = jax.lax.dynamic_update_slice(alld, td, (0, p * kt))
        alli = jax.lax.dynamic_update_slice(alli, ti, (0, p * kt))
        return (alld, alli), None

    init = (jnp.full((nq, n_probes * kt), worst, jnp.float32),
            jnp.full((nq, n_probes * kt), -1, jnp.int32))
    (alld, alli), _ = jax.lax.scan(probe_step, init,
                                   jnp.arange(n_probes))
    from raft_tpu.neighbors import grouped
    return grouped.finalize_topk(
        alld, alli, nq, k, not ip_metric,
        metric in (DistanceType.L2SqrtExpanded,
                   DistanceType.L2SqrtUnexpanded), select_k)


def super_tile_factor(cap: int, n_lists: int, n_probes: int
                      ) -> Tuple[int, int]:
    """(F, n_lists_eff) for the small-cap super-tile scan: how many
    adjacent lists one tile reads.  The ONE owner of the gate —
    ``search()`` and the exactness test both derive tiling from here,
    so a threshold change cannot desynchronize them."""
    F = 1
    while (cap * F < 512 and F < 8
           and n_lists % 2 == 0 and n_lists > n_probes):
        F *= 2
        n_lists //= 2
    return F, n_lists


@functools.partial(jax.jit, static_argnames=("n_probes", "metric",
                                             "recall_target", "exact"))
def _select_clusters(centers, queries, n_probes, metric,
                     recall_target=0.95, exact=False):
    """Coarse top-``n_probes`` ranking (the select_clusters analogue).

    ``approx_max_k`` instead of ``top_k``: probe selection needs a good
    candidate SET, not an exact ranking — the TPU-native partial
    reduction measured 1.8x faster at (5000, 16384) with a 99.3%
    probe-set overlap (the ~0.7% swapped probes are the marginal ones,
    far below the recall noise floor).  On CPU it lowers to the exact
    select, so test assertions are unaffected.

    ``recall_target`` / ``exact`` come from ``SearchParams``
    (coarse_recall_target / exact_coarse).  When ``n_probes`` is within
    1/8 of ``n_lists`` the approx reduction is bypassed for ``lax.top_k``:
    its oversampled partial reduction degenerates to a full select there,
    so approx would cost the overlap loss for no speedup."""
    qf = queries.astype(jnp.float32)
    cf = centers.astype(jnp.float32)
    q_dot_c = jax.lax.dot_general(qf, cf, (((1,), (1,)), ((), ())),
                                  precision=get_matmul_precision(),
                                  preferred_element_type=jnp.float32)
    if metric == DistanceType.InnerProduct:
        score = q_dot_c
    else:
        c_sq = jnp.sum(cf * cf, axis=1)
        score = 2.0 * q_dot_c - c_sq[None, :]
    n_lists = centers.shape[0]
    if exact or n_probes >= n_lists - (n_lists // 8):
        _, probes = jax.lax.top_k(score, n_probes)
    else:
        _, probes = jax.lax.approx_max_k(score, n_probes,
                                         recall_target=recall_target)
    return probes


@functools.partial(jax.jit, static_argnames=("k", "metric", "n_groups",
                                             "block", "use_pallas",
                                             "pallas_interpret"))
def _search_impl_grouped(centers, list_data, list_indices, queries, probes,
                         k, metric, n_groups, block, list_data_sq=None,
                         use_pallas=False, pallas_interpret=False,
                         filter_words=None):
    """List-centric scan over fixed-size pair groups: each group is GROUP
    (query, probe) pairs of one list, so list vectors are read ~once and
    the distance block is a full batched MXU GEMM.  See
    :mod:`raft_tpu.neighbors.grouped` for the design; distances here are
    exact fp32 (same restructure as ivf_pq._search_impl_recon_grouped).
    On TPU the scan runs as the fused Pallas kernel
    (:mod:`raft_tpu.ops.pq_group_scan_pallas`, flat variant).
    """
    from raft_tpu.neighbors import grouped

    nq, n_probes = probes.shape
    P = nq * n_probes
    n_lists = centers.shape[0]
    cap = list_data.shape[1]
    dim = list_data.shape[2]
    ip_metric = metric == DistanceType.InnerProduct
    worst = -jnp.inf if ip_metric else jnp.inf

    qf = queries.astype(jnp.float32)
    q_sq = jnp.sum(qf * qf, axis=1)

    group_list, slot_pairs = grouped.build_groups(probes, n_lists, n_groups)
    # per-(slot, candidate) admission words in list-slot order — the
    # layout the kernel streams through VMEM; note list_indices here may
    # be the SUPER-TILED view (F*cap wide), which is exactly the layout
    # the kernel iterates, so the packing follows it for free
    adm_words = None
    if filter_words is not None:
        adm_words = _fbits.group_admission_words(
            filter_words, group_list, slot_pairs, list_indices, n_probes, P)

    kt = min(k, cap)
    if use_pallas:
        from raft_tpu.ops import pq_group_scan_pallas as pqp

        if pqp.supported(not ip_metric, cap, dim, kt, nq,
                         data_elem_bytes=4):
            d_sq = (list_data_sq if list_data_sq is not None
                    else jnp.sum(list_data.astype(jnp.float32) ** 2,
                                 axis=-1))
            vals, ti = pqp.grouped_flat_l2_scan(
                group_list, slot_pairs, qf, list_data, d_sq,
                list_indices, kt, n_probes, interpret=pallas_interpret,
                adm_words=adm_words)
            outd, outi = grouped.scatter_packed(vals, ti, slot_pairs, P,
                                                not ip_metric)
            return grouped.finalize_topk(
                outd, outi, nq, k, not ip_metric,
                metric in (DistanceType.L2SqrtExpanded,
                           DistanceType.L2SqrtUnexpanded), select_k)

    def distance_block(gl, slot):
        qid = jnp.where(slot < P, slot // n_probes, 0)
        qv = qf[qid]                                     # (B, G, d)
        data = list_data[gl].astype(jnp.float32)         # (B, cap, d)
        ids = list_indices[gl]
        ip = jnp.einsum("bqd,bcd->bqc", qv, data,
                        precision=get_matmul_precision())
        if ip_metric:
            d = ip
        else:
            d_sq = jnp.sum(data * data, axis=-1)         # (B, cap)
            d = jnp.maximum(q_sq[qid][:, :, None]
                            + d_sq[:, None, :] - 2.0 * ip, 0.0)
        d = jnp.where(ids[:, None, :] >= 0, d, worst)
        if filter_words is not None:
            adm = _fbits.query_bits(
                filter_words, qid, jnp.broadcast_to(ids[:, None, :],
                                                    d.shape))
            d = jnp.where(adm > 0, d, worst)
        return d, ids

    outd, outi = grouped.scan_and_scatter(
        group_list, slot_pairs, P, cap, k, not ip_metric, block,
        select_k, distance_block)
    return grouped.finalize_topk(
        outd, outi, nq, k, not ip_metric,
        metric in (DistanceType.L2SqrtExpanded,
                   DistanceType.L2SqrtUnexpanded), select_k)


@auto_convert_output
def search(res, params: SearchParams, index: Index, queries, k: int, *,
           filter=None) -> Tuple[jax.Array, jax.Array]:
    """Search the index (reference: ivf_flat.cuh:389).

    Returns ``(distances (q, k), indices (q, k) int32)``; unfilled slots
    (fewer than k valid candidates in the probed lists) carry id -1 and
    +inf / -inf distance, matching the reference's sentinel behavior.

    ``filter`` (a :class:`~raft_tpu.filters.SampleFilter` or an
    (nq, n_rows) bool mask) restricts each query's candidate set by
    source id; rejected rows fold to the worst-distance sentinel before
    every top-k (see docs/api.md, "Filtered search & tenancy").

    .. note:: the first TPU search mutates ``index`` in place, lazily
       attaching derived caches (``list_data_sq`` row norms, the group
       count and id-exactness caches).  ``list_data_sq`` is a pytree
       leaf, so the index's registered pytree structure changes from a
       ``None`` leaf to an array leaf — code that captured the index in
       a jitted closure before the first search will retrace once, and
       tree-structure comparisons across that boundary will differ.

    Queries pass through the boundary validator (see
    :mod:`raft_tpu.integrity.boundary`): under policy ``mask``,
    non-finite query rows return id -1 / worst distance instead of
    poisoning the batch.
    """
    queries = ensure_array(queries, "queries")
    queries, ok_rows = _boundary.check_matrix(
        queries, "queries", site="ivf_flat.search", dim=index.dim)
    # legacy shape guard: still fires when the validator policy is "off"
    expects(queries.ndim == 2 and queries.shape[1] == index.dim,
            "ivf_flat.search: query dim mismatch")
    dist, ids = _search_checked(res, params, index, queries, k,
                                filter=filter)
    if ok_rows is not None:
        dist, ids = _boundary.mask_search_outputs(
            dist, ids, ok_rows,
            select_min=index.metric != DistanceType.InnerProduct)
    return dist, ids


def _search_checked(res, params: SearchParams, index: Index, queries,
                    k: int, filter=None) -> Tuple[jax.Array, jax.Array]:
    with named_range("ivf_flat::search"):
        from raft_tpu.neighbors import grouped

        fw = _fbits.query_filter_words(filter, queries.shape[0],
                                       "ivf_flat.search")
        if fw is not None and obs.enabled():
            obs.registry().counter("ivf_flat.search.filtered").inc()
        n_probes = min(params.n_probes, index.n_lists)
        coarse_rt = getattr(params, "coarse_recall_target", 0.95)
        exact_coarse = getattr(params, "exact_coarse", False)
        if (isinstance(queries, jax.core.Tracer)
                or isinstance(index.centers, jax.core.Tracer)):
            # queries or the Index pytree traced by an outer jit/vmap:
            # use the fully traceable probe-order scan
            return _search_impl(index.centers, index.list_data,
                                index.list_indices, queries, k, n_probes,
                                index.metric, recall_target=coarse_rt,
                                exact=exact_coarse, filter_words=fw)
        with obs.stage("ivf_flat.search.coarse") as st:
            probes = _select_clusters(index.centers, queries, n_probes,
                                      index.metric, recall_target=coarse_rt,
                                      exact=exact_coarse)
            st.fence(probes)
        # the fused kernel's one-hot id contraction is f32 — require
        # every actual candidate id (incl. user-supplied extend ids)
        # to be f32-exact, not just the row count
        use_pallas = (jax.default_backend() == "tpu"
                      and grouped.ids_f32_exact(index, index.list_indices))
        if use_pallas and index.list_data_sq is None:
            # lazily attach the row-norm cache (stays on the index);
            # the XLA fallback recomputes row norms in its own fused
            # block, so attaching here would only force a retrace
            index.list_data_sq = jnp.sum(
                index.list_data.astype(jnp.float32) ** 2, axis=-1)

        # super-tiles: the fused scan's per-group cost is flat in cap
        # (~22 us measured at cap 160 AND 416, round 5), so small lists
        # — the nlist=16384 regime — fragment pairs into pure overhead.
        # Scan F adjacent lists per tile and dedupe per-query probes
        # that land in the same tile.
        cap = index.capacity
        F, n_lists_eff = super_tile_factor(cap, index.n_lists, n_probes)
        dsq = index.list_data_sq
        if F > 1:
            probes_eff = grouped.dedup_super_probes(probes, F,
                                                    n_lists_eff)
            data_eff = index.list_data.reshape(n_lists_eff, F * cap,
                                               index.dim)
            ids_eff = index.list_indices.reshape(n_lists_eff, F * cap)
            dsq_eff = (dsq.reshape(n_lists_eff, F * cap)
                       if dsq is not None else None)
            centers_eff = index.centers[::F]
        else:
            probes_eff, data_eff, ids_eff = probes, index.list_data, \
                index.list_indices
            dsq_eff, centers_eff = dsq, index.centers

        # static group capacity (round 10): the worst-case bound
        # ceil(P/G) + n_touched is exact-safe — no pair can drop at it —
        # so dispatch needs no host-synced group count, the shape is a
        # pure function of (nq, n_probes, n_lists_eff), and one warmed
        # executable serves every batch at the shape (the old
        # cached_groups ratchet recompiled on probe-distribution shift)
        n_groups, _ = grouped.group_capacity(
            queries.shape[0], n_probes, n_lists_eff)
        G = grouped.GROUP
        block = grouped.block_size(
            n_groups,
            G * F * cap * 8,            # fp32 distances + broadcast ids
            (F * cap + G) * index.dim * 4)  # data slice + query gather

        with obs.stage("ivf_flat.search.scan") as st:
            out = _search_impl_grouped(centers_eff, data_eff,
                                       ids_eff, queries, probes_eff,
                                       k, index.metric, n_groups, block,
                                       list_data_sq=dsq_eff,
                                       use_pallas=use_pallas,
                                       filter_words=fw)
            st.fence(out)
        return out


# ---------------------------------------------------------------------------
# serialization (reference: ivf_flat_serialize.cuh; version hard-checked)
# ---------------------------------------------------------------------------

# v2: trailing recall-canary block (nested envelope, may be absent)
_SERIALIZATION_VERSION = 2
_MIN_READ_VERSION = 1


def serialize(res, stream: BinaryIO, index: Index) -> None:
    """Versioned index dump (reference: detail/ivf_flat_serialize.cuh),
    wrapped in the CRC32 integrity envelope (core/serialize)."""
    with ser.enveloped_writer(stream) as body:
        ser.serialize_scalar(res, body, np.int32(_SERIALIZATION_VERSION))
        ser.serialize_scalar(res, body, np.int32(index.metric))
        ser.serialize_scalar(res, body, np.int32(index.adaptive_centers))
        for arr in (index.centers, index.list_data, index.list_indices,
                    index.list_sizes):
            ser.serialize_mdspan(res, body, arr)
        _canary.to_stream(res, body, index.canaries)


def deserialize(res, stream: BinaryIO) -> Index:
    """Truncated / bit-flipped streams raise
    :class:`~raft_tpu.core.serialize.CorruptIndexError` (CRC-checked
    envelope), never load as garbage arrays."""
    body = ser.open_envelope(stream)
    version = int(ser.deserialize_scalar(res, body))
    if not _MIN_READ_VERSION <= version <= _SERIALIZATION_VERSION:
        raise ValueError(
            f"ivf_flat serialization version mismatch: got {version}, "
            f"expected {_MIN_READ_VERSION}..{_SERIALIZATION_VERSION}")
    metric = int(ser.deserialize_scalar(res, body))
    adaptive = bool(ser.deserialize_scalar(res, body))
    arrays = [jnp.asarray(ser.deserialize_mdspan(res, body))
              for _ in range(4)]
    index = Index(*arrays, metric=metric, adaptive_centers=adaptive)
    if version >= 2:
        index.canaries = _canary.from_stream(res, body)
    return index


def save(res, filename: str, index: Index, *, retry_policy=None,
         deadline=None) -> None:
    """Atomic file dump (tmp + fsync + rename) with transient-IO retry —
    the filename overload of the reference's serialize, hardened."""
    from raft_tpu.resilience import _save_index
    _save_index("ivf_flat.save", lambda b: serialize(res, b, index),
                filename, retry_policy, deadline)


def load(res, filename: str, *, retry_policy=None, deadline=None) -> Index:
    """File-load overload; transient IO errors retry, corruption raises
    :class:`~raft_tpu.core.serialize.CorruptIndexError` immediately.

    Indexes carrying recall canaries are health-checked before being
    returned (see :func:`raft_tpu.integrity.health_check`)."""
    from raft_tpu.resilience import _load_index
    index = _load_index("ivf_flat.load", lambda b: deserialize(res, b),
                        filename, retry_policy, deadline)
    _canary.auto_check(res, index, site="load")
    return index
