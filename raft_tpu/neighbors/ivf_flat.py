"""IVF-Flat: inverted-file index over a balanced-k-means coarse quantizer.

Reference: raft/neighbors/ivf_flat.cuh:65 ``build``, :201 ``extend``, :389
``search``; types ivf_flat_types.hpp:44 (index_params), :76 (search_params),
:126 (index).  Build internals: detail/ivf_flat_build.cuh (kmeans_balanced fit
:336-339, predict + calc_centers_and_sizes :180-204); search:
detail/ivf_flat_search.cuh:670 ``interleaved_scan_kernel`` + select_k.

TPU design — the central impedance mismatch is the reference's *ragged*
inverted lists vs XLA's static shapes (SURVEY.md §7 "hard parts"):

- lists are stored **padded to one shared capacity** (rounded to a multiple of
  32, like the reference rounds list allocations — ivf_flat_types.hpp /
  ivf_list.hpp); slot validity comes from ``list_indices >= 0``;
- balanced k-means keeps the padding overhead bounded (that is *why* the
  reference uses a balanced quantizer: list occupancy = search cost);
- search scans the ``n_probes`` probed lists with a ``lax.scan``, each step
  one gathered (q, capacity, d) block → a batched-matmul distance + masked
  top-k merge.  The gather+matmul per probe is the TPU analogue of the
  interleaved-scan kernel: MXU does the FLOPs, the mask replaces list length.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import BinaryIO, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_types import KMeansBalancedParams
from raft_tpu.core import serialize as ser
from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.tracing import range as named_range
from raft_tpu.distance.types import DistanceType
from raft_tpu.matrix.select_k import select_k
from raft_tpu.utils.precision import get_matmul_precision
from raft_tpu.core.outputs import auto_convert_output

_LIST_ALIGN = 32  # reference: list sizes rounded to warp multiples (ivf_list.hpp)


@dataclasses.dataclass
class IndexParams:
    """Reference: ivf_flat_types.hpp:44 ``index_params``."""

    n_lists: int = 1024
    metric: int = DistanceType.L2Expanded
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    adaptive_centers: bool = False
    add_data_on_build: bool = True


@dataclasses.dataclass
class SearchParams:
    """Reference: ivf_flat_types.hpp:76 ``search_params``."""

    n_probes: int = 20


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Index:
    """Reference: ivf_flat_types.hpp:126 ``index`` (centers + per-list data
    + per-list source ids + sizes).  ``list_data`` is (n_lists, capacity, dim)
    with invalid slots zero; ``list_indices`` is (n_lists, capacity) int32
    with -1 marking empty slots."""

    centers: jax.Array          # (n_lists, dim) f32
    list_data: jax.Array        # (n_lists, capacity, dim)
    list_indices: jax.Array     # (n_lists, capacity) int32
    list_sizes: jax.Array       # (n_lists,) int32
    metric: int = DistanceType.L2Expanded
    adaptive_centers: bool = False

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def capacity(self) -> int:
        return self.list_data.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_sizes))

    def tree_flatten(self):
        leaves = (self.centers, self.list_data, self.list_indices,
                  self.list_sizes)
        return leaves, (self.metric, self.adaptive_centers)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, metric=aux[0], adaptive_centers=aux[1])


def _round_up(x: int, align: int) -> int:
    return -(-x // align) * align


def _pack_lists(dataset: jax.Array, labels: jax.Array, source_ids: jax.Array,
                n_lists: int, capacity: int
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Scatter rows into padded per-list storage.

    The TPU analogue of the reference's list layout + fill kernels
    (detail/ivf_flat_build.cuh; codepacking in ivf_pq does the same dance):
    sort by label, compute each row's rank within its list, one scatter.
    """
    n = dataset.shape[0]
    order = jnp.argsort(labels)
    sorted_labels = labels[order]
    sizes = jax.ops.segment_sum(jnp.ones(n, jnp.int32), labels,
                                num_segments=n_lists)
    starts = jnp.cumsum(sizes) - sizes
    rank = jnp.arange(n) - starts[sorted_labels]
    list_data = jnp.zeros((n_lists, capacity, dataset.shape[1]),
                          dataset.dtype)
    list_idx = jnp.full((n_lists, capacity), -1, jnp.int32)
    list_data = list_data.at[sorted_labels, rank].set(dataset[order])
    list_idx = list_idx.at[sorted_labels, rank].set(
        source_ids[order].astype(jnp.int32))
    return list_data, list_idx, sizes


def build(res, params: IndexParams, dataset) -> Index:
    """Build an IVF-Flat index (reference: ivf_flat.cuh:65).

    Trains the balanced coarse quantizer on a subsample
    (``kmeans_trainset_fraction``, as detail/ivf_flat_build.cuh:336), then
    assigns and packs all rows.
    """
    with named_range("ivf_flat::build"):
        dataset = ensure_array(dataset, "dataset")
        expects(dataset.ndim == 2, "ivf_flat.build: 2-D dataset required")
        n, dim = dataset.shape
        expects(params.n_lists <= n, "ivf_flat.build: n_lists > n_rows")

        n_train = max(params.n_lists,
                      int(n * params.kmeans_trainset_fraction))
        if n_train < n:
            key = res.next_key()
            sel = jax.random.choice(key, n, (n_train,), replace=False)
            trainset = dataset[sel]
        else:
            trainset = dataset
        bal = KMeansBalancedParams(n_iters=params.kmeans_n_iters,
                                   metric=params.metric
                                   if params.metric == DistanceType.InnerProduct
                                   else DistanceType.L2Expanded)
        centers = kmeans_balanced.fit(res, bal, trainset, params.n_lists)

        index = Index(centers=centers,
                      list_data=jnp.zeros((params.n_lists, _LIST_ALIGN, dim),
                                          dataset.dtype),
                      list_indices=jnp.full((params.n_lists, _LIST_ALIGN), -1,
                                            jnp.int32),
                      list_sizes=jnp.zeros(params.n_lists, jnp.int32),
                      metric=params.metric,
                      adaptive_centers=params.adaptive_centers)
        if params.add_data_on_build:
            index = extend(res, index, dataset,
                           jnp.arange(n, dtype=jnp.int32))
        return index


def extend(res, index: Index, new_vectors, new_indices=None) -> Index:
    """Add vectors to an index (reference: ivf_flat.cuh:201 ``extend``).

    Rebuilds the padded list storage at the new capacity (the reference
    reallocates lists that outgrow their capacity too — ivf_list.hpp); the
    coarse centers optionally drift when ``adaptive_centers`` is set
    (ivf_flat_types.hpp adaptive_centers semantics).
    """
    with named_range("ivf_flat::extend"):
        new_vectors = ensure_array(new_vectors, "new_vectors")
        expects(new_vectors.ndim == 2 and new_vectors.shape[1] == index.dim,
                "ivf_flat.extend: dim mismatch")
        n_new = new_vectors.shape[0]
        if new_indices is None:
            new_indices = index.size + jnp.arange(n_new, dtype=jnp.int32)
        else:
            new_indices = ensure_array(new_indices, "new_indices")

        bal = KMeansBalancedParams(metric=index.metric
                                   if index.metric == DistanceType.InnerProduct
                                   else DistanceType.L2Expanded)
        new_labels = kmeans_balanced.predict(res, bal, new_vectors,
                                             index.centers)

        # existing rows, flattened back out of the padded storage
        old_valid = index.list_indices >= 0
        old_labels = jnp.repeat(jnp.arange(index.n_lists, dtype=jnp.int32),
                                index.capacity)[old_valid.ravel()]
        old_vecs = index.list_data.reshape(-1, index.dim)[old_valid.ravel()]
        old_ids = index.list_indices.ravel()[old_valid.ravel()]

        all_vecs = jnp.concatenate([old_vecs, new_vectors.astype(
            index.list_data.dtype)], axis=0)
        all_ids = jnp.concatenate([old_ids, new_indices.astype(jnp.int32)])
        all_labels = jnp.concatenate([old_labels, new_labels])

        sizes = jax.ops.segment_sum(
            jnp.ones(all_labels.shape[0], jnp.int32), all_labels,
            num_segments=index.n_lists)
        capacity = _round_up(max(int(jnp.max(sizes)), _LIST_ALIGN),
                             _LIST_ALIGN)
        list_data, list_idx, sizes = _pack_lists(
            all_vecs, all_labels, all_ids, index.n_lists, capacity)

        centers = index.centers
        if index.adaptive_centers:
            # drift centers toward the new per-list means (reference:
            # ivf_flat_build extend updates centers when adaptive)
            sums = jax.ops.segment_sum(all_vecs.astype(jnp.float32),
                                       all_labels,
                                       num_segments=index.n_lists)
            means = sums / jnp.maximum(sizes, 1)[:, None]
            centers = jnp.where((sizes > 0)[:, None], means, centers)

        return Index(centers=centers, list_data=list_data,
                     list_indices=list_idx, list_sizes=sizes,
                     metric=index.metric,
                     adaptive_centers=index.adaptive_centers)


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "metric"))
def _search_impl(centers, list_data, list_indices, queries, k, n_probes,
                 metric):
    nq = queries.shape[0]
    qf = queries.astype(jnp.float32)
    cf = centers.astype(jnp.float32)
    ip_metric = metric == DistanceType.InnerProduct

    # ---- coarse: pick n_probes lists per query (select_clusters analogue) --
    q_dot_c = jax.lax.dot_general(qf, cf, (((1,), (1,)), ((), ())),
                                  precision=get_matmul_precision(),
                                  preferred_element_type=jnp.float32)
    if ip_metric:
        coarse = q_dot_c
        _, probes = jax.lax.top_k(coarse, n_probes)
    else:
        c_sq = jnp.sum(cf * cf, axis=1)
        coarse = c_sq[None, :] - 2.0 * q_dot_c  # + q² is rank-invariant
        _, probes = jax.lax.top_k(-coarse, n_probes)

    # ---- fine: scan probed lists, hierarchical select --------------------
    # per-probe local top-k inside the scan + ONE final select over the
    # n_probes*k survivors (exact — probe lists are disjoint; same
    # restructure as ivf_pq._search_impl_recon, where the trace showed the
    # per-probe merge chain / single wide sort dominating)
    worst = -jnp.inf if ip_metric else jnp.inf
    q_sq = jnp.sum(qf * qf, axis=1)
    cap = list_data.shape[1]
    kt = min(k, cap)

    def probe_step(carry, p):
        alld, alli = carry
        lists = probes[:, p]                        # (q,)
        data = list_data[lists].astype(jnp.float32)  # (q, cap, d)
        ids = list_indices[lists]                   # (q, cap)
        ip = jnp.einsum("qd,qcd->qc", qf, data,
                        precision=get_matmul_precision())
        if ip_metric:
            d = jnp.where(ids >= 0, ip, worst)
        else:
            d_sq = jnp.sum(data * data, axis=-1)
            d = jnp.maximum(q_sq[:, None] + d_sq - 2.0 * ip, 0.0)
            d = jnp.where(ids >= 0, d, worst)
        td, ti = select_k(d, kt, in_idx=ids, select_min=not ip_metric)
        alld = jax.lax.dynamic_update_slice(alld, td, (0, p * kt))
        alli = jax.lax.dynamic_update_slice(alli, ti, (0, p * kt))
        return (alld, alli), None

    init = (jnp.full((nq, n_probes * kt), worst, jnp.float32),
            jnp.full((nq, n_probes * kt), -1, jnp.int32))
    (alld, alli), _ = jax.lax.scan(probe_step, init,
                                   jnp.arange(n_probes))
    kf = min(k, n_probes * kt)
    best_d, best_i = select_k(alld, kf, in_idx=alli,
                              select_min=not ip_metric)
    if kf < k:
        best_d = jnp.pad(best_d, ((0, 0), (0, k - kf)),
                         constant_values=worst)
        best_i = jnp.pad(best_i, ((0, 0), (0, k - kf)),
                         constant_values=-1)
    if metric in (DistanceType.L2SqrtExpanded, DistanceType.L2SqrtUnexpanded):
        best_d = jnp.sqrt(jnp.maximum(best_d, 0.0))
    return best_d, best_i


@auto_convert_output
def search(res, params: SearchParams, index: Index, queries, k: int
           ) -> Tuple[jax.Array, jax.Array]:
    """Search the index (reference: ivf_flat.cuh:389).

    Returns ``(distances (q, k), indices (q, k) int32)``; unfilled slots
    (fewer than k valid candidates in the probed lists) carry id -1 and
    +inf / -inf distance, matching the reference's sentinel behavior.
    """
    with named_range("ivf_flat::search"):
        queries = ensure_array(queries, "queries")
        expects(queries.ndim == 2 and queries.shape[1] == index.dim,
                "ivf_flat.search: query dim mismatch")
        n_probes = min(params.n_probes, index.n_lists)
        return _search_impl(index.centers, index.list_data,
                            index.list_indices, queries, k, n_probes,
                            index.metric)


# ---------------------------------------------------------------------------
# serialization (reference: ivf_flat_serialize.cuh; version hard-checked)
# ---------------------------------------------------------------------------

_SERIALIZATION_VERSION = 1


def serialize(res, stream: BinaryIO, index: Index) -> None:
    """Versioned index dump (reference: detail/ivf_flat_serialize.cuh)."""
    ser.serialize_scalar(res, stream, np.int32(_SERIALIZATION_VERSION))
    ser.serialize_scalar(res, stream, np.int32(index.metric))
    ser.serialize_scalar(res, stream, np.int32(index.adaptive_centers))
    for arr in (index.centers, index.list_data, index.list_indices,
                index.list_sizes):
        ser.serialize_mdspan(res, stream, arr)


def deserialize(res, stream: BinaryIO) -> Index:
    version = int(ser.deserialize_scalar(res, stream))
    if version != _SERIALIZATION_VERSION:
        raise ValueError(
            f"ivf_flat serialization version mismatch: got {version}, "
            f"expected {_SERIALIZATION_VERSION}")  # reference hard-fails too
    metric = int(ser.deserialize_scalar(res, stream))
    adaptive = bool(ser.deserialize_scalar(res, stream))
    arrays = [jnp.asarray(ser.deserialize_mdspan(res, stream))
              for _ in range(4)]
    return Index(*arrays, metric=metric, adaptive_centers=adaptive)
