"""Nearest-neighbor search — the flagship layer.

Reference: cpp/include/raft/neighbors/ (SURVEY.md §2.6) — brute-force kNN
(+ partitioned-result merge), IVF-Flat, IVF-PQ, CAGRA, refinement, ball cover,
epsilon neighborhood, and versioned index serialization.
"""

from raft_tpu.neighbors import ball_cover  # noqa: F401
from raft_tpu.neighbors import brute_force  # noqa: F401
from raft_tpu.neighbors import ivf_flat  # noqa: F401
from raft_tpu.neighbors import ivf_pq  # noqa: F401
from raft_tpu.neighbors.brute_force import knn, knn_merge_parts  # noqa: F401
from raft_tpu.neighbors.refine import refine  # noqa: F401
from raft_tpu.neighbors.epsilon_neighborhood import (  # noqa: F401
    eps_neighbors_l2sq,
)
