"""Public IVF-PQ list-data helpers.

Reference: raft/neighbors/ivf_pq_helpers.cuh — the tuning/inspection
surface over a built index's per-list storage: ``unpack_list_data``
(codes out of the bit-packed list layout), ``pack_list_data`` (codes
back in), and ``reconstruct_list_data`` (decode codes to approximate
dataset vectors).  The reference operates in-place on device buffers;
here the pack path returns the functionally-updated :class:`Index`
(JAX arrays are immutable) with its derived reconstruction caches kept
consistent.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.core.outputs import auto_convert_output
from raft_tpu.neighbors import mutate as _mutate
from raft_tpu.neighbors.ivf_pq import (
    Index,
    _decode_rows,
    _pack_codes,
    _recon_sq,
    _unpack_codes,
)


def _row_bounds(index: Index, label: int, offset: int,
                n_rows: Optional[int]) -> int:
    expects(0 <= label < index.n_lists,
            "ivf_pq_helpers: list label out of range")
    size = int(index.list_sizes[label])
    expects(0 <= offset <= size,
            f"ivf_pq_helpers: offset {offset} > list size {size}")
    if n_rows is None:
        n_rows = size - offset
    expects(offset + n_rows <= size,
            f"ivf_pq_helpers: offset+n_rows {offset + n_rows} > list "
            f"size {size}")
    return n_rows


@auto_convert_output
def unpack_list_data(res, index: Index, label: int, *, offset: int = 0,
                     n_rows: Optional[int] = None) -> jax.Array:
    """Flat (n_rows, pq_dim) uint8 codes of one list, starting at
    ``offset`` (reference: ivf_pq_helpers.cuh ``unpack_list_data`` /
    ``unpack_contiguous_list_data``)."""
    n_rows = _row_bounds(index, label, offset, n_rows)
    packed = jax.lax.dynamic_slice_in_dim(index.list_codes[label], offset,
                                          n_rows, axis=0)
    return _unpack_codes(packed, index.pq_dim, index.pq_bits)


def pack_list_data(res, index: Index, label: int, codes, *,
                   offset: int = 0) -> Index:
    """Write flat (n_rows, pq_dim) uint8 codes into one list at
    ``offset`` (reference: ivf_pq_helpers.cuh ``pack_list_data``);
    returns the updated index.  The rows must already exist (this edits
    codes in place; use ``extend`` to add rows).  The bf16
    reconstruction cache, when attached, is re-decoded for the edited
    rows so searches stay consistent."""
    codes = ensure_array(codes, "codes")
    expects(codes.ndim == 2 and codes.shape[1] == index.pq_dim,
            "ivf_pq_helpers.pack_list_data: (n_rows, pq_dim) codes "
            "required")
    n_rows = codes.shape[0]
    _row_bounds(index, label, offset, n_rows)
    packed = _pack_codes(codes.astype(jnp.uint8), index.pq_bits)
    upd = {"list_codes": index.list_codes.at[
        label, offset:offset + n_rows].set(packed)}
    if index.list_recon is not None:
        labels = jnp.full((n_rows,), label, jnp.int32)
        recon = _decode_rows(index.codebooks, codes.astype(jnp.uint8),
                             labels, index.codebook_kind)
        upd["list_recon"] = index.list_recon.at[
            label, offset:offset + n_rows].set(recon)
        if index.list_recon_sq is not None:
            upd["list_recon_sq"] = index.list_recon_sq.at[
                label, offset:offset + n_rows].set(
                    _recon_sq(recon[None])[0])
    return _mutate.next_generation(index,
                                   dataclasses.replace(index, **upd))


@auto_convert_output
def reconstruct_list_data(res, index: Index, label: int, *,
                          offset: int = 0,
                          n_rows: Optional[int] = None) -> jax.Array:
    """Decode one list's codes back to approximate dataset vectors
    (n_rows, dim) float32 (reference: ivf_pq_helpers.cuh
    ``reconstruct_list_data``): residual reconstruction + list center,
    rotated back through the orthonormal transform."""
    n_rows = _row_bounds(index, label, offset, n_rows)
    codes = unpack_list_data.__wrapped__(res, index, label, offset=offset,
                                         n_rows=n_rows)
    labels = jnp.full((n_rows,), label, jnp.int32)
    recon = _decode_rows(index.codebooks, codes, labels,
                         index.codebook_kind).astype(jnp.float32)
    x_rot = recon + index.centers[label][None, :]
    return x_rot @ index.rotation.T
