"""Epsilon neighborhood: all pairs within distance eps.

Reference: raft/neighbors/epsilon_neighborhood.cuh:121
``epsUnexpL2SqNeighborhood`` — boolean adjacency of ``||x - y||^2 < eps^2``
plus per-row neighbor counts (vertex degrees), used by DBSCAN-style
algorithms.

TPU design: the (m, n) squared-L2 block is one MXU gemm + epilogue; the
comparison and degree reduction fuse into it.  For large m the caller tiles
rows (the adjacency output itself is O(m·n) either way, as in the reference).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.mdarray import ensure_array
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.integrity import boundary as _boundary
from raft_tpu.distance.types import DistanceType
from raft_tpu.core.outputs import raw


def eps_neighbors_l2sq(
    res,
    x,
    y,
    eps_sq: float,
) -> Tuple[jax.Array, jax.Array]:
    """Adjacency (m, n) bool of ``||x_i - y_j||^2 < eps_sq`` + degrees (m,).

    Reference: epsilon_neighborhood.cuh:121 (adj + vd outputs; vd's last
    element there is the total count — we return degrees only, total is
    ``degrees.sum()``).
    """
    x = ensure_array(x, "x")
    y = ensure_array(y, "y")
    x, ok_rows = _boundary.check_matrix(x, "x",
                                        site="eps_neighbors_l2sq")
    y, _ = _boundary.check_matrix(y, "y", site="eps_neighbors_l2sq")
    d = raw(pairwise_distance)(x, y, DistanceType.L2Unexpanded)
    adj = d < eps_sq
    if ok_rows is not None:
        # masked x rows report no neighbors rather than eps-balls around 0
        adj = adj & ok_rows[:, None]
    return adj, jnp.sum(adj, axis=1).astype(jnp.int32)
