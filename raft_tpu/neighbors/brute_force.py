"""Brute-force (exact) k-nearest-neighbor search.

Reference: raft/neighbors/brute_force.cuh:150 ``knn`` (tiled pairwise distance
+ select_k, detail/knn_brute_force.cuh) and :80 ``knn_merge_parts``
(merge of row-partitioned kNN results, detail/knn_merge_parts.cuh); the fused
L2 kNN kernel lives at spatial/knn/detail/fused_l2_knn.cuh.

TPU design: a ``lax.scan`` over database tiles.  Each step computes one
(n_queries, tile_n) distance block — a single MXU gemm + fused epilogue for
the expanded metrics — takes the block's local top-k, and merges it into the
running top-k by re-selecting over the 2k concatenated candidates.  HBM
traffic is O(q·d + n·d + q·k) and peak memory O(q·tile_n), the same bound the
reference's tiling buys (detail/knn_brute_force.cuh tiles queries×db).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.error import expects
from raft_tpu.core.mdarray import ensure_array
from raft_tpu.integrity import boundary as _boundary
from raft_tpu.core.tracing import range as named_range
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.distance.types import DistanceType
from raft_tpu.filters import bitset as _fbits
from raft_tpu.matrix.select_k import merge_topk, select_k
from raft_tpu.core.outputs import auto_convert_output, raw

_TILE_N = 8192


@functools.partial(jax.jit, static_argnames=("k", "metric", "tile_n"))
def _knn_impl(database, queries, k, metric, metric_arg, tile_n,
              filter_words=None, id_offset=None):
    n, dim = database.shape
    nq = queries.shape[0]
    select_min = metric != DistanceType.InnerProduct
    n_tiles = -(-n // tile_n)
    padded = n_tiles * tile_n
    db = jnp.pad(database, ((0, padded - n), (0, 0)))
    db_tiles = db.reshape(n_tiles, tile_n, dim)

    worst = jnp.inf if select_min else -jnp.inf
    init = (jnp.full((nq, k), worst, jnp.float32),
            jnp.full((nq, k), -1, jnp.int32))

    def step(carry, xs):
        best_d, best_i = carry
        tile, t = xs
        d = pairwise_distance(queries, tile, metric,
                              metric_arg=metric_arg).astype(jnp.float32)
        valid = (t * tile_n + jnp.arange(tile_n)) < n
        d = jnp.where(valid[None, :], d, worst)
        if filter_words is not None:
            # admission by GLOBAL id: row j of tile t is global id
            # id_offset + t*tile_n + j — the id space the filter (and a
            # sharded caller's global_id_offset) declares
            gids = (id_offset + t * tile_n
                    + jnp.arange(tile_n, dtype=jnp.int32))
            adm = _fbits.query_bits(
                filter_words, jnp.arange(nq),
                jnp.broadcast_to(gids[None, :], (nq, tile_n)))
            d = jnp.where(adm > 0, d, worst)
        kt = min(k, tile_n)
        td, ti = select_k(d, kt, select_min=select_min)
        ti = ti.astype(jnp.int32) + t * tile_n
        return merge_topk(best_d, best_i, td, ti,
                          select_min=select_min), None

    (best_d, best_i), _ = jax.lax.scan(
        step, init, (db_tiles, jnp.arange(n_tiles)))
    if filter_words is not None:
        # a query can now run out of admissible rows: surface the
        # (worst, -1) sentinel rather than a positional id
        best_i = jnp.where(best_d == worst, -1, best_i)
    return best_d, best_i


@auto_convert_output
def knn(
    res,
    database,
    queries,
    k: int,
    *,
    metric: int = DistanceType.L2Unexpanded,
    metric_arg: float = 2.0,
    global_id_offset: int = 0,
    tile_n: int = _TILE_N,
    filter=None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN of ``queries`` (q, d) against ``database`` (n, d).

    Reference: neighbors/brute_force.cuh:150 ``knn``.  Returns
    ``(distances (q, k), indices (q, k) int32)`` sorted best-first;
    ``global_id_offset`` shifts returned ids (the reference's translation
    argument for row-partitioned databases).

    ``filter`` restricts the scan to admitted rows: a
    :class:`raft_tpu.filters.SampleFilter` (or an (q, n) bool mask)
    whose bit ``j`` admits GLOBAL id ``j`` — i.e. ids *after* the
    ``global_id_offset`` shift, so a sharded caller can broadcast one
    filter over the whole logical id space.  Slots with no admissible
    row come back as ``(worst, -1)``.
    """
    with named_range("brute_force::knn"):
        database = ensure_array(database, "database")
        queries = ensure_array(queries, "queries")
        expects(database.ndim == 2 and queries.ndim == 2
                and database.shape[1] == queries.shape[1],
                "knn: (n,d) database and (q,d) queries required")
        expects(0 < k <= database.shape[0], "knn: need 0 < k <= n")
        queries, ok_rows = _boundary.check_matrix(
            queries, "queries", site="brute_force.knn",
            dim=database.shape[1])
        fw = _fbits.query_filter_words(
            filter, queries.shape[0], "brute_force.knn")
        tile = min(tile_n, database.shape[0])
        d, i = _knn_impl(database, queries, k, metric, metric_arg, tile,
                         filter_words=fw,
                         id_offset=jnp.int32(global_id_offset)
                         if fw is not None else None)
        if global_id_offset:
            # -1 is the starved-slot sentinel under filtering; keep it
            i = jnp.where(i >= 0, i + global_id_offset, i)
        if ok_rows is not None:
            d, i = _boundary.mask_search_outputs(
                d, i, ok_rows,
                select_min=metric != DistanceType.InnerProduct)
        return d, i


@auto_convert_output
def knn_merge_parts(
    in_keys: jax.Array,
    in_values: jax.Array,
    *,
    n_samples: Optional[int] = None,
    translations: Optional[jax.Array] = None,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Merge kNN results from row-partitioned database parts.

    Reference: neighbors/brute_force.cuh:80 ``knn_merge_parts``
    (detail/knn_merge_parts.cuh) — the scale-out seam for sharded search:
    each of ``n_parts`` shards contributes a (q, k) result; the merge is a
    top-k over the union with per-part id translations.

    ``in_keys``/``in_values``: (n_parts, q, k) distances / indices.
    ``translations``: optional (n_parts,) id offsets (defaults to the
    reference's uniform-partition offsets ``part * n_samples``).
    """
    expects(in_keys.ndim == 3 and in_values.shape == in_keys.shape,
            "knn_merge_parts: (n_parts, q, k) inputs required")
    n_parts, nq, k = in_keys.shape
    # id dtype follows the caller's index dtype: int32 by default (JAX's
    # default int), int64 when the caller passes int64 ids with x64 enabled —
    # silently requesting int64 under x64-disabled JAX would truncate.
    idx_t = in_values.dtype
    if translations is None:
        expects(n_samples is not None,
                "knn_merge_parts: need n_samples or translations")
        expects(np.int64(n_parts - 1) * np.int64(n_samples)
                <= np.iinfo(idx_t).max,
                "knn_merge_parts: global ids overflow the index dtype; pass "
                "int64 in_values (with jax x64 enabled) or explicit "
                "translations")
        translations = jnp.arange(n_parts, dtype=idx_t) * n_samples
    else:
        translations = translations.astype(idx_t)
    ids = in_values + translations[:, None, None]
    keys = jnp.transpose(in_keys, (1, 0, 2)).reshape(nq, n_parts * k)
    vals = jnp.transpose(ids, (1, 0, 2)).reshape(nq, n_parts * k)
    return raw(select_k)(keys, k, in_idx=vals, select_min=select_min)


def tiled_brute_force_knn(res, database, queries, k, **kw):
    """Alias for :func:`knn` (reference: detail/knn_brute_force.cuh
    ``tiled_brute_force_knn`` — tiling is always on here)."""
    return knn(res, database, queries, k, **kw)
