"""Random ball cover (RBC) nearest-neighbor search.

API parity with ``raft::neighbors::ball_cover``
(`/root/reference/cpp/include/raft/neighbors/ball_cover.cuh:62` —
``build_index``, ``:112`` — ``all_knn_query``, ``:259`` — ``knn_query``;
index type ``ball_cover_types.hpp`` — ``BallCoverIndex``; impl
``spatial/knn/detail/ball_cover.cuh``).  RBC (Cayton) samples ~sqrt(n)
random landmarks, assigns every point to its closest landmark ball, and uses
the triangle inequality ``d(q, x) >= d(q, L) - radius(L)`` to skip whole
balls during search.

TPU-native design (vs the reference's warp-level registers + sorted-ball
kernels): balls are **padded static lists** (same layout as IVF-Flat —
``ivf_flat._pack_lists``), and search probes balls in ascending
query-to-landmark-distance order in fixed-size chunks inside a
``lax.while_loop``.  A per-query suffix minimum of
``d(q, L_j) - weight * radius_j`` over the remaining (sorted) balls gives an
exact, O(1)-per-step termination test: once the suffix bound exceeds the
running k-th distance, no unprobed ball can contain a closer point, which is
precisely the reference's post-filtering guarantee expressed as a loop bound
instead of a second filtered pass.  ``weight < 1`` shrinks radii (fewer
probes, approximate) exactly as documented at ball_cover.cuh:102-110.

Unlike the reference (2-D/3-D only, ball_cover.cuh:66), any dimensionality is
supported for the L2 metrics; haversine requires 2-D (lat, lon) radians.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.error import expects
from ..core.mdarray import ensure_array
from ..integrity import boundary as _boundary
from ..core.outputs import auto_convert_output
from ..core.tracing import range as named_range
from ..distance.types import DistanceType, resolve_metric
from ..matrix.select_k import merge_topk, select_k
from ..utils.precision import get_matmul_precision
from .ivf_flat import _pack_lists, _round_up

_SUPPORTED = (DistanceType.Haversine, DistanceType.L2SqrtExpanded,
              DistanceType.L2SqrtUnexpanded, DistanceType.L2Expanded,
              DistanceType.L2Unexpanded)


def _haversine(x: jax.Array, y: jax.Array) -> jax.Array:
    """Pointwise haversine over broadcastable (..., 2) radians arrays."""
    dlat = 0.5 * (x[..., 0] - y[..., 0])
    dlon = 0.5 * (x[..., 1] - y[..., 1])
    a = jnp.sin(dlat) ** 2 + jnp.cos(x[..., 0]) * jnp.cos(y[..., 0]) \
        * jnp.sin(dlon) ** 2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


def _sqrt_metric(metric: DistanceType) -> bool:
    return metric in (DistanceType.Haversine, DistanceType.L2SqrtExpanded,
                      DistanceType.L2SqrtUnexpanded)


def _cross_dist(q: jax.Array, pts: jax.Array, metric: DistanceType
                ) -> jax.Array:
    """(nq, d) x (m, d) -> (nq, m) in REAL distance units — always sqrt for
    the L2 family, regardless of the metric's output form.  Triangle-
    inequality pruning (``d(q,L) - r``) is only a valid lower bound in real
    units: in squared units ``d² - r²`` over-prunes and drops true
    neighbors.  Output conversion back to squared form happens at the end
    of the query (a monotone map, so top-k order is unaffected)."""
    if metric == DistanceType.Haversine:
        return _haversine(q[:, None, :], pts[None, :, :])
    ip = jax.lax.dot_general(q, pts, (((1,), (1,)), ((), ())),
                             precision=get_matmul_precision(),
                             preferred_element_type=jnp.float32)
    d = jnp.maximum(jnp.sum(q * q, axis=1)[:, None]
                    + jnp.sum(pts * pts, axis=1)[None, :] - 2.0 * ip, 0.0)
    return jnp.sqrt(d)


class BallCoverIndex:
    """``BallCoverIndex`` analogue (reference ball_cover_types.hpp).

    Built state: ``landmarks (L, d)``, padded ball storage
    ``list_data (L, cap, d)`` / ``list_indices (L, cap)``, per-ball
    ``radii (L,)`` (in triangle-comparable units — real distance).
    """

    def __init__(self, handle, X, metric=DistanceType.L2SqrtExpanded,
                 n_landmarks: Optional[int] = None):
        X = ensure_array(X, "X")
        expects(X.ndim == 2, "BallCoverIndex: X must be (n, d)")
        metric = resolve_metric(metric)
        expects(metric in _SUPPORTED,
                f"ball_cover: unsupported metric {metric}")
        if metric == DistanceType.Haversine:
            expects(X.shape[1] == 2, "haversine needs (lat, lon) columns")
        self._handle = handle
        self.X = X
        self.metric = metric
        self.n = X.shape[0]
        self.dim = X.shape[1]
        self.n_landmarks = int(n_landmarks or
                               max(1, int(math.ceil(math.sqrt(self.n)))))
        self.trained = False
        self.landmarks = None
        self.list_data = None
        self.list_indices = None
        self.radii = None


def build_index(res, index: BallCoverIndex) -> BallCoverIndex:
    """Sample landmarks, assign every point to its closest ball, compute
    radii (reference ball_cover.cuh:62 ``build_index`` →
    detail ``rbc_build_index``)."""
    with named_range("ball_cover::build_index"):
        expects(not index.trained, "index already built")
        X = index.X.astype(jnp.float32)
        X, _ = _boundary.check_matrix(X, "X", site="ball_cover.build_index",
                                      allow_empty=False)
        n, L = index.n, index.n_landmarks
        # uniform random landmark sample — the "random" in random ball cover
        perm = jax.random.permutation(res.next_key(), n)[:L]
        landmarks = X[perm]
        d = _cross_dist(X, landmarks, index.metric)        # (n, L)
        labels = jnp.argmin(d, axis=1).astype(jnp.int32)
        member_d = jnp.take_along_axis(d, labels[:, None], axis=1)[:, 0]
        radii = jnp.zeros((L,), jnp.float32).at[labels].max(member_d)
        sizes = jax.ops.segment_sum(jnp.ones(n, jnp.int32), labels,
                                    num_segments=L)
        capacity = _round_up(max(1, int(jnp.max(sizes))), 32)
        list_data, list_idx, _ = _pack_lists(
            X, labels, jnp.arange(n, dtype=jnp.int32), L, capacity)
        index.landmarks = landmarks
        index.list_data = list_data
        index.list_indices = list_idx
        index.radii = radii
        index.trained = True
        return index


@functools.partial(jax.jit,
                   static_argnames=("k", "metric", "chunk", "max_chunks",
                                    "post_filter"))
def _query_impl(landmarks, radii, list_data, list_indices, queries, k,
                metric, chunk, max_chunks, post_filter, weight):
    nq = queries.shape[0]
    L = landmarks.shape[0]
    cap = list_data.shape[1]
    qf = queries.astype(jnp.float32)

    d_ql = _cross_dist(qf, landmarks, metric)               # (nq, L)
    order = jnp.argsort(d_ql, axis=1)                       # ascending balls
    d_sorted = jnp.take_along_axis(d_ql, order, axis=1)
    r_sorted = radii[order]
    # pad to a whole number of chunks with a sentinel empty ball (index L)
    # so chunk slices never clamp and re-probe (which would duplicate
    # candidates in the merged top-k)
    W = max_chunks * chunk
    if W > L:
        pad = W - L
        order = jnp.pad(order, ((0, 0), (0, pad)), constant_values=L)
        d_sorted = jnp.pad(d_sorted, ((0, 0), (0, pad)),
                           constant_values=jnp.inf)
        r_sorted = jnp.pad(r_sorted, ((0, 0), (0, pad)))
    list_data = jnp.concatenate(
        [list_data, jnp.zeros((1,) + list_data.shape[1:], list_data.dtype)])
    list_indices = jnp.concatenate(
        [list_indices, jnp.full((1, cap), -1, list_indices.dtype)])
    # suffix min of the triangle lower bound over the sorted remainder:
    # lb[j] = min_{j' >= j} d(q, L_j') - weight * r_j'
    lb = jax.lax.cummin(d_sorted - weight * r_sorted, axis=1, reverse=True)
    lb = jnp.concatenate([lb, jnp.full((nq, 1), jnp.inf)], axis=1)

    # all comparisons below are in real distance units (see _cross_dist)
    def probe_chunk(best_d, best_i, t):
        sl = jax.lax.dynamic_slice(order, (0, t * chunk), (nq, chunk))
        data = list_data[sl]                                # (nq, chunk, cap, d)
        ids = list_indices[sl].reshape(nq, chunk * cap)
        data = data.reshape(nq, chunk * cap, -1)
        if metric == DistanceType.Haversine:
            cd = _haversine(qf[:, None, :], data)
        else:
            ip = jnp.einsum("qd,qcd->qc", qf, data,
                            precision=get_matmul_precision())
            cd = jnp.sqrt(jnp.maximum(
                jnp.sum(qf * qf, axis=1)[:, None]
                + jnp.sum(data * data, axis=-1) - 2.0 * ip, 0.0))
        cd = jnp.where(ids >= 0, cd, jnp.inf)
        kt = min(k, cd.shape[1])
        td, ti = select_k(cd, kt, in_idx=ids, select_min=True)
        return merge_topk(best_d, best_i, td, ti, select_min=True)

    init_d = jnp.full((nq, k), jnp.inf, jnp.float32)
    init_i = jnp.full((nq, k), -1, jnp.int32)
    # first pass: the closest `chunk` balls (reference first phase — the
    # closest-landmark sweep)
    best_d, best_i = probe_chunk(init_d, init_i, 0)

    if post_filter and max_chunks > 1:
        def cond(state):
            best_d, _, t = state
            # any query whose k-th distance can still be beaten by a ball in
            # the un-probed suffix?
            return jnp.logical_and(
                t < max_chunks,
                jnp.any(lb[:, t * chunk] < best_d[:, -1]))

        def body(state):
            best_d, best_i, t = state
            nd, ni = probe_chunk(best_d, best_i, t)
            return nd, ni, t + 1

        best_d, best_i, _ = jax.lax.while_loop(
            cond, body, (best_d, best_i, jnp.int32(1)))

    if not _sqrt_metric(metric):      # squared-form output metrics
        best_d = best_d * best_d
    return best_d, best_i


def _query(res, index: BallCoverIndex, queries, k: int,
           perform_post_filtering: bool, weight: float
           ) -> Tuple[jax.Array, jax.Array]:
    expects(index.trained, "ball cover index not built")
    queries = ensure_array(queries, "queries").astype(jnp.float32)
    expects(queries.ndim == 2 and queries.shape[1] == index.dim,
            "ball_cover: query dim mismatch")
    queries, ok_rows = _boundary.check_matrix(
        queries, "queries", site="ball_cover.query", dim=index.dim)
    L = index.n_landmarks
    chunk = min(L, max(1, k))
    max_chunks = -(-L // chunk)
    d, i = _query_impl(index.landmarks, index.radii, index.list_data,
                       index.list_indices, queries, int(k), index.metric,
                       chunk, max_chunks, bool(perform_post_filtering),
                       jnp.float32(weight))
    if ok_rows is not None:
        d, i = _boundary.mask_search_outputs(d, i, ok_rows)
    return d, i


@auto_convert_output
def all_knn_query(res, index: BallCoverIndex, k: int, *,
                  perform_post_filtering: bool = True, weight: float = 1.0
                  ) -> Tuple[jax.Array, jax.Array]:
    """All-neighbors kNN over the index's own points, building the index if
    needed (reference ball_cover.cuh:112)."""
    with named_range("ball_cover::all_knn_query"):
        if not index.trained:
            build_index(res, index)
        return _query(res, index, index.X, k, perform_post_filtering, weight)


@auto_convert_output
def knn_query(res, index: BallCoverIndex, queries, k: int, *,
              perform_post_filtering: bool = True, weight: float = 1.0
              ) -> Tuple[jax.Array, jax.Array]:
    """kNN of out-of-index queries (reference ball_cover.cuh:259)."""
    with named_range("ball_cover::knn_query"):
        return _query(res, index, queries, k, perform_post_filtering, weight)
