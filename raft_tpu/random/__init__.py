"""Random generation: counter-based RNG + dataset generators.

Reference: cpp/include/raft/random/ (SURVEY.md §2.8) — ``RngState`` with
Philox/PCG counter-based device generators (rng_state.hpp:28-52,
rng_device.cuh:30-31), a distribution suite (rng.cuh), and data generators
(make_blobs, make_regression, rmat, sample_without_replacement, permute,
multi_variable_gaussian).

JAX's threefry PRNG is already counter-based — the reference's whole
"seed + subsequence" design maps directly onto jax keys + fold_in.
"""

from raft_tpu.random.rng import (  # noqa: F401
    RngState,
    GeneratorType,
    uniform,
    uniformInt,
    normal,
    normalInt,
    lognormal,
    gumbel,
    laplace,
    logistic,
    exponential,
    rayleigh,
    bernoulli,
    scaled_bernoulli,
    discrete,
)
from raft_tpu.random.generators import (  # noqa: F401
    make_blobs,
    make_regression,
    rmat_rectangular_generator,
    sample_without_replacement,
    permute,
    multi_variable_gaussian,
)
