"""Counter-based RNG state + distributions.

Reference: raft/random/rng_state.hpp:28-52 (``RngState{seed, base_subsequence,
type}``), rng_device.cuh (Philox / PCG generators), rng.cuh (distribution
suite).  jax.random is counter-based (threefry) with explicit keys, which is
exactly the reference's design goal — so ``RngState`` here is a thin
deterministic key chain and each distribution is a pure function of a state.

Every distribution advances the state (matching the reference, where each call
bumps the subsequence so successive calls are independent).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects


class GeneratorType:
    """Reference: rng_state.hpp ``GeneratorType`` (GenPhilox/GenPC). jax's
    threefry plays both roles; the tag is kept for API parity."""

    GenDefault = "threefry"
    GenPhilox = "threefry"
    GenPC = "threefry"


class RngState:
    """Deterministic RNG state (reference: rng_state.hpp:28-52).

    ``advance`` mirrors ``RngState::advance`` — it bumps the subsequence so the
    next draw is independent.
    """

    def __init__(self, seed: int = 0,
                 gen_type: str = GeneratorType.GenDefault) -> None:
        self.seed = seed
        self.base_subsequence = 0
        self.type = gen_type

    def advance(self, n: int = 1) -> None:
        self.base_subsequence += n

    def next_key(self) -> jax.Array:
        key = jax.random.fold_in(jax.random.key(self.seed), self.base_subsequence)
        self.advance()
        return key


def _as_state(rng: Union[RngState, int, jax.Array]) -> jax.Array:
    """Accept an RngState, an int seed, or a raw jax key."""
    if isinstance(rng, RngState):
        return rng.next_key()
    if isinstance(rng, int):
        return jax.random.key(rng)
    return rng


def uniform(rng, shape, *, low: float = 0.0, high: float = 1.0,
            dtype=jnp.float32) -> jax.Array:
    """Reference: rng.cuh ``uniform``."""
    return jax.random.uniform(_as_state(rng), shape, dtype=dtype,
                              minval=low, maxval=high)


def uniformInt(rng, shape, *, low: int = 0, high: int = 2**31 - 1,
               dtype=jnp.int32) -> jax.Array:
    """Reference: rng.cuh ``uniformInt`` (end-exclusive)."""
    return jax.random.randint(_as_state(rng), shape, low, high, dtype=dtype)


def normal(rng, shape, *, mu: float = 0.0, sigma: float = 1.0,
           dtype=jnp.float32) -> jax.Array:
    """Reference: rng.cuh ``normal``."""
    return mu + sigma * jax.random.normal(_as_state(rng), shape, dtype=dtype)


def normalInt(rng, shape, *, mu: float = 0.0, sigma: float = 1.0,
              dtype=jnp.int32) -> jax.Array:
    """Reference: rng.cuh ``normalInt`` — rounded normal."""
    x = mu + sigma * jax.random.normal(_as_state(rng), shape)
    return jnp.round(x).astype(dtype)


def lognormal(rng, shape, *, mu: float = 0.0, sigma: float = 1.0,
              dtype=jnp.float32) -> jax.Array:
    return jnp.exp(normal(rng, shape, mu=mu, sigma=sigma, dtype=dtype))


def gumbel(rng, shape, *, mu: float = 0.0, beta: float = 1.0,
           dtype=jnp.float32) -> jax.Array:
    return mu + beta * jax.random.gumbel(_as_state(rng), shape, dtype=dtype)


def laplace(rng, shape, *, mu: float = 0.0, scale: float = 1.0,
            dtype=jnp.float32) -> jax.Array:
    return mu + scale * jax.random.laplace(_as_state(rng), shape, dtype=dtype)


def logistic(rng, shape, *, mu: float = 0.0, scale: float = 1.0,
             dtype=jnp.float32) -> jax.Array:
    return mu + scale * jax.random.logistic(_as_state(rng), shape, dtype=dtype)


def exponential(rng, shape, *, lam: float = 1.0,
                dtype=jnp.float32) -> jax.Array:
    return jax.random.exponential(_as_state(rng), shape, dtype=dtype) / lam


def rayleigh(rng, shape, *, sigma: float = 1.0,
             dtype=jnp.float32) -> jax.Array:
    u = jax.random.uniform(_as_state(rng), shape, dtype=dtype,
                           minval=jnp.finfo(dtype).tiny, maxval=1.0)
    return sigma * jnp.sqrt(-2.0 * jnp.log(u))


def bernoulli(rng, shape, *, prob: float = 0.5) -> jax.Array:
    return jax.random.bernoulli(_as_state(rng), prob, shape)


def scaled_bernoulli(rng, shape, *, prob: float = 0.5, scale: float = 1.0,
                     dtype=jnp.float32) -> jax.Array:
    """Reference: rng.cuh ``scaled_bernoulli`` — ±scale with prob."""
    b = jax.random.bernoulli(_as_state(rng), prob, shape)
    return jnp.where(b, scale, -scale).astype(dtype)


def discrete(rng, shape, weights: jax.Array, dtype=jnp.int32) -> jax.Array:
    """Sample indices proportional to weights (reference: rng.cuh ``discrete``)."""
    logits = jnp.log(jnp.maximum(weights.astype(jnp.float32), 1e-30))
    return jax.random.categorical(_as_state(rng), logits, shape=shape).astype(dtype)
