"""Dataset generators.

Reference: raft/random/{make_blobs,make_regression,rmat_rectangular_generator,
sample_without_replacement,permute,multi_variable_gaussian}.cuh.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from raft_tpu.core.error import expects
from raft_tpu.random.rng import RngState, _as_state
from raft_tpu.core.outputs import auto_convert_output


@auto_convert_output
def make_blobs(
    n_samples: int,
    n_features: int,
    *,
    n_clusters: int = 5,
    cluster_std: float = 1.0,
    center_box: Tuple[float, float] = (-10.0, 10.0),
    centers: Optional[jax.Array] = None,
    shuffle: bool = True,
    seed: Union[int, RngState, jax.Array] = 0,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array]:
    """Gaussian-blob dataset (reference: random/make_blobs.cuh).

    Returns (data (n_samples, n_features), labels (n_samples,)).
    """
    key = _as_state(seed) if not isinstance(seed, int) else jax.random.key(seed)
    k_centers, k_labels, k_noise, k_shuffle = jax.random.split(key, 4)
    if centers is None:
        centers = jax.random.uniform(
            k_centers, (n_clusters, n_features), dtype=dtype,
            minval=center_box[0], maxval=center_box[1])
    else:
        centers = jnp.asarray(centers, dtype=dtype)
        n_clusters = centers.shape[0]
    labels = jax.random.randint(k_labels, (n_samples,), 0, n_clusters)
    noise = cluster_std * jax.random.normal(
        k_noise, (n_samples, n_features), dtype=dtype)
    data = centers[labels] + noise
    if shuffle:
        perm = jax.random.permutation(k_shuffle, n_samples)
        data, labels = data[perm], labels[perm]
    return data, labels.astype(jnp.int32)


@auto_convert_output
def make_regression(
    n_samples: int,
    n_features: int,
    *,
    n_informative: Optional[int] = None,
    n_targets: int = 1,
    bias: float = 0.0,
    noise: float = 0.0,
    effective_rank: Optional[int] = None,
    tail_strength: float = 0.5,
    shuffle: bool = True,
    seed: Union[int, RngState, jax.Array] = 0,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Linear-model dataset (reference: random/make_regression.cuh).

    Returns (X, y, coef) with y = X @ coef + bias + noise.
    """
    if n_informative is None:
        n_informative = n_features
    n_informative = min(n_informative, n_features)
    key = _as_state(seed) if not isinstance(seed, int) else jax.random.key(seed)
    kx, kc, kn, ks, kr = jax.random.split(key, 5)
    X = jax.random.normal(kx, (n_samples, n_features), dtype=dtype)
    if effective_rank is not None:
        # low-rank-ish covariance via spectral decay, as in the reference
        sv = jnp.exp(-jnp.arange(n_features, dtype=dtype) / effective_rank) \
            * (1 - tail_strength) + tail_strength * jax.random.uniform(
                kr, (n_features,), dtype=dtype)
        X = X * sv[None, :]
    coef = jnp.zeros((n_features, n_targets), dtype=dtype)
    coef = coef.at[:n_informative].set(
        100.0 * jax.random.uniform(kc, (n_informative, n_targets), dtype=dtype))
    y = X @ coef + bias
    if noise > 0:
        y = y + noise * jax.random.normal(kn, y.shape, dtype=dtype)
    if shuffle:
        perm = jax.random.permutation(ks, n_samples)
        X, y = X[perm], y[perm]
    if n_targets == 1:
        y = y[:, 0]
    return X, y, coef


def rmat_rectangular_generator(
    rng: Union[int, RngState, jax.Array],
    theta: jax.Array,
    r_scale: int,
    c_scale: int,
    n_edges: int,
) -> Tuple[jax.Array, jax.Array]:
    """R-MAT power-law graph edges (reference: random/rmat_rectangular_generator.cuh).

    ``theta`` is (max(r_scale, c_scale), 4) per-level quadrant probabilities
    (a,b,c,d); returns (src, dst) int32 arrays of length n_edges.  Implemented
    as a vectorized per-level quadrant draw — one categorical per level over
    all edges at once (no per-edge loops; all VPU work).
    """
    key = _as_state(rng) if not isinstance(rng, int) else jax.random.key(rng)
    theta = jnp.asarray(theta, jnp.float32)
    max_scale = max(r_scale, c_scale)
    expects(theta.shape[0] >= max_scale and theta.shape[1] == 4,
            "theta must be (max_scale, 4)")
    src = jnp.zeros((n_edges,), jnp.int32)
    dst = jnp.zeros((n_edges,), jnp.int32)
    keys = jax.random.split(key, max_scale)
    for lvl in range(max_scale):
        q = jax.random.categorical(
            keys[lvl], jnp.log(jnp.maximum(theta[lvl], 1e-30)), shape=(n_edges,))
        r_bit = (q >= 2).astype(jnp.int32)   # quadrants c,d are lower half
        c_bit = (q % 2).astype(jnp.int32)    # quadrants b,d are right half
        if lvl < r_scale:
            src = src * 2 + r_bit
        if lvl < c_scale:
            dst = dst * 2 + c_bit
    return src, dst


def sample_without_replacement(
    rng: Union[int, RngState, jax.Array],
    n_population: int,
    n_samples: int,
    *,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """Sample distinct indices (reference: random/sample_without_replacement.cuh).

    Weighted case uses the Gumbel-top-k trick — the jit/TPU-native equivalent
    of the reference's per-item keyed sort.
    """
    expects(n_samples <= n_population, "cannot sample more than population")
    key = _as_state(rng) if not isinstance(rng, int) else jax.random.key(rng)
    if weights is None:
        return jax.random.permutation(key, n_population)[:n_samples]
    g = jax.random.gumbel(key, (n_population,))
    scores = jnp.log(jnp.maximum(weights.astype(jnp.float32), 1e-30)) + g
    _, idx = jax.lax.top_k(scores, n_samples)
    return idx


def permute(rng: Union[int, RngState, jax.Array],
            data: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Random row permutation; returns (permuted, perm) (reference: random/permute.cuh)."""
    key = _as_state(rng) if not isinstance(rng, int) else jax.random.key(rng)
    perm = jax.random.permutation(key, data.shape[0])
    return data[perm], perm.astype(jnp.int32)


def multi_variable_gaussian(
    rng: Union[int, RngState, jax.Array],
    mean: jax.Array,
    cov: jax.Array,
    n_samples: int,
) -> jax.Array:
    """Samples from N(mean, cov) (reference: random/multi_variable_gaussian.cuh).

    Cholesky formulation (the reference offers cholesky/jacobi/qr methods; on
    TPU cholesky + gemm is the right one)."""
    key = _as_state(rng) if not isinstance(rng, int) else jax.random.key(rng)
    d = mean.shape[0]
    L = jnp.linalg.cholesky(cov + 1e-6 * jnp.eye(d, dtype=cov.dtype))
    z = jax.random.normal(key, (n_samples, d), dtype=cov.dtype)
    return mean[None, :] + z @ L.T
