"""Retry / backoff / deadline wrappers.

Counterpart of the failure-handling the reference leaves to its callers
(raft-dask resubmits tasks; NCCL aborts bubble to the service layer):
here retries are a library primitive so distributed entry points and
index IO survive transient faults.

- :class:`RetryPolicy` — jittered exponential backoff; only exceptions
  in ``retryable`` are retried (``TransientFault`` and ``OSError`` by
  default — logic errors and corruption are deterministic and must not
  be retried).
- :class:`Deadline` — a wall-clock budget threaded through retries:
  the sleep before an attempt never overshoots the budget, and an
  expired deadline raises :class:`DeadlineExceededError` instead of
  starting another attempt.
- :func:`retry_call` — run a thunk under a policy + deadline, bumping
  ``resilience.retry.<site>`` per re-attempt and
  ``resilience.giveup.<site>`` when attempts/deadline are exhausted
  (observability registry, collection-gated like every other counter).

This module is the ONE place in the library allowed to sleep: CI rejects
bare ``time.sleep`` anywhere else under ``raft_tpu/`` (the same style of
guard that keeps raw ``time.perf_counter`` out of library code).
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

from raft_tpu.core.error import RaftError
from raft_tpu.resilience.faults import TransientFault

T = TypeVar("T")


class DeadlineExceededError(RaftError):
    """The operation's time budget ran out (attempts may remain)."""


class Deadline:
    """A monotonic wall-clock budget.

    ``Deadline(5.0)`` expires 5 s after construction; pass it through
    nested calls so one budget bounds the whole operation (build +
    retries + IO), the way the reference's stream-ordered work is
    bounded by the caller's stream lifetime.  ``Deadline(None)`` never
    expires (the default everywhere).
    """

    __slots__ = ("_expires_at", "_clock")

    def __init__(self, seconds: Optional[float],
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._expires_at = (None if seconds is None
                            else clock() + float(seconds))

    @classmethod
    def unlimited(cls) -> "Deadline":
        return cls(None)

    def remaining(self) -> float:
        """Seconds left (``inf`` for unlimited, clamped at 0.0)."""
        if self._expires_at is None:
            return float("inf")
        return max(0.0, self._expires_at - self._clock())

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, what: str = "operation") -> None:
        """Raise :class:`DeadlineExceededError` if the budget is spent."""
        if self.expired:
            raise DeadlineExceededError(
                f"deadline exceeded before {what}")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff.

    Attempt ``i`` (1-based) sleeps ``base_delay * multiplier**(i-1)``
    capped at ``max_delay``, scaled by a uniform jitter in
    ``[1 - jitter, 1 + jitter]`` — full determinism when the caller
    passes a seeded ``rng``.  ``max_attempts`` counts *total* attempts
    (1 = no retry)."""

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    retryable: Tuple[Type[BaseException], ...] = (TransientFault, OSError)
    # deterministic failures inside otherwise-retryable families: a
    # missing file will still be missing on attempt 2
    non_retryable: Tuple[Type[BaseException], ...] = (FileNotFoundError,)

    def delay(self, attempt: int, rng: Optional[random.Random] = None
              ) -> float:
        """Backoff before attempt ``attempt + 1`` (attempt is 1-based)."""
        d = min(self.max_delay,
                self.base_delay * self.multiplier ** (attempt - 1))
        if self.jitter:
            u = (rng.random() if rng is not None else random.random())
            d *= 1.0 + self.jitter * (2.0 * u - 1.0)
        return max(d, 0.0)

    def is_retryable(self, exc: BaseException) -> bool:
        return (isinstance(exc, self.retryable)
                and not isinstance(exc, self.non_retryable))


DEFAULT_POLICY = RetryPolicy()

# test seam: monkeypatch to a no-op to run backoff schedules instantly
_sleep = time.sleep


def retry_call(fn: Callable[..., T], *args,
               site: str,
               policy: Optional[RetryPolicy] = None,
               deadline: Optional[Deadline] = None,
               rng: Optional[random.Random] = None,
               **kwargs) -> T:
    """Call ``fn(*args, **kwargs)`` under ``policy`` + ``deadline``.

    Per re-attempt: ``resilience.retry.<site>`` +1.  On giving up
    (attempts exhausted, non-retryable error, or deadline expiry):
    ``resilience.giveup.<site>`` +1 and the last error (or
    :class:`DeadlineExceededError`) propagates.
    """
    policy = policy or DEFAULT_POLICY
    deadline = deadline or Deadline.unlimited()
    attempt = 0
    while True:
        attempt += 1
        try:
            deadline.check(f"{site} attempt {attempt}")
        except DeadlineExceededError:
            _count(f"resilience.giveup.{site}")
            raise
        try:
            return fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 - classified below
            if (not policy.is_retryable(e)
                    or attempt >= policy.max_attempts):
                _count(f"resilience.giveup.{site}")
                raise
            pause = min(policy.delay(attempt, rng), deadline.remaining())
            _count(f"resilience.retry.{site}")
            if pause > 0.0:
                _sleep(pause)


def retryable(site: str, *, policy: Optional[RetryPolicy] = None):
    """Decorator form of :func:`retry_call`; the wrapped function gains
    optional ``retry_policy=`` / ``deadline=`` keyword-only arguments."""
    def wrap(fn: Callable[..., T]) -> Callable[..., T]:
        def inner(*args, retry_policy: Optional[RetryPolicy] = None,
                  deadline: Optional[Deadline] = None, **kwargs) -> T:
            return retry_call(fn, *args, site=site,
                              policy=retry_policy or policy,
                              deadline=deadline, **kwargs)
        inner.__name__ = fn.__name__
        inner.__doc__ = fn.__doc__
        inner.__wrapped__ = fn
        return inner
    return wrap


def _count(name: str) -> None:
    from raft_tpu import observability as obs
    if obs.enabled():
        obs.registry().counter(name).inc()
