"""Checkpointed index builds: atomic stage persistence + resume.

TPU fleets preempt routinely; a CAGRA/IVF-PQ build measured in minutes
must not restart from scratch.  Builds persist their loop-carry state
(kmeans centers, graph-so-far, PQ codebooks) at their
``interruptible.synchronize`` points through a
:class:`CheckpointManager`; ``build(..., checkpoint=dir, resume=True)``
then restarts from the last completed stage.

File layout (documented contract, docs/api.md "Resilience")::

    <dir>/MANIFEST.json      # {"stages": [name, ...]} in completion order
    <dir>/<stage>.ckpt       # one CRC32 envelope (core/serialize) wrapping
                             # count + (name, array) records

Atomicity: every file is written to ``<name>.tmp.<pid>``, flushed,
``fsync``'d, and ``os.replace``'d into place (POSIX-atomic), then the
directory entry is fsync'd — a crash mid-save leaves either the old
file or the new one, never a torn one.  The MANIFEST is rewritten
(same protocol) only *after* its stage file landed, so a stage listed
in the manifest is always readable.  Payloads ride the same CRC32
envelope as index serialization: a torn/bit-flipped checkpoint raises
:class:`~raft_tpu.core.serialize.CorruptIndexError` at load and the
stage is rebuilt instead of poisoning the resumed index.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Union

import numpy as np

from raft_tpu.core import serialize as ser
from raft_tpu.core.error import expects
from raft_tpu.resilience import faults


def _fsync_dir(path: str) -> None:
    # direntry durability (rename alone is atomic but not durable)
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms/filesystems without dir fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes) -> None:
    """tmp + flush + fsync + rename: the file at ``path`` is either the
    previous version or all of ``data`` — never a prefix."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


class CheckpointManager:
    """Directory of atomically-written, CRC-enveloped build stages."""

    _MANIFEST = "MANIFEST.json"

    def __init__(self, path: str) -> None:
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)
        self._stages: List[str] = self._read_manifest()

    # -- manifest ----------------------------------------------------------
    def _read_manifest(self) -> List[str]:
        p = os.path.join(self.path, self._MANIFEST)
        if not os.path.exists(p):
            return []
        try:
            with open(p, "r") as f:
                doc = json.load(f)
            return list(doc.get("stages", []))
        except (OSError, ValueError):
            # torn manifest (should be impossible given atomic_write;
            # treat as empty rather than failing the resumed build)
            return []

    def _write_manifest(self) -> None:
        doc = json.dumps({"stages": self._stages}).encode()
        atomic_write(os.path.join(self.path, self._MANIFEST), doc)

    @property
    def completed(self) -> List[str]:
        """Stage names in completion order."""
        return list(self._stages)

    def has(self, stage: str) -> bool:
        return (stage in self._stages
                and os.path.exists(self._file(stage)))

    def _file(self, stage: str) -> str:
        expects("/" not in stage and stage not in ("", ".", ".."),
                f"checkpoint: bad stage name {stage!r}")
        return os.path.join(self.path, f"{stage}.ckpt")

    # -- stage IO ----------------------------------------------------------
    def save(self, stage: str, arrays: Dict[str, np.ndarray]) -> None:
        """Persist one stage's named arrays atomically; the stage enters
        the manifest only after its file is durable."""
        faults.maybe_fail("checkpoint.save")
        import io

        body = io.BytesIO()
        ser.serialize_scalar(None, body, np.int32(len(arrays)))
        for name, arr in arrays.items():
            ser.serialize_mdspan(None, body, np.asarray(name))
            ser.serialize_mdspan(None, body, np.asarray(arr))
        out = io.BytesIO()
        ser.write_envelope(out, body.getvalue())
        atomic_write(self._file(stage), out.getvalue())
        if stage in self._stages:
            self._stages.remove(stage)
        self._stages.append(stage)
        self._write_manifest()
        _count("resilience.checkpoint.save")

    def load(self, stage: str) -> Dict[str, np.ndarray]:
        """Restore one stage; raises
        :class:`~raft_tpu.core.serialize.CorruptIndexError` on a torn or
        bit-flipped file (callers rebuild the stage instead)."""
        faults.maybe_fail("checkpoint.load")
        import io

        with open(self._file(stage), "rb") as f:
            payload = ser.read_envelope(f)
        body = io.BytesIO(payload)
        n = int(ser.deserialize_scalar(None, body))
        out: Dict[str, np.ndarray] = {}
        for _ in range(n):
            name = str(ser.deserialize_mdspan(None, body))
            out[name] = ser.deserialize_mdspan(None, body)
        _count("resilience.checkpoint.load")
        return out

    def clear(self) -> None:
        """Drop all stages (a completed build retires its checkpoints)."""
        for s in list(self._stages):
            try:
                os.remove(self._file(s))
            except OSError:
                pass
        self._stages = []
        self._write_manifest()


def as_manager(checkpoint: Optional[Union[str, CheckpointManager]]
               ) -> Optional[CheckpointManager]:
    """Builds accept a path or a manager; normalize (None passes through)."""
    if checkpoint is None or isinstance(checkpoint, CheckpointManager):
        return checkpoint
    return CheckpointManager(str(checkpoint))


def _count(name: str) -> None:
    from raft_tpu import observability as obs
    if obs.enabled():
        obs.registry().counter(name).inc()
