"""Deterministic fault injection at named library sites.

The failure-handling analogue of the reference's test-only CUDA error
stubs: production RAFT is hardened against transient NCCL / IO failures
by the surrounding service; raft_tpu bakes the seam into the library so
failure paths are *testable on a laptop*.  Library code calls
:func:`maybe_fail(site)` at well-known points; with no plan active the
call is a single ``None`` check (zero allocation, zero locking) — the
hot search path pays nothing.

Named sites (see docs/api.md "Resilience"):

======================================  ====================================
site                                    where it fires
======================================  ====================================
``comms.<op>``                          each collective in
                                        :mod:`raft_tpu.comms.comms`
                                        (``allreduce``, ``reduce``,
                                        ``bcast``, ``allgather``,
                                        ``allgatherv``, ``gather``,
                                        ``gatherv``, ``reducescatter``,
                                        ``barrier``, ``isend``) — fires at
                                        *trace* time (collectives are
                                        traced-context calls; a jit cache
                                        hit does not re-enter the site)
``distributed.ann.search`` /            host-side, once per distributed
``distributed.ann.build`` (+ ``_flat``  search/build call, before dispatch
/ ``_cagra`` variants)
``interruptible.synchronize``           every ``interruptible.synchronize``
                                        host sync point
``serialize.write`` /                   every record written/read by
``serialize.read``                      :mod:`raft_tpu.core.serialize`
``checkpoint.save`` /                   every :class:`CheckpointManager`
``checkpoint.load``                     stage persisted / restored
======================================  ====================================

Scripting is explicit and deterministic::

    plan = (FaultPlan(seed=7)
            .at("comms.allreduce", times=1, exc=TransientFault)
            .fail_shards(1))          # shard 1's leaves are "lost"
    with plan.active():
        ...   # first traced allreduce raises TransientFault; shard 1
              # is reported failed by faults.failed_shards(n)

``times`` bounds how often a spec fires, ``after`` skips the first N
matching calls ("fail the 2nd synchronize"), and ``p`` draws from the
plan's seeded RNG (``RAFT_TPU_FAULT_SEED`` pins the default seed) so a
probabilistic schedule replays identically.  Fired injections bump
``resilience.fault.injected.<site>`` in the observability registry when
collection is enabled.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import random
import threading
from typing import Callable, Iterator, List, Optional, Tuple

from raft_tpu.core.error import RaftError

_SEED_ENV = "RAFT_TPU_FAULT_SEED"


class FaultInjected(RaftError):
    """Base class for injected failures (never raised organically)."""


class TransientFault(FaultInjected):
    """An injected failure that retry wrappers treat as retryable —
    the scripted analogue of a flaky collective / flaky filesystem."""


@dataclasses.dataclass
class FaultSpec:
    """One scripted failure: fire at ``site`` up to ``times`` times
    (None = unbounded), skipping the first ``after`` matching calls,
    each firing gated by probability ``p`` from the plan's seeded RNG.
    ``exc`` is an exception class or zero/one-arg factory."""

    site: str
    times: Optional[int] = 1
    exc: Callable[..., BaseException] = TransientFault
    after: int = 0
    p: float = 1.0
    _seen: int = 0
    _fired: int = 0

    def matches(self, site: str) -> bool:
        return self.site == site

    @property
    def fired(self) -> int:
        """How many times this spec has raised (for test assertions)."""
        return self._fired


class FaultPlan:
    """An ordered set of :class:`FaultSpec` plus failed-shard flags,
    activated via :meth:`active` (or :func:`inject`).  Thread-safe:
    sites may be hit from worker threads (host callbacks, build
    threads)."""

    def __init__(self, seed: Optional[int] = None) -> None:
        if seed is None:
            seed = int(os.environ.get(_SEED_ENV, "0"))
        self.seed = seed
        self._rng = random.Random(seed)
        self._specs: List[FaultSpec] = []
        self._failed_shards: set = set()
        self._lock = threading.Lock()

    # -- scripting ---------------------------------------------------------
    def at(self, site: str, *, times: Optional[int] = 1,
           exc: Callable[..., BaseException] = TransientFault,
           after: int = 0, p: float = 1.0) -> "FaultPlan":
        """Script a failure at ``site``; returns self for chaining."""
        self._specs.append(FaultSpec(site=site, times=times, exc=exc,
                                     after=after, p=p))
        return self

    def fail_shards(self, *shards: int) -> "FaultPlan":
        """Flag distributed-index shards as failed: degraded search
        (``distributed.ann``) drops them and reports them in the status
        vector instead of crashing the query."""
        self._failed_shards.update(int(s) for s in shards)
        return self

    @property
    def specs(self) -> Tuple[FaultSpec, ...]:
        return tuple(self._specs)

    # -- evaluation --------------------------------------------------------
    def _check(self, site: str) -> None:
        with self._lock:
            for spec in self._specs:
                if not spec.matches(site):
                    continue
                if spec.times is not None and spec._fired >= spec.times:
                    continue
                spec._seen += 1
                if spec._seen <= spec.after:
                    continue
                if spec.p < 1.0 and self._rng.random() >= spec.p:
                    continue
                spec._fired += 1
                _count(site)
                try:
                    raise spec.exc(f"injected fault at {site!r}")
                except TypeError:
                    raise spec.exc()  # zero-arg factories

    @contextlib.contextmanager
    def active(self) -> Iterator["FaultPlan"]:
        """Install this plan for the body (plans nest LIFO)."""
        token = _push(self)
        try:
            yield self
        finally:
            _pop(token)


# ---------------------------------------------------------------------------
# active-plan stack.  A plain module global (not a ContextVar): sites are
# hit from worker threads the test's context never propagates to, and the
# whole point is that the *process* is under a scripted failure regime.

_ACTIVE: Optional[FaultPlan] = None
_STACK: List[FaultPlan] = []
_STATE_LOCK = threading.Lock()


def _push(plan: FaultPlan) -> int:
    global _ACTIVE
    with _STATE_LOCK:
        _STACK.append(plan)
        _ACTIVE = plan
        return len(_STACK) - 1


def _pop(token: int) -> None:
    global _ACTIVE
    with _STATE_LOCK:
        del _STACK[token:]
        _ACTIVE = _STACK[-1] if _STACK else None


def _count(site: str) -> None:
    from raft_tpu import observability as obs
    if obs.enabled():
        obs.registry().counter(f"resilience.fault.injected.{site}").inc()


@contextlib.contextmanager
def inject(*args, seed: Optional[int] = None, **at_kwargs) -> Iterator[FaultPlan]:
    """Shorthand: ``with inject("comms.allreduce", times=1): ...``
    activates a one-spec plan (or an empty plan with no site, useful to
    scope :meth:`FaultPlan.fail_shards` set on the yielded plan)."""
    plan = FaultPlan(seed=seed)
    if args:
        plan.at(args[0], **at_kwargs)
    with plan.active():
        yield plan


def is_active() -> bool:
    return _ACTIVE is not None


def maybe_fail(site: str) -> None:
    """The library-side hook: raise if the active plan scripts a failure
    here.  **No plan active → a single attribute load + None check.**"""
    plan = _ACTIVE
    if plan is None:
        return
    plan._check(site)


def failed_shards(n_shards: int) -> Tuple[int, ...]:
    """Shards the active plan flags failed, clipped to ``range(n_shards)``
    (empty when no plan is active)."""
    plan = _ACTIVE
    if plan is None:
        return ()
    return tuple(sorted(s for s in plan._failed_shards
                        if 0 <= s < n_shards))
