"""Deterministic fault injection at named library sites.

The failure-handling analogue of the reference's test-only CUDA error
stubs: production RAFT is hardened against transient NCCL / IO failures
by the surrounding service; raft_tpu bakes the seam into the library so
failure paths are *testable on a laptop*.  Library code calls
:func:`maybe_fail(site)` at well-known points; with no plan active the
call is a single ``None`` check (zero allocation, zero locking) — the
hot search path pays nothing.

Named sites (see docs/api.md "Resilience"):

======================================  ====================================
site                                    where it fires
======================================  ====================================
``comms.<op>``                          each collective in
                                        :mod:`raft_tpu.comms.comms`
                                        (``allreduce``, ``reduce``,
                                        ``bcast``, ``allgather``,
                                        ``allgatherv``, ``gather``,
                                        ``gatherv``, ``reducescatter``,
                                        ``barrier``, ``isend``) — fires at
                                        *trace* time (collectives are
                                        traced-context calls; a jit cache
                                        hit does not re-enter the site)
``distributed.ann.search`` /            host-side, once per distributed
``distributed.ann.build`` (+ ``_flat``  search/build call, before dispatch
/ ``_cagra`` variants)
``interruptible.synchronize``           every ``interruptible.synchronize``
                                        host sync point
``serialize.write`` /                   every record written/read by
``serialize.read``                      :mod:`raft_tpu.core.serialize`
``checkpoint.save`` /                   every :class:`CheckpointManager`
``checkpoint.load``                     stage persisted / restored
``ingest.<step>``                       single-writer ingest tier
                                        (``append``, ``fsync``, ``apply``,
                                        ``fold``, ``truncate``) — see
                                        :mod:`raft_tpu.serving.ingest`
``ingest.dist.<step>``                  routed replicated ingest tier
                                        (``route``, ``append``, ``ack``,
                                        ``replicate``, ``fold``,
                                        ``catch_up``) — see
                                        :mod:`raft_tpu.serving.dist_ingest`;
                                        ``kill_shard_at`` here is the write
                                        -path kill matrix
======================================  ====================================

Scripting is explicit and deterministic::

    plan = (FaultPlan(seed=7)
            .at("comms.allreduce", times=1, exc=TransientFault)
            .fail_shards(1))          # shard 1's leaves are "lost"
    with plan.active():
        ...   # first traced allreduce raises TransientFault; shard 1
              # is reported failed by faults.failed_shards(n)

``times`` bounds how often a spec fires, ``after`` skips the first N
matching calls ("fail the 2nd synchronize"), and ``p`` draws from the
plan's seeded RNG (``RAFT_TPU_FAULT_SEED`` pins the default seed) so a
probabilistic schedule replays identically.  Fired injections bump
``resilience.fault.injected.<site>`` in the observability registry when
collection is enabled.

Latency injection (the overload / slow-shard regime) uses the same
sites and the same determinism contract: :meth:`FaultPlan.delay_at`
scripts a spec that *sleeps* ``delay + jitter * rng()`` seconds instead
of raising (jitter draws come from the plan's seeded RNG, so a jittered
schedule replays identically under a pinned seed), bumping
``resilience.fault.delayed.<site>``.  Per-shard stragglers are scripted
with :meth:`FaultPlan.straggle_shard`; ``distributed.ann`` calls
:func:`straggler_pause` once per search, which host-side pauses for the
slowest scripted shard — the SPMD dispatch returns when the last shard
answers, results stay exact, only latency moves.  All sleeping happens
inside this module (the graftlint timing-discipline pass keeps
``time.sleep`` out of everything outside ``raft_tpu/resilience/``),
through the monkeypatchable ``_sleep`` seam.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import random
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from raft_tpu.core.error import RaftError

_SEED_ENV = "RAFT_TPU_FAULT_SEED"

# test seam (mirrors retry._sleep): fault delays pause through this so
# latency tests can count sleeps without slowing the suite down
_sleep = time.sleep


class FaultInjected(RaftError):
    """Base class for injected failures (never raised organically)."""


class TransientFault(FaultInjected):
    """An injected failure that retry wrappers treat as retryable —
    the scripted analogue of a flaky collective / flaky filesystem."""


@dataclasses.dataclass
class FaultSpec:
    """One scripted failure: fire at ``site`` up to ``times`` times
    (None = unbounded), skipping the first ``after`` matching calls,
    each firing gated by probability ``p`` from the plan's seeded RNG.
    ``exc`` is an exception class or zero/one-arg factory.

    A spec with ``delay > 0`` (or ``jitter > 0``) is a *latency* spec:
    instead of raising it sleeps ``delay + jitter * rng()`` seconds when
    it fires (``exc`` is ignored).  Jitter draws come from the plan's
    seeded RNG, so the schedule is deterministic under a pinned seed.

    A spec with ``_kill_shard`` set (via :meth:`FaultPlan.kill_shard_at`)
    is a *shard-kill* spec: firing adds the shard to the plan's
    failed-shard set instead of raising — the kill lands at a precise
    lifecycle boundary and takes effect at the next
    :func:`failed_shards` poll."""

    site: str
    times: Optional[int] = 1
    exc: Callable[..., BaseException] = TransientFault
    after: int = 0
    p: float = 1.0
    delay: float = 0.0
    jitter: float = 0.0
    _kill_shard: Optional[int] = None
    _seen: int = 0
    _fired: int = 0

    def matches(self, site: str) -> bool:
        return self.site == site

    @property
    def is_delay(self) -> bool:
        return self.delay > 0.0 or self.jitter > 0.0

    @property
    def fired(self) -> int:
        """How many times this spec has raised (for test assertions)."""
        return self._fired


class FaultPlan:
    """An ordered set of :class:`FaultSpec` plus failed-shard flags,
    activated via :meth:`active` (or :func:`inject`).  Thread-safe:
    sites may be hit from worker threads (host callbacks, build
    threads)."""

    def __init__(self, seed: Optional[int] = None) -> None:
        if seed is None:
            seed = int(os.environ.get(_SEED_ENV, "0"))
        self.seed = seed
        self._rng = random.Random(seed)
        self._specs: List[FaultSpec] = []
        self._failed_shards: set = set()
        self._stragglers: Dict[int, Tuple[float, float]] = {}
        self._flapping: Dict[int, int] = {}   # shard -> poll period
        self._flap_polls = 0
        self._lock = threading.Lock()

    # -- scripting ---------------------------------------------------------
    def at(self, site: str, *, times: Optional[int] = 1,
           exc: Callable[..., BaseException] = TransientFault,
           after: int = 0, p: float = 1.0) -> "FaultPlan":
        """Script a failure at ``site``; returns self for chaining."""
        self._specs.append(FaultSpec(site=site, times=times, exc=exc,
                                     after=after, p=p))
        return self

    def delay_at(self, site: str, *, delay: float, jitter: float = 0.0,
                 times: Optional[int] = None, after: int = 0,
                 p: float = 1.0) -> "FaultPlan":
        """Script injected latency at ``site``: each firing sleeps
        ``delay + jitter * rng()`` seconds (unbounded by default — a
        latency regime usually spans the whole scenario).  Returns self
        for chaining."""
        if delay < 0 or jitter < 0:
            raise ValueError("delay and jitter must be non-negative")
        self._specs.append(FaultSpec(site=site, times=times, after=after,
                                     p=p, delay=delay, jitter=jitter))
        return self

    def straggle_shard(self, shard: int, *, delay: float,
                       jitter: float = 0.0) -> "FaultPlan":
        """Make distributed-index shard ``shard`` a straggler: every
        routed search pauses ``delay + jitter * rng()`` seconds before
        its merge (via :func:`straggler_pause`).  Unlike
        :meth:`fail_shards` the shard still answers — results stay
        exact, only latency moves."""
        if delay < 0 or jitter < 0:
            raise ValueError("delay and jitter must be non-negative")
        self._stragglers[int(shard)] = (float(delay), float(jitter))
        return self

    def fail_shards(self, *shards: int) -> "FaultPlan":
        """Flag distributed-index shards as failed: degraded search
        (``distributed.ann``) drops them and reports them in the status
        vector instead of crashing the query (with a replicated
        placement the shard's lists fail over to replicas first)."""
        self._failed_shards.update(int(s) for s in shards)
        return self

    def kill_shard_at(self, site: str, shard: int, *,
                      after: int = 0) -> "FaultPlan":
        """Kill ``shard`` when execution next passes ``site`` — the
        lifecycle-boundary shard kill (route / scan / gather / swap /
        catch-up).  Unlike :meth:`fail_shards` the shard is healthy
        until the site fires; a search already past its failed-set
        computation finishes on the pre-kill routing (the in-flight
        race a real failure also exposes) and the NEXT search sees the
        shard down."""
        self._specs.append(FaultSpec(site=site, times=1, after=after,
                                     _kill_shard=int(shard)))
        return self

    def flap_shard(self, shard: int, *, period: int = 1) -> "FaultPlan":
        """Make ``shard`` flap: it alternates failed / healthy every
        ``period`` :func:`failed_shards` polls (starting failed) — the
        pathological readmission churn the health machine's hysteresis
        + dwell exists to absorb."""
        if period < 1:
            raise ValueError("period must be >= 1")
        self._flapping[int(shard)] = int(period)
        return self

    @property
    def specs(self) -> Tuple[FaultSpec, ...]:
        return tuple(self._specs)

    # -- evaluation --------------------------------------------------------
    def _check(self, site: str) -> None:
        pause = 0.0
        err: Optional[BaseException] = None
        with self._lock:
            for spec in self._specs:
                if not spec.matches(site):
                    continue
                if spec.times is not None and spec._fired >= spec.times:
                    continue
                spec._seen += 1
                if spec._seen <= spec.after:
                    continue
                if spec.p < 1.0 and self._rng.random() >= spec.p:
                    continue
                spec._fired += 1
                if spec._kill_shard is not None:
                    # shard-kill spec: the "failure" is a membership
                    # change, not an exception — the current call keeps
                    # its pre-kill routing, the next failed_shards()
                    # poll sees the shard down
                    self._failed_shards.add(spec._kill_shard)
                    _count(site)
                    continue
                if spec.is_delay:
                    # draw jitter under the lock (deterministic order),
                    # sleep after releasing it — a straggling site must
                    # not serialize checks at unrelated sites
                    pause += spec.delay + (
                        spec.jitter * self._rng.random() if spec.jitter else 0.0)
                    _count_delayed(site)
                    continue
                _count(site)
                try:
                    err = spec.exc(f"injected fault at {site!r}")
                except TypeError:
                    err = spec.exc()  # zero-arg factories
                break
        # a site scripting both latency and failure sleeps FIRST: the
        # injected slowness must be observable even on the failing call
        if pause > 0.0:
            _sleep(pause)
        if err is not None:
            raise err

    def _straggler_delays(self, n_shards: int) -> Tuple[float, ...]:
        """Per-shard injected delays for one routed search (0.0 for
        non-stragglers); jitter draws happen under the lock so the
        schedule replays under a pinned seed."""
        with self._lock:
            if not self._stragglers:
                return ()
            out = []
            for s in range(n_shards):
                delay, jitter = self._stragglers.get(s, (0.0, 0.0))
                out.append(delay + (jitter * self._rng.random()
                                    if jitter else 0.0))
            return tuple(out)

    @contextlib.contextmanager
    def active(self) -> Iterator["FaultPlan"]:
        """Install this plan for the body (plans nest LIFO)."""
        token = _push(self)
        try:
            yield self
        finally:
            _pop(token)


# ---------------------------------------------------------------------------
# active-plan stack.  A plain module global (not a ContextVar): sites are
# hit from worker threads the test's context never propagates to, and the
# whole point is that the *process* is under a scripted failure regime.

_ACTIVE: Optional[FaultPlan] = None
_STACK: List[FaultPlan] = []
_STATE_LOCK = threading.Lock()


def _push(plan: FaultPlan) -> int:
    global _ACTIVE
    with _STATE_LOCK:
        _STACK.append(plan)
        _ACTIVE = plan
        return len(_STACK) - 1


def _pop(token: int) -> None:
    global _ACTIVE
    with _STATE_LOCK:
        del _STACK[token:]
        _ACTIVE = _STACK[-1] if _STACK else None


def _count(site: str) -> None:
    from raft_tpu import observability as obs
    if obs.enabled():
        obs.registry().counter(f"resilience.fault.injected.{site}").inc()


def _count_delayed(site: str) -> None:
    from raft_tpu import observability as obs
    if obs.enabled():
        obs.registry().counter(f"resilience.fault.delayed.{site}").inc()


@contextlib.contextmanager
def inject(*args, seed: Optional[int] = None, **at_kwargs) -> Iterator[FaultPlan]:
    """Shorthand: ``with inject("comms.allreduce", times=1): ...``
    activates a one-spec plan (or an empty plan with no site, useful to
    scope :meth:`FaultPlan.fail_shards` set on the yielded plan)."""
    plan = FaultPlan(seed=seed)
    if args:
        plan.at(args[0], **at_kwargs)
    with plan.active():
        yield plan


def is_active() -> bool:
    return _ACTIVE is not None


def maybe_fail(site: str) -> None:
    """The library-side hook: raise if the active plan scripts a failure
    here.  **No plan active → a single attribute load + None check.**"""
    plan = _ACTIVE
    if plan is None:
        return
    plan._check(site)


def failed_shards(n_shards: int) -> Tuple[int, ...]:
    """Shards the active plan flags failed, clipped to ``range(n_shards)``
    (empty when no plan is active).  Flapping shards
    (:meth:`FaultPlan.flap_shard`) alternate membership per poll —
    starting failed — so each call may return a different set."""
    plan = _ACTIVE
    if plan is None:
        return ()
    with plan._lock:
        down = set(plan._failed_shards)
        if plan._flapping:
            poll = plan._flap_polls
            plan._flap_polls = poll + 1
            for s, period in plan._flapping.items():
                if (poll // period) % 2 == 0:
                    down.add(s)
    return tuple(sorted(s for s in down if 0 <= s < n_shards))


def straggler_delays(n_shards: int) -> Tuple[float, ...]:
    """Probe the active plan's per-shard straggler schedule WITHOUT
    sleeping: the per-shard delay vector for one routed search (empty
    when no plan scripts stragglers).  **No plan active → a single None
    check.**  ``distributed.ann`` uses this to decide which shards to
    hedge *before* paying the wait — a hedged shard's wait collapses to
    its deadline (or zero) because the replica answers instead; the
    residual wait goes through :func:`pause`."""
    plan = _ACTIVE
    if plan is None:
        return ()
    return plan._straggler_delays(n_shards)


def pause(seconds: float) -> None:
    """Host-side pause for an injected straggler wait.  The sleep lives
    here, not in ``distributed.ann``, because the timing-discipline lint
    confines ``time.sleep`` to the resilience layer.  Ticks
    ``resilience.fault.delayed.distributed.straggler`` whenever a
    positive wait is paid (the same counter :func:`straggler_pause`
    always ticked)."""
    if seconds > 0.0:
        _count_delayed("distributed.straggler")
        _sleep(seconds)


def straggler_pause(n_shards: int) -> Tuple[float, ...]:
    """The legacy one-shot straggler hook: probe + pause for the slowest
    scripted shard, returning the per-shard delay vector.  The SPMD
    dispatch semantics ("the merge completes when the last shard
    answers") make one max-delay pause per search the honest host-side
    model — every shard's results still merge, exactly.  Hedging-aware
    callers use :func:`straggler_delays` / :func:`pause` separately."""
    delays = straggler_delays(n_shards)
    if delays and max(delays) > 0.0:
        pause(max(delays))
    return delays
