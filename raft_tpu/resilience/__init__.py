"""raft_tpu.resilience — fault injection, retries/deadlines, checkpoints.

The failure-handling layer (PR 2) on top of PR 1's observability: the
reference ships ``raft::interruptible`` and versioned serializers
because cancellation and corrupt indexes are the first things that
break at scale; this package adds the rest of the survival kit for a
preemptible TPU fleet:

- :mod:`~raft_tpu.resilience.faults` — deterministic, seed-pinned fault
  injection at named sites (comms collectives, distributed search,
  sync points, stream IO) so every failure path below is testable;
- :mod:`~raft_tpu.resilience.retry` — jittered-backoff retries and
  :class:`Deadline` budgets on distributed entry points and index IO,
  counted as ``resilience.retry.*`` / ``resilience.giveup.*``;
- :mod:`~raft_tpu.resilience.checkpoint` — atomic (tmp+fsync+rename)
  build-stage persistence powering ``build(..., resume=True)``.

Hardened serialization (CRC32 envelopes, short-read detection,
:class:`~raft_tpu.core.serialize.CorruptIndexError`) lives in
:mod:`raft_tpu.core.serialize`; degraded-mode sharded search lives in
:mod:`raft_tpu.distributed.ann`.
"""

from raft_tpu.resilience.faults import (  # noqa: F401
    FaultInjected,
    FaultPlan,
    FaultSpec,
    TransientFault,
    failed_shards,
    inject,
    is_active,
    maybe_fail,
    straggler_pause,
)
from raft_tpu.resilience.retry import (  # noqa: F401
    DEFAULT_POLICY,
    Deadline,
    DeadlineExceededError,
    RetryPolicy,
    retry_call,
    retryable,
)
from raft_tpu.resilience.checkpoint import (  # noqa: F401
    CheckpointManager,
    as_manager,
    atomic_write,
)
from raft_tpu.resilience.io import (  # noqa: F401
    load_index,
    save_index,
)

# short internal aliases used by the neighbors save/load overloads
_save_index = save_index
_load_index = load_index

__all__ = [
    "CheckpointManager",
    "DEFAULT_POLICY",
    "Deadline",
    "DeadlineExceededError",
    "FaultInjected",
    "FaultPlan",
    "FaultSpec",
    "RetryPolicy",
    "TransientFault",
    "as_manager",
    "atomic_write",
    "failed_shards",
    "inject",
    "is_active",
    "load_index",
    "maybe_fail",
    "retry_call",
    "retryable",
    "save_index",
    "straggler_pause",
]
