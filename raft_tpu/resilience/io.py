"""File-level index IO: atomic save + retried load.

Backs the ``save`` / ``load`` filename overloads on the index modules
(:mod:`raft_tpu.neighbors.cagra` / ``ivf_flat`` / ``ivf_pq``): writes go
through :func:`~raft_tpu.resilience.checkpoint.atomic_write` (tmp +
fsync + rename — a crash never leaves a torn index file), and both
directions run under :func:`~raft_tpu.resilience.retry.retry_call` so
transient filesystem faults (flaky NFS, injected ``TransientFault``)
are retried while corruption
(:class:`~raft_tpu.core.serialize.CorruptIndexError`) fails fast —
re-reading a bit-flipped file cannot fix it.
"""

from __future__ import annotations

import io
from typing import BinaryIO, Callable, Optional, TypeVar

from raft_tpu.resilience import checkpoint as _checkpoint
from raft_tpu.resilience import retry as _retry

T = TypeVar("T")


def save_index(site: str, write_body: Callable[[BinaryIO], None],
               filename: str,
               policy: Optional[_retry.RetryPolicy] = None,
               deadline: Optional[_retry.Deadline] = None) -> None:
    """Serialize via ``write_body`` into ``filename`` atomically, with
    retry on transient IO errors (the serialization itself reruns — the
    payload must land whole or not at all)."""
    def attempt() -> None:
        buf = io.BytesIO()
        write_body(buf)
        _checkpoint.atomic_write(filename, buf.getvalue())

    _retry.retry_call(attempt, site=site, policy=policy, deadline=deadline)


def load_index(site: str, read_body: Callable[[BinaryIO], T],
               filename: str,
               policy: Optional[_retry.RetryPolicy] = None,
               deadline: Optional[_retry.Deadline] = None) -> T:
    """Open + deserialize ``filename`` with retry on transient IO errors.
    ``CorruptIndexError`` is deliberately NOT retryable."""
    def attempt() -> T:
        with open(filename, "rb") as f:
            return read_body(f)

    return _retry.retry_call(attempt, site=site, policy=policy,
                             deadline=deadline)
