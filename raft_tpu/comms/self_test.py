"""Comms self-tests, runnable on any mesh.

Reference: cpp/include/raft/comms/comms_test.hpp:171 + detail/test.hpp —
``test_collective_allreduce`` etc. assert the numerical result of each
collective *inside* the workers; raft-dask drives them via
``perform_test_comms_*`` (comms_utils.pyx:78+) on a LocalCUDACluster.

Here each ``perform_test_comms_*`` jits a shard_map over the session's mesh
and checks the result host-side — the virtual-8-CPU-device mesh is the
LocalCUDACluster analogue (SURVEY.md §4).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.compat import shard_map
from raft_tpu.comms.comms import Comms, op_t
from raft_tpu.comms.session import CommsSession

P = jax.sharding.PartitionSpec


def _run(session: CommsSession, fn, *args):
    mesh = session.mesh
    shard = shard_map(fn, mesh=mesh, in_specs=P(),
                          out_specs=P(session.axis_name), check_vma=False)
    return jax.jit(shard)(*args)


def perform_test_comms_allreduce(session: CommsSession) -> bool:
    """Each rank contributes 1; result must be n_ranks everywhere
    (reference: detail/test.hpp test_collective_allreduce)."""
    comms = session.comms()
    n = comms.get_size()

    def body():
        out = comms.allreduce(jnp.ones((), jnp.float32), op_t.SUM)
        return out[None]

    res = np.asarray(_run(session, body))
    return bool((res == n).all())


def perform_test_comms_bcast(session: CommsSession, root: int = 0) -> bool:
    comms = session.comms()

    def body():
        mine = (jax.lax.axis_index(session.axis_name) + 1).astype(jnp.float32)
        out = comms.bcast(mine, root=root)
        return out[None]

    res = np.asarray(_run(session, body))
    return bool((res == root + 1).all())


def perform_test_comms_reduce(session: CommsSession, root: int = 0) -> bool:
    comms = session.comms()
    n = comms.get_size()

    def body():
        out = comms.reduce(jnp.ones((), jnp.float32), root=root)
        return out[None]

    res = np.asarray(_run(session, body))
    return bool(res[root] == n)


def perform_test_comms_allgather(session: CommsSession) -> bool:
    comms = session.comms()
    n = comms.get_size()

    def body():
        mine = jax.lax.axis_index(session.axis_name).astype(
            jnp.float32)[None]
        return comms.allgather(mine).reshape(1, n)

    res = np.asarray(_run(session, body))
    expected = np.arange(n, dtype=np.float32)
    return bool((res == expected[None, :]).all())


def perform_test_comms_gatherv(session: CommsSession, root: int = 0) -> bool:
    """Ragged gather: rank r contributes r+1 elements of value r
    (reference: test.hpp test_collective_gatherv)."""
    comms = session.comms()
    n = comms.get_size()
    counts = [r + 1 for r in range(n)]
    pad_to = max(counts)

    def body():
        rank = jax.lax.axis_index(session.axis_name)
        mine = jnp.where(jnp.arange(pad_to) < rank + 1,
                         rank.astype(jnp.float32), jnp.nan)
        gathered, _ = comms.gatherv(mine, counts, root=root)
        return gathered[None]

    res = np.asarray(_run(session, body))[0]  # (n, pad_to)
    for r in range(n):
        if not (res[r, :counts[r]] == r).all():
            return False
    return True


def perform_test_comms_reducescatter(session: CommsSession) -> bool:
    comms = session.comms()
    n = comms.get_size()

    def body():
        full = jnp.ones((n,), jnp.float32)
        out = comms.reducescatter(full, op_t.SUM)
        return out

    res = np.asarray(_run(session, body))
    return bool((res == n).all())


def perform_test_comms_device_sendrecv(session: CommsSession) -> bool:
    """Ring shift-by-one (reference: test.hpp test_pointToPoint_simple_send_recv
    via UCX; ppermute ring here)."""
    comms = session.comms()
    n = comms.get_size()

    def body():
        mine = jax.lax.axis_index(session.axis_name).astype(jnp.float32)
        got = comms.device_send(mine, 1)   # send to rank+1
        return got[None]

    res = np.asarray(_run(session, body))
    expected = (np.arange(n) - 1) % n
    return bool((res.ravel() == expected).all())


def perform_test_comm_split(session: CommsSession) -> bool:
    """2D split: allreduce over rows then cols multiplies out to the full
    size (reference: test.hpp test_commsplit)."""
    mesh_devs = session.mesh.devices.ravel()
    n = len(mesh_devs)
    if n % 2 != 0:
        return True  # need an even grid
    mesh2 = jax.sharding.Mesh(
        np.asarray(mesh_devs).reshape(2, n // 2), ("row", "col"))

    def body():
        row = Comms("row")
        col = row.comm_split("col")
        a = row.allreduce(jnp.ones((), jnp.float32))
        b = col.allreduce(a)
        # MPI-style color split: ranks sharing a row-index communicate
        # along "col" — summing row indices over that communicator gives
        # row_index * n_cols
        same_row = row.comm_split(grouped_by="row")
        ri = jax.lax.axis_index("row").astype(jnp.float32)
        row_sum = same_row.allreduce(ri)
        ok = row_sum == ri * col.get_size()
        return (b * ok)[None]

    shard = shard_map(body, mesh=mesh2, in_specs=P(),
                          out_specs=P(("row", "col")), check_vma=False)
    res = np.asarray(jax.jit(shard)())
    return bool((res == n).all())


def perform_test_comms_isend_irecv(session: CommsSession) -> bool:
    """Tagged p2p: a ring exchange and a pair swap posted under two tags,
    completed by one waitall (reference: test.hpp
    test_pointToPoint_simple_send_recv — UCX tags over absolute ranks)."""
    comms = session.comms()
    n = comms.get_size()
    if n < 2:
        return True

    ring_dst = [(r + 1) % n for r in range(n)]
    ring_src = [(r - 1) % n for r in range(n)]
    # pairwise swap; for odd n the last rank self-sends (stays a permutation)
    swap = [r + 1 if r % 2 == 0 and r + 1 < n
            else (r - 1 if r % 2 == 1 else r) for r in range(n)]

    def body():
        mine = jax.lax.axis_index(session.axis_name).astype(jnp.float32)
        reqs = [
            comms.isend(mine, ring_dst, tag=0),
            comms.irecv(ring_src, tag=0),
            comms.isend(mine * 10.0, swap, tag=1),
            comms.irecv(swap, tag=1),        # swap is its own inverse
        ]
        ring_got, swap_got = comms.waitall(reqs)
        ok_ring = ring_got == (mine - 1) % n
        ok_swap = swap_got == jnp.asarray(swap, jnp.float32)[
            jax.lax.axis_index(session.axis_name)] * 10.0
        return (ok_ring & ok_swap)[None]

    res = np.asarray(_run(session, body))
    return bool(res.all())
