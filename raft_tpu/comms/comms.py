"""comms_t — the collective/p2p communicator abstraction.

Reference: cpp/include/raft/core/comms.hpp:125-230 ``comms_iface`` /
``comms_t`` (:242): allreduce, bcast, reduce, allgather(v), gather(v),
reducescatter, isend/irecv/waitall, device_send/recv/sendrecv/multicast,
comm_split, barrier, sync_stream; ops/dtypes enums :33-34; status_t :39.
Implementations: ``std_comms`` (NCCL + UCX, comms/detail/std_comms.hpp) and
``mpi_comms`` (comms/detail/mpi_comms.hpp).

TPU-native design (SURVEY.md §5 "distributed communication backend"):
collectives map 1:1 onto XLA's mesh collectives, which ride ICI within a
slice and DCN across slices —

    allreduce     → lax.psum / pmax / pmin
    bcast         → psum of root-masked value
    reduce        → allreduce (result defined on all ranks; the reference
                    only guarantees it at root)
    allgather     → lax.all_gather
    allgatherv    → all_gather of padded buffers + per-rank sizes
    reducescatter → lax.psum_scatter
    p2p send/recv → lax.ppermute (tagged-endpoint UCX analogue)
    comm_split    → a Comms bound to a different mesh axis (2D grids are
                    expressed as mesh axes up front — resource/sub_comms.hpp)

A ``Comms`` is a *traced-context* object: its methods are called inside
``shard_map``/``pjit`` over the mesh axis it is bound to, exactly where the
reference calls ``handle.get_comms().allreduce(...)`` inside a kernel-issuing
scope.  Rank/size are ``lax.axis_index``/mesh extent.  There is no NCCL
uniqueId rendezvous: device bootstrap is ``jax.distributed`` + the mesh
(see :mod:`raft_tpu.comms.session`).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import compat
from raft_tpu.core.error import expects
from raft_tpu import observability as obs
from raft_tpu.resilience import faults


def _record_collective(op: str, x=None) -> None:
    """Bump ``comms.<op>.calls`` / ``comms.<op>.bytes`` when collection is
    on, then give the fault harness its shot at ``comms.<op>``.

    Collectives run inside traced contexts (shard_map / pjit), so these
    counters record *traced* calls — collectives in the program, with bytes
    from the static shard shape — not per-step executions; a jit cache hit
    re-runs the collective without re-tracing it.  Injected faults at
    ``comms.*`` sites fire under the same trace-time contract (documented
    in resilience/faults.py)."""
    if obs.enabled():
        reg = obs.registry()
        reg.counter(f"comms.{op}.calls").inc()
        if x is not None:
            try:
                nbytes = int(x.size) * x.dtype.itemsize
            except (AttributeError, TypeError):
                nbytes = 0
            if nbytes:
                reg.counter(f"comms.{op}.bytes").inc(nbytes)
    faults.maybe_fail(f"comms.{op}")


class op_t:
    """Reduction ops (reference: core/comms.hpp:33 ``op_t``)."""

    SUM = "sum"
    PROD = "prod"
    MIN = "min"
    MAX = "max"


class status_t:
    """Reference: core/comms.hpp:39 ``status_t``."""

    SUCCESS = 0
    ERROR = 1
    ABORT = 2


@dataclasses.dataclass
class P2pRequest:
    """A posted isend/irecv awaiting waitall (reference: the request_t
    handles of comms.hpp:146-168).  ``pattern`` is the full rank→peer map;
    ``data`` holds the delivered buffer for recv requests after waitall."""

    kind: str                    # "send" | "recv"
    comms: "Comms"
    payload: Optional[object]
    pattern: Tuple[int, ...]
    tag: int
    data: Optional[object] = None


@dataclasses.dataclass(frozen=True)
class Comms:
    """Communicator bound to a named mesh axis (reference: comms_t,
    core/comms.hpp:242).  Methods must be called within a traced context
    (shard_map / pjit) that carries ``axis_name``."""

    axis_name: str = "data"
    _size: Optional[int] = None   # static size when known (host queries)

    # -- topology ----------------------------------------------------------
    def get_size(self):
        """Number of ranks on the axis (reference: get_size)."""
        if self._size is not None:
            return self._size
        return compat.axis_size(self.axis_name)

    def get_rank(self):
        """This shard's rank (reference: get_rank) — traced value."""
        return jax.lax.axis_index(self.axis_name)

    # -- collectives -------------------------------------------------------
    def _reduce_dispatch(self, x, op: str):
        """Shared lowering for allreduce/reduce (recorded by the callers
        under their own names, before dispatch — every branch, PROD
        included)."""
        if op == op_t.SUM:
            return jax.lax.psum(x, self.axis_name)
        if op == op_t.MAX:
            return jax.lax.pmax(x, self.axis_name)
        if op == op_t.MIN:
            return jax.lax.pmin(x, self.axis_name)
        if op == op_t.PROD:
            # no pprod primitive: log-domain trick would lose sign; use
            # all_gather + product (small payloads expected for PROD)
            return jnp.prod(jax.lax.all_gather(x, self.axis_name), axis=0)
        raise ValueError(f"unknown reduce op {op!r}")

    def allreduce(self, x, op: str = op_t.SUM):
        """Reference: comms.hpp allreduce → ncclAllReduce."""
        _record_collective("allreduce", x)
        return self._reduce_dispatch(x, op)

    def bcast(self, x, root: int = 0):
        """Broadcast root's value to all ranks (reference: bcast →
        ncclBroadcast): psum of the root-masked buffer."""
        _record_collective("bcast", x)
        is_root = jax.lax.axis_index(self.axis_name) == root
        masked = jnp.where(is_root, x, jnp.zeros_like(x))
        return jax.lax.psum(masked, self.axis_name)

    def reduce(self, x, root: int = 0, op: str = op_t.SUM):
        """Reduce to root (reference: reduce → ncclReduce).  XLA collectives
        are bulk-synchronous: every rank computes the result; the reference
        contract only *guarantees* it at root, so returning it everywhere is
        a superset.  Recorded under its OWN counter name (not aliased to
        allreduce) so per-op traffic attribution stays truthful."""
        _record_collective("reduce", x)
        return self._reduce_dispatch(x, op)

    def allgather(self, x):
        """Concatenate equal-size shards along a new leading axis
        (reference: allgather → ncclAllGather; callers reshape)."""
        _record_collective("allgather", x)
        return jax.lax.all_gather(x, self.axis_name)

    def _allgatherv_dispatch(self, x, recvcounts: Sequence[int]):
        counts = tuple(int(c) for c in recvcounts)
        pad_to = max(counts)
        pad = [(0, pad_to - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        gathered = jax.lax.all_gather(jnp.pad(x, pad), self.axis_name)
        return gathered, counts

    def allgatherv(self, x, recvcounts: Sequence[int]):
        """Ragged allgather (reference: allgatherv, 'MPI Does Not Make it
        Easy' padding dance done for the caller): shards padded to
        max(recvcounts) on axis 0; returns (n_ranks, max_count, ...) plus the
        static counts for unpadding."""
        _record_collective("allgatherv", x)
        return self._allgatherv_dispatch(x, recvcounts)

    def gather(self, x, root: int = 0):
        """Gather to root (reference: gather).  All ranks receive (superset
        of the root-only contract).  Own counter name, not an allgather
        alias."""
        _record_collective("gather", x)
        return jax.lax.all_gather(x, self.axis_name)

    def gatherv(self, x, recvcounts: Sequence[int], root: int = 0):
        """Ragged gather-to-root (reference: gatherv); own counter name."""
        _record_collective("gatherv", x)
        return self._allgatherv_dispatch(x, recvcounts)

    def reducescatter(self, x, op: str = op_t.SUM):
        """Reference: reducescatter → ncclReduceScatter.  ``x`` is the
        full-size buffer on every rank; each rank gets its 1/n slice of the
        sum, scattered along axis 0."""
        _record_collective("reducescatter", x)
        expects(op == op_t.SUM,
                "reducescatter supports SUM (as XLA psum_scatter)")
        return jax.lax.psum_scatter(x, self.axis_name, tiled=True)

    # -- tagged point-to-point (UCX isend/irecv/waitall analogue) ----------
    #
    # The reference's UCX path (comms.hpp:146-160 isend/irecv, :168 waitall;
    # ucp_helper.hpp) posts per-rank absolute-destination messages matched
    # by tag at completion.  XLA has no dynamic routing: a communication
    # pattern must be static at trace time.  The honest TPU translation
    # keeps the *posting* API (absolute ranks, tags, deferred completion)
    # but takes the full rank→rank pattern up front — every rank runs the
    # same program, so rank r's destination is ``dst[r]`` of a shared list.
    # waitall() fuses all posted messages of a tag into ONE ppermute (the
    # tag plays NCCL-group/UCX-tag's role of batching and matching).

    def isend(self, x, dst: Sequence[int], tag: int = 0) -> "P2pRequest":
        """Post a send: rank r's buffer goes to absolute rank ``dst[r]``
        (reference: comms.hpp:146 ``isend``).  Completion at waitall().

        Permutation patterns complete as ONE ``ppermute`` (ICI-direct).
        Partial fan-in patterns (``dst[r] = -1`` marks rank r as not
        sending; an injective map over the senders) complete via an
        ``all_gather`` + per-rank select — n× the bandwidth of a true
        p2p message, the honest XLA translation of dynamic routing.
        Two senders targeting one rank need two tags (one recv can only
        name one source); waitall() rejects unclaimed sends."""
        _record_collective("isend", x)
        n = self.get_size()
        expects(isinstance(n, int), "isend needs a static axis size")
        dsts = []
        for d in dst:
            d = int(d)
            expects(-1 <= d < n,
                    f"isend: dst ranks must be in [0, {n}) or the -1 "
                    "no-send sentinel")
            dsts.append(d)
        expects(len(dsts) == n, f"isend: dst must list all {n} ranks")
        return P2pRequest(kind="send", comms=self, payload=x,
                          pattern=tuple(dsts), tag=tag)

    def irecv(self, src: Sequence[int], tag: int = 0) -> "P2pRequest":
        """Post a receive: rank r expects the message sent by absolute rank
        ``src[r]`` under ``tag`` (reference: comms.hpp:156 ``irecv``).  The
        buffer materializes at waitall().  ``src[r] = -1`` marks rank r
        as receiving nothing for this tag (fan-in patterns where only
        some ranks are destinations); its buffer fills with zeros."""
        n = self.get_size()
        expects(isinstance(n, int), "irecv needs a static axis size")
        srcs = []
        for s in src:
            s = int(s)
            expects(-1 <= s < n,
                    f"irecv: src ranks must be in [0, {n}) or the -1 "
                    "receive-nothing sentinel")
            srcs.append(s)
        expects(len(srcs) == n, f"irecv: src must list all {n} ranks")
        return P2pRequest(kind="recv", comms=self, payload=None,
                          pattern=tuple(srcs), tag=tag)

    def waitall(self, requests: Sequence["P2pRequest"]):
        """Complete posted p2p requests (reference: comms.hpp:168
        ``waitall``).  Matches send/recv pairs by tag, checks the patterns
        agree, issues one ppermute per tag, and fills each recv request's
        ``.data``.  Returns the list of delivered recv buffers in posting
        order."""
        sends = {r.tag: r for r in requests if r.kind == "send"}
        recvs = [r for r in requests if r.kind == "recv"]
        expects(len(sends) == len([r for r in requests
                                   if r.kind == "send"]),
                "waitall: one send per tag (batch distinct messages under "
                "distinct tags)")
        delivered = []
        for r in recvs:
            expects(r.tag in sends, f"waitall: no send posted for tag "
                                    f"{r.tag}")
            s = sends[r.tag]
            expects(s.comms.axis_name == r.comms.axis_name,
                    "waitall: send and recv posted on different "
                    "communicators for tag "
                    f"{r.tag} ({s.comms.axis_name} vs {r.comms.axis_name})")
            # consistency both ways: the sender targeting rank k must be
            # the rank k expects (dst[src[k]] == k; src -1 receives
            # nothing), and every posted send must be claimed by its
            # destination — an unclaimed message would otherwise vanish
            # silently (true many-to-one needs one tag per sender)
            for k, src_k in enumerate(r.pattern):
                if src_k >= 0:
                    expects(s.pattern[src_k] == k,
                            "waitall: send dst pattern and recv src "
                            f"pattern disagree at rank {k}")
            for j, dst_j in enumerate(s.pattern):
                if dst_j >= 0:
                    expects(r.pattern[dst_j] == j,
                            f"waitall: rank {j}'s send to rank {dst_j} "
                            "is not claimed by any receiver (two senders "
                            "to one rank need distinct tags)")
            n = s.comms.get_size()
            is_perm = (sorted(s.pattern) == list(range(n))
                       and min(r.pattern) >= 0)
            if is_perm:
                perm = [(rank, dst) for rank, dst in enumerate(s.pattern)]
                # permute on the axis the requests were POSTED on (not
                # the communicator waitall happens to be called through)
                r.data = jax.lax.ppermute(s.payload, s.comms.axis_name,
                                          perm)
            else:
                # many-to-one / partial fan-in: gather everyone's
                # payload and select the named source (src -1 -> zeros)
                gathered = jax.lax.all_gather(s.payload,
                                              s.comms.axis_name)
                me = jax.lax.axis_index(s.comms.axis_name)
                src_arr = jnp.asarray(r.pattern, jnp.int32)
                src_me = src_arr[me]
                picked = gathered[jnp.maximum(src_me, 0)]
                r.data = jnp.where(src_me >= 0, picked,
                                   jnp.zeros_like(picked))
            delivered.append(r.data)
        return delivered

    # -- point-to-point (shift patterns) -----------------------------------
    def device_sendrecv(self, x, dst: int, src: int):
        """Simultaneous send-to-dst / recv-from-src
        (reference: device_sendrecv).  Expressed as a ppermute: every rank
        declares its (src → this) edge; ranks not in any edge get zeros."""
        _record_collective("device_sendrecv", x)
        n = self.get_size()
        expects(isinstance(n, int),
                "device_sendrecv needs a static axis size")
        me = jax.lax.axis_index(self.axis_name)
        # build the permutation {(rank r sends to dst_r)}: here every rank
        # uses the same (dst, src) arguments, so the global pattern must be
        # consistent — the common shift patterns are expressed directly
        perm = [(r, (r + (dst - src)) % n) for r in range(n)]
        return jax.lax.ppermute(x, self.axis_name, perm)

    def device_send(self, x, dst_shift: int):
        """Shift-pattern send (reference: device_send; UCX tags replaced by
        a static ring/shift pattern — the idiomatic TPU p2p)."""
        _record_collective("device_send", x)
        n = self.get_size()
        perm = [(r, (r + dst_shift) % n) for r in range(n)]
        return jax.lax.ppermute(x, self.axis_name, perm)

    def device_recv(self, x, src_shift: int):
        return self.device_send(x, -src_shift)

    def device_multicast_sendrecv(self, x, dsts: Sequence[int]):
        """Multicast (reference: device_multicast_sendrecv): gather-based —
        every rank sees every shard, selects its sources."""
        _record_collective("multicast_sendrecv", x)
        return jax.lax.all_gather(x, self.axis_name)

    # -- split / sync ------------------------------------------------------
    def comm_split(self, axis_name: Optional[str] = None, key: int = 0, *,
                   grouped_by: Optional[str] = None) -> "Comms":
        """Sub-communicator (reference: comm_split, core/comms.hpp:272 —
        the 2D row/col grid pattern of resource/sub_comms.hpp).

        On TPU the 2D grid is the *mesh* itself, declared up front
        (``session.make_2d_session``); splitting means binding to one of
        its axes:

        - ``comm_split("row")`` — explicit axis bind (the 0.1.x API);
        - ``comm_split(grouped_by="row")`` — MPI-color style: ranks
          sharing a row-index form a communicator, which on a
          ("row", "col") mesh is the communicator ALONG "col" (and vice
          versa).  ``key`` (rank reordering) is accepted for signature
          parity; mesh-axis order already fixes ranks.
        """
        del key
        expects((axis_name is None) != (grouped_by is None),
                "comm_split: pass exactly one of axis_name / grouped_by")
        if axis_name is not None:
            return Comms(axis_name=axis_name)
        expects(grouped_by in ("row", "col"),
                "comm_split: grouped_by must be 'row' or 'col' (the "
                "2D-grid contract); arbitrary groupings require declaring "
                "them as a mesh axis up front")
        # same row-index ⇒ communicate along the col axis, and vice versa
        return Comms(axis_name="col" if grouped_by == "row" else "row")

    def barrier(self):
        """Reference: barrier.  A psum of a scalar is a full barrier in the
        bulk-synchronous XLA model."""
        _record_collective("barrier")
        jax.lax.psum(jnp.zeros((), jnp.int32), self.axis_name)

    def sync_stream(self) -> int:
        """Reference: sync_stream (error propagation point).  XLA surfaces
        collective failures at block_until_ready; inside a traced context
        this is a no-op returning SUCCESS."""
        return status_t.SUCCESS
