"""Communicator fabric over XLA mesh collectives.

Reference: cpp/include/raft/comms/ + core/comms.hpp (SURVEY.md §2.9) — the
``comms_t`` interface with NCCL/UCX (``std_comms``) and MPI (``mpi_comms``)
backends, injected into handles and bootstrapped by raft-dask.

TPU-native: one backend — XLA collectives on a ``jax.sharding.Mesh`` (ICI
within a slice, DCN across slices); process bootstrap via jax.distributed.
"""

from raft_tpu.comms.comms import (  # noqa: F401
    Comms,
    P2pRequest,
    op_t,
    status_t,
)
from raft_tpu.comms.session import (  # noqa: F401
    CommsSession,
    inject_comms_on_handle,
    local_handle,
    make_2d_session,
)
from raft_tpu.comms import self_test  # noqa: F401
