"""Comms session bootstrap — the raft-dask ``Comms`` analogue.

Reference: python/raft-dask/raft_dask/common/comms.py:37 ``Comms`` (init
:170 → NCCL uniqueId rendezvous → per-worker ``_func_init_all`` :424 →
``inject_comms_on_handle`` storing a ``comms_t`` into each worker's handle),
``local_handle(sessionId)`` :245 retrieving it inside submitted tasks.

TPU-native: the NCCL rendezvous + Dask orchestration collapse into
``jax.distributed.initialize`` (multi-host process bootstrap, done once by
the launcher) + a ``jax.sharding.Mesh`` over the global device set.  A
session pins (mesh, axis) and injects a :class:`raft_tpu.comms.Comms` into a
:class:`~raft_tpu.core.resources.DeviceResources` handle, which algorithms
retrieve via ``handle.get_comms()`` — the same wiring the reference's
``inject_comms_on_handle`` does.
"""

from __future__ import annotations

import uuid
from typing import Dict, Optional, Sequence

import jax
import numpy as np

from raft_tpu.comms.comms import Comms
from raft_tpu.core.error import expects
from raft_tpu.core.resources import DeviceResources

_sessions: Dict[str, "CommsSession"] = {}


def inject_comms_on_handle(handle: DeviceResources, comms: Comms,
                           mesh: jax.sharding.Mesh) -> None:
    """Store a communicator + its mesh in a handle (reference:
    comms_utils.pyx ``inject_comms_on_handle`` → handle COMMUNICATOR slot)."""
    handle.set_comms(comms)
    handle.add_resource_factory("mesh", lambda: mesh)


class CommsSession:
    """Session wiring a mesh + axis to worker handles (reference:
    raft_dask/common/comms.py:37 ``Comms``)."""

    def __init__(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        axis_name: str = "data",
    ) -> None:
        if mesh is None:
            devs = list(devices) if devices is not None else jax.devices()
            mesh = jax.sharding.Mesh(np.asarray(devs), (axis_name,))
        self.mesh = mesh
        self.axis_name = axis_name
        self.session_id = uuid.uuid4().hex
        self._initialized = False

    @property
    def nccl_initialized(self) -> bool:  # API-parity alias
        return self._initialized

    def init(self) -> "CommsSession":
        """Register the session (reference: Comms.init :170).  Rendezvous is
        jax.distributed (done at process start for multi-host); here we
        validate the mesh and publish the session."""
        expects(self.axis_name in self.mesh.axis_names,
                f"axis '{self.axis_name}' not in mesh {self.mesh.axis_names}")
        _sessions[self.session_id] = self
        self._initialized = True
        return self

    def comms(self) -> Comms:
        size = int(np.prod([self.mesh.shape[a]
                            for a in (self.axis_name,)]))
        return Comms(axis_name=self.axis_name, _size=size)

    def worker_handle(self, seed: int = 0) -> DeviceResources:
        """A handle with comms injected (reference: _func_build_handle :517
        + inject_comms_on_handle)."""
        handle = DeviceResources(mesh=self.mesh, seed=seed)
        inject_comms_on_handle(handle, self.comms(), self.mesh)
        return handle

    def destroy(self) -> None:
        """Tear down (reference: Comms.destroy)."""
        _sessions.pop(self.session_id, None)
        self._initialized = False


def make_2d_session(rows: int, cols: int,
                    devices: Optional[Sequence[jax.Device]] = None,
                    axis_name: str = "row") -> "CommsSession":
    """Session over a 2-D (row, col) device grid — the reference's
    sub-communicator pattern (core/resource/sub_comms.hpp; comm_split
    core/comms.hpp:272).  ``comms().comm_split(grouped_by="row"|"col")``
    (MPI-color style; ``key`` accepted for parity) then yields the
    row/col communicators."""
    devs = list(devices) if devices is not None else jax.devices()
    expects(len(devs) >= rows * cols,
            f"make_2d_session: need {rows * cols} devices, "
            f"have {len(devs)}")
    mesh = jax.sharding.Mesh(
        np.asarray(devs[:rows * cols]).reshape(rows, cols), ("row", "col"))
    return CommsSession(mesh=mesh, axis_name=axis_name)


def local_handle(session_id: str, seed: int = 0) -> DeviceResources:
    """Fetch a handle bound to a registered session (reference:
    raft_dask/common/comms.py:245 ``local_handle``)."""
    expects(session_id in _sessions, f"no comms session '{session_id}'")
    return _sessions[session_id].worker_handle(seed=seed)
