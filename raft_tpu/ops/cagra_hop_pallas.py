"""Pallas TPU kernel: fused CAGRA hop (score + dedupe + buffer merge).

The low-batch CAGRA serving path (buckets of 1-64 queries) spends its
hop not on the fat-row gather — one scattered fetch per parent — but on
the candidate epilogue: the (q, wd) approximate-distance matrix, the
membership-mask dedupe, and the bitonic buffer merge all round-trip
through HBM between XLA fusions, and at nq <= 64 every one of those
intermediates is a sliver that cannot amortize its traffic.  This kernel
applies the round-7 IVF-PQ fusion shape (see
:mod:`raft_tpu.ops.pq_group_scan_pallas`) to the graph walk: one kernel
invocation per hop scores all ``wd = search_width * graph_degree``
decoded neighbors against the queries, merges them into the sorted
``itopk`` buffer, and writes back ONLY the buffer — candidate distances
never touch HBM.

Dedupe happens *inside* the merge rather than as a pre-pass: each of the
``itopk`` min-extraction rounds neutralizes every remaining copy of the
extracted id, which removes candidate-vs-buffer and candidate-vs-self
duplicates in O(itopk * rows) vector ops instead of the O(wd^2)
membership masks of :func:`raft_tpu.neighbors.cagra._merge_candidates`.
Ties select the lowest concatenated row, and buffer rows come first, so
a candidate duplicating a buffer entry yields to the buffer copy and its
``visited`` flag — the walk's termination invariant is preserved.

Layout: queries ride the 128-lane axis (padded), buffer / candidate
slots ride sublanes, and ids + visited flags travel as exact f32 lanes
(ids < 2^24; the caller gates on index size).  Buffer values may be
``+inf`` (empty slots, id -1) — safe here because nothing multiplies
them; the IVF-PQ kernels' finite-sentinel trick is not needed.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# serving-bucket bounds: the fused hop targets the low-latency regime
_HOP_MAX_BATCH = 64
_HOP_MAX_ITOPK = 32
_HOP_MAX_WD = 128
_HOP_VMEM_BUDGET = 8 << 20
_LANES = 128


def supported_hop(nq: int, itopk: int, wd: int, pdim: int) -> bool:
    """Static shape gate for the fused hop kernel (VMEM + unroll)."""
    if not (0 < nq <= _HOP_MAX_BATCH and 0 < itopk <= _HOP_MAX_ITOPK):
        return False
    if not (0 < wd <= _HOP_MAX_WD and 0 < pdim <= 256):
        return False
    rows = itopk + wd
    vmem = (wd * pdim * _LANES * 4          # neighbor lanes
            + (pdim + 1) * _LANES * 4       # qpT + q_sq
            + 2 * wd * _LANES * 4           # nb_sq / nb_id
            + 9 * itopk * _LANES * 4        # buffer triple, in + out
            + 4 * rows * _LANES * 4)        # merge working set
    return vmem <= _HOP_VMEM_BUDGET


def _kernel_hop(qpT_ref, qsq_ref, nbp_ref, nbsq_ref, nbid_ref,
                bufd_ref, bufi_ref, vis_ref,
                od_ref, oi_ref, ov_ref, *,
                itopk: int, wd: int, pdim: int, ip_metric: bool):
    nq = qpT_ref.shape[1]
    qpT = qpT_ref[:]                                   # (pdim, nq)

    # ---- score: wd unrolled VPU rows, candidates stay in VMEM ----------
    ip_rows = []
    for j in range(wd):
        nb_j = nbp_ref[j * pdim:(j + 1) * pdim, :]     # (pdim, nq)
        ip_rows.append(jnp.sum(qpT * nb_j, axis=0, keepdims=True))
    ip = jnp.concatenate(ip_rows, axis=0)              # (wd, nq)
    if ip_metric:
        d = -ip                                        # KEY space
    else:
        d = qsq_ref[:] + nbsq_ref[:] - 2.0 * ip
    cid = nbid_ref[:]                                  # (wd, nq) f32 ids
    ok = cid >= 0.0
    d = jnp.where(ok, d, jnp.inf)
    cid = jnp.where(ok, cid, -1.0)

    # ---- merge with in-pass dedupe -------------------------------------
    cat_v = jnp.concatenate([bufd_ref[:], d], axis=0)  # (rows, nq)
    cat_i = jnp.concatenate([bufi_ref[:], cid], axis=0)
    cat_s = jnp.concatenate([vis_ref[:], jnp.zeros_like(d)], axis=0)
    rows = itopk + wd
    riota = jax.lax.broadcasted_iota(jnp.int32, (rows, nq), 0)
    out_d, out_i, out_s = [], [], []
    for _ in range(itopk):
        m = jnp.min(cat_v, axis=0, keepdims=True)
        hit = cat_v == m
        rmin = jnp.min(jnp.where(hit, riota, rows), axis=0, keepdims=True)
        sel = riota == rmin
        wi = jnp.sum(jnp.where(sel, cat_i, 0.0), axis=0, keepdims=True)
        ws = jnp.max(jnp.where(sel, cat_s, 0.0), axis=0, keepdims=True)
        out_d.append(m)
        out_i.append(wi)
        out_s.append(ws)
        # kill the winner AND every other copy of its id: this is the
        # dedupe — a real id appears at most once in the output buffer
        kill = sel | ((cat_i == wi) & (wi >= 0.0))
        cat_v = jnp.where(kill, jnp.inf, cat_v)
    od_ref[:] = jnp.concatenate(out_d, axis=0)
    oi_ref[:] = jnp.concatenate(out_i, axis=0)
    ov_ref[:] = jnp.concatenate(out_s, axis=0)


@functools.partial(jax.jit,
                   static_argnames=("itopk", "ip_metric", "interpret"))
def fused_hop(qp_t, q_sq, nb_p, nb_sq, nb_id, buf_d, buf_i, visited, *,
              itopk: int, ip_metric: bool, interpret: bool = False
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One fused graph-walk hop.

    Args (natural walk layout, nq rows):
      qp_t     (nq, pdim) query projections (table scale already folded)
      q_sq     (nq,) exact query squared norms
      nb_p     (nq, wd, pdim) decoded neighbor projections
      nb_sq    (nq, wd) neighbor squared norms
      nb_id    (nq, wd) int32 neighbor ids, -1 = masked parent slot
      buf_d / buf_i / visited   (nq, itopk) sorted candidate buffer

    Returns the merged (buf_d, buf_i int32, visited bool), sorted
    ascending-better, ids deduped — drop-in for the XLA
    ``_merge_candidates`` + ``_bitonic_merge`` pair.
    """
    nq, wd, pdim = nb_p.shape
    pad = _LANES - nq

    def col(x, fill):
        x = x.astype(jnp.float32)
        return jnp.pad(x.T, ((0, 0), (0, pad)), constant_values=fill)

    qpT = col(qp_t, 0.0)                               # (pdim, LANES)
    qsq = col(q_sq[:, None], 0.0)                      # (1, LANES)
    nbp = jnp.pad(
        jnp.transpose(nb_p.astype(jnp.float32), (1, 2, 0)),
        ((0, 0), (0, 0), (0, pad))).reshape(wd * pdim, _LANES)
    nbsq = col(nb_sq, 0.0)                             # (wd, LANES)
    nbid = col(nb_id, -1.0)
    bufd = col(buf_d, jnp.inf)
    bufi = col(buf_i, -1.0)
    vis = col(visited, 1.0)

    out = pl.pallas_call(
        functools.partial(_kernel_hop, itopk=itopk, wd=wd, pdim=pdim,
                          ip_metric=ip_metric),
        out_shape=[jax.ShapeDtypeStruct((itopk, _LANES), jnp.float32)] * 3,
        interpret=interpret,
    )(qpT, qsq, nbp, nbsq, nbid, bufd, bufi, vis)
    od, oi, ov = (o[:, :nq].T for o in out)
    return od, oi.astype(jnp.int32), ov > 0.5
