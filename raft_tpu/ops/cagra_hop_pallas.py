"""Pallas TPU kernel: fused CAGRA hop (score + dedupe + buffer merge).

The low-batch CAGRA serving path (buckets of 1-64 queries) spends its
hop not on the fat-row gather — one scattered fetch per parent — but on
the candidate epilogue: the (q, wd) approximate-distance matrix, the
membership-mask dedupe, and the bitonic buffer merge all round-trip
through HBM between XLA fusions, and at nq <= 64 every one of those
intermediates is a sliver that cannot amortize its traffic.  This kernel
applies the round-7 IVF-PQ fusion shape (see
:mod:`raft_tpu.ops.pq_group_scan_pallas`) to the graph walk: one kernel
invocation per hop scores all ``wd = search_width * graph_degree``
decoded neighbors against the queries, merges them into the sorted
``itopk`` buffer, and writes back ONLY the buffer — candidate distances
never touch HBM.

Dedupe happens *inside* the merge rather than as a pre-pass: each of the
``itopk`` min-extraction rounds neutralizes every remaining copy of the
extracted id, which removes candidate-vs-buffer and candidate-vs-self
duplicates in O(itopk * rows) vector ops instead of the O(wd^2)
membership masks of :func:`raft_tpu.neighbors.cagra._merge_candidates`.
Ties select the lowest concatenated row, and buffer rows come first, so
a candidate duplicating a buffer entry yields to the buffer copy and its
``visited`` flag — the walk's termination invariant is preserved.

Layout: queries ride the 128-lane axis (padded), buffer / candidate
slots ride sublanes, and ids + visited flags travel as exact f32 lanes
(ids < 2^24; the caller gates on index size).  Buffer values may be
``+inf`` (empty slots, id -1) — safe here because nothing multiplies
them; the IVF-PQ kernels' finite-sentinel trick is not needed.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from raft_tpu.ops import vmem_budget as vb

# serving-bucket bounds: the fused hop targets the low-latency regime
_HOP_MAX_BATCH = 64
_HOP_MAX_ITOPK = 32          # legacy in-pass merge (W=1)
_HOP_MAX_ITOPK_STAGED = 64   # staged extraction + bitonic merge (W=2)
_HOP_MAX_WD = 128
_HOP_VMEM_BUDGET = 8 << 20
_LANES = 128


def hop_merge_window(nq: int, itopk: int, wd: int, pdim: int,
                     requested: int = 0) -> int:
    """Host-static merge-window choice for the fused hop: 1 = legacy
    in-pass merge (itopk <= 32), 2 = staged extraction + in-kernel
    bitonic merge (itopk to 64), 0 = no variant fits (fall back to the
    XLA hop).  The walk consumes the merged buffer every hop, so there
    is no deeper window; ``requested`` 0 is auto."""
    if not (0 < nq <= _HOP_MAX_BATCH and 0 < wd <= _HOP_MAX_WD
            and 0 < pdim <= 256):
        return 0
    return vb.select_hop_window(requested, itopk=itopk, wd=wd, pdim=pdim,
                                lanes=_LANES, budget=_HOP_VMEM_BUDGET,
                                itopk_legacy_max=_HOP_MAX_ITOPK,
                                itopk_staged_max=_HOP_MAX_ITOPK_STAGED)


def supported_hop(nq: int, itopk: int, wd: int, pdim: int,
                  merge_window: int = 0) -> bool:
    """Static shape gate for the fused hop kernel (VMEM + unroll); some
    merge window — legacy or staged — must fit."""
    return hop_merge_window(nq, itopk, wd, pdim, merge_window) > 0


def hop_reject_reason(nq: int, itopk: int, wd: int, pdim: int,
                      merge_window: int = 0) -> str:
    """Reason code for a fused-hop gate miss ('' when supported):
    'itopk-gate' (itopk past the staged bound, or its VMEM share is
    what overflows), 'bucket-too-wide' (batch / width / pdim)."""
    if supported_hop(nq, itopk, wd, pdim, merge_window):
        return ""
    if itopk > _HOP_MAX_ITOPK_STAGED:
        return "itopk-gate"
    if not (0 < nq <= _HOP_MAX_BATCH and 0 < wd <= _HOP_MAX_WD
            and 0 < pdim <= 256):
        return "bucket-too-wide"
    if itopk > _HOP_MAX_ITOPK:
        return "itopk-gate"
    return "bucket-too-wide"


def _unpack_hop_admission(adm_ref, wd):
    """Unpack the (W32, LANES) packed admission words — bit b of word w
    admitting candidate ``32*w + b`` of each lane's query — to a
    (wd, LANES) 0/1 block.  Sublane-axis shift/mask, no gather."""
    aw = adm_ref[:]                                    # (W32, LANES) int32
    shifts = jax.lax.broadcasted_iota(
        jnp.int32, (aw.shape[0], 32, aw.shape[1]), 1)
    bits = (aw[:, None, :] >> shifts) & 1
    return bits.reshape(aw.shape[0] * 32, aw.shape[1])[:wd]


def _hop_scores(qpT_ref, qsq_ref, nbp_ref, nbsq_ref, nbid_ref, wd, pdim,
                ip_metric, adm=None):
    """Shared score block: wd unrolled VPU rows — the (wd, nq) distance
    KEYS and f32 candidate ids, masked parents at (+inf, -1).  ``adm``
    (wd, nq) 0/1 admission bits fold rejected candidates through the
    SAME (+inf, -1) seam as masked parents — a filtered node never
    enters the buffer, so the walk does not traverse it."""
    qpT = qpT_ref[:]                                   # (pdim, nq)
    ip_rows = []
    for j in range(wd):
        nb_j = nbp_ref[j * pdim:(j + 1) * pdim, :]     # (pdim, nq)
        ip_rows.append(jnp.sum(qpT * nb_j, axis=0, keepdims=True))
    ip = jnp.concatenate(ip_rows, axis=0)              # (wd, nq)
    if ip_metric:
        d = -ip                                        # KEY space
    else:
        d = qsq_ref[:] + nbsq_ref[:] - 2.0 * ip
    cid = nbid_ref[:]                                  # (wd, nq) f32 ids
    ok = cid >= 0.0
    if adm is not None:
        ok = ok & (adm > 0)
    d = jnp.where(ok, d, jnp.inf)
    cid = jnp.where(ok, cid, -1.0)
    return d, cid


def _kernel_hop(qpT_ref, qsq_ref, nbp_ref, nbsq_ref, nbid_ref,
                bufd_ref, bufi_ref, vis_ref, *rest,
                itopk: int, wd: int, pdim: int, ip_metric: bool,
                has_adm: bool = False):
    adm_ref, rest = (rest[0], rest[1:]) if has_adm else (None, rest)
    od_ref, oi_ref, ov_ref = rest
    nq = qpT_ref.shape[1]

    adm = _unpack_hop_admission(adm_ref, wd) if has_adm else None
    d, cid = _hop_scores(qpT_ref, qsq_ref, nbp_ref, nbsq_ref, nbid_ref,
                         wd, pdim, ip_metric, adm=adm)

    # ---- merge with in-pass dedupe -------------------------------------
    cat_v = jnp.concatenate([bufd_ref[:], d], axis=0)  # (rows, nq)
    cat_i = jnp.concatenate([bufi_ref[:], cid], axis=0)
    cat_s = jnp.concatenate([vis_ref[:], jnp.zeros_like(d)], axis=0)
    rows = itopk + wd
    riota = jax.lax.broadcasted_iota(jnp.int32, (rows, nq), 0)
    out_d, out_i, out_s = [], [], []
    for _ in range(itopk):
        m = jnp.min(cat_v, axis=0, keepdims=True)
        hit = cat_v == m
        rmin = jnp.min(jnp.where(hit, riota, rows), axis=0, keepdims=True)
        sel = riota == rmin
        wi = jnp.sum(jnp.where(sel, cat_i, 0.0), axis=0, keepdims=True)
        ws = jnp.max(jnp.where(sel, cat_s, 0.0), axis=0, keepdims=True)
        out_d.append(m)
        out_i.append(wi)
        out_s.append(ws)
        # kill the winner AND every other copy of its id: this is the
        # dedupe — a real id appears at most once in the output buffer
        kill = sel | ((cat_i == wi) & (wi >= 0.0))
        cat_v = jnp.where(kill, jnp.inf, cat_v)
    od_ref[:] = jnp.concatenate(out_d, axis=0)
    oi_ref[:] = jnp.concatenate(out_i, axis=0)
    ov_ref[:] = jnp.concatenate(out_s, axis=0)


def _kernel_hop_staged(qpT_ref, qsq_ref, nbp_ref, nbsq_ref, nbid_ref,
                       bufd_ref, bufi_ref, vis_ref, *rest,
                       itopk: int, wd: int, pdim: int, ip_metric: bool,
                       has_adm: bool = False):
    """Staged hop variant (merge window 2): instead of itopk
    min-extraction rounds over ALL itopk+wd rows, candidates are
    deduped, extracted SORTED into the (t, nq) staging block
    (t = min(itopk, wd) — deeper ranks cannot survive the merge), and
    folded into the buffer by one in-kernel bitonic merge — the exact
    compare-exchange network of ``cagra._bitonic_merge`` (concat
    [buffer | inf pad | staged DESCENDING] is bitonic; strict-> swaps
    keep tie order), so outputs match the XLA twin.  This lifts the
    itopk gate from 32 to 64: extraction passes shrink from
    itopk*(itopk+wd) to t*wd row-ops plus a log2-depth merge."""
    adm_ref, rest = (rest[0], rest[1:]) if has_adm else (None, rest)
    od_ref, oi_ref, ov_ref, stg_d, stg_i = rest
    nq = qpT_ref.shape[1]

    adm = _unpack_hop_admission(adm_ref, wd) if has_adm else None
    d, cid = _hop_scores(qpT_ref, qsq_ref, nbp_ref, nbsq_ref, nbid_ref,
                         wd, pdim, ip_metric, adm=adm)

    # ---- candidate-vs-buffer dedupe: membership kill against every
    # buffer row (duplicate ids carry bitwise-identical keys, so the
    # buffer copy — and its visited flag — is the one that survives) ----
    bufi = bufi_ref[:]                                 # (itopk, nq)
    dup = jnp.zeros(d.shape, jnp.bool_)
    for j in range(itopk):
        dup = dup | (cid == bufi[j:j + 1, :])
    ok = (cid >= 0.0) & ~dup
    d = jnp.where(ok, d, jnp.inf)
    cid = jnp.where(ok, cid, -1.0)

    # ---- staged extraction: top-t of the candidates, sorted, with
    # in-pass self-dedupe; stored DESCENDING so the bitonic concat
    # needs no runtime reverse.  Exhausted ranks emit (inf, -1) —
    # exactly the XLA twin's killed/padded candidate rows ----
    t = vb.hop_stage_rows(itopk, wd)
    riota = jax.lax.broadcasted_iota(jnp.int32, (wd, nq), 0)
    for j in range(t):
        m = jnp.min(d, axis=0, keepdims=True)          # (1, nq)
        rmin = jnp.min(jnp.where(d == m, riota, wd), axis=0, keepdims=True)
        sel = riota == rmin
        wi = jnp.sum(jnp.where(sel, cid, 0.0), axis=0, keepdims=True)
        wi = jnp.where(jnp.isinf(m), -1.0, wi)
        stg_d[t - 1 - j:t - j, :] = m
        stg_i[t - 1 - j:t - j, :] = wi
        kill = sel | ((cid == wi) & (wi >= 0.0))
        d = jnp.where(kill, jnp.inf, d)

    # ---- bitonic merge of the sorted buffer with the staged block ----
    size = vb.hop_pow2(itopk + t)
    pad = size - itopk - t
    k_ = jnp.concatenate([bufd_ref[:],
                          jnp.full((pad, nq), jnp.inf, jnp.float32),
                          stg_d[:]], axis=0)           # (size, nq)
    i_ = jnp.concatenate([bufi_ref[:],
                          jnp.full((pad, nq), -1.0, jnp.float32),
                          stg_i[:]], axis=0)
    v_ = jnp.concatenate([vis_ref[:],
                          jnp.zeros((pad + t, nq), jnp.float32)], axis=0)

    srow = jax.lax.broadcasted_iota(jnp.int32, (size, nq), 0)

    def roll(x, sh):
        return jnp.concatenate([x[sh:], x[:sh]], axis=0)

    s = size // 2
    while s >= 1:
        lo = (srow & s) == 0
        up_k, dn_k = roll(k_, s), roll(k_, size - s)
        up_i, dn_i = roll(i_, s), roll(i_, size - s)
        up_v, dn_v = roll(v_, s), roll(v_, size - s)
        swap_lo = k_ > up_k                            # strict: ties stay
        swap_hi = dn_k > k_
        k_ = jnp.where(lo, jnp.where(swap_lo, up_k, k_),
                       jnp.where(swap_hi, dn_k, k_))
        i_ = jnp.where(lo, jnp.where(swap_lo, up_i, i_),
                       jnp.where(swap_hi, dn_i, i_))
        v_ = jnp.where(lo, jnp.where(swap_lo, up_v, v_),
                       jnp.where(swap_hi, dn_v, v_))
        s //= 2
    od_ref[:] = k_[:itopk]
    oi_ref[:] = i_[:itopk]
    ov_ref[:] = v_[:itopk]


@functools.partial(jax.jit,
                   static_argnames=("itopk", "ip_metric", "interpret",
                                    "merge_window"))
def fused_hop(qp_t, q_sq, nb_p, nb_sq, nb_id, buf_d, buf_i, visited, *,
              itopk: int, ip_metric: bool, interpret: bool = False,
              merge_window: int = 0, adm_words=None
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One fused graph-walk hop.

    Args (natural walk layout, nq rows):
      qp_t     (nq, pdim) query projections (table scale already folded)
      q_sq     (nq,) exact query squared norms
      nb_p     (nq, wd, pdim) decoded neighbor projections
      nb_sq    (nq, wd) neighbor squared norms
      nb_id    (nq, wd) int32 neighbor ids, -1 = masked parent slot
      buf_d / buf_i / visited   (nq, itopk) sorted candidate buffer

    Returns the merged (buf_d, buf_i int32, visited bool), sorted
    ascending-better, ids deduped — drop-in for the XLA
    ``_merge_candidates`` + ``_bitonic_merge`` pair.

    ``merge_window`` selects the variant (0 auto): 1 = legacy in-pass
    merge (itopk <= 32), 2 = staged extraction + in-kernel bitonic
    merge (itopk to 64) — see :func:`hop_merge_window`.

    ``adm_words`` (nq, ceil(wd/32)) int32, optional: packed
    per-(query, candidate) admission bits over this hop's ``wd``
    neighbors (bit j of a query's stream admits its candidate j);
    rejected candidates fold like masked parents.
    """
    nq, wd, pdim = nb_p.shape
    if merge_window > 0:
        mw = 2 if merge_window > 1 else 1
    else:
        mw = 1 if itopk <= _HOP_MAX_ITOPK else 2
    pad = _LANES - nq

    def col(x, fill):
        x = x.astype(jnp.float32)
        return jnp.pad(x.T, ((0, 0), (0, pad)), constant_values=fill)

    qpT = col(qp_t, 0.0)                               # (pdim, LANES)
    qsq = col(q_sq[:, None], 0.0)                      # (1, LANES)
    nbp = jnp.pad(
        jnp.transpose(nb_p.astype(jnp.float32), (1, 2, 0)),
        ((0, 0), (0, 0), (0, pad))).reshape(wd * pdim, _LANES)
    nbsq = col(nb_sq, 0.0)                             # (wd, LANES)
    nbid = col(nb_id, -1.0)
    bufd = col(buf_d, jnp.inf)
    bufi = col(buf_i, -1.0)
    vis = col(visited, 1.0)

    has_adm = adm_words is not None
    args = [qpT, qsq, nbp, nbsq, nbid, bufd, bufi, vis]
    if has_adm:
        # packed words ride sublanes (W32, LANES); padded lanes get 0
        # words (inadmissible) and are sliced away with the other pads
        args.append(jnp.pad(adm_words.astype(jnp.int32).T,
                            ((0, 0), (0, pad))))

    kern = _kernel_hop if mw <= 1 else _kernel_hop_staged
    out = pl.pallas_call(
        functools.partial(kern, itopk=itopk, wd=wd, pdim=pdim,
                          ip_metric=ip_metric, has_adm=has_adm),
        out_shape=[jax.ShapeDtypeStruct((itopk, _LANES), jnp.float32)] * 3,
        scratch_shapes=vb.hop_scratch(itopk, wd, mw, _LANES),
        interpret=interpret,
    )(*args)
    od, oi, ov = (o[:, :nq].T for o in out)
    return od, oi.astype(jnp.int32), ov > 0.5
