"""Pallas TPU kernel: fused L2 distance + 1-NN argmin.

The k-means / IVF hot kernel (reference: distance/fused_l2_nn.cuh:100
``fusedL2NN`` — a CUTLASS-tiled GEMM with a custom argmin epilogue in
registers; detail/fused_l2_nn.cuh).  The XLA formulation
(:mod:`raft_tpu.distance.fused_l2_nn`) scans y tiles and materializes an
(m, tile_n) distance block in HBM per step; this kernel keeps the distance
tile in VMEM and fuses the argmin epilogue right after the MXU dot —
the same register-resident epilogue property the CUDA kernel buys, expressed
as a Pallas grid over (m tiles, n tiles) with the n axis innermost
accumulating into the output block.

Grid layout:
  grid = (m/TILE_M, n/TILE_N); x block (TILE_M, k) revisits across j;
  y block (TILE_N, k) marches; outputs (1, TILE_M) revisit across j and
  accumulate the running (min, argmin).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TILE_M = 256
_TILE_N = 512
_BIG = 3.0e38  # Python float: jnp scalars would be captured as consts


def _kernel(x_ref, y_ref, xsq_ref, ysq_ref, out_d_ref, out_i_ref, *,
            precision):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_d_ref[...] = jnp.full_like(out_d_ref, _BIG)
        out_i_ref[...] = jnp.zeros_like(out_i_ref)

    x = x_ref[...]                                   # (TILE_M, k)
    y = y_ref[...]                                   # (TILE_N, k)
    # MXU: (TILE_M, k) @ (k, TILE_N), fp32 accumulate; precision follows
    # the library policy (HIGHEST = fp32-true multi-pass, as the XLA path)
    ip = jax.lax.dot_general(x, y, (((1,), (1,)), ((), ())),
                             precision=precision,
                             preferred_element_type=jnp.float32)
    d = xsq_ref[...].reshape(-1, 1) + ysq_ref[...].reshape(1, -1) \
        - 2.0 * ip                                   # (TILE_M, TILE_N)
    # argmin epilogue, VMEM-resident: min + first-match index
    tile_min = jnp.min(d, axis=1)
    iota = jax.lax.broadcasted_iota(jnp.int32, d.shape, 1)
    tile_arg = jnp.min(jnp.where(d == tile_min[:, None], iota,
                                 jnp.int32(2 ** 30)), axis=1)
    tile_arg = tile_arg + j * _TILE_N

    best = out_d_ref[0, :]
    upd = tile_min < best
    out_d_ref[0, :] = jnp.where(upd, tile_min, best)
    out_i_ref[0, :] = jnp.where(upd, tile_arg, out_i_ref[0, :])


def fused_l2_nn_pallas(x: jax.Array, y: jax.Array, *, sqrt: bool = False,
                       interpret: bool = False, precision=None
                       ) -> Tuple[jax.Array, jax.Array]:
    """(m, k), (n, k) -> (min L2^2 distance (m,), argmin (m,) int32).

    Drop-in for :func:`raft_tpu.distance.fused_l2_nn.fused_l2_nn`'s core.
    ``interpret=True`` runs the Pallas interpreter (CPU-testable).
    The precision policy is resolved HERE (eager boundary) and keys the jit
    cache — reading the global inside the trace would go stale under
    ``matmul_precision()``.
    """
    from raft_tpu.utils.precision import get_matmul_precision
    if precision is None:
        precision = get_matmul_precision()
    return _pallas_jit(x, y, sqrt=sqrt, interpret=interpret,
                       precision=precision)


@functools.partial(jax.jit,
                   static_argnames=("sqrt", "interpret", "precision"))
def _pallas_jit(x: jax.Array, y: jax.Array, *, sqrt: bool,
                interpret: bool, precision) -> Tuple[jax.Array, jax.Array]:
    m, k = x.shape
    n = y.shape[0]
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)

    m_pad = -(-m // _TILE_M) * _TILE_M
    n_pad = -(-n // _TILE_N) * _TILE_N
    xp = jnp.pad(xf, ((0, m_pad - m), (0, 0)))
    yp = jnp.pad(yf, ((0, n_pad - n), (0, 0)))
    xsq = jnp.sum(xp * xp, axis=1).reshape(1, m_pad)
    # padded y rows get +BIG norms so they never win the argmin
    ysq = jnp.sum(yp * yp, axis=1)
    ysq = jnp.where(jnp.arange(n_pad) < n, ysq,
                    jnp.float32(_BIG)).reshape(1, n_pad)

    grid = (m_pad // _TILE_M, n_pad // _TILE_N)
    out_d, out_i = pl.pallas_call(
        functools.partial(_kernel, precision=precision),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE_M, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE_N, k), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _TILE_M), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _TILE_N), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, _TILE_M), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _TILE_M), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, m_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, m_pad), jnp.int32),
        ],
        interpret=interpret,
    )(xp, yp, xsq, ysq)

    best_d = jnp.maximum(out_d[0, :m], 0.0)
    best_i = out_i[0, :m]
    if sqrt:
        best_d = jnp.sqrt(best_d)
    return best_d, best_i
