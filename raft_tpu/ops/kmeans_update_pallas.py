"""k-means assignment + centroid-update pass (XLA distance + Pallas epilogue).

The ``fusedL2NN`` + ``update_centroids`` analogue (reference:
distance/fused_l2_nn.cuh:100 feeding cluster/detail/kmeans.cuh:432): one
logical pass over the data per Lloyd iteration that computes per-row
nearest centroids and accumulates the weighted per-cluster sums/counts.

Round-5 structure — a two-stage split, measured faster than the fully
fused round-4 kernel (12.1 ms vs 20.5 ms best-observed for the whole
pass at 1M x 128, k=1024, tile 2048 on one v5e):

1. **Distance + argmin (XLA)** — ``argmin ||x-c||^2 = argmin (||c||^2 -
   2 x.c)`` (the per-row ``||x||^2`` term cannot change the argmin and
   is never computed).  XLA fuses the row min/argmin into the matmul
   loop without materializing the (n, K) block in HBM, and its matmul
   schedule reaches ~120 TF/s on this part where a hand-written Mosaic
   grid loop over the same shape measured ~21 TF/s (profiles/
   kmeans_decomp_r5.py: a (2048,128)@(128,1024) step per grid tick is
   too small to hide Mosaic's per-step overhead, and fatter K blocks
   blow VMEM).  Do not re-fuse stage 1 into the kernel — this split IS
   the optimization.
2. **One-hot epilogue (Pallas)** — per data tile, expand labels to a
   one-hot block and accumulate the **weighted per-cluster sums as an
   MXU matmul** (``onehot_w^T @ x``) into a VMEM-resident (K, dim)
   accumulator, plus counts as a VPU column reduce.  The round-3 XLA
   Lloyd loop was epilogue-bound precisely here: ``segment_sum`` lowers
   to a serialized HBM scatter-add (23.7 ms measured vs 10.9 ms for
   this kernel), and labels round-trip through HBM either way, so the
   epilogue — not the distance matmul — is what Pallas should own.

Padding contract (callers: :func:`fused_assign_update`):
- rows are padded to the tile size with **zero weights** — padded rows
  contribute nothing to sums/counts;
- K is padded to a lane multiple with ``c_sq = +inf`` sentinels — the
  argmin never selects a padded cluster;
- dim is padded with zero columns on both x and centroids — distances
  and sums are unchanged; callers slice the result.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _epi_kernel(x_ref, w_ref, lab_ref, sums_ref, counts_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sums_ref[...] = jnp.zeros_like(sums_ref)
        counts_ref[...] = jnp.zeros_like(counts_ref)

    x = x_ref[...]                                   # (T, dim) bf16
    lab = lab_ref[...]                               # (T, 1) int32
    k_pad = counts_ref.shape[1]
    cols = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], k_pad), 1)
    onehot_w = (cols == lab).astype(jnp.float32) * w_ref[...]

    # weighted sums: (K, dim) += onehot_w^T @ x  (MXU, f32 accumulate;
    # the one-hot factor is exact in bf16 — values are 0 or w, and
    # integer/short-float weights survive the cast for the common
    # uniform-weight case)
    sums_ref[...] += jax.lax.dot_general(
        onehot_w.astype(jnp.bfloat16), x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    counts_ref[...] += jnp.sum(onehot_w, axis=0, keepdims=True)


def _round_up(v, m):
    return -(-v // m) * m


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def fused_assign_update(x, weights, centroids, tile=1024, interpret=False):
    """One assignment+update pass (see module docstring for the split).

    ``x`` (n, dim); ``weights`` (n,) f32; ``centroids`` (k, dim).
    Returns ``(sums (k, dim) f32, counts (k,) f32, dmin (n,) f32)`` —
    the weighted per-cluster sums, total weights, and each row's
    ``min_c(||c||^2 - 2 x.c)`` (add the row's own ``||x||^2`` for a
    true squared distance); callers derive the means and keep old
    centroids for empty clusters (update_centroids contract, reference
    detail/kmeans.cuh:285).

    bf16 MXU passes with f32 accumulation: x is rounded once (~1e-3
    relative) — within Lloyd's self-correcting tolerance (see
    test_kmeans_fused_matches_xla).
    """
    n, dim = x.shape
    k = centroids.shape[0]
    k_pad = _round_up(k, 128)
    d_pad = _round_up(dim, 128)
    # row padding serves both stages: the epilogue needs a tile
    # multiple, stage 1 a chunk multiple (chunk = a tile multiple, so
    # one padded size fits both — computed up front to pad exactly once)
    n_pad = _round_up(n, tile)
    n_chunks = -(-n_pad // (128 * tile))
    chunk = _round_up(-(-n_pad // n_chunks), tile)
    n_pad = chunk * n_chunks

    cf = centroids.astype(jnp.float32)
    c_sq = jnp.sum(cf * cf, axis=1)
    csq_p = jnp.full((1, k_pad), jnp.inf, jnp.float32).at[0, :k].set(c_sq)
    c_p = jnp.zeros((k_pad, d_pad), jnp.bfloat16)
    c_p = c_p.at[:k, :dim].set(cf.astype(jnp.bfloat16))
    x_p = jnp.zeros((n_pad, d_pad), jnp.bfloat16)
    x_p = x_p.at[:n, :dim].set(x.astype(jnp.bfloat16))
    w_p = jnp.zeros((n_pad, 1), jnp.float32)
    w_p = w_p.at[:n, 0].set(weights.astype(jnp.float32))

    # stage 1 (XLA): fused matmul + row argmin/min (padded rows get a
    # harmless real argmin; their zero weight drops them from the
    # epilogue).  Chunked over rows with lax.map so peak memory is
    # O(chunk * k_pad) by construction — XLA fuses the reductions into
    # the matmul at the sizes measured, but nothing guarantees that at
    # every (n, k), and a materialized (n_pad, k_pad) f32 block at
    # 50M x 1024 would be ~200 GB.
    def _assign_chunk(xc):
        ip = jax.lax.dot_general(xc, c_p, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        d = csq_p - 2.0 * ip
        return jnp.argmin(d, axis=1).astype(jnp.int32), jnp.min(d, axis=1)

    labels, dmin = jax.lax.map(_assign_chunk,
                               x_p.reshape(n_pad // chunk, chunk, d_pad))
    labels = labels.reshape(n_pad)
    dmin = dmin.reshape(n_pad)

    # stage 2 (Pallas): one-hot epilogue
    sums, counts = pl.pallas_call(
        _epi_kernel,
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, d_pad), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
        ],
        interpret=interpret,
    )(x_p, w_p, labels[:, None])
    return sums[:k, :dim], counts[0, :k], dmin[:n]


def supported(n: int, dim: int, k: int, metric_is_l2: bool,
              tile: int = 1024) -> bool:
    """Shapes the epilogue kernel handles at this tile; callers fall
    back to the XLA path otherwise.  VMEM: x tile + one-hot (f32 + the
    bf16 cast) + accumulator must fit; the distance block lives in
    stage 1 (XLA) and costs no VMEM here.  The k_pad*d_pad cap keeps
    the VMEM-resident sums accumulator bounded, which also bounds the
    stage-1 regime to sizes where XLA's matmul+argmin fusion is
    verified (k <= ~4096 at dim 128)."""
    k_pad = _round_up(k, 128)
    d_pad = _round_up(dim, 128)
    vmem = (tile * d_pad * 2            # x tile bf16
            + tile * k_pad * 6          # one-hot f32 + bf16 cast
            + k_pad * d_pad * 4         # sums accumulator
            + 2 * k_pad * 4)
    return (metric_is_l2 and n >= tile and vmem <= (15 << 20)
            and k_pad * d_pad * 4 <= (4 << 20))


def best_tile(n: int, dim: int, k: int, metric_is_l2: bool) -> int:
    """Largest supported data tile (descending ladder), 0 if none —
    large cluster counts shrink the tile so the one-hot block stays
    inside VMEM (k=4096 @ dim 128 fits at 512)."""
    for tile in (2048, 1024, 512, 256):
        if supported(n, dim, k, metric_is_l2, tile=tile):
            return tile
    return 0


def fused_tile(n: int, dim: int, k: int) -> int:
    """The ONE backend+shape gate for routing a Lloyd-style loop through
    this pass (kmeans.fit and kmeans_balanced share it; each checks
    its own metric family first).  dim < 32 is unprofitable — lane
    padding makes the bf16 tiles mostly zeros."""
    import jax

    if jax.default_backend() != "tpu" or dim < 32:
        return 0
    return best_tile(n, dim, k, True)
