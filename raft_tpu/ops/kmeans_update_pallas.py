"""Pallas TPU kernel: fused k-means assignment + centroid-update pass.

The ``fusedL2NN`` + ``update_centroids`` analogue (reference:
distance/fused_l2_nn.cuh:100 feeding cluster/detail/kmeans.cuh:432): one
pass over the data per Lloyd iteration that
  1. computes the (tile, K) distance block on the MXU
     (``argmin ||x-c||^2 = argmin (||c||^2 - 2 x.c)`` — the per-row
     ``||x||^2`` term cannot change the argmin and is never computed),
  2. takes the per-row argmin (VPU reduce),
  3. expands the labels to a one-hot block and accumulates the
     **weighted per-cluster sums as a second MXU matmul**
     (``onehot^T @ (w * x)``) into a VMEM-resident (K, dim) accumulator,
     plus per-cluster counts as a VPU column reduce.

The round-3 XLA Lloyd loop was epilogue-bound: ``segment_sum`` lowers to
a serialized HBM scatter-add and the labels round-trip through HBM.
Here neither labels nor distances ever leave VMEM; the epilogue rides
the MXU next to the distance matmul (PERFORMANCE.md round-4 notes).

Padding contract (callers: :func:`fused_assign_update`):
- rows are padded to the tile size with **zero weights** — padded rows
  contribute nothing to sums/counts;
- K is padded to a lane multiple with ``c_sq = +inf`` sentinels — the
  argmin never selects a padded cluster;
- dim is padded with zero columns on both x and centroids — distances
  and sums are unchanged; callers slice the result.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, w_ref, c_ref, csq_ref, sums_ref, counts_ref,
            dmin_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sums_ref[...] = jnp.zeros_like(sums_ref)
        counts_ref[...] = jnp.zeros_like(counts_ref)

    x = x_ref[...]                                   # (T, dim) bf16
    ip = jax.lax.dot_general(x, c_ref[...], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    d = csq_ref[...] - 2.0 * ip                      # (T, K) f32
    labels = jnp.argmin(d, axis=1)                   # (T,)
    # per-row min of the ||x||^2-free distance form; callers add the
    # loop-invariant row norms back (balanced k-means' re-seed sampling)
    dmin_ref[...] = jnp.min(d, axis=1, keepdims=True)

    k_pad = d.shape[1]
    cols = jax.lax.broadcasted_iota(jnp.int32, d.shape, 1)
    onehot = (cols == labels[:, None]).astype(jnp.float32)
    w = w_ref[...].reshape(-1)                       # (T,) f32
    onehot_w = onehot * w[:, None]

    # weighted sums: (K, dim) += onehot_w^T @ x  (MXU, f32 accumulate)
    sums_ref[...] += jax.lax.dot_general(
        onehot_w.astype(jnp.bfloat16), x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    counts_ref[...] += jnp.sum(onehot_w, axis=0, keepdims=True)


def _round_up(v, m):
    return -(-v // m) * m


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def fused_assign_update(x, weights, centroids, tile=1024, interpret=False):
    """One fused assignment+update pass.

    ``x`` (n, dim); ``weights`` (n,) f32; ``centroids`` (k, dim).
    Returns ``(sums (k, dim) f32, counts (k,) f32, dmin (n,) f32)`` —
    the weighted per-cluster sums, total weights, and each row's
    ``min_c(||c||^2 - 2 x.c)`` (add the row's own ``||x||^2`` for a
    true squared distance); callers derive the means and keep old
    centroids for empty clusters (update_centroids contract, reference
    detail/kmeans.cuh:285).

    bf16 MXU passes with f32 accumulation: the one-hot factor is exact
    in bf16; x is rounded once (~1e-3 relative) — within Lloyd's
    self-correcting tolerance (see test_kmeans_fused_matches_xla).
    """
    n, dim = x.shape
    k = centroids.shape[0]
    n_pad = _round_up(n, tile)
    k_pad = _round_up(k, 128)
    d_pad = _round_up(dim, 128)

    cf = centroids.astype(jnp.float32)
    c_sq = jnp.sum(cf * cf, axis=1)
    csq_p = jnp.full((1, k_pad), jnp.inf, jnp.float32).at[0, :k].set(c_sq)
    c_p = jnp.zeros((k_pad, d_pad), jnp.bfloat16)
    c_p = c_p.at[:k, :dim].set(cf.astype(jnp.bfloat16))
    x_p = jnp.zeros((n_pad, d_pad), jnp.bfloat16)
    x_p = x_p.at[:n, :dim].set(x.astype(jnp.bfloat16))
    w_p = jnp.zeros((n_pad, 1), jnp.float32)
    w_p = w_p.at[:n, 0].set(weights.astype(jnp.float32))

    sums, counts, dmin = pl.pallas_call(
        _kernel,
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, d_pad), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x_p, w_p, c_p, csq_p)
    return sums[:k, :dim], counts[0, :k], dmin[:n, 0]


def supported(n: int, dim: int, k: int, metric_is_l2: bool,
              tile: int = 1024) -> bool:
    """Shapes the kernel handles at this tile; callers fall back to the
    XLA path otherwise.  VMEM: x tile + distance block + one-hot +
    accumulator + centroids must fit (cap measured round 5: tile 2048 @
    k 1024, dim 128 — ~17.5 MB of blocks — compiles and runs ~20%
    faster than tile 1024; the earlier 12 MB cap was conservative)."""
    k_pad = _round_up(k, 128)
    d_pad = _round_up(dim, 128)
    vmem = (tile * d_pad * 2            # x tile bf16
            + 2 * tile * k_pad * 4      # distances + one-hot
            + k_pad * d_pad * 2         # centroids bf16
            + k_pad * d_pad * 4         # sums accumulator
            + 2 * k_pad * 4)
    return (metric_is_l2 and n >= tile and vmem <= (18 << 20)
            and k_pad * d_pad * 4 <= (4 << 20))


def best_tile(n: int, dim: int, k: int, metric_is_l2: bool) -> int:
    """Largest supported data tile (descending ladder), 0 if none —
    large cluster counts shrink the tile so the (tile, K) distance and
    one-hot blocks stay inside VMEM (k=4096 @ dim 128 fits at 256)."""
    for tile in (2048, 1024, 512, 256):
        if supported(n, dim, k, metric_is_l2, tile=tile):
            return tile
    return 0


def fused_tile(n: int, dim: int, k: int) -> int:
    """The ONE backend+shape gate for routing a Lloyd-style loop through
    this kernel (kmeans.fit and kmeans_balanced share it; each checks
    its own metric family first).  dim < 32 is unprofitable — lane
    padding makes the bf16 tiles mostly zeros."""
    import jax

    if jax.default_backend() != "tpu" or dim < 32:
        return 0
    return best_tile(n, dim, k, True)
